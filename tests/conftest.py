"""Test harness: force an 8-virtual-device CPU platform.

This is the TPU analog of the reference's parts>GPUs trick (numParts =
numMachines*numGPUs, gnn.cc:61-63, lets distributed code paths run on one
box): XLA's host platform is split into 8 virtual devices so every
mesh/collective path is exercised on CPU-only CI.

The environment may carry a TPU PJRT plugin (registered by sitecustomize
before pytest starts) whose initialization dials a remote chip; tests must
never depend on — or block on — that tunnel, so we (a) pin the platform to
cpu via jax.config (env vars are too late: the plugin's own registration can
override JAX_PLATFORMS programmatically) and (b) drop any non-cpu backend
factories before first use.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "").split()
_flags.append("--xla_force_host_platform_device_count=8")
# XLA:CPU hard-kills the process (rendezvous.cc "Termination timeout ...
# Exiting") when a collective's device threads skew more than 40 s apart
# — on a 1-core box running 8 virtual devices over 1e8-edge shards that
# skew is routine, and the giant scale-guard programs aborted
# intermittently (~50%) until these were raised.  Pre-set values win
# (only appended when absent), so an operator can still tighten them.
# These flags landed with jaxlib 0.5-era XLA; an older XLA hard-aborts
# ("Unknown flags in XLA_FLAGS") on ANY unrecognized flag, so gate them.
try:
    import jaxlib.version as _jlv
    _jaxlib_v = tuple(int(p) for p in _jlv.__version__.split(".")[:2])
except Exception:  # pragma: no cover - be permissive about version layout
    _jaxlib_v = (0, 0)
if _jaxlib_v >= (0, 5):
    for _d in ("--xla_cpu_collective_call_terminate_timeout_seconds=1200",
               "--xla_cpu_collective_call_warn_stuck_timeout_seconds=120"):
        if not any(f.startswith(_d.split("=")[0]) for f in _flags):
            _flags.append(_d)
os.environ["XLA_FLAGS"] = " ".join(_flags)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
# The axon plugin registration (sitecustomize) sets jax_disable_bwd_checks
# for its own backend quirks; that also disables the shard_map custom-vjp
# vma typecheck and once let a bwd-rule bug pass CI while failing in every
# clean environment.  Tests must run strict.
try:
    jax.config.update("jax_disable_bwd_checks", False)
except AttributeError:
    # jax < 0.5 has no bwd checks (nor the vma machinery they verify) —
    # nothing to re-enable; roc_tpu._jax_compat polyfills the rest.
    pass
try:
    from jax._src import xla_bridge

    # Drop only the tunnel-dialing plugin; the 'tpu' factory must stay
    # registered (pallas.tpu registers MLIR lowerings against that platform
    # name at import) but never initializes under jax_platforms=cpu.
    xla_bridge._backend_factories.pop("axon", None)
except Exception:  # pragma: no cover - private API may move across versions
    pass

# Persistent compile cache: the fast lane is dominated by XLA compiles of
# the sharded train steps (one-core box, ~70% of a cold 435 s run);
# repeated runs — the common case for a developer and the driver alike —
# hit the cache and the lane drops well under the 300 s budget
# (README §Testing).  Keyed by HLO hash, so a code change that alters a
# program recompiles exactly that program.  Same per-user location rule
# as bench.py; ROC_TEST_NO_COMPILE_CACHE=1 opts out (cold-timing runs).
if not os.environ.get("ROC_TEST_NO_COMPILE_CACHE"):
    try:   # cache is best-effort, never fatal (same rule as bench.py —
        # a jax that renames these options must not break collection)
        _cache = os.environ.get(
            "ROC_JAX_CACHE_DIR",
            os.path.join(os.path.expanduser("~"), ".cache",
                         f"roc_jax_u{os.getuid()}"))
        jax.config.update("jax_compilation_cache_dir", _cache)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1)
    except Exception:
        pass

import roc_tpu  # noqa: E402, F401  (installs jax 0.4.x polyfills)
import numpy as np  # noqa: E402
import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: multi-epoch end-to-end runs (golden curves)")


@pytest.fixture
def rng():
    return np.random.default_rng(1234)


@pytest.fixture
def lock_witness():
    """Arm the runtime lock-order witness for one test: locks created
    inside the test become recording proxies, and at teardown every
    observed (outer, inner) acquisition pair must be an edge of the
    static graph in roc_tpu/analysis/threads.json.  The threaded suites
    (serve/delta/stream/fleet) wrap this in an autouse fixture, which is
    what pins the analyzer sound against reality, not just fixtures."""
    from roc_tpu.analysis import witness
    witness.reset()
    witness.arm(True)
    yield witness
    violations = witness.validate()
    witness.arm(False)
    witness.reset()
    assert violations == [], violations
