"""Online cost-model load balancer (the reference's partitioner loop).

ROC's signature contribution is an *online* partitioner: a linear cost model
fit to observed per-partition runtimes, driving a repartition search between
training rounds.  This package closes the same loop for the TPU port:

  telemetry.py   per-shard work counters + probe timings (ring buffer, JSONL)
  cost_model.py  least-squares fit t_p ~ w . [nodes, edges, halo_in, halo_out, 1]
  search.py      min-max repartition search over the contiguous-cut space
  manager.py     BalanceManager: collect -> fit -> propose -> apply

Entry point: ``BalanceManager.from_config(cfg)``; the trainers drive it at
epoch boundaries (train/driver.py) and apply proposals via
``SpmdTrainer.reshard`` (parallel/spmd.py).
"""

from roc_tpu.balance.cost_model import OnlineCostModel
from roc_tpu.balance.manager import BalanceManager
from roc_tpu.balance.telemetry import ShardSample, TelemetryBuffer

__all__ = ["BalanceManager", "OnlineCostModel", "ShardSample",
           "TelemetryBuffer"]
