"""ULP-distance parity check between served and eval logits.

The serving parity gate (tests/test_serve.py) is "≤ 32 ULPs", not an
atol/rtol pair: served and eval forwards run the SAME jitted program on
the SAME inputs, so any divergence is reduction-order jitter from the
query gather's fusion decisions — a few ULPs at most — and an absolute
tolerance would either mask real divergence on small logits or
false-positive on large ones.  ULP distance is scale-free: reinterpret
the float bits as lexicographically ordered integers and diff.
"""

from __future__ import annotations

import numpy as np


def _lex_int(x: np.ndarray) -> np.ndarray:
    """Map float32 bit patterns to integers ordered like the floats:
    adjacent representable floats differ by exactly 1.  Negative floats
    (sign bit set) reflect around zero so -0.0 and +0.0 coincide."""
    b = np.ascontiguousarray(x, np.float32).view(np.int32).astype(np.int64)
    return np.where(b < 0, np.int64(-(2 ** 31)) - b, b)


def max_ulp_diff(a, b) -> int:
    """Largest elementwise ULP distance between two float arrays.

    Inputs cast to float32 first (bf16 storage still accumulates and
    emits fp32 logits, so fp32 is the comparison precision everywhere).
    NaNs must match positionally; any unmatched NaN is reported as the
    maximum distance rather than poisoning the integer math.
    """
    # The parity gate runs off the request path (tests / selftest only),
    # so pulling both operands to the host is its job, not a leak.
    a = np.asarray(a, np.float32)  # roclint: allow(host-sync) — off-request-path parity gate; the host pull is its job
    b = np.asarray(b, np.float32)  # roclint: allow(host-sync) — off-request-path parity gate; the host pull is its job
    assert a.shape == b.shape, f"shape mismatch: {a.shape} vs {b.shape}"
    nan_a, nan_b = np.isnan(a), np.isnan(b)
    if (nan_a != nan_b).any():
        return int(np.iinfo(np.int64).max)
    ok = ~nan_a
    if not ok.any():
        return 0
    d = np.abs(_lex_int(a[ok]) - _lex_int(b[ok]))
    return int(d.max()) if d.size else 0
