"""Exact DP over layer retention decisions (ROC Algorithm 2 analog).

Given per-layer estimates (estimator.py), choose KEEP / REMAT /
OFFLOAD-candidate per layer to minimize predicted step time subject to a
per-device HBM budget.  The cost model the DP optimizes (and that the
brute-force acceptance test enumerates) is:

  peak(d)  = fixed + sum_{keep} saved_i + max_{remat} full_i
  time(d)  = base                                  if no layer remats
           = base + sum_{keep} cheap_i
                  + sum_{remat} full_i             otherwise

The transient ``max_{remat} full_i`` term is the working set of the
largest rematerialized segment: its residuals exist only while its own
backward runs (the other remat segments' residuals are gone by then), so
a plan only saves memory once MULTIPLE segments drop out of residence —
rematting a single dominant layer buys nothing, which the DP discovers by
itself.  ``cheap_i`` is the elementwise recompute every kept layer pays
once any plan is active (per-tensor granularity: only linear / aggregate /
gat outputs are saved — estimator.py).

Exactness: for a plan with >= 1 remat, order layers by (bytes_full,
index) descending; the FIRST rematted layer in that order determines the
transient term and forces everything before it to KEEP.  Trying each
candidate position reduces the problem to a 0/1 knapsack over the
remaining layers (maximize avoided recompute subject to saved-bytes
budget), solved exactly with Pareto-pruned states.  Layer counts above
``DP_MAX_LAYERS`` fall back to a density-greedy pack (flagged in the
plan).

OFFLOAD: a rematted layer whose tagged bytes would round-trip to host
memory faster than its segment recomputes is relabeled "offload".  How
that verdict EXECUTES depends on the run's executor, recorded in
``MemPlan.offload_executes_as``: under ``-stream`` the verdict is real —
the stream executor (roc_tpu/stream) keeps boundary activations
host-resident and the checkpoint policy offloads tagged saves to pinned
host memory (policy.py, ``offload_executes_as="stream-host"``).  Without
``-stream`` there is no planner-controlled host-offload path on the
in-core executors, so OFFLOAD layers still execute as remat and every
artifact (plan-dump, bench ROC_BENCH_MEM) carries the explicit
``"offload_executes_as": "remat"`` label rather than implying bytes moved
that never did.  docs/DESIGN.md §Memory planner, §Streaming executor.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Optional, Sequence, Tuple

from roc_tpu.memory.estimator import ModelEstimate

KEEP = "keep"
REMAT = "remat"
OFFLOAD = "offload"     # host round-trip beats recompute; executes as
                        # stream-host residency under -stream, as REMAT
                        # otherwise (MemPlan.offload_executes_as says which)

# Beyond this many layers the exact DP (L knapsacks, Pareto states) gives
# way to the greedy pack.  GNNs in this repo are 2-8 layers; 16 is already
# far past anything the step cache has seen.
DP_MAX_LAYERS = 16
# Host-DMA round-trip bandwidth used only to flag offload candidates
# (PCIe-class; deliberately conservative).
OFFLOAD_BYTES_PER_S = 5e10
# NVMe-class round-trip bandwidth for the spill tier (-stream-spill):
# when boundary stores live on disk, an OFFLOAD verdict's bytes pay the
# slower device, so fewer layers clear the recompute-beats-transfer bar.
SPILL_BYTES_PER_S = 3e9


@dataclasses.dataclass(frozen=True)
class MemPlan:
    """A compiled retention plan plus its predicted costs."""

    mode: str                       # keep | remat | auto (the -mem-plan ask)
    budget_bytes: int               # 0 = unbounded
    decisions: Tuple[str, ...]      # per layer: keep | remat | offload
    layer_names: Tuple[str, ...]
    predicted_peak_bytes: int
    predicted_step_s: float
    keep_peak_bytes: int            # all-KEEP baseline
    keep_step_s: float
    remat_peak_bytes: int           # all-REMAT baseline
    remat_step_s: float
    planner: str                    # fixed | dp | greedy
    feasible: bool                  # predicted peak <= budget (or no budget)
    # how an OFFLOAD verdict executes in this run: "stream-host" when the
    # stream executor is active, "remat" otherwise (the honest default)
    offload_executes_as: str = REMAT

    def any_remat(self) -> bool:
        return any(d != KEEP for d in self.decisions)

    def num_remat(self) -> int:
        return sum(d != KEEP for d in self.decisions)

    def any_offload(self) -> bool:
        return any(d == OFFLOAD for d in self.decisions)

    def key(self):
        """The plan's contribution to the structure-keyed step cache: two
        plans with equal keys compile to the same checkpoint policy."""
        return (self.mode, self.budget_bytes, self.decisions,
                self.offload_executes_as)

    def to_dict(self) -> dict:
        return {
            "mode": self.mode,
            "budget_bytes": self.budget_bytes,
            "decisions": list(self.decisions),
            "layer_names": list(self.layer_names),
            # serialized plan fields, not new prediction sites — the plan's
            # predictions are ledgered where they are made (bench stamping)
            "predicted_peak_bytes": self.predicted_peak_bytes,  # roclint: allow(unledgered-prediction) — serialized plan field; the prediction is ledgered at bench stamping
            "predicted_step_s": round(self.predicted_step_s, 9),  # roclint: allow(unledgered-prediction) — serialized plan field; the prediction is ledgered at bench stamping
            "keep_peak_bytes": self.keep_peak_bytes,
            "keep_step_s": round(self.keep_step_s, 9),
            "remat_peak_bytes": self.remat_peak_bytes,
            "remat_step_s": round(self.remat_step_s, 9),
            "planner": self.planner,
            "feasible": self.feasible,
            "offload_executes_as": self.offload_executes_as,
        }

    def to_json(self) -> str:
        """Deterministic serialization (preflight pins byte-identity)."""
        return json.dumps(self.to_dict(), sort_keys=True,
                          separators=(",", ":")) + "\n"

    def summary(self) -> str:
        dec = " ".join(f"{n}={d}" for n, d in zip(self.layer_names,
                                                  self.decisions))
        off = ""
        if self.any_offload():
            off = f" (offload executes-as-{self.offload_executes_as})"
        return (f"mem-plan[{self.mode}/{self.planner}] {dec} "
                f"peak={self.predicted_peak_bytes / 1e6:.1f}MB"
                f"{'' if self.feasible else ' OVER-BUDGET'} "
                f"(keep={self.keep_peak_bytes / 1e6:.1f}MB) "
                f"step=+{(self.predicted_step_s / max(self.keep_step_s, 1e-12) - 1) * 100:.1f}%"
                f"{off}")


def predict_peak(est: ModelEstimate, decisions: Sequence[str]) -> int:
    """Predicted per-device peak bytes under a decision vector."""
    kept = sum(l.bytes_saved for l, d in zip(est.layers, decisions)
               if d == KEEP)
    remat = [l.bytes_full for l, d in zip(est.layers, decisions)
             if d != KEEP]
    if not remat:   # all-KEEP runs unwrapped: full residuals stay live
        return est.fixed_bytes + est.total_full_bytes()
    return est.fixed_bytes + kept + max(remat)


def predict_time(est: ModelEstimate, decisions: Sequence[str]) -> float:
    """Predicted step seconds under a decision vector."""
    if not any(d != KEEP for d in decisions):
        return est.base_step_s
    extra = sum(l.recompute_full_s if d != KEEP else l.recompute_cheap_s
                for l, d in zip(est.layers, decisions))
    return est.base_step_s + extra


def feasible(est: ModelEstimate, decisions: Sequence[str],
             budget_bytes: int) -> bool:
    return budget_bytes <= 0 or predict_peak(est, decisions) <= budget_bytes


def _knapsack(items, budget: int):
    """Exact 0/1 knapsack: items [(weight, value, idx)], weights/budget in
    bytes.  Returns (best_value, chosen idx frozenset).  Pareto-pruned
    state list — exact, and small in practice (layer counts <= 16)."""
    states = [(0, 0.0, frozenset())]       # (weight, value, chosen)
    for w, v, idx in items:
        merged = dict()
        for weight, value, chosen in states:
            for nw, nv, nc in ((weight, value, chosen),
                               (weight + w, value + v, chosen | {idx})):
                if nw > budget:
                    continue
                cur = merged.get(nw)
                # deterministic tie-break: higher value, then fewer kept,
                # then lexicographically smallest index set
                cand = (nv, -len(nc), tuple(sorted(nc)))
                if cur is None or (cand[0], cand[1], cand[2]) > \
                        (cur[1], -len(cur[2]), tuple(sorted(cur[2]))):
                    merged[nw] = (nw, nv, nc)
        # Pareto prune: increasing weight must strictly increase value
        pruned = []
        best = -1.0
        for wgt in sorted(merged):
            st = merged[wgt]
            if st[1] > best:
                pruned.append(st)
                best = st[1]
        states = pruned
    return max(states, key=lambda s: (s[1], -s[0]))[1:]


def _plan_auto(est: ModelEstimate, budget_bytes: int):
    """Minimize predict_time subject to predict_peak <= budget.  Returns
    (decisions list, planner name)."""
    L = len(est.layers)
    all_keep = [KEEP] * L
    if feasible(est, all_keep, budget_bytes):
        return all_keep, "dp"     # base time is the global minimum
    if L > DP_MAX_LAYERS:
        return _plan_greedy(est, budget_bytes), "greedy"
    # Order by (bytes_full, index) desc; candidate k = first rematted
    # layer in this order (fixes the transient term, forces 0..k-1 KEEP).
    order = sorted(range(L), key=lambda i: (-est.layers[i].bytes_full, i))
    best = None    # (time, decisions)
    for k in range(L):
        lk = est.layers[order[k]]
        head = budget_bytes - est.fixed_bytes - lk.bytes_full - \
            sum(est.layers[order[j]].bytes_saved for j in range(k))
        if head < 0:
            continue
        free = order[k + 1:]
        items = [(est.layers[i].bytes_saved,
                  est.layers[i].recompute_full_s
                  - est.layers[i].recompute_cheap_s, i) for i in free]
        _, chosen = _knapsack(items, head)
        decisions = list(all_keep)
        decisions[order[k]] = REMAT
        for i in free:
            if i not in chosen:
                decisions[i] = REMAT
        t = predict_time(est, decisions)
        if feasible(est, decisions, budget_bytes) and \
                (best is None or t < best[0] - 1e-15):
            best = (t, decisions)
    if best is None:
        # even all-REMAT is over budget: ship it anyway (least-peak plan)
        # and let the caller surface the infeasibility
        return [REMAT] * L, "dp"
    return best[1], "dp"


def _plan_greedy(est: ModelEstimate, budget_bytes: int):
    """Density-greedy fallback for deep models: start all-REMAT, re-KEEP
    layers by avoided-recompute per saved byte while the budget holds."""
    L = len(est.layers)
    decisions = [REMAT] * L
    order = sorted(
        range(L),
        key=lambda i: (-(est.layers[i].recompute_full_s
                         - est.layers[i].recompute_cheap_s)
                       / max(est.layers[i].bytes_saved, 1), i))
    for i in order:
        trial = list(decisions)
        trial[i] = KEEP
        if feasible(est, trial, budget_bytes):
            decisions = trial
    return decisions


def _mark_offload(est: ModelEstimate, decisions,
                  bytes_per_s: float = OFFLOAD_BYTES_PER_S):
    """Relabel remats whose round-trip to the offload tier (host DMA by
    default, NVMe under the spill tier) would beat recomputing."""
    out = []
    for l, d in zip(est.layers, decisions):
        if d == REMAT:
            transfer = 2.0 * l.bytes_saved / bytes_per_s
            if transfer < l.recompute_full_s - l.recompute_cheap_s:
                d = OFFLOAD
        out.append(d)
    return out


def plan_memory(est: ModelEstimate, mode: str = "auto",
                budget_bytes: int = 0,
                offload_executed: bool = False,
                offload_spills: bool = False) -> MemPlan:
    """Compile a :class:`MemPlan` for the given estimates.

    ``mode="keep"`` / ``"remat"`` pin every layer (budget ignored);
    ``"auto"`` runs the DP under ``budget_bytes`` (0 = unbounded, which
    makes all-KEEP optimal by construction).  ``offload_executed`` records
    whether this run's executor actually moves OFFLOAD bytes to host
    (the stream executor does; the in-core ones execute them as remat).
    ``offload_spills`` prices the round-trip at the NVMe tier
    (-stream-spill: boundary stores live on disk, so OFFLOAD's bytes ride
    the slower device and must beat recompute at SPILL_BYTES_PER_S).
    """
    L = len(est.layers)
    if mode == "keep":
        decisions, planner = [KEEP] * L, "fixed"
    elif mode == "remat":
        decisions, planner = [REMAT] * L, "fixed"
    elif mode == "auto":
        decisions, planner = _plan_auto(est, int(budget_bytes))
    else:
        raise ValueError(f"mem plan mode {mode!r}: must be keep|remat|auto")
    decisions = _mark_offload(
        est, decisions,
        SPILL_BYTES_PER_S if offload_spills else OFFLOAD_BYTES_PER_S)
    all_keep, all_remat = [KEEP] * L, [REMAT] * L
    return MemPlan(
        mode=mode, budget_bytes=int(budget_bytes),
        decisions=tuple(decisions),
        layer_names=tuple(l.name for l in est.layers),
        predicted_peak_bytes=predict_peak(est, decisions),
        predicted_step_s=predict_time(est, decisions),
        keep_peak_bytes=predict_peak(est, all_keep),
        keep_step_s=predict_time(est, all_keep),
        remat_peak_bytes=predict_peak(est, all_remat) if L else 0,
        remat_step_s=predict_time(est, all_remat),
        planner=planner,
        feasible=feasible(est, decisions, int(budget_bytes)),
        offload_executes_as=("stream-spill" if offload_executed
                             and offload_spills else
                             "stream-host" if offload_executed else REMAT),
    )


def device_budget_bytes() -> int:
    """The accelerator's own memory limit, where the platform reports one
    (TPU/GPU ``memory_stats``); 0 on hosts that don't (CPU)."""
    import jax
    try:
        stats = jax.local_devices()[0].memory_stats()
    except Exception:
        return 0
    if not stats:
        return 0
    return int(stats.get("bytes_limit", 0))


def measured_peak_bytes() -> Optional[int]:
    """Max peak-bytes-in-use across local devices, None where the platform
    keeps no allocator stats (CPU)."""
    import jax
    peak = 0
    for d in jax.local_devices():
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        if stats and "peak_bytes_in_use" in stats:
            peak = max(peak, int(stats["peak_bytes_in_use"]))
    return peak or None
