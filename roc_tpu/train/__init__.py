from roc_tpu.train.config import Config
from roc_tpu.train.driver import Trainer

__all__ = ["Config", "Trainer"]
