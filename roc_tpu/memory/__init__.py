"""DP activation-memory planner (ROC's memory manager, Algorithm 2 analog).

estimator.py  per-layer activation bytes + recompute time, priced with the
              balance cost-model prior and cross-checked against XLA's own
              buffer sizes via the hlo_audit lowering machinery.
planner.py    exact DP choosing KEEP / REMAT / OFFLOAD-candidate per layer
              under a per-device HBM budget (greedy fallback for deep
              models); deterministic JSON plans (preflight pins this).
policy.py     compiles a plan into jax.checkpoint + save_only_these_names
              over the models' checkpoint-name-tagged intermediates — the
              only sanctioned raw-remat site in the tree (roclint `remat`).

Driven by -mem-plan {auto,keep,remat} / -mem-budget (ROC_MEM_* env); the
chosen plan joins the structure-keyed step cache so same-plan reshards
still hit the jit caches with zero retraces.
"""

from roc_tpu.memory.estimator import (LayerEstimate, ModelEstimate,
                                      estimate_for_trainer, estimate_model,
                                      fixed_bytes_for, step_arg_bytes,
                                      xla_memory_stats)
from roc_tpu.memory.planner import (KEEP, MemPlan, OFFLOAD, REMAT,
                                    device_budget_bytes, feasible,
                                    measured_peak_bytes, plan_memory,
                                    predict_peak, predict_time)
from roc_tpu.memory.policy import checkpoint_policy, loss_fn, saved_names

__all__ = [
    "KEEP", "REMAT", "OFFLOAD", "LayerEstimate", "ModelEstimate", "MemPlan",
    "estimate_for_trainer", "estimate_model", "fixed_bytes_for",
    "step_arg_bytes", "xla_memory_stats", "device_budget_bytes",
    "measured_peak_bytes", "plan_memory", "predict_peak", "predict_time",
    "feasible", "checkpoint_policy", "loss_fn", "saved_names",
]
