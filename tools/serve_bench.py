#!/usr/bin/env python
"""Serving bench: p50/p99 latency at offered QPS + cold-start artifact.

Emits `BENCH_SERVE.json` (schema gated by `tools/perf_ledger.py --check`
and folded into BENCH_TRAJECTORY.json under its own "serve" key — NEVER
a training-claim round row):

  {"metric": "serve_p50", "value": ..., "unit": "s",
   "p50_s": ..., "p99_s": ..., "qps_offered": ..., "qps_achieved": ...,
   "cold_start_s": ..., "plan_builds": ..., "platform": ...,
   "delta": {"apply_p50_s": ..., "apply_p99_s": ..., "batches": ...,
             "applied_adds": ..., "applied_retires": ..., "replans": ...},
   "fleet": {"replicas": ..., "p50_s": ..., "p99_s": ..., "shed": ...,
             "shed_rate": ..., "lag_p50_s": ..., "lag_p99_s": ...,
             "segments_shipped": ..., "scale_events": ...},   # --fleet N
   "measured_at": ...}

The cold start reported is the WARM-cache cold start (the serving
contract: cache load + one trace, zero plan rebuilds).  The first engine
build of a fresh checkout populates the plan cache; the bench then tears
it down and times a second build, which is the number a restarting
replica would see.  The load phase is open-loop (roc_tpu/serve/loadgen)
so overload shows up in the tail instead of throttling the offer rate.

  python tools/serve_bench.py                 # bench, write BENCH_SERVE.json
  python tools/serve_bench.py --fleet 3       # + replicated-fleet sweep:
                                              # open-loop QPS against the
                                              # fleet router, "fleet" block
                                              # in the artifact
  python tools/serve_bench.py --selftest      # tiny CPU run into a tmp
                                              # root, schema-validated via
                                              # perf_ledger.check (preflight)

The delta block times `apply_delta` on a SEPARATE volatile delta-enabled
engine (the serve-latency numbers stay pure static-graph; a delta-enabled
engine runs the unfused two-pass plan).  Chaos is never armed here —
bench numbers exclude fault legs, per the PR 14 convention.

The fleet block (``--fleet N`` / ROC_SERVE_BENCH_FLEET) stands up one
primary + N-1 followers on in-proc transports behind the FleetRouter and
repeats the open-loop sweep against the ROUTER, with delta churn pumped
through the replication log every few requests — so the numbers price
dispatch + sibling retry + replication on top of the single-engine
serve path: p50/p99 through the router, shed rate (typed FleetOverloaded
at submit, counted — never silent), replication lag p50/p99
(seal-to-applied, from the segment headers), and autoscale events.

Knobs (env, matching bench.py's style): ROC_SERVE_BENCH_DATASET,
ROC_SERVE_BENCH_REQUESTS, ROC_SERVE_BENCH_QPS, ROC_SERVE_BATCH,
ROC_SERVE_WAIT_MS, ROC_SERVE_BENCH_CKPT (optional checkpoint to serve),
ROC_SERVE_BENCH_DELTAS (delta batches to time, default 40),
ROC_SERVE_BENCH_FLEET (replica count for the fleet sweep; 0 = skip).
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)


def _env(name, default, cast):
    try:
        return cast(os.environ.get(name, default))
    except ValueError:
        raise SystemExit(f"{name} must be {cast.__name__}")


def run_bench(dataset: str, n_requests: int, qps: float,
              ckpt: str = "", fleet: int = 0) -> dict:
    """Build engine (twice — populate then warm-start), offer load,
    return the BENCH_SERVE payload."""
    import jax

    from roc_tpu.graph import datasets
    from roc_tpu.models import build_model
    from roc_tpu.serve import ServeEngine, run_load
    from roc_tpu.train.config import Config

    cfg = Config(dataset=dataset, layers=[], model="gcn")
    ds = datasets.get(dataset, seed=cfg.seed)
    cfg.layers = [ds.features.shape[1], 16, ds.num_classes]
    model = build_model(cfg.model, cfg.layers, cfg.dropout_rate, cfg.aggr,
                        heads=cfg.heads)

    # first build populates the content-keyed plan cache (and jit cache
    # for this process — so the warm timing below is generous on trace
    # time; plan_builds is the honest zero-rebuild pin)
    ServeEngine(cfg, ds, model, checkpoint_path=ckpt or None,
                start_queue=False).close()

    with ServeEngine(cfg, ds, model, checkpoint_path=ckpt or None) as eng:
        eng.warmup()
        stats = run_load(eng, n_requests=n_requests, qps=qps)
        cs = eng.cold_start_stats
        payload = {
            "metric": "serve_p50",
            "value": stats["p50_s"],
            "unit": "s",
            "p50_s": stats["p50_s"],
            "p99_s": stats["p99_s"],
            "mean_s": stats["mean_s"],
            "n_requests": stats["n"],
            "qps_offered": stats["qps_offered"],
            "qps_achieved": stats["qps_achieved"],
            "cold_start_s": cs["cold_start_s"],
            "plan_builds": cs["plan_builds"],
            "serve_batch": cfg.serve_batch,
            "serve_wait_ms": cfg.serve_wait_ms,
            "buckets": cs["buckets"],
            "platform": jax.default_backend(),
            # artifact timestamp, not a measurement record (the ledger
            # pairing lives in the engine); mirrors bench.py's waiver
            "measured_at": time.strftime("%Y-%m-%dT%H:%M:%S%z"),  # roclint: allow(unledgered-prediction) — artifact timestamp, not a measurement record
        }
    payload["delta"] = _bench_deltas(cfg, ds, model, ckpt)
    if fleet >= 2:
        payload["fleet"] = _bench_fleet(cfg, ds, model, ckpt, fleet,
                                        n_requests, qps)
    return payload


def _bench_deltas(cfg, ds, model, ckpt: str) -> dict:
    """Time apply_delta on a volatile delta-enabled engine: mixed
    add/retire churn, p50/p99 of the per-batch apply wall."""
    import warnings

    import numpy as np

    from roc_tpu.serve import ServeEngine

    n_batches = _env("ROC_SERVE_BENCH_DELTAS", "40", int)
    rng = np.random.default_rng(17)
    n = ds.graph.num_nodes
    times = []
    # deltas exist only for the binned backend; pin it regardless of
    # what the serve phase's auto-resolution picked
    import dataclasses
    cfg = dataclasses.replace(cfg, aggregate_backend="binned")
    with ServeEngine(cfg, ds, model, checkpoint_path=ckpt or None,
                     start_queue=False, delta_journal="") as eng:
        eng.warmup()
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", RuntimeWarning)
            for _ in range(n_batches):
                adds = rng.integers(0, n, (2, 2))
                rets = None
                if rng.random() < 0.25:
                    k = int(rng.integers(0, len(eng.deltas._src)))
                    rets = np.asarray([[eng.deltas._src[k],
                                        eng.deltas._dst[k]]])
                # apply latency is the artifact being measured; spans
                # cannot time it (percentiles need the raw samples)
                t0 = time.perf_counter()  # roclint: allow(raw-timing) — apply-latency percentiles need the raw samples; spans cannot
                eng.apply_delta(adds, rets, wait_replan=True)
                times.append(time.perf_counter() - t0)  # roclint: allow(raw-timing) — apply-latency percentiles need the raw samples; spans cannot
        st = eng.delta_stats()
    lat = sorted(times)
    return {
        "apply_p50_s": lat[len(lat) // 2],
        "apply_p99_s": lat[min(int(0.99 * (len(lat) - 1)), len(lat) - 1)],
        "batches": int(st["batches"]),
        "applied_adds": int(st["applied_adds"]),
        "applied_retires": int(st["applied_retires"]),
        "noops": int(st["noop_adds"] + st["noop_retires"]),
        "cells_patched": int(st["cells_patched"]),
        "replans": int(st["replans"]),
    }


def _bench_fleet(cfg, ds, model, ckpt: str, n_replicas: int,
                 n_requests: int, qps: float) -> dict:
    """Open-loop sweep against the fleet router: primary + followers on
    in-proc transports, delta churn pumped mid-stream.  Shed and lag are
    first-class outputs, not failures."""
    import dataclasses
    import warnings

    import numpy as np

    from roc_tpu.fleet import FleetRouter, InProcTransport, Replica, \
        ReplicationLog
    from roc_tpu.obs.watchdog import PerfWatchdog
    from roc_tpu.serve.loadgen import percentile
    from roc_tpu.serve.queue import Overloaded

    assert n_replicas >= 2, "--fleet wants at least 2 replicas"
    cfg = dataclasses.replace(cfg, aggregate_backend="binned")
    tmp = tempfile.mkdtemp(prefix="roc_fleet_bench_")
    wd = PerfWatchdog()
    reps = [Replica(f"bench-{i}", cfg, ds, model, ckpt or None,
                    os.path.join(tmp, f"bench-{i}.wal"), watchdog=wd)
            for i in range(n_replicas)]
    replog = ReplicationLog(reps[0].engine)
    for rep in reps[1:]:
        rep.transport = replog.attach(InProcTransport())
    router = FleetRouter(reps[0], reps[1:], replog, freshness_floor=0,
                         max_retries=1, watchdog=wd)
    rng = np.random.default_rng(23)
    n = ds.graph.num_nodes
    futures, lags = [], []
    shed = 0
    try:
        for rep in reps:
            rep.engine.warmup()
        # open-loop offer schedule (same anchor discipline as
        # serve/loadgen.run_load; raw clock for the same reason)
        t0 = time.perf_counter()  # roclint: allow(raw-timing) — open-loop offer schedule anchor, same discipline as loadgen
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", RuntimeWarning)
            for i in range(n_requests):
                target = t0 + i / qps
                delay = target - time.perf_counter()  # roclint: allow(raw-timing) — open-loop offer schedule anchor, same discipline as loadgen
                if delay > 0:
                    time.sleep(delay)
                if i % 10 == 5:   # delta churn rides the query stream
                    router.apply_delta(rng.integers(0, n, (2, 2)), None)
                    live = [r for r in reps[1:] if r.alive]
                    lags.append(max((r.last_lag_s for r in live),
                                    default=0.0))
                k = int((1, 3, 8)[i % 3])
                try:
                    futures.append(router.submit(
                        rng.integers(0, n, size=k)))
                except Overloaded:
                    shed += 1   # typed backpressure is an output here
        for f in futures:
            f.result(120.0)
        wall = time.perf_counter() - t0  # roclint: allow(raw-timing) — open-loop offer schedule anchor, same discipline as loadgen
        lats = sorted(f.latency_s for f in futures)
        lags.sort()
        st = router.stats()
        return {
            "replicas": int(n_replicas),
            "n_requests": int(n_requests),
            "p50_s": round(percentile(lats, 0.50), 6),
            "p99_s": round(percentile(lats, 0.99), 6),
            "qps_offered": round(qps, 3),
            "qps_achieved": round(len(futures) / max(wall, 1e-9), 3),
            "shed": int(shed),
            "shed_rate": round(shed / max(n_requests, 1), 6),
            "sibling_retries": int(st["sibling_retries"]),
            "lag_p50_s": round(percentile(lags, 0.50), 6),
            "lag_p99_s": round(percentile(lags, 0.99), 6),
            "segments_shipped": int(st["replog"]["segments_shipped"]),
            "records_shipped": int(st["replog"]["records_shipped"]),
            "catch_ups": int(st["catch_ups"]),
            "scale_events": len(st["scale_events"]),
        }
    finally:
        router.close()


def write_artifact(payload: dict, root: str = ROOT) -> str:
    path = os.path.join(root, "BENCH_SERVE.json")
    with open(path, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=1, sort_keys=True)
        f.write("\n")
    return path


def selftest() -> int:
    """Tiny CPU end-to-end into a tmp root; the artifact must pass the
    perf-ledger schema gate byte-for-byte as a real run's would."""
    tmp = tempfile.mkdtemp(prefix="roc_serve_bench_")
    os.environ["ROC_PLAN_CACHE_DIR"] = os.path.join(tmp, "plan_cache")
    os.environ["ROC_PLAN_CACHE_MIN_EDGES"] = "0"
    os.environ.setdefault("ROC_SERVE_BATCH", "8")
    os.environ.setdefault("ROC_SERVE_WAIT_MS", "1.0")
    payload = run_bench("roc-audit", n_requests=40, qps=500.0, fleet=3)
    path = write_artifact(payload, root=tmp)
    assert payload["plan_builds"] == 0, (
        f"warm cold start rebuilt {payload['plan_builds']} plan(s)")
    assert payload["delta"]["batches"] > 0 and \
        payload["delta"]["apply_p50_s"] > 0, "delta block did not measure"
    fl = payload["fleet"]
    assert fl["replicas"] == 3 and fl["segments_shipped"] > 0 and \
        fl["lag_p99_s"] > 0, "fleet block did not measure replication"
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import perf_ledger
    errs = perf_ledger.check(root=tmp)
    assert not errs, f"BENCH_SERVE.json failed the schema gate: {errs}"
    dl = payload["delta"]
    print(f"# serve_bench selftest: OK — p50={payload['p50_s'] * 1e3:.2f}ms "
          f"p99={payload['p99_s'] * 1e3:.2f}ms at "
          f"{payload['qps_offered']} qps offered, warm cold start "
          f"{payload['cold_start_s']:.3f}s, plan_builds=0; delta apply "
          f"p50={dl['apply_p50_s'] * 1e3:.2f}ms "
          f"p99={dl['apply_p99_s'] * 1e3:.2f}ms over {dl['batches']} "
          f"batches, replans={dl['replans']}; fleet({fl['replicas']}) "
          f"p99={fl['p99_s'] * 1e3:.2f}ms shed_rate={fl['shed_rate']:.3f} "
          f"lag_p99={fl['lag_p99_s'] * 1e3:.2f}ms over "
          f"{fl['segments_shipped']} segments ({path})")
    return 0


def main(argv) -> int:
    if "--selftest" in argv:
        return selftest()
    fleet = _env("ROC_SERVE_BENCH_FLEET", "0", int)
    if "--fleet" in argv:
        i = argv.index("--fleet")
        if i + 1 >= len(argv):
            raise SystemExit("--fleet needs a replica count")
        fleet = int(argv[i + 1])
    payload = run_bench(
        _env("ROC_SERVE_BENCH_DATASET", "roc-audit", str),
        _env("ROC_SERVE_BENCH_REQUESTS", "200", int),
        _env("ROC_SERVE_BENCH_QPS", "100.0", float),
        ckpt=os.environ.get("ROC_SERVE_BENCH_CKPT", ""),
        fleet=fleet)
    path = write_artifact(payload)
    print(json.dumps(payload))
    print(f"# serve_bench: wrote {path}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
