"""In-graph metrics channel: scalars that ride the jitted step's outputs.

The contract (DESIGN.md §Observability): metrics computed *inside* jit may
add **zero host syncs** (roclint's host-sync rule stays clean — nothing
here calls device_get/asarray under trace), **zero collectives** (the
static budget audit diffs collective op counts; a metrics build must not
move them), and **zero retraces** (the obs flag keys the step cache once;
epochs 2..N still hit).  That pins the design:

  * grad/param norms are computed on values that are ALREADY replicated —
    grads after the step's existing psum, params after the update — so a
    replicated `P()` out-spec needs no new collective;
  * per-exchange wire bytes are a *trace-time Python constant* (the
    exchange geometry — send rows, feature width, wire dtype — is static
    metadata), folded in as a literal;
  * per-shard edge counts reduce only the shard's own block
    (`P(PARTS_AXIS)` out-spec: one scalar per device, no exchange).

The host fetches the whole metrics pytree once per epoch with the same
`jax.device_get` cadence as eval — after the epoch's timed window, so the
fetch never pollutes `epoch_times`.
"""

from __future__ import annotations

from typing import Iterable

import jax
import jax.numpy as jnp


def global_norm(tree) -> jnp.ndarray:
    """L2 norm over every leaf of a pytree (fp32 accumulation)."""
    leaves = [l for l in jax.tree.leaves(tree) if hasattr(l, "dtype")]
    if not leaves:
        return jnp.float32(0.0)
    total = sum(jnp.sum(jnp.square(l.astype(jnp.float32))) for l in leaves)
    return jnp.sqrt(total)


def wire_itemsize(xch_dtype: str, xch_comp: str = "plain") -> int:
    """Effective bytes per exchanged fp32-equivalent element: bf16 plain
    halves the wire, compensated (hi, lo bf16 pair) is fp32-width again."""
    item = 2 if xch_dtype == "bf16" else 4
    if xch_comp == "compensated":
        item *= 2
    return item


def exchange_rows(exchange: str, num_parts: int, rows_per_shard: int,
                  send_cols: int = 0) -> int:
    """Feature rows ONE device puts on the wire per exchange round.

    halo: the send map ships ``send_cols`` rows to each of ``num_parts``
    destinations (send_idx is [P, P, K]); allgather: the shard contributes
    its padded ``rows_per_shard`` once (fan-out is the fabric's job, not
    payload); ring: the shard's rows forwarded on each of P-1 hops."""
    if exchange == "halo":
        return num_parts * send_cols
    if exchange == "ring":
        return max(num_parts - 1, 0) * rows_per_shard
    return rows_per_shard  # allgather / single-device all_gather


def wire_bytes_per_step(exchange: str, num_parts: int, rows_per_shard: int,
                        widths: Iterable[int], send_cols: int = 0,
                        xch_dtype: str = "fp32",
                        xch_comp: str = "plain") -> int:
    """Static per-device wire bytes for one train step: one exchange per
    aggregation at each feature width in ``widths`` (a GCN forward
    exchanges at every layer's output width; backward re-exchanges — the
    caller decides which passes to count).  Pure Python on static
    geometry: fold the result into the traced program as a constant."""
    rows = exchange_rows(exchange, num_parts, rows_per_shard, send_cols)
    item = wire_itemsize(xch_dtype, xch_comp)
    return int(rows * item * sum(int(w) for w in widths))
