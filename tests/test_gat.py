"""Edge-tensor ops + GAT model tests.

The reference leaves edge tensors latent (create_edge_tensor,
gnn.cc:534-589, never produced by a live op); these tests pin the TPU
realization: edge softmax and attention aggregation against dense NumPy,
sharded == single-device equality (the edge-partitioned path), and
end-to-end GAT training on the synthetic oracle.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from roc_tpu import ops
from roc_tpu.graph import datasets
from roc_tpu.models import build_gat
from roc_tpu.parallel.spmd import SpmdTrainer
from roc_tpu.train.config import Config
from roc_tpu.train.driver import Trainer


def graph_and_x(seed=3, n=150, h=6):
    ds = datasets.synthetic("t", n, 4.0, 8, 4, n_train=30, n_val=30,
                            n_test=30, seed=seed)
    g = ds.graph
    x = np.random.default_rng(seed).normal(size=(g.num_nodes, h)).astype(
        np.float32)
    return ds, g, x


def test_edge_softmax_normalizes():
    _, g, _ = graph_and_x()
    rng = np.random.default_rng(0)
    scores = rng.normal(size=(g.num_edges, 3)).astype(np.float32)
    alpha = np.asarray(ops.edge_softmax(jnp.asarray(scores),
                                        jnp.asarray(g.dst_idx), g.num_nodes))
    # per-destination sums == 1 wherever the vertex has in-edges
    sums = np.zeros((g.num_nodes, 3), np.float32)
    np.add.at(sums, g.dst_idx, alpha)
    has_edges = np.diff(g.row_ptr) > 0
    np.testing.assert_allclose(sums[has_edges], 1.0, rtol=1e-5)
    # matches a direct NumPy softmax per destination
    v = int(np.argmax(np.diff(g.row_ptr)))
    sl = slice(int(g.row_ptr[v]), int(g.row_ptr[v + 1]))
    expect = np.exp(scores[sl] - scores[sl].max(0))
    expect /= expect.sum(0)
    np.testing.assert_allclose(alpha[sl], expect, rtol=1e-5)


def test_gat_attend_matches_dense():
    _, g, x = graph_and_x(h=8)
    K, F = 2, 4
    h = x.reshape(g.num_nodes, K, F)
    rng = np.random.default_rng(7)
    a_src = rng.normal(size=(K, F)).astype(np.float32)
    a_dst = rng.normal(size=(K, F)).astype(np.float32)
    out = np.asarray(ops.gat_attend(
        jnp.asarray(h), jnp.asarray(h), jnp.asarray(g.col_idx),
        jnp.asarray(g.dst_idx), g.num_nodes, jnp.asarray(a_src),
        jnp.asarray(a_dst), 0.2))

    # dense reference
    s = np.einsum("nkf,kf->nk", h, a_dst)[g.dst_idx] \
        + np.einsum("nkf,kf->nk", h, a_src)[g.col_idx]
    s = np.where(s >= 0, s, 0.2 * s)
    expect = np.zeros_like(h)
    for v in range(g.num_nodes):
        sl = slice(int(g.row_ptr[v]), int(g.row_ptr[v + 1]))
        if sl.start == sl.stop:
            continue
        a = np.exp(s[sl] - s[sl].max(0))
        a /= a.sum(0)
        expect[v] = np.einsum("ek,ekf->kf", a, h[g.col_idx[sl]])
    np.testing.assert_allclose(out, expect, rtol=1e-4, atol=1e-5)


def test_chunked_gat_matches_dense(monkeypatch):
    """The memory-bounded edge-chunked GAT path (taken automatically above
    2^28 gathered elements — Reddit-scale GAT would OOM a 16 GB chip
    otherwise) must match the dense path up to float reassociation, in
    value AND gradient."""
    from roc_tpu.ops import edge as edge_mod

    _, g, x = graph_and_x(h=8)
    K, F = 2, 4
    h = jnp.asarray(x.reshape(g.num_nodes, K, F))
    rng = np.random.default_rng(11)
    a_src = jnp.asarray(rng.normal(size=(K, F)).astype(np.float32))
    a_dst = jnp.asarray(rng.normal(size=(K, F)).astype(np.float32))
    args = (h, h, jnp.asarray(g.col_idx), jnp.asarray(g.dst_idx),
            g.num_nodes, a_src, a_dst, 0.2)

    dense = np.asarray(ops.gat_attend(*args))
    # force the chunked path with a tiny chunk so the scan has many steps
    # (floor included — otherwise the 1024-edge minimum masks the shrink)
    monkeypatch.setattr(edge_mod, "_GAT_CHUNK_THRESHOLD_ELEMS", 1)
    monkeypatch.setattr(edge_mod, "_GAT_CHUNK_TARGET_ELEMS", 16 * K * F)
    monkeypatch.setattr(edge_mod, "_GAT_CHUNK_MIN", 16)
    chunked = np.asarray(ops.gat_attend(*args))
    np.testing.assert_allclose(chunked, dense, rtol=1e-5, atol=1e-5)

    def loss(hh):
        return jnp.sum(ops.gat_attend(hh, hh, jnp.asarray(g.col_idx),
                                      jnp.asarray(g.dst_idx), g.num_nodes,
                                      a_src, a_dst, 0.2) ** 2)
    gc = jax.grad(loss)(h)                        # chunked (threshold = 1)
    monkeypatch.setattr(edge_mod, "_GAT_CHUNK_THRESHOLD_ELEMS", 1 << 60)
    gd = jax.grad(loss)(h)                        # dense
    np.testing.assert_allclose(np.asarray(gc), np.asarray(gd),
                               rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("configs", [
    # fast lane: one representative shape; the other shapes ride the slow
    # lane (each config compiles 6 programs — value+grad for both impls)
    [(3, 150, 3, 5)],
    pytest.param([(7, 333, 1, 16), (11, 64, 4, 3)], marks=pytest.mark.slow),
])
def test_gat_plan_matches_dense_and_grads(configs):
    """Plan-backend attention (ops.gat_attend_plan — scatter-free chunk-plan
    softmax/aggregation) must match the dense oracle in value and in every
    gradient (its backward is hand-derived, not autodiff)."""
    for seed, n, K, F in configs:
        ds = datasets.synthetic("t", n, 4.0, 8, 4, n_train=10, n_val=10,
                                n_test=10, seed=seed)
        g = ds.graph
        N = g.num_nodes
        rng = np.random.default_rng(seed)
        h = jnp.asarray(rng.normal(size=(N, K, F)).astype(np.float32))
        a_s = jnp.asarray(rng.normal(size=(K, F)).astype(np.float32))
        a_d = jnp.asarray(rng.normal(size=(K, F)).astype(np.float32))
        es, ed = jnp.asarray(g.col_idx), jnp.asarray(g.dst_idx)
        plans = ops.build_gat_plans(g.col_idx, g.dst_idx, N, N)
        ref = ops.gat_attend(h, h, es, ed, N, a_s, a_d, 0.2)
        got = ops.gat_attend_plan(h, h, a_s, a_d, plans, (es, ed), 0.2)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=1e-4, atol=1e-5)

        def loss_ref(h, a_s, a_d):
            return jnp.sum(jnp.sin(
                ops.gat_attend(h, h, es, ed, N, a_s, a_d, 0.2)))

        def loss_plan(h, a_s, a_d):
            return jnp.sum(jnp.sin(
                ops.gat_attend_plan(h, h, a_s, a_d, plans, (es, ed), 0.2)))
        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(h, a_s, a_d)
        gp = jax.grad(loss_plan, argnums=(0, 1, 2))(h, a_s, a_d)
        for a, b in zip(gr, gp):
            np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                       rtol=1e-3, atol=1e-4)


def test_gat_plan_multistep_scan_matches_oracle():
    """A graph big enough that _plan_max/_plan_sum run MULTIPLE scan steps
    (chunk count > the per-step block), with large-magnitude scores so a
    wrong softmax max cannot hide behind shift-invariance.  Pins the
    window-vs-row accumulator indexing (caught broken in review: every
    step after the first wrote maxima to the wrong windows)."""
    from roc_tpu.ops import edge as em
    ds = datasets.synthetic("t", 2000, 20.0, 8, 4, n_train=10, n_val=10,
                            n_test=10, seed=5)
    g = ds.graph
    N, K, F = g.num_nodes, 2, 4
    plans = ops.build_gat_plans(g.col_idx, g.dst_idx, N, N)
    assert plans.dst_obi.shape[0] > em._PLAN_CB_MAX, \
        "graph too small to exercise the multi-step path"
    rng = np.random.default_rng(5)
    # 20x scale: exp(s - wrong_m) visibly diverges or overflows
    h = jnp.asarray(20 * rng.normal(size=(N, K, F)).astype(np.float32))
    a_s = jnp.asarray(rng.normal(size=(K, F)).astype(np.float32))
    a_d = jnp.asarray(rng.normal(size=(K, F)).astype(np.float32))
    es, ed = jnp.asarray(g.col_idx), jnp.asarray(g.dst_idx)
    # _plan_max against the NumPy segment-max oracle
    s = np.einsum("nkf,kf->nk", np.asarray(h), np.asarray(a_d))[g.dst_idx] \
        + np.einsum("nkf,kf->nk", np.asarray(h), np.asarray(a_s))[g.col_idx]
    s = np.where(s >= 0, s, 0.2 * s).astype(np.float32)
    mo = np.full((N, K), -np.inf, np.float32)
    np.maximum.at(mo, g.dst_idx, s)
    m = np.asarray(em._plan_max(jnp.asarray(s), plans.dst_obi,
                                plans.dst_edst, plans.dst_pos, N))
    np.testing.assert_allclose(m, mo, rtol=1e-5, atol=1e-5)
    # end-to-end against the dense oracle
    ref = ops.gat_attend(h, h, es, ed, N, a_s, a_d, 0.2)
    got = ops.gat_attend_plan(h, h, a_s, a_d, plans, (es, ed), 0.2)
    assert np.isfinite(np.asarray(got)).all()
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-3, atol=1e-3)


@pytest.mark.slow
def test_gat_plan_training_matches_xla():
    """End-to-end GAT training with -aggr-backend matmul (which routes
    attention through the plan backend) must track the xla-backend run."""
    ds, g, _ = graph_and_x(n=200)
    layers = [ds.in_dim, 8, ds.num_classes]

    def run(backend):
        cfg = Config(layers=layers, num_epochs=5, dropout_rate=0.0,
                     learning_rate=0.01, weight_decay=0.0, eval_every=10**9,
                     model="gat", heads=2, aggregate_backend=backend)
        tr = Trainer(cfg, ds, build_gat(layers, 0.0, heads=2))
        return [float(tr.run_epoch()) for _ in range(5)], tr

    lx, _ = run("xla")
    lm, tr = run("matmul")
    assert tr.gdata.gat_plans is not None, "plan backend not engaged"
    np.testing.assert_allclose(lm, lx, rtol=1e-3)


def test_gat_plan_sharded_equals_single():
    """Plan attention under halo vertex sharding: 4-part run must match the
    single-device xla run epoch for epoch."""
    ds, g, _ = graph_and_x(n=220)
    layers = [ds.in_dim, 6, ds.num_classes]
    cfg1 = Config(layers=layers, num_epochs=2, dropout_rate=0.0,
                  eval_every=10**9)
    cfgP = Config(layers=layers, num_epochs=2, dropout_rate=0.0,
                  eval_every=10**9, num_parts=4, halo=True,
                  aggregate_backend="matmul")
    t1 = Trainer(cfg1, ds, build_gat(layers, 0.0, heads=2))
    tp = SpmdTrainer(cfgP, ds, build_gat(layers, 0.0, heads=2))
    assert tp.gdata.gat_plans is not None, "plan backend not engaged"
    for i in range(2):
        l1, lp = float(t1.run_epoch()), float(tp.run_epoch())
        np.testing.assert_allclose(lp, l1, rtol=1e-4, err_msg=f"epoch {i}")
    m1 = jax.device_get(t1.evaluate())
    mp = jax.device_get(tp.evaluate())
    assert int(m1.train_correct) == int(mp.train_correct)
    assert int(m1.val_correct) == int(mp.val_correct)


def test_gat_ring_attention_equals_single():
    """-exchange ring + GAT = literal ring attention (online softmax over
    rotating shards, two-buffer memory, no source table).  Must train
    equal to the single-device and halo runs up to fp32 reassociation."""
    ds, g, _ = graph_and_x(n=220)
    layers = [ds.in_dim, 6, ds.num_classes]
    base = dict(layers=layers, num_epochs=3, dropout_rate=0.0,
                eval_every=10**9, edge_shard="off")
    t1 = Trainer(Config(**base), ds, build_gat(layers, 0.0, heads=2))
    th = SpmdTrainer(Config(**base, num_parts=4, halo=True), ds,
                     build_gat(layers, 0.0, heads=2))
    tr = SpmdTrainer(Config(**base, num_parts=4, exchange="ring"), ds,
                     build_gat(layers, 0.0, heads=2))
    assert tr.gdata.mode == "ring"
    for i, rtol in enumerate((2e-5, 5e-3, 5e-3)):
        l1 = float(t1.run_epoch())
        lh = float(th.run_epoch())
        lr = float(tr.run_epoch())
        np.testing.assert_allclose(lr, l1, rtol=rtol, err_msg=f"epoch {i}")
        np.testing.assert_allclose(lr, lh, rtol=rtol, err_msg=f"epoch {i}")
    m1 = jax.device_get(t1.evaluate())
    mr = jax.device_get(tr.evaluate())
    assert int(m1.val_correct) == int(mr.val_correct)


def test_gat_edge_shard_equals_single():
    """-edge-shard + GAT (the last model x distribution cell): block-local
    scores, pmax softmax shift, psum_scatter normalizer/output.  Must
    train equal to the single-device and halo runs."""
    ds, g, _ = graph_and_x(n=220)
    layers = [ds.in_dim, 6, ds.num_classes]
    base = dict(layers=layers, num_epochs=3, dropout_rate=0.0,
                eval_every=10**9)
    t1 = Trainer(Config(**base, edge_shard="off"), ds,
                 build_gat(layers, 0.0, heads=2))
    te = SpmdTrainer(Config(**base, num_parts=4, edge_shard=True), ds,
                     build_gat(layers, 0.0, heads=2))
    assert te.gdata.mode == "edge"
    for i, rtol in enumerate((2e-5, 5e-3, 5e-3)):
        l1, le = float(t1.run_epoch()), float(te.run_epoch())
        np.testing.assert_allclose(le, l1, rtol=rtol, err_msg=f"epoch {i}")
    m1 = jax.device_get(t1.evaluate())
    me = jax.device_get(te.evaluate())
    assert int(m1.val_correct) == int(me.val_correct)


def test_gat_edge_shard_plan_equals_single_and_scatter_free():
    """Edge-sharded GAT on the PLAN backend (edge_gat_attend, round 4):
    must train equal to the single-device run, and the compiled sharded
    train step must contain no HLO scatter op — the autodiff-backward
    serialized-scatter pathology VERDICT r3 item 5 flagged is gone
    (reduce-scatter, the collective, is fine and expected)."""
    import re

    ds, g, _ = graph_and_x(n=220)
    layers = [ds.in_dim, 6, ds.num_classes]
    base = dict(layers=layers, num_epochs=3, dropout_rate=0.0,
                eval_every=10**9)
    t1 = Trainer(Config(**base, edge_shard="off"), ds,
                 build_gat(layers, 0.0, heads=2))
    te = SpmdTrainer(Config(**base, num_parts=4, edge_shard=True,
                            aggregate_backend="matmul"), ds,
                     build_gat(layers, 0.0, heads=2))
    assert te.gdata.mode == "edge" and te.gdata.gat_plans is not None
    for i, rtol in enumerate((2e-5, 5e-3, 5e-3)):
        l1, le = float(t1.run_epoch()), float(te.run_epoch())
        np.testing.assert_allclose(le, l1, rtol=rtol, err_msg=f"epoch {i}")
    m1 = jax.device_get(t1.evaluate())
    me = jax.device_get(te.evaluate())
    assert int(m1.val_correct) == int(me.val_correct)

    # compiled-text check: no scatter op anywhere in the fwd+bwd step
    # (matches " scatter(" but not "reduce-scatter(" / "select-and-scatter(")
    txt = te._train_step.lower(
        te.params, te.opt_state, te.x, te.labels, te.mask, te.gdata,
        jax.random.key(0), jnp.float32(0.01),
        np.float32(1.0)).compile().as_text()
    hits = re.findall(r"(?<![\w-])scatter\(", txt)
    assert not hits, f"compiled step still contains {len(hits)} scatter ops"


@pytest.mark.slow
def test_gat_plan_perhost_equals_full_load(tmp_path):
    """Plan attention under -perhost (per-host `.lux` slice loading):
    the per-host-built, floor-padded plans must train identically to the
    full-load sharded run."""
    from roc_tpu.graph import lux

    ds, g, _ = graph_and_x(n=240)
    prefix = str(tmp_path / "g")
    lux.write_dataset(prefix, ds.graph, ds.features, ds.label_ids, ds.mask)
    layers = [ds.in_dim, 6, ds.num_classes]
    base = dict(layers=layers, num_epochs=2, dropout_rate=0.0,
                eval_every=10**9, num_parts=4, halo=True,
                aggregate_backend="matmul")
    tp = SpmdTrainer(Config(**base), ds, build_gat(layers, 0.0, heads=2))
    from roc_tpu.graph import datasets as dsets
    ds_stub = dsets.load_roc_dataset(prefix, ds.in_dim, ds.num_classes,
                                     graph_stub=True)
    th = SpmdTrainer(Config(**base, perhost_load=True, filename=prefix),
                     ds_stub, build_gat(layers, 0.0, heads=2))
    assert th.gdata.gat_plans is not None, "perhost plan attention off"
    for i in range(2):
        lp, lh = float(tp.run_epoch()), float(th.run_epoch())
        np.testing.assert_allclose(lh, lp, rtol=1e-4, err_msg=f"epoch {i}")


def test_gat_training_learns():
    ds, g, _ = graph_and_x(n=200)
    cfg = Config(layers=[ds.in_dim, 8, ds.num_classes], num_epochs=30,
                 dropout_rate=0.0, learning_rate=0.01, weight_decay=0.0,
                 eval_every=10**9, model="gat", heads=2)
    tr = Trainer(cfg, ds, build_gat(cfg.layers, 0.0, heads=2))
    first = float(tr.run_epoch())
    for _ in range(29):
        last = float(tr.run_epoch())
    assert last < first * 0.5, (first, last)
    m = jax.device_get(tr.evaluate())
    assert int(m.train_correct) / max(int(m.train_all), 1) > 0.6


@pytest.mark.parametrize("halo", [
    # all_gather exchange rides the slow lane: same code path shape as
    # halo, and every non-GAT sharded test covers halo=False fast
    pytest.param(False, marks=pytest.mark.slow), True])
def test_gat_sharded_equals_single(halo):
    ds, g, _ = graph_and_x(n=220)
    layers = [ds.in_dim, 6, ds.num_classes]
    cfg1 = Config(layers=layers, num_epochs=2, dropout_rate=0.0,
                  eval_every=10**9)
    cfgP = Config(layers=layers, num_epochs=2, dropout_rate=0.0,
                  eval_every=10**9, num_parts=4, halo=halo)
    t1 = Trainer(cfg1, ds, build_gat(layers, 0.0, heads=2))
    tp = SpmdTrainer(cfgP, ds, build_gat(layers, 0.0, heads=2))
    for i in range(2):
        l1, lp = float(t1.run_epoch()), float(tp.run_epoch())
        np.testing.assert_allclose(lp, l1, rtol=1e-4, err_msg=f"epoch {i}")
    m1 = jax.device_get(t1.evaluate())
    mp = jax.device_get(tp.evaluate())
    assert int(m1.train_correct) == int(mp.train_correct)
    assert int(m1.val_correct) == int(mp.val_correct)


def test_gat_cli_registry():
    from roc_tpu.models import build_model
    m = build_model("gat", [8, 4, 3], 0.5, heads=2)
    kinds = [op.kind for op in m.ops]
    assert "gat" in kinds and "aggregate" not in kinds
