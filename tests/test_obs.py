"""Observability tests (roc_tpu/obs): tracer schema + nesting, metrics
channel parity, zero retraces with obs on, watchdog behavior, the span
overhead bound, and the raw-timing lint rule.

The parity tests are the load-bearing ones: `-obs` must be a pure
*observer* — bitwise-identical losses/params vs an obs-off run, zero new
traces across epochs and a same-cut reshard — or the metrics channel is
changing the thing it measures.
"""

import json
import os

import jax
import numpy as np
import pytest

from roc_tpu import obs
from roc_tpu.analysis import AuditSpec, build_audit_trainer, lint
from roc_tpu.analysis.retrace import RetraceGuard
from roc_tpu.graph import datasets
from roc_tpu.models import build_gcn
from roc_tpu.obs import report as obs_report
from roc_tpu.obs.tracer import SpanTracer, validate_chrome_trace
from roc_tpu.obs.watchdog import PerfWatchdog, seed_for_graph
from roc_tpu.parallel.spmd import SpmdTrainer
from roc_tpu.train.config import Config
from roc_tpu.train.driver import Trainer


@pytest.fixture(autouse=True)
def _obs_reset():
    """Trainers with -obs flip the process-global tracer on; restore it so
    obs state never leaks across tests."""
    tr = obs.get_tracer()
    prev = tr.enabled
    yield
    tr.enabled = prev
    tr.clear()


def _dataset(n=80, deg=3.0, in_dim=8, classes=3, seed=13):
    return datasets.synthetic("t", n, deg, in_dim, classes, n_train=20,
                              n_val=20, n_test=20, seed=seed)


# -- tracer ----------------------------------------------------------------

def test_span_nesting_and_chrome_schema():
    tr = SpanTracer(capacity=16)
    tr.enabled = True
    with tr.span("outer", epoch=1):
        with tr.span("inner"):
            pass
        with tr.span("inner"):
            pass
    spans = tr.spans()
    assert [s.name for s in spans] == ["inner", "inner", "outer"]
    assert [s.depth for s in spans] == [1, 1, 0]
    outer = spans[-1]
    assert outer.args == {"epoch": 1}
    assert outer.dur_ns >= sum(s.dur_ns for s in spans[:2])
    trace = tr.to_chrome_trace()
    assert validate_chrome_trace(trace) == []
    json.dumps(trace)  # Perfetto needs real JSON, not just a dict
    ev = trace["traceEvents"][-1]
    assert ev["ph"] == "X" and ev["name"] == "outer"
    assert ev["args"] == {"epoch": 1}


def test_disabled_span_times_but_records_nothing():
    tr = SpanTracer()
    assert not tr.enabled
    with tr.span("quiet") as sp:
        pass
    assert sp.dur_s > 0          # dur_s is the repo's timing primitive
    assert tr.spans() == []      # ...but nothing lands in the ring


def test_tracer_ring_capacity_bounds_memory():
    tr = SpanTracer(capacity=4)
    tr.enabled = True
    for i in range(10):
        with tr.span(f"s{i}"):
            pass
    assert len(tr.spans()) == 4
    assert tr.spans()[-1].name == "s9"


def test_validate_chrome_trace_flags_bad_events():
    assert validate_chrome_trace([]) != []
    assert validate_chrome_trace({"traceEvents": [{"ph": "X"}]}) != []
    assert validate_chrome_trace(
        {"traceEvents": [{"ph": "X", "name": "a", "ts": "oops", "dur": 1,
                          "pid": 1, "tid": 1}]}) != []


# -- watchdog --------------------------------------------------------------

def test_watchdog_fires_on_injected_slow_epoch():
    wd = PerfWatchdog()
    for epoch in range(5):
        assert wd.observe_epoch(epoch, 0.1) is None
    alert = wd.observe_epoch(5, 0.3)
    assert alert is not None and alert["kind"] == "slow-epoch"
    assert alert["ratio"] == pytest.approx(3.0, rel=0.05)
    assert wd.verdict() == "regressed"
    # outlier clamping: the anomaly must not poison the EWMA it was
    # measured against — the next normal epoch stays quiet
    assert wd.observe_epoch(6, 0.1) is None


def test_watchdog_quiet_on_noise():
    wd = PerfWatchdog()
    noise = [0.1, 0.102, 0.098, 0.101, 0.099, 0.103, 0.097, 0.1]
    assert all(wd.observe_epoch(i, t) is None for i, t in enumerate(noise))
    assert wd.verdict() == "ok" and wd.alerts == []


def test_watchdog_seeded_is_armed_from_epoch_zero():
    wd = PerfWatchdog(seed_s=0.1)
    alert = wd.observe_epoch(0, 0.5)
    assert alert is not None and alert["ewma_s"] == pytest.approx(0.1)
    # unseeded: epoch 0 carries compile time and never trips the detector
    assert PerfWatchdog().observe_epoch(0, 99.0) is None


def test_watchdog_straggler_detection():
    wd = PerfWatchdog()
    assert wd.observe_shards(0, [0.1, 0.1, 0.1, 0.1]) == []
    alerts = wd.observe_shards(1, [0.1, 0.1, 0.1, 0.5])
    assert len(alerts) == 1 and alerts[0]["part"] == 3
    assert alerts[0]["kind"] == "straggler"
    assert wd.verdict() == "straggler"
    # degenerate inputs never fire
    assert wd.observe_shards(2, [0.1]) == []
    assert wd.observe_shards(3, [0.0, 0.0]) == []


def test_watchdog_budget_seed():
    """reddit_scaled is pinned in tools/kernel_budgets.json: the seed is
    its committed steps_total x the binned per-grid-step overhead."""
    from roc_tpu.ops.pallas.binned import _CHUNK_OVERHEAD_S
    seed = seed_for_graph(32768, 4194304)
    assert seed == pytest.approx(3358 * _CHUNK_OVERHEAD_S)
    assert seed_for_graph(17, 17) is None  # unpinned shape -> warmup EWMA


# -- metrics registry ------------------------------------------------------

def test_metrics_registry_shares_telemetry_schema(tmp_path):
    path = str(tmp_path / "m.jsonl")
    reg = obs.MetricsRegistry(jsonl_path=path)
    reg.emit("metrics", epoch=0, loss=1.5, grad_norm=2.0)
    reg.emit("metrics", epoch=1, loss=1.25, grad_norm=1.0)
    reg.emit("watchdog", kind="slow-epoch", epoch=1, ratio=3.0)
    recs = obs.load_jsonl(path)
    # every record rides the balance-telemetry envelope: {"type": kind, ...}
    assert [r["type"] for r in recs] == ["metrics", "metrics", "watchdog"]
    assert recs[1]["loss"] == 1.25
    assert reg.series("metrics", "loss") == [1.5, 1.25]
    assert reg.of_kind("watchdog")[0]["ratio"] == 3.0
    prom = str(tmp_path / "m.prom")
    assert reg.write_prometheus(prom)
    text = open(prom).read()
    assert "roc_metrics_loss 1.25" in text
    assert "roc_metrics_grad_norm 1" in text


def test_load_jsonl_skips_torn_lines(tmp_path):
    path = tmp_path / "torn.jsonl"
    path.write_text('{"type": "metrics", "epoch": 0}\n{"type": "me')
    assert obs.load_jsonl(str(path)) == [{"type": "metrics", "epoch": 0}]


# -- driver integration ----------------------------------------------------

def _trainer(obs_on, tmp_path=None, **kw):
    cfg = dict(layers=[8, 4, 3], num_epochs=4, eval_every=1000,
               dropout_rate=0.0, obs=obs_on)
    if obs_on:
        cfg["obs_dir"] = str(tmp_path / "obs") if tmp_path else ""
    cfg.update(kw)
    cfg = Config(**cfg)
    return Trainer(cfg, _dataset(), build_gcn(cfg.layers, 0.0))


def test_obs_is_a_pure_observer(tmp_path):
    """Losses and params of an obs-on run are bitwise identical to the
    obs-off run: the metrics channel observes the step, never changes it."""
    ta = _trainer(False)
    tb = _trainer(True, tmp_path)
    for _ in range(4):
        la = float(jax.device_get(ta.run_epoch()))
        lb = float(jax.device_get(tb.run_epoch()))
        assert la == lb  # bitwise, not approx
    for ka in ta.params:
        np.testing.assert_array_equal(np.asarray(ta.params[ka]),
                                      np.asarray(tb.params[ka]))


def test_metrics_channel_values(tmp_path):
    """The in-graph metrics match an independent host-side recompute."""
    from roc_tpu.obs import channel
    tr = _trainer(True, tmp_path)
    tr.run_epoch()
    vals = jax.device_get(tr._last_step_metrics)
    # param_norm was computed in-graph on the updated params — recompute
    # from the live (updated) param pytree
    expect = float(jax.jit(channel.global_norm)(tr.params))
    assert float(vals["param_norm"]) == pytest.approx(expect, rel=1e-6)
    assert float(vals["grad_norm"]) > 0.0
    assert float(vals["wire_bytes"]) == 0.0   # single device: no wire
    assert int(vals["edges"][0]) == int(
        np.asarray(jax.device_get(tr.gdata.in_degree)).sum())


def test_obs_train_artifacts_and_span_types(tmp_path):
    """A -obs run emits a Perfetto-loadable trace with >= 8 span types and
    the unified JSONL metrics stream."""
    obs.get_tracer().clear()
    tr = _trainer(True, tmp_path, num_epochs=4, eval_every=2,
                  aggregate_backend="matmul", checkpoint_every=2,
                  checkpoint_path=str(tmp_path / "ck.npz"))
    tr.train(print_fn=lambda *a, **k: None)
    types = obs.get_tracer().span_types()
    assert {"train", "epoch", "step_dispatch", "device_sync",
            "metrics_fetch", "eval", "checkpoint", "plan_build"} <= types
    assert len(types) >= 8
    trace = json.load(open(tmp_path / "obs" / "trace.json"))
    assert validate_chrome_trace(trace) == []
    recs = obs.load_jsonl(str(tmp_path / "obs" / "metrics.jsonl"))
    kinds = [r["type"] for r in recs]
    assert kinds.count("metrics") == 4 and kinds[-1] == "train"
    for r in recs:
        if r["type"] == "metrics":
            assert {"epoch", "wall_s", "loss", "grad_norm", "param_norm",
                    "wire_bytes", "edges_per_shard"} <= set(r)
    assert recs[-1]["watchdog_verdict"] in ("ok", "regressed", "straggler")
    assert (tmp_path / "obs" / "metrics.prom").exists()
    # the report CLI's renderer digests both artifacts
    text = obs_report.report(str(tmp_path / "obs" / "trace.json"),
                             str(tmp_path / "obs" / "metrics.jsonl"))
    assert "step_dispatch" in text and "verdict" in text


def test_spmd_obs_wire_bytes_and_shard_edges(tmp_path):
    """SPMD halo run: wire_bytes reflects the exchange accounting and
    edges land per-shard (out_spec P(PARTS_AXIS))."""
    ds = _dataset(n=400, deg=4.0, in_dim=16, classes=4, seed=3)
    cfg = Config(layers=[16, 16, 4], num_epochs=3, num_parts=4, halo=True,
                 eval_every=1000, dropout_rate=0.0, obs=True,
                 obs_dir=str(tmp_path / "obs"))
    tr = SpmdTrainer(cfg, ds, build_gcn(cfg.layers, 0.0))
    tr.train(print_fn=lambda *a, **k: None)
    recs = [r for r in obs.load_jsonl(str(tmp_path / "obs" / "metrics.jsonl"))
            if r["type"] == "metrics"]
    assert len(recs) == 3
    last = recs[-1]
    assert last["wire_bytes"] > 0
    assert len(last["edges_per_shard"]) == 4
    assert sum(last["edges_per_shard"]) > 0
    from roc_tpu.obs import channel
    gd = tr.gdata
    expect = channel.wire_bytes_per_step(
        "halo", 4, tr.part.shard_nodes, tr._aggregate_widths(),
        send_cols=gd.send_idx.shape[-1] if gd.send_idx is not None else 0,
        xch_dtype=gd.xch_dtype, xch_comp=gd.xch_comp)
    assert last["wire_bytes"] == expect


def test_zero_retraces_with_obs(monkeypatch, tmp_path):
    """The obs acceptance bar: 3 epochs + a same-cut reshard with the
    metrics channel riding the step add ZERO retraces (mirror of
    test_analysis.py::test_zero_retraces_across_epochs_and_reshard)."""
    monkeypatch.setenv("ROC_OBS", "1")
    monkeypatch.setenv("ROC_OBS_DIR", str(tmp_path / "obs"))
    spec = AuditSpec("gcn", 2, "matmul", "halo")
    tr = build_audit_trainer(spec)
    assert tr.config.obs
    tr.config.num_epochs = 3
    with RetraceGuard(warmup=1) as g:
        tr.train(print_fn=lambda *a, **k: None)
        assert g.counts["train_step"] >= 1
        snap = g.snapshot()
        step_ids = (id(tr._train_step), id(tr._eval_step))
        tr.reshard(tr.part.bounds)           # same cut, same shapes
        assert (id(tr._train_step), id(tr._eval_step)) == step_ids
        g.arm()
        tr.run_epoch()
        tr.evaluate()
        g.assert_no_new_traces(snap)


def test_obs_toggle_is_in_the_step_cache_key(monkeypatch, tmp_path):
    """Flipping obs on the same SPMD trainer rebuilds the step (4-tuple
    out) instead of aliasing the cached 3-tuple callable."""
    monkeypatch.setenv("ROC_OBS_DIR", str(tmp_path / "obs"))
    spec = AuditSpec("gcn", 2, "matmul", "halo")
    tr = build_audit_trainer(spec)
    assert not tr.config.obs
    off_step = tr._train_step
    tr.config.obs = True
    tr._obs_init()
    tr._build_steps(tr.gdata)
    assert tr._train_step is not off_step
    tr.run_epoch()
    assert tr._last_step_metrics is not None


# -- overhead gate ---------------------------------------------------------

def test_span_overhead_bound():
    """Per-span cost (the always-on steady state) stays under the report
    gate; obs measures itself — no raw clocks in this test.  Best-of-3:
    a scheduler hiccup on a loaded CI box can smear one probe loop, and
    the honest statistic for "what does a span cost" is the quiet run."""
    tr = SpanTracer()
    tr.enabled = True
    reps = 2000
    best = float("inf")
    for _ in range(3):
        with tr.span("gate") as gate:
            for _ in range(reps):
                with tr.span("probe"):
                    pass
        best = min(best, gate.dur_s / reps)
        if best < obs_report.MAX_SPAN_OVERHEAD_S:
            break
    assert best < obs_report.MAX_SPAN_OVERHEAD_S


def test_obs_epoch_overhead_within_two_percent(tmp_path):
    """Accounting form of the <=2% CPU overhead acceptance bar: the obs
    spans' own cost per epoch (span bookkeeping + the one metrics fetch)
    against the measured epoch wall time."""
    ds = _dataset(n=2000, deg=6.0, in_dim=32, classes=4, seed=5)
    cfg = Config(layers=[32, 32, 4], num_epochs=6, eval_every=1000,
                 dropout_rate=0.0, obs=True, obs_dir=str(tmp_path / "obs"))
    tr = Trainer(cfg, ds, build_gcn(cfg.layers, 0.0))
    obs.get_tracer().clear()
    tr.train(print_fn=lambda *a, **k: None)
    epochs = sorted(s.dur_s for s in obs.get_tracer().spans()
                    if s.name == "epoch")
    med_epoch = epochs[len(epochs) // 2]
    fetches = [s.dur_s for s in obs.get_tracer().spans()
               if s.name == "metrics_fetch"]
    # measure the per-span bookkeeping cost with obs itself — best-of-3,
    # so a loaded box charging one smeared probe loop to obs cannot
    # fail the 2% accounting below
    probe = SpanTracer()
    probe.enabled = True
    reps = 1000
    per_span = float("inf")
    for _ in range(3):
        with probe.span("gate") as gate:
            for _ in range(reps):
                with probe.span("p"):
                    pass
        per_span = min(per_span, gate.dur_s / reps)
    spans_per_epoch = len(obs.get_tracer().spans()) / max(len(epochs), 1)
    cost = spans_per_epoch * per_span + sorted(fetches)[len(fetches) // 2]
    assert cost <= 0.02 * med_epoch, (cost, med_epoch)


def test_selftest_passes():
    msgs = []
    assert obs_report.selftest(out=msgs.append) == 0
    assert any("ok" in m for m in msgs)


# -- config ----------------------------------------------------------------

def test_profile_window_parsing(monkeypatch):
    assert Config().profile_window() == (3, 3)
    assert Config(profile_epochs="0:1").profile_window() == (0, 1)
    with pytest.raises(SystemExit):
        Config(profile_epochs="nope")
    with pytest.raises(SystemExit):
        Config(profile_epochs="3")
    with pytest.raises(SystemExit):
        Config(profile_epochs="-1:2")
    monkeypatch.setenv("ROC_PROFILE_EPOCHS", "5:2")
    assert Config().profile_window() == (5, 2)


def test_obs_env_mirror(monkeypatch):
    monkeypatch.setenv("ROC_OBS", "1")
    cfg = Config()
    assert cfg.obs and cfg.obs_dir == "roc_obs"
    monkeypatch.setenv("ROC_OBS_DIR", "/tmp/elsewhere")
    assert Config().obs_dir == "/tmp/elsewhere"
    monkeypatch.setenv("ROC_OBS", "0")
    assert not Config().obs


# -- raw-timing lint rule --------------------------------------------------

_TIMING_SRC = ("import time\n"
               "def bench(fn):\n"
               "    t0 = time.perf_counter()\n"
               "    fn()\n"
               "    return time.perf_counter() - t0\n")


def test_lint_raw_timing_positive():
    fs = lint.lint_source(_TIMING_SRC, "roc_tpu/train/somefile.py")
    assert any(f.rule == "raw-timing" for f in fs), fs
    # perf_counter_ns windows count too
    src_ns = _TIMING_SRC.replace("perf_counter()", "perf_counter_ns()")
    fs = lint.lint_source(src_ns, "roc_tpu/train/somefile.py")
    assert any(f.rule == "raw-timing" for f in fs), fs
    # module-level windows (script idiom) count too
    src_mod = ("import time\nt0 = time.perf_counter()\nwork()\n"
               "dt = time.perf_counter() - t0\n")
    fs = lint.lint_source(src_mod, "tools/somescript.py")
    assert any(f.rule == "raw-timing" for f in fs), fs


def test_lint_raw_timing_exemptions():
    # roc_tpu/obs/ is the sanctioned clock site
    assert lint.lint_source(_TIMING_SRC, "roc_tpu/obs/tracer.py") == []
    # inline fixtures (non-.py paths) never fire the rule
    assert [f for f in lint.lint_source(_TIMING_SRC, "<string>")
            if f.rule == "raw-timing"] == []
    # a start with no `- t0` use is not a timing window
    src = "import time\ndef f():\n    t0 = time.perf_counter()\n    return 0\n"
    assert lint.lint_source(src, "roc_tpu/train/x.py") == []
    # waivers work like every other rule
    waived = _TIMING_SRC.replace(
        "t0 = time.perf_counter()",
        "t0 = time.perf_counter()  # roclint: allow(raw-timing)")
    assert lint.lint_source(waived, "roc_tpu/train/x.py") == []


# -- calibration ledger ----------------------------------------------------

def _fresh_ledger():
    from roc_tpu.obs.ledger import CalibrationLedger
    return CalibrationLedger()


def test_ledger_content_key_is_order_insensitive():
    from roc_tpu.obs.ledger import content_key
    assert content_key(rows=4, edges=9) == content_key(edges=9, rows=4)
    assert content_key(rows=4, edges=9) == "edges=9|rows=4"


def test_ledger_predict_measure_join_and_ratio():
    led = _fresh_ledger()
    led.predict("plan_steps", "e=9|n=4", 100, "steps")
    r = led.measure("plan_steps", "e=9|n=4", 150, "steps")
    assert r == pytest.approx(1.5)
    kinds = [k for k, _ in led.records]
    assert kinds == ["prediction", "measurement"]
    meas = led.records[-1][1]
    assert meas["predicted"] == 100.0 and meas["ratio"] == pytest.approx(1.5)
    # a different content key does NOT join
    assert led.measure("plan_steps", "e=7|n=4", 150, "steps") is None
    # re-predicting overwrites: the join pairs against the newest
    led.predict("plan_steps", "e=9|n=4", 300, "steps")
    assert led.measure("plan_steps", "e=9|n=4", 150, "steps") \
        == pytest.approx(0.5)


def test_ledger_emission_is_gated_on_attach(tmp_path):
    from roc_tpu.obs.metrics import MetricsRegistry
    led = _fresh_ledger()
    led.predict("x", "k=1", 1.0, "s")          # detached: no sink, no error
    reg = MetricsRegistry(jsonl_path=str(tmp_path / "m.jsonl"))
    led.attach(reg.emit)
    led.predict("step_time", "k=1", 2.0, "s")
    led.measure("step_time", "k=1", 3.0, "s")
    led.detach()
    led.measure("step_time", "k=1", 9.0, "s")  # detached again: not emitted
    kinds = [k for k, _ in reg.records]
    assert kinds == ["prediction", "measurement"]


def test_ledger_drain_ratios_feeds_and_clears():
    led = _fresh_ledger()
    led.predict("m", "k", 2.0, "s")
    led.measure("m", "k", 4.0, "s")
    assert led.drain_ratios() == [("m", 2.0)]
    assert led.drain_ratios() == []            # drained


def test_ledger_validate_and_offline_join():
    from roc_tpu.obs.ledger import calibration_report, join, validate_records
    stream = [
        {"type": "prediction", "model": "m", "key": "k", "value": 2.0,
         "units": "s"},
        {"type": "measurement", "model": "m", "key": "k", "value": 3.0,
         "units": "s"},                        # unpaired in-stream: re-joins
        {"type": "metrics", "wall_s": 0.1},    # foreign kinds pass through
    ]
    assert validate_records(stream) == []
    joined = join(stream)
    assert joined[0]["ratio"] == pytest.approx(1.5)
    rep = calibration_report(stream)
    assert rep["models"]["m"]["pairs"] == 1
    assert rep["models"]["m"]["ratio_mean"] == pytest.approx(1.5)
    # broken records are named, not crashed on
    bad = [{"type": "measurement", "model": "m", "key": "k", "value": 1.0,
            "units": "s", "ratio": 2.0}]       # ratio without predicted
    assert validate_records(bad)


def test_watchdog_calibration_drift_fires_and_quiet():
    wd = PerfWatchdog(warmup=2)
    # in-band ratios never alert, regardless of count
    for _ in range(6):
        assert wd.observe_calibration("plan_steps", 1.1) is None
    # out-of-band model: warmup pairs build the EWMA silently, then fire
    assert wd.observe_calibration("step_time", 5.0, epoch=0) is None
    assert wd.observe_calibration("step_time", 5.0, epoch=1) is None
    alert = wd.observe_calibration("step_time", 5.0, epoch=2)
    assert alert is not None and alert["kind"] == "calibration-drift"
    assert alert["model"] == "step_time"
    assert wd.verdict() == "calibration-drift"
    # a non-positive ratio is a broken pair, not drift
    assert wd.observe_calibration("peak_memory", 0.0) is None


def test_report_renders_unknown_span_and_alert_kinds():
    """The report is generic over span names and alert kinds: a kind
    invented after this renderer was written must show up, not fall into
    some slow-epoch-shaped else branch."""
    trace = {"traceEvents": [
        {"name": "never_seen_span", "ph": "X", "ts": 0, "dur": 1500.0,
         "pid": 1, "tid": 1}]}
    lines = "\n".join(obs_report.summarize_trace(trace))
    assert "never_seen_span" in lines
    records = [
        {"type": "somefuturekind", "x": 1},
        {"type": "watchdog", "kind": "flux-capacitor", "epoch": 3,
         "overcharge": 1.21},
    ]
    txt = "\n".join(obs_report.summarize_metrics(records))
    assert "somefuturekind x1" in txt          # census counts unknown kinds
    assert "flux-capacitor" in txt
    assert "overcharge=1.21" in txt            # numeric fields render generically


# -- Prometheus export format ----------------------------------------------

def test_prometheus_labeled_gauges_and_escaping(tmp_path):
    from roc_tpu.obs.metrics import MetricsRegistry
    reg = MetricsRegistry(jsonl_path="")
    reg.emit("epoch", wall_s=0.25)
    reg.set_gauge("calibration_ratio", 1.5, model="plan_steps")
    # label values with every escape-worthy character
    reg.set_gauge("calibration_ratio", 2.0, model='we"ird\\mo\ndel')
    path = str(tmp_path / "prom.txt")
    assert reg.write_prometheus(path)
    text = open(path, encoding="utf-8").read()
    assert 'roc_calibration_ratio{model="plan_steps"} 1.5' in text
    assert r'model="we\"ird\\mo\nmodel"' not in text  # name kept intact...
    assert r'we\"ird\\mo\ndel' in text                # ...escaped, not mangled
    assert "roc_epoch_wall_s 0.25" in text
    assert "\n\n" not in text.strip()


def test_prometheus_skips_nonfinite_and_updates_latest(tmp_path):
    from roc_tpu.obs.metrics import MetricsRegistry
    reg = MetricsRegistry(jsonl_path="")
    reg.emit("epoch", loss=float("nan"), wall_s=float("inf"), ok=3.0)
    reg.set_gauge("calibration_ratio", float("nan"), model="m")
    path = str(tmp_path / "prom.txt")
    assert reg.write_prometheus(path)
    text = open(path, encoding="utf-8").read()
    assert "nan" not in text and "inf" not in text
    assert "roc_epoch_ok 3" in text
    # a later finite value for the same series replaces the skip
    reg.emit("epoch", loss=0.5)
    reg.set_gauge("calibration_ratio", 1.25, model="m")
    assert reg.write_prometheus(path)
    text = open(path, encoding="utf-8").read()
    assert "roc_epoch_loss 0.5" in text
    assert 'roc_calibration_ratio{model="m"} 1.25' in text


def test_measurement_records_auto_export_calibration_gauge(tmp_path):
    """The registry turns ledger measurement records into per-model
    roc_calibration_ratio{model=...} gauges without extra wiring."""
    from roc_tpu.obs.metrics import MetricsRegistry
    led = _fresh_ledger()
    reg = MetricsRegistry(jsonl_path="")
    led.attach(reg.emit)
    led.predict("wire_bytes", "k=1", 100, "B")
    led.measure("wire_bytes", "k=1", 110, "B")
    led.detach()
    path = str(tmp_path / "prom.txt")
    assert reg.write_prometheus(path)
    text = open(path, encoding="utf-8").read()
    assert 'roc_calibration_ratio{model="wire_bytes"} 1.1' in text


# -- perf ledger (tools/perf_ledger.py) ------------------------------------

def _perf_ledger_mod():
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "perf_ledger", os.path.join(os.path.dirname(__file__), "..",
                                    "tools", "perf_ledger.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _write_rounds(root, rounds):
    for n, env in rounds:
        with open(os.path.join(root, f"BENCH_r{n:02d}.json"), "w") as f:
            json.dump(env, f)


def test_perf_ledger_fold_and_schema(tmp_path):
    pl = _perf_ledger_mod()
    root = str(tmp_path)
    _write_rounds(root, [
        (1, {"n": 1, "cmd": "python bench.py", "rc": 1,
             "tail": "RuntimeError: tunnel wedged",
             "parsed": {"metric": "epoch_time", "value": None, "unit": "s",
                        "error": "RuntimeError: tunnel wedged"}}),
        (2, {"n": 2, "cmd": "python bench.py", "rc": 0, "tail": "",
             "parsed": {"metric": "epoch_time", "value": 0.7, "unit": "s",
                        "mfu": 0.002, "roofline_frac": 0.06,
                        "fusion": "mega"}}),
    ])
    with open(os.path.join(root, "BENCH_LAST_HW.json"), "w") as f:
        json.dump({"metric": "epoch_time", "value": 0.7, "unit": "s",
                   "measured_at": "2026-08-02T00:00:00Z"}, f)
    assert pl.check(root) == []
    traj = pl.fold(root)
    assert [r["round"] for r in traj["rounds"]] == [1, 2]
    assert traj["rounds"][0]["error"]           # failed round keeps receipt
    assert traj["rounds"][1]["mfu"] == 0.002
    assert traj["last_hw"]["value"] == 0.7
    md = pl.markdown(traj)
    assert "| 2 | 0 | epoch_time | 0.7 | s |" in md
    assert "fusion=mega" in md                  # leg-distinguishing stamps
    assert "tunnel wedged" in md                # failure line is data


def test_perf_ledger_check_flags_malformed(tmp_path):
    pl = _perf_ledger_mod()
    root = str(tmp_path)
    _write_rounds(root, [
        (1, {"n": 7, "cmd": "x", "rc": 0, "tail": "",   # n != filename
             "parsed": {"metric": "m", "unit": "s"}}),  # value missing,
    ])                                                  # no error either
    errs = pl.check(root)
    assert any("n=7" in e for e in errs)
    assert any("parsed.value" in e for e in errs)


def test_perf_ledger_md_block_is_idempotent(tmp_path):
    pl = _perf_ledger_mod()
    root = str(tmp_path)
    os.makedirs(os.path.join(root, "docs"))
    with open(os.path.join(root, "docs", "PERF.md"), "w") as f:
        f.write("# PERF\n\nhand-written content\n")
    _write_rounds(root, [(1, {"n": 1, "cmd": "x", "rc": 0, "tail": "",
                              "parsed": {"metric": "m", "value": 1.0,
                                         "unit": "s"}})])
    table = pl.markdown(pl.fold(root))
    assert pl.update_perf_md(table, root)
    assert pl.update_perf_md(table, root)       # second run must replace
    text = open(os.path.join(root, "docs", "PERF.md")).read()
    assert text.count(pl.MD_BEGIN) == 1
    assert "hand-written content" in text       # never clobbers prose
