"""Crash-consistent dynamic-graph deltas (roc_tpu/serve/delta.py).

The contract under test mirrors the acceptance gates:

- journal recovery matrix: torn tail truncated on open, CRC bit-rot
  with valid frames after it -> typed DeltaJournalError, sequence gap
  -> typed DeltaJournalError, kill windows on either side of the
  journal fsync / the replan swap / the checkpoint replay to the exact
  served state, and the same spec with the journal disabled
  demonstrably loses the deltas;
- parity: after >= 1000 mixed add/retire deltas the patched plans
  produce BITWISE-identical aggregation to a from-scratch rebuild of
  the mutated graph (integer-valued features — exactly representable
  sums), and served engine logits match a rebuilt engine within the
  32-ULP serving gate, with ZERO retraces and ZERO plan rebuilds on
  the patch path (both pinned);
- degradation ladder: a capacity-exhausting batch escalates to a
  background full replan while the OLD plan keeps serving, the atomic
  swap lands at a window boundary, counters exported;
- validation-or-reject: malformed/out-of-range input raises DeltaError
  and the journal records NOTHING; idempotent no-ops are counted and
  warned once; close()/in-flight-mutation resolves every pending
  future.
"""

import dataclasses
import os
import threading
import time
import warnings

import numpy as np
import pytest

import jax.numpy as jnp

from roc_tpu import obs
from roc_tpu.fault import inject
from roc_tpu.graph.csr import from_edges, with_edge_delta
from roc_tpu.ops.aggregate import BinnedPlans
from roc_tpu.ops.pallas import binned
from roc_tpu.serve.delta import (DeltaError, DeltaJournal,
                                 DeltaJournalError, DeltaManager,
                                 _PlanPatcher, _strip_fused)
from roc_tpu.train.driver import DenseGraphData


@pytest.fixture(autouse=True)
def _lock_order_witness(lock_witness):
    # every delta test runs under the armed lock-order witness; any
    # acquisition order outside threads.json fails at teardown
    yield


# -- fixtures ---------------------------------------------------------------

N_NODES = 96
N_EDGES = 200     # the single (block, bin) cell pads to 256: headroom 56


def _graph(seed=3, n=N_NODES, e=N_EDGES):
    # base edges live on nodes 0..63 only: any edge touching a node
    # >= 64 is deterministically fresh (adds) or dead (retires)
    rng = np.random.default_rng(seed)
    return from_edges(n, rng.integers(0, 64, e), rng.integers(0, 64, e))


def _gdata(csr):
    s = np.asarray(csr.col_idx, np.int64)
    d = np.asarray(csr.dst_idx, np.int64)
    n = csr.num_nodes
    fwd = binned.build_binned_plan(s, d, n, n, tuned_ok=False)
    bwd = binned.build_binned_plan(d, s, n, n, tuned_ok=False)
    return DenseGraphData(
        edge_src=jnp.asarray(s, jnp.int32),
        edge_dst=jnp.asarray(d, jnp.int32),
        in_degree=jnp.asarray(np.bincount(d, minlength=n), jnp.float32),
        plans=BinnedPlans(fwd=fwd, bwd=bwd),
        backend="binned", precision="exact")


def _manager(csr, journal_path, **kw):
    holder = {"gd": _gdata(csr)}
    mgr = DeltaManager(lambda: holder["gd"],
                       lambda g: holder.__setitem__("gd", g),
                       threading.RLock(), csr.num_nodes,
                       journal_path=journal_path, **kw)
    return holder, mgr


def _plan_bytes(holder):
    gd = holder["gd"]
    return b"".join(np.asarray(a).tobytes() for a in (
        gd.plans.fwd.p1_srcl, gd.plans.fwd.p2_dstl,
        gd.plans.bwd.p1_srcl, gd.plans.bwd.p2_dstl))


def _agg(holder, x):
    """One forward aggregation through the resident fwd plan."""
    return np.asarray(binned.run_binned(x, holder["gd"].plans.fwd,
                                        interpret=True))


def _quiet_apply(mgr, *a, **kw):
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        return mgr.apply(*a, **kw)


# edges guaranteed fresh against _graph()'s 0..63 base
_F1 = np.asarray([[70, 71], [72, 73]])
_F2 = np.asarray([[80, 81]])


# -- journal recovery matrix (pure I/O, no jax work) ------------------------

def _rec(n):
    return (np.arange(2 * n, dtype=np.int64).reshape(n, 2),
            np.zeros((0, 2), np.int64))


def test_journal_roundtrip_and_truncate(tmp_path):
    p = str(tmp_path / "j.wal")
    j = DeltaJournal(p)
    for seq in (1, 2, 3):
        j.append(seq, *_rec(seq))
    j.close()
    j2 = DeltaJournal(p)
    assert [r[0] for r in j2.records] == [1, 2, 3]
    assert j2.base_seq == 0 and j2.last_seq == 3
    np.testing.assert_array_equal(j2.records[2][1], _rec(3)[0])
    j2.truncate_to(3)
    assert j2.records == [] and j2.base_seq == 3
    j2.append(4, *_rec(1))
    j2.close()
    j3 = DeltaJournal(p)
    assert j3.base_seq == 3 and [r[0] for r in j3.records] == [4]
    j3.close()


def test_journal_torn_tail_truncated(tmp_path):
    p = str(tmp_path / "j.wal")
    j = DeltaJournal(p)
    j.append(1, *_rec(2))
    size_good = os.path.getsize(p)
    j.append(2, *_rec(2))
    j.close()
    # crash mid-frame: chop the final record short
    with open(p, "r+b") as f:
        f.truncate(size_good + 7)
    j2 = DeltaJournal(p)
    assert [r[0] for r in j2.records] == [1]
    assert j2.torn_bytes == 7
    assert os.path.getsize(p) == size_good     # tail gone from disk too
    j2.close()


def test_journal_bitrot_is_typed_error(tmp_path):
    p = str(tmp_path / "j.wal")
    j = DeltaJournal(p)
    j.append(1, *_rec(2))
    off_mid = os.path.getsize(p) - 10   # inside record 1's payload
    j.append(2, *_rec(2))
    j.close()
    with open(p, "r+b") as f:
        f.seek(off_mid)
        b = f.read(1)
        f.seek(off_mid)
        f.write(bytes([b[0] ^ 0xFF]))
    with pytest.raises(DeltaJournalError, match="bit rot"):
        DeltaJournal(p)


def test_journal_sequence_gap_is_typed_error(tmp_path):
    p = str(tmp_path / "j.wal")
    j = DeltaJournal(p)
    j.append(1, *_rec(1))
    j.append(3, *_rec(1))   # append frames what it is told; the scan
    j.close()               # is where monotonicity is enforced
    with pytest.raises(DeltaJournalError, match="sequence gap"):
        DeltaJournal(p)


def test_journal_bad_magic_and_header(tmp_path):
    p = str(tmp_path / "j.wal")
    DeltaJournal(p).close()
    with open(p, "r+b") as f:
        f.write(b"XXXX")
    with pytest.raises(DeltaJournalError, match="bad magic"):
        DeltaJournal(p)
    with open(p, "wb") as f:
        f.write(b"RDJ1\x00")
    with pytest.raises(DeltaJournalError, match="header"):
        DeltaJournal(p)


# -- kill-window chaos: every site replays exactly --------------------------

@pytest.mark.parametrize("site,recorded", [
    ("delta.journal.kill_record", False),   # lost BEFORE the WAL: gone
    ("delta.journal.kill_fsync", True),     # written + flushed: replays
    ("delta.journal.kill_ack", True),       # durable, patch never ran
])
def test_journal_kill_windows_replay_exactly(tmp_path, site, recorded):
    csr = _graph()
    jp = str(tmp_path / "j.wal")
    holder, mgr = _manager(csr, jp)
    _quiet_apply(mgr, _F1, None)
    inject.configure(f"seed=2,{site}=1")
    try:
        with pytest.raises(inject.SimulatedCrash):
            _quiet_apply(mgr, _F2, None)
    finally:
        inject.configure("")
    # restart over fresh frozen artifacts + the surviving journal
    holder2, mgr2 = _manager(csr, jp)
    # fault-free oracle applies exactly the batches the WAL promised
    oh, om = _manager(csr, str(tmp_path / "oracle.wal"))
    _quiet_apply(om, _F1, None)
    if recorded:
        _quiet_apply(om, _F2, None)
    assert mgr2._seq == om._seq
    assert mgr2.counters["replayed"] == (2 if recorded else 1)
    assert _plan_bytes(holder2) == _plan_bytes(oh)
    for m in (mgr2, om):
        m.close()


def _escalating_batch(k=80):
    # unique fresh edges, enough to overflow the 56-row headroom
    i = np.arange(k)
    return np.stack([64 + i % 32, (7 * i + 1) % N_NODES], 1)


@pytest.mark.parametrize("site", ["delta.swap.kill_pre",
                                  "delta.swap.kill_post"])
def test_swap_kill_windows_replay_exactly(tmp_path, site):
    csr = _graph()
    jp = str(tmp_path / "j.wal")
    holder, mgr = _manager(csr, jp)
    big = _escalating_batch()
    inject.configure(f"seed=2,{site}=1")
    try:
        with pytest.raises(DeltaError) as ei:
            _quiet_apply(mgr, big, None, wait_replan=True)
        assert isinstance(ei.value.__cause__, inject.SimulatedCrash)
    finally:
        inject.configure("")
    mgr.close()
    # the escalating batch hit the WAL before the swap died: restart
    # replays it through a (synchronous) replay replan to swapped state
    holder2, mgr2 = _manager(csr, jp)
    oh, om = _manager(csr, str(tmp_path / "oracle.wal"))
    _quiet_apply(om, big, None, wait_replan=True)
    assert mgr2._rebuilt and mgr2._seq == om._seq
    assert _plan_bytes(holder2) == _plan_bytes(oh)
    x = jnp.asarray(np.eye(N_NODES, 8, dtype=np.float32))
    np.testing.assert_array_equal(_agg(holder2, x), _agg(oh, x))
    for m in (mgr2, om):
        m.close()


@pytest.mark.parametrize("site", ["delta.ckpt.kill_tmp",
                                  "delta.ckpt.kill_rename",
                                  "delta.ckpt.kill_snap"])
def test_checkpoint_kill_windows_consistent(tmp_path, site):
    csr = _graph()
    jp = str(tmp_path / "j.wal")
    holder, mgr = _manager(csr, jp)
    _quiet_apply(mgr, _F1, None)
    _quiet_apply(mgr, None, np.asarray([[70, 71]]))
    inject.configure(f"seed=2,{site}=1")
    try:
        with pytest.raises(inject.SimulatedCrash):
            mgr.checkpoint()
    finally:
        inject.configure("")
    # whichever side of the snapshot write / journal truncate the kill
    # landed on, the restart reaches the exact pre-crash served state
    holder2, mgr2 = _manager(csr, jp)
    oh, om = _manager(csr, str(tmp_path / "oracle.wal"))
    _quiet_apply(om, _F1, None)
    _quiet_apply(om, None, np.asarray([[70, 71]]))
    assert mgr2._seq == om._seq
    assert _plan_bytes(holder2) == _plan_bytes(oh)
    for m in (mgr2, om):
        m.close()


def test_journal_disabled_demonstrably_loses_deltas(tmp_path):
    csr = _graph()
    holder, mgr = _manager(csr, None)          # volatile: no WAL
    _quiet_apply(mgr, _F1, None)
    mutated = _plan_bytes(holder)
    assert mgr.stats()["journal"] is None
    mgr.close()
    # "restart": a fresh volatile manager over the frozen artifacts has
    # nothing to replay — the deltas are gone (back to the base plans)
    holder2, mgr2 = _manager(csr, None)
    assert _plan_bytes(holder2) != mutated
    mgr2.close()


# -- parity: >= 1000 mixed deltas, bitwise vs from-scratch rebuild ----------

def test_thousand_delta_bitwise_parity_zero_rebuilds(tmp_path):
    csr = _graph(seed=11)
    holder, mgr = _manager(csr, str(tmp_path / "j.wal"))
    n = csr.num_nodes
    # independent oracle: a live-edge multiset under the same semantics
    # (add is a no-op while any instance is live; retire drops one)
    counts: dict = {}
    for s, d in zip(csr.col_idx.tolist(), csr.dst_idx.tolist()):
        counts[(s, d)] = counts.get((s, d), 0) + 1
    rng = np.random.default_rng(4)
    builds0 = binned.plan_build_count()
    pending = []      # bounded in-flight set keeps cells inside headroom
    ops = 0
    while ops < 1000:
        adds = rng.integers(0, n, (5, 2))
        rets = None
        if len(pending) >= 30:
            rets = np.asarray([pending.pop(0) for _ in range(5)])
        r = _quiet_apply(mgr, adds, rets)
        assert r["mode"] in ("applied", "noop")
        pending.extend(map(tuple, adds.tolist()))
        for s, d in adds.tolist():
            if counts.get((s, d), 0) == 0:
                counts[(s, d)] = 1
        if rets is not None:
            for s, d in rets.tolist():
                if counts.get((s, d), 0) > 0:
                    counts[(s, d)] -= 1
        ops += len(adds) + (0 if rets is None else len(rets))
    st = mgr.stats()
    assert st["replans"] == 0, "parity churn must stay on the patch path"
    assert binned.plan_build_count() == builds0, "patch path rebuilt a plan"
    assert st["applied_adds"] + st["applied_retires"] \
        + st["noop_adds"] + st["noop_retires"] >= 1000
    assert st["cells_patched"] > 0

    # the manager's live store must equal the oracle multiset...
    live_s, live_d = mgr._live_edges()
    got = sorted(zip(live_s.tolist(), live_d.tolist()))
    want = sorted(sd for sd, c in counts.items() for _ in range(c))
    assert got == want
    # ...and the patched plans must aggregate bitwise-identically to a
    # from-scratch rebuild of that multiset (integer-valued features:
    # the sums are exact, so a different edge order cannot differ)
    oracle = from_edges(n, np.asarray([s for s, _ in want]),
                        np.asarray([d for _, d in want]))
    rebuilt = {"gd": _gdata(oracle)}
    x = jnp.asarray(rng.integers(-8, 9, (n, 16)).astype(np.float32))
    np.testing.assert_array_equal(_agg(holder, x), _agg(rebuilt, x))
    got_b = np.asarray(binned.run_binned(x, holder["gd"].plans.bwd,
                                         interpret=True))
    want_b = np.asarray(binned.run_binned(x, rebuilt["gd"].plans.bwd,
                                          interpret=True))
    np.testing.assert_array_equal(got_b, want_b)
    # in-degree repatched alongside the plans
    np.testing.assert_array_equal(
        np.asarray(holder["gd"].in_degree),
        np.bincount(np.asarray(oracle.dst_idx),
                    minlength=n).astype(np.float32))
    mgr.close()


# -- engine-level: served logits, zero retraces, degradation ladder ---------

def _serve_engine(ds, delta_journal, start_queue=False):
    from roc_tpu.models import build_model
    from roc_tpu.serve import ServeEngine
    from roc_tpu.train.config import Config
    cfg = Config(layers=[ds.in_dim, 16, ds.num_classes], dropout_rate=0.0,
                 eval_every=10**9, model="gcn", aggregate_backend="binned",
                 serve_batch=8, serve_wait_ms=1.0,
                 aggregate_precision="exact")
    m = build_model("gcn", cfg.layers, cfg.dropout_rate, cfg.aggr)
    return ServeEngine(cfg, ds, m, start_queue=start_queue,
                       delta_journal=delta_journal)


def test_engine_served_parity_after_churn_zero_retraces(tmp_path):
    from roc_tpu.graph import datasets
    from roc_tpu.serve import max_ulp_diff
    ds = datasets.get("roc-audit", seed=1)
    rng = np.random.default_rng(9)
    n = ds.graph.num_nodes
    eng = _serve_engine(ds, str(tmp_path / "j.wal"))
    try:
        eng.warmup()
        base = eng._guard.snapshot()
        builds0 = binned.plan_build_count()
        pending = []
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", RuntimeWarning)
            for _ in range(30):
                adds = rng.integers(0, n, (2, 2))
                rets = None
                if len(pending) >= 8:
                    rets = np.asarray([pending.pop(0), pending.pop(0)])
                eng.apply_delta(adds, rets)
                pending.extend(map(tuple, adds.tolist()))
        st = eng.delta_stats()
        assert st["replans"] == 0 and st["applied_adds"] > 0
        served = eng._serve_rows(np.arange(n, dtype=np.int32))
        eng._guard.assert_no_new_traces(base)       # ZERO retraces
        assert binned.plan_build_count() == builds0  # ZERO plan rebuilds
        # from-scratch oracle engine on the mutated graph, same params
        live_s, live_d = eng.deltas._live_edges()
        ds2 = dataclasses.replace(ds, graph=from_edges(n, live_s, live_d))
        oracle = _serve_engine(ds2, None)
        try:
            oracle.bundle.params = eng.bundle.params
            want = oracle._serve_rows(np.arange(n, dtype=np.int32))
            assert max_ulp_diff(served, want) <= 32
        finally:
            oracle.close()
    finally:
        eng.close()


def test_capacity_exhaustion_replan_while_serving(tmp_path):
    from roc_tpu.graph import datasets
    ds = datasets.get("roc-audit", seed=1)
    n = ds.graph.num_nodes
    eng = _serve_engine(ds, str(tmp_path / "j.wal"), start_queue=True)
    try:
        eng.warmup()
        i = np.arange(300)
        big = np.stack([i % n, (7 * i + 1) % n], 1)
        # stall the background replan so the serving-through-it window
        # is wide enough to assert against, not a race
        inject.configure("seed=1,slow_ms=300,delta.replan.slow=1")
        try:
            with warnings.catch_warnings():
                warnings.simplefilter("ignore", RuntimeWarning)
                r = eng.apply_delta(big, None)
            assert r["mode"] == "replanning"
            # the OLD plan keeps answering queries during the replan
            out = eng.query(np.arange(8, dtype=np.int32), timeout=60.0)
            assert out.shape == (8, ds.num_classes)
        finally:
            inject.configure("")
        deadline = time.time() + 60.0
        while eng.delta_stats()["swaps"] < 1:
            assert time.time() < deadline, "replan swap never landed"
            time.sleep(0.01)
        st = eng.stats()["deltas"]          # counters exported
        assert st["replans"] == 1 and st["swaps"] == 1 and st["rebuilt"]
        # and the swapped plan serves the mutated graph
        out = eng.query(np.arange(8, dtype=np.int32), timeout=60.0)
        assert np.all(np.isfinite(out))
    finally:
        eng.close()


def test_close_during_inflight_mutation_resolves_everything(tmp_path):
    from roc_tpu.graph import datasets
    ds = datasets.get("roc-audit", seed=1)
    n = ds.graph.num_nodes
    jp = str(tmp_path / "j.wal")
    eng = _serve_engine(ds, jp, start_queue=True)
    eng.warmup()
    fut = eng.submit(np.arange(4, dtype=np.int32))
    i = np.arange(300)
    big = np.stack([i % n, (7 * i + 1) % n], 1)
    inject.configure("seed=1,slow_ms=200,delta.replan.slow=1")
    applied = threading.Event()

    def mutate():
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", RuntimeWarning)
            eng.apply_delta(big, None)      # escalates; replan stalled
        applied.set()
    t = threading.Thread(target=mutate)
    t.start()
    try:
        assert applied.wait(30.0), "apply_delta never returned"
        # close while the replan is still in flight: must finish the
        # journaled batch (join the swap), drain the queue, resolve the
        # pending future — and not deadlock
        eng.close()
    finally:
        inject.configure("")
        t.join(30.0)
    assert fut.result(5.0).shape == (4, ds.num_classes)
    st = eng.delta_stats()
    assert st["swaps"] == 1, "close() did not finish the in-flight swap"
    with pytest.raises(DeltaError, match="closed"):
        eng.deltas.apply(np.asarray([[0, 1]]), None)
    # restart replays to the state close() finished (snapshot + journal)
    holder2, mgr2 = _manager(ds.graph, jp)
    assert mgr2._seq == st["seq"] and mgr2._rebuilt
    mgr2.close()


def test_engine_without_deltas_raises_typed():
    from roc_tpu.graph import datasets
    ds = datasets.get("roc-audit", seed=1)
    eng = _serve_engine(ds, None)
    try:
        with pytest.raises(DeltaError, match="delta_journal"):
            eng.apply_delta(np.asarray([[0, 1]]), None)
        assert eng.delta_stats() == {}
    finally:
        eng.close()


# -- validation, idempotence, counters --------------------------------------

def test_rejection_is_typed_and_never_journaled(tmp_path):
    csr = _graph()
    holder, mgr = _manager(csr, str(tmp_path / "j.wal"))
    before = _plan_bytes(holder)
    for bad_add in ([[0, N_NODES]], [[-1, 0]],
                    np.asarray([[0.5, 1.5]], np.float64)):
        with pytest.raises(DeltaError):
            mgr.apply(np.asarray(bad_add), None)
    assert mgr.journal.records == [] and mgr._seq == 0
    assert _plan_bytes(holder) == before, "rejected batch touched a plan"
    assert mgr.stats()["rejected"] == 3
    mgr.close()


def test_idempotent_noops_counted_and_warned_once(tmp_path):
    csr = _graph()
    holder, mgr = _manager(csr, str(tmp_path / "j.wal"))
    live = (int(csr.col_idx[0]), int(csr.dst_idx[0]))
    with pytest.warns(RuntimeWarning, match="idempotent"):
        r = mgr.apply(np.asarray([live]), np.asarray([[90, 91]]))
    assert r["mode"] == "noop"
    assert r["noop_adds"] == 1 and r["noop_retires"] == 1
    # pure-noop batches never consume a sequence number or a WAL record
    assert mgr._seq == 0 and mgr.journal.records == []
    with warnings.catch_warnings():
        warnings.simplefilter("error", RuntimeWarning)   # warned ONCE
        mgr.apply(np.asarray([live]), None)
    st = mgr.stats()
    assert st["noop_adds"] == 2 and st["noop_retires"] == 1
    assert st["batches"] == 2
    mgr.close()


def test_retire_then_readd_and_within_batch_ordering(tmp_path):
    csr = _graph()
    holder, mgr = _manager(csr, str(tmp_path / "j.wal"))
    e = (int(csr.col_idx[0]), int(csr.dst_idx[0]))
    r = _quiet_apply(mgr, None, np.asarray([e]))
    assert r["applied_retires"] == 1
    r = _quiet_apply(mgr, np.asarray([e]), None)     # re-add after retire
    assert r["applied_adds"] == 1
    # one batch adding then retiring the same NEW edge: both effective
    # (adds classify before retires), net zero live instances
    r = _quiet_apply(mgr, np.asarray([[70, 71]]), np.asarray([[70, 71]]))
    assert r["applied_adds"] == 1 and r["applied_retires"] == 1
    live_s, live_d = mgr._live_edges()
    assert not ((live_s == 70) & (live_d == 71)).any()
    mgr.close()


def test_with_edge_delta_oracle_helper():
    csr = _graph()
    g2 = with_edge_delta(csr, add=[[70, 71], [70, 71]], retire=[[70, 71]])
    assert g2.num_edges == csr.num_edges + 1
    with pytest.raises(KeyError):
        with_edge_delta(csr, retire=[[95, 94]])


# -- multi-cell layouts: both schedules, multiple groups --------------------

@pytest.mark.parametrize("flat", [0, 1])
def test_multigroup_cell_patch_bitwise(flat):
    # grt=512 forces bins_per_group=1 -> one group per destination bin,
    # exercising cross-group cell addressing in both schedules
    geom = binned.Geometry(512, 2048, 128, 512, 4096, grt=512, flat=flat)
    rng = np.random.default_rng(6)
    n, e = 1600, 4000
    s = rng.integers(0, n, e).astype(np.int64)
    d = rng.integers(0, n, e).astype(np.int64)
    order = np.argsort(d, kind="stable")
    s, d = s[order], d[order]
    plan = binned.build_binned_plan(s, d, n, n, geom=geom, tuned_ok=False)
    assert plan.p1_blk.shape[0] > 1, "geometry lever failed to multi-group"
    patcher = _PlanPatcher(_strip_fused(plan), s, d, swap=False)
    patcher.verify(s.tolist(), d.tolist(), "test")    # layout == builder
    lay = patcher.layout
    # pick three distinct cells with build-time headroom and aim one add
    # at each (an add may not overflow its cell's padded capacity)
    cells = lay.cells_of(s, d)
    occupancy = np.bincount(cells, minlength=lay.ncell)
    roomy = np.nonzero(lay.cell_cap - occupancy >= 4)[0][:3]
    assert len(roomy) == 3
    store_s, store_d = s.tolist(), d.tolist()
    gi0 = len(store_s)
    for ci in roomy:
        store_s.append(int(lay.cell_blk[ci]) * geom.sb)
        store_d.append(int(lay.cell_bin[ci]) * geom.rb)
    rets = [0, 1000, 2000]          # global indices of base edges
    touched = patcher.stage(store_s, store_d,
                            list(range(gi0, gi0 + len(roomy))), rets)
    assert touched is not None and len(touched) >= 3
    patcher.commit(store_s, store_d, touched)
    p1, p2 = patcher.device_arrays()
    patched = dataclasses.replace(_strip_fused(plan),
                                  p1_srcl=p1, p2_dstl=p2)
    live = np.ones(len(store_s), bool)
    live[rets] = False
    x = rng.integers(-4, 5, (n, 8)).astype(np.float32)
    got = np.asarray(binned.run_binned(jnp.asarray(x), patched,
                                       interpret=True))
    want = np.zeros((n, 8), np.float64)
    ls = np.asarray(store_s)[live]
    ld = np.asarray(store_d)[live]
    np.add.at(want, ld, x.astype(np.float64)[ls])
    np.testing.assert_array_equal(got, want.astype(np.float32))


def test_cell_overflow_raises_before_any_write():
    csr = _graph()
    s = np.asarray(csr.col_idx, np.int64)
    d = np.asarray(csr.dst_idx, np.int64)
    lay = binned.plan_cell_layout(s, d, N_NODES, N_NODES)
    p1, p2 = binned.empty_cell_arrays(lay)
    cap = int(lay.cell_cap[0])
    over = np.zeros(cap + 1, np.int64)
    with pytest.raises(binned.CellOverflowError):
        binned.patch_plan_cells(lay, p1, p2, 0, over, over)
    ref1, ref2 = binned.empty_cell_arrays(lay)
    np.testing.assert_array_equal(p1, ref1)   # nothing partially written
    np.testing.assert_array_equal(p2, ref2)


# -- observability ----------------------------------------------------------

def test_watchdog_delta_ewma_and_verdict():
    from roc_tpu.obs.watchdog import PerfWatchdog
    wd = PerfWatchdog(ratio=2.0, warmup=2)
    assert wd.observe_delta(0, 5.0) is None      # obs 0 never seeds
    assert wd.delta_ewma is None
    for i in range(1, 4):
        assert wd.observe_delta(i, 0.010) is None
    alert = wd.observe_delta(4, 0.500)
    assert alert is not None and alert["kind"] == "delta-apply"
    assert alert["ratio"] > 2.0
    assert wd.verdict() == "delta-apply"
    # serve-latency outranks delta-apply in the verdict ladder
    wd.alerts.append({"kind": "serve-latency"})
    assert wd.verdict() == "serve-latency"
    state = wd.state_dict()
    assert "delta_ewma" in state and "delta_observed" in state
    wd2 = PerfWatchdog()
    wd2.load_state(state)
    assert wd2.delta_ewma == wd.delta_ewma


def test_delta_counters_and_ledger_pair(tmp_path):
    csr = _graph()
    holder, mgr = _manager(csr, str(tmp_path / "j.wal"))
    _quiet_apply(mgr, _F2, None)
    st = mgr.stats()
    assert st["batches"] == 1 and st["applied_adds"] == 1
    assert st["seq"] == 1 and st["live_edges"] == N_EDGES + 1
    assert st["cells_patched"] >= 2     # one fwd cell + one bwd cell
    # every applied batch lands a joined delta-apply pair in the ledger
    paired = [rec for kind, rec in obs.get_ledger().records
              if kind == "measurement" and rec["model"] == "delta-apply"
              and "ratio" in rec]
    assert paired
    mgr.close()


# -- concurrent apply vs. close: the shutdown race, pinned -------------------

def test_concurrent_apply_vs_close_stress(tmp_path):
    """Mutator threads hammer apply() while the main thread close()s
    mid-stream.  The contract the lock discipline buys: applies
    serialize under _mu, every one either fully commits (WAL before
    memory) or surfaces DeltaError("closed") — the committed sequence
    numbers form a dense prefix with no tears and no duplicates — and
    a restart over the WAL replays exactly that prefix.  Runs under the
    armed lock-order witness (autouse fixture)."""
    csr = _graph()
    jp = str(tmp_path / "j.wal")
    holder, mgr = _manager(csr, jp)
    committed = [[] for _ in range(3)]
    surprises = []
    started = threading.Barrier(4)

    def mutate(k):
        # each thread toggles its own fresh edge: add, retire, add, ...
        # net growth stays zero, so the stream never exhausts cells
        edge = np.asarray([[64 + k, 80 + k]])
        started.wait(10.0)
        for i in range(10_000):
            try:
                r = _quiet_apply(mgr, edge if i % 2 == 0 else None,
                                 edge if i % 2 == 1 else None)
            except DeltaError as e:
                assert "closed" in str(e), e
                return
            except BaseException as e:
                surprises.append(repr(e))
                return
            committed[k].append(r["seq"])

    threads = [threading.Thread(target=mutate, args=(k,)) for k in range(3)]
    for t in threads:
        t.start()
    started.wait(10.0)
    time.sleep(0.15)                     # let the streams interleave
    mgr.close()                          # the race under test
    for t in threads:
        t.join(30.0)
    assert not any(t.is_alive() for t in threads), "a mutator hung on close"
    assert surprises == [], surprises
    seqs = sorted(s for per in committed for s in per)
    assert seqs, "close() won the race before any apply committed"
    # dense prefix: no torn, skipped, or double-committed sequence
    assert seqs == list(range(1, len(seqs) + 1))
    assert mgr.applied_seq == len(seqs)
    # restart over the WAL pair: exactly the committed prefix comes back
    holder2, mgr2 = _manager(csr, jp)
    assert mgr2.applied_seq == len(seqs)
    mgr2.close()
