"""Checkpoint/resume tests (capability added over the reference)."""

import numpy as np

from roc_tpu.graph import datasets
from roc_tpu.models import build_gcn
from roc_tpu.train.config import Config
from roc_tpu.train.driver import Trainer


def make_trainer(tmp_path, resume=False):
    ds = datasets.synthetic("t", 80, 3.0, 8, 3, n_train=20, n_val=20,
                            n_test=20, seed=13)
    cfg = Config(layers=[8, 4, 3], num_epochs=4, eval_every=1000,
                 checkpoint_path=str(tmp_path / "ck.npz"),
                 checkpoint_every=2, resume=resume, dropout_rate=0.0)
    return Trainer(cfg, ds, build_gcn(cfg.layers, 0.0)), cfg


def test_checkpoint_roundtrip_and_resume(tmp_path):
    tr, cfg = make_trainer(tmp_path)
    tr.train(print_fn=lambda *_: None)
    assert (tmp_path / "ck.npz").exists()
    w_after = np.asarray(tr.params["linear_0"])
    assert tr.epoch == 4

    # Fresh trainer with -resume restores epoch counter + params exactly.
    tr2, _ = make_trainer(tmp_path, resume=True)
    assert tr2.epoch == 4
    np.testing.assert_array_equal(np.asarray(tr2.params["linear_0"]), w_after)
    # optimizer moments restored too
    np.testing.assert_array_equal(
        np.asarray(tr2.opt_state.m["linear_0"]),
        np.asarray(tr.opt_state.m["linear_0"]))
    # and training continues from where it left off
    tr2.train(print_fn=lambda *_: None)
    assert tr2.epoch == 8


def test_checkpoint_extra_roundtrip(tmp_path):
    """The free-form `extra` dict (host-side trainer state beyond
    params/opt/epoch/alpha) must survive save -> load intact."""
    from roc_tpu.train import checkpoint

    tr, cfg = make_trainer(tmp_path)
    extra = {"best_val": 0.875, "note": "after sweep", "ids": [1, 2, 3]}
    tr.save_checkpoint(cfg.checkpoint_path, extra=extra)
    _, _, epoch, alpha, got = checkpoint.load(
        cfg.checkpoint_path, tr.params, tr.opt_state)
    assert got == extra
    assert epoch == tr.epoch and alpha == tr.optimizer.alpha


def test_checkpoint_atomic_overwrite(tmp_path):
    tr, cfg = make_trainer(tmp_path)
    tr.save_checkpoint(cfg.checkpoint_path)
    tr.run_epoch()
    tr.save_checkpoint(cfg.checkpoint_path)  # overwrite in place
    tr2, _ = make_trainer(tmp_path, resume=True)
    assert tr2.epoch == 1
