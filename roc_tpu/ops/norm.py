"""In-degree normalization (the reference's InDegreeNorm op).

``out[v] = x[v] / sqrt(in_degree(v))`` (norm_coop_kernel,
graphnorm_kernel.cu:19-57).  Applied before AND after aggregation this yields
the symmetric D^{-1/2} A D^{-1/2} GCN propagation (gnn.cc:82-84).  The
backward pass is the same scaling (graphnorm_kernel.cu:126-136) — which JAX
autodiff derives for free since the op is linear.

The reference recomputes degrees from row_ptr inside the kernel every call;
we precompute the degree vector once at partition time (Partition.in_degree,
pad rows get degree 1) and make this a fused broadcast-multiply.
"""

from __future__ import annotations

import jax


def indegree_norm(x, in_degree):
    """x: [N, H]; in_degree: [N] float.

    No zero-guard needed: degrees are >= 1 everywhere by construction
    (self-edges on real nodes, explicit 1.0 on pad rows).
    """
    return x * jax.lax.rsqrt(in_degree)[:, None]
