"""ROC on-disk format IO (`.lux`, `.feats.csv/.bin`, `.label`, `.mask`).

Binary `.lux` layout (reference: gnn.cc:755-801 + load_task.cu:222-245):
    uint32  numNodes
    uint64  numEdges                      (FILE_HEADER_SIZE = 12, gnn.h:33)
    uint64  raw_rows[numNodes]            inclusive END offsets per vertex
                                          (raw_rows[N-1] == numEdges)
    uint32  raw_cols[numEdges]            source vertex id per in-edge

Sidecar files (load_task.cu:25-184):
    <prefix>.feats.csv   one comma-separated float row per vertex
    <prefix>.feats.bin   row-major float32 cache, written on first CSV parse
    <prefix>.label       one int class id per vertex (whitespace separated)
    <prefix>.mask        one of Train/Val/Test/None per line

Mask encoding matches gnn.h:98-103: TRAIN=0, VAL=1, TEST=2, NONE=3.

A fast native (C++) parse path is used when the roc_tpu native library is
built (roc_tpu/native); this module is the authoritative pure-NumPy
implementation and the correctness oracle for it.
"""

from __future__ import annotations

import os

import numpy as np

from roc_tpu import fault
from roc_tpu.graph.csr import Csr, E_DTYPE, V_DTYPE

MASK_TRAIN, MASK_VAL, MASK_TEST, MASK_NONE = 0, 1, 2, 3
_MASK_NAMES = {"Train": MASK_TRAIN, "Val": MASK_VAL, "Test": MASK_TEST, "None": MASK_NONE}
_MASK_STRS = {v: k for k, v in _MASK_NAMES.items()}

LUX_SUFFIX = ".add_self_edge.lux"
# Transposed-graph sidecar (out-edge CSR over sources) — the preprocessed
# input edge-sharded -perhost loading needs for its src-sorted backward
# blocks (shard_load.load_edge_blocks).  Produced once offline, the same
# pattern as the reference's *.add_self_edge.lux preprocessing itself.
TLUX_SUFFIX = ".add_self_edge.t.lux"


def read_header(path: str) -> "tuple[int, int]":
    """The 12-byte `.lux` header: (num_nodes, num_edges).  Single home for
    the header layout + native-vs-NumPy fallback (read_lux, the graph-stub
    dataset mode, and the per-host loader all go through here)."""
    from roc_tpu import native
    if native.available():
        return native.lux_header(path)
    with open(path, "rb") as f:
        num_nodes = int(np.fromfile(f, dtype=np.uint32, count=1)[0])
        num_edges = int(np.fromfile(f, dtype=np.uint64, count=1)[0])
    return num_nodes, num_edges


_HEADER_SIZE = 12  # uint32 numNodes + uint64 numEdges (gnn.h:33)


def read_rows_slice(path: str, lo: int, hi: int) -> np.ndarray:
    """raw_rows[lo:hi] (inclusive end offsets) via per-range seek+read (the
    reference's per-partition seeking, load_task.cu:231-243).

    Range checks run *before* any seek, on both the native and the NumPy
    path: the stream executor derives these ranges from external (balancer)
    bounds thousands of times per run, and a bad range must fail loudly
    here rather than as a short read or a silent negative-count no-op."""
    if lo < 0 or hi < lo:
        raise ValueError(f".lux row range [{lo}, {hi}) is invalid "
                         "(need 0 <= lo <= hi)")
    num_nodes, _ = read_header(path)    # 12-byte read; uniform EOF check
    if hi > num_nodes:                  # on the native and NumPy paths
        raise ValueError(f".lux row range [{lo}, {hi}) runs past the end "
                         f"of {path} ({num_nodes} nodes)")
    from roc_tpu import native

    def _read():
        # Retried as one unit (roc_tpu/fault): the seek+read is
        # idempotent, and a short read (NFS hiccup, torn write seen
        # mid-replace) surfaces as the ValueError below — transient by
        # construction, so it retries alongside real OSErrors.
        fault.point("lux.read")
        if native.available():
            rows, _ = native.lux_read_slice(path, lo, hi, 0, 0)
            return rows
        with open(path, "rb") as f:
            f.seek(_HEADER_SIZE + 8 * lo)
            rows = np.fromfile(f, dtype=np.uint64, count=hi - lo)
        if rows.shape[0] != hi - lo:
            raise ValueError(f".lux row range [{lo}, {hi}) runs past the "
                             f"end of {path} (got {rows.shape[0]} offsets)")
        return rows
    return fault.retrying("lux.read", _read,
                          retry_on=(OSError, ValueError))


def read_cols_slice(path: str, num_nodes: int, e0: int, e1: int
                    ) -> np.ndarray:
    """raw_cols[e0:e1] (source vertex ids) via per-range seek+read."""
    if e0 < 0 or e1 < e0:
        raise ValueError(f".lux edge range [{e0}, {e1}) is invalid "
                         "(need 0 <= e0 <= e1)")
    _, num_edges = read_header(path)
    if e1 > num_edges:
        raise ValueError(f".lux edge range [{e0}, {e1}) runs past the end "
                         f"of {path} ({num_edges} edges)")
    from roc_tpu import native

    def _read():
        fault.point("lux.read")
        if native.available():
            _, cols = native.lux_read_slice(path, 0, 0, e0, e1)
            return cols
        with open(path, "rb") as f:
            f.seek(_HEADER_SIZE + 8 * num_nodes + 4 * e0)
            cols = np.fromfile(f, dtype=np.uint32, count=e1 - e0)
        if cols.shape[0] != e1 - e0:
            raise ValueError(f".lux edge range [{e0}, {e1}) runs past the "
                             f"end of {path} (got {cols.shape[0]} ids)")
        return cols
    return fault.retrying("lux.read", _read,
                          retry_on=(OSError, ValueError))


def read_lux(path: str) -> Csr:
    """Read a `.lux` graph file into an exclusive-prefix CSR (native C++
    reader when built, NumPy otherwise)."""
    num_nodes, num_edges = read_header(path)
    raw_rows = read_rows_slice(path, 0, num_nodes)
    raw_cols = read_cols_slice(path, num_nodes, 0, num_edges)
    # Reference asserts monotonicity and the final offset (gnn.cc:797-800).
    assert np.all(np.diff(raw_rows.astype(np.int64)) >= 0)
    assert num_nodes == 0 or raw_rows[-1] == num_edges
    row_ptr = np.zeros(num_nodes + 1, dtype=E_DTYPE)
    row_ptr[1:] = raw_rows.astype(E_DTYPE)
    g = Csr(num_nodes, num_edges, row_ptr, raw_cols.astype(V_DTYPE))
    g.validate()
    return g


def write_lux(path: str, g: Csr) -> None:
    """Write a CSR in the reference's `.lux` layout (inclusive end offsets)."""
    with open(path, "wb") as f:
        np.asarray([g.num_nodes], dtype=np.uint32).tofile(f)
        np.asarray([g.num_edges], dtype=np.uint64).tofile(f)
        g.row_ptr[1:].astype(np.uint64).tofile(f)
        g.col_idx.astype(np.uint32).tofile(f)


def write_transpose(prefix: str, g: Csr) -> None:
    """Write the transposed-graph sidecar (``prefix + TLUX_SUFFIX``).
    One offline O(E log E) sort buys -edge-shard -perhost its src-sorted
    backward blocks as plain byte-range reads."""
    write_lux(prefix + TLUX_SUFFIX, g.transpose())


def _cache_fresh(bin_path: str, src_path: str) -> bool:
    """A binary sidecar cache is usable iff it exists and is no older than
    its source text file (a regenerated source invalidates it, like make).

    Equal mtimes count as fresh.  Multihost note: on shared storage with
    cross-host clock skew a just-written cache can still look stale to a
    late process, in which case several processes may re-parse the text
    source concurrently — wasteful but correct (the atomic write-then-rename
    in _atomic_tofile means readers never see a torn file).  Hosts that want
    to avoid the duplicated parse should pre-warm the cache once (any
    single-process run) before launching the fleet."""
    if not os.path.exists(bin_path):
        return False
    if not os.path.exists(src_path):
        return True      # binary-only distribution
    return os.path.getmtime(bin_path) >= os.path.getmtime(src_path)


def _atomic_tofile(arr: np.ndarray, path: str) -> None:
    """Write-then-rename so concurrent readers (multihost processes on
    shared storage) never observe a truncated cache file; fsync on both
    sides of the rename (fault.fsync_replace) so a kill/power-loss never
    leaves a correctly-named file with unflushed garbage behind it."""
    tmp = f"{path}.tmp.{os.getpid()}"
    arr.tofile(tmp)
    fault.fsync_replace(tmp, path)


def load_features(prefix: str, num_nodes: int, in_dim: int,
                  mmap: bool = False) -> np.ndarray:
    """Load node features, preferring the `.feats.bin` cache and writing it
    after a CSV parse, exactly like the reference (load_task.cu:41-73).

    ``mmap=True`` returns a read-only np.memmap of the binary cache instead
    of materializing [N, in_dim] in RAM — the sharded-host-loading path for
    graphs whose features exceed host memory (SURVEY §7 "papers100M"):
    per-part placement then touches only this host's row ranges."""
    bin_path = prefix + ".feats.bin"
    csv_path = prefix + ".feats.csv"
    if not _cache_fresh(bin_path, csv_path):
        from roc_tpu import native
        if native.available():
            feats = native.parse_feats_csv(csv_path, num_nodes, in_dim)
        else:
            feats = np.loadtxt(csv_path, delimiter=",", dtype=np.float32,
                               ndmin=2)
            assert feats.shape == (num_nodes, in_dim), (
                f"feats.csv shape {feats.shape} != ({num_nodes},{in_dim})")
        _atomic_tofile(feats, bin_path)
        if not mmap:
            return feats
    if mmap:
        return np.memmap(bin_path, dtype=np.float32, mode="r",
                         shape=(num_nodes, in_dim))
    feats = np.fromfile(bin_path, dtype=np.float32, count=num_nodes * in_dim)
    assert feats.size == num_nodes * in_dim, "feats.bin size mismatch"
    return feats.reshape(num_nodes, in_dim)


def one_hot(ids: np.ndarray, num_classes: int) -> np.ndarray:
    """[...,] int ids -> [..., C] float32 one-hot (the reference's on-host
    label layout, load_task.cu:110-123)."""
    out = np.zeros(ids.shape + (num_classes,), dtype=np.float32)
    out.reshape(-1, num_classes)[np.arange(ids.size), ids.reshape(-1)] = 1.0
    return out


def load_label_ids(prefix: str, num_nodes: int,
                   num_classes: int) -> np.ndarray:
    """Load `.label` int class ids, caching the text parse to `.label.bin`
    (same pattern as the `.feats.bin` cache — a 1e8-line text parse costs
    minutes; the binary reload is instant)."""
    bin_path = prefix + ".label.bin"
    if _cache_fresh(bin_path, prefix + ".label"):
        ids = np.fromfile(bin_path, dtype=np.int32, count=num_nodes)
        assert ids.size == num_nodes, "label.bin size mismatch"
        ids = ids.astype(np.int64)
    else:
        ids = np.loadtxt(prefix + ".label", dtype=np.int64).reshape(-1)
        assert ids.shape[0] == num_nodes
        _atomic_tofile(ids.astype(np.int32), bin_path)
    assert ids.min() >= 0 and ids.max() < num_classes
    return ids


def load_labels(prefix: str, num_nodes: int, num_classes: int) -> np.ndarray:
    """Load int class ids and expand to one-hot float32 rows
    (load_task.cu:110-123)."""
    return one_hot(load_label_ids(prefix, num_nodes, num_classes),
                   num_classes)


def load_mask(prefix: str, num_nodes: int) -> np.ndarray:
    """Load the Train/Val/Test/None text mask (load_task.cu:160-180)."""
    with open(prefix + ".mask") as f:
        lines = [line.rstrip("\n") for line in f][:num_nodes]
    assert len(lines) == num_nodes, "mask file too short"
    try:
        return np.asarray([_MASK_NAMES[ln] for ln in lines], dtype=np.int32)
    except KeyError as e:
        raise ValueError(f"Unrecognized mask: {e.args[0]!r}") from None


def write_dataset(prefix: str, g: Csr, feats: np.ndarray, label_ids: np.ndarray,
                  mask: np.ndarray) -> None:
    """Write a full ROC-format dataset (graph + sidecars) under `prefix`."""
    parent = os.path.dirname(prefix)
    if parent:
        os.makedirs(parent, exist_ok=True)
    write_lux(prefix + LUX_SUFFIX, g)
    # %.9g is FLT_DECIMAL_DIG significant digits: every float32 round-trips
    # the text exactly, so a consumer that loses the .bin sidecar and
    # reparses the CSV gets bit-identical features to a cache-hit load
    # (with %.6g the two representations diverged in the last ulp).
    feats32 = np.ascontiguousarray(feats, np.float32)
    np.savetxt(prefix + ".feats.csv", feats32, delimiter=",", fmt="%.9g")
    # Also write the binary cache the loader would otherwise build on
    # first read: saves the O(N*D) CSV parse (written after the CSV, so
    # _cache_fresh accepts it).
    _atomic_tofile(feats32, prefix + ".feats.bin")
    np.savetxt(prefix + ".label", label_ids.reshape(-1, 1), fmt="%d")
    with open(prefix + ".mask", "w") as f:
        for m in mask:
            f.write(_MASK_STRS[int(m)] + "\n")
