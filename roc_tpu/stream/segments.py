"""Split the model op IR at aggregation boundaries for shard streaming.

Every op in the IR except ``aggregate``/``gat`` is row-local: row r of the
output depends only on row r of the input, so it can run on one shard's
node slot without seeing any other shard.  The two aggregation kinds are
the only cross-row ops — they read a *source table* indexed by edge
sources, which under streaming is the gathered ``[S + P*K]`` local+halo
table the executor assembles from the host stores (the same table layout
``shard_load.build_halo_local`` gives the perhost SPMD path).

A *segment* is therefore: one optional aggregation head followed by the
row-local ops up to (not including) the next head.  Segment 0 has no head
(the ops before the first aggregation, e.g. dropout+linear for GCN).  The
executor runs each segment as one jitted function per shard, storing the
segment's boundary outputs back to host between sweeps.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

from roc_tpu.models.model import Model, OpNode
from roc_tpu.memory.estimator import _op_out_dims
from roc_tpu import ops

__all__ = ["Segment", "split_segments", "run_segment",
           "predicted_epoch_bytes"]

_HEAD_KINDS = ("aggregate", "gat")


@dataclasses.dataclass(frozen=True)
class Segment:
    """One streamable slice of the op IR.

    ``table_tid`` is the tensor the head reads through the local+halo
    table (-1 when headless); ``own_in_tids`` are earlier-produced
    tensors the body reads row-locally (only this shard's rows are
    needed); ``out_tids`` are tensors produced here that any later
    segment consumes — the executor persists exactly these to host."""

    index: int
    head: Optional[OpNode]
    body: Tuple[OpNode, ...]
    table_tid: int
    own_in_tids: Tuple[int, ...]
    out_tids: Tuple[int, ...]
    is_last: bool
    out_dims: Dict[int, int]


def split_segments(model: Model) -> List[Segment]:
    ops_list = list(model.ops)
    dims = _op_out_dims(model)
    head_pos = [i for i, op in enumerate(ops_list) if op.kind in _HEAD_KINDS]
    starts = [0] + head_pos
    ends = head_pos + [len(ops_list)]

    raw = []  # (head, body) per segment
    for k, (lo, hi) in enumerate(zip(starts, ends)):
        if k == 0:
            raw.append((None, tuple(ops_list[lo:hi])))
        else:
            raw.append((ops_list[lo], tuple(ops_list[lo + 1:hi])))

    produced = []
    for head, body in raw:
        p = {op.out for op in body}
        if head is not None:
            p.add(head.out)
        produced.append(p)

    # tid -> set of segment indices that consume it (as table or row-local)
    consumers: Dict[int, set] = {}
    for k, (head, body) in enumerate(raw):
        tids = set()
        if head is not None:
            tids.add(head.inputs[0])
        for op in body:
            tids.update(op.inputs)
        for t in tids:
            consumers.setdefault(t, set()).add(k)

    segs = []
    n = len(raw)
    for k, (head, body) in enumerate(raw):
        for op in body:
            assert op.kind not in _HEAD_KINDS, "aggregation op in segment body"
        own_in = sorted(
            t for op in body for t in op.inputs if t not in produced[k])
        outs = sorted(
            t for t in produced[k]
            if any(c > k for c in consumers.get(t, ())))
        touched = produced[k] | set(own_in)
        if head is not None:
            touched.add(head.inputs[0])
        segs.append(Segment(
            index=k,
            head=head,
            body=body,
            table_tid=head.inputs[0] if head is not None else -1,
            own_in_tids=tuple(dict.fromkeys(own_in)),
            out_tids=tuple(outs),
            is_last=(k == n - 1),
            out_dims={t: dims[t] for t in touched},
        ))
    return segs


def predicted_epoch_bytes(segments: List[Segment], parts: int,
                          shard_nodes: int, shard_edges: int, halo_k: int,
                          num_classes: int, *, act_itemsize: int = 4,
                          esrc_itemsize: int = 4,
                          edst_itemsize: int = 4) -> int:
    """Analytic bytes the executor's ``_fetch`` ships in one training
    epoch: the sweep schedule ((nseg-1) fwd + nseg bwd), each sweep
    rotating all ``parts`` shards, priced from the same store shapes
    ``_fetch`` slices.  ``act_itemsize`` is the streamed storage dtype's
    width (2 under -bf16-storage) and covers every float wire — tables,
    own rows, labels, and the cotangent fetch, which the executor casts
    to the storage dtype before shipping; in-degrees stay fp32 and the
    mask int32.  Edge-index widths are passed separately because the
    bf16 layout also narrows them to uint16 when the table fits.  PRNG
    keys (a few device words per fetch) are not counted.  The kernel
    budget gate (tools/check_kernel_budgets.py, ``check_stream_claim``)
    prices both dtypes through this one function, so the committed
    ratio and the runtime's ledger prediction can never drift apart."""
    n = len(segments)
    P, S, E, K = int(parts), int(shard_nodes), int(shard_edges), int(halo_k)
    sweeps = [("fwd", k) for k in range(n - 1)] + \
             [("bwd", k) for k in range(n - 1, -1, -1)]
    total = 0
    for phase, k in sweeps:
        seg = segments[k]
        b = E * (esrc_itemsize + edst_itemsize) + S * 4  # edges + indeg f32
        if seg.head is not None:
            b += (S + P * K) * seg.out_dims[seg.table_tid] * act_itemsize
        for t in seg.own_in_tids:
            b += S * seg.out_dims[t] * act_itemsize
        if seg.is_last:
            b += S * (num_classes * act_itemsize + 4)  # labels + mask i32
        if phase == "bwd" and not seg.is_last:
            for t in seg.out_tids:
                b += S * seg.out_dims[t] * act_itemsize
        total += b * P
    return int(total)


def run_segment(seg: Segment, params, table, own, esrc, edst, indeg, key,
                train: bool, num_nodes: int):
    """Trace one segment for one shard; mirrors ``Model.apply`` dispatch.

    ``table`` is the ``[S + P*K, d]`` gathered source table (None for the
    headless segment 0), ``own`` maps tid -> this shard's ``[S, d]`` rows,
    ``esrc``/``edst`` the table-local edge endpoints, ``indeg`` the
    per-row in-degree.  Returns the full tid -> value map; callers select
    ``seg.out_tids`` (or the logits tid) from it."""
    import jax

    vals = dict(own)
    if seg.head is not None:
        op = seg.head
        if op.kind == "aggregate":
            vals[op.out] = ops.scatter_gather(
                table, esrc, edst, num_nodes, op.attrs["aggr"])
        else:  # gat
            name = op.attrs["param"]
            kk, fd = op.attrs["heads"], op.attrs["head_dim"]
            h_tab = ops.linear(table, params[name + "_w"]).reshape(-1, kk, fd)
            vals[op.out] = ops.gat_attend(
                h_tab[:num_nodes], h_tab, esrc, edst, num_nodes,
                params[name + "_asrc"], params[name + "_adst"],
                op.attrs["slope"],
            ).reshape(num_nodes, kk * fd)

    for op in seg.body:
        a = vals[op.inputs[0]]
        if op.kind == "dropout":
            k = (jax.random.fold_in(key, op.attrs["slot"])
                 if train and key is not None else None)
            out = ops.dropout(k, a, op.attrs["rate"], train)
        elif op.kind == "linear":
            out = ops.linear(a, params[op.attrs["param"]],
                             op.attrs["activation"])
        elif op.kind == "norm":
            out = ops.indegree_norm(a, indeg)
        elif op.kind == "activation":
            out = ops.apply_activation(a, op.attrs["mode"])
        elif op.kind == "add":
            out = ops.add(a, vals[op.inputs[1]])
        else:  # pragma: no cover - split_segments asserts heads out of body
            raise ValueError(f"unstreamable op kind {op.kind!r}")
        vals[op.out] = out
    return vals
