"""Benchmark: full-graph GCN training throughput (the reference's canonical
workload, test.sh:8 — 2-layer GCN, Reddit-shaped graph, layers 602-256-41).

Prints ONE JSON line:
  {"metric": ..., "value": ..., "unit": ..., "vs_baseline": ...}

The graph is a deterministic synthetic Reddit-scale stand-in (zero-egress
environment; same node/feature/class counts as reddit-dgl, ~23.5M in-edges).
Metric is wall-clock per training epoch (fwd+bwd+Adam, full graph, no
sampling).  vs_baseline compares against REF_EPOCH_S, the reference system's
single-GPU epoch time for this workload; the reference repo publishes no
numbers (BASELINE.md), so REF_EPOCH_S holds the MLSys'20 paper's reported
~1 s/epoch for single-GPU full-graph Reddit until a measured value replaces
it.  vs_baseline > 1 means faster than that reference number.
"""

import json
import sys
import time

REF_EPOCH_S = 1.0  # assumed reference (see module docstring); >1.0 = we win

NODES, IN_DIM, CLASSES = 232_965, 602, 41
LAYERS = [IN_DIM, 256, CLASSES]
AVG_DEG = 50.0
WARMUP, MEASURED = 3, 10


def main():
    import jax

    from roc_tpu.graph import datasets
    from roc_tpu.models import build_gcn
    from roc_tpu.train.config import Config
    from roc_tpu.train.driver import Trainer

    t0 = time.time()
    ds = datasets.synthetic(
        "reddit-bench", NODES, AVG_DEG, IN_DIM, CLASSES,
        n_train=153431, n_val=23831, n_test=55703, seed=1)
    print(f"# graph ready: {ds.graph.num_nodes} nodes "
          f"{ds.graph.num_edges} edges ({time.time()-t0:.1f}s)",
          file=sys.stderr)

    n_dev = len(jax.devices())
    cfg = Config(layers=LAYERS, num_epochs=1, learning_rate=0.01,
                 weight_decay=1e-4, dropout_rate=0.5, eval_every=10**9,
                 num_parts=n_dev, halo=True)
    if n_dev > 1:
        from roc_tpu.parallel.spmd import SpmdTrainer
        trainer = SpmdTrainer(cfg, ds, build_gcn(LAYERS, cfg.dropout_rate))
    else:
        trainer = Trainer(cfg, ds, build_gcn(LAYERS, cfg.dropout_rate))

    # device_sync fetches the loss to the host: each epoch's params feed the
    # next, so syncing the last loss transitively waits on every step.
    from roc_tpu.train.driver import device_sync
    for _ in range(WARMUP):
        loss = trainer.run_epoch()
    device_sync(loss)
    t1 = time.perf_counter()
    for _ in range(MEASURED):
        loss = trainer.run_epoch()
    device_sync(loss)
    epoch_s = (time.perf_counter() - t1) / MEASURED

    edges_per_sec_per_chip = ds.graph.num_edges / epoch_s / n_dev
    print(f"# {epoch_s*1e3:.1f} ms/epoch on {n_dev} device(s), "
          f"{edges_per_sec_per_chip/1e6:.1f}M edges/s/chip", file=sys.stderr)
    print(json.dumps({
        "metric": "gcn_reddit602-256-41_epoch_time",
        "value": round(epoch_s, 4),
        "unit": "s",
        "vs_baseline": round(REF_EPOCH_S / epoch_s, 3),
    }))


if __name__ == "__main__":
    main()
