"""Dataset converters (roc_tpu/graph/convert.py): edge-list and OGB-style
dumps -> ROC on-disk format, plus the vendored *real* graph (Zachary's
karate club) and its golden semi-supervised curve.

The reference ships no converter (its datasets were prepared out-of-tree,
test.sh:8); SURVEY §7.1 calls for one.  The karate test is the repo's one
real-data accuracy oracle: the GCN must reproduce the published result
(Zachary 1977's model: 33/34 members, node 8 the sole miss)."""

import numpy as np
import pytest

from roc_tpu.graph import convert, datasets, lux
from roc_tpu.models import build_model
from roc_tpu.train.config import Config
from roc_tpu.train.driver import Trainer


def _write(path, text):
    with open(path, "w") as f:
        f.write(text)


def test_edge_list_basic(tmp_path):
    _write(tmp_path / "g.txt", "# comment\n0 1\n1 2\n2,0\n\n")
    ds = convert.from_edge_list(str(tmp_path / "g.txt"))
    assert ds.graph.num_nodes == 3
    # 3 directed edges + 3 self-edges
    assert ds.graph.num_edges == 6
    assert ds.in_dim == 3                      # identity features
    np.testing.assert_array_equal(ds.features, np.eye(3, dtype=np.float32))


def test_edge_list_undirected_dedups(tmp_path):
    # both orientations listed + a duplicate: symmetrize must dedup
    _write(tmp_path / "g.txt", "0 1\n1 0\n0 1\n1 2\n")
    ds = convert.from_edge_list(str(tmp_path / "g.txt"), undirected=True,
                                self_edges=False)
    assert ds.graph.num_edges == 4             # 0<->1, 1<->2
    t = ds.graph.transpose()                   # undirected: CSR == CSR^T
    np.testing.assert_array_equal(ds.graph.row_ptr, t.row_ptr)
    np.testing.assert_array_equal(ds.graph.col_idx, t.col_idx)


def test_edge_list_sidecars_and_roundtrip(tmp_path):
    _write(tmp_path / "g.txt", "0 1\n1 2\n3 0\n")
    _write(tmp_path / "f.csv", "1,0\n0,1\n1,1\n0,0\n")
    _write(tmp_path / "l.txt", "0\n1\n1\n0\n")
    ds = convert.from_edge_list(
        str(tmp_path / "g.txt"), feats_path=str(tmp_path / "f.csv"),
        labels_path=str(tmp_path / "l.txt"), split=(2, 1, 1), seed=0)
    assert ds.num_classes == 2 and ds.in_dim == 2
    convert.write(ds, str(tmp_path / "out"))
    back = datasets.load_roc_dataset(str(tmp_path / "out"), 2, 2)
    np.testing.assert_array_equal(back.graph.row_ptr, ds.graph.row_ptr)
    np.testing.assert_array_equal(back.graph.col_idx, ds.graph.col_idx)
    np.testing.assert_allclose(back.features, ds.features, atol=1e-6)
    np.testing.assert_array_equal(back.label_ids, ds.label_ids)
    np.testing.assert_array_equal(back.mask, ds.mask)


def test_edge_list_out_of_range(tmp_path):
    _write(tmp_path / "g.txt", "0 7\n")
    with pytest.raises(ValueError, match="out of range"):
        convert.from_edge_list(str(tmp_path / "g.txt"), num_nodes=4)
    _write(tmp_path / "neg.txt", "5 -1\n0 1\n")
    with pytest.raises(ValueError, match="out of range"):
        convert.from_edge_list(str(tmp_path / "neg.txt"), num_nodes=10,
                               undirected=True)


def test_edge_list_keeps_input_self_loops(tmp_path):
    # a self-loop in the input must survive symmetrization even when
    # self_edges=False (no uniform re-add)
    _write(tmp_path / "g.txt", "2 2\n0 1\n")
    ds = convert.from_edge_list(str(tmp_path / "g.txt"), undirected=True,
                                self_edges=False)
    assert ds.graph.num_edges == 3          # 0<->1 + the (2,2) loop
    src, dst = ds.graph.col_idx, ds.graph.dst_idx
    assert ((src == 2) & (dst == 2)).sum() == 1


def test_stratified_split_covers_classes():
    ids = np.array([0] * 50 + [1] * 30 + [2] * 20)
    mask = convert.stratified_split(ids, 6, 10, 20, seed=3)
    train = ids[mask == lux.MASK_TRAIN]
    assert (mask == lux.MASK_TRAIN).sum() == 6
    assert (mask == lux.MASK_VAL).sum() == 10
    assert (mask == lux.MASK_TEST).sum() == 20
    assert set(np.unique(train)) == {0, 1, 2}   # every class in train


def test_ogb_dir(tmp_path):
    root = tmp_path / "raw"
    root.mkdir()
    (root / "split").mkdir()
    _write(root / "edge.csv", "0,1\n1,2\n2,3\n")
    _write(root / "node-feat.csv", "1,0\n0,1\n1,1\n0,0\n")
    _write(root / "node-label.csv", "0\n1\n1\n0\n")
    _write(root / "split" / "train.csv", "0\n1\n")
    _write(root / "split" / "valid.csv", "2\n")
    _write(root / "split" / "test.csv", "3\n")
    ds = convert.from_ogb_dir(str(root))
    assert ds.graph.num_nodes == 4
    # 3 undirected pairs = 6 directed + 4 self-edges
    assert ds.graph.num_edges == 10
    np.testing.assert_array_equal(
        ds.mask, [lux.MASK_TRAIN, lux.MASK_TRAIN, lux.MASK_VAL,
                  lux.MASK_TEST])


def test_mtx(tmp_path):
    _write(tmp_path / "g.mtx",
           "%%MatrixMarket matrix coordinate pattern symmetric\n"
           "% a comment\n"
           "4 4 3\n"
           "2 1\n3 2\n4 1\n")
    ds = convert.from_mtx(str(tmp_path / "g.mtx"))
    assert ds.graph.num_nodes == 4
    # 3 symmetric pairs = 6 directed + 4 self-edges
    assert ds.graph.num_edges == 10
    t = ds.graph.transpose()       # symmetrized: CSR == CSR^T as edge sets
    np.testing.assert_array_equal(ds.graph.row_ptr, t.row_ptr)
    for v in range(4):             # within-row order may differ; compare
        sl = slice(int(ds.graph.row_ptr[v]),        # sorted multisets
                   int(ds.graph.row_ptr[v + 1]))
        np.testing.assert_array_equal(np.sort(ds.graph.col_idx[sl]),
                                      np.sort(t.col_idx[sl]))
    with pytest.raises(ValueError, match="MatrixMarket"):
        _write(tmp_path / "bad.mtx", "not a header\n1 1 0\n")
        convert.from_mtx(str(tmp_path / "bad.mtx"))


def test_karate_is_the_real_graph():
    ds = convert.karate_club()
    assert ds.graph.num_nodes == 34
    assert ds.graph.num_edges == 2 * 78 + 34   # symmetrized + self-edges
    # the observed fission outcome as recorded in the networkx dataset:
    # 17 members with Mr. Hi, 17 with the officers
    assert int((ds.label_ids == 0).sum()) == 17
    assert int((ds.label_ids == 1).sum()) == 17
    # canonical semi-supervised split: leaders train, everyone else test
    assert list(np.nonzero(ds.mask == lux.MASK_TRAIN)[0]) == [0, 33]
    assert int((ds.mask == lux.MASK_TEST).sum()) == 32


def test_davis_is_the_real_graph():
    ds = convert.davis_women()
    assert ds.graph.num_nodes == 32            # 18 women + 14 events
    assert ds.graph.num_edges == 2 * 89 + 32   # symmetrized + self-edges
    # Freeman's consensus split is 9 women per group; events unlabeled
    assert int((ds.label_ids[:18] == 0).sum()) == 9
    assert int((ds.label_ids[:18] == 1).sum()) == 9
    assert list(np.nonzero(ds.mask == lux.MASK_TRAIN)[0]) == [0, 13]
    assert int((ds.mask == lux.MASK_TEST).sum()) == 16
    assert int((ds.mask[18:] == lux.MASK_NONE).sum()) == 14


def test_lesmis_is_the_real_graph():
    ds = convert.les_miserables()
    assert ds.graph.num_nodes == 77
    assert ds.graph.num_edges == 2 * 254 + 77
    assert ds.num_classes == 5                 # CNM modularity communities
    assert int((ds.mask == lux.MASK_TRAIN).sum()) == 10   # 2 per class


def test_convert_rocfile_reorder_roundtrip(tmp_path):
    """tools/convert.py rocfile --reorder: re-processing an on-disk
    dataset through the RCM pass (the preprocess-once workflow) must
    yield an ISOMORPHIC dataset — same losses, features/labels/mask
    moved with their vertices — plus the transpose sidecar."""
    import os
    import subprocess
    import sys
    tool = os.path.join(os.path.dirname(__file__), "..", "tools",
                        "convert.py")
    a = str(tmp_path / "a")
    b = str(tmp_path / "b")
    env = dict(os.environ, PALLAS_AXON_POOL_IPS="", JAX_PLATFORMS="cpu")
    assert subprocess.run([sys.executable, tool, "lesmis", "-o", a],
                          env=env).returncode == 0
    assert subprocess.run([sys.executable, tool, "rocfile", "--file", a,
                           "--in-dim", "77", "--classes", "5", "-o", b,
                           "--reorder", "--with-transpose"],
                          env=env).returncode == 0
    assert os.path.exists(b + lux.TLUX_SUFFIX)
    da = datasets.load_roc_dataset(a, 77, 5)
    db = datasets.load_roc_dataset(b, 77, 5)
    assert da.graph.num_edges == db.graph.num_edges
    assert int((da.mask == lux.MASK_TRAIN).sum()) == \
        int((db.mask == lux.MASK_TRAIN).sum())
    cfg = Config(layers=[77, 8, 5], num_epochs=2, dropout_rate=0.0,
                 eval_every=10**9, seed=1)
    ta = Trainer(cfg, da, build_model("gcn", cfg.layers, 0.0, "sum"))
    tb = Trainer(cfg, db, build_model("gcn", cfg.layers, 0.0, "sum"))
    for i in range(2):
        la, lb = float(ta.run_epoch()), float(tb.run_epoch())
        np.testing.assert_allclose(lb, la, rtol=2e-4, err_msg=f"epoch {i}")


@pytest.mark.slow
def test_golden_davis_curve():
    """Real-data golden curve on a BIPARTITE graph (docs/GOLDEN.md):
    2-layer GCN, identity features, train = one seed woman per group
    (Evelyn, Nora).  Must reproduce Freeman's consensus split for 15 of
    the 16 held-out women, with node 15 (Dorothy Murchison — one of the
    classically ambiguous cases; she attended only two events) the sole
    miss."""
    import jax

    ds = convert.davis_women()
    cfg = Config(layers=[32, 16, 2], num_epochs=100, learning_rate=0.01,
                 weight_decay=5e-4, dropout_rate=0.5, eval_every=10**9)
    tr = Trainer(cfg, ds, build_model("gcn", cfg.layers, cfg.dropout_rate,
                                      "sum"))
    for _ in range(100):
        tr.run_epoch()
    m = jax.device_get(tr.evaluate())
    assert int(m.test_correct) == 15 and int(m.test_all) == 16
    pred = np.argmax(np.asarray(tr.predict_logits()), axis=-1)
    women = np.arange(18)
    assert list(women[(pred[:18] != ds.label_ids[:18])]) == [15]


@pytest.mark.slow
def test_golden_lesmis_curve():
    """The repo's one real NON-SATURATING pin (docs/GOLDEN.md): 5-class
    community recovery on Knuth's Les Misérables graph lands near 90%,
    not 100% — so a kernel/plan bug costing 1-2 samples moves this
    assert.  Measured (CPU, seed 1): epoch 50 val 15/19 test 45/48;
    epoch 200 val 15/19 test 45/48, train loss 0.34.  Pins leave
    2-sample cross-platform headroom."""
    import jax

    ds = convert.les_miserables()
    cfg = Config(layers=[77, 16, 5], num_epochs=200, learning_rate=0.01,
                 weight_decay=5e-4, dropout_rate=0.5, seed=1,
                 eval_every=10**9)
    tr = Trainer(cfg, ds, build_model("gcn", cfg.layers, cfg.dropout_rate,
                                      "sum"))
    for _ in range(200):
        tr.run_epoch()
    m = jax.device_get(tr.evaluate())
    assert int(m.val_correct) >= 13 and int(m.val_all) == 19
    assert int(m.test_correct) >= 43 and int(m.test_all) == 48
    assert float(m.train_loss) <= 1.0


@pytest.mark.slow
def test_golden_karate_curve():
    """Real-data golden curve (docs/GOLDEN.md): 2-layer GCN, identity
    features, train = the two faction leaders only.  Must reproduce the
    published result — 31/32 test members (33/34 overall, matching
    Zachary's own model) with node 8 the sole structural miss."""
    ds = convert.karate_club()
    cfg = Config(layers=[34, 16, 2], num_epochs=100, learning_rate=0.01,
                 weight_decay=5e-4, dropout_rate=0.5, eval_every=10**9)
    tr = Trainer(cfg, ds, build_model("gcn", cfg.layers, cfg.dropout_rate,
                                      "sum"))
    for _ in range(100):
        tr.run_epoch()
    import jax
    m = jax.device_get(tr.evaluate())
    assert int(m.test_correct) == 31 and int(m.test_all) == 32
    pred = np.argmax(np.asarray(tr.predict_logits()), axis=-1)
    assert list(np.nonzero(pred != ds.label_ids)[0]) == [8]
