"""Inference serving path (roc_tpu/serve/).

The contract under test mirrors ISSUE 13's acceptance gates:

- served logits match the training-side eval forward to <= 32 ULPs,
  across matmul/binned/megafuse backends and fp32/bf16 storage (same
  params, same graph data, same model.apply — serving adds a gather,
  never a different forward);
- an arbitrary mixed-batch-size request stream never retraces after
  `warmup()` — queries are bucketed to the power-of-two ladder and
  padded, so at most len(buckets) serve_step variants ever compile;
- cold start from a warm content-keyed plan cache performs ZERO plan
  rebuilds (pinned by diffing the builder's process counter);
- the microbatch queue drains on batch-or-deadline, resolves errors to
  futures without killing the worker, and prices queueing delay into
  per-request latency;
- the observability edges hold: watchdog serve-latency EWMA, the
  serve-p50 calibration-ledger pair, the BENCH_SERVE.json schema gate,
  and roclint's serve host-sync rule.
"""

import json
import os
import time

import numpy as np
import pytest

from roc_tpu.graph import datasets
from roc_tpu.models import build_model
from roc_tpu.obs.watchdog import PerfWatchdog
from roc_tpu.serve import (MicrobatchQueue, ServeEngine, bucket_sizes,
                           max_ulp_diff, run_load)
from roc_tpu.serve.loadgen import percentile
from roc_tpu.train.config import Config


@pytest.fixture(autouse=True)
def _lock_order_witness(lock_witness):
    # every serve test runs under the armed lock-order witness; any
    # acquisition order outside threads.json fails at teardown
    yield


def _engine(ds, *, model="gcn", backend="matmul", megafuse=False,
            bf16_storage=False, heads=2, start_queue=False, serve_batch=8,
            serve_wait_ms=1.0, precision="fast"):
    cfg = Config(layers=[ds.in_dim, 16, ds.num_classes], dropout_rate=0.0,
                 eval_every=10**9, model=model, heads=heads,
                 aggregate_backend=backend, megafuse=megafuse,
                 bf16_storage=bf16_storage, serve_batch=serve_batch,
                 serve_wait_ms=serve_wait_ms, aggregate_precision=precision)
    m = build_model(model, cfg.layers, cfg.dropout_rate, cfg.aggr,
                    heads=heads)
    return ServeEngine(cfg, ds, m, start_queue=start_queue)


# -- bucketing -------------------------------------------------------------

def test_bucket_ladder():
    assert bucket_sizes(1) == [1]
    assert bucket_sizes(8) == [1, 2, 4, 8]
    # a non-power-of-two cap still appears as the top bucket
    assert bucket_sizes(6) == [1, 2, 4, 6]
    assert bucket_sizes(64) == [1, 2, 4, 8, 16, 32, 64]


def test_bucket_for_maps_to_smallest_fitting():
    ds = datasets.get("roc-audit", seed=1)
    eng = _engine(ds, serve_batch=8)
    try:
        assert [eng.bucket_for(n) for n in (1, 2, 3, 5, 8)] == \
            [1, 2, 4, 8, 8]
        assert eng.bucket_for(100) == 8     # oversize chunks split at cap
    finally:
        eng.close()


# -- parity: served == eval forward, <= 32 ULPs ----------------------------

@pytest.mark.parametrize("backend,megafuse,bf16", [
    ("matmul", False, False),
    ("binned", False, False),
    ("binned", True, False),      # whole-layer megakernel
    ("binned", False, True),      # bf16 storage / fp32 accumulation
])
def test_served_matches_eval_forward(backend, megafuse, bf16, monkeypatch):
    """Every query row must equal the eval forward's row to <= 32 ULPs.

    The oracle is `FrozenBundle.predict_logits` — the SAME jitted program
    eval runs — so this pins that bucketing/padding/gather never perturb
    the forward, per backend and storage mode."""
    if megafuse:
        # the megakernel path runs the flat schedule (test_mega.py's pin)
        monkeypatch.setenv("ROC_BINNED_GEOM", "flat")
    ds = datasets.get("roc-audit", seed=1)
    eng = _engine(ds, backend=backend, megafuse=megafuse, bf16_storage=bf16)
    try:
        ref = np.asarray(eng.bundle.predict_logits())
        rng = np.random.default_rng(7)
        # unsorted, duplicated, every bucket + an over-cap chunk
        for k in (1, 3, 8, 17):
            ids = rng.integers(0, ds.graph.num_nodes, size=k)
            got = eng._serve_rows(ids.astype(np.int32))
            assert got.shape == (k, ds.num_classes)
            assert max_ulp_diff(got, ref[ids]) <= 32
    finally:
        eng.close()


def test_served_bitwise_at_exact_precision():
    """At exact aggregation precision the served rows are BITWISE the
    eval forward's (0 ULPs) — serving is the same program plus a
    gather, and exact precision removes every reassociation excuse."""
    ds = datasets.get("roc-audit", seed=1)
    eng = _engine(ds, backend="binned", precision="exact")
    try:
        ref = np.asarray(eng.bundle.predict_logits())
        ids = np.arange(ds.graph.num_nodes, dtype=np.int32)
        assert max_ulp_diff(eng._serve_rows(ids), ref) == 0
    finally:
        eng.close()


def test_served_matches_eval_forward_gat():
    """Attention coefficients ride the same forward: GAT parity too."""
    ds = datasets.get("roc-audit", seed=1)
    eng = _engine(ds, model="gat", backend="binned", heads=2)
    try:
        ref = np.asarray(eng.bundle.predict_logits())
        ids = np.arange(ds.graph.num_nodes, dtype=np.int32)
        got = eng._serve_rows(ids)
        assert max_ulp_diff(got, ref) <= 32
    finally:
        eng.close()


def test_served_matches_eval_forward_gat_fused(tmp_path, monkeypatch):
    """Round 19: serving inherits the fused attention megakernel for
    free — the fused-GAT engine serves what eval computes (<= 32 ULPs),
    a warm plan cache means zero plan rebuilds at cold start, and
    ``gat_fused`` is pytree metadata so the step caches key on it."""
    import dataclasses as dc

    import jax

    monkeypatch.setenv("ROC_BINNED_GEOM", "flat")
    monkeypatch.setenv("ROC_PLAN_CACHE_DIR", str(tmp_path))
    monkeypatch.setenv("ROC_PLAN_CACHE_MIN_EDGES", "0")
    ds = datasets.get("roc-audit", seed=1)
    first = _engine(ds, model="gat", backend="binned", megafuse=True)
    first.close()
    eng = _engine(ds, model="gat", backend="binned", megafuse=True)
    try:
        gd = eng.bundle.gdata
        assert gd.gat_bplans is not None and gd.gat_fused
        # flipping gat_fused flips the treedef — the serve/eval jit
        # caches therefore key on the fused mode (zero silent replays)
        assert (jax.tree_util.tree_structure(gd)
                != jax.tree_util.tree_structure(
                    dc.replace(gd, gat_fused=False)))
        assert eng.cold_start_stats["plan_builds"] == 0
        assert eng.cold_start_stats["traces"] == 1
        ref = np.asarray(eng.bundle.predict_logits())
        ids = np.arange(ds.graph.num_nodes, dtype=np.int32)
        assert max_ulp_diff(eng._serve_rows(ids), ref) <= 32
    finally:
        eng.close()


def test_ulp_metric():
    a = np.float32([1.0, -2.0, 0.0])
    assert max_ulp_diff(a, a.copy()) == 0
    assert max_ulp_diff(np.float32([1.0]),
                        np.float32([np.nextafter(np.float32(1.0),
                                                np.float32(2.0))])) == 1
    # sign-crossing distance counts through zero, not bit-pattern delta
    tiny = np.nextafter(np.float32(0.0), np.float32(1.0))
    assert max_ulp_diff(np.float32([tiny]), np.float32([-tiny])) == 2
    # NaN matches NaN positionally; NaN-vs-number is maximally far
    nan = np.float32([np.nan])
    assert max_ulp_diff(nan, nan) == 0
    assert max_ulp_diff(nan, np.float32([1.0])) == np.iinfo(np.int64).max


# -- cold start: warm plan cache means ZERO plan rebuilds ------------------

def test_cold_start_zero_plan_builds(tmp_path, monkeypatch):
    from roc_tpu.ops.pallas import binned as B
    monkeypatch.setenv("ROC_PLAN_CACHE_DIR", str(tmp_path))
    monkeypatch.setenv("ROC_PLAN_CACHE_MIN_EDGES", "0")
    ds = datasets.get("roc-audit", seed=1)
    first = _engine(ds, backend="binned")
    builds_cold = first.cold_start_stats["plan_builds"]
    first.close()
    assert builds_cold >= 1                 # fresh cache: plans were built
    warm = _engine(ds, backend="binned")
    try:
        cs = warm.cold_start_stats
        assert cs["plan_builds"] == 0       # THE serving cold-start pin
        assert cs["traces"] == 1            # one jit trace, smallest bucket
        assert cs["cold_start_s"] > 0.0
        assert cs["buckets"] == [1, 2, 4, 8]
    finally:
        warm.close()


# -- zero retraces across a mixed-size request stream ----------------------

def test_zero_retrace_over_mixed_stream():
    """100 requests with sizes drawn across every bucket (and over the
    cap): after warmup() the guard must record zero new serve_step
    traces — the whole stream reuses the warm ladder."""
    ds = datasets.get("roc-audit", seed=1)
    eng = _engine(ds, backend="binned", start_queue=True)
    try:
        eng.warmup()
        assert sum(eng._guard.counts.values()) == len(eng.buckets)
        baseline = eng._guard.snapshot()
        rng = np.random.default_rng(11)
        sizes = [1, 2, 3, 5, 8, 13]
        futs = [eng.submit(rng.integers(0, ds.graph.num_nodes,
                                        size=sizes[i % len(sizes)]))
                for i in range(100)]
        for f in futs:
            assert f.result(timeout=60.0).shape[1] == ds.num_classes
        eng._guard.assert_no_new_traces(baseline)
        st = eng.stats()
        assert st["requests"] == 100 and st["windows"] >= 1
    finally:
        eng.close()


def test_query_rejects_out_of_range_ids():
    ds = datasets.get("roc-audit", seed=1)
    eng = _engine(ds, start_queue=True)
    try:
        with pytest.raises(IndexError):
            eng.query([ds.graph.num_nodes + 5], timeout=30.0)
        # the worker survived the error: the next request still serves
        assert eng.query([0], timeout=30.0).shape == (1, ds.num_classes)
    finally:
        eng.close()


def test_apply_delta_requires_enable_at_construction():
    # enabling deltas after warmup would change the plan treedef and
    # retrace; an engine built without delta support must say so, not
    # silently degrade (full delta coverage lives in tests/test_delta.py)
    from roc_tpu.serve import DeltaError
    ds = datasets.get("roc-audit", seed=1)
    eng = _engine(ds)
    try:
        with pytest.raises(DeltaError, match="delta_journal"):
            eng.apply_delta(add_edges=[(0, 1)])
    finally:
        eng.close()


# -- microbatch queue (no engine: a recording serve_fn) --------------------

def _echo_serve(ids):
    return ids.astype(np.float32)[:, None]


def test_queue_batches_and_slices_per_request():
    q = MicrobatchQueue(_echo_serve, batch=4, wait_ms=20.0)
    try:
        futs = [q.submit([i]) for i in range(4)]
        outs = [f.result(timeout=10.0) for f in futs]
        for i, out in enumerate(outs):
            np.testing.assert_array_equal(out, [[float(i)]])
        assert q.served == 4
        # latency prices queue wait + serve, never negative
        assert all(f.latency_s >= 0.0 for f in futs)
    finally:
        q.close()


def test_queue_deadline_drains_partial_window():
    """A lone sub-batch request must not wait forever: the wait_ms
    deadline drains it."""
    q = MicrobatchQueue(_echo_serve, batch=64, wait_ms=5.0)
    try:
        t0 = time.perf_counter()
        out = q.query([3], timeout=10.0)
        assert time.perf_counter() - t0 < 5.0   # deadline, not timeout
        np.testing.assert_array_equal(out, [[3.0]])
    finally:
        q.close()


def test_queue_resolves_errors_without_dying():
    calls = {"n": 0}

    def flaky(ids):
        calls["n"] += 1
        if calls["n"] == 1:
            raise ValueError("injected")
        return _echo_serve(ids)

    q = MicrobatchQueue(flaky, batch=1, wait_ms=1.0)
    try:
        with pytest.raises(ValueError, match="injected"):
            q.query([1], timeout=10.0)
        np.testing.assert_array_equal(q.query([2], timeout=10.0), [[2.0]])
    finally:
        q.close()


def test_queue_rejects_empty_and_closed():
    from roc_tpu.serve.queue import Closed
    q = MicrobatchQueue(_echo_serve, batch=2, wait_ms=1.0)
    with pytest.raises(AssertionError):
        q.submit([])
    q.close()
    # submit-after-close is TYPED: the fleet router tells this lifecycle
    # signal ("re-route to a sibling") apart from a depth-cap Overloaded
    with pytest.raises(Closed):
        q.submit([1])
    # ... while pre-taxonomy callers catching RuntimeError still work
    assert issubclass(Closed, RuntimeError)
    q.close()                        # idempotent: double close is a no-op


# -- load generator --------------------------------------------------------

def test_percentile_nearest_rank():
    vals = [float(i) for i in range(1, 101)]
    assert percentile(vals, 0.50) == 51.0
    assert percentile(vals, 0.99) == 99.0
    assert percentile(vals, 1.00) == 100.0
    assert percentile([7.0], 0.99) == 7.0
    assert percentile([], 0.5) == 0.0


def test_run_load_open_loop_stats():
    ds = datasets.get("roc-audit", seed=1)
    eng = _engine(ds, start_queue=True)
    try:
        eng.warmup()
        stats = run_load(eng, n_requests=12, qps=400.0, sizes=(1, 2))
        assert stats["n"] == 12
        assert stats["qps_offered"] == 400.0
        assert 0.0 < stats["p50_s"] <= stats["p99_s"]
        assert stats["qps_achieved"] > 0
    finally:
        eng.close()


# -- watchdog: serve-latency EWMA ------------------------------------------

def test_watchdog_serve_latency_alert_and_verdict():
    wd = PerfWatchdog(ratio=3.0, warmup=1)
    assert wd.observe_serve(0, 0.010) is None   # obs 0: warmup noise
    assert wd.observe_serve(1, 0.010) is None   # sets the EWMA baseline
    alert = wd.observe_serve(2, 0.050)          # 5x the tail: collapse
    assert alert is not None and alert["kind"] == "serve-latency"
    assert alert["ratio"] == pytest.approx(5.0)
    assert wd.verdict() == "serve-latency"
    # the outlier was clamped into the EWMA: baseline not poisoned
    assert wd.serve_ewma < 0.050


def test_watchdog_serve_quiet_on_noise():
    wd = PerfWatchdog(ratio=3.0, warmup=1)
    for w, p in enumerate([0.010, 0.011, 0.009, 0.012, 0.010]):
        assert wd.observe_serve(w, p) is None
    assert wd.verdict() == "ok"


# -- calibration ledger: the serve-p50 pair --------------------------------

def test_serve_p50_ledger_pair():
    """Each watchdog feed must land a joined prediction/measurement pair
    under the serve-p50 cost model (roofline forward bound vs observed
    p50) — the pair `python -m roc_tpu.obs calibration` reports."""
    from roc_tpu import obs
    ds = datasets.get("roc-audit", seed=1)
    eng = _engine(ds)
    try:
        led = obs.get_ledger()
        n0 = len(led.records)
        for _ in range(8):                  # one full feed window
            eng._note_window([0.002, 0.003, 0.004])
        recs = list(led.records)[n0:]
        ms = [r for kind, r in recs
              if kind == "measurement" and r["model"] == "serve-p50"]
        assert ms and "ratio" in ms[-1] and ms[-1]["predicted"] > 0
        assert ms[-1]["value"] == 0.003     # the window median
    finally:
        eng.close()


# -- BENCH_SERVE.json schema gate (tools/perf_ledger.py) -------------------

def _perf_ledger_mod():
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "perf_ledger", os.path.join(os.path.dirname(__file__), "..",
                                    "tools", "perf_ledger.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _serve_payload(**over):
    d = {"metric": "serve_p50", "value": 0.002, "unit": "s",
         "p50_s": 0.002, "p99_s": 0.006, "qps_offered": 100.0,
         "cold_start_s": 0.8, "platform": "cpu",
         "delta": {"apply_p50_s": 0.001, "apply_p99_s": 0.004,
                   "batches": 40, "replans": 1},
         "measured_at": "2026-08-05T00:00:00Z"}
    d.update(over)
    return {k: v for k, v in d.items() if v is not None}


def test_perf_ledger_serve_artifact_schema(tmp_path):
    pl = _perf_ledger_mod()
    root = str(tmp_path)
    with open(os.path.join(root, pl.SERVE_ARTIFACT), "w") as f:
        json.dump(_serve_payload(), f)
    assert pl.check(root) == []
    traj = pl.fold(root)
    assert traj["serve"]["p99_s"] == 0.006
    md = pl.markdown(traj)
    # serving folds in under its own line, NEVER a training-claim row
    assert "Serving (excluded from training claims)" in md
    assert "| serve_p50 |" not in md


def test_perf_ledger_serve_artifact_malformed(tmp_path):
    pl = _perf_ledger_mod()
    root = str(tmp_path)
    with open(os.path.join(root, pl.SERVE_ARTIFACT), "w") as f:
        json.dump(_serve_payload(p99_s=None, measured_at=None,
                                 delta={"apply_p50_s": 0.001}), f)
    errs = pl.check(root)
    assert any("BENCH_SERVE.json" in e and "p99_s" in e for e in errs)
    assert any("measured_at" in e for e in errs)
    # the nested delta block is schema-gated too
    assert any("delta.apply_p99_s" in e for e in errs)
    assert any("delta.replans" in e for e in errs)


# -- roclint: serve host-sync rule -----------------------------------------

def test_lint_serve_sync_rule():
    from roc_tpu.analysis import lint
    src = "import numpy as np\ndef f(x):\n    return np.asarray(x)\n"
    fs = lint.lint_source(src, "roc_tpu/serve/fake.py")
    assert any(f.rule == "host-sync" for f in fs), fs
    # the same conversion outside roc_tpu/serve/ is not a finding
    assert not any(f.rule == "host-sync"
                   for f in lint.lint_source(src, "roc_tpu/train/fake.py"))
    # explicit device syncs are findings too
    src2 = "def g(y):\n    return y.block_until_ready()\n"
    assert any(f.rule == "host-sync"
               for f in lint.lint_source(src2, "roc_tpu/serve/fake.py"))


def test_lint_serve_sync_waiver():
    from roc_tpu.analysis import lint
    src = ("import numpy as np\ndef f(x):\n"
           "    return np.asarray(x)  # roclint: allow(host-sync)\n")
    assert lint.lint_source(src, "roc_tpu/serve/fake.py") == []


# -- config knobs ----------------------------------------------------------

def test_serve_config_knobs(monkeypatch):
    assert Config(layers=[4, 4]).serve_batch == 64
    monkeypatch.setenv("ROC_SERVE_BATCH", "16")
    monkeypatch.setenv("ROC_SERVE_WAIT_MS", "0.5")
    cfg = Config(layers=[4, 4])
    assert cfg.serve_batch == 16 and cfg.serve_wait_ms == 0.5
    monkeypatch.setenv("ROC_SERVE_BATCH", "junk")
    with pytest.raises(SystemExit):
        Config(layers=[4, 4])
    monkeypatch.delenv("ROC_SERVE_BATCH")
    monkeypatch.delenv("ROC_SERVE_WAIT_MS")
    with pytest.raises(SystemExit):
        Config(layers=[4, 4], serve_batch=0)
    with pytest.raises(SystemExit):
        Config(layers=[4, 4], serve_wait_ms=-1.0)
