"""Fault-tolerant runtime: chaos injection, retries, durability, guard.

  inject.py   deterministic seeded fault injection — `point("site")`
              hooks, armed via ROC_FAULT / -fault, no-op otherwise
  retry.py    bounded jittered-exponential retry (`retrying`), per-site
              counters surfaced in the obs JSONL
  durable.py  fsync-before-rename (`fsync_replace`) shared by every
              atomic writer in the tree
  guard.py    in-graph non-finite step guard (`guarded_update`) — skip-
              step via jnp.where, zero syncs/retraces  [imports jax]

`python -m roc_tpu.fault --selftest` is the seeded chaos smoke wired
into tools/preflight.sh.  The core three modules are stdlib-only so
graph/lux.py (numpy + stdlib) can import them; guard is lazy here for
the same reason.
"""

from roc_tpu.fault.durable import fsync_replace
from roc_tpu.fault.inject import (InjectedFault, SimulatedCrash, armed,
                                  attach, configure, counters, detach,
                                  emit_event, parse_spec, point, spec)
from roc_tpu.fault.retry import reset_retry_counts, retry_counts, retrying

__all__ = [
    "InjectedFault", "SimulatedCrash", "armed", "attach", "configure",
    "counters", "detach", "emit_event", "fsync_replace", "guarded_update",
    "nan_scale", "parse_spec", "point", "reset_retry_counts",
    "retry_counts", "retrying", "spec",
]


def __getattr__(name):
    if name in ("guarded_update", "nan_scale"):
        from roc_tpu.fault import guard
        return getattr(guard, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
