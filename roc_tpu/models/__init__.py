from roc_tpu.models.model import GraphCtx, Model
from roc_tpu.models.gcn import build_gcn

__all__ = ["Model", "GraphCtx", "build_gcn"]
