"""Cross-layer megakernel (round 16): whole fusion regions —
aggregate -> linear (-> ReLU) -> aggregate -> linear ... — through ONE
Pallas grid (ops/pallas/binned.py run_binned_region[_bwd] + the
custom-VJP dispatch in ops/aggregate.py region_linear_binned + the
mega_regions planner in models/model.py), in interpret mode on CPU.

Bit-equality strategy mirrors tests/test_mega_bwd.py, with one twist the
region depth adds: magnitudes COMPOUND across fused layers, and the
in-kernel dW accumulates per chunk window while the per-layer oracle
issues one GEMM — the associations only agree bitwise while every
partial sum stays fp32-integer-exact (< 2^24).  A depth-3 chain cubes
the growth, so the bitwise lanes below use small bounded integers (and
the bf16-unit lane keeps every STAGED intermediate bf16-exact, <= 256).

Relu tie rule: the region kernel masks with the replayed forward's
``> 0``, the per-layer FUSED backward masks the saved output ``> 0`` —
tie-consistent — but the fully-unfused replay's ``maximum`` VJP emits
0.5*g at exact-zero pre-activations, which bounded integer data hits
constantly (and a chained dominance construction that avoids ties blows
the 2^24 exactness bound — the magnitudes compound per layer).  So the
relu lanes pin the tie-consistent pair (region vs per-layer-fused), and
the fully-unfused rung joins on the activation-free shape where the tie
rule never fires.  tests/test_mega_bwd.py already pins per-layer-fused
vs fully-unfused WITH relu under single-layer dominance, closing the
triangle.

The decline ladder is the contract under test as much as the kernel:
region -> per-layer fused -> two-pass unfused, each step byte-identical
to the program the narrower mode would have run.
"""

import json
import os
import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from roc_tpu import ops
from roc_tpu.graph import datasets
from roc_tpu.models import build_gcn, build_gin, build_sage
from roc_tpu.models.model import mega_matches, mega_regions
from roc_tpu.ops.aggregate import _unfused_region
from roc_tpu.ops.pallas import binned as B
from roc_tpu.train.config import Config
from roc_tpu.train.driver import Trainer

GF = B.Geometry(sb=256, ch=512, slot=128, rb=256, ch2=512, grt=1 << 14,
                flat=1)
GFB = GF._replace(unit=16)

BASE = dict(num_epochs=3, learning_rate=0.01, weight_decay=5e-4,
            dropout_rate=0.0, eval_every=1000)

_ORIG_XL_RUN = B._xlayer_run
_ORIG_XL_BWD_RUN = B._xlayer_bwd_run


def _spy_region(monkeypatch):
    """(fwd launches, bwd launches) of the REAL region kernels, so the
    decline paths can't fake a fused pass."""
    fwd, bwd = [], []
    monkeypatch.setattr(
        B, "_xlayer_run",
        lambda *a, **k: (fwd.append(1), _ORIG_XL_RUN(*a, **k))[1])
    monkeypatch.setattr(
        B, "_xlayer_bwd_run",
        lambda *a, **k: (bwd.append(1), _ORIG_XL_BWD_RUN(*a, **k))[1])
    return fwd, bwd


def _chain_graph(depth, seed, n=256, h=8, lo=-1, hi=1):
    """Square integer graph + weight chain with magnitudes small enough
    that every partial sum both paths stage or accumulate stays
    fp32-integer-exact at this depth (module docstring).  In-degrees are
    all powers of 4 (1 or 4), so GCN-fold's ``rsqrt(deg)`` scales are
    EXACT powers of two — the folded lanes stay bitwise too; a general
    degree's irrational rsqrt would expose every dW reassociation at the
    ULP level."""
    rng = np.random.default_rng(seed)
    reps = np.ones(n, np.int64)
    reps[rng.permutation(n)[:n // 4]] = 4
    dst = np.repeat(np.arange(n, dtype=np.int64), reps)
    e = int(dst.shape[0])
    src = rng.integers(0, n, e).astype(np.int64)
    x = rng.integers(0, 2, (n, h)).astype(np.float32)
    ws = tuple(rng.integers(lo, hi + 1, (h, h)).astype(np.float32)
               for _ in range(depth))
    g = rng.integers(lo, hi + 1, (n, h)).astype(np.float32)
    return src, dst, x, ws, g, jnp.asarray(reps.astype(np.float32))


def _region_grads(src, dst, x, ws, g, deg, geom, precision, acts, fold,
                  monkeypatch, *, oracle=None):
    """(y, dx, dws, fwd/bwd launch lists) through the region custom VJP,
    or through `_unfused_region` when ``oracle`` names a decline rung:
    "perlayer" keeps the per-layer megakernels, "unfused" kills them."""
    n = int(x.shape[0])
    plans = ops.build_binned_plans(src, dst, n, n, geom=geom)
    if oracle == "unfused":
        monkeypatch.setenv("ROC_BINNED_NO_FUSE", "1")
        monkeypatch.setenv("ROC_MEGA_BWD", "0")
        monkeypatch.setattr(B, "_MEGA_BWD_KILL_WARNED", [True])
    else:
        monkeypatch.delenv("ROC_BINNED_NO_FUSE", raising=False)
        monkeypatch.delenv("ROC_MEGA_BWD", raising=False)
    cf, cb = _spy_region(monkeypatch)
    if oracle is None:
        widths = (x.shape[-1],) + tuple(w.shape[-1] for w in ws)
        assert B.region_ok(plans.fwd, widths, precision, jnp.float32)
        fn = lambda xx, wws: ops.region_linear_binned(
            xx, wws, deg, plans, True, precision, acts, fold)
    else:
        fn = lambda xx, wws: _unfused_region(
            xx, wws, deg, plans, True, precision, acts, fold)
    y, vjp = jax.vjp(fn, jnp.asarray(x), ws)
    dx, dws = vjp(jnp.asarray(g))
    return (np.asarray(y), np.asarray(dx),
            tuple(np.asarray(d) for d in dws), cf, cb)


# -- region vs per-layer-fused vs fully-unfused: bitwise lanes -------------

@pytest.mark.parametrize("fold", [False, True])
@pytest.mark.parametrize("depth", [2, 3])
def test_region_bitwise_exact_fp32(depth, fold, monkeypatch):
    """fp32 staging at ``precision="exact"``: the fused region's forward
    AND backward must be BIT-identical on bounded integer data at depths
    2 and 3 (both fold shapes) — to the per-layer-fused chain with relu
    on every interior layer (the tie-consistent pair: both mask the
    forward output ``> 0``), and to ALL rungs including the fully-unfused
    two-pass chain on the activation-free shape (the ``maximum`` VJP's
    0.5*g tie rule never fires without a relu)."""
    src, dst, x, ws, g, deg = _chain_graph(depth, seed=3 + depth)
    relus = tuple("relu" if d < depth - 1 else "none"
                  for d in range(depth))
    for acts, rungs in (((("none",) * depth), ("perlayer", "unfused")),
                        (relus, ("perlayer",))):
        yf, dxf, dwsf, cf, cb = _region_grads(
            src, dst, x, ws, g, deg, GF, "exact", acts, fold, monkeypatch)
        assert cf and cb, "region kernel fell back"
        for rung in rungs:
            yr, dxr, dwsr, cf2, cb2 = _region_grads(
                src, dst, x, ws, g, deg, GF, "exact", acts, fold,
                monkeypatch, oracle=rung)
            assert not cf2 and not cb2
            np.testing.assert_array_equal(yf, yr)
            np.testing.assert_array_equal(dxf, dxr)
            for a, b in zip(dwsf, dwsr):
                np.testing.assert_array_equal(a, b)


@pytest.mark.parametrize("depth", [2, 3])
def test_region_bitwise_fast_bf16_unit(depth, monkeypatch):
    """bf16 16-row staging unit at ``precision="fast"``: the same bitwise
    rung ladder while every staged intermediate stays bf16-exact — the
    bounded construction keeps row sums under 256."""
    src, dst, x, ws, g, deg = _chain_graph(depth, seed=7 + depth)
    relus = tuple("relu" if d < depth - 1 else "none"
                  for d in range(depth))
    for acts, rungs in (((("none",) * depth), ("perlayer", "unfused")),
                        (relus, ("perlayer",))):
        yf, dxf, dwsf, cf, cb = _region_grads(
            src, dst, x, ws, g, deg, GFB, "fast", acts, False, monkeypatch)
        assert cf and cb
        for rung in rungs:
            yr, dxr, dwsr, _, _ = _region_grads(
                src, dst, x, ws, g, deg, GFB, "fast", acts, False,
                monkeypatch, oracle=rung)
            np.testing.assert_array_equal(yf, yr)
            np.testing.assert_array_equal(dxf, dxr)
            for a, b in zip(dwsf, dwsr):
                np.testing.assert_array_equal(a, b)


def test_region_exact_ulp_bound_continuous(monkeypatch):
    """Continuous data at ``precision="exact"``, depth 2: the region's
    add reassociation (per-chunk in-kernel dW vs the oracle's GEMMs)
    stays within 32 normalized ULPs (abs diff / (eps * row max))."""
    n, e, h = 512, 3000, 64
    rng = np.random.default_rng(11)
    src = rng.integers(0, n, e).astype(np.int64)
    dst = np.sort(np.concatenate([np.arange(n, dtype=np.int64),
                                  rng.integers(0, n, e - n)]))
    x = rng.standard_normal((n, h)).astype(np.float32)
    ws = tuple(jnp.asarray(rng.standard_normal((h, h)).astype(np.float32))
               for _ in range(2))
    g = rng.standard_normal((n, h)).astype(np.float32)
    deg = np.zeros(n, np.float32)
    np.add.at(deg, dst, 1.0)
    deg = jnp.asarray(np.maximum(deg, 1.0))
    acts = ("relu", "none")
    yf, dxf, dwsf, cf, cb = _region_grads(src, dst, x, ws, g, deg, GF,
                                          "exact", acts, False, monkeypatch)
    assert cf and cb
    yr, dxr, dwsr, _, _ = _region_grads(src, dst, x, ws, g, deg, GF,
                                        "exact", acts, False, monkeypatch,
                                        oracle="perlayer")
    eps = np.finfo(np.float32).eps

    def nulp(a, b):
        scale = np.maximum(np.abs(b).max(axis=-1, keepdims=True), 1e-30)
        return float((np.abs(a - b) / (eps * scale)).max())

    assert nulp(yf, yr) <= 32.0
    assert nulp(dxf, dxr) <= 32.0
    for a, b in zip(dwsf, dwsr):
        assert nulp(a, b) <= 32.0


# -- the mega_regions planner (static op-IR grammar) -----------------------

def test_mega_regions_chain_grammar():
    """Residual-free deep GCN: layers 0..L-2 chain (the logits layer
    never joins), depth caps bite, depth 1 disables, and the region's
    skip/gone sets cover exactly the replaced interior."""
    m = build_gcn([64, 16, 16, 16, 8], 0.0, residual=False)
    assert set(mega_matches(m)) == {1, 7, 13, 19}   # stride 6: no residual
    full = mega_regions(m, 0)
    assert set(full) == {1}
    assert len(full[1]["members"]) == 3          # logits layer stays out
    assert full[1]["fold"] is True
    capped = mega_regions(m, 2)
    assert [len(r["members"]) for _, r in sorted(capped.items())] == [2]
    assert mega_regions(m, 1) == {}
    # the dispatch head survives, everything else the region replaces is
    # skipped, and the interior boundaries are the dropped tensors
    r = capped[1]
    assert 1 not in r["skip"]
    assert r["final"].out not in r["gone"]       # region OUTPUT survives
    assert all(t != m.logits.id for t in r["gone"])


def test_mega_regions_residual_and_mlp_break_chains():
    """The deep-GCN residual ``add`` pins every layer boundary (no
    regions), and GIN's second MLP linear is not an admissible
    interstitial — per-layer matches stay available either way."""
    assert mega_regions(build_gcn([64, 16, 16, 8], 0.0), 0) == {}
    assert mega_regions(build_gin([64, 16, 16, 8], 0.0), 0) == {}
    assert mega_matches(build_gin([64, 16, 16, 8], 0.0))


def test_mega_regions_sage_avg_ineligible():
    """SAGE aggregates with avg: the divide-by-degree runs outside any
    kernel, so no member is region-eligible — the decline path."""
    assert mega_regions(build_sage([64, 16, 16, 8], 0.0), 0) == {}


def test_mega_regions_deterministic():
    """Same builder config -> byte-identical region partition (the
    preflight determinism gate's in-process half)."""
    def plan():
        regs = mega_regions(build_gcn([64, 16, 16, 16, 8], 0.0,
                                      residual=False), 0)
        return json.dumps(
            {str(k): {"depth": len(r["members"]), "fold": r["fold"],
                      "skip": list(r["skip"]), "gone": list(r["gone"])}
             for k, r in regs.items()}, sort_keys=True)
    assert plan() == plan()


def test_estimator_prices_region_kept_dropped():
    """Memory-planner honesty (satellite): the estimator consumes the
    region's kept/dropped tuple — inter-layer boundaries inside a fusion
    region price to zero bytes shard-locally, the halo frontier's rows
    survive, and the region OUTPUT boundary stays fully priced."""
    from roc_tpu.memory.estimator import estimate_model
    m = build_gcn([64, 16, 16, 16, 8], 0.0, residual=False)
    rows, edges, h = 1000, 5000, 16
    e1 = estimate_model(m, rows, edges, megafuse=True, fusion_depth=1)
    e2 = estimate_model(m, rows, edges, megafuse=True, fusion_depth=2)
    e0 = estimate_model(m, rows, edges, megafuse=True, fusion_depth=0)
    # monotone in depth: each extra fused boundary drops [rows, h] bytes
    assert e1.total_full_bytes() > e2.total_full_bytes() \
        > e0.total_full_bytes()
    # the full region (3 members) hides 2 interior boundaries; the
    # region-output boundary (layer 2) and logits layer keep full price
    b1 = [l.bytes_boundary for l in e1.layers]
    b0 = [l.bytes_boundary for l in e0.layers]
    assert b0[0] == 0 and b0[1] == 0
    assert b0[2] == b1[2] and b0[3] == b1[3]
    # halo frontier survives: each hidden interior boundary re-prices at
    # [K, h] — twice per boundary, for the activation output AND its
    # pass-through (rate-0) dropout view, both region-dropped tensors
    halo = 64
    eh = estimate_model(m, rows, edges, megafuse=True, fusion_depth=0,
                        halo_rows=halo)
    assert eh.total_full_bytes() - e0.total_full_bytes() \
        == 2 * 2 * halo * h * 4
    assert [l.bytes_boundary for l in eh.layers][0] == halo * h * 4


# -- kill switch + VMEM gate decline ladder --------------------------------

def test_xlayer_kill_switch_warns_once_and_disables(monkeypatch):
    monkeypatch.setattr(B, "_XLAYER_KILL_WARNED", [False])
    monkeypatch.setenv("ROC_XLAYER", "0")
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        assert B.xlayer_killed()
        assert B.xlayer_killed()
    assert sum("ROC_XLAYER" in str(r.message) for r in rec) == 1
    src, dst, x, ws, g, deg = _chain_graph(2, seed=5)
    plans = ops.build_binned_plans(src, dst, 256, 256, geom=GF)
    widths = (8, 8, 8)
    assert not B.region_ok(plans.fwd, widths, "exact", jnp.float32)
    monkeypatch.delenv("ROC_XLAYER")
    monkeypatch.setattr(B, "_XLAYER_KILL_WARNED", [False])
    assert not B.xlayer_killed()
    assert B.region_ok(plans.fwd, widths, "exact", jnp.float32)


def _mega_ds():
    return datasets.get("mega-shard", seed=1)


def _xlayer_trainstep(build, fdepth, monkeypatch, expect_region):
    """One 3-epoch driver leg at the mega-shard shape: returns (logits,
    loss) with the region kernels' launch counts asserted."""
    monkeypatch.setenv("ROC_BINNED_GEOM", "flat")
    monkeypatch.delenv("ROC_XLAYER", raising=False)
    monkeypatch.delenv("ROC_MEGA_BWD", raising=False)
    ds = _mega_ds()
    layers = [ds.in_dim, 16, 16, ds.num_classes]
    cfg = Config(layers=layers, **BASE, aggregate_backend="binned",
                 aggregate_precision="exact", megafuse=True,
                 fusion_depth=fdepth)
    tr = Trainer(cfg, ds, build(layers))
    cf, cb = _spy_region(monkeypatch)
    tr.train(print_fn=lambda *a, **k: None)
    assert bool(cf) == expect_region and bool(cb) == expect_region
    logits = np.asarray(tr._logits_step(tr.params, tr.x, tr.gdata))
    loss = float(ops.masked_softmax_cross_entropy(
        jnp.asarray(logits), tr.labels, tr.mask))
    return logits, loss


def test_gcn_norm_folded_region_trainstep_parity(monkeypatch):
    """Residual-free GCN, norm-folded: 3 training epochs with the region
    forward AND backward land within 1e-3 of the per-layer-fused
    (fusion_depth=1) leg on logits and loss (measured ~5e-7 exact)."""
    build = lambda layers: build_gcn(layers, 0.0, residual=False)
    base = _xlayer_trainstep(build, 1, monkeypatch, expect_region=False)
    for fd in (2, 0):
        got = _xlayer_trainstep(build, fd, monkeypatch, expect_region=True)
        np.testing.assert_allclose(got[0], base[0], atol=1e-3)
        assert abs(got[1] - base[1]) <= 1e-3


def test_sage_decline_is_byte_identical(monkeypatch):
    """SAGE (avg lane): mega_regions offers nothing, so fusion_depth=2
    must run the EXACT fusion_depth=1 program — logits byte-identical,
    zero region launches."""
    build = lambda layers: build_sage(layers, 0.0)
    base = _xlayer_trainstep(build, 1, monkeypatch, expect_region=False)
    got = _xlayer_trainstep(build, 2, monkeypatch, expect_region=False)
    np.testing.assert_array_equal(got[0], base[0])
    assert got[1] == base[1]


def test_region_vmem_gate_falls_back_to_depth1_byte_identical(monkeypatch):
    """A region that fails its VMEM gate must fall through to the
    per-layer pass — byte-identical logits, zero region launches."""
    assert not B._xlayer_vmem_ok(GF, B._pad_to(16384, 128), 3, 2)
    build = lambda layers: build_gcn(layers, 0.0, residual=False)
    base = _xlayer_trainstep(build, 1, monkeypatch, expect_region=False)
    monkeypatch.setattr(B, "_xlayer_vmem_ok", lambda *a, **k: False)
    got = _xlayer_trainstep(build, 2, monkeypatch, expect_region=False)
    np.testing.assert_array_equal(got[0], base[0])
    assert got[1] == base[1]


def test_xlayer_kill_switch_restores_per_layer_program(monkeypatch):
    """ROC_XLAYER=0 with fusion_depth=2 runs the PR-10 per-layer program
    byte for byte (the wholesale kill switch the round promises)."""
    build = lambda layers: build_gcn(layers, 0.0, residual=False)
    base = _xlayer_trainstep(build, 1, monkeypatch, expect_region=False)
    monkeypatch.setenv("ROC_XLAYER", "0")
    monkeypatch.setattr(B, "_XLAYER_KILL_WARNED", [True])
    ds = _mega_ds()
    layers = [ds.in_dim, 16, 16, ds.num_classes]
    cfg = Config(layers=layers, **BASE, aggregate_backend="binned",
                 aggregate_precision="exact", megafuse=True, fusion_depth=2)
    tr = Trainer(cfg, ds, build(layers))
    cf, cb = _spy_region(monkeypatch)
    tr.train(print_fn=lambda *a, **k: None)
    assert not cf and not cb
    logits = np.asarray(tr._logits_step(tr.params, tr.x, tr.gdata))
    np.testing.assert_array_equal(logits, base[0])


# -- budget pins -----------------------------------------------------------

def test_xlayer_budget_rows_pin():
    """Acceptance pin: predicted train-step HBM PER LAYER of a depth-2
    region at the Reddit GCN shape is <= 0.5x the per-layer mega+bwd
    number of record (PR 10's 134.5 MB), and the committed
    ``megakernel_xlayer`` budget rows carry exactly these numbers."""
    n, h = 32768, 256
    perlayer = B.predicted_trainstep_hbm_bytes(n, h, h, mega_bwd=True)
    for depth in (2, 3):
        region = B.predicted_xlayer_trainstep_hbm_bytes(n, h, depth)
        assert region <= 0.5 * depth * perlayer
    path = os.path.join(os.path.dirname(__file__), "..", "tools",
                        "kernel_budgets.json")
    data = json.load(open(path))
    r = data["reddit_scaled"]["megakernel_xlayer"]
    assert r["hbm_trainstep_bytes_perlayer"] == perlayer
    assert r["hbm_trainstep_bytes_xlayer_d2"] == \
        B.predicted_xlayer_trainstep_hbm_bytes(n, h, 2)
    m = data["mega_shard_scaled"]["megakernel_xlayer"]
    assert m["hbm_trainstep_bytes_xlayer_d2"] == \
        B.predicted_xlayer_trainstep_hbm_bytes(1024, h, 2)


# -- retrace + step-cache keying -------------------------------------------

def test_zero_retraces_with_region_active(monkeypatch):
    """Steady-state retrace proof with the region active: fusion depth is
    trace-time static, so epochs 2..N re-enter the same jitted step."""
    from roc_tpu.analysis.retrace import RetraceGuard
    monkeypatch.setenv("ROC_BINNED_GEOM", "flat")
    monkeypatch.delenv("ROC_XLAYER", raising=False)
    ds = _mega_ds()
    layers = [ds.in_dim, 16, 16, ds.num_classes]
    cfg = Config(layers=layers, **BASE, aggregate_backend="binned",
                 megafuse=True, fusion_depth=2)
    tr = Trainer(cfg, ds, build_gcn(layers, 0.0, residual=False))
    cf, cb = _spy_region(monkeypatch)
    with RetraceGuard(warmup=1) as g:
        tr.train(print_fn=lambda *a, **k: None)
        assert g.counts["train_step"] >= 1
    assert cf and cb


def test_sharded_step_cache_keys_on_fusion_depth(monkeypatch):
    """fusion_depth rides ShardedGraphData as STATIC metadata: changing
    the cap changes tree_structure(gd), so the step cache can never serve
    a program traced at another region depth."""
    from roc_tpu.parallel.spmd import SpmdTrainer
    ds = _mega_ds()
    layers = [ds.in_dim, 8, ds.num_classes]

    def make(fd):
        return SpmdTrainer(Config(layers=layers, **BASE, num_parts=4,
                                  halo=True, megafuse=True,
                                  fusion_depth=fd),
                           ds, build_gcn(layers, 0.0))

    t1, t2 = make(1), make(2)
    assert t1.gdata.fusion_depth == 1
    assert t2.gdata.fusion_depth == 2
    assert jax.tree_util.tree_structure(t1.gdata) != \
        jax.tree_util.tree_structure(t2.gdata)


def test_spmd_zero_retraces_and_reshard_with_fusion_depth(monkeypatch):
    """3 sharded epochs + a same-cut reshard with fusion_depth=2 threaded
    through ShardedGraphData: the step cache returns the SAME jitted
    callables and nothing re-traces."""
    from roc_tpu.analysis.retrace import RetraceGuard
    from roc_tpu.parallel.spmd import SpmdTrainer
    ds = _mega_ds()
    layers = [ds.in_dim, 8, ds.num_classes]
    tr = SpmdTrainer(Config(layers=layers, **BASE, num_parts=4, halo=True,
                            megafuse=True, fusion_depth=2),
                     ds, build_gcn(layers, 0.0))
    with RetraceGuard(warmup=1) as g:
        tr.train(print_fn=lambda *a, **k: None)
        assert g.counts["train_step"] >= 1
        snap = g.snapshot()
        step_ids = (id(tr._train_step), id(tr._eval_step))
        tr.reshard(tr.part.bounds)           # same cut, same shapes
        assert (id(tr._train_step), id(tr._eval_step)) == step_ids
        g.arm()
        tr.run_epoch()
        g.assert_no_new_traces(snap)
