"""Host-side span tracer: ring buffer, monotonic clocks, Chrome export.

The repo's timing story before this module was ad hoc: epoch wall-clock in
the driver, probe loops in the balancer, `_time.perf_counter()` pairs in
`reshard` — each with its own roclint waiver and no common schema.  This
module is now the ONE sanctioned wall-clock site (the `raw-timing` lint
rule in roc_tpu/analysis/lint.py enforces it): everything times through

    with obs.span("epoch", epoch=3) as sp:
        ...
    wall = sp.dur_s

A span ALWAYS measures (callers like the driver's epoch loop and the
balance probe use `dur_s` as their timing primitive, tracing on or off);
it is only *recorded* into the ring when tracing is enabled — via
``ROC_OBS=1`` in the environment, ``-obs`` on the CLI, or ``enable()``.
Disabled spans cost two `perf_counter_ns` calls and a list append/pop
(~1 µs; the selftest and tests/test_obs.py gate this), so instrumentation
stays on the hot path unconditionally.

Export is Chrome trace-event JSON (`{"traceEvents": [{"ph": "X", ...}]}`,
timestamps/durations in microseconds) — loadable directly in Perfetto /
chrome://tracing, so a host-side trace from a `-obs` run lines up next to
the device-side xprof trace from `-profile`.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Set

DEFAULT_CAPACITY = 65536  # spans kept; old ones fall off the ring


class Span:
    """One closed span.  ``start_ns`` is `time.perf_counter_ns` (monotonic,
    process-local — NOT wall time); ``depth`` is the nesting level within
    its thread at open time (0 = top level)."""

    __slots__ = ("name", "start_ns", "dur_ns", "tid", "depth", "args")

    def __init__(self, name: str, start_ns: int, dur_ns: int, tid: int,
                 depth: int, args: Optional[dict]):
        self.name = name
        self.start_ns = start_ns
        self.dur_ns = dur_ns
        self.tid = tid
        self.depth = depth
        self.args = args

    @property
    def dur_s(self) -> float:
        return self.dur_ns / 1e9

    def to_event(self) -> dict:
        """Chrome trace-event "complete" ("X") event, microsecond units."""
        ev = {"ph": "X", "name": self.name, "cat": "roc",
              "ts": self.start_ns / 1e3, "dur": self.dur_ns / 1e3,
              "pid": os.getpid(), "tid": self.tid}
        if self.args:
            ev["args"] = {k: _jsonable(v) for k, v in self.args.items()}
        return ev


def _jsonable(v):
    try:
        json.dumps(v)
        return v
    except TypeError:
        return str(v)


class _SpanCtx:
    """Context manager for one span: measures on exit, records into the
    tracer's ring only when tracing is enabled at close time."""

    __slots__ = ("_tracer", "name", "args", "start_ns", "dur_ns", "depth")

    def __init__(self, tracer: "SpanTracer", name: str, args: dict):
        self._tracer = tracer
        self.name = name
        self.args = args
        self.start_ns = 0
        self.dur_ns = 0
        self.depth = 0

    def __enter__(self) -> "_SpanCtx":
        stack = self._tracer._stack()
        self.depth = len(stack)
        stack.append(self)
        self.start_ns = time.perf_counter_ns()
        return self

    def __exit__(self, exc_type, exc, tb):
        self.dur_ns = time.perf_counter_ns() - self.start_ns
        stack = self._tracer._stack()
        if stack and stack[-1] is self:
            stack.pop()
        t = self._tracer
        if t.enabled:
            t._ring.append(Span(self.name, self.start_ns, self.dur_ns,
                                threading.get_ident(), self.depth,
                                self.args or None))
        return False

    @property
    def dur_s(self) -> float:
        return self.dur_ns / 1e9


class SpanTracer:
    """Ring buffer of closed spans + per-thread open-span stacks."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        self.enabled = False
        self._ring: deque = deque(maxlen=capacity)
        self._tls = threading.local()

    def _stack(self) -> list:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        return stack

    def span(self, name: str, **args) -> _SpanCtx:
        return _SpanCtx(self, name, args)

    def spans(self) -> List[Span]:
        return list(self._ring)

    def span_types(self) -> Set[str]:
        return {s.name for s in self._ring}

    def clear(self):
        self._ring.clear()

    def summary(self) -> Dict[str, dict]:
        """Per-span-type aggregate: count, total/mean/max seconds."""
        out: Dict[str, dict] = {}
        for s in self._ring:
            st = out.setdefault(s.name, {"count": 0, "total_s": 0.0,
                                         "max_s": 0.0})
            st["count"] += 1
            st["total_s"] += s.dur_s
            st["max_s"] = max(st["max_s"], s.dur_s)
        for st in out.values():
            st["mean_s"] = st["total_s"] / st["count"]
        return out

    def to_chrome_trace(self) -> dict:
        return {"traceEvents": [s.to_event() for s in self._ring],
                "displayTimeUnit": "ms"}

    def write_chrome_trace(self, path: str) -> bool:
        """Best-effort write (observability must never kill a run)."""
        try:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            with open(path, "w", encoding="utf-8") as f:
                json.dump(self.to_chrome_trace(), f)
                f.write("\n")
            return True
        except OSError:
            return False


def validate_chrome_trace(obj) -> List[str]:
    """Schema problems in a Chrome trace dict ([] = Perfetto-loadable).
    Used by the tests and `python -m roc_tpu.obs selftest`."""
    problems: List[str] = []
    if not isinstance(obj, dict) or "traceEvents" not in obj:
        return ["top level must be a dict with a 'traceEvents' list"]
    events = obj["traceEvents"]
    if not isinstance(events, list):
        return ["'traceEvents' must be a list"]
    for i, ev in enumerate(events):
        if not isinstance(ev, dict):
            problems.append(f"event {i}: not a dict")
            continue
        for key in ("ph", "name", "ts", "pid", "tid"):
            if key not in ev:
                problems.append(f"event {i}: missing {key!r}")
        if ev.get("ph") == "X" and "dur" not in ev:
            problems.append(f"event {i}: complete event missing 'dur'")
        for key in ("ts", "dur"):
            if key in ev and not isinstance(ev[key], (int, float)):
                problems.append(f"event {i}: {key!r} not numeric")
    return problems


# -- module singleton ------------------------------------------------------
# ROC_OBS=1 arms tracing at import so driverless entry points (bench.py,
# pytest fixtures) record without plumbing a flag; Config mirrors the same
# env into cfg.obs and the driver calls enable() for the CLI path.

_TRACER = SpanTracer()
_TRACER.enabled = os.environ.get("ROC_OBS", "") == "1"


def get_tracer() -> SpanTracer:
    return _TRACER


def span(name: str, **args) -> _SpanCtx:
    return _TRACER.span(name, **args)


def enable(on: bool = True):
    _TRACER.enabled = bool(on)


def enabled() -> bool:
    return _TRACER.enabled
