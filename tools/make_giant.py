#!/usr/bin/env python
"""Giant-graph generator + out-of-core drill (the round-20 exit artifact).

Two halves, one file, so the drill can never run against a graph laid
out differently than the generator wrote it:

Generate (default): a power-law synthetic graph in the reference's
on-disk layout — `.lux` CSR plus BINARY-ONLY sidecars (`.feats.bin`,
`.label.bin`).  lux._cache_fresh treats a missing text source as a
binary-only distribution, so the O(N*D) feats CSV that
lux.write_dataset would emit is skipped: at the 100M-node target that
one text file would be terabytes.  Only the `.mask` stays text (the
loader has no binary path for it); it is written in chunks.  Hub
structure: destination ranks are drawn from an inverse-power CDF
(``rank = floor(N * u**skew)``, density ~ rank^(1/skew - 1)) and then
scattered over the id space with a seeded permutation, so the hot rows
land in arbitrary shards instead of shard 0 — the worst case for the
halo maps, which is the case worth drilling.  The generator is O(E)
host RAM (one int64 src/dst pair in flight); a true 1e8/1e9 run is a
big-memory-host job, and --nodes/--deg scale the same layout down to
CI size.

Drill (--drill): load the generated graph, size -stream-budget so the
placed data is >= --budget-ratio x (default 8x) the device budget —
the in-core gate would refuse this graph — then train through the
streaming executor with BOTH giant-tier cuts live: the NVMe spill ring
(--spill, default <out>.spill) and optionally bf16 slots (--bf16).
Epoch 1 compiles; epoch 2 runs under an armed RetraceGuard, so any
rotation/tier retrace fails the drill loudly.  The artifact
(BENCH_STREAM_GIANT.json) records the measured overlap fraction and
bytes/epoch next to the predicted bytes, plus the spill stall split —
the exit-criterion numbers for the giant-graph ROADMAP item.

    python tools/make_giant.py --out /data/giant/g                # generate
    python tools/make_giant.py --out /data/giant/g --drill --bf16 # + drill
"""

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

OUT_JSON = "BENCH_STREAM_GIANT.json"

# feature/label/mask rows written per chunk: bounds generator host RAM to
# ~CHUNK * in_dim * 4 bytes regardless of --nodes
CHUNK = 1 << 20


def _power_law_dst(rng, count, num_nodes, skew):
    """Destination ranks with a power-law hub profile: density ~
    rank^(1/skew - 1), so skew=1 is uniform and skew=3 gives the few-hot-
    hubs shape real social/co-purchase graphs show."""
    u = rng.random(count)
    return np.minimum((num_nodes * u ** skew).astype(np.int64),
                      num_nodes - 1)


def generate(args):
    from roc_tpu import fault
    from roc_tpu.graph import lux
    from roc_tpu.graph.csr import add_self_edges, from_edges

    rng = np.random.default_rng(args.seed)
    n, e = args.nodes, int(args.nodes * args.deg)
    t0 = time.time()
    src = rng.integers(0, n, size=e)
    # scatter the hub ranks across the id space so hot rows land in
    # arbitrary shards (rank 0 at node id perm[0], not node id 0)
    perm = rng.permutation(n)
    dst = perm[_power_law_dst(rng, e, n, args.skew)]
    # self-edges like datasets.synthetic: a zero in-degree row would put
    # 1/sqrt(0) into the GCN norm and train on NaN
    g = add_self_edges(from_edges(n, src, dst))
    del src, dst, perm
    parent = os.path.dirname(args.out)
    if parent:
        os.makedirs(parent, exist_ok=True)
    lux.write_lux(args.out + lux.LUX_SUFFIX, g)
    deg_max = int(np.max(np.diff(g.row_ptr)))
    del g

    labels = rng.integers(0, args.classes, size=n).astype(np.int32)
    lux._atomic_tofile(labels, args.out + ".label.bin")

    # class-informative features so the drill's loss actually moves:
    # per-class mean + unit noise, streamed out in chunks
    means = rng.standard_normal((args.classes, args.in_dim),
                                dtype=np.float32)
    tmp = f"{args.out}.feats.bin.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        for lo in range(0, n, CHUNK):
            hi = min(lo + CHUNK, n)
            x = means[labels[lo:hi]] + rng.standard_normal(
                (hi - lo, args.in_dim), dtype=np.float32)
            x.tofile(f)
    fault.fsync_replace(tmp, args.out + ".feats.bin")

    # scatter the split across the id space: a contiguous Train block
    # would leave every late shard without a single labeled row (its
    # masked loss is 0/0 — the drill would train on NaN)
    n_train = min(args.nodes // 2, 10 * CHUNK)
    n_eval = min(args.nodes // 8, CHUNK)
    status = np.zeros(n, np.uint8)               # 0 = None
    picks = rng.permutation(n)[:n_train + 2 * n_eval]
    status[picks[:n_train]] = 1                  # Train
    status[picks[n_train:n_train + n_eval]] = 2  # Val
    status[picks[n_train + n_eval:]] = 3         # Test
    names = np.array(["None", "Train", "Val", "Test"])
    with open(args.out + ".mask", "w") as f:
        for lo in range(0, n, CHUNK):
            f.write("\n".join(names[status[lo:lo + CHUNK]]) + "\n")
    print(f"# make_giant: wrote {args.out}[.lux/.feats.bin/.label.bin/"
          f".mask] — {n} nodes, {e} edges, max in-degree {deg_max} "
          f"({time.time() - t0:.1f}s)", file=sys.stderr)


def drill(args):
    import jax

    from roc_tpu.analysis import retrace as retrace_mod
    from roc_tpu.analysis.retrace import RetraceGuard
    from roc_tpu.graph import datasets
    from roc_tpu.models import build_model
    from roc_tpu.stream import incore_resident_bytes
    from roc_tpu.train.config import Config
    from roc_tpu.train.driver import make_trainer

    ds = datasets.load_roc_dataset(args.out, args.in_dim, args.classes)
    need = incore_resident_bytes(ds)
    budget = max(int(need // args.budget_ratio), 1)
    spill = args.spill or args.out + ".spill"
    cfg = Config(layers=[args.in_dim, args.hidden, args.classes],
                 num_epochs=1, dropout_rate=0.0, eval_every=10 ** 9,
                 num_parts=args.parts, halo=True, stream=True,
                 stream_slots=args.slots, stream_budget=str(budget),
                 stream_spill=spill, bf16_storage=args.bf16)
    model = build_model("gcn", cfg.layers, cfg.dropout_rate, "")
    t0 = time.time()
    tr = make_trainer(cfg, ds, model)
    loss_cold = float(tr.run_epoch())        # compiles + first rotation
    cold_s = time.time() - t0
    # the zero-retrace claim: a warm epoch through every tier must reuse
    # the compiled programs bit-for-bit (any violation raises here)
    with RetraceGuard(warmup=1, on_violation="raise"):
        retrace_mod.epoch_boundary(1)
        t1 = time.time()
        loss_warm = float(tr.run_epoch())
        warm_s = time.time() - t1
    if not (np.isfinite(loss_cold) and np.isfinite(loss_warm)):
        raise SystemExit(f"drill RED: non-finite loss (cold {loss_cold}, "
                         f"warm {loss_warm}) — the artifact would be a lie")
    st = tr.stream_stats()
    artifact = {
        "metric": "stream_giant_drill",
        "nodes": int(ds.graph.num_nodes),
        "edges": int(ds.graph.num_edges),
        "layers": cfg.layers,
        "parts": args.parts, "slots": args.slots,
        "stream_dtype": st["stream_dtype"],
        "stream_spill": spill,
        "platform": jax.default_backend(),
        # the over-budget claim, measured: placed bytes vs the device
        # budget the in-core gate would have enforced
        "incore_resident_bytes": int(need),
        "stream_budget_bytes": int(budget),
        "budget_ratio": round(need / budget, 2),
        "loss_cold": round(loss_cold, 6),
        "loss_warm": round(loss_warm, 6),
        "epoch_s_cold": round(cold_s, 3),
        "epoch_s_warm": round(warm_s, 3),
        "retraces_warm_epoch": 0,            # guard raised otherwise
        "bytes_per_epoch": st["stream_bytes"],
        "predicted_bytes_per_epoch": int(tr._predicted_epoch_xfer_bytes()),  # roclint: allow(unledgered-prediction) — artifact stamping of the executor's already-ledgered stream_xfer_s predict

        "overlap_frac": st["stream_overlap_frac"],
        "stall_frac": st["stream_stall_frac"],
        "spill_stall_frac": st.get("stream_spill_stall_frac"),
        "spill_bytes": st.get("stream_spill_bytes"),
        "host_stores": st["host_stores"],
    }
    path = args.out_json or os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        OUT_JSON)
    with open(path, "w") as f:
        json.dump(artifact, f, indent=1)
        f.write("\n")
    print(json.dumps(artifact, indent=1))
    print(f"# make_giant: drill artifact -> {path}", file=sys.stderr)


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--out", required=True,
                   help="dataset prefix (writes <out>.lux etc.)")
    p.add_argument("--nodes", type=int, default=1_000_000)
    p.add_argument("--deg", type=float, default=10.0)
    p.add_argument("--skew", type=float, default=3.0,
                   help="power-law skew (1 = uniform, 3 = hubby)")
    p.add_argument("--in-dim", type=int, default=64)
    p.add_argument("--classes", type=int, default=16)
    p.add_argument("--hidden", type=int, default=128)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--drill", action="store_true",
                   help="train 2 epochs out-of-core after generating "
                        "(epoch 2 under an armed RetraceGuard)")
    p.add_argument("--skip-generate", action="store_true",
                   help="drill against an already-generated <out> prefix")
    p.add_argument("--parts", type=int, default=8)
    p.add_argument("--slots", type=int, default=2)
    p.add_argument("--bf16", action="store_true",
                   help="bf16 slot tier (storage dtype; fp32 accumulation)")
    p.add_argument("--spill", default="",
                   help="NVMe spill dir (default <out>.spill)")
    p.add_argument("--budget-ratio", type=float, default=8.0,
                   help="placed-bytes / device-budget ratio the drill "
                        "asserts (the giant-graph claim)")
    p.add_argument("--out-json", default="",
                   help=f"drill artifact path (default repo-root "
                        f"{OUT_JSON})")
    args = p.parse_args(argv)
    if not args.skip_generate:
        generate(args)
    if args.drill:
        drill(args)
    return 0


if __name__ == "__main__":
    sys.exit(main())
