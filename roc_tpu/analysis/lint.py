"""roclint: AST lint for SPMD / jit hazards (CLI: tools/roclint.py).

The runtime checker (`parallel/check.py`) catches *value* bugs by diffing
sharded vs single-device metrics; this pass catches the *performance and
correctness hazards that never change a value*: a host sync hiding inside
a jitted function (silent device→host round trip per step), Python
control flow on a tracer, legacy global-RNG randomness, and the two
classic Python traps (mutable default args, late-binding loop closures).

Rules (waive with ``# roclint: allow(<rule>)`` on the offending or the
preceding line):

``host-sync``
    Inside a *jitted context* (see below): ``.item()``, ``float()/int()/
    bool()`` of a non-literal, ``np.asarray``/``np.array`` of a function
    parameter, ``jax.device_get``, ``device_sync``,
    ``.block_until_ready()``.  Also — anywhere — one of
    ``block_until_ready / device_get / device_sync / .item`` inside a
    *tight timing window* (between ``t = time.perf_counter()`` and its
    ``... - t`` use, windows <= ``TIMED_WINDOW_MAX_LINES`` lines): a sync
    there is being *timed*, which is either the point (waive it, saying
    why) or a measurement bug.
``tracer-branch``
    ``if``/``while`` whose condition calls into ``jnp``/``jax`` inside a
    jitted context — tracer truthiness raises on abstract values, or
    silently specializes the trace.
``unkeyed-rand``
    Legacy numpy global-RNG calls (``np.random.rand/randn/seed/...``) —
    process-global state; use ``np.random.default_rng(seed)`` or
    ``jax.random`` keys.
``mutable-default``
    ``def f(x, acc=[])`` / ``={}`` / ``=set()``.
``closure-capture``
    A ``def``/``lambda`` inside a ``for`` body that captures the loop
    variable freely (late binding: every closure sees the last value).
``remat``
    A raw ``jax.checkpoint`` / ``jax.remat`` call outside
    ``roc_tpu/memory/policy.py`` — ad-hoc rematerialization bypasses the
    memory planner's budget accounting (activation plans must go through
    ``-mem-plan``); policy.py is the one sanctioned call site.  Scan-body
    remat (where the plan abstraction doesn't apply) carries explicit
    waivers.
``raw-timing``
    A ``t = time.perf_counter()`` / ``perf_counter_ns()`` assignment
    paired with a later ``... - t`` use — a hand-rolled timing window —
    in any ``.py`` file outside ``roc_tpu/obs/``.  The obs span tracer
    is the one sanctioned wall-clock site (``with obs.span("x") as sp``
    then ``sp.dur_s``): spans land in the exported trace, nest, and are
    disabled in one place.  Only real file paths are checked (inline
    ``lint_source`` fixtures are exempt).
``unledgered-prediction``
    A ``predicted_*`` / ``measured_*`` string key in a dict literal, or
    an ``emit()``/``record_event()`` keyword of that shape, outside
    ``roc_tpu/obs/`` — the raw-timing rule's sibling for cost models.
    Predictions flow through the calibration ledger
    (``obs.get_ledger().predict/measure``) so they content-key-join and
    show up in `python -m roc_tpu.obs calibration`; an ad-hoc
    ``predicted_foo`` field never pairs with its measurement and drifts
    unchecked.  Legacy artifact stampers (bench.py's memory section,
    the memory plan's ``to_dict``) carry explicit waivers: they
    serialize already-ledgered values for human-facing JSON, they are
    not new prediction sites.

``silent-swallow``
    An ``except:`` handler whose entire body is ``pass``/``continue`` —
    the error vanishes without a log line, a counter, or a comment that
    survives review.  A fault-tolerant runtime is allowed to *drop* an
    error only where the drop is deliberate and visible (warn-once +
    counted, like the plan-cache save path, or an obs JSONL event);
    everything else either propagates or carries a waiver stating why
    swallowing is correct.  Tests are exempt (fixtures poke error paths
    on purpose).

``unpinned-host-buffer``
    A raw numpy allocation (``np.empty/np.zeros/np.ones/np.full``, any
    import spelling) inside ``roc_tpu/stream/`` outside the sanctioned
    allocator module (``roc_tpu/stream/host.py``).  Streamed host stores
    are device-bound staging: the sanctioned allocator backs them with
    pinned zero-copy buffers where the runtime supports it, so a raw
    ``np.zeros`` silently reintroduces the pageable-copy tax on every
    rotation.  Host-side scratch that never ships (index maps being
    assembled, d2h sinks) carries waivers saying so.

``hand-rolled-geometry``
    A ``Geometry(...)`` constructor call outside the sanctioned sites —
    the kernel module that owns the presets
    (``roc_tpu/ops/pallas/binned.py``), the plan builders
    (``roc_tpu/ops/aggregate.py``), the autotuner (``roc_tpu/tune/``),
    and tests.  A hand-rolled geometry bypasses both the analytic cost
    model and the persisted tuned tier, silently pinning a config the
    sweep may already have beaten; go through ``GEOM_PRESETS`` /
    ``choose_geometry``, or waive with a rationale (forced-A/B sweep
    harnesses do).

A *jitted context* is a function that is (a) decorated with ``jax.jit``
/ ``jax.shard_map`` / ``jax.custom_vjp`` (directly or via ``partial``),
(b) passed by name to a tracing entry point (``jax.jit``, ``shard_map``,
``jax.lax.scan/fori_loop/while_loop/cond/switch``, ``grad``,
``value_and_grad``, ``vmap``, ``checkpoint``, ``*.defvjp``), or (c)
syntactically nested inside one of those.  The analysis is per-file and
does not chase calls across functions — a deliberate precision/recall
trade (zero false positives on this tree is a pinned test).
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
from typing import Dict, List, Optional, Set

TIMED_WINDOW_MAX_LINES = 12

# Dotted callables whose bare-Name arguments become traced functions.
_TRACE_CALLS = {
    "jax.jit", "jit", "jax.shard_map", "shard_map", "jax.checkpoint",
    "jax.remat", "jax.vmap", "jax.grad", "jax.value_and_grad",
    "jax.custom_vjp", "jax.custom_jvp", "jax.lax.scan",
    "jax.lax.fori_loop", "jax.lax.while_loop", "jax.lax.cond",
    "jax.lax.switch", "jax.lax.map",
}
# Decorator heads that make the decorated function a traced context.
_TRACE_DECOS = {
    "jax.jit", "jit", "jax.shard_map", "shard_map", "jax.custom_vjp",
    "jax.custom_jvp", "jax.checkpoint", "jax.remat", "jax.vmap",
}
_HOST_SYNC_FNS = {"jax.device_get", "device_get", "device_sync"}
_TIMED_SYNC_ATTRS = {"block_until_ready", "item"}
_LEGACY_NP_RANDOM = {
    "rand", "randn", "randint", "random", "random_sample", "normal",
    "uniform", "seed", "shuffle", "permutation", "choice", "binomial",
    "poisson", "standard_normal",
}
_WAIVER_RE = re.compile(r"#\s*roclint:\s*allow\(([a-z\-,\s]+)\)")
# Raw rematerialization entry points (the `remat` rule); ad_checkpoint
# spellings included so the rule can't be dodged by import path.
_REMAT_CALLS = {
    "jax.checkpoint", "jax.remat", "jax.ad_checkpoint.checkpoint",
    "ad_checkpoint.checkpoint", "checkpoint", "remat",
}
# The one module allowed to call them: the memory planner's policy
# compiler (plans are budgeted there; see roc_tpu/memory).
_REMAT_EXEMPT_SUFFIX = os.path.join("roc_tpu", "memory", "policy.py")
# The one package allowed raw monotonic clocks: the span tracer itself
# (everything else times through `obs.span` so measurements reach the
# exported trace).
_RAW_TIMING_EXEMPT_DIR = os.path.join("roc_tpu", "obs") + os.sep
# Serving hot path (roc_tpu/serve/): the microbatch contract is ONE
# device->host sync per drained window, so ANY sync-shaped call there is
# a finding unless it carries a documented waiver — the jit-scope rule
# can't see these (the serving queue/engine host code isn't jit-traced,
# but a per-request .item() or np.asarray() inside the window still
# serializes the batch it was built to amortize).
_SERVE_DIR = os.path.join("roc_tpu", "serve") + os.sep
# The fleet (roc_tpu/fleet/) rides the same serving hot path — its
# router sits BETWEEN clients and the microbatch window, so a stray
# sync there serializes every replica's batch at once.  Sanctioned
# sites (router ingress id coercion, egress result hand-off) carry
# documented waivers.
_FLEET_DIR = os.path.join("roc_tpu", "fleet") + os.sep
_SERVE_SYNC_CALLS = _HOST_SYNC_FNS | {
    "np.asarray", "np.array", "numpy.asarray", "numpy.array",
}
# Field names that smell like an out-of-ledger prediction/measurement
# (the unledgered-prediction rule); the ledger itself (roc_tpu/obs/)
# is exempt — it *is* the sanctioned sink for these.
_PRED_KEY_RE = re.compile(r"^(predicted|measured)_")
# Paths allowed to construct Geometry(...) literals (the
# hand-rolled-geometry rule): the kernel module that defines it and its
# presets, the plan builders that thread it, the autotuner whose whole
# job is manufacturing candidates, and tests.
_GEOM_EXEMPT_SUFFIXES = (
    os.path.join("roc_tpu", "ops", "pallas", "binned.py"),
    os.path.join("roc_tpu", "ops", "aggregate.py"),
)
_GEOM_EXEMPT_DIRS = (
    os.path.join("roc_tpu", "tune") + os.sep,
    "tests" + os.sep,
)
# Streaming tier (roc_tpu/stream/): host stores are device-bound staging
# and must come from the pinned-capable allocator; host.py is the one
# sanctioned constructor site (the unpinned-host-buffer rule).
_STREAM_DIR = os.path.join("roc_tpu", "stream") + os.sep
_STREAM_ALLOC_EXEMPT_SUFFIX = os.path.join("roc_tpu", "stream", "host.py")
_RAW_ALLOC_CALLS = {
    "np.empty", "np.zeros", "np.ones", "np.full",
    "numpy.empty", "numpy.zeros", "numpy.ones", "numpy.full",
}


@dataclasses.dataclass(frozen=True)
class Finding:
    path: str
    line: int
    rule: str
    msg: str

    def __str__(self):
        return f"{self.path}:{self.line}: [{self.rule}] {self.msg}"


def _dotted(node) -> Optional[str]:
    """'jax.lax.scan' for Attribute chains rooted at a Name, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    parts.append(node.id)
    return ".".join(reversed(parts))


def _call_head(call: ast.Call) -> Optional[str]:
    """Dotted name of what a Call invokes; sees through partial(...)."""
    head = _dotted(call.func)
    if head in ("partial", "functools.partial") and call.args:
        return _dotted(call.args[0])
    return head


def _deco_head(deco) -> Optional[str]:
    if isinstance(deco, ast.Call):
        return _call_head(deco)
    return _dotted(deco)


_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


class _FileLint:
    def __init__(self, path: str, src: str):
        self.path = path
        self.src_lines = src.splitlines()
        self.tree = ast.parse(src, filename=path)
        self.findings: List[Finding] = []
        self.parents: Dict[int, ast.AST] = {}
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                self.parents[id(child)] = node

    # -- helpers ----------------------------------------------------------
    def _flag(self, node, rule: str, msg: str):
        line = getattr(node, "lineno", 1)
        for ln in (line, line - 1):
            if 1 <= ln <= len(self.src_lines):
                m = _WAIVER_RE.search(self.src_lines[ln - 1])
                if m and rule in [r.strip()
                                  for r in m.group(1).split(",")]:
                    return
        self.findings.append(Finding(self.path, line, rule, msg))

    def _enclosing_funcs(self, node):
        cur = self.parents.get(id(node))
        while cur is not None:
            if isinstance(cur, _FUNC_NODES):
                yield cur
            cur = self.parents.get(id(cur))

    # -- jitted-context discovery ----------------------------------------
    def _jitted_roots(self) -> Set[int]:
        jit_names: Set[str] = set()
        roots: Set[int] = set()
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Call):
                head = _call_head(node)
                if head in _TRACE_CALLS or (head or "").endswith(".defvjp"):
                    for a in node.args:
                        if isinstance(a, ast.Name):
                            jit_names.add(a.id)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for deco in node.decorator_list:
                    if _deco_head(deco) in _TRACE_DECOS:
                        roots.add(id(node))
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node.name in jit_names:
                roots.add(id(node))
        return roots

    def _in_jitted(self, node, roots: Set[int]) -> Optional[ast.AST]:
        for f in self._enclosing_funcs(node):
            if id(f) in roots:
                return f
        return None

    @staticmethod
    def _params(func) -> Set[str]:
        if isinstance(func, ast.Lambda):
            a = func.args
        else:
            a = func.args
        names = [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]
        for extra in (a.vararg, a.kwarg):
            if extra is not None:
                names.append(extra.arg)
        return set(names)

    # -- rules ------------------------------------------------------------
    def run(self) -> List[Finding]:
        roots = self._jitted_roots()
        self._rule_jit_scope(roots)
        self._rule_timed_windows()
        self._rule_raw_timing()
        self._rule_unkeyed_rand()
        self._rule_mutable_default()
        self._rule_closure_capture()
        self._rule_remat()
        self._rule_unledgered_prediction()
        self._rule_hand_rolled_geometry()
        self._rule_serve_sync()
        self._rule_silent_swallow()
        self._rule_unpinned_host_buffer()
        return self.findings

    def _rule_unpinned_host_buffer(self):
        """Raw numpy allocations in roc_tpu/stream/ (outside the
        sanctioned allocator, stream/host.py) — streamed stores must go
        through the pinned-capable constructor or carry a waiver saying
        why this buffer never stages to device."""
        p = self.path.replace("/", os.sep)
        if _STREAM_DIR not in p or p.endswith(_STREAM_ALLOC_EXEMPT_SUFFIX):
            return
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Call) and \
                    _call_head(node) in _RAW_ALLOC_CALLS:
                self._flag(
                    node, "unpinned-host-buffer",
                    f"raw {_call_head(node)}(...) in roc_tpu/stream/ — "
                    "device-bound staging must use the sanctioned "
                    "allocator (stream/host.py alloc/to_store, pinned "
                    "zero-copy where supported); waive only for "
                    "host-side scratch that never ships")

    def _rule_silent_swallow(self):
        """``except: pass`` / ``except: continue`` with no logging — the
        error disappears untraced.  Flags the handler's first body
        statement, so a waiver works on the ``pass`` line, the comment
        directly above it, or the ``except`` line when ``pass`` follows
        immediately.  Tests are exempt."""
        p = self.path.replace("/", os.sep)
        if "tests" + os.sep in p or \
                os.path.basename(p).startswith("test_"):
            return
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if node.body and all(isinstance(s, (ast.Pass, ast.Continue))
                                 for s in node.body):
                kind = _dotted(node.type) if node.type is not None \
                    else "bare except"
                self._flag(node.body[0], "silent-swallow",
                           f"except handler ({kind}) swallows the error "
                           f"with no log/counter; emit a warn-once or obs "
                           f"event, or waive with a rationale for why "
                           f"dropping it is correct")

    def _rule_serve_sync(self):
        """Sync-shaped calls in roc_tpu/serve/ and roc_tpu/fleet/ (see
        the _SERVE_DIR / _FLEET_DIR notes)."""
        p = self.path.replace("/", os.sep)
        if _SERVE_DIR not in p and _FLEET_DIR not in p:
            return
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            head = _dotted(node.func)
            if isinstance(node.func, ast.Attribute) and \
                    node.func.attr in _TIMED_SYNC_ATTRS:
                self._flag(node, "host-sync",
                           f".{node.func.attr}() on the serving path "
                           f"forces a device->host sync; the microbatch "
                           f"window sanctions exactly one (waiver it)")
            elif head in _SERVE_SYNC_CALLS:
                self._flag(node, "host-sync",
                           f"{head}() on the serving path is a potential "
                           f"device->host sync; one per drained window is "
                           f"the contract (waiver the sanctioned site)")

    def _rule_jit_scope(self, roots: Set[int]):
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Call):
                owner = self._in_jitted(node, roots)
                if owner is None:
                    continue
                head = _dotted(node.func)
                if isinstance(node.func, ast.Attribute) and \
                        node.func.attr in ("item", "block_until_ready"):
                    self._flag(node, "host-sync",
                               f".{node.func.attr}() inside jit-traced "
                               f"code forces a device->host sync per call")
                elif head in _HOST_SYNC_FNS:
                    self._flag(node, "host-sync",
                               f"{head}() inside jit-traced code is a "
                               f"host transfer on every step")
                elif head in ("float", "int", "bool") and node.args and \
                        not isinstance(node.args[0], ast.Constant):
                    self._flag(node, "host-sync",
                               f"{head}(tracer) concretizes a traced "
                               f"value (host sync / ConcretizationError)")
                elif head in ("np.asarray", "np.array", "numpy.asarray",
                              "numpy.array", "onp.asarray"):
                    names = {n.id for n in ast.walk(node)
                             if isinstance(n, ast.Name)}
                    enclosing_params = set()
                    for f in self._enclosing_funcs(node):
                        enclosing_params |= self._params(f)
                    if names & enclosing_params:
                        self._flag(node, "host-sync",
                                   f"{head}() of a traced argument pulls "
                                   f"the value to the host")
            elif isinstance(node, (ast.If, ast.While)):
                if self._in_jitted(node, roots) is None:
                    continue
                for sub in ast.walk(node.test):
                    if isinstance(sub, ast.Call):
                        h = _dotted(sub.func) or ""
                        if h.split(".")[0] in ("jnp", "jax"):
                            self._flag(
                                node, "tracer-branch",
                                f"Python branch on {h}(...) — tracer "
                                f"truthiness; use jnp.where/lax.cond")
                            break

    def _rule_timed_windows(self):
        """Host syncs inside a tight perf_counter window."""
        for func in ast.walk(self.tree):
            if not isinstance(func, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            starts: Dict[str, int] = {}
            ends: Dict[str, int] = {}
            for node in ast.walk(func):
                if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                        and isinstance(node.targets[0], ast.Name):
                    if any(isinstance(c, ast.Call)
                           and (_dotted(c.func) or "").endswith(
                               "perf_counter")
                           for c in ast.walk(node.value)):
                        t = node.targets[0].id
                        starts.setdefault(t, node.lineno)
                elif isinstance(node, ast.BinOp) and \
                        isinstance(node.op, ast.Sub) and \
                        isinstance(node.right, ast.Name) and \
                        node.right.id in starts and \
                        node.lineno > starts[node.right.id]:
                    t = node.right.id
                    if t not in ends:
                        ends[t] = node.lineno
            for t, lo in starts.items():
                hi = ends.get(t)
                if hi is None or hi - lo > TIMED_WINDOW_MAX_LINES:
                    continue
                for node in ast.walk(func):
                    if not isinstance(node, ast.Call):
                        continue
                    if not (lo < getattr(node, "lineno", 0) < hi):
                        continue
                    name = None
                    if isinstance(node.func, ast.Attribute) and \
                            node.func.attr in _TIMED_SYNC_ATTRS:
                        name = "." + node.func.attr + "()"
                    elif _dotted(node.func) in _HOST_SYNC_FNS:
                        name = _dotted(node.func) + "()"
                    if name:
                        self._flag(
                            node, "host-sync",
                            f"{name} inside the timed window of "
                            f"{t!r} ({lo}..{hi}) — timing a host sync; "
                            f"move it out or waive with a justification")

    @classmethod
    def _scope_walk(cls, scope):
        """Pre-order walk that does not descend into nested functions, so
        each timing window binds within one scope."""
        for child in ast.iter_child_nodes(scope):
            yield child
            if not isinstance(child, _FUNC_NODES):
                yield from cls._scope_walk(child)

    @staticmethod
    def _is_perf_clock(expr) -> bool:
        for c in ast.walk(expr):
            if isinstance(c, ast.Call):
                head = _dotted(c.func) or ""
                if head.endswith("perf_counter") or \
                        head.endswith("perf_counter_ns"):
                    return True
        return False

    def _rule_raw_timing(self):
        """Hand-rolled perf_counter windows outside roc_tpu/obs/."""
        if not self.path.endswith(".py"):
            return  # inline lint_source fixtures ("<string>") are exempt
        if _RAW_TIMING_EXEMPT_DIR in self.path.replace("/", os.sep):
            return
        scopes = [self.tree] + [n for n in ast.walk(self.tree)
                                if isinstance(n, _FUNC_NODES)]
        for scope in scopes:
            starts: Dict[str, ast.AST] = {}
            flagged: Set[str] = set()
            for node in self._scope_walk(scope):
                if isinstance(node, ast.Assign) and \
                        len(node.targets) == 1 and \
                        isinstance(node.targets[0], ast.Name) and \
                        self._is_perf_clock(node.value):
                    starts.setdefault(node.targets[0].id, node)
                elif isinstance(node, ast.BinOp) and \
                        isinstance(node.op, ast.Sub) and \
                        isinstance(node.right, ast.Name) and \
                        node.right.id in starts and \
                        node.right.id not in flagged and \
                        node.lineno > starts[node.right.id].lineno:
                    t = node.right.id
                    flagged.add(t)
                    self._flag(
                        starts[t], "raw-timing",
                        f"raw perf_counter timing window for {t!r}; time "
                        f"through obs.span (roc_tpu/obs is the sanctioned "
                        f"clock site) so the measurement reaches the trace")

    def _rule_unkeyed_rand(self):
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Call):
                head = _dotted(node.func) or ""
                parts = head.split(".")
                if len(parts) == 3 and parts[0] in ("np", "numpy") and \
                        parts[1] == "random" and \
                        parts[2] in _LEGACY_NP_RANDOM:
                    self._flag(node, "unkeyed-rand",
                               f"{head}() uses the process-global legacy "
                               f"RNG; use np.random.default_rng(seed)")

    def _rule_mutable_default(self):
        for node in ast.walk(self.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                     ast.Lambda)):
                continue
            defaults = list(node.args.defaults) + \
                [d for d in node.args.kw_defaults if d is not None]
            for d in defaults:
                bad = isinstance(d, (ast.List, ast.Dict, ast.Set)) or (
                    isinstance(d, ast.Call)
                    and _dotted(d.func) in ("list", "dict", "set")
                    and not d.args and not d.keywords)
                if bad:
                    self._flag(d, "mutable-default",
                               "mutable default argument is shared "
                               "across calls; default to None")

    def _rule_remat(self):
        """Raw jax.checkpoint/jax.remat outside the memory policy module."""
        if self.path.replace("/", os.sep).endswith(_REMAT_EXEMPT_SUFFIX):
            return
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Call) and \
                    _call_head(node) in _REMAT_CALLS:
                self._flag(node, "remat",
                           f"raw {_call_head(node)}() bypasses the memory "
                           f"planner's budget accounting; route remat "
                           f"through roc_tpu/memory (-mem-plan) or waive "
                           f"with a rationale")

    def _rule_hand_rolled_geometry(self):
        """Geometry(...) literals outside the sanctioned construction
        sites.  A hand-rolled geometry bypasses choose_geometry's cost
        model AND the tuned tier (roc_tpu/tune), so it silently pins a
        config the sweep may already have beaten — route through the
        GEOM_PRESETS / choose_geometry / the tuner, or waive with a
        rationale (forced A/B harnesses do)."""
        p = self.path.replace("/", os.sep)
        if any(p.endswith(s) for s in _GEOM_EXEMPT_SUFFIXES) or \
                any(d in p for d in _GEOM_EXEMPT_DIRS) or \
                os.path.basename(p).startswith("test_"):
            return
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            head = _call_head(node)
            if head and (head == "Geometry"
                         or head.endswith(".Geometry")):
                self._flag(node, "hand-rolled-geometry",
                           f"{head}(...) hand-rolls a kernel geometry, "
                           f"bypassing choose_geometry and the tuned "
                           f"tier; use GEOM_PRESETS/choose_geometry or "
                           f"waive with a rationale")

    def _rule_unledgered_prediction(self):
        """predicted_*/measured_* fields minted outside the ledger."""
        if _RAW_TIMING_EXEMPT_DIR in self.path.replace("/", os.sep):
            return  # roc_tpu/obs/ is the ledger — the sanctioned sink
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Dict):
                for k in node.keys:
                    if isinstance(k, ast.Constant) and \
                            isinstance(k.value, str) and \
                            _PRED_KEY_RE.match(k.value):
                        self._flag(
                            k, "unledgered-prediction",
                            f"dict key {k.value!r} mints a prediction/"
                            f"measurement outside the calibration ledger; "
                            f"route it through obs.get_ledger()."
                            f"predict/measure so it content-key-joins, or "
                            f"waive with a rationale")
            elif isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr in ("emit", "record_event"):
                for kw in node.keywords:
                    if kw.arg and _PRED_KEY_RE.match(kw.arg):
                        self._flag(
                            node, "unledgered-prediction",
                            f"{node.func.attr}(..., {kw.arg}=...) emits a "
                            f"prediction/measurement field outside the "
                            f"calibration ledger; use obs.get_ledger()."
                            f"predict/measure so it content-key-joins")

    def _rule_closure_capture(self):
        for loop in ast.walk(self.tree):
            if not isinstance(loop, (ast.For, ast.AsyncFor)):
                continue
            targets = {n.id for n in ast.walk(loop.target)
                       if isinstance(n, ast.Name)}
            for node in ast.walk(loop):
                if node is loop or not isinstance(node, _FUNC_NODES):
                    continue
                bound = self._params(node)
                # names the closure assigns locally are not captures
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Name) and \
                            isinstance(sub.ctx, ast.Store):
                        bound.add(sub.id)
                free = {n.id for n in ast.walk(node)
                        if isinstance(n, ast.Name)
                        and isinstance(n.ctx, ast.Load)} - bound
                # decorator expressions evaluate at def time, not call
                # time — a loop variable there is bound immediately
                # (e.g. @pl.when(c == i)), so it is not a late capture
                if not isinstance(node, ast.Lambda):
                    deco_names = set()
                    for deco in node.decorator_list:
                        deco_names |= {n.id for n in ast.walk(deco)
                                       if isinstance(n, ast.Name)}
                    body_names = set()
                    for part in node.body:
                        body_names |= {n.id for n in ast.walk(part)
                                       if isinstance(n, ast.Name)
                                       and isinstance(n.ctx, ast.Load)}
                    free -= deco_names - body_names
                captured = free & targets
                if captured:
                    self._flag(node, "closure-capture",
                               f"closure captures loop variable(s) "
                               f"{sorted(captured)} by reference (late "
                               f"binding); bind via default arg")


def lint_source(src: str, path: str = "<string>") -> List[Finding]:
    return _FileLint(path, src).run()


def lint_file(path: str) -> List[Finding]:
    with open(path, encoding="utf-8") as f:
        return lint_source(f.read(), path)


def lint_paths(paths) -> List[Finding]:
    """Lint files and/or directory trees (``.py`` only)."""
    out: List[Finding] = []
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = [d for d in dirs
                           if d not in ("__pycache__", ".git")]
                for fn in sorted(files):
                    if fn.endswith(".py"):
                        out.extend(lint_file(os.path.join(root, fn)))
        elif p.endswith(".py"):
            out.extend(lint_file(p))
    return sorted(out, key=lambda f: (f.path, f.line))
