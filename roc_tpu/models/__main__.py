"""Region-plan dump CLI: deterministic fusion-region JSON for a model.

    python -m roc_tpu.models [--model gcn-chain] [--layers 100-256-256-47]
                             [--depth 0] [--heads 4]

Prints the round-16 fusion-region planner's canonical partition — which
per-layer megakernel matches exist, how ``mega_regions`` chains them at
the requested depth cap, and exactly which tensors each region skips and
drops.  Purely analytic (op IR only, no jax arrays), so it is fast
enough for tools/preflight.sh to run twice and ``cmp`` the outputs: the
region partition participates in the step-cache key via
``fusion_depth``, so a nondeterministic plan here would mean phantom
retraces on device.
"""

from __future__ import annotations

import argparse
import json
import sys

from roc_tpu.models import build_model
from roc_tpu.models.model import mega_matches, mega_regions


def region_plan_json(model_name: str, layers, depth: int,
                     heads: int = 4) -> str:
    """Canonical (sorted-key, fixed-separator) region-plan JSON."""
    model = build_model(model_name, layers, dropout_rate=0.0, heads=heads)
    regs = mega_regions(model, depth)
    plan = {
        "model": model_name,
        "layers": list(layers),
        "fusion_depth": depth,
        "matches": sorted(mega_matches(model)),
        "regions": {
            str(head): {
                "depth": len(r["members"]),
                "fold": bool(r["fold"]),
                "members": [
                    {"param": m["linear"].attrs["param"],
                     "in_dim": m["linear"].attrs["in_dim"],
                     "out_dim": m["linear"].attrs["out_dim"],
                     "activation": m["activation"]}
                    for m in r["members"]],
                "final_out": int(r["final"].out),
                "skip": sorted(int(t) for t in r["skip"]),
                "gone": sorted(int(t) for t in r["gone"]),
            }
            for head, r in regs.items()},
    }
    return json.dumps(plan, sort_keys=True, separators=(",", ":"))


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="roc_tpu.models")
    p.add_argument("--model", default="gcn-chain",
                   choices=["gcn", "gcn-chain", "sage", "gin", "gat"])
    p.add_argument("--layers", default="100-256-256-47",
                   help="dash-separated widths incl. input and classes")
    p.add_argument("--depth", type=int, default=0,
                   help="fusion-region depth cap (0 = full, 1 = disabled)")
    p.add_argument("--heads", type=int, default=4)
    ns = p.parse_args(argv)
    layers = [int(x) for x in ns.layers.split("-")]
    sys.stdout.write(region_plan_json(ns.model, layers, ns.depth,
                                      heads=ns.heads) + "\n")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
