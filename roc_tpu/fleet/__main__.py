"""`python -m roc_tpu.fleet --selftest`: the replicated-serving drill.

End-to-end on CPU with the tiny audit graph (preflight's fleet step):
warm the content-keyed plan cache, then stand up a 3-replica fleet
(primary + 2 followers on in-proc transports) behind the router and pin
the fleet contracts in one process:

  1. every replica cold-starts from the warm cache with ZERO plan
     rebuilds (cache read + one trace each),
  2. a 1000-event mixed query+delta stream keeps all replicas in seq
     lockstep with ZERO retraces and ZERO plan rebuilds after warmup,
  3. a seeded hard kill (``fleet.replica.kill``) of one follower
     mid-stream never loses an acked delta: the router keeps answering
     on the survivors, and the restarted replica replays its local WAL
     then catches the missed records up through the snapshot protocol
     (checkpoint + truncated journal + tail segments),
  4. every replica's served logits match a single delta-enabled
     ServeEngine oracle fed the exact same deltas, bitwise (0 ULPs),
  5. backpressure is typed and visible: deadline-expired requests and
     fleet sheds are counted in ``router.stats()``, never silent.

Exit 0 with a one-line summary per contract; any violation raises.
"""

from __future__ import annotations

import os
import sys
import tempfile
import warnings


def selftest() -> int:
    tmp = tempfile.mkdtemp(prefix="roc_fleet_selftest_")
    os.environ["ROC_PLAN_CACHE_DIR"] = os.path.join(tmp, "plan_cache")
    os.environ["ROC_PLAN_CACHE_MIN_EDGES"] = "0"

    import numpy as np

    from roc_tpu.fault import SimulatedCrash, inject
    from roc_tpu.fleet import (FleetRouter, InProcTransport, Replica,
                               ReplicationLog)
    from roc_tpu.graph import datasets
    from roc_tpu.models import build_model
    from roc_tpu.obs.watchdog import PerfWatchdog
    from roc_tpu.ops.pallas import binned as _B
    from roc_tpu.serve import ServeEngine, max_ulp_diff
    from roc_tpu.serve.queue import Overloaded
    from roc_tpu.train import checkpoint
    from roc_tpu.train.config import Config
    from roc_tpu.train.driver import make_trainer

    cfg = Config(dataset="roc-audit", layers=[8, 16, 4], num_epochs=2,
                 aggregate_backend="binned", serve_batch=8,
                 serve_wait_ms=1.0)
    ds = datasets.get(cfg.dataset, seed=cfg.seed)
    model = build_model(cfg.model, cfg.layers, cfg.dropout_rate, cfg.aggr,
                        heads=cfg.heads)

    # -- warm: train briefly so every cold start below is a cache read
    trainer = make_trainer(cfg, ds, model)
    trainer.train()
    ckpt = os.path.join(tmp, "fleet.ckpt.npz")
    checkpoint.save(ckpt, trainer.params, trainer.opt_state, trainer.epoch,
                    trainer.optimizer.alpha)
    del trainer

    wd = PerfWatchdog()
    n = ds.graph.num_nodes
    all_ids = np.arange(n, dtype=np.int32)

    def make_replica(name):
        return Replica(name, cfg, ds, model, ckpt,
                       os.path.join(tmp, f"{name}.wal"), watchdog=wd)

    primary = make_replica("primary")
    followers = [make_replica("follower-1"), make_replica("follower-2")]
    replog = ReplicationLog(primary.engine)
    for rep in followers:
        rep.transport = replog.attach(InProcTransport())
    router = FleetRouter(primary, followers, replog, freshness_floor=0,
                         max_retries=1, watchdog=wd)
    # the oracle: ONE delta-enabled engine (volatile journal — same
    # two-pass unfused execution as the fleet members) fed every delta
    oracle = ServeEngine(cfg, ds, model, checkpoint_path=ckpt,
                         delta_journal="")
    builds0 = _B.plan_build_count()

    for rep in router.replicas:
        cs = rep.engine.cold_start_stats
        assert cs["plan_builds"] == 0, (
            f"{rep.name} cold start rebuilt {cs['plan_builds']} plan(s); "
            f"the shared warm plan cache must make every fleet cold "
            f"start a cache read")
    print(f"# fleet selftest: 3 replicas cold-started from the shared "
          f"plan cache, plan_builds=0 each")

    # -- warmup + retrace baselines for the members that live all drill
    for eng in (primary.engine, followers[0].engine, oracle):
        eng.warmup()
    # trace notes are global across engines, so ONE guard's baseline
    # covers the whole process; keyed by drill window
    guards = {"primary": primary.engine._guard.snapshot()}

    # -- 1000-event mixed stream with a seeded kill window ------------------
    rng = np.random.default_rng(17)
    added: list = []
    deltas = 0
    answered = 0
    fleet_shed = 0
    kill_at, restart_at = 400, 700
    seq_at_kill = None

    def one_delta():
        nonlocal deltas
        if added and (len(added) >= 12 or rng.random() < 0.4):
            rets = np.stack([added.pop(0), added.pop(0)], 0)
            adds = None
        else:
            adds = rng.integers(0, n, (2, 2))
            added.extend(list(adds))
            rets = None
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", RuntimeWarning)
            router.apply_delta(adds, rets)   # primary + pump to followers
            oracle.apply_delta(adds, rets)   # same batch, single engine
        deltas += 1

    for event in range(1000):
        if event == kill_at:
            # seeded hard kill: no graceful drain, transport lost too
            inject.configure("fleet.replica.kill=1")
            try:
                followers[1].kill()
                raise AssertionError("armed kill site did not fire")
            except SimulatedCrash:
                pass  # roclint: allow(silent-swallow) — the crash IS the drill
            finally:
                inject.configure("")
            seq_at_kill = primary.applied_seq
            replog.detach(followers[1].transport)
            print(f"# fleet selftest: follower-2 hard-killed at event "
                  f"{kill_at} (seq {seq_at_kill}); serving continues on "
                  f"{len(router.eligible())} replicas")
            # deltas keep landing while it is down — the records it will
            # have to catch up on through the snapshot protocol
            for _ in range(3):
                one_delta()
        if event == restart_at:
            # the kill window itself must not have retraced any survivor
            # (trace notes are GLOBAL across engines, so one guard's
            # baseline diff covers the whole process up to this point)
            primary.engine._guard.assert_no_new_traces(guards["primary"])
            followers[1].restart()
            assert followers[1].applied_seq == seq_at_kill, (
                f"restart should replay the local WAL exactly to the "
                f"kill-time watermark {seq_at_kill}, got "
                f"{followers[1].applied_seq}")
            followers[1].transport = replog.attach(InProcTransport())
            head = primary.applied_seq
            applied = router.pump()   # gap -> snapshot catch-up, in-line
            assert router.catch_ups >= 1, (
                "restarted replica should have needed snapshot catch-up")
            assert followers[1].applied_seq == primary.applied_seq, (
                f"catch-up left follower-2 at seq "
                f"{followers[1].applied_seq}, head {primary.applied_seq}")
            print(f"# fleet selftest: follower-2 restarted, replayed its "
                  f"WAL to seq {seq_at_kill}, snapshot catch-up to seq "
                  f"{head} ({applied} records this pump)")
            # the two rebuilds above legitimately traced their cold-start
            # buckets; re-warm the new engine and re-baseline — from here
            # to the end of the drill, zero new traces is the contract
            followers[1].engine.warmup()
            guards["post-restart"] = primary.engine._guard.snapshot()
        if rng.random() < 0.05:
            one_delta()
        else:
            k = int(rng.integers(1, 9))
            ids = rng.integers(0, n, k).astype(np.int32)
            try:
                got = router.query(ids, timeout=120.0)
                assert got.shape[0] == k
                answered += 1
            except Overloaded:
                fleet_shed += 1   # typed, counted — never silent

    router.pump()
    head = primary.applied_seq
    for rep in router.replicas:
        assert rep.applied_seq == head, (
            f"{rep.name} at seq {rep.applied_seq}, head {head}: fleet "
            f"out of lockstep after the stream")
    print(f"# fleet selftest: 1000-event stream — {answered} answered, "
          f"{deltas + 3} delta batches to seq {head}, "
          f"{fleet_shed} shed at the router")

    # -- parity: every replica bitwise vs the single-engine oracle ----------
    want = oracle.query(all_ids, timeout=120.0)
    routed = router.query(all_ids, timeout=120.0)
    assert max_ulp_diff(routed, want) == 0, "routed query diverged"
    for rep in router.replicas:
        got = rep.engine.query(all_ids, timeout=120.0)
        ulps = max_ulp_diff(got, want)
        assert ulps == 0, (
            f"{rep.name} diverged from the single-engine oracle by "
            f"{ulps} ULPs (want bitwise)")
    print(f"# fleet selftest: parity — all 3 replicas bitwise-identical "
          f"to the single-engine oracle (0 ULPs), incl. the restarted one")

    # -- zero retraces / zero plan rebuilds across the whole drill ----------
    # (trace notes are global: the post-restart baseline covers every
    # live engine — 300 more events, catch-up replay, parity queries)
    primary.engine._guard.assert_no_new_traces(guards["post-restart"])
    assert _B.plan_build_count() == builds0, (
        "the drill rebuilt a plan; replication must ride the patch path")
    st = primary.engine.delta_stats()
    assert st["replans"] == 0, "churn escalated to a replan"
    print(f"# fleet selftest: zero retraces outside the sanctioned "
          f"restart window, zero plan rebuilds fleet-wide, zero replans")

    # -- typed backpressure: deadline-expired requests are counted ----------
    futs = [router.submit([int(i % n)], deadline_s=0.0) for i in range(16)]
    expired = 0
    for f in futs:
        try:
            f.result(timeout=30.0)
        except Overloaded:
            expired += 1
    rstats = router.stats()
    assert expired > 0 and rstats["expired"] >= expired
    assert wd.fleet_observed > 0, "observe_fleet never fed"
    print(f"# fleet selftest: backpressure typed + counted "
          f"(expired={rstats['expired']}, shed={rstats['shed']}, "
          f"sibling_retries={rstats['sibling_retries']}); replication "
          f"lag EWMA fed {wd.fleet_observed} times, "
          f"{rstats['replog']['segments_shipped']} segments shipped")

    oracle.close()
    router.close()
    print("# fleet selftest: OK")
    return 0


def main(argv) -> int:
    if "--selftest" in argv:
        return selftest()
    print("usage: python -m roc_tpu.fleet --selftest", file=sys.stderr)
    return 2


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
