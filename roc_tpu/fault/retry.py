"""Bounded retry-with-backoff — the one retry policy in the tree.

``retrying(site, fn)`` runs ``fn`` up to ``attempts`` times, sleeping a
jittered exponential backoff between tries, and re-raises the last
error when the budget is spent.  Every retry is counted per-site and
emitted into the obs JSONL (``{"type": "retry", ...}``) when a trainer
has attached its metrics sink, and the backoff sleep itself runs under
an ``obs.span`` so chaos legs show their stalls in the exported trace.

The ``ROC_FAULT`` spec's ``retries=N`` token overrides the budget at
every site at once — ``retries=0`` is how the chaos tests prove the
fault legs *need* the retries they exercise.

Backoff jitter is a hash of (site, attempt), not a clock or an RNG:
deterministic schedules keep the seeded chaos runs reproducible.
"""

from __future__ import annotations

import threading
import time
import zlib
from typing import Callable, Tuple, Type

from roc_tpu import obs
from roc_tpu.fault import inject

_LOCK = threading.Lock()
_RETRIES: dict = {}   # site -> retries performed (sleep-then-try count)


def retry_counts() -> dict:
    with _LOCK:
        return dict(_RETRIES)


def reset_retry_counts() -> None:
    with _LOCK:
        _RETRIES.clear()


def _backoff_s(site: str, attempt: int, base_s: float,
               max_s: float) -> float:
    delay = min(max_s, base_s * (2.0 ** attempt))
    frac = (zlib.crc32(f"{site}:{attempt}".encode()) & 0xFFFF) / 0xFFFF
    return delay * (0.5 + 0.5 * frac)


def retrying(site: str, fn: Callable, *, attempts: int = 3,
             base_s: float = 0.05, max_s: float = 2.0,
             retry_on: Tuple[Type[BaseException], ...] = (OSError,)):
    """Call ``fn()`` with up to ``attempts`` total tries.

    ``retry_on`` must be ``Exception`` subclasses — ``SimulatedCrash``
    is a ``BaseException`` precisely so it can NOT be retried away.
    """
    override = inject.retry_override()
    if override is not None:
        attempts = override + 1
    attempt = 0
    while True:
        try:
            return fn()
        except retry_on as e:
            attempt += 1
            with _LOCK:
                _RETRIES[site] = _RETRIES.get(site, 0) + 1
            inject.emit_event("retry", site=site, attempt=attempt,
                              limit=attempts, error=type(e).__name__,
                              detail=str(e)[:200])
            if attempt >= attempts:
                raise
            with obs.span("fault_retry", site=site, attempt=attempt):
                time.sleep(_backoff_s(site, attempt - 1, base_s, max_s))
