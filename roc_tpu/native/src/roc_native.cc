// Native runtime layer: graph IO + partitioning hot paths.
//
// The reference implements its whole data layer natively (load_task.cu:
// per-partition fseeko/fread of the .lux byte ranges; gnn.cc:751-872 header
// read + greedy edge-balanced partition; load_task.cu:25-74 feature CSV
// parse with .feats.bin caching).  This library is the TPU framework's
// equivalent: the byte-level parsing/seeking/partitioning runs in C++, and
// Python (roc_tpu.graph.lux / .partition) calls it through ctypes, with a
// NumPy fallback that doubles as the correctness oracle in tests.
//
// Build: make -C roc_tpu/native    (g++ -O3 -shared; no external deps)
// ABI: plain C symbols; all buffers are caller-allocated NumPy arrays.

#include <algorithm>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <vector>

extern "C" {

// .lux layout (see roc_tpu/graph/lux.py): u32 numNodes, u64 numEdges,
// u64 raw_rows[numNodes] (inclusive end offsets), u32 raw_cols[numEdges].
static const long HEADER_SIZE = 12;  // sizeof(u32) + sizeof(u64)

// Returns 0 on success; fills *num_nodes / *num_edges.
int roc_lux_header(const char* path, uint32_t* num_nodes,
                   uint64_t* num_edges) {
  FILE* f = fopen(path, "rb");
  if (!f) return -1;
  int ok = fread(num_nodes, sizeof(uint32_t), 1, f) == 1 &&
           fread(num_edges, sizeof(uint64_t), 1, f) == 1;
  fclose(f);
  return ok ? 0 : -2;
}

// Read a vertex/edge slice: rows [row_lo, row_hi) of the offset section and
// cols [col_lo, col_hi) of the column section — the per-partition seeking
// pattern of the reference's load_graph_impl.  Whole-graph read = one slice.
int roc_lux_read_slice(const char* path, uint64_t row_lo, uint64_t row_hi,
                       uint64_t col_lo, uint64_t col_hi,
                       uint64_t* rows_out, uint32_t* cols_out) {
  FILE* f = fopen(path, "rb");
  if (!f) return -1;
  uint32_t nv;
  uint64_t ne;
  if (fread(&nv, sizeof nv, 1, f) != 1 || fread(&ne, sizeof ne, 1, f) != 1) {
    fclose(f);
    return -2;
  }
  if (row_hi > nv || col_hi > ne || row_lo > row_hi || col_lo > col_hi) {
    fclose(f);
    return -3;
  }
  int rc = 0;
  uint64_t nrows = row_hi - row_lo, ncols = col_hi - col_lo;
  if (nrows) {
    if (fseeko(f, HEADER_SIZE + 8 * (long)row_lo, SEEK_SET) != 0 ||
        fread(rows_out, 8, nrows, f) != nrows)
      rc = -4;
  }
  if (rc == 0 && ncols) {
    if (fseeko(f, HEADER_SIZE + 8 * (long)nv + 4 * (long)col_lo,
               SEEK_SET) != 0 ||
        fread(cols_out, 4, ncols, f) != ncols)
      rc = -5;
  }
  fclose(f);
  return rc;
}

int roc_lux_write(const char* path, uint32_t num_nodes, uint64_t num_edges,
                  const uint64_t* raw_rows, const uint32_t* raw_cols) {
  FILE* f = fopen(path, "wb");
  if (!f) return -1;
  int ok = fwrite(&num_nodes, sizeof num_nodes, 1, f) == 1 &&
           fwrite(&num_edges, sizeof num_edges, 1, f) == 1 &&
           fwrite(raw_rows, 8, num_nodes, f) == num_nodes &&
           fwrite(raw_cols, 4, num_edges, f) == num_edges;
  fclose(f);
  return ok ? 0 : -2;
}

// Greedy edge-balanced contiguous partition — the exact cut rule of the
// reference (gnn.cc:806-829): accumulate in-degrees, open a new part when
// the running count exceeds ceil(E/P).  raw_rows are inclusive end offsets
// (the on-disk form).  bounds_out: [num_parts][2] inclusive vertex ranges.
// Returns the number of parts actually produced (may differ from
// num_parts for pathological graphs; Python repairs, as the reference
// would have assert-failed).
int64_t roc_partition(const uint64_t* raw_rows, uint64_t num_nodes,
                      uint64_t num_edges, int64_t num_parts,
                      int64_t* bounds_out) {
  if (num_parts < 1 || num_nodes == 0) return 0;
  uint64_t edge_cap = (num_edges + num_parts - 1) / num_parts;
  uint64_t cnt = 0, left = 0;
  int64_t p = 0;
  for (uint64_t v = 0; v < num_nodes; v++) {
    cnt += raw_rows[v] - (v ? raw_rows[v - 1] : 0);
    if (cnt > edge_cap) {
      if (p < num_parts) {
        bounds_out[2 * p] = (int64_t)left;
        bounds_out[2 * p + 1] = (int64_t)v;
      }
      p++;
      cnt = 0;
      left = v + 1;
    }
  }
  if (cnt > 0 || left < num_nodes) {
    if (p < num_parts) {
      bounds_out[2 * p] = (int64_t)left;
      bounds_out[2 * p + 1] = (int64_t)num_nodes - 1;
    }
    p++;
  }
  return p;
}

// Fast CSV float parse: num_rows lines of num_cols comma-separated floats
// (the reference's cold-start path before it writes .feats.bin,
// load_task.cu:44-66).  Returns rows parsed, or negative errno-style code.
int64_t roc_parse_feats_csv(const char* path, int64_t num_rows,
                            int64_t num_cols, float* out) {
  FILE* f = fopen(path, "rb");
  if (!f) return -1;
  // Stream with a buffered reader; strtof consumes "+1.5e-3" etc. and
  // leaves the pointer on the delimiter.
  size_t cap = 1 << 20;
  char* line = (char*)malloc(cap);
  int64_t r = 0;
  for (; r < num_rows; r++) {
    ssize_t len = getline(&line, &cap, f);
    if (len < 0) break;
    char* p = line;
    for (int64_t c = 0; c < num_cols; c++) {
      char* end;
      out[r * num_cols + c] = strtof(p, &end);
      if (end == p) {  // malformed/empty cell — match NumPy-path strictness
        free(line);
        fclose(f);
        return -(r + 2);
      }
      p = end;
      if (c + 1 < num_cols) {
        if (*p != ',') {  // exactly one delimiter; too few columns errors
          free(line);
          fclose(f);
          return -(r + 2);
        }
        p++;
      }
    }
    while (*p == ' ' || *p == '\r') p++;
    if (*p != '\n' && *p != '\0') {  // trailing junk / extra columns
      free(line);
      fclose(f);
      return -(r + 2);
    }
  }
  // Match the NumPy path's strictness on row count too: anything but
  // trailing blank lines after num_rows rows is an error.
  if (r == num_rows) {
    ssize_t len;
    while ((len = getline(&line, &cap, f)) >= 0) {
      char* p = line;
      while (*p == ' ' || *p == '\r' || *p == '\n') p++;
      if (*p != '\0') {
        free(line);
        fclose(f);
        return -(num_rows + 2);
      }
    }
  }
  free(line);
  fclose(f);
  return r;
}

// ---------------------------------------------------------------------------
// Chunk-plan builder for the TPU aggregation backends (the host-side
// "scheduler" of roc_tpu/ops/pallas/segment_sum.py::build_chunk_plan —
// identical semantics, linear single pass).  The dst-sorted edge list is cut
// into chunks of EB edge slots, each owning a VB-row output window; sparse
// windows get one padded (zeroing) chunk; the chunk count is padded to a
// multiple of CPAD.  At ogbn-papers100M scale (1.6e9 edges) the NumPy plan
// build costs minutes; this runs at memory speed.
// ---------------------------------------------------------------------------

static const int64_t PLAN_VB = 8, PLAN_EB = 256, PLAN_CPAD = 8;

// Export the compiled-in geometry so the Python side (whose
// segment_sum.VB/EB/CPAD are the source of truth) can assert agreement.
void roc_plan_geometry(int64_t* out3) {
  out3[0] = PLAN_VB;
  out3[1] = PLAN_EB;
  out3[2] = PLAN_CPAD;
}

// Number of chunks (already CPAD-padded) for a dst-sorted edge list.
int64_t roc_chunk_plan_count(const int32_t* dst, int64_t num_edges,
                             int64_t num_rows) {
  int64_t windows = (num_rows + PLAN_VB - 1) / PLAN_VB;
  if (windows < 1) windows = 1;
  int64_t C = 0, e = 0;
  for (int64_t w = 0; w < windows; w++) {
    int64_t hi = (w + 1) * PLAN_VB;
    int64_t cnt = 0;
    while (e < num_edges && dst[e] < hi) { e++; cnt++; }
    int64_t nc = (cnt + PLAN_EB - 1) / PLAN_EB;
    C += nc < 1 ? 1 : nc;
  }
  return (C + PLAN_CPAD - 1) / PLAN_CPAD * PLAN_CPAD;
}

// Fill obi/first/esrc/edst (each caller-allocated: [C], [C], [C*EB], [C*EB]).
// Returns 0 on success, -1 if the passed C does not match.
int64_t roc_chunk_plan_fill(const int32_t* src, const int32_t* dst,
                            int64_t num_edges, int64_t num_rows, int64_t C,
                            int32_t* obi, int32_t* first, int32_t* esrc,
                            int32_t* edst) {
  int64_t windows = (num_rows + PLAN_VB - 1) / PLAN_VB;
  if (windows < 1) windows = 1;
  int64_t c = 0, e = 0;
  for (int64_t w = 0; w < windows; w++) {
    int64_t hi = (w + 1) * PLAN_VB;
    int64_t start = e;
    while (e < num_edges && dst[e] < hi) e++;
    int64_t cnt = e - start;
    int64_t nc = (cnt + PLAN_EB - 1) / PLAN_EB;
    if (nc < 1) nc = 1;
    for (int64_t j = 0; j < nc; j++, c++) {
      if (c >= C) return -1;
      obi[c] = (int32_t)w;
      first[c] = j == 0;
      int64_t lo = start + j * PLAN_EB;
      int64_t take = cnt - j * PLAN_EB;
      if (take > PLAN_EB) take = PLAN_EB;
      if (take < 0) take = 0;
      int32_t* es = esrc + c * PLAN_EB;
      int32_t* ed = edst + c * PLAN_EB;
      for (int64_t k = 0; k < take; k++) {
        es[k] = src[lo + k];
        ed[k] = (int32_t)(dst[lo + k] - w * PLAN_VB);
      }
      for (int64_t k = take; k < PLAN_EB; k++) {
        es[k] = 0;
        ed[k] = (int32_t)PLAN_VB;  // masked pad slot
      }
    }
  }
  // CPAD padding: no-op chunks against the last window.
  int32_t last = c ? obi[c - 1] : 0;
  for (; c < C; c++) {
    obi[c] = last;
    first[c] = 0;
    for (int64_t k = 0; k < PLAN_EB; k++) {
      esrc[c * PLAN_EB + k] = 0;
      edst[c * PLAN_EB + k] = (int32_t)PLAN_VB;
    }
  }
  return 0;
}

// ---------------------------------------------------------------------------
// Halo-map builder (roc_tpu/parallel/halo.py fast path).  For each dest part
// p the per-(p,q) send lists are the sorted-unique remote padded-global
// sources grouped by owner q; each edge source is remapped into the combined
// table [S own rows ++ P*K recv rows].  Two-call protocol like the chunk
// planner: sizes first (fixes K), then fill.
//
// No sorts anywhere: a byte-mark over the padded id space [0, P*S) makes
// "sorted unique remote sources" a linear block scan (ids are already
// (owner, local)-ordered by construction), and the per-edge remap a direct
// lookup.  All passes are streaming or L2-resident.  At products scale
// (1.25e8 edges) this runs in ~3 s vs ~60 s for round-1's per-pair NumPy
// loops (docs/PERF.md).
// ---------------------------------------------------------------------------

// sizes_out: [P*P] int64, sizes_out[p*P+q] = rows part p needs from part q.
int roc_halo_sizes(const int64_t* edge_src, int64_t P, int64_t E, int64_t S,
                   int64_t* sizes_out) {
  std::vector<uint8_t> mark((size_t)(P * S));
  for (int64_t p = 0; p < P; p++) {
    memset(mark.data(), 0, mark.size());
    const int64_t* src = edge_src + p * E;
    int64_t own_lo = p * S, own_hi = own_lo + S;
    for (int64_t e = 0; e < E; e++) {
      int64_t s = src[e];
      if (s < own_lo || s >= own_hi) mark[(size_t)s] = 1;
    }
    int64_t* row = sizes_out + p * P;
    for (int64_t q = 0; q < P; q++) {
      const uint8_t* b = mark.data() + q * S;
      int64_t cnt = 0;
      for (int64_t i = 0; i < S; i++) cnt += b[i];
      row[q] = cnt;
    }
  }
  return 0;
}

// send_idx_out: [P*P*K] int32 ((q, p, k) layout), fully written (pad S-1).
// edge_src_local_out: [P*E] int32 into [0, S + P*K).
int roc_halo_fill(const int64_t* edge_src, int64_t P, int64_t E, int64_t S,
                  int64_t K, int32_t* send_idx_out,
                  int32_t* edge_src_local_out) {
  for (int64_t i = 0; i < P * P * K; i++)
    send_idx_out[i] = (int32_t)(S - 1);
  std::vector<uint8_t> mark((size_t)(P * S));
  std::vector<int32_t> lut((size_t)(P * S));  // padded id -> combined index
  for (int64_t p = 0; p < P; p++) {
    memset(mark.data(), 0, mark.size());
    const int64_t* src = edge_src + p * E;
    int64_t own_lo = p * S, own_hi = own_lo + S;
    for (int64_t e = 0; e < E; e++) {
      int64_t s = src[e];
      if (s < own_lo || s >= own_hi) mark[(size_t)s] = 1;
    }
    for (int64_t q = 0; q < P; q++) {
      if (q == p) continue;
      const uint8_t* b = mark.data() + q * S;
      int32_t* send_row = send_idx_out + (q * P + p) * K;
      int64_t pos = 0;
      for (int64_t i = 0; i < S; i++) {
        if (b[i]) {
          if (pos >= K) return -1;  // K too small
          send_row[pos] = (int32_t)i;
          lut[(size_t)(q * S + i)] = (int32_t)(S + q * K + pos);
          pos++;
        }
      }
    }
    int32_t* out = edge_src_local_out + p * E;
    for (int64_t e = 0; e < E; e++) {
      int64_t s = src[e];
      out[e] = (s >= own_lo && s < own_hi) ? (int32_t)(s - own_lo)
                                           : lut[(size_t)s];
    }
  }
  return 0;
}

// In-degree computation from inclusive end offsets (device CSR build prep;
// the reference does this on-GPU in init_graph_kernel, load_task.cu:271-294
// — on TPU the degree vector is a host-side precompute).

// ---------------------------------------------------------------------------
// Binned two-phase aggregation plan (roc_tpu/ops/pallas/binned.py fast path).
// Same two-call protocol as the chunk planner: sizes first (G/C1/C2/bpg),
// then fill.  No comparison sorts: one counting pass buckets edges by bin
// group, a second counting pass orders each group's edges by (source block,
// local bin) — O(E) end to end, which matters because the NumPy lexsort
// build costs ~17 s per direction at Reddit scale.
// Geometry constants mirror binned.py; roc_binned_geometry exports them so
// Python can assert agreement before trusting a native plan.
// ---------------------------------------------------------------------------

static const int64_t BN_SB = 512, BN_CH = 2048, BN_SLOT = 128;
static const int64_t BN_RB = 512, BN_CH2 = 4096;
static const int64_t BN_K2_CAP = (int64_t)1 << 25;   // binned.py _K2_CAP

// Runtime geometry (round 4): the builder takes (sb, ch, slot, rb, ch2) as
// arguments so the sparse-graph presets (binned.py GEOM_MID/GEOM_SPARSE)
// get the O(E) native build too.  The BN_* constants above remain the
// default exported by roc_binned_geometry (compat with older callers).
struct BnGeo {
  int64_t sb, ch, slot, rb, ch2, nslot, slot2;
};

static int bn_geo_from(const int64_t* geo5, BnGeo* g) {
  g->sb = geo5[0]; g->ch = geo5[1]; g->slot = geo5[2];
  g->rb = geo5[3]; g->ch2 = geo5[4];
  if (g->sb < 1 || g->rb < 1 || g->slot < 1) return -1;
  // ch/ch2 below slot would make nslot/slot2 zero and the chunk-count
  // divisions SIGFPE — reject instead
  if (g->ch < g->slot || g->ch % g->slot) return -1;
  if (g->ch2 < g->slot || g->ch2 % g->slot) return -1;
  g->nslot = g->ch / g->slot;
  g->slot2 = g->ch2 / g->slot;
  return 0;
}

static const int64_t BN_DEFAULT5[5] = {BN_SB, BN_CH, BN_SLOT, BN_RB, BN_CH2};

void roc_binned_geometry(int64_t* out5) {
  for (int i = 0; i < 5; i++) out5[i] = BN_DEFAULT5[i];
}

static void bn_params(const BnGeo& geo, int64_t E, int64_t num_rows,
                      int64_t table_rows, int64_t group_row_target,
                      int64_t* num_bins, int64_t* num_blocks, int64_t* bpg,
                      int64_t* G) {
  *num_bins = (num_rows + geo.rb - 1) / geo.rb;
  if (*num_bins < 1) *num_bins = 1;
  *num_blocks = (table_rows + geo.sb - 1) / geo.sb;
  if (*num_blocks < 1) *num_blocks = 1;
  double per_bin = (double)E / (double)*num_bins;
  if (per_bin < 1.0) per_bin = 1.0;
  int64_t b = (int64_t)((double)group_row_target / per_bin);
  if (b > *num_bins) b = *num_bins;
  if (b > BN_K2_CAP / *num_blocks) b = BN_K2_CAP / *num_blocks;
  if (b < 1) b = 1;
  *bpg = b;
  *G = (*num_bins + b - 1) / b;
}

// Shared walk: buckets edges, computes per-group geometry, and (when fill
// buffers are non-null) writes every plan array.  Returns 0, or -1 when the
// caller-passed C1/C2 disagree with the recomputed geometry.
static int bn_build(const BnGeo& geo, const int64_t* src, const int64_t* dst, int64_t E,
                    int64_t num_rows, int64_t table_rows,
                    int64_t group_row_target,
                    int64_t* out_G, int64_t* out_C1, int64_t* out_C2,
                    int64_t* out_bpg,
                    int64_t C1, int64_t C2,
                    int32_t* p1_srcl, int32_t* p1_off, int32_t* p1_blk,
                    int32_t* p2_dstl, int32_t* p2_obi, int32_t* p2_first) {
  int64_t num_bins, num_blocks, bpg, G;
  bn_params(geo, E, num_rows, table_rows, group_row_target,
            &num_bins, &num_blocks, &bpg, &G);
  const bool fill = p1_srcl != nullptr;
  const int64_t rows_pg = geo.rb * bpg;

  // Pass 0: bucket edge (src, dst) VALUES by group (stable).  Buckets hold
  // values, not edge ids — every later pass then reads sequentially
  // instead of chasing id indirections through the original arrays (the
  // difference between ~15 s and ~55 s at ogbn-products scale).
  std::vector<int64_t> gcnt(G + 1, 0);
  for (int64_t e = 0; e < E; e++) gcnt[dst[e] / rows_pg + 1]++;
  for (int64_t g = 0; g < G; g++) gcnt[g + 1] += gcnt[g];
  std::vector<int64_t> gsrc(E), gdst(E), gpos(gcnt.begin(), gcnt.end() - 1);
  for (int64_t e = 0; e < E; e++) {
    const int64_t p = gpos[dst[e] / rows_pg]++;
    gsrc[p] = src[e];
    gdst[p] = dst[e];
  }

  const int64_t K2 = num_blocks * bpg;
  std::vector<int64_t> ccnt(K2, 0), cbase(K2), pos(K2);
  std::vector<int64_t> blk_slots(num_blocks), blk_cbase(num_blocks);
  std::vector<int64_t> bin_slots(bpg), bin_cbase(bpg), bin_off(bpg);
  std::vector<int64_t> csrc, cdst;
  if (fill) { csrc.resize(E); cdst.resize(E); }
  int64_t maxC1 = 1, maxC2 = 1;

  for (int64_t g = 0; g < G; g++) {
    const int64_t lo = gcnt[g], hi = gcnt[g + 1];
    // Reset only the cells the previous group touched (ccnt starts zeroed;
    // a dense std::fill over K2 per group would dominate on sparse graphs).
    if (g > 0) {
      const int64_t plo = gcnt[g - 1], phi = gcnt[g];
      for (int64_t i = plo; i < phi; i++)
        ccnt[(gsrc[i] / geo.sb) * bpg
             + (gdst[i] / geo.rb - (g - 1) * bpg)] = 0;
    }
    for (int64_t i = lo; i < hi; i++)
      ccnt[(gsrc[i] / geo.sb) * bpg + (gdst[i] / geo.rb - g * bpg)]++;
    // Geometry: per-block and per-bin slot totals -> chunk bases.
    std::fill(blk_slots.begin(), blk_slots.end(), 0);
    std::fill(bin_slots.begin(), bin_slots.end(), 0);
    for (int64_t k = 0; k < K2; k++) {
      if (!ccnt[k]) continue;
      const int64_t slots = (ccnt[k] + geo.slot - 1) / geo.slot;
      blk_slots[k / bpg] += slots;
      bin_slots[k % bpg] += slots;
    }
    int64_t c1 = 0, c2 = 0;
    for (int64_t b = 0; b < num_blocks; b++) {
      blk_cbase[b] = c1;
      c1 += (blk_slots[b] + geo.nslot - 1) / geo.nslot;
    }
    for (int64_t b = 0; b < bpg; b++) {
      bin_cbase[b] = c2;
      int64_t ch = (bin_slots[b] + geo.slot2 - 1) / geo.slot2;
      c2 += ch < 1 ? 1 : ch;
    }
    if (c1 > maxC1) maxC1 = c1;
    if (c2 > maxC2) maxC2 = c2;
    if (!fill) continue;
    if (c1 > C1 || c2 > C2) return -1;

    // Cell-order the group's edges (stable counting sort by k2).
    cbase[0] = 0;
    for (int64_t k = 1; k < K2; k++) cbase[k] = cbase[k - 1] + ccnt[k - 1];
    std::copy(cbase.begin(), cbase.end(), pos.begin());
    for (int64_t i = lo; i < hi; i++) {
      const int64_t p = lo + pos[(gsrc[i] / geo.sb) * bpg
                                 + (gdst[i] / geo.rb - g * bpg)]++;
      csrc[p] = gsrc[i];
      cdst[p] = gdst[i];
    }
    // Fill: walk cells in (blk, lbin) order.
    int32_t* srcl = p1_srcl + g * C1 * geo.ch;
    int32_t* offp = p1_off + g * C1 * geo.nslot;
    int32_t* blkp = p1_blk + g * C1;
    int32_t* dstl = p2_dstl + g * C2 * geo.ch2;
    std::fill(bin_off.begin(), bin_off.end(), 0);
    int64_t blk_slot_run = 0, cur_blk = -1;
    for (int64_t k = 0; k < K2; k++) {
      const int64_t cnt = ccnt[k];
      if (!cnt) continue;
      const int64_t blk = k / bpg, lbin = k % bpg;
      if (blk != cur_blk) { cur_blk = blk; blk_slot_run = 0; }
      const int64_t slots = (cnt + geo.slot - 1) / geo.slot;
      const int64_t stg_slot = bin_cbase[lbin] * geo.slot2 + bin_off[lbin];
      const int64_t p1_slot = blk_cbase[blk] * geo.nslot + blk_slot_run;
      for (int64_t kk = 0; kk < slots; kk++)
        offp[p1_slot + kk] = (int32_t)(stg_slot + kk);
      const int64_t p1_row = p1_slot * geo.slot;
      const int64_t stg_row = stg_slot * geo.slot;
      const int64_t cello = lo + cbase[k];
      for (int64_t r = 0; r < cnt; r++) {
        srcl[p1_row + r] = (int32_t)(csrc[cello + r] - blk * geo.sb);
        dstl[stg_row + r] = (int32_t)(cdst[cello + r]
                                      - (g * bpg + lbin) * geo.rb);
      }
      bin_off[lbin] += slots;
      blk_slot_run += slots;
    }
    for (int64_t b = 0; b < num_blocks; b++) {
      const int64_t n = (blk_slots[b] + geo.nslot - 1) / geo.nslot;
      for (int64_t j = 0; j < n; j++) blkp[blk_cbase[b] + j] = (int32_t)b;
    }
    int32_t* obi = p2_obi + g * C2;
    int32_t* first = p2_first + g * C2;
    int64_t c = 0;
    for (int64_t b = 0; b < bpg; b++) {
      int64_t ch = (bin_slots[b] + geo.slot2 - 1) / geo.slot2;
      if (ch < 1) ch = 1;
      for (int64_t j = 0; j < ch; j++, c++) {
        obi[c] = (int32_t)b;
        first[c] = j == 0;
      }
    }
    for (; c < C2; c++) { obi[c] = (int32_t)(bpg - 1); first[c] = 0; }
  }
  *out_G = G;
  *out_C1 = (maxC1 + 7) / 8 * 8;
  *out_C2 = maxC2;
  *out_bpg = bpg;
  return 0;
}

// Geometry-parametric entry points (round 4): geo5 = (sb, ch, slot, rb,
// ch2).  Returns -2 on invalid geometry.
int roc_binned_plan_sizes_g(const int64_t* geo5, const int64_t* src,
                            const int64_t* dst, int64_t E, int64_t num_rows,
                            int64_t table_rows, int64_t group_row_target,
                            int64_t* out4) {
  BnGeo geo;
  if (bn_geo_from(geo5, &geo) != 0) return -2;
  return bn_build(geo, src, dst, E, num_rows, table_rows, group_row_target,
                  &out4[0], &out4[1], &out4[2], &out4[3],
                  0, 0, nullptr, nullptr, nullptr, nullptr, nullptr,
                  nullptr);
}

// Caller allocates: p1_srcl [G*C1*CH], p1_off [G*C1*NSLOT] (pre-filled by
// this call: unused slots get -1), p1_blk [G*C1], p2_dstl [G*C2*CH2],
// p2_obi [G*C2], p2_first [G*C2].  Returns 0, -1 on geometry mismatch,
// -2 on invalid geometry.
int roc_binned_plan_fill_g(const int64_t* geo5, const int64_t* src,
                           const int64_t* dst, int64_t E, int64_t num_rows,
                           int64_t table_rows, int64_t group_row_target,
                           int64_t G, int64_t C1, int64_t C2,
                           int32_t* p1_srcl, int32_t* p1_off,
                           int32_t* p1_blk, int32_t* p2_dstl,
                           int32_t* p2_obi, int32_t* p2_first) {
  BnGeo geo;
  if (bn_geo_from(geo5, &geo) != 0) return -2;
  std::fill(p1_srcl, p1_srcl + G * C1 * geo.ch, 0);
  std::fill(p1_off, p1_off + G * C1 * geo.nslot, -1);
  std::fill(p1_blk, p1_blk + G * C1, 0);
  std::fill(p2_dstl, p2_dstl + G * C2 * geo.ch2, (int32_t)geo.rb);
  std::fill(p2_obi, p2_obi + G * C2, 0);
  std::fill(p2_first, p2_first + G * C2, 0);
  int64_t g2, c1, c2, bpg;
  int rc = bn_build(geo, src, dst, E, num_rows, table_rows,
                    group_row_target, &g2, &c1, &c2, &bpg, C1, C2, p1_srcl,
                    p1_off, p1_blk, p2_dstl, p2_obi, p2_first);
  if (rc != 0 || g2 != G || c1 > C1 || c2 > C2) return -1;
  return 0;
}

int roc_binned_plan_sizes(const int64_t* src, const int64_t* dst, int64_t E,
                          int64_t num_rows, int64_t table_rows,
                          int64_t group_row_target, int64_t* out4) {
  return roc_binned_plan_sizes_g(BN_DEFAULT5, src, dst, E, num_rows,
                                 table_rows, group_row_target, out4);
}

int roc_binned_plan_fill(const int64_t* src, const int64_t* dst, int64_t E,
                         int64_t num_rows, int64_t table_rows,
                         int64_t group_row_target, int64_t G, int64_t C1,
                         int64_t C2, int32_t* p1_srcl, int32_t* p1_off,
                         int32_t* p1_blk, int32_t* p2_dstl, int32_t* p2_obi,
                         int32_t* p2_first) {
  return roc_binned_plan_fill_g(BN_DEFAULT5, src, dst, E, num_rows,
                                table_rows, group_row_target, G, C1, C2,
                                p1_srcl, p1_off, p1_blk, p2_dstl, p2_obi,
                                p2_first);
}

// ---------------------------------------------------------------------------
// Flat-schedule binned plan (binned.py _build_flat_plan_numpy mirror).
// Cells pad to unit-row units (BN_UNIT=8 for fp32 staging; 16 for the
// bf16 tile-aligned variant, geo6[5]); each group's per-block unit streams
// pack back-to-back into CH-row chunks (a chunk may span at most TWO
// blocks — early cut when a third would enter a partly-filled chunk); the
// slot-offset table becomes per-chunk run lists of size-classed staging
// copies (16/4/1 units), KD = CH/unit entries max per chunk.  Phase 2
// keeps the slot builder's layout with units instead of slots.  Must stay
// element-identical to the NumPy builder (test_native_flat_plan_equals_numpy).
// ---------------------------------------------------------------------------

static const int64_t BN_UNIT = 8;                      // binned.py _UNIT
static const int64_t BN_DMA_CLS[3] = {16, 4, 1};       // binned.py _DMA_CLS

struct BnFlatGeo {
  int64_t sb, ch, rb, ch2, unit, uc, u2, kd;
};

static int bn_flat_geo_units(BnFlatGeo* g, int64_t unit) {
  if (unit != 8 && unit != 16) return -1;
  g->unit = unit;
  if (g->sb < 1 || g->rb < 1) return -1;
  if (g->ch < unit || g->ch % unit) return -1;
  if (g->ch2 < unit || g->ch2 % unit) return -1;
  g->uc = g->ch / unit;
  g->u2 = g->ch2 / unit;
  g->kd = g->ch / unit;
  return 0;
}

static int bn_flat_geo_from(const int64_t* geo5, BnFlatGeo* g) {
  g->sb = geo5[0]; g->ch = geo5[1]; g->rb = geo5[3]; g->ch2 = geo5[4];
  return bn_flat_geo_units(g, BN_UNIT);
}

// geo6 = (sb, ch, slot, rb, ch2, unit); unit 0 means the BN_UNIT default.
static int bn_flat_geo_from6(const int64_t* geo6, BnFlatGeo* g) {
  g->sb = geo6[0]; g->ch = geo6[1]; g->rb = geo6[3]; g->ch2 = geo6[4];
  return bn_flat_geo_units(g, geo6[5] ? geo6[5] : BN_UNIT);
}

static int bn_flat_build(const BnFlatGeo& geo, const int64_t* src,
                         const int64_t* dst, int64_t E, int64_t num_rows,
                         int64_t table_rows, int64_t group_row_target,
                         int64_t* out_G, int64_t* out_C1, int64_t* out_C2,
                         int64_t* out_bpg, int64_t C1, int64_t C2,
                         int32_t* p1_srcl, int32_t* p1_blk,
                         int32_t* p1_blk2, int32_t* p1_dsrc,
                         int32_t* p1_ddst, int32_t* p2_dstl,
                         int32_t* p2_obi, int32_t* p2_first) {
  const int64_t U = geo.unit;
  BnGeo pgeo;  // bn_params only reads sb/rb
  pgeo.sb = geo.sb; pgeo.rb = geo.rb;
  int64_t num_bins, num_blocks, bpg, G;
  bn_params(pgeo, E, num_rows, table_rows, group_row_target,
            &num_bins, &num_blocks, &bpg, &G);
  const bool fill = p1_srcl != nullptr;
  const int64_t rows_pg = geo.rb * bpg;

  // Pass 0: bucket edge values by group (same as the slot builder).
  std::vector<int64_t> gcnt(G + 1, 0);
  for (int64_t e = 0; e < E; e++) gcnt[dst[e] / rows_pg + 1]++;
  for (int64_t g = 0; g < G; g++) gcnt[g + 1] += gcnt[g];
  std::vector<int64_t> gsrc(E), gdst(E), gpos(gcnt.begin(), gcnt.end() - 1);
  for (int64_t e = 0; e < E; e++) {
    const int64_t p = gpos[dst[e] / rows_pg]++;
    gsrc[p] = src[e];
    gdst[p] = dst[e];
  }

  const int64_t K2 = num_blocks * bpg;
  std::vector<int64_t> ccnt(K2, 0), cbase(K2), pos(K2);
  std::vector<int64_t> bin_units(bpg), bin_cbase(bpg), bin_offu(bpg);
  std::vector<int64_t> csrc, cdst;
  if (fill) { csrc.resize(E); cdst.resize(E); }
  int64_t maxC1 = 1, maxC2 = 1;

  for (int64_t g = 0; g < G; g++) {
    const int64_t lo = gcnt[g], hi = gcnt[g + 1];
    if (g > 0) {
      const int64_t plo = gcnt[g - 1], phi = gcnt[g];
      for (int64_t i = plo; i < phi; i++)
        ccnt[(gsrc[i] / geo.sb) * bpg
             + (gdst[i] / geo.rb - (g - 1) * bpg)] = 0;
    }
    for (int64_t i = lo; i < hi; i++)
      ccnt[(gsrc[i] / geo.sb) * bpg + (gdst[i] / geo.rb - g * bpg)]++;

    // Phase-2 geometry: per-bin unit totals -> CH2-aligned chunk bases
    // (empty bins still cost one chunk, mirroring the slot builder).
    std::fill(bin_units.begin(), bin_units.end(), 0);
    for (int64_t k = 0; k < K2; k++)
      if (ccnt[k]) bin_units[k % bpg] += (ccnt[k] + U - 1) / U;
    int64_t c2 = 0;
    for (int64_t b = 0; b < bpg; b++) {
      bin_cbase[b] = c2;
      int64_t ch = (bin_units[b] + geo.u2 - 1) / geo.u2;
      c2 += ch < 1 ? 1 : ch;
    }
    if (c2 > maxC2) maxC2 = c2;

    // Phase-1 flat pack (unit-level replay of binned.py _flat_pack):
    // walk cells in (blk, lbin) order; a blk change starts a new stream.
    int64_t chunk = 0, fillu = 0, nblk = 0, cur_blk = -1;
    bool newspan = false;
    // run state (staging-copy run list; only used when filling)
    int64_t run_chunk = -1, run_pos0 = 0, run_stg0 = 0, run_len = 0;
    int64_t prev_stg = -2, ecur_chunk = -1, ecount = 0;
    int32_t* srcl = fill ? p1_srcl + g * C1 * geo.ch : nullptr;
    int32_t* blkp = fill ? p1_blk + g * C1 : nullptr;
    int32_t* blk2p = fill ? p1_blk2 + g * C1 : nullptr;
    int32_t* dsrcp = fill ? p1_dsrc + g * C1 * geo.kd : nullptr;
    int32_t* ddstp = fill ? p1_ddst + g * C1 * geo.kd : nullptr;
    int32_t* dstl = fill ? p2_dstl + g * C2 * geo.ch2 : nullptr;
    bool overflow = false;
    auto flush_run = [&]() {
      if (run_len <= 0) return;
      if (run_chunk != ecur_chunk) { ecur_chunk = run_chunk; ecount = 0; }
      int64_t off = 0;
      for (int ci = 0; ci < 3; ci++) {
        const int64_t csz = BN_DMA_CLS[ci];
        while (run_len - off >= csz) {
          if (ecount >= geo.kd) { overflow = true; return; }
          dsrcp[run_chunk * geo.kd + ecount] =
              (int32_t)(ci * 65536 + run_pos0 + off);
          ddstp[run_chunk * geo.kd + ecount] =
              (int32_t)(run_stg0 + off);
          ecount++;
          off += csz;
        }
      }
      run_len = 0;
    };

    if (fill) {
      if (c2 > C2) return -1;
      // Cell-order the group's edges (stable counting sort by k2).
      cbase[0] = 0;
      for (int64_t k = 1; k < K2; k++) cbase[k] = cbase[k - 1] + ccnt[k - 1];
      std::copy(cbase.begin(), cbase.end(), pos.begin());
      for (int64_t i = lo; i < hi; i++) {
        const int64_t p = lo + pos[(gsrc[i] / geo.sb) * bpg
                                   + (gdst[i] / geo.rb - g * bpg)]++;
        csrc[p] = gsrc[i];
        cdst[p] = gdst[i];
      }
    }
    std::fill(bin_offu.begin(), bin_offu.end(), 0);
    for (int64_t k = 0; k < K2; k++) {
      const int64_t cnt = ccnt[k];
      if (!cnt) continue;
      const int64_t blk = k / bpg, lbin = k % bpg;
      const int64_t units = (cnt + U - 1) / U;
      if (blk != cur_blk) {                       // stream start
        cur_blk = blk;
        if (nblk >= 2 && fillu > 0) { chunk++; fillu = 0; nblk = 0; }
        newspan = true;
      }
      const int64_t stg_unit0 = bin_cbase[lbin] * geo.u2 + bin_offu[lbin];
      const int64_t cello = fill ? lo + cbase[k] : 0;
      for (int64_t j = 0; j < units; j++) {
        if (fillu == geo.uc) { chunk++; fillu = 0; nblk = 0; newspan = true; }
        if (newspan) {
          nblk++;
          newspan = false;
          if (fill && chunk < C1) {
            if (fillu == 0) {                     // open span: primary blk
              blkp[chunk] = (int32_t)blk;
              blk2p[chunk] = (int32_t)blk;
            } else {                              // tail span: secondary
              blk2p[chunk] = (int32_t)blk;
            }
          }
        }
        if (fill) {
          if (chunk >= C1) return -1;
          const int64_t stg = stg_unit0 + j;
          if (chunk != run_chunk || stg != prev_stg + 1) {
            flush_run();
            if (overflow) return -3;
            run_chunk = chunk;
            run_pos0 = fillu;
            run_stg0 = stg;
          }
          run_len++;
          prev_stg = stg;
          const int64_t r0 = j * U;
          const int64_t r1 = r0 + U < cnt ? r0 + U : cnt;
          const int64_t row = chunk * geo.ch + fillu * U;
          const int64_t sec = blkp[chunk] != (int32_t)blk ? geo.sb : 0;
          for (int64_t r = r0; r < r1; r++)
            srcl[row + (r - r0)] =
                (int32_t)(csrc[cello + r] - blk * geo.sb + sec);
        }
        fillu++;
      }
      if (fill) {
        const int64_t stg_row = stg_unit0 * U;
        const int64_t boff = (g * bpg + lbin) * geo.rb;
        for (int64_t r = 0; r < cnt; r++)
          dstl[stg_row + r] = (int32_t)(cdst[cello + r] - boff);
      }
      bin_offu[lbin] += units;
    }
    if (fill) {
      flush_run();
      if (overflow) return -3;
    }
    const int64_t c1 = chunk + (fillu > 0 ? 1 : 0);
    if (c1 > maxC1) maxC1 = c1;
    if (fill && c1 > C1) return -1;

    if (fill) {
      int32_t* obi = p2_obi + g * C2;
      int32_t* first = p2_first + g * C2;
      int64_t c = 0;
      for (int64_t b = 0; b < bpg; b++) {
        int64_t ch = (bin_units[b] + geo.u2 - 1) / geo.u2;
        if (ch < 1) ch = 1;
        for (int64_t j = 0; j < ch; j++, c++) {
          obi[c] = (int32_t)b;
          first[c] = j == 0;
        }
      }
      for (; c < C2; c++) { obi[c] = (int32_t)(bpg - 1); first[c] = 0; }
    }
  }
  *out_G = G;
  *out_C1 = (maxC1 + 7) / 8 * 8;
  *out_C2 = maxC2;
  *out_bpg = bpg;
  return 0;
}

static int bn_flat_sizes_impl(const BnFlatGeo& geo, const int64_t* src,
                              const int64_t* dst, int64_t E,
                              int64_t num_rows, int64_t table_rows,
                              int64_t group_row_target, int64_t* out4) {
  return bn_flat_build(geo, src, dst, E, num_rows, table_rows,
                       group_row_target, &out4[0], &out4[1], &out4[2],
                       &out4[3], 0, 0, nullptr, nullptr, nullptr, nullptr,
                       nullptr, nullptr, nullptr, nullptr);
}

static int bn_flat_fill_impl(const BnFlatGeo& geo, const int64_t* src,
                             const int64_t* dst, int64_t E,
                             int64_t num_rows, int64_t table_rows,
                             int64_t group_row_target, int64_t G,
                             int64_t C1, int64_t C2, int32_t* p1_srcl,
                             int32_t* p1_blk, int32_t* p1_blk2,
                             int32_t* p1_dsrc, int32_t* p1_ddst,
                             int32_t* p2_dstl, int32_t* p2_obi,
                             int32_t* p2_first) {
  std::fill(p1_srcl, p1_srcl + G * C1 * geo.ch, -1);
  std::fill(p1_blk, p1_blk + G * C1, 0);
  std::fill(p1_blk2, p1_blk2 + G * C1, 0);
  std::fill(p1_dsrc, p1_dsrc + G * C1 * geo.kd, -1);
  std::fill(p1_ddst, p1_ddst + G * C1 * geo.kd, -1);
  std::fill(p2_dstl, p2_dstl + G * C2 * geo.ch2, (int32_t)geo.rb);
  std::fill(p2_obi, p2_obi + G * C2, 0);
  std::fill(p2_first, p2_first + G * C2, 0);
  int64_t g2, c1, c2, bpg;
  int rc = bn_flat_build(geo, src, dst, E, num_rows, table_rows,
                         group_row_target, &g2, &c1, &c2, &bpg, C1, C2,
                         p1_srcl, p1_blk, p1_blk2, p1_dsrc, p1_ddst,
                         p2_dstl, p2_obi, p2_first);
  if (rc != 0) return rc;
  if (g2 != G || c1 > C1 || c2 > C2) return -1;
  return 0;
}

int roc_binned_flat_plan_sizes_g(const int64_t* geo5, const int64_t* src,
                                 const int64_t* dst, int64_t E,
                                 int64_t num_rows, int64_t table_rows,
                                 int64_t group_row_target, int64_t* out4) {
  BnFlatGeo geo;
  if (bn_flat_geo_from(geo5, &geo) != 0) return -2;
  return bn_flat_sizes_impl(geo, src, dst, E, num_rows, table_rows,
                            group_row_target, out4);
}

// geo6 variant: geo6[5] is the unit-row count (0/8 = fp32 staging,
// 16 = the bf16 tile-aligned unit).
int roc_binned_flat_plan_sizes_g2(const int64_t* geo6, const int64_t* src,
                                  const int64_t* dst, int64_t E,
                                  int64_t num_rows, int64_t table_rows,
                                  int64_t group_row_target, int64_t* out4) {
  BnFlatGeo geo;
  if (bn_flat_geo_from6(geo6, &geo) != 0) return -2;
  return bn_flat_sizes_impl(geo, src, dst, E, num_rows, table_rows,
                            group_row_target, out4);
}

// Caller allocates: p1_srcl [G*C1*CH], p1_blk [G*C1], p1_blk2 [G*C1],
// p1_dsrc [G*C1*KD], p1_ddst [G*C1*KD] (KD = CH/unit), p2_dstl [G*C2*CH2],
// p2_obi [G*C2], p2_first [G*C2].  This call pre-fills the pad values
// (srcl/dsrc/ddst -1, blk/blk2 0, dstl RB).  Returns 0, -1 on geometry
// mismatch, -2 on invalid geometry, -3 on run-list overflow.
int roc_binned_flat_plan_fill_g(const int64_t* geo5, const int64_t* src,
                                const int64_t* dst, int64_t E,
                                int64_t num_rows, int64_t table_rows,
                                int64_t group_row_target, int64_t G,
                                int64_t C1, int64_t C2, int32_t* p1_srcl,
                                int32_t* p1_blk, int32_t* p1_blk2,
                                int32_t* p1_dsrc, int32_t* p1_ddst,
                                int32_t* p2_dstl, int32_t* p2_obi,
                                int32_t* p2_first) {
  BnFlatGeo geo;
  if (bn_flat_geo_from(geo5, &geo) != 0) return -2;
  return bn_flat_fill_impl(geo, src, dst, E, num_rows, table_rows,
                           group_row_target, G, C1, C2, p1_srcl, p1_blk,
                           p1_blk2, p1_dsrc, p1_ddst, p2_dstl, p2_obi,
                           p2_first);
}

int roc_binned_flat_plan_fill_g2(const int64_t* geo6, const int64_t* src,
                                 const int64_t* dst, int64_t E,
                                 int64_t num_rows, int64_t table_rows,
                                 int64_t group_row_target, int64_t G,
                                 int64_t C1, int64_t C2, int32_t* p1_srcl,
                                 int32_t* p1_blk, int32_t* p1_blk2,
                                 int32_t* p1_dsrc, int32_t* p1_ddst,
                                 int32_t* p2_dstl, int32_t* p2_obi,
                                 int32_t* p2_first) {
  BnFlatGeo geo;
  if (bn_flat_geo_from6(geo6, &geo) != 0) return -2;
  return bn_flat_fill_impl(geo, src, dst, E, num_rows, table_rows,
                           group_row_target, G, C1, C2, p1_srcl, p1_blk,
                           p1_blk2, p1_dsrc, p1_ddst, p2_dstl, p2_obi,
                           p2_first);
}

void roc_in_degrees(const uint64_t* raw_rows, uint64_t num_nodes,
                    float* deg_out) {
  for (uint64_t v = 0; v < num_nodes; v++)
    deg_out[v] = (float)(raw_rows[v] - (v ? raw_rows[v - 1] : 0));
}

// ---------------------------------------------------------------------------
// CSR transpose (graph/csr.py Csr.transpose fast path): stable counting
// sort by source — O(E) where the NumPy argsort path is O(E log E)
// (~30-60 s at ogbn-products scale, on the reorder and .t.lux-sidecar
// preprocessing paths).  Stability matters: the transposed cols must be
// the dst ids in original edge order within each source, element-equal
// to the NumPy oracle.
// row_ptr [N+1] int64 exclusive prefix; col_idx [E] int32 sources;
// outputs t_row_ptr [N+1], t_col_idx [E].  Returns 0.
// ---------------------------------------------------------------------------

int roc_csr_transpose(const int64_t* row_ptr, const int32_t* col_idx,
                      int64_t N, int64_t E, int64_t* t_row_ptr,
                      int32_t* t_col_idx) {
  std::fill(t_row_ptr, t_row_ptr + N + 1, 0);
  for (int64_t e = 0; e < E; e++) t_row_ptr[col_idx[e] + 1]++;
  for (int64_t v = 0; v < N; v++) t_row_ptr[v + 1] += t_row_ptr[v];
  std::vector<int64_t> pos(t_row_ptr, t_row_ptr + N);
  for (int64_t v = 0; v < N; v++) {        // dst of edge e = row owner v
    for (int64_t e = row_ptr[v]; e < row_ptr[v + 1]; e++)
      t_col_idx[pos[col_idx[e]]++] = (int32_t)v;
  }
  return 0;
}

// ---------------------------------------------------------------------------
// RCM locality order (graph/reorder.py fast path): level-synchronous BFS
// from minimum-degree seeds, each level sorted by (degree, id), isolated
// vertices appended, whole order reversed.  Semantics match the NumPy
// oracle element for element (tests/test_reorder.py parity test) — the
// (deg, id) total order is unique, so both implementations agree exactly.
// O(E + N log N); at ogbn-products scale the NumPy pass costs minutes,
// this runs in seconds.
// Inputs: in-edge CSR (row_ptr [N+1], col_idx [E]) and its transpose.
// Output: order_out [N] with order[new_id] = old_id.  Returns 0.
// ---------------------------------------------------------------------------

int roc_rcm_order(const int64_t* row_ptr, const int32_t* col_idx,
                  const int64_t* t_row_ptr, const int32_t* t_col_idx,
                  int64_t N, int64_t* order_out) {
  if (N == 0) return 0;
  std::vector<int64_t> deg(N), self_cnt(N, 0);
  for (int64_t v = 0; v < N; v++) {
    deg[v] = (row_ptr[v + 1] - row_ptr[v]) +
             (t_row_ptr[v + 1] - t_row_ptr[v]);
    for (int64_t e = row_ptr[v]; e < row_ptr[v + 1]; e++)
      if (col_idx[e] == v) self_cnt[v]++;
  }
  std::vector<char> visited(N, 0);
  std::vector<int64_t> order;
  order.reserve(N);
  std::vector<int64_t> isolated;
  for (int64_t v = 0; v < N; v++)
    if (deg[v] - 2 * self_cnt[v] == 0) {
      visited[v] = 1;
      isolated.push_back(v);
    }
  // seed scan in (deg, id) order — a stable sort of ids by degree
  std::vector<int64_t> seeds(N);
  for (int64_t v = 0; v < N; v++) seeds[v] = v;
  std::stable_sort(seeds.begin(), seeds.end(),
                   [&](int64_t a, int64_t b) { return deg[a] < deg[b]; });
  std::vector<int64_t> frontier, next;
  size_t seed_pos = 0;
  while (true) {
    while (seed_pos < (size_t)N && visited[seeds[seed_pos]]) seed_pos++;
    if (seed_pos >= (size_t)N) break;
    frontier.assign(1, seeds[seed_pos]);
    visited[seeds[seed_pos]] = 1;
    while (!frontier.empty()) {
      order.insert(order.end(), frontier.begin(), frontier.end());
      next.clear();
      for (int64_t u : frontier) {
        for (int64_t e = row_ptr[u]; e < row_ptr[u + 1]; e++) {
          int64_t w = col_idx[e];
          if (!visited[w]) { visited[w] = 1; next.push_back(w); }
        }
        for (int64_t e = t_row_ptr[u]; e < t_row_ptr[u + 1]; e++) {
          int64_t w = t_col_idx[e];
          if (!visited[w]) { visited[w] = 1; next.push_back(w); }
        }
      }
      // (deg, id): sort by id first (claim order above is arbitrary),
      // then stable by degree
      std::sort(next.begin(), next.end());
      std::stable_sort(next.begin(), next.end(), [&](int64_t a, int64_t b) {
        return deg[a] < deg[b];
      });
      frontier.swap(next);
    }
  }
  order.insert(order.end(), isolated.begin(), isolated.end());
  for (int64_t i = 0; i < N; i++) order_out[i] = order[N - 1 - i];
  return 0;
}

}  // extern "C"
