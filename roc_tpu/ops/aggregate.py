"""Sparse neighborhood aggregation (the reference's ScatterGather op).

Semantics (scattergather_kernel.cu:20-76): for every destination vertex v,
``out[v] = Σ_{e : dst(e)=v} x[src(e)]`` — a sum over in-edges.  The reference
runs a block-cooperative CUDA kernel with a CUB prefix-scan; on TPU the same
contraction has three backends: gather + sorted segment-sum (`xla`, the
oracle), scatter-free one-hot MXU matmuls over a host-built chunk plan
(`matmul`, fp32-exact), and the binned two-phase Pallas kernels
(`binned`, the hardware fast path — roc_tpu/ops/pallas/binned.py).

Backward needs no hand-written task pair (the reference reuses its forward
kernel on the transposed role, scattergather_kernel.cu:160-170): JAX
autodiff of gather+segment_sum *is* the transposed aggregation.

Aggregation variants (AggrType, gnn.h:77-81 — the reference enumerates
AVG/MAX/MIN/SUM but only wires SUM): all four are provided here.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np


def _vary_like(init, ref):
    """Promote a scan-carry init to ``ref``'s device-varying vma annotation
    WITHOUT a gradient edge: the `+ 0 * ref` spelling creates one, through
    which a non-finite cotangent transposes to 0 * inf = NaN and a
    non-finite ref element broadcasts NaN into the whole carry primal —
    the _ring_attend bug class (spmd.py pcast note).  Axis-agnostic
    (reads ref's vma), so it is a no-op outside shard_map."""
    import jax as _jax
    return _jax.lax.pcast(init, tuple(_jax.typeof(ref).vma), to="varying")


# Above this many elements in the gathered [E, H] intermediate, sum
# aggregation switches to an edge-chunked scan with in-place accumulation
# (bounded memory).  2^28 elems = 1 GiB fp32.
_CHUNK_THRESHOLD_ELEMS = 1 << 28
_CHUNK_TARGET_ELEMS = 1 << 25      # ~128 MiB fp32 per chunk


def _chunked_segment_sum(x, edge_src, edge_dst, num_nodes: int):
    """Memory-bounded sum aggregation: scan over edge chunks, scatter-adding
    into a donated accumulator.

    XLA materializes jnp.take's [E, H] result before segment_sum; at
    reference scale (reddit: 2.3e7 edges x 256 features x 4 B = 24 GB) that
    alone overflows a chip's HBM.  The reference never faces this because
    each GPU task only touches its partition's edge slice and stages rows
    through a fixed framebuffer cache (load_task.cu:365-374) — this scan is
    the single-chip analog: fixed [chunk, H] working set, out + one chunk
    in flight.  Pad edges route to an extra throwaway row (num_nodes).
    """
    E, H = edge_src.shape[0], x.shape[1]
    chunk = max(_CHUNK_TARGET_ELEMS // max(H, 1), 1024)
    nchunks = -(-E // chunk)
    pad = nchunks * chunk - E
    src = jnp.pad(edge_src, (0, pad))                      # row 0: harmless
    dst = jnp.pad(edge_dst, (0, pad), constant_values=num_nodes)
    # The scan carry must be device-varying like x under shard_map's vma
    # tracking; without the promotion the chunked path crashes the moment
    # a SHARD's E*H crosses the threshold — caught at products shape with
    # H=32, just past the bound the round-3 test grazed under.
    acc = _vary_like(jnp.zeros((num_nodes + 1, H), x.dtype), x)

    def body(acc, sl):
        s, d = sl
        return acc.at[d].add(jnp.take(x, s, axis=0),
                             indices_are_sorted=True,
                             mode="promise_in_bounds"), None
    acc, _ = jax.lax.scan(
        body, acc, (src.reshape(nchunks, chunk), dst.reshape(nchunks, chunk)))
    return acc[:num_nodes]


def scatter_gather(x, edge_src, edge_dst, num_nodes: int, aggr: str = "sum"):
    """out[v] = aggr over in-edges of x[src].

    Args:
      x: [N_table, H] source feature table (may be larger than num_nodes when
         it includes halo/remote rows).
      edge_src: [E] int indices into x.
      edge_dst: [E] int destination rows, sorted ascending (CSR order).
      num_nodes: number of output rows (static).
      aggr: one of sum/avg/max/min.
    """
    if (aggr == "sum"
            and edge_src.shape[0] * x.shape[1] > _CHUNK_THRESHOLD_ELEMS):
        return _chunked_segment_sum(x, edge_src, edge_dst, num_nodes)
    gathered = jnp.take(x, edge_src, axis=0)
    if aggr == "sum":
        return jax.ops.segment_sum(gathered, edge_dst, num_segments=num_nodes,
                                   indices_are_sorted=True)
    if aggr == "avg":
        s = jax.ops.segment_sum(gathered, edge_dst, num_segments=num_nodes,
                                indices_are_sorted=True)
        cnt = jax.ops.segment_sum(jnp.ones_like(edge_dst, dtype=x.dtype),
                                  edge_dst, num_segments=num_nodes,
                                  indices_are_sorted=True)
        return s / jnp.maximum(cnt, 1.0)[:, None]
    if aggr in ("max", "min"):
        seg = jax.ops.segment_max if aggr == "max" else jax.ops.segment_min
        out = seg(gathered, edge_dst, num_segments=num_nodes,
                  indices_are_sorted=True)
        # Empty neighborhoods fill with the segment identity (+-inf), which
        # NaN-poisons any later linear layer (inf * 0 weight).  Zero exactly
        # those — the zero-preserving convention the shard-padding machinery
        # relies on (graph/partition.py).  Matching the identity (not
        # isfinite) keeps genuine NaN blow-ups visible.
        empty = jnp.isneginf(out) if aggr == "max" else jnp.isposinf(out)
        return jnp.where(empty, 0, out)
    raise ValueError(f"unknown aggr {aggr!r}")


def divide_by_degree(out, in_degree):
    """avg from a sum aggregation: out / max(in_degree, 1), matching the
    xla oracle's count guard.  The single semantics shared by every avg
    call site (single-device plan path, sharded plan path, ring,
    edge-shard): in_degree is the live in-edge count per output row (pad
    rows carry 1 and their sums are zero, so they stay zero).

    The division runs in float32 regardless of out.dtype: a bf16 cast of
    the degree rounds counts above 256 (up to ~0.4% relative error in avg),
    so the degree stays exact and only the quotient is cast back."""
    deg = jnp.maximum(in_degree, 1.0).astype(jnp.float32)
    return (out.astype(jnp.float32) / deg[:, None]).astype(out.dtype)


# ---------------------------------------------------------------------------
# Chunk plans shared by the one-hot (matmul) backend.
# ---------------------------------------------------------------------------

class AggregatePlans(NamedTuple):
    """Fwd + transposed-bwd chunk schedules as jit-traceable arrays.

    Kept as a flat NamedTuple of int32 arrays so it rides inside the graph-
    data pytree passed to jitted steps (and can be stacked + sharded on a
    leading parts axis for shard_map)."""
    fwd_obi: jnp.ndarray    # [C_f]
    fwd_first: jnp.ndarray  # [C_f]
    fwd_edst: jnp.ndarray   # [C_f, EB]
    fwd_esrc: jnp.ndarray   # [C_f, EB]
    bwd_obi: jnp.ndarray    # [C_b]
    bwd_first: jnp.ndarray  # [C_b]
    bwd_edst: jnp.ndarray   # [C_b, EB]
    bwd_esrc: jnp.ndarray   # [C_b, EB]


def build_aggregate_plans(edge_src: np.ndarray, edge_dst: np.ndarray,
                          num_rows: int, table_rows: int) -> AggregatePlans:
    """Chunk schedules for out = A@x (fwd) and grad_x = A^T@grad (bwd).

    The transposed plan re-sorts the edge list by source — the exact move
    the reference makes by launching its forward kernel with input/output
    roles swapped (scattergather_kernel.cu:160-170)."""
    from roc_tpu.ops.pallas.segment_sum import build_chunk_plan
    fwd = build_chunk_plan(np.asarray(edge_src, np.int32),
                           np.asarray(edge_dst, np.int32), num_rows)
    order = np.argsort(edge_src, kind="stable")
    bwd = build_chunk_plan(np.asarray(edge_dst)[order].astype(np.int32),
                           np.asarray(edge_src)[order].astype(np.int32),
                           table_rows)
    # _one_hot_dots relies on consecutive obi increasing by at most 1 (every
    # window, even an empty one, gets >= 1 chunk) so that within a scan step
    # lw = ob - ob[0] < CB; a plan builder that skipped empty windows would
    # silently drop contributions there.  Pin the invariant here, where every
    # plan (python or native) passes through.
    for plan in (fwd, bwd):
        assert np.all(np.diff(np.asarray(plan.obi)) <= 1), \
            "chunk plan skips output windows (obi jump > 1)"
    return AggregatePlans(
        fwd_obi=jnp.asarray(fwd.obi), fwd_first=jnp.asarray(fwd.first),
        fwd_edst=jnp.asarray(fwd.edst), fwd_esrc=jnp.asarray(fwd.esrc),
        bwd_obi=jnp.asarray(bwd.obi), bwd_first=jnp.asarray(bwd.first),
        bwd_edst=jnp.asarray(bwd.edst), bwd_esrc=jnp.asarray(bwd.esrc))


def pad_plans(plans: "list[AggregatePlans]", min_fwd: int = 0,
              min_bwd: int = 0) -> AggregatePlans:
    """Stack per-shard plans to common chunk counts (shard_map needs one
    static program).  Pad chunks are the canonical no-ops of
    :func:`roc_tpu.ops.pallas.segment_sum.pad_chunks`.

    ``min_fwd``/``min_bwd`` raise the target chunk counts — the per-host
    loader passes the allgathered global maxima so every process compiles
    the same program even though each only sees its local parts' plans."""
    from roc_tpu.ops.pallas.segment_sum import pad_chunks

    def stack(prefix):
        quads = [(getattr(p, prefix + "obi"), getattr(p, prefix + "first"),
                  getattr(p, prefix + "edst"), getattr(p, prefix + "esrc"))
                 for p in plans]
        C = max(max(q[0].shape[0] for q in quads),
                min_fwd if prefix == "fwd_" else min_bwd)
        padded = [pad_chunks(*q, C - q[0].shape[0], jnp) for q in quads]
        return [jnp.stack([p[i] for p in padded]) for i in range(4)]

    f, b = stack("fwd_"), stack("bwd_")
    return AggregatePlans(fwd_obi=f[0], fwd_first=f[1], fwd_edst=f[2],
                          fwd_esrc=f[3], bwd_obi=b[0], bwd_first=b[1],
                          bwd_edst=b[2], bwd_esrc=b[3])


# ---------------------------------------------------------------------------
# Matmul backend (sum; avg = sum/in-degree at the call sites):
# scatter-free aggregation in pure XLA.
# ---------------------------------------------------------------------------
#
# TPU scatter is serialized per index (measured ~6.5 s for one Reddit-scale
# aggregation on v5e); the reference never pays this because its CUDA kernel
# scatter-adds through shared-memory atomics (scattergather_kernel.cu:20-76).
# The TPU-native answer is to turn the scatter into MXU matmuls against
# one-hot matrices, using the same host-built chunk schedule as the Pallas
# kernel: chunks of EB dst-sorted edges, each owning a VB-row output window.
# Per scan step (CB chunks):
#   G    = x[esrc]                          gather  [CB*EB, H]
#   psum = S1 @ G   (batched, S1 one-hot)   scatter within window  [CB, VB, H]
#   outs = S2 @ psum (S2 one-hot over chunks->windows)             [CB, VB, H]
#   acc[window range] += outs               dynamic-slice RMW (windows in a
#                                           step are contiguous: obi sorted)
# No scatter instruction anywhere; everything is gather + matmul + DUS.

_MM_CB = 512   # chunks per scan step


def _one_hot_dots(g, ed, ob, cb, precision):
    """S1/S2 one-hot matmuls for one scan step (see module comment)."""
    from roc_tpu.ops.pallas.segment_sum import EB, VB
    H = g.shape[-1]
    s1 = (jax.lax.broadcasted_iota(jnp.int32, (cb, VB, EB), 1)
          == ed[:, None, :]).astype(g.dtype)
    psum = jax.lax.dot_general(
        s1, g.reshape(cb, EB, H), (((2,), (1,)), ((0,), (0,))),
        precision=precision, preferred_element_type=jnp.float32)
    lw = ob - ob[0]                                   # [CB] in [0, CB)
    s2 = (jax.lax.broadcasted_iota(jnp.int32, (cb, cb), 0)
          == lw[None, :]).astype(g.dtype)
    outs = jax.lax.dot_general(
        s2, psum.reshape(cb, VB * H), (((1,), (0,)), ((), ())),
        precision=precision, preferred_element_type=jnp.float32)
    return outs.reshape(cb * VB, H)   # fp32: accumulated across steps


def _matmul_run(x, obi, edst, esrc, num_rows: int, precision):
    """out = A @ x over the chunk plan, scatter-free (sum aggregation)."""
    from roc_tpu.ops.pallas.segment_sum import EB, VB
    from roc_tpu.ops.pallas.segment_sum import pad_chunks
    H = x.shape[-1]
    C = obi.shape[0]
    cb = min(_MM_CB, max(8, C))
    nsteps = -(-C // cb)
    obi, _, edst, esrc = pad_chunks(obi, jnp.zeros_like(obi), edst, esrc,
                                    nsteps * cb - C, jnp)
    num_windows = (num_rows + VB - 1) // VB
    acc_rows = (num_windows - 1 + cb) * VB   # DUS windows never clamp

    def body(acc, sl):
        ob, es, ed = sl
        g = jnp.take(x, es.reshape(cb * EB), axis=0, mode="clip")
        outs = _one_hot_dots(g, ed, ob, cb, precision)
        base = ob[0] * VB
        cur = jax.lax.dynamic_slice(acc, (base, 0), (cb * VB, H))
        return jax.lax.dynamic_update_slice(acc, cur + outs, (base, 0)), None

    # Accumulate across steps in fp32 even for bf16 activations (the Pallas
    # path does the same via x.astype(fp32); the reference sums in fp32);
    # carry promoted to x's device-varying annotation, axis-agnostically.
    acc = _vary_like(jnp.zeros((acc_rows, H), jnp.float32), x)
    acc, _ = jax.lax.scan(
        body, acc, (obi.reshape(nsteps, cb), esrc.reshape(nsteps, cb, EB),
                    edst.reshape(nsteps, cb, EB)))
    return acc[:num_rows].astype(x.dtype)


@partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def scatter_gather_matmul(x, plans: AggregatePlans, num_rows: int,
                          table_rows: int, precision: str = "highest"):
    """Sum-aggregation via one-hot MXU matmuls (no scatter, no Pallas).

    Plan-driven like the binned backend; `precision` feeds
    the one-hot dots — "highest" keeps fp32-exact sums (the one-hot factor
    is exact in bf16, so error comes only from rounding the features), while
    "default" trades ~1e-2 relative error for single-pass MXU throughput.
    """
    with jax.named_scope("roc_matmul_agg"):
        return _matmul_run(x, plans.fwd_obi, plans.fwd_edst, plans.fwd_esrc,
                           num_rows, precision)


def _mm_fwd(x, plans, num_rows, table_rows, precision):
    return scatter_gather_matmul(x, plans, num_rows, table_rows,
                                 precision), plans


def _mm_bwd(num_rows, table_rows, precision, plans, g):
    gx = _matmul_run(g, plans.bwd_obi, plans.bwd_edst, plans.bwd_esrc,
                     table_rows, precision)
    zero = jax.tree.map(
        lambda a: np.zeros(a.shape, dtype=jax.dtypes.float0), plans)
    return gx, zero


scatter_gather_matmul.defvjp(_mm_fwd, _mm_bwd)


# ---------------------------------------------------------------------------
# Binned backend (sum; avg = sum/in-degree at the call sites):
# two-phase Pallas kernels, gather-free.
# ---------------------------------------------------------------------------

class BinnedPlans(NamedTuple):
    """Fwd + transposed-bwd binned schedules (see ops/pallas/binned.py).

    Same role as :class:`AggregatePlans` for the plan-based one-hot
    backends; the payloads are :class:`roc_tpu.ops.pallas.binned.BinnedPlan`
    dataclasses (registered pytrees with static geometry fields).

    ``mm`` (optional) is the matmul side of a HYBRID plan: on power-law
    graphs the thin (sub-``hub_minc``) cells' edges pay less on the
    per-edge one-hot matmul path than as slot padding, so choose_geometry
    can split the edge list — dense hub cells stay binned, the tail rides
    an :class:`AggregatePlans` whose output simply adds in.  A = A_dense +
    A_thin, so fwd sums the two paths and bwd sums their transposes."""
    fwd: object
    bwd: object
    mm: object = None


def build_binned_plans(edge_src: np.ndarray, edge_dst: np.ndarray,
                       num_rows: int, table_rows: int,
                       geom=None,
                       storage_dtype: str = "fp32",
                       fuse_linear: bool = False) -> BinnedPlans:
    """Schedules for out = A@x (fwd) and grad_x = A^T@grad (bwd) — the bwd
    plan swaps roles exactly as the reference re-launches its forward
    kernel transposed (scattergather_kernel.cu:160-170).

    geom: None = the module-default geometry; a Geometry = both directions
    at that geometry; "auto" = per-direction choose_geometry from actual
    cell statistics (the directions transpose, so a directed graph can
    legitimately want different windows each way), falling back to the
    default where the model prefers matmul (the caller already chose
    binned).  A (fwd_spec, bwd_spec) pair sets each direction separately —
    resolve_backend_geom threads its already-chosen forward Geometry this
    way so the O(E) statistics aren't recomputed.

    A forward geometry with ``hub_minc`` set (choose_geometry's hybrid
    verdict, or an explicit caller) splits the edges: the binned pair
    covers only the dense-cell edges and ``mm`` carries the rest.

    ``fuse_linear`` applies the megakernel's layer-handoff pricing to BOTH
    directions' auto-choice (round 12): the backward plan now carries the
    fused-backward schedule (u = A^T g and dx = u @ W^T in one grid), so
    its round-trip credit prices the same way the forward's does.

    ROC_BINNED_GEOM=<preset name> (binned.GEOM_PRESETS) overrides the
    forward auto-choice for hardware A/B runs that must isolate one
    variable (tools/hw_revalidate.sh step 4c).  A forced preset builds
    with ``tuned_ok=False``: an A/B run must get exactly the geometry it
    named even when the tuned tier disagrees (round 12)."""
    import os
    from roc_tpu.ops.pallas.binned import (GEOM_PRESETS, Geometry,
                                           _default_geom,
                                           build_binned_plan,
                                           choose_geometry, split_hub_edges)
    # Geometry is itself a NamedTuple: only a PLAIN pair is (fwd, bwd)
    if isinstance(geom, tuple) and not isinstance(geom, Geometry):
        fwd_spec, bwd_spec = geom
    else:
        fwd_spec, bwd_spec = geom, geom

    def pick(spec, src, dst, n, t, fuse=False, forced=""):
        if spec != "auto":
            return spec
        if forced:
            return GEOM_PRESETS[forced]
        g, _ = choose_geometry(src, dst, n, t, force=True,
                               storage_dtype=storage_dtype,
                               fuse_linear=fuse)
        return g or _default_geom()

    forced_env = os.environ.get("ROC_BINNED_GEOM", "")
    fwd_geom = pick(fwd_spec, edge_src, edge_dst, num_rows, table_rows,
                    fuse=fuse_linear, forced=forced_env)
    es, ed = np.asarray(edge_src), np.asarray(edge_dst)
    mm = None
    if getattr(fwd_geom, "hub_minc", 0):
        keep = split_hub_edges(es, ed, fwd_geom)
        if keep.any() and not keep.all():
            ts, td = es[~keep], ed[~keep]
            o = np.argsort(td, kind="stable")   # chunk plans want dst-sorted
            mm = build_aggregate_plans(ts[o], td[o], num_rows, table_rows)
            es, ed = es[keep], ed[keep]
    bwd_geom = pick(bwd_spec, ed, es, table_rows, num_rows,
                    fuse=fuse_linear, forced=forced_env)
    if getattr(bwd_geom, "hub_minc", 0):
        # the split happened (once) on the forward cells; the bwd binned
        # plan covers exactly the transposed dense edges
        bwd_geom = bwd_geom._replace(hub_minc=0)
    tuned_ok = not forced_env
    return BinnedPlans(
        fwd=build_binned_plan(es, ed, num_rows, table_rows, geom=fwd_geom,
                              tuned_ok=tuned_ok),
        bwd=build_binned_plan(ed, es, table_rows, num_rows, geom=bwd_geom,
                              tuned_ok=tuned_ok),
        mm=mm)


def matmul_precision(aggregate_precision: str) -> str:
    """Map the config-level precision name to the dot_general precision,
    rejecting anything but the two supported spellings (a silent fallthrough
    to the fast path would drop the fp32-exact guarantee)."""
    if aggregate_precision == "exact":
        return "highest"
    if aggregate_precision == "fast":
        return "default"
    raise ValueError(f"aggregate_precision={aggregate_precision!r}: "
                     f"must be 'exact' or 'fast'")


def pad_binned_plans(plans: "list[BinnedPlans]", min_fwd=(0, 0),
                     min_bwd=(0, 0)) -> BinnedPlans:
    """Stack per-shard binned plans to common chunk counts (shard_map
    needs one static program) — the binned analog of :func:`pad_plans`.
    All shards share (G, bins_per_group, num_rows, table_rows) by
    construction: those derive only from the padded shard shapes, which
    are equal across shards.  ``min_fwd``/``min_bwd`` are (C1, C2) floors
    — the per-host loader passes allgathered global maxima."""
    from roc_tpu.ops.pallas.binned import pad_binned_plan
    assert all(b.mm is None for b in plans), \
        "hybrid (binned+matmul) plans are single-device only"

    def stack(side, floors):
        from roc_tpu.ops.pallas.binned import _PLAN_DATA_FIELDS
        ps = [getattr(b, side) for b in plans]
        meta = {(p.num_rows, p.table_rows, p.bins_per_group,
                 p.p1_blk.shape[0], p.geom) for p in ps}
        assert len(meta) == 1, f"shards disagree on plan geometry: {meta}"
        C1 = max(max(p.p1_blk.shape[1] for p in ps), floors[0])
        C2 = max(max(p.p2_obi.shape[1] for p in ps), floors[1])
        padded = [pad_binned_plan(p, C1, C2) for p in ps]
        import dataclasses as _dc
        # The fused (f_*) schedules are a single-device fast path: their
        # step lists bake in the per-shard chunk counts, which diverge
        # under shard_map's one static program — strip them so the
        # stacked plans take the two-pass scan uniformly.
        arrays = {}
        for f in _PLAN_DATA_FIELDS:
            vals = [getattr(p, f) for p in padded]
            if f.startswith("f_"):
                arrays[f] = None
                continue
            present = [v is not None for v in vals]
            assert all(present) or not any(present), \
                f"shards disagree on plan field {f}"
            arrays[f] = jnp.stack(vals) if all(present) else None
        return _dc.replace(padded[0], **arrays)

    return BinnedPlans(fwd=stack("fwd", min_fwd), bwd=stack("bwd", min_bwd))


@partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def scatter_gather_binned(x, plans: BinnedPlans, interpret: bool = False,
                          precision: str = "fast"):
    """Sum-aggregation via the binned two-phase kernels.  precision
    "fast": one bf16 rounding of features, fp32 accumulation; "exact":
    fp32 staging + 3-way bf16 split dots — fp32-exact like the matmul
    backend, at the binned kernels' memory schedule (the round-3 answer
    to "the fp32-exact path loses to the reference figure").
    Differentiable w.r.t. x.

    A hybrid plan (plans.mm set) adds the thin-cell edges' one-hot matmul
    aggregation: A = A_dense + A_thin."""
    from roc_tpu.ops.pallas.binned import run_binned
    out = run_binned(x, plans.fwd, interpret, precision)
    if plans.mm is not None:
        out = out + _matmul_run(
            x, plans.mm.fwd_obi, plans.mm.fwd_edst, plans.mm.fwd_esrc,
            plans.fwd.num_rows, matmul_precision(precision))
    return out


def _bn_fwd(x, plans, interpret, precision):
    return scatter_gather_binned(x, plans, interpret, precision), plans


def _bn_bwd(interpret, precision, plans, g):
    from roc_tpu.ops.pallas.binned import run_binned
    gx = run_binned(g, plans.bwd, interpret, precision)
    if plans.mm is not None:
        gx = gx + _matmul_run(
            g, plans.mm.bwd_obi, plans.mm.bwd_edst, plans.mm.bwd_esrc,
            plans.bwd.num_rows, matmul_precision(precision))
    zero = jax.tree.map(
        lambda a: np.zeros(a.shape, dtype=jax.dtypes.float0), plans)
    return gx, zero


scatter_gather_binned.defvjp(_bn_fwd, _bn_bwd)


# ---------------------------------------------------------------------------
# Whole-layer megakernel (round 10): aggregate -> linear (-> ReLU) fused
# into one Pallas grid — see roc_tpu/ops/pallas/binned.py run_binned_linear.
# ---------------------------------------------------------------------------

def _unfused_layer(x, w, plans, interpret, precision, activation):
    """The two-pass reference composition the megakernel must match:
    binned sum-aggregation, then ops.linear (fp32 `highest` matmul +
    activation).  Forward oracle for parity tests AND the backward's
    recompute target."""
    from roc_tpu.ops.linear import linear
    return linear(scatter_gather_binned(x, plans, interpret, precision),
                  w, activation)


@partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def scatter_gather_linear_binned(x, w, plans: BinnedPlans,
                                 interpret: bool = False,
                                 precision: str = "fast",
                                 activation: str = "none"):
    """linear(sum-aggregate(x), w)[, ReLU] through the megakernel when the
    plan's fused schedule and the VMEM gate allow it, else the identical
    two-pass composition.  Differentiable w.r.t. x and w.

    Backward (round 12) fuses too when ``run_binned_linear_bwd`` admits
    the transposed plan: one Pallas grid computes u = A^T(g * relu_mask)
    and dx = u @ W^T, so the ``[rows, H]`` aggregation cotangent never
    round-trips HBM, and dW = x^T u finishes as a single XLA GEMM (no
    forward recompute: (Ax)^T g = x^T A^T g).  When the fused backward
    declines (VMEM gate, non-flat bwd geometry, ROC_MEGA_BWD=0), the VJP
    replays ``scatter_gather_binned`` -> ``ops.linear`` under jax.vjp —
    byte-identical to the gradient program the unfused layer would have
    run, and the bitwise oracle the fused path is tested against on
    integer data (tests/test_mega_bwd.py; fp32 reassociates within a
    documented ULP bound).  Hybrid plans (plans.mm) are not eligible:
    their matmul side adds outside the kernel, so callers route those
    through the unfused ops."""
    from roc_tpu.ops.pallas.binned import run_binned_linear
    assert plans.mm is None, \
        "megakernel fusion requires a pure binned plan (no hybrid side)"
    return run_binned_linear(x, w, plans.fwd, interpret, precision,
                             activation)


def _bnl_fwd(x, w, plans, interpret, precision, activation):
    out = scatter_gather_linear_binned(
        x, w, plans, interpret, precision, activation)
    # the saved output is the relu-mask source for the fused backward;
    # for activation="none" it rides the residuals unused (same buffer
    # the caller holds anyway — no extra liveness)
    return out, (x, w, plans, out)


def _bnl_bwd(interpret, precision, activation, res, g):
    x, w, plans, out = res
    from roc_tpu.ops.pallas.binned import run_binned_linear_bwd
    fused = run_binned_linear_bwd(g, out, w, plans.bwd, interpret,
                                  precision, relu=(activation == "relu"))
    zero = jax.tree.map(
        lambda a: np.zeros(a.shape, dtype=jax.dtypes.float0), plans)
    if fused is not None:
        u, dx = fused
        # dW = x^T u as one XLA GEMM (matches ops.linear's grad precision)
        gw = jax.lax.dot_general(
            x.astype(jnp.float32), u, (((0,), (0,)), ((), ())),
            precision=jax.lax.Precision.HIGHEST,
            preferred_element_type=jnp.float32).astype(w.dtype)
        return dx.astype(x.dtype), gw, zero
    _, vjp = jax.vjp(
        lambda xx, ww: _unfused_layer(xx, ww, plans, interpret, precision,
                                      activation), x, w)
    gx, gw = vjp(g)
    return gx, gw, zero


scatter_gather_linear_binned.defvjp(_bnl_fwd, _bnl_bwd)


# ---------------------------------------------------------------------------
# Cross-layer megakernel (round 16): a whole fusion region —
# aggregate -> linear (-> ReLU) -> aggregate -> linear ... — through one
# Pallas grid; see roc_tpu/ops/pallas/binned.py run_binned_region.
# ---------------------------------------------------------------------------

def _unfused_region(x, ws, in_degree, plans, interpret, precision,
                    activations, fold):
    """The per-layer composition the cross-layer kernel must match:
    scatter_gather_linear_binned per member, with GCN's folded norm pair
    (post-scale of layer l + pre-scale of layer l+1) applied between
    members.  Forward parity oracle AND the region backward's fallback
    recompute target (jax.vjp of this function is byte-identical to the
    gradient program the unchained layers would have run)."""
    from roc_tpu.ops.norm import indegree_norm
    h = x
    for d, (w, act) in enumerate(zip(ws, activations)):
        h = scatter_gather_linear_binned(h, w, plans, interpret,
                                         precision, act)
        if fold and d + 1 < len(ws):
            # the boundary carries both layers' scales: layer d's
            # post-norm then layer d+1's pre-norm
            h = indegree_norm(indegree_norm(h, in_degree), in_degree)
    return h


@partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def region_linear_binned(x, ws, in_degree, plans: BinnedPlans,
                         interpret: bool = False, precision: str = "fast",
                         activations=(), fold: bool = False):
    """A whole fusion region through one Pallas grid: layer l's
    post-linear tile feeds layer l+1's aggregation while still in VMEM,
    so the ``[rows, H]`` inter-layer boundaries never exist in HBM
    (round 16).  ``ws``/``activations`` are the region's weight and
    activation chains, head to tail; ``fold`` applies GCN's norm pair at
    each interior boundary (``in_degree`` participates only then, and is
    nondifferentiable by ROC's convention — degrees are graph structure).
    Differentiable w.r.t. x and every w.

    The caller must pre-gate with ``region_ok`` (this primal asserts);
    the backward self-gates: ``run_binned_region_bwd`` replays the
    forward in-kernel for relu masks, ping-pongs interior cotangents in
    VMEM, and accumulates every dW in-kernel — declining to the
    ``_unfused_region`` jax.vjp oracle when the transposed plan or the
    VMEM price says no."""
    from roc_tpu.ops.pallas.binned import run_binned_region
    assert plans.mm is None, \
        "region fusion requires a pure binned plan (no hybrid side)"
    return run_binned_region(x, ws, in_degree, plans.fwd, interpret,
                             precision, activations, fold)


def _rnl_fwd(x, ws, in_degree, plans, interpret, precision, activations,
             fold):
    out = region_linear_binned(x, ws, in_degree, plans, interpret,
                               precision, activations, fold)
    # saved out is the last layer's relu-mask source; interior masks are
    # replayed in-kernel by the backward (that's the HBM saving)
    return out, (x, ws, in_degree, plans, out)


def _rnl_bwd(interpret, precision, activations, fold, res, g):
    x, ws, in_degree, plans, out = res
    from roc_tpu.ops.pallas.binned import run_binned_region_bwd
    zero_p = jax.tree.map(
        lambda a: np.zeros(a.shape, dtype=jax.dtypes.float0), plans)
    fused = run_binned_region_bwd(g, out, x, ws, in_degree, plans.fwd,
                                  plans.bwd, interpret, precision,
                                  activations, fold)
    if fused is not None:
        dx, gws = fused
        return (dx.astype(x.dtype),
                tuple(gw.astype(w.dtype) for gw, w in zip(gws, ws)),
                jnp.zeros_like(in_degree), zero_p)
    _, vjp = jax.vjp(
        lambda xx, wws: _unfused_region(xx, wws, in_degree, plans,
                                        interpret, precision, activations,
                                        fold), x, tuple(ws))
    gx, gws = vjp(g)
    return gx, gws, jnp.zeros_like(in_degree), zero_p


region_linear_binned.defvjp(_rnl_fwd, _rnl_bwd)
