"""Scale guards (VERDICT r2 weak #7): the host-side planners — partition,
halo maps, chunk/binned plans, padding — must stay O(E) in time and memory
at the reference's largest claimed scales.  Without these, a quadratic
regression in any builder ships green (everything else tests at toy scale)
and only explodes on a pod.

Two layers of guard:
  * an end-to-end products-shape build (~1.25e8 edges) under wall-clock
    and peak-RSS budgets (slow-marked; runs in CI's slow lane);
  * a papers100M-geometry HBM budget computation (no arrays) against the
    v5p chip capacity — the configuration BASELINE.md §targets names.
"""

import resource

import jax
import time

import numpy as np
import pytest

from roc_tpu.graph.csr import Csr


def _uniform_graph(num_nodes: int, num_edges: int, seed: int = 0) -> Csr:
    """Uniform random in-edge CSR at scale, built directly (the SBM
    generator's class machinery would dominate the build time; topology
    structure is irrelevant to planner complexity)."""
    rng = np.random.default_rng(seed)
    src = rng.integers(0, num_nodes, num_edges, dtype=np.int64)
    dst = np.sort(rng.integers(0, num_nodes, num_edges, dtype=np.int64))
    counts = np.bincount(dst, minlength=num_nodes)
    row_ptr = np.zeros(num_nodes + 1, np.int64)
    np.cumsum(counts, out=row_ptr[1:])
    return Csr(num_nodes, num_edges, row_ptr, src.astype(np.int32))


def _peak_rss_gb() -> float:
    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1e6


@pytest.mark.slow
def test_products_shape_planners_are_linear():
    """ogbn-products shape: 2.45M nodes, ~1.25e8 edges, 8 parts.  The full
    host-side build chain — partition + halo maps + matmul AND binned
    plans (stacked/padded, both directions) — under generous absolute
    budgets that a quadratic (or even E*P) regression cannot meet:
    products is ~50x the toy-test scale, so an O(E^2)-ish builder blows
    the time budget by orders of magnitude, and a planner materializing
    [P*S] per part blows RSS."""
    from roc_tpu.graph.partition import partition_graph
    from roc_tpu.parallel.halo import build_halo_maps
    from roc_tpu.parallel.spmd import _build_shard_plans

    from roc_tpu.ops.pallas.binned import binned_viable

    N, E, P = 2_449_029, 125_000_000, 8
    rss0 = _peak_rss_gb()
    t0 = time.monotonic()
    g = _uniform_graph(N, E)
    t_gen = time.monotonic() - t0

    t0 = time.monotonic()
    part = partition_graph(g, P)
    halo = build_halo_maps(part)
    t_part = time.monotonic() - t0

    S = part.shard_nodes
    table_rows = S + P * halo.K
    # Production routing at this shape: binned_viable must REJECT (a
    # products-density uniform graph slot-pads ~5x — the documented case
    # the bound exists for) and the matmul plans are the fast path.
    # Building binned plans anyway would itself be the memory bug: ~80 GB
    # of slot-padded schedules (OOM-verified while writing this test).
    assert not binned_viable(S, table_rows, int(part.num_edges_valid.max()))
    t0 = time.monotonic()
    mm = _build_shard_plans("matmul", halo.edge_src_local, part.edge_dst,
                            S, table_rows)
    t_plans = time.monotonic() - t0

    # Linearity contract: chunk counts stay within 2x of (edges/EB +
    # windows) per direction.  The bwd window floor spans the halo TABLE
    # (2.75M rows here — a uniform graph's halo is nearly the whole
    # graph), so plan bytes are O(E + P*table_rows/VB*EB), ~55 B/edge at
    # this shape (6.9 GB measured) — linear, but the floor term dominates;
    # a quadratic planner blows the 2x margin immediately.
    from roc_tpu.ops.pallas.segment_sum import EB, VB
    E_shard = int(part.shard_edges)
    assert mm.fwd_obi.shape[1] < 2 * (E_shard / EB + S / VB + 1), \
        f"fwd chunks {mm.fwd_obi.shape[1]}"
    assert mm.bwd_obi.shape[1] < 2 * (E_shard / EB + table_rows / VB + 1), \
        f"bwd chunks {mm.bwd_obi.shape[1]}"
    mm_bytes = sum(a.size * a.dtype.itemsize for a in
                   (mm.fwd_esrc, mm.fwd_edst, mm.bwd_esrc, mm.bwd_edst))

    peak = _peak_rss_gb()
    # budgets: generous absolutes a quadratic regression cannot meet
    assert t_part < 300, f"partition+halo took {t_part:.0f}s"
    assert t_plans < 900, f"plan build took {t_plans:.0f}s"
    assert peak < 60, f"peak RSS {peak:.1f} GB (start {rss0:.1f})"
    print(f"# products-shape guard: gen {t_gen:.0f}s part+halo "
          f"{t_part:.0f}s plans {t_plans:.0f}s peak {peak:.1f} GB, "
          f"mm {mm_bytes/E:.1f} B/edge")


@pytest.mark.slow
def test_reddit_shape_binned_plans_are_linear():
    """The binned planner's O(E) guard runs at the shape it actually
    serves (Reddit density, where binned_viable accepts): single-part,
    23.5M edges.  Dense-enough graphs keep the slot padding ~25%;
    the plan arrays must stay small-constant x E."""
    from roc_tpu.ops.pallas.binned import binned_viable
    from roc_tpu import ops

    N, E = 232_965, 23_526_267
    g = _uniform_graph(N, E, seed=1)
    assert binned_viable(N, N, E)
    rss0 = _peak_rss_gb()     # ru_maxrss is a process-lifetime high-water
    t0 = time.monotonic()     # mark: assert on the DELTA so an earlier
    bn = ops.build_binned_plans(g.col_idx, g.dst_idx, N, N)  # test's peak
    t_build = time.monotonic() - t0                          # can't fail us
    leaves = [np.asarray(x) for pl in (bn.fwd, bn.bwd)
              for x in (pl.p1_srcl, pl.p1_off, pl.p1_blk, pl.p2_dstl,
                        pl.p2_obi, pl.p2_first)]
    bn_bytes = sum(a.size * a.dtype.itemsize for a in leaves)
    assert bn_bytes < 80 * E, f"binned plans {bn_bytes/E:.1f} B/edge"
    assert t_build < 300, f"binned plan build took {t_build:.0f}s"
    grew = _peak_rss_gb() - rss0
    # delta guards the build without false failures from earlier tests'
    # peaks; the absolute bound still caps the footprint when this test
    # runs after memory-heavy neighbors (both directions covered)
    assert grew < 30, f"binned plan build grew peak RSS by {grew:.1f} GB"
    assert _peak_rss_gb() < 60, f"absolute peak {_peak_rss_gb():.1f} GB"
    print(f"# reddit-shape binned guard: build {t_build:.0f}s "
          f"{bn_bytes/E:.1f} B/edge new-peak delta {grew:.1f} GB")


@pytest.mark.slow
def test_products_shape_perhost_end_to_end(tmp_path):
    """The pod-scale data path, end to end at real scale on one host:
    write a products-shape dataset in the on-disk format (binary feature
    sidecar — the CSV would be tens of GB), load it with graph_stub=True
    (12-byte header only), and train one perhost epoch on the 8-virtual-
    device mesh: per-part `.lux` byte-range reads, local halo build with
    allgathered floors, per-device placement, one full train step + eval.
    This is the single-host rehearsal of the papers100M story (SURVEY §7
    'sharded host loading')."""
    from roc_tpu.graph import datasets, lux
    from roc_tpu.models import build_gcn
    from roc_tpu.parallel.spmd import SpmdTrainer
    from roc_tpu.train.config import Config

    N, E, P = 2_449_029, 125_000_000, 8
    in_dim, classes = 16, 8        # feature width scaled down: the point
    g = _uniform_graph(N, E)       # is the graph-scale path, not the GEMMs
    prefix = str(tmp_path / "products")
    t0 = time.monotonic()
    lux.write_lux(prefix + lux.LUX_SUFFIX, g)
    rng = np.random.default_rng(0)
    feats = rng.standard_normal((N, in_dim)).astype(np.float32)
    feats.tofile(prefix + ".feats.bin")       # binary sidecar directly
    labels = rng.integers(0, classes, N).astype(np.int32)
    labels.tofile(prefix + ".label.bin")
    mask = np.full(N, lux.MASK_NONE, np.int32)
    mask[:200_000] = lux.MASK_TRAIN
    mask[200_000:240_000] = lux.MASK_VAL
    with open(prefix + ".mask", "w") as f:
        f.write("\n".join("Train" if m == lux.MASK_TRAIN else
                          "Val" if m == lux.MASK_VAL else "None"
                          for m in mask) + "\n")
    t_write = time.monotonic() - t0

    ds = datasets.load_roc_dataset(prefix, in_dim, classes,
                                   graph_stub=True)
    assert ds.graph.num_edges == E and ds.features.shape == (N, in_dim)
    cfg = Config(layers=[in_dim, 16, classes], num_epochs=1,
                 dropout_rate=0.0, num_parts=P, halo=True,
                 perhost_load=True, filename=prefix, eval_every=10**9,
                 aggregate_backend="xla", lazy_load=True)
    t0 = time.monotonic()
    tr = SpmdTrainer(cfg, ds, build_gcn(cfg.layers, 0.0))
    t_setup = time.monotonic() - t0
    t0 = time.monotonic()
    loss = float(tr.run_epoch())
    t_epoch = time.monotonic() - t0
    assert np.isfinite(loss)
    m = jax.device_get(tr.evaluate())
    assert int(m.train_all) == 200_000
    print(f"# products perhost e2e: write {t_write:.0f}s setup "
          f"{t_setup:.0f}s epoch {t_epoch:.0f}s loss {loss:.1f} "
          f"peak {_peak_rss_gb():.1f} GB")


@pytest.mark.slow
def test_papers100m_sixteenth_rehearsal(tmp_path):
    """The papers100M configuration at 1/16 linear scale, end to end
    (VERDICT r3 item 7): 6.94M nodes / 2.09e8 edges written in the
    on-disk format, loaded perhost (graph stub + byte-range reads), and an
    8-LAYER GCN (the BASELINE.json depth, deep-residual path incl.) with
    -bf16 trained one epoch on the 8-virtual-device mesh.  Budgets are
    generous absolutes a superlinear builder or program-build regression
    cannot meet — this is ~1.7x the products guard's edge count AND 4x its
    layer count, so it exercises the deep-program compile path the other
    guards don't."""
    from roc_tpu.graph import datasets, lux
    from roc_tpu.models import build_gcn
    from roc_tpu.parallel.spmd import SpmdTrainer
    from roc_tpu.train.config import Config

    N, E, P = 111_059_956 // 16, 3_340_000_000 // 16, 8
    in_dim, hidden, classes = 8, 8, 8   # width scaled: graph-scale path +
    layers = [in_dim] + [hidden] * 7 + [classes]   # depth, not the GEMMs
    g = _uniform_graph(N, E, seed=2)
    prefix = str(tmp_path / "papers16")
    t0 = time.monotonic()
    lux.write_lux(prefix + lux.LUX_SUFFIX, g)
    rng = np.random.default_rng(0)
    feats = rng.standard_normal((N, in_dim)).astype(np.float32)
    feats.tofile(prefix + ".feats.bin")
    rng.integers(0, classes, N).astype(np.int32).tofile(
        prefix + ".label.bin")
    mask = np.full(N, lux.MASK_NONE, np.int32)
    mask[:100_000] = lux.MASK_TRAIN
    with open(prefix + ".mask", "w") as f:
        f.write("\n".join("Train" if m == lux.MASK_TRAIN else "None"
                          for m in mask) + "\n")
    t_write = time.monotonic() - t0

    ds = datasets.load_roc_dataset(prefix, in_dim, classes, graph_stub=True)
    cfg = Config(layers=layers, num_epochs=1, dropout_rate=0.0,
                 num_parts=P, halo=True, perhost_load=True, filename=prefix,
                 eval_every=10**9, aggregate_backend="xla", lazy_load=True,
                 use_bf16=True)
    t0 = time.monotonic()
    tr = SpmdTrainer(cfg, ds, build_gcn(cfg.layers, 0.0))
    t_setup = time.monotonic() - t0
    t0 = time.monotonic()
    loss = float(tr.run_epoch())
    t_epoch = time.monotonic() - t0
    assert np.isfinite(loss)
    peak = _peak_rss_gb()
    # superlinearity guard: generous absolutes (CPU, 8 virtual devices)
    assert t_setup < 900, f"perhost setup took {t_setup:.0f}s"
    assert t_epoch < 1500, f"8-layer epoch took {t_epoch:.0f}s"
    assert peak < 80, f"peak RSS {peak:.1f} GB"
    print(f"# papers16 rehearsal: write {t_write:.0f}s setup {t_setup:.0f}s "
          f"epoch {t_epoch:.0f}s loss {loss:.2f} peak {peak:.1f} GB")


def test_papers100m_fits_v5p_hbm():
    """BASELINE.md target config: 8-layer GCN on ogbn-papers100M across a
    v5p-32 slice.  Pure geometry computation (no arrays): the per-device
    budget — features, activations, halo table, plans, binned staging —
    must fit a v5p chip's 95 GB HBM with headroom, and the binned staging
    term must be bounded by the group-row target, not by E."""
    from roc_tpu.ops.pallas.binned import _GROUP_ROW_TARGET
    from roc_tpu.parallel.budget import HBM, estimate_device_bytes

    # papers100M: 111M nodes, 1.6e9 directed edges -> ~3.3e9 symmetrized
    # + self edges; 128-dim features, 172 classes; 8 layers, 256 hidden.
    geom = dict(num_nodes=111_059_956, num_edges=3_340_000_000, in_dim=128,
                hidden=256, num_classes=172, parts=32, layers=8,
                halo_fraction=0.5, backend="binned")
    # fp32 does NOT fit (activations + halo ~119 GB of 95): the estimator
    # is what documents WHY pod-scale deep GCN runs take -bf16
    b32 = estimate_device_bytes(dtype_bytes=4, **geom)
    assert b32.total > HBM["v5p"]
    # the supported configuration: bf16 activations (-bf16)
    b = estimate_device_bytes(dtype_bytes=2, **geom)
    assert b.staging <= 2 * _GROUP_ROW_TARGET * 256 * 2 + 1, \
        "staging must be group-bounded, not O(E)"
    assert b.total < 0.8 * HBM["v5p"], (
        f"papers100M/v5p-32 -bf16 budget {b.total/1e9:.1f} GB exceeds 80% "
        f"of {HBM['v5p']/1e9:.0f} GB: {b}")
    # and the same geometry must NOT fit one v5e chip (sanity that the
    # estimator isn't vacuously small)
    b1 = estimate_device_bytes(
        num_nodes=111_059_956, num_edges=3_340_000_000, in_dim=128,
        hidden=256, num_classes=172, parts=1, layers=8)
    assert b1.total > HBM["v5e"]


def test_budget_reddit_fits_v5e():
    """The canonical bench config must fit the bench chip — ties the
    estimator to a configuration that demonstrably runs (BASELINE.md)."""
    from roc_tpu.parallel.budget import HBM, estimate_device_bytes
    b = estimate_device_bytes(num_nodes=232_965, num_edges=23_526_267,
                              in_dim=602, hidden=256, num_classes=41,
                              parts=1, layers=2, backend="binned")
    assert b.total < HBM["v5e"], f"{b.total/1e9:.1f} GB"
