"""Checkpoint / resume (capability the reference lacks, SURVEY.md §5.4 —
weights there live only in GPU framebuffers and every run starts from Glorot
init).  Plain .npz of the flattened param/optimizer pytrees plus host-side
training state; no external deps, works for multi-MB GNN weights.

Crash consistency (roc_tpu/fault): the save writes a temp file (retried —
a transient ENOSPC/EIO must not kill a multi-hour run), fsyncs data and
directory entry before/after the rename (`fault.fsync_replace`), and
stamps a CRC32 of the payload arrays into the meta record.  `load`
verifies the CRC and raises :class:`CheckpointError` with a clear message
on any torn/corrupt file instead of an opaque zipfile traceback.  The
`ckpt.kill_tmp` / `ckpt.kill_rename` injection sites simulate a kill -9
on either side of the rename; the resume tests pin that both leave a
loadable checkpoint behind (the old one, or the new one — never garbage).
"""

from __future__ import annotations

import json
import os
import zlib
from typing import Any, Dict, Tuple

import jax
import numpy as np

from roc_tpu import fault

_FORMAT_VERSION = 1


class CheckpointError(RuntimeError):
    """A checkpoint file that cannot be trusted (corrupt, truncated, or
    from an incompatible format version)."""


def _flatten(tree) -> Dict[str, np.ndarray]:
    leaves, _ = jax.tree.flatten(tree)
    return {f"leaf_{i}": np.asarray(jax.device_get(x))
            for i, x in enumerate(leaves)}


def _unflatten(tree_like, arrays: Dict[str, np.ndarray]):
    leaves, treedef = jax.tree.flatten(tree_like)
    new = [arrays[f"leaf_{i}"] for i in range(len(leaves))]
    return jax.tree.unflatten(treedef, new)


def _payload_crc(arrays: Dict[str, np.ndarray]) -> int:
    """CRC32 over every payload array (sorted key order), covering key,
    dtype, shape, and bytes — the integrity stamp `load` verifies."""
    crc = 0
    for k in sorted(arrays):
        a = np.ascontiguousarray(arrays[k])
        crc = zlib.crc32(f"{k}:{a.dtype.str}:{a.shape}".encode(), crc)
        crc = zlib.crc32(a.tobytes(), crc)
    return crc & 0xFFFFFFFF


def save(path: str, params, opt_state, epoch: int, alpha: float,
         extra: Dict[str, Any] | None = None) -> None:
    """Durable atomic save: retried tmp write, then fsync(file) +
    rename + fsync(dir), with a payload CRC32 in the meta record."""
    payload = {f"p_{k}": v for k, v in _flatten(params).items()}
    payload.update({f"o_{k}": v for k, v in _flatten(opt_state).items()})
    meta = {"version": _FORMAT_VERSION, "epoch": epoch, "alpha": alpha,
            "extra": extra or {}, "crc32": _payload_crc(payload)}
    payload["meta"] = np.frombuffer(
        json.dumps(meta).encode(), dtype=np.uint8)
    tmp = path + ".tmp"

    def _write():
        fault.point("ckpt.write")
        with open(tmp, "wb") as f:
            np.savez(f, **payload)
    fault.retrying("ckpt.write", _write)
    fault.point("ckpt.kill_tmp")      # crash window A: tmp on disk, target
    fault.fsync_replace(tmp, path)    # untouched — old checkpoint survives
    fault.point("ckpt.kill_rename")   # crash window B: new one is complete


def save_arrays(path: str, arrays: Dict[str, np.ndarray],
                extra: Dict[str, Any] | None = None,
                site: str = "ckpt") -> None:
    """The durable-save protocol of :func:`save` for an arbitrary named
    array dict (the delta-journal snapshot rides this, roc_tpu/serve/
    delta.py): retried tmp write, CRC32 stamp in the meta record, fsync +
    rename + dir fsync, with the same two kill windows exposed under
    ``site`` ("<site>.write" / "<site>.kill_tmp" / "<site>.kill_rename")."""
    payload = {k: np.asarray(v) for k, v in arrays.items()}
    meta = {"version": _FORMAT_VERSION, "epoch": -1, "alpha": 0.0,
            "extra": extra or {}, "crc32": _payload_crc(payload)}
    payload["meta"] = np.frombuffer(
        json.dumps(meta).encode(), dtype=np.uint8)
    tmp = path + ".tmp"

    def _write():
        fault.point(f"{site}.write")
        with open(tmp, "wb") as f:
            np.savez(f, **payload)
    fault.retrying(f"{site}.write", _write)
    fault.point(f"{site}.kill_tmp")
    fault.fsync_replace(tmp, path)
    fault.point(f"{site}.kill_rename")


def load_arrays(path: str) -> Tuple[Dict[str, np.ndarray],
                                    Dict[str, Any]]:
    """Verified load of a :func:`save_arrays` file; CheckpointError on
    anything torn, corrupt, or version-skewed."""
    meta, arrays = _read_verified(path)
    return arrays, meta.get("extra", {})


def _read_verified(path: str) -> Tuple[Dict[str, Any],
                                       Dict[str, np.ndarray]]:
    """Load + integrity-check an .npz checkpoint; CheckpointError with a
    clear message on anything torn, corrupt, or version-skewed."""
    try:
        with np.load(path) as z:
            arrays = {k: z[k] for k in z.files}
        if "meta" not in arrays:
            raise ValueError("missing meta record")
        meta = json.loads(bytes(arrays.pop("meta")).decode())
    except FileNotFoundError:
        raise
    except Exception as e:
        raise CheckpointError(
            f"corrupt or truncated checkpoint {path!r} "
            f"({type(e).__name__}: {e}); the durable-save protocol never "
            f"produces this — restore from an older checkpoint") from e
    if meta.get("version") != _FORMAT_VERSION:
        raise CheckpointError(
            f"checkpoint {path!r} has format version "
            f"{meta.get('version')!r}, this build reads "
            f"{_FORMAT_VERSION}")
    want = meta.get("crc32")
    if want is not None and _payload_crc(arrays) != want:
        raise CheckpointError(
            f"checkpoint {path!r} failed its CRC32 integrity check — "
            f"payload bytes do not match the stamp written at save time "
            f"(torn write or bit rot); restore from an older checkpoint")
    return meta, arrays


def load(path: str, params_like, opt_state_like
         ) -> Tuple[Any, Any, int, float, Dict[str, Any]]:
    """Restore into the same pytree structure as `params_like`/`opt_state_like`."""
    meta, arrays = _read_verified(path)
    p = {k[2:]: v for k, v in arrays.items() if k.startswith("p_")}
    o = {k[2:]: v for k, v in arrays.items() if k.startswith("o_")}
    params = _unflatten(params_like, p)
    opt_state = _unflatten(opt_state_like, o)
    return params, opt_state, meta["epoch"], meta["alpha"], meta["extra"]


def load_params(path: str, params_like) -> Any:
    """Params-only restore (frozen/serving paths — roc_tpu/train/frozen.py):
    only the param arrays are kept/unflattened.  (The CRC verification
    does stream every payload byte once — integrity beats the transient
    read; only the params stay resident.)"""
    meta, arrays = _read_verified(path)
    p = {k[2:]: v for k, v in arrays.items() if k.startswith("p_")}
    return _unflatten(params_like, p)
