"""StreamTrainer: the out-of-core shard-rotation executor behind -stream.

The full padded graph — features, labels, edge arrays, and every
segment-boundary activation — lives in host memory as numpy stores of
shape ``[P*S, d]`` (shard-major, the same padded layout the SPMD path puts
on device).  Device memory only ever holds ``stream_slots`` shard slots:
the one being computed plus the prefetch depth.  An epoch is a sequence of
*sweeps* — one per model segment (segments.py), forward then reverse — and
each sweep rotates all P shards through the slots while the PrefetchRing
transfers shard i+1 under shard i's compute.

Per-segment jitted functions take every shard-varying tensor (table, own
rows, edge arrays, cotangents) as *arguments*, so all P rotations and all
epochs share one trace per function — the zero-retrace property the
RetraceGuard test pins.  The backward pass recomputes each segment's
forward from its host-stored inputs (rematerialize-from-host: the
streaming analog of the memory planner's REMAT, which is why the planner's
OFFLOAD verdict compiles to this executor), accumulating parameter
gradients on device and activation cotangents in host stores via the
transposed table gather (``np.add.at`` over the same ``[S + P*K]`` table
index map the forward used).  The scatter itself runs on the prefetch
ring's worker thread, overlapped with the next shard's compute: the
single worker serializes scatters against each other (shards share halo
rows in the cotangent stores) and against the next sweep's fetches, and
the executor additionally drains pending scatters before any sweep that
reads ``self._cots`` and before the epoch-end optimizer update.
``stream_scatter_overlap_frac`` in the epoch stats reports how much of
that scatter time stayed hidden under compute.

Parity: per-shard loss terms and metric tallies are pure sums
(ops/softmax.py), so the streamed epoch computes the same loss/gradient as
the in-core step up to float reassociation; Adam (weight decay included)
then applies the identical update.  tests/test_stream.py holds the 3-epoch
loss gap under 1e-3.

Storage tiers: under ``-bf16-storage`` every float wire — host stores,
slot ``device_put``s, boundary outputs, the cotangent fetch — rides
bf16 with the wire codec's one-rounding-per-row nearest contract
(parallel/spmd.py precedent): values round exactly once at a store/wire
boundary, and all arithmetic (segment compute, loss, ``np.add.at``
cotangent accumulation, Adam) stays fp32.  Integer-valued data is bf16-
exact, so streamed-bf16 matches fp32 streaming bitwise on integer
features (tests/test_stream_tiers.py pins it).  The bf16 layout also
narrows the edge-index wire to uint16 when the local+halo table fits in
16 bits.  Host stores come from the sanctioned allocator
(stream/host.py): pinned zero-copy buffers when the backend has a
pinned_host space, plain numpy otherwise.  Under ``-stream-spill DIR``
the boundary-activation and cotangent stores drop to a third tier —
CRC-headered memmaps on disk (stream/spill.py) — and the ring prefetches
slot i+1's spill read behind slot i's compute exactly like device
staging (``stream_spill_*`` spans/stats; write time that blocks the
consumer feeds the watchdog's spill-stall EWMA).
"""

from __future__ import annotations

import os

import numpy as np

import jax
import jax.numpy as jnp

from roc_tpu import fault, obs, ops
from roc_tpu.analysis import retrace as _retrace
from roc_tpu.graph import shard_load
from roc_tpu.graph.csr import Csr
from roc_tpu.graph.lux import LUX_SUFFIX
from roc_tpu.graph.partition import _round_up, partition_graph
from roc_tpu.ops.softmax import MASK_NONE
from roc_tpu.stream import host as stream_host
from roc_tpu.stream import spill as stream_spill
from roc_tpu.stream.ring import PrefetchRing
from roc_tpu.stream.segments import (predicted_epoch_bytes, run_segment,
                                     split_segments)
from roc_tpu.train.driver import BaseTrainer

__all__ = ["StreamTrainer"]

_tree_map = jax.tree_util.tree_map


def _stream_maps(meta, edge_src, K_force=None):
    """Frozen-shape halo maps for the rotating table gather.

    Returns ``(K, tbl_idx, esrc_local)`` where ``tbl_idx[i]`` gathers
    shard i's ``[S + P*K]`` source table from a ``[P*S, d]`` host store
    (first S entries = own rows, then K halo rows per owner, unfilled
    entries parked on each owner's guaranteed pad row S-1), and
    ``esrc_local[i]`` rewrites the padded-global edge sources into that
    table — the same local+halo layout ``shard_load.build_halo_local``
    gives the perhost SPMD path, with the per-(i,q) need lists collapsed
    to one frozen width K so every rotation and every reshard reuses the
    compiled step (``K_force`` pins K across reshards; a cut that needs
    more halo than the frozen K raises instead of silently retracing)."""
    P, S, E = int(meta.num_parts), int(meta.shard_nodes), int(meta.shard_edges)
    need = []
    kmax = 1
    for i in range(P):
        src = np.asarray(edge_src[i], np.int64)
        owner = src // S
        per = {}
        for q in np.unique(owner[owner != i]):
            rows = np.unique(src[owner == q] - q * S)
            per[int(q)] = rows
            kmax = max(kmax, len(rows))
        need.append(per)
    if K_force is None:
        # headroom over the observed worst per-owner halo need: a later
        # balancer cut shifts boundary nodes between owners, and the
        # frozen K must absorb the move without retracing (25% + one
        # alignment unit, mirroring the padded-shape slack elsewhere)
        K = _round_up(kmax + max(8, kmax // 4), 8)
    else:
        if kmax > K_force:
            raise ValueError(
                f"stream reshard: new cut needs halo width {kmax} > frozen "
                f"K={K_force}; restart -stream to rebuild the slot shapes")
        K = int(K_force)

    # host-side map assembly scratch: tbl_idx indexes host stores and
    # never ships; esrc_local is copied into a sanctioned store by
    # _install_graph before any device staging
    tbl_idx = np.empty((P, S + P * K), np.int64)     # roclint: allow(unpinned-host-buffer) — gather-index scratch, never staged
    esrc_local = np.empty((P, E), np.int32)          # roclint: allow(unpinned-host-buffer) — copied into a host.to_store buffer before staging
    owners_base = np.repeat(np.arange(P, dtype=np.int64) * S + (S - 1), K)
    for i in range(P):
        halo = owners_base.copy()
        for q, rows in need[i].items():
            halo[q * K: q * K + len(rows)] = q * S + rows
        tbl_idx[i, :S] = i * S + np.arange(S, dtype=np.int64)
        tbl_idx[i, S:] = halo
        src = np.asarray(edge_src[i], np.int64)
        owner = src // S
        local = src - owner * S
        out = local.copy()  # own rows (incl. pad edges) index directly
        for q, rows in need[i].items():
            sel = owner == q
            out[sel] = S + q * K + np.searchsorted(rows, local[sel])
        esrc_local[i] = out.astype(np.int32)
    return K, tbl_idx, esrc_local


def _f32(x):
    """Device-side upcast to the fp32 compute dtype.  On fp32 wires this
    is the identity (jnp.astype to the same dtype inserts no convert), so
    fp32 streaming compiles to the exact pre-bf16 programs."""
    return x.astype(jnp.float32)


def _f32d(d):
    return {t: _f32(v) for t, v in d.items()}


class StreamTrainer(BaseTrainer):
    """Host-streaming trainer: fixed device slots, rotating shards."""

    # -- setup -------------------------------------------------------------

    def _setup(self):
        cfg, ds = self.config, self.dataset
        if self.dtype != jnp.float32:
            raise SystemExit("error: -stream computes in fp32 (-bf16 casts "
                             "the whole model; use -bf16-storage to stream "
                             "bf16 slots with fp32 compute)")
        if cfg.bf16_storage and (cfg.bf16_rounding != "nearest"
                                 or cfg.bf16_exchange != "plain"):
            raise SystemExit("error: -stream bf16 slots implement the "
                             "nearest/plain wire contract only (stochastic "
                             "rounding and compensated exchange live in the "
                             "shard_map collective codec)")
        self._sdtype = np.dtype(jnp.bfloat16) if cfg.bf16_storage \
            else np.dtype(np.float32)
        self._spill_dir = str(cfg.stream_spill or "")
        if self._spill_dir:
            os.makedirs(self._spill_dir, exist_ok=True)
        P = int(cfg.num_parts)
        if P < 2:
            raise SystemExit("error: -stream needs -parts >= 2 (one slot "
                             "computing, at least one in flight)")
        self._P = P
        self._lux_path = ""
        g = ds.graph
        if isinstance(g, Csr):
            self.part = partition_graph(g, P)
            meta = self.part.meta
            edge_src = np.asarray(self.part.edge_src)
            edge_dst = np.asarray(self.part.edge_dst)
            in_degree = np.asarray(self.part.in_degree, np.float32)
        else:
            # GraphStub: stream straight off the .lux byte ranges — the
            # graph is never materialized whole, on host or device.
            if jax.process_count() > 1:
                raise SystemExit("error: -stream is single-process (it is "
                                 "the out-of-core alternative to scaling "
                                 "out across hosts)")
            self._lux_path = cfg.filename + LUX_SUFFIX
            meta = shard_load.meta_from_lux(self._lux_path, P)
            self.part = meta
            edge_src, edge_dst, in_degree = self._load_lux_shards(meta)

        self.segments = split_segments(self.model)
        self._nseg = len(self.segments)
        self._install_graph(meta, edge_src, edge_dst, in_degree)
        self._alloc_stores()
        self.params = self.model.init_params(self.key)
        self.opt_state = self.optimizer.init(self.params)
        self.num_nodes = int(meta.num_nodes)
        self._resolve_mem_plan()
        self._build_steps()

        self._ring = PrefetchRing(cfg.stream_slots, self._fetch)
        self._keys = None
        self._grad_acc = None
        self._last_gnorm = None
        self._xfer_bytes = 0
        self._scatter_futs = []
        self._scatter_s = 0.0
        self._scatter_wait_s = 0.0
        self._spill_read_s = 0.0
        self._spill_write_s = 0.0
        self._spill_read_bytes = 0
        self._spill_write_bytes = 0
        self._logits_sink = None
        self._epoch_stream = []
        self._last_stream_stats = None
        # Ledger predictions from the static slot geometry, paired per
        # epoch: _fetch's accumulated bytes against the analytic sweep
        # schedule, and the ring's overlap fraction against the design
        # target (prefetch fully hides transfers).
        led = obs.get_ledger()
        if led.attached:
            from roc_tpu.obs.ledger import content_key
            self._wire_key = content_key(parts=self._P,
                                         segments=self._nseg,
                                         slots=int(cfg.stream_slots))
            pred_bytes = self._predicted_epoch_xfer_bytes()
            led.predict("wire_bytes", self._wire_key, pred_bytes, "bytes")
            led.predict("overlap_frac", self._wire_key, 1.0, "frac")
            # pinned-store transfer-time model: the epoch's staged bytes
            # over the assumed host<->device bandwidth (stream/host.py;
            # ROC_STREAM_BW_BYTES calibrates), paired against the ring's
            # measured transfer seconds each epoch
            led.predict("stream_xfer_s", self._wire_key,
                        pred_bytes / stream_host.STREAM_BW_BYTES_S, "s")
        if cfg.verbose:
            budget = cfg.stream_budget_bytes()
            held = cfg.stream_slots * self.slot_bytes()
            note = ""
            if budget:
                note = (f" vs budget {budget / 2**20:.0f} MiB "
                        f"({'fits' if held <= budget else 'OVER'})")
            tier = "bf16" if self._sdtype.itemsize == 2 else "fp32"
            if self._spill_dir:
                tier += f"+spill({self._spill_dir})"
            print(f"# stream: {P} shards x {self._nseg} segments through "
                  f"{cfg.stream_slots} slots, ~{held / 2**20:.1f} MiB "
                  f"device-resident{note}, halo K={self._K}, {tier} slots, "
                  f"{'pinned' if stream_host.pinned_supported() else 'pageable'}"
                  " host stores")

    def _load_lux_shards(self, meta):
        shards = shard_load.load_local_shards(
            self._lux_path, meta, list(range(int(meta.num_parts))))
        return (np.asarray(shards.edge_src),
                np.asarray(shards.edge_dst),
                np.asarray(shards.in_degree, np.float32))

    def _install_graph(self, meta, edge_src, edge_dst, in_degree,
                       K_force=None):
        """(Re)bind everything derived from the current cut: table/edge
        maps plus the padded node-data stores.  Boundary-activation stores
        are allocated once (`_alloc_stores`) — their [P*S, d] shapes do not
        depend on the cut, which is what keeps reshard retrace-free."""
        self._meta = meta
        self._S = int(meta.shard_nodes)
        self._E = int(meta.shard_edges)
        K, tbl_idx, esrc_local = _stream_maps(meta, edge_src, K_force)
        self._K = K
        self._tbl_idx = tbl_idx
        # The compact bf16 wire also narrows edge indices to uint16 when
        # they fit (table rows for esrc, shard rows for edst) — the jitted
        # steps upcast to int32 on device.  fp32 streaming keeps the int32
        # wire so its byte layout is unchanged from the fp32-only era.
        compact = self._sdtype.itemsize < 4
        esrc_dt = np.uint16 if compact and self._S + self._P * K <= 1 << 16 \
            else np.int32
        edst_dt = np.uint16 if compact and self._S <= 1 << 16 else np.int32
        self._esrc = stream_host.to_store(esrc_local.astype(esrc_dt))
        self._edst = stream_host.to_store(
            np.asarray(edge_dst).astype(edst_dt))
        self._indeg = stream_host.to_store(
            np.asarray(in_degree, np.float32))
        self._edges_valid = jnp.asarray(
            np.asarray(meta.num_edges_valid), jnp.int32)
        ds = self.dataset
        # one nearest rounding per row at load — the storage-dtype contract
        self._store_x = stream_host.to_store(
            np.asarray(meta.pad_nodes(ds.features), self._sdtype))
        self._labels = stream_host.to_store(np.asarray(
            meta.pad_nodes(ds.onehot_labels()), self._sdtype))
        self._mask = stream_host.to_store(np.asarray(
            meta.pad_nodes(np.asarray(ds.mask), fill=MASK_NONE), np.int32))
        if hasattr(self, "_stores"):
            self._stores[0] = self._store_x

    def _alloc_stores(self):
        """Stores for segment-boundary activations and their cotangents;
        tid 0 aliases the padded feature store.  Activations live in the
        storage dtype; cotangent stores stay fp32 host-side because
        ``np.add.at`` accumulates partial sums there (the bf16 contract
        rounds the *wire*, in ``_fetch``, never the accumulator).  Under
        -stream-spill both move to CRC-headered memmaps on disk — they
        are the stores that scale with model depth, which is what the
        third tier exists to absorb."""
        PS = self._P * self._S
        dims = {}
        for seg in self.segments:
            dims.update(seg.out_dims)
        self._stores = {0: self._store_x}
        self._cots = {}
        self._spill_tids = set()
        for seg in self.segments:
            for t in seg.out_tids:
                if self._spill_dir:
                    self._stores[t] = stream_spill.create_store(
                        os.path.join(self._spill_dir, f"act{t}.spill"),
                        (PS, dims[t]), self._sdtype)
                    self._cots[t] = stream_spill.create_store(
                        os.path.join(self._spill_dir, f"cot{t}.spill"),
                        (PS, dims[t]), np.float32)
                    self._spill_tids.add(t)
                else:
                    self._stores[t] = stream_host.alloc(
                        (PS, dims[t]), self._sdtype)
                    self._cots[t] = stream_host.alloc(
                        (PS, dims[t]), np.float32)

    def _predicted_epoch_xfer_bytes(self) -> int:
        """Analytic bytes ``_fetch`` ships in one training epoch, priced
        by the shared ``segments.predicted_epoch_bytes`` model from the
        live store itemsizes (so bf16 slots and the uint16 edge wire are
        reflected, and the kernel-budget gate prices the same way)."""
        return predicted_epoch_bytes(
            self.segments, self._P, self._S, self._E, self._K,
            self.dataset.num_classes,
            act_itemsize=self._sdtype.itemsize,
            esrc_itemsize=self._esrc.itemsize,
            edst_itemsize=self._edst.itemsize)

    def slot_bytes(self) -> int:
        """Worst-case bytes one device slot holds (table + own rows +
        outputs + edge arrays) — what -stream-budget should be sized to,
        times the ring depth.  Staged inputs ride the storage dtype (and
        the narrow edge wire); compute upcasts are transient and outputs
        accumulate fp32 on device, so outputs price at 4 bytes."""
        S, E, T = self._S, self._E, self._S + self._P * self._K
        ai = self._sdtype.itemsize
        worst = 0
        for seg in self.segments:
            b = E * (self._esrc.itemsize + self._edst.itemsize) + S * 4
            if seg.head is not None:
                b += T * seg.out_dims[seg.table_tid] * ai
            for t in seg.own_in_tids:
                b += S * seg.out_dims[t] * ai
            for t in seg.out_tids:
                b += 2 * S * seg.out_dims[t] * 4  # value + cotangent
            worst = max(worst, b)
        return worst

    def _balance_supported(self) -> bool:
        # The balancer's probe harness reads full per-part edge arrays
        # (trainer.part) and the in-memory CSR; the lux path still
        # supports reshard() itself (re-reading moved byte ranges).
        return isinstance(self.dataset.graph, Csr) \
            and jax.process_count() == 1

    # -- jitted per-segment steps ------------------------------------------

    def _build_steps(self):
        self._fwd = [self._make_fwd(s) for s in self.segments[:-1]]
        self._bwd = [self._make_bwd(s) for s in self.segments]
        self._ev = [self._make_eval(s) for s in self.segments]
        opt = self.optimizer

        @jax.jit
        def update(params, grads, opt_state, alpha, gscale):
            _retrace.note_trace("stream_update")
            # gscale is 1.0 on healthy steps (exact multiply); the chaos
            # harness feeds NaN to exercise the non-finite guard
            grads = jax.tree.map(lambda g: g * gscale, grads)
            return fault.guarded_update(opt, params, grads, opt_state,
                                        alpha)

        self._update = update

    def _make_fwd(self, seg):
        S, outs, name = self._S, seg.out_tids, f"stream_fwd{seg.index}"
        sd = jnp.dtype(self._sdtype)  # boundary outputs ride the wire dtype
        if seg.head is None:
            @jax.jit
            def fwd(params, own, esrc, edst, indeg, key):
                _retrace.note_trace(name)
                vals = run_segment(seg, params, None, _f32d(own),
                                   esrc.astype(jnp.int32),
                                   edst.astype(jnp.int32),
                                   indeg, key, True, S)
                return {t: vals[t].astype(sd) for t in outs}
        else:
            @jax.jit
            def fwd(params, table, own, esrc, edst, indeg, key):
                _retrace.note_trace(name)
                vals = run_segment(seg, params, _f32(table), _f32d(own),
                                   esrc.astype(jnp.int32),
                                   edst.astype(jnp.int32),
                                   indeg, key, True, S)
                return {t: vals[t].astype(sd) for t in outs}
        return fwd

    def _make_bwd(self, seg):
        """Backward step.  Upcasts happen *inside* the differentiated
        function, so we differentiate with respect to the storage-dtype
        table/own inputs: the returned dt/down cotangents come back in
        the storage dtype (halving the device->host scatter pull under
        bf16, one nearest rounding per row), and the host-side fp32
        cotangent stores accumulate the upcast values.  Fetched cots
        upcast to fp32 before seeding the vjp (the primal outs are
        fp32)."""
        S, name = self._S, f"stream_bwd{seg.index}"
        logits_tid = self.model.logits.id
        if seg.is_last:
            if seg.head is None:
                @jax.jit
                def bwd(params, own, esrc, edst, indeg, key, labels, mask):
                    _retrace.note_trace(name)
                    es, ed = esrc.astype(jnp.int32), edst.astype(jnp.int32)
                    lab = _f32(labels)

                    def f(p, ow):
                        vals = run_segment(seg, p, None, _f32d(ow), es, ed,
                                           indeg, key, True, S)
                        return ops.masked_softmax_cross_entropy(
                            vals[logits_tid], lab, mask)

                    loss, (dp, down) = jax.value_and_grad(
                        f, argnums=(0, 1))(params, own)
                    return loss, dp, None, down
            else:
                @jax.jit
                def bwd(params, table, own, esrc, edst, indeg, key,
                        labels, mask):
                    _retrace.note_trace(name)
                    es, ed = esrc.astype(jnp.int32), edst.astype(jnp.int32)
                    lab = _f32(labels)

                    def f(p, tab, ow):
                        vals = run_segment(seg, p, _f32(tab), _f32d(ow),
                                           es, ed, indeg, key, True, S)
                        return ops.masked_softmax_cross_entropy(
                            vals[logits_tid], lab, mask)

                    loss, (dp, dt, down) = jax.value_and_grad(
                        f, argnums=(0, 1, 2))(params, table, own)
                    return loss, dp, dt, down
        else:
            outs = seg.out_tids
            if seg.head is None:
                @jax.jit
                def bwd(params, own, esrc, edst, indeg, key, cots):
                    _retrace.note_trace(name)
                    es, ed = esrc.astype(jnp.int32), edst.astype(jnp.int32)

                    def f(p, ow):
                        vals = run_segment(seg, p, None, _f32d(ow), es, ed,
                                           indeg, key, True, S)
                        return {t: vals[t] for t in outs}

                    _, vjp = jax.vjp(f, params, own)
                    dp, down = vjp(_f32d(cots))
                    return dp, None, down
            else:
                @jax.jit
                def bwd(params, table, own, esrc, edst, indeg, key, cots):
                    _retrace.note_trace(name)
                    es, ed = esrc.astype(jnp.int32), edst.astype(jnp.int32)

                    def f(p, tab, ow):
                        vals = run_segment(seg, p, _f32(tab), _f32d(ow),
                                           es, ed, indeg, key, True, S)
                        return {t: vals[t] for t in outs}

                    _, vjp = jax.vjp(f, params, table, own)
                    dp, dt, down = vjp(_f32d(cots))
                    return dp, dt, down
        return bwd

    def _make_eval(self, seg):
        S, name = self._S, f"stream_eval{seg.index}"
        sd = jnp.dtype(self._sdtype)
        if seg.is_last:
            logits_tid = self.model.logits.id
            if seg.head is None:
                @jax.jit
                def ev(params, own, esrc, edst, indeg, labels, mask):
                    _retrace.note_trace(name)
                    vals = run_segment(seg, params, None, _f32d(own),
                                       esrc.astype(jnp.int32),
                                       edst.astype(jnp.int32),
                                       indeg, None, False, S)
                    logits = vals[logits_tid]
                    return logits, ops.perf_metrics(logits, _f32(labels),
                                                    mask)
            else:
                @jax.jit
                def ev(params, table, own, esrc, edst, indeg, labels, mask):
                    _retrace.note_trace(name)
                    vals = run_segment(seg, params, _f32(table), _f32d(own),
                                       esrc.astype(jnp.int32),
                                       edst.astype(jnp.int32),
                                       indeg, None, False, S)
                    logits = vals[logits_tid]
                    return logits, ops.perf_metrics(logits, _f32(labels),
                                                    mask)
        else:
            outs = seg.out_tids
            if seg.head is None:
                @jax.jit
                def ev(params, own, esrc, edst, indeg):
                    _retrace.note_trace(name)
                    vals = run_segment(seg, params, None, _f32d(own),
                                       esrc.astype(jnp.int32),
                                       edst.astype(jnp.int32),
                                       indeg, None, False, S)
                    return {t: vals[t].astype(sd) for t in outs}
            else:
                @jax.jit
                def ev(params, table, own, esrc, edst, indeg):
                    _retrace.note_trace(name)
                    vals = run_segment(seg, params, _f32(table), _f32d(own),
                                       esrc.astype(jnp.int32),
                                       edst.astype(jnp.int32),
                                       indeg, None, False, S)
                    return {t: vals[t].astype(sd) for t in outs}
        return ev

    # -- host<->device staging ---------------------------------------------

    def _fetch(self, item):
        """Worker-side slot assembly: gather one shard's inputs from the
        host stores and ship them.  Runs on the ring's prefetch thread,
        overlapped with the previous shard's compute."""
        phase, k, i = item
        seg = self.segments[k]
        S = self._S
        lo = i * S
        a = {"esrc": self._esrc[i], "edst": self._edst[i],
             "indeg": self._indeg[i]}
        if seg.head is not None:
            tid = seg.table_tid
            with obs.span("stream_gather", seg=k, shard=i) as gsp:
                a["table"] = self._stores[tid][self._tbl_idx[i]]
            if tid in self._spill_tids:
                # the fancy-index gather above just paged the table rows
                # off the spill memmap; attribute it to the spill tier
                self._spill_read_s += gsp.dur_s
                self._spill_read_bytes += a["table"].nbytes
        a["own"] = {t: self._pull_rows(self._stores[t], t, lo, i)
                    for t in seg.own_in_tids}
        if phase != "eval":
            a["key"] = self._keys[i]
        if seg.is_last:
            a["labels"] = self._labels[lo:lo + S]
            a["mask"] = self._mask[lo:lo + S]
        if phase == "bwd" and not seg.is_last:
            # the cotangent wire rides the storage dtype: one nearest
            # rounding per row here, fp32 accumulation left behind in the
            # host store
            a["cots"] = {t: self._pull_rows(self._cots[t], t, lo, i,
                                            out_dtype=self._sdtype)
                         for t in seg.out_tids}
        self._xfer_bytes += sum(
            getattr(v, "nbytes", 0) for v in jax.tree_util.tree_leaves(a))
        with obs.span("stream_transfer", seg=k, shard=i):
            fault.point("stream.device_put")  # chaos site: a transient
            a = jax.device_put(a)             # h2d failure is retried by
            jax.block_until_ready(a)          # the ring's fetch wrapper
        return a

    def _pull_rows(self, store, tid, lo, shard, out_dtype=None):
        """One shard's rows from a host or spill store, on the ring's
        worker.  RAM-tier same-dtype pulls ship the store view directly
        (zero copy — the pinned allocator is what makes that DMA-able);
        spill-tier pulls force the disk read here, under their own span,
        so the prefetch overlap of the third tier is measured honestly
        rather than smeared into device_put."""
        view = store[lo:lo + self._S]
        dt = np.dtype(out_dtype) if out_dtype is not None else view.dtype
        if tid in self._spill_tids:
            with obs.span("stream_spill_read", tid=tid, shard=shard) as sp:
                out = np.array(view, dtype=dt)  # copy=True: page it in now
            self._spill_read_s += sp.dur_s
            self._spill_read_bytes += out.nbytes
            return out
        return np.asarray(view, dt) if dt != view.dtype else view

    def _sweep(self, phase, k, consume):
        """Rotate all P shards of one (phase, segment) sweep through the
        slots.  Prefetch never crosses the sweep boundary: the next
        sweep's inputs include stores this sweep is still writing."""
        ring = self._ring
        items = [(phase, k, i) for i in range(self._P)]
        for j, it in enumerate(items):
            for nxt in items[j:j + ring.num_slots]:
                if not ring.ensure(nxt):
                    break
            a = ring.wait(it)
            with obs.span("stream_rotate", phase=phase, seg=k, shard=it[2]):
                consume(it[2], a)

    def _write_outs(self, i, outs):
        """Persist one shard's boundary outputs.  The device already
        rounded them to the storage dtype, so the store assignment is an
        exact copy; spill-tier writes get their own span (they block the
        consumer, which is what the spill-stall watchdog signal keys on)."""
        lo = i * self._S
        outs = jax.device_get(outs)
        spilled = [t for t in outs if t in self._spill_tids]
        for t, arr in outs.items():
            if t not in self._spill_tids:
                self._stores[t][lo:lo + self._S] = arr
        if spilled:
            with obs.span("stream_spill_write", shard=i,
                          tids=len(spilled)) as sp:
                for t in spilled:
                    self._stores[t][lo:lo + self._S] = outs[t]
            self._spill_write_s += sp.dur_s
            self._spill_write_bytes += sum(outs[t].nbytes for t in spilled)

    def _scatter_table(self, seg, i, dt):
        cot = self._cots.get(seg.table_tid)
        if cot is None:  # the table was the input features; nothing upstream
            return
        np.add.at(cot, self._tbl_idx[i], np.asarray(dt))

    def _scatter_own(self, seg, i, down):
        lo = i * self._S
        for t, arr in (down or {}).items():
            cot = self._cots.get(t)
            if cot is not None:
                cot[lo:lo + self._S] += np.asarray(arr)

    def _scatter_async(self, seg, i, dt, down):
        """Queue shard i's cotangent scatter on the ring's worker so the
        device→host pull and ``np.add.at`` overlap the next shard's
        compute.  The d2h pulls (``np.asarray``) run on the worker under
        a bounded retry — and ONLY the pulls: the mutating ``np.add.at``
        / ``+=`` into the shared cotangent stores runs exactly once after
        the pulls succeed, so a retried attempt can never double-count."""
        def work():
            def _pull():
                fault.point("stream.scatter")
                # pulls come back in the storage dtype (bf16 halves the
                # d2h wire); upcast here so the fp32 host accumulators
                # never see a rounded partial sum
                dt_h = None if dt is None else np.asarray(dt, np.float32)
                down_h = {t: np.asarray(arr, np.float32)
                          for t, arr in (down or {}).items()}
                return dt_h, down_h
            with obs.span("stream_scatter", seg=seg.index, shard=i) as sp:
                dt_h, down_h = fault.retrying(
                    "stream.scatter", _pull,
                    retry_on=(OSError, RuntimeError))
                if dt_h is not None:
                    self._scatter_table(seg, i, dt_h)
                self._scatter_own(seg, i, down_h)
            self._scatter_s += sp.dur_s
        self._scatter_futs.append(self._ring.submit(work))

    def _drain_scatters(self):
        """Block until queued scatters land; called before any sweep whose
        fetches read ``self._cots`` and before the epoch-end update.  Only
        time blocked on still-running scatters counts against overlap;
        worker exceptions re-raise here either way."""
        futs, self._scatter_futs = self._scatter_futs, []
        if not futs:
            return
        if not futs[-1].done():
            with obs.span("stream_scatter_wait", pending=len(futs)) as sp:
                for f in futs:
                    f.result()
            self._scatter_wait_s += sp.dur_s
        else:
            for f in futs:
                f.result()

    # -- epoch execution ---------------------------------------------------

    def _run_step(self, step_key, alpha):
        P, n = self._P, self._nseg
        ring = self._ring
        ring.reset_epoch_stats()
        self._xfer_bytes = 0
        self._scatter_s = 0.0
        self._scatter_wait_s = 0.0
        self._spill_read_s = 0.0
        self._spill_write_s = 0.0
        self._spill_read_bytes = 0
        self._spill_write_bytes = 0
        self._keys = [jax.random.fold_in(step_key, i) for i in range(P)]
        for c in self._cots.values():
            c[:] = 0.0
        self._grad_acc = None
        loss_parts = []

        with obs.span("stream_epoch", parts=P, segments=n) as sp:
            for k in range(n - 1):
                self._sweep("fwd", k, self._consume_fwd(k))
            for k in range(n - 1, -1, -1):
                # the cots this sweep fetches are written by the previous
                # sweep's scatters; FIFO on the worker already orders them
                # ahead of this sweep's fetches, the drain makes it explicit
                # (and surfaces worker exceptions at a defined point)
                self._drain_scatters()
                self._sweep("bwd", k, self._consume_bwd(k, loss_parts))
            self._drain_scatters()
            (self.params, self.opt_state, self._last_nonfinite,
             self._last_gnorm) = self._update(
                self.params, self._grad_acc, self.opt_state, alpha,
                fault.nan_scale())
            loss = jnp.sum(jnp.stack(loss_parts))
        self._note_epoch_stats(sp.dur_s)
        return loss

    def _consume_fwd(self, k):
        seg, fn = self.segments[k], self._fwd[k]

        def consume(i, a):
            if seg.head is None:
                outs = fn(self.params, a["own"], a["esrc"], a["edst"],
                          a["indeg"], a["key"])
            else:
                outs = fn(self.params, a["table"], a["own"], a["esrc"],
                          a["edst"], a["indeg"], a["key"])
            self._write_outs(i, outs)

        return consume

    def _consume_bwd(self, k, loss_parts):
        seg, fn = self.segments[k], self._bwd[k]

        def consume(i, a):
            if seg.is_last:
                tail = (a["key"], a["labels"], a["mask"])
            else:
                tail = (a["key"], a["cots"])
            if seg.head is None:
                out = fn(self.params, a["own"], a["esrc"], a["edst"],
                         a["indeg"], *tail)
            else:
                out = fn(self.params, a["table"], a["own"], a["esrc"],
                         a["edst"], a["indeg"], *tail)
            if seg.is_last:
                loss, dp, dt, down = out
                loss_parts.append(loss)
            else:
                dp, dt, down = out
            self._grad_acc = dp if self._grad_acc is None else \
                _tree_map(jnp.add, self._grad_acc, dp)
            if dt is not None or down:
                self._scatter_async(seg, i, dt, down)

        return consume

    def _note_epoch_stats(self, wall_s):
        st = self._ring.epoch_stats()
        wall = max(float(wall_s), 1e-12)
        scat_overlap = 1.0 - self._scatter_wait_s / max(self._scatter_s,
                                                        1e-12)
        self._last_stream_stats = {
            "stream_stall_s": round(st["stall_s"], 6),
            "stream_transfer_s": round(st["transfer_s"], 6),
            "stream_overlap_frac": round(st["overlap_frac"], 4),
            "stream_stall_frac": round(min(st["stall_s"] / wall, 1.0), 4),
            "stream_bytes": int(self._xfer_bytes),
            "stream_scatter_s": round(self._scatter_s, 6),
            "stream_scatter_overlap_frac": round(
                min(max(scat_overlap, 0.0), 1.0), 4),
        }
        if self._spill_dir:
            # spill reads overlap via the ring (they run in _fetch on the
            # worker); writes block the consumer, so the write fraction of
            # wall time is the stall signal the watchdog tracks
            self._last_stream_stats.update({
                "stream_spill_read_s": round(self._spill_read_s, 6),
                "stream_spill_write_s": round(self._spill_write_s, 6),
                "stream_spill_bytes": int(self._spill_read_bytes
                                          + self._spill_write_bytes),
                "stream_spill_stall_frac": round(
                    min(self._spill_write_s / wall, 1.0), 4),
            })
        self._epoch_stream.append(
            dict(self._last_stream_stats, epoch=int(self.epoch)))
        led = obs.get_ledger()
        wk = getattr(self, "_wire_key", None)
        if led.attached and wk is not None:
            # the epoch's measured ring overlap against the _setup
            # prediction; wire bytes pair in driver._obs_epoch off the
            # metrics channel
            led.measure("overlap_frac", wk, st["overlap_frac"], "frac",
                        epoch=int(self.epoch))
            led.measure("stream_xfer_s", wk, st["transfer_s"], "s",
                        epoch=int(self.epoch))
        if self._metrics is not None and self._grad_acc is not None:
            from roc_tpu.obs import channel as obs_channel
            self._last_step_metrics = {
                "grad_norm": obs_channel.global_norm(self._grad_acc),
                "param_norm": obs_channel.global_norm(self.params),
                # for the stream executor the wire is the host<->device one
                "wire_bytes": jnp.float32(self._xfer_bytes),
                "edges": self._edges_valid,
            }

    def _obs_epoch_extra(self, epoch):
        """Streamed-epoch fields merged into the shared obs JSONL record
        (driver._obs_epoch); stall_frac also feeds the watchdog's
        stream-stall EWMA."""
        del epoch
        return dict(self._last_stream_stats) \
            if self._last_stream_stats else None

    def stream_stats(self):
        """Bench-artifact summary: ring geometry + per-epoch overlap."""
        return dict(self._last_stream_stats or {},
                    slots=int(self.config.stream_slots),
                    num_parts=self._P, segments=self._nseg,
                    halo_width=self._K, slot_bytes=self.slot_bytes(),
                    stream_dtype="bf16" if self._sdtype.itemsize == 2
                    else "fp32",
                    stream_spill=self._spill_dir,
                    host_stores=stream_host.stats(),
                    epochs=list(self._epoch_stream))

    # -- eval / inference --------------------------------------------------

    def evaluate(self):
        n = self._nseg
        for k in range(n - 1):
            self._sweep("eval", k, self._consume_eval_mid(k))
        acc = []
        self._sweep("eval", n - 1, self._consume_eval_last(acc))
        tot = acc[0]
        for m in acc[1:]:
            tot = _tree_map(jnp.add, tot, m)
        return tot

    def _consume_eval_mid(self, k):
        seg, fn = self.segments[k], self._ev[k]

        def consume(i, a):
            if seg.head is None:
                outs = fn(self.params, a["own"], a["esrc"], a["edst"],
                          a["indeg"])
            else:
                outs = fn(self.params, a["table"], a["own"], a["esrc"],
                          a["edst"], a["indeg"])
            self._write_outs(i, outs)

        return consume

    def _consume_eval_last(self, acc):
        seg, fn = self.segments[-1], self._ev[-1]

        def consume(i, a):
            if seg.head is None:
                logits, m = fn(self.params, a["own"], a["esrc"], a["edst"],
                               a["indeg"], a["labels"], a["mask"])
            else:
                logits, m = fn(self.params, a["table"], a["own"], a["esrc"],
                               a["edst"], a["indeg"], a["labels"], a["mask"])
            acc.append(m)
            if self._logits_sink is not None:
                lo = i * self._S
                self._logits_sink[lo:lo + self._S] = np.asarray(logits)

        return consume

    def predict_logits(self):
        """Padded [P*S, C] logits (shard-major, same convention as the
        SPMD path; ``self._meta.unpad_nodes`` strips the padding)."""
        # d2h sink: filled from device_get results, never staged back
        self._logits_sink = np.zeros(  # roclint: allow(unpinned-host-buffer) — device->host sink, never ships
            (self._P * self._S, self.dataset.num_classes), np.float32)
        try:
            self.evaluate()
            return jnp.asarray(self._logits_sink)
        finally:
            self._logits_sink = None

    # -- resharding (balancer hook) ----------------------------------------

    def reshard(self, new_bounds) -> float:
        """Apply a balancer cut under the frozen slot shapes.  Under
        streaming this is pure host work: re-cut (or re-read, on the .lux
        path) the moved byte ranges and rebuild the table maps; no step
        recompiles (same padded shapes, same frozen halo K)."""
        bounds = np.asarray(new_bounds, np.int64)
        with obs.span("reshard", parts=self._P, mode="stream") as sp:
            if self._lux_path:
                meta = shard_load.meta_from_lux(
                    self._lux_path, self._P, bounds=bounds,
                    shard_nodes=self._S, shard_edges=self._E)
                edge_src, edge_dst, indeg = self._load_lux_shards(meta)
            else:
                self.part = partition_graph(
                    self.dataset.graph, self._P, bounds=bounds,
                    shard_nodes=self._S, shard_edges=self._E)
                meta = self.part.meta
                edge_src = np.asarray(self.part.edge_src)
                edge_dst = np.asarray(self.part.edge_dst)
                indeg = np.asarray(self.part.in_degree, np.float32)
            if self._lux_path:
                self.part = meta
            self._install_graph(meta, edge_src, edge_dst, indeg,
                                K_force=self._K)
        return sp.dur_s
