"""Elementwise binary ops (the reference's Element op).

ElementType ADD/MUL (gnn.h:88-91; op_kernel element_kernel.cu:19-39).  ADD is
what the residual path uses (gnn.cc:86-90).  The reference's MUL backward is
unimplemented (`assert(false)`, element_kernel.cu:102-104); ours comes from
autodiff, so MUL is fully supported here.
"""


def add(a, b):
    return a + b


def mul(a, b):
    return a * b
