"""Autotuner (roc_tpu/tune) acceptance pins — ISSUE round 12.

What this file proves, in dependency order:

  * the candidate lattice is deterministic, sorted, and admissible-only;
  * the tuned store round-trips, validates, and rejects garbage;
  * two identical CPU sweeps write BYTE-IDENTICAL tuned.json files (the
    seeded-surrogate closed-world contract);
  * ``choose_geometry`` consumes a tuned entry at the swept graphs
    (every swept shape — deterministic, so the >=90% policy bar is met
    at 100%), falls back to the analytic model off-key and for an
    unswept variant, and the tuned pick changes NOTHING numerically
    (output parity vs the analytic plan and segment_sum);
  * swapping a tuned geometry in under the same content key costs ZERO
    retraces (the plan is a pytree with static schedule fields — a
    rebuilt identical plan must hit the jit cache);
  * plan-cache hygiene both orders: plan cached first then a tuned
    entry appears, and tuned entry first then a stale explicit geometry
    — both warn once and build the tuned winner; tuned_ok=False is the
    forced-A/B escape that builds exactly what was asked;
  * refit recovers the generating surrogate constants within 5% from
    the sweep's own trial records (TrialRecord path) AND from raw
    ledger-style dicts (JSONL path), and update_budgets refuses to
    commit an interpret table as rates (measured_calibration contract);
  * surrogate.analytic_seconds is a faithful mirror of binned's
    _binned_cost_model at default constants.

The sweep runs ONCE per session (module fixture) at two small synthetic
shapes; everything downstream shares its entries/trials.
"""

import json
import warnings

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

import roc_tpu.ops.pallas.binned as B  # noqa: E402
from roc_tpu.tune import lattice, refit, search, store  # noqa: E402
from roc_tpu.tune import surrogate as S  # noqa: E402

# two CI-sized synthetic graphs = the policy test's "grid"
_SHAPE_SPECS = [("mega_shard_scaled", 1024, 8192, 2),
                ("tiny", 512, 4096, 3)]


@pytest.fixture(autouse=True)
def _fresh_store_cache():
    """The store memoizes per (path, mtime) and warns once per key;
    tests monkeypatch env paths, so both caches must reset around each
    test or a prior test's warn-once eats this test's warning."""
    store.clear_cache()
    yield
    store.clear_cache()


@pytest.fixture(scope="module")
def swept():
    """One surrogate sweep over the test grid, shared by every
    consumer: (shapes, entries, trials)."""
    shapes = [search.synth_shape(*spec) for spec in _SHAPE_SPECS]
    entries, trials = search.sweep(shapes, seed=0)
    return shapes, entries, trials


def _winner(shapes, entries, i=0, vkey="fp32"):
    sh = shapes[i]
    gkey = store.graph_key(sh.edge_src, sh.edge_dst, sh.num_rows,
                           sh.table_rows)
    return sh, B.Geometry(*entries[gkey][vkey]["geom"])


# ---------------------------------------------------------------- lattice

def test_lattice_deterministic_sorted_admissible():
    a = lattice.candidate_lattice()
    b = lattice.candidate_lattice()
    assert a == b
    assert [c.label for c in a] == sorted(c.label for c in a)
    assert len({c.label for c in a}) == len(a)      # labels are keys
    for c in a:
        c.geom.check()                               # admissible only
        assert B._vmem_bytes(c.geom) <= B._VMEM_BUDGET
    # bf16 storage adds the 16-row-unit flat family
    bf = lattice.candidate_lattice("bf16")
    assert any(c.geom.unit == 16 for c in bf)
    assert not any(c.geom.unit == 16 for c in a)


def test_refit_probes_admissible_and_not_mac_bound():
    probes = search.refit_probes()
    assert len(probes) >= 5
    for cfg in probes:
        # linear pricing is the whole point of the designed experiment
        assert cfg.geom.ch * cfg.geom.sb * B._MODEL_H * 2 \
            / B._MXU_EFF_FLOPS < B._CHUNK_OVERHEAD_S
    assert any(cfg.geom.flat for cfg in probes)      # flat_dma_s column


# ------------------------------------------------------------------ store

def test_store_roundtrip_and_validation(tmp_path):
    p = str(tmp_path / "tuned.json")
    doc = {"version": store.VERSION, "interpret": True, "seed": 0,
           "entries": {"rows=8|table_rows=8|edges=1|sha=00": {
               "fp32": {"geom": list(B.GEOM_MID), "knobs": {},
                        "modeled_s": 1e-3, "trial_s": 1.1e-3,
                        "source": "surrogate"}}}}
    assert store.validate_store(doc) == []
    store.save_store(p, doc)
    assert store.load_store(p) == doc
    # negatives: each corruption must be named, and save must refuse
    bad = json.loads(json.dumps(doc))
    bad["version"] = 99
    assert store.validate_store(bad)
    bad = json.loads(json.dumps(doc))
    bad["entries"]["rows=8|table_rows=8|edges=1|sha=00"]["fp32"]["geom"] \
        = [1, 2]
    assert store.validate_store(bad)
    with pytest.raises(ValueError):
        store.save_store(p, bad)
    bad = json.loads(json.dumps(doc))
    bad["entries"]["rows=8|table_rows=8|edges=1|sha=00"]["fp32"][
        "source"] = "vibes"
    assert store.validate_store(bad)
    assert store.validate_store("not a dict")
    # unreadable/absent files read as "no store", never raise
    assert store.load_store(str(tmp_path / "absent.json")) is None
    (tmp_path / "torn.json").write_text("{")
    assert store.load_store(str(tmp_path / "torn.json")) is None


def test_tuned_store_path_env(tmp_path, monkeypatch):
    monkeypatch.setenv("ROC_PLAN_CACHE_DIR", str(tmp_path))
    monkeypatch.delenv("ROC_NO_TUNED", raising=False)
    monkeypatch.delenv("ROC_TUNED_PATH", raising=False)
    assert store.tuned_store_path() == str(tmp_path / "tuned.json")
    monkeypatch.setenv("ROC_TUNED_PATH", str(tmp_path / "elsewhere.json"))
    assert store.tuned_store_path() == str(tmp_path / "elsewhere.json")
    monkeypatch.setenv("ROC_NO_TUNED", "1")
    assert store.tuned_store_path() == ""
    monkeypatch.delenv("ROC_NO_TUNED")
    monkeypatch.delenv("ROC_TUNED_PATH")
    monkeypatch.setenv("ROC_PLAN_CACHE", "0")
    assert store.tuned_store_path() == ""


# ------------------------------------------------------------ determinism

def test_sweep_byte_identical(tmp_path, swept):
    """Same seed, same shapes -> byte-identical tuned.json (acceptance:
    the CI surrogate is a closed deterministic world)."""
    shapes, entries, _ = swept
    entries2, _ = search.sweep(
        [search.synth_shape(*spec) for spec in _SHAPE_SPECS], seed=0)
    pa, pb = str(tmp_path / "a.json"), str(tmp_path / "b.json")
    store.merge_entries(pa, entries, interpret=True, seed=0)
    store.merge_entries(pb, entries2, interpret=True, seed=0)
    ba = open(pa, "rb").read()
    assert ba == open(pb, "rb").read()
    assert len(ba) > 0
    # and a different seed draws different surrogate noise: the
    # recorded trial timings must move even if the winner holds
    entries3, _ = search.sweep(
        [search.synth_shape(*_SHAPE_SPECS[0])], seed=7)
    (gkey,) = entries3
    assert entries3[gkey]["fp32"]["trial_s"] \
        != entries[gkey]["fp32"]["trial_s"]


# ----------------------------------------------------------- tuned policy

def test_choose_geometry_tuned_policy_grid(tmp_path, monkeypatch, swept):
    """With tuned.json present, choose_geometry returns the stored
    winner at EVERY swept shape (>= the 90% policy bar) and provably
    stays analytic off-key and for the unswept bf16 variant."""
    shapes, entries, _ = swept
    p = str(tmp_path / "tuned.json")
    store.merge_entries(p, entries, interpret=True, seed=0)
    monkeypatch.setenv("ROC_TUNED_PATH", p)
    monkeypatch.delenv("ROC_NO_TUNED", raising=False)
    hits = 0
    for i in range(len(shapes)):
        sh, win = _winner(shapes, entries, i)
        g, t = B.choose_geometry(sh.edge_src, sh.edge_dst, sh.num_rows,
                                 sh.table_rows)
        assert np.isfinite(t) and t > 0
        hits += tuple(g) == tuple(win)
    assert hits / len(shapes) >= 0.9, (hits, len(shapes))
    # off-key graph / unswept variant: the tuned tier must NOT engage
    monkeypatch.setattr(
        B, "_priced_tuned",
        lambda *a, **k: pytest.fail("tuned tier engaged off-key"))
    other = search.synth_shape("other", 2048, 4096, 7)
    B.choose_geometry(other.edge_src, other.edge_dst, other.num_rows,
                      other.table_rows)
    sh = shapes[0]
    B.choose_geometry(sh.edge_src, sh.edge_dst, sh.num_rows,
                      sh.table_rows, storage_dtype="bf16")
    # explicit candidate lists (forced A/Bs) never consult the tier
    g, _ = B.choose_geometry(sh.edge_src, sh.edge_dst, sh.num_rows,
                             sh.table_rows, candidates=[B.GEOM_MID],
                             force=True)
    assert tuple(g) == tuple(B.GEOM_MID)
    # kill switch
    monkeypatch.setenv("ROC_NO_TUNED", "1")
    B.choose_geometry(sh.edge_src, sh.edge_dst, sh.num_rows,
                      sh.table_rows)


def test_tuned_parity_and_zero_retrace(tmp_path, monkeypatch, swept):
    """The tuned pick is a SCHEDULE choice, not a numeric one: its plan
    reproduces segment_sum exactly as the analytic plan does.  And a
    rebuild under the same content key — the reshard that swaps the
    tuned geometry in — costs zero retraces: the plan is a pytree with
    static schedule fields, so an identical rebuilt plan must hit the
    jit cache."""
    shapes, entries, _ = swept
    sh, win = _winner(shapes, entries)
    p = str(tmp_path / "tuned.json")
    store.merge_entries(p, entries, interpret=True, seed=0)
    monkeypatch.setenv("ROC_TUNED_PATH", p)
    monkeypatch.delenv("ROC_NO_TUNED", raising=False)

    n, h = sh.num_rows, 16
    x = jnp.asarray(np.random.default_rng(0)
                    .standard_normal((n, h), dtype=np.float32))
    ref = jax.ops.segment_sum(x[sh.edge_src], jnp.asarray(sh.edge_dst),
                              num_segments=n)

    plan = B.build_binned_plan(sh.edge_src, sh.edge_dst, n, n)
    assert tuple(plan.geom) == tuple(win)

    traces = []

    def _step(v, pl):
        traces.append(1)
        return B.run_binned(v, pl, True, precision="exact")

    step = jax.jit(_step)
    out = step(x, plan)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-4)
    assert len(traces) == 1
    # reshard: rebuild under the same content key -> identical plan,
    # zero new traces
    plan2 = B.build_binned_plan(sh.edge_src, sh.edge_dst, n, n)
    assert tuple(plan2.geom) == tuple(win)
    out2 = step(x, plan2)
    assert len(traces) == 1, "tuned-geometry rebuild retraced"
    np.testing.assert_allclose(np.asarray(out2), np.asarray(out))
    # parity against the analytic pick (tuned tier off)
    monkeypatch.setenv("ROC_NO_TUNED", "1")
    plan_an = B.build_binned_plan(sh.edge_src, sh.edge_dst, n, n)
    out_an = jax.jit(
        lambda v: B.run_binned(v, plan_an, True, precision="exact"))(x)
    np.testing.assert_allclose(np.asarray(out_an), np.asarray(ref),
                               rtol=1e-5, atol=1e-4)


# ------------------------------------------------------ plan-cache hygiene

def _stale_preset(win):
    for g in (B.GEOM_MID, B.GEOM_SPARSE, B.GEOM_WIDE):
        if tuple(g) != tuple(win):
            return g
    raise AssertionError("no preset differs from the winner")


def test_plan_cache_hygiene_plan_first(tmp_path, monkeypatch, swept):
    """Order A: a plan is cached BEFORE the tuned entry exists.  When
    the store appears, the next build of the stale geometry warns once
    and builds (and caches) the tuned winner instead."""
    shapes, entries, _ = swept
    sh, win = _winner(shapes, entries)
    stale = _stale_preset(win)
    monkeypatch.setenv("ROC_PLAN_CACHE_DIR", str(tmp_path / "plans"))
    monkeypatch.setenv("ROC_PLAN_CACHE_MIN_EDGES", "0")
    monkeypatch.setenv("ROC_TUNED_PATH", str(tmp_path / "tuned.json"))
    monkeypatch.setenv("ROC_NO_TUNED", "1")   # pre-tuner era
    p0 = B.build_binned_plan(sh.edge_src, sh.edge_dst, sh.num_rows,
                             sh.table_rows, geom=stale)
    assert tuple(p0.geom) == tuple(stale)
    # the tuner runs; the store appears
    monkeypatch.delenv("ROC_NO_TUNED")
    store.merge_entries(str(tmp_path / "tuned.json"), entries,
                        interpret=True, seed=0)
    with pytest.warns(UserWarning, match="disagrees with the tuned"):
        p1 = B.build_binned_plan(sh.edge_src, sh.edge_dst, sh.num_rows,
                                 sh.table_rows, geom=stale)
    assert tuple(p1.geom) == tuple(win)
    # warn-once: the second stale request swaps silently
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        p2 = B.build_binned_plan(sh.edge_src, sh.edge_dst, sh.num_rows,
                                 sh.table_rows, geom=stale)
    assert tuple(p2.geom) == tuple(win)
    assert not [w for w in rec if "disagrees" in str(w.message)]


def test_plan_cache_hygiene_tuned_first(tmp_path, monkeypatch, swept):
    """Order B: the tuned entry exists BEFORE any plan is cached.  An
    explicit stale geometry yields (with the warning); tuned_ok=False
    is the forced-A/B escape and builds exactly what was asked; a
    request that already matches the winner is silent."""
    shapes, entries, _ = swept
    sh, win = _winner(shapes, entries)
    stale = _stale_preset(win)
    monkeypatch.setenv("ROC_PLAN_CACHE_DIR", str(tmp_path / "plans"))
    monkeypatch.setenv("ROC_PLAN_CACHE_MIN_EDGES", "0")
    monkeypatch.setenv("ROC_TUNED_PATH", str(tmp_path / "tuned.json"))
    monkeypatch.delenv("ROC_NO_TUNED", raising=False)
    store.merge_entries(str(tmp_path / "tuned.json"), entries,
                        interpret=True, seed=0)
    with pytest.warns(UserWarning, match="disagrees with the tuned"):
        p1 = B.build_binned_plan(sh.edge_src, sh.edge_dst, sh.num_rows,
                                 sh.table_rows, geom=stale)
    assert tuple(p1.geom) == tuple(win)
    # forced A/B escape
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        pf = B.build_binned_plan(sh.edge_src, sh.edge_dst, sh.num_rows,
                                 sh.table_rows, geom=stale,
                                 tuned_ok=False)
    assert tuple(pf.geom) == tuple(stale)
    assert not [w for w in rec if "disagrees" in str(w.message)]
    # agreeing request: no warning, no swap needed
    store.clear_cache()
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        pw = B.build_binned_plan(sh.edge_src, sh.edge_dst, sh.num_rows,
                                 sh.table_rows, geom=win)
    assert tuple(pw.geom) == tuple(win)
    assert not [w for w in rec if "disagrees" in str(w.message)]


# ------------------------------------------------------------------ refit

def test_refit_recovers_constants(swept):
    """Acceptance: the refit's rates land within 5% of the generating
    surrogate constants on the CI sweep's own records."""
    _, _, trials = swept
    out = refit.refit_rates(trials)
    assert out["n_agg"] > 0 and out["n_mm"] > 0
    for name, ratio in out["vs_constants"].items():
        assert abs(ratio - 1.0) <= 0.05, (name, ratio, out)


def test_refit_from_ledger_dicts(swept):
    """The JSONL path: raw ledger measurement dicts (model + schedule
    extras) refit to the same rates as the TrialRecords they mirror."""
    _, _, trials = swept
    dicts = []
    for tr in trials:
        model = {"trial": "tune_trial", "confirm": "tune_confirm",
                 "probe": "tune_probe",
                 "matmul": "tune_trial"}[tr.stage]
        dicts.append({"model": model, "value": tr.trial_s,
                      "steps": tr.steps, "dma_units": tr.dma_units,
                      "flat": int(tr.geom[7]) if len(tr.geom) > 7 else 0,
                      "mac_bound": tr.mac_bound,
                      "default_knobs": tr.default_knobs,
                      "matmul": tr.stage == "matmul",
                      "stage": tr.stage, "variant": tr.variant,
                      "shape": tr.shape})
    a = refit.refit_rates(trials)
    b = refit.refit_rates(dicts)
    for k in ("chunk_s", "slot_dma_s", "flat_dma_s", "mm_chunk_s"):
        if a[k] is None:
            assert b[k] is None
        else:
            np.testing.assert_allclose(b[k], a[k], rtol=1e-9)
    # records without schedule facts are skipped, not crashed on
    assert refit.refit_rates([{"model": "geom_time", "value": 1.0}]
                             )["n_agg"] == 0


def test_update_budgets_refuses_interpret(tmp_path, swept):
    """The measured_calibration contract: interpret timings never
    become rate tables."""
    _, _, trials = swept
    table = refit.to_measured_table(trials, interpret=True,
                                    platform="cpu")
    with pytest.raises(SystemExit):
        refit.update_budgets(table, path=str(tmp_path / "budgets.json"))
    # the device path commits and measured_calibration-style readers
    # can see the rows
    dev = refit.to_measured_table(trials, interpret=False,
                                  platform="tpu")
    p = str(tmp_path / "budgets.json")
    refit.update_budgets(dev, path=p)
    with open(p, encoding="utf-8") as f:
        doc = json.load(f)
    assert doc["measured"]["interpret"] is False
    assert doc["measured"]["shapes"]


def test_measure_seconds_refuses_cpu(swept):
    """Hardware trials refuse to run on interpret backends — the same
    refusal measured_calibration enforces on its input tables."""
    shapes, _, _ = swept
    sh = shapes[0]
    cfg = lattice.KernelConfig(geom=B.GEOM_MID)
    with pytest.raises(SystemExit, match="refusing"):
        S.measure_seconds(cfg, sh.edge_src, sh.edge_dst, sh.num_rows,
                          sh.table_rows)


# -------------------------------------------------------------- surrogate

def test_analytic_seconds_mirrors_cost_model(monkeypatch):
    """surrogate.analytic_seconds at default constants must equal
    binned._binned_cost_model (measured tables off) — the property that
    makes the refit's recovered rates commensurable with the shipped
    constants."""
    monkeypatch.setenv("ROC_NO_MEASURED_CAL", "1")
    for geom in (B.GEOM_MID, B.GEOM_SPARSE, B.GEOM_FLAT,
                 B.GEOM_FLAT_SPARSE, B.GEOM_WIDE):
        for padded, s1, s2 in ((1 << 16, 40, 20), (1 << 20, 700, 350)):
            np.testing.assert_allclose(
                S.analytic_seconds(padded, geom, s1, s2),
                B._binned_cost_model(padded, geom, steps1=s1, steps2=s2),
                rtol=1e-12, err_msg=str(tuple(geom)))


def test_noise_is_deterministic_and_bounded():
    e1 = S.noise_eps(0, "trial", "some-label")
    e2 = S.noise_eps(0, "trial", "some-label")
    assert e1 == e2
    assert abs(e1) <= S.NOISE
    assert S.noise_eps(1, "trial", "some-label") != e1
    assert S.noise_eps(0, "confirm", "some-label") != e1
