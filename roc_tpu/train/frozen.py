"""Frozen-parameter loading, shared by eval and serve.

Before this module, checkpoint loading was duplicated per driver mode:
`BaseTrainer.restore` rebuilt params *and* optimizer state through a full
trainer, the `-stream` path grew its own gdata-less restore, and anything
that only wanted a forward pass (eval tooling, now the serving engine)
had to construct a throwaway trainer to get one.  `load_frozen` is the
one entry point: checkpoint + plan cache in, a `FrozenBundle` out —
params restored (weights only, no optimizer arrays), graph data built
through the SAME backend-resolution policy as training
(`driver.effective_backend`), plans pulled from the content-keyed plan
cache (a warm cache means ZERO plan rebuilds — the serve cold-start
contract, pinned in tests/test_serve.py).

Graphs that don't fit in-core keep working: under `config.stream` the
bundle wraps the streaming executor's slot machinery instead of a
resident DenseGraphData, and `predict_logits` sweeps shards through the
frozen padded slots exactly as streamed eval does.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp

from roc_tpu.graph.datasets import Dataset
from roc_tpu.models.model import Model
from roc_tpu.train import checkpoint
from roc_tpu.train.config import Config


@dataclasses.dataclass
class FrozenBundle:
    """Everything a forward-only consumer needs, loaded exactly once.

    ``gdata`` is a resident DenseGraphData on the in-core path and None
    under streaming, where ``stream_trainer`` holds the slot machinery
    instead.  ``params`` are device-resident (placed via device_put at
    load) and never updated — the serving engine treats them as frozen
    donated buffers for the lifetime of the process.
    """

    config: Config
    dataset: Dataset
    model: Model
    params: object
    x: Optional[jnp.ndarray]
    gdata: object
    num_nodes: int
    megafuse: bool
    stream_trainer: object = None
    _logits_jit: object = dataclasses.field(default=None, repr=False)

    def predict_logits(self):
        """Full-graph logits [N, C] in global node order — the parity
        oracle served queries are gated against (tests/test_serve.py).
        Jitted with the same program as the trainer's logits_step, so
        eval and serve run byte-identical forwards."""
        if self.stream_trainer is not None:
            tr = self.stream_trainer
            padded = tr.predict_logits()
            import numpy as np
            return jnp.asarray(tr._meta.unpad_nodes(np.asarray(padded)))
        if self._logits_jit is None:
            from roc_tpu.analysis import retrace as _retrace
            from roc_tpu.train.driver import make_gctx
            model, n, mega = self.model, self.num_nodes, self.megafuse

            @jax.jit
            def frozen_logits(params, x, gdata):
                _retrace.note_trace("frozen_logits")
                return model.apply(params, x, make_gctx(gdata, n, mega),
                                   train=False)

            self._logits_jit = frozen_logits
        return self._logits_jit(self.params, self.x, self.gdata)


def load_frozen(config: Config, dataset: Dataset, model: Model,
                checkpoint_path: Optional[str] = None) -> FrozenBundle:
    """Load a checkpoint + the plan cache into a forward-only bundle.

    With ``checkpoint_path`` (or ``config.checkpoint_path``) the weights
    are restored via `checkpoint.load_params` — optimizer state is never
    materialized.  Without one, Glorot-init params are returned (tests
    and selftests exercise parity without a training run).  Plan builds
    go through the same content-keyed disk cache as training
    (ops/pallas/binned.py): when the training run already built this
    graph's plans, loading here is a cache read, not a rebuild.
    """
    from roc_tpu import obs

    path = checkpoint_path or config.checkpoint_path
    with obs.span("load_frozen", stream=bool(config.stream)):
        if config.stream:
            from roc_tpu.stream.executor import StreamTrainer
            tr = StreamTrainer(config, dataset, model)
            if path:
                tr.params = checkpoint.load_params(path, tr.params)
            return FrozenBundle(
                config=config, dataset=dataset, model=model,
                params=tr.params, x=None, gdata=None,
                num_nodes=dataset.graph.num_nodes,
                megafuse=config.megafuse, stream_trainer=tr)
        from roc_tpu.train.driver import (dense_graph_data,
                                          effective_backend,
                                          effective_gat_backend,
                                          model_gat_dims)
        backend = effective_backend(config, dataset, model)
        gheads, gdim = model_gat_dims(model)
        gdata = dense_graph_data(
            dataset.graph, backend, config.aggregate_precision,
            gat_backend=effective_gat_backend(config, dataset, model),
            storage_dtype="bf16" if config.bf16_storage else "fp32",
            megafuse=config.megafuse,
            gat_heads=gheads, gat_head_dim=gdim)
        dtype = jnp.bfloat16 if config.use_bf16 else jnp.float32
        x = jnp.asarray(dataset.features, dtype)
        params = model.init_params(jax.random.PRNGKey(config.seed))
        if path:
            params = checkpoint.load_params(path, params)
        params = jax.device_put(params)
        return FrozenBundle(
            config=config, dataset=dataset, model=model, params=params,
            x=x, gdata=gdata, num_nodes=dataset.graph.num_nodes,
            megafuse=config.megafuse)
