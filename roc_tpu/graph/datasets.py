"""Dataset registry: ROC-format loaders + deterministic synthetic graphs.

The reference ships no datasets (test.sh:8 points at an absent
``dataset/reddit-dgl``); it consumes preprocessed ``<prefix>.add_self_edge.lux``
+ sidecar files.  We support exactly that on-disk contract via
:func:`load_roc_dataset`, and — because this environment has no network —
provide deterministic synthetic generators whose shapes mirror the standard
citation/Reddit benchmarks so correctness and performance work is
reproducible offline.  Synthetic graphs are stochastic-block-model-ish so a
GCN genuinely learns on them (accuracy is the reference's de-facto test
oracle, SURVEY.md §4).
"""

from __future__ import annotations

import dataclasses

import numpy as np

from roc_tpu.graph import lux
from roc_tpu.graph.csr import Csr, add_self_edges, from_edges


@dataclasses.dataclass(frozen=True)
class GraphStub:
    """Graph header only (num_nodes/num_edges) — the per-host loading path
    never materializes the topology on any single host; SpmdTrainer reads
    per-part `.lux` slices itself (roc_tpu/graph/shard_load.py)."""
    num_nodes: int
    num_edges: int


@dataclasses.dataclass(frozen=True)
class Dataset:
    name: str
    graph: Csr              # includes self-edges (the reference's input
                            # contract); a GraphStub under -perhost
    features: np.ndarray    # [N, in_dim] float32 (may be a read-only memmap)
    labels: "np.ndarray | None"  # [N, C] one-hot float32, or None when lazy
    label_ids: np.ndarray   # [N] int64
    mask: np.ndarray        # [N] int32 in {TRAIN, VAL, TEST, NONE}
    in_dim: int
    num_classes: int

    def onehot_labels(self) -> np.ndarray:
        """One-hot labels, materialized on demand (lazy datasets skip the
        [N, C] float32 allocation — 69 GB at papers100M scale)."""
        if self.labels is not None:
            return self.labels
        return lux.one_hot(self.label_ids, self.num_classes)


def load_roc_dataset(prefix: str, in_dim: int, num_classes: int,
                     name: str = "", lazy: bool = False,
                     graph_stub: bool = False) -> Dataset:
    """Load a dataset laid out in the reference's on-disk format.

    ``in_dim``/``num_classes`` come from the layer spec exactly as in the
    reference CLI (`-layers 602-256-41` supplies both, gnn.cc:68-69).
    ``lazy=True`` memory-maps features and defers one-hot label expansion —
    the sharded-host-loading mode: each host's per-part placement then reads
    only its own vertex ranges from disk (the TPU analog of the reference's
    per-partition `.lux` seeking, load_task.cu:231-243).
    ``graph_stub=True`` (implies lazy) reads only the 12-byte `.lux` header:
    the per-host trainer loads topology slices itself.
    """
    if graph_stub:
        lazy = True
        g = GraphStub(*lux.read_header(prefix + lux.LUX_SUFFIX))
    else:
        g = lux.read_lux(prefix + lux.LUX_SUFFIX)
    feats = lux.load_features(prefix, g.num_nodes, in_dim, mmap=lazy)
    ids = lux.load_label_ids(prefix, g.num_nodes, num_classes)
    mask = lux.load_mask(prefix, g.num_nodes)
    onehot = None if lazy else lux.one_hot(ids, num_classes)
    return Dataset(name or prefix, g, feats, onehot, ids, mask, in_dim,
                   num_classes)


def synthetic(name: str, num_nodes: int, avg_degree: float, in_dim: int,
              num_classes: int, *, n_train: int, n_val: int, n_test: int,
              p_intra: float = 0.8, feature_snr: float = 1.0,
              seed: int = 0, inter_mode: str = "uniform") -> Dataset:
    """Deterministic SBM-style graph with class-informative features.

    Edges prefer endpoints in the same class block with probability
    ``p_intra``; features are a per-class mean plus unit Gaussian noise.  A
    2-layer GCN reaches high val/test accuracy on these, giving us the same
    kind of end-to-end oracle the reference relies on.

    ``inter_mode`` shapes the (1 - p_intra) inter-community edges:
    "uniform" (default, the historical behavior) spreads them over the
    whole graph — the locality WORST case, since even an optimal vertex
    order leaves those edges touching ~every (block, bin) tile;
    "ring" sends them to the two adjacent communities (communities on a
    ring) — the hierarchical-locality structure real co-purchase/social
    graphs exhibit, which a reordering pass (graph/reorder.py) can
    actually exploit.  Benchmarks label which one they measured.
    """
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, num_classes, size=num_nodes)
    num_rand_edges = int(num_nodes * avg_degree)
    src = rng.integers(0, num_nodes, size=num_rand_edges)
    # With prob p_intra rewire dst into src's class block.
    dst = rng.integers(0, num_nodes, size=num_rand_edges)
    intra = rng.random(num_rand_edges) < p_intra
    # pick a same-class partner: order nodes by class, sample a position
    # inside the class segment of the src's class
    order = np.argsort(labels, kind="stable")
    class_start = np.searchsorted(labels[order], np.arange(num_classes))
    class_count = np.bincount(labels, minlength=num_classes)
    cls = labels[src[intra]]
    pos = class_start[cls] + (rng.random(intra.sum()) * class_count[cls]).astype(np.int64)
    dst[intra] = order[np.minimum(pos, num_nodes - 1)]
    if inter_mode == "ring":
        # inter edges land in a neighbor community on the class ring
        inter = ~intra
        cls_i = labels[src[inter]]
        step = np.where(rng.random(inter.sum()) < 0.5, 1,
                        num_classes - 1).astype(np.int64)
        tgt = (cls_i + step) % num_classes
        pos_i = class_start[tgt] + (rng.random(inter.sum())
                                    * class_count[tgt]).astype(np.int64)
        dst[inter] = order[np.minimum(pos_i, num_nodes - 1)]
    elif inter_mode != "uniform":
        raise ValueError(f"inter_mode={inter_mode!r}: uniform|ring")
    # symmetrize (undirected, like the citation benchmarks)
    s = np.concatenate([src, dst])
    d = np.concatenate([dst, src])
    keep = s != d
    g = add_self_edges(from_edges(num_nodes, s[keep], d[keep]))

    means = rng.normal(0.0, 1.0, size=(num_classes, in_dim)).astype(np.float32)
    feats = (feature_snr * means[labels]
             + rng.normal(0.0, 1.0, size=(num_nodes, in_dim))).astype(np.float32)

    mask = np.full(num_nodes, lux.MASK_NONE, dtype=np.int32)
    perm = rng.permutation(num_nodes)
    mask[perm[:n_train]] = lux.MASK_TRAIN
    mask[perm[n_train:n_train + n_val]] = lux.MASK_VAL
    mask[perm[n_train + n_val:n_train + n_val + n_test]] = lux.MASK_TEST

    onehot = np.zeros((num_nodes, num_classes), dtype=np.float32)
    onehot[np.arange(num_nodes), labels] = 1.0
    return Dataset(name, g, feats, onehot, labels.astype(np.int64), mask,
                   in_dim, num_classes)


# Named configs mirroring the standard benchmarks' shapes (node/feature/class
# counts match the real datasets; topology/features are synthetic).
_REGISTRY = {
    # name: (num_nodes, avg_degree, in_dim, classes, n_train, n_val, n_test)
    "cora":         (2708,    2.0, 1433,  7,   140,  500, 1000),
    "citeseer":     (3327,    1.4, 3703,  6,   120,  500, 1000),
    "pubmed":       (19717,   2.3, 500,   3,    60,  500, 1000),
    "reddit-small": (23296,  25.0, 602,  41,  3600, 1200, 1200),
    "reddit":       (232965, 50.0, 602,  41, 153431, 23831, 55703),
    "arxiv":        (169343,  7.0, 128,  40, 90941, 29799, 48603),
    "products":     (2449029, 25.0, 100, 47, 196615, 39323, 2213091),
    # the static-analyzer's budget matrix shape (analysis/hlo_audit.py):
    # registered so `-dataset roc-audit -analyze` reaches the committed
    # budgets.json entries from the CLI (budgets are shape-keyed; seed
    # doesn't affect the lowered program)
    "roc-audit":    (96,      4.0, 8,     4,    48,   24,   24),
    # megakernel A/B shape (tools/hw_revalidate.sh step 4c): one bin, one
    # block at GEOM_FLAT, so the fused aggregate->linear schedule attaches
    # AND clears the kernel's trace-time VMEM gate at H<=128 in fp32
    # (C2=1); sized like one greedy-cut shard of a medium graph
    "mega-shard":   (448,     4.0, 64,    8,   128,   64,   64),
}


# Vendored REAL graphs (data/*/README.md), fetched by the same `-dataset`
# name as the synthetic stand-ins: name -> constructor attr on
# roc_tpu.graph.convert (one mapping; names() derives from it).  `seed`
# does not apply: karate/davis use the canonical published splits, and
# lesmis pins its golden-curve split (convert.les_miserables's default
# seed) — the docs/GOLDEN.md pins are fixed-split by design.
_REAL = {"karate": "karate_club", "davis": "davis_women",
         "lesmis": "les_miserables"}


def get(name: str, seed: int = 0) -> Dataset:
    """Fetch a named dataset: a vendored real graph (fixed canonical
    split; `seed` ignored), or a deterministic synthetic stand-in
    (seeded)."""
    if name in _REAL:
        from roc_tpu.graph import convert
        return getattr(convert, _REAL[name])()
    if name == "roc-audit":
        # fixed fixture: the halo sizes (hence the committed collective
        # budgets) depend on the edge structure, so this graph pins its
        # seed like the _REAL fixed-split datasets do
        seed = 7
    n, deg, in_dim, classes, ntr, nva, nte = _REGISTRY[name]
    return synthetic(name, n, deg, in_dim, classes,
                     n_train=ntr, n_val=nva, n_test=nte, seed=seed)


def names():
    return sorted(_REGISTRY) + list(_REAL)
