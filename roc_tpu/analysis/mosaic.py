"""Static Mosaic-alignment lint for Pallas kernels (rule: mosaic-align).

Mosaic tiles fp32 VMEM as (8, 128): a DMA slice or BlockSpec window whose
lane (last) dimension is not a multiple of 128, or whose sublane
(second-to-last) dimension is not a multiple of 8, lowers fine in
interpret mode and then hard-errors (or silently pads) on hardware —
the class behind both interpret-only escapes that cost hardware windows
(the H=41 slot DMA and the 1-row HBM gather, docs/PERF.md).  This pass
walks ``pl.ds``/``pl.dslice`` slice sizes and ``pl.BlockSpec`` shape
tuples offline and flags provably-misaligned ones.

Resolution is deliberately conservative — zero false positives on the
shipped kernels is a pinned test (test_mosaic_lint_clean_on_tree):

* Only module-level ``NAME = <int>`` constants and integer literals
  resolve; runtime values (geometry fields, feature widths) don't, and
  unresolvable dims are skipped, not flagged.
* A ``a * b`` size passes if EITHER factor is provably a multiple of the
  requirement (``csz * _UNIT`` with ``_UNIT = 8`` is aligned for any
  csz).
* ``BlockSpec`` shapes with ``memory_space=...SMEM`` are exempt (scalar
  metadata blocks aren't tiled), as is a lane dimension of exactly 1
  (the (N, 1) int32 indicator-column layout Mosaic handles specially).

Waive a finding with ``# roclint: allow(mosaic-align)`` on the offending
or preceding line, same as every other roclint rule.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional

from roc_tpu.analysis.lint import Finding, _WAIVER_RE, _dotted

RULE = "mosaic-align"
_DS_HEADS = {"pl.ds", "pl.dslice", "pltpu.ds", "pallas.ds"}
_SPEC_HEADS = {"pl.BlockSpec", "pallas.BlockSpec", "pltpu.BlockSpec"}
LANE, SUBLANE = 128, 8


def _module_int_consts(tree: ast.Module) -> Dict[str, int]:
    """Top-level NAME = <int literal> bindings (incl. tuple unpacking)."""
    consts: Dict[str, int] = {}
    for node in tree.body:
        if not isinstance(node, ast.Assign):
            continue
        for tgt in node.targets:
            if isinstance(tgt, ast.Name) and \
                    isinstance(node.value, ast.Constant) and \
                    isinstance(node.value.value, int):
                consts[tgt.id] = node.value.value
            elif isinstance(tgt, ast.Tuple) and \
                    isinstance(node.value, ast.Tuple) and \
                    len(tgt.elts) == len(node.value.elts):
                for tn, tv in zip(tgt.elts, node.value.elts):
                    if isinstance(tn, ast.Name) and \
                            isinstance(tv, ast.Constant) and \
                            isinstance(tv.value, int):
                        consts[tn.id] = tv.value
    return consts


def _resolve(node, consts: Dict[str, int]) -> Optional[int]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int) \
            and not isinstance(node.value, bool):
        return node.value
    if isinstance(node, ast.Name):
        return consts.get(node.id)
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Mult):
        a = _resolve(node.left, consts)
        b = _resolve(node.right, consts)
        if a is not None and b is not None:
            return a * b
    return None


def _aligned(node, m: int, consts: Dict[str, int]) -> Optional[bool]:
    """True/False when alignment to ``m`` is provable; None = unknown."""
    v = _resolve(node, consts)
    if v is not None:
        return v % m == 0
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Mult):
        # a multiple-of-m factor makes the whole product aligned
        for side in (node.left, node.right):
            if _aligned(side, m, consts):
                return True
    return None


def _is_smem_spec(call: ast.Call) -> bool:
    for kw in call.keywords:
        if kw.arg == "memory_space":
            return (_dotted(kw.value) or "").endswith("SMEM")
    return False


class _MosaicLint:
    def __init__(self, path: str, src: str):
        self.path = path
        self.src_lines = src.splitlines()
        self.tree = ast.parse(src, filename=path)
        self.consts = _module_int_consts(self.tree)
        self.findings: List[Finding] = []

    def _flag(self, node, msg: str):
        line = getattr(node, "lineno", 1)
        for ln in (line, line - 1):
            if 1 <= ln <= len(self.src_lines):
                m = _WAIVER_RE.search(self.src_lines[ln - 1])
                if m and RULE in [r.strip() for r in m.group(1).split(",")]:
                    return
        self.findings.append(Finding(self.path, line, RULE, msg))

    def run(self) -> List[Finding]:
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            head = _dotted(node.func) or ""
            if head in _DS_HEADS:
                self._check_ds(node)
            elif head in _SPEC_HEADS:
                self._check_spec(node)
        return self.findings

    def _check_ds(self, call: ast.Call):
        if len(call.args) < 2:      # pl.ds(start) has implicit size 1:
            return                  # axis-dependent, can't judge statically
        size = call.args[1]
        ok = _aligned(size, SUBLANE, self.consts)
        if ok is False:
            v = _resolve(size, self.consts)
            self._flag(call,
                       f"pl.ds slice size {v} is not a multiple of "
                       f"{SUBLANE} — Mosaic sublane tiling rejects this "
                       f"DMA on hardware (interpret mode hides it)")

    def _check_spec(self, call: ast.Call):
        if not call.args or not isinstance(call.args[0], ast.Tuple):
            return
        if _is_smem_spec(call):
            return
        dims = call.args[0].elts
        if not dims:
            return
        lane = _resolve(dims[-1], self.consts)
        if lane == 1:
            return          # (N, 1) indicator-column layout
        if lane is not None and lane % LANE:
            self._flag(call,
                       f"BlockSpec lane dimension {lane} is not a "
                       f"multiple of {LANE} — pad the feature axis "
                       f"(interpret mode hides the hardware error)")
        if len(dims) >= 2:
            sub = _aligned(dims[-2], SUBLANE, self.consts)
            if sub is False:
                v = _resolve(dims[-2], self.consts)
                self._flag(call,
                           f"BlockSpec sublane dimension {v} is not a "
                           f"multiple of {SUBLANE} — Mosaic tiling "
                           f"rejects this window on hardware")


def lint_source(src: str, path: str = "<string>") -> List[Finding]:
    if "pallas" not in src:     # cheap gate: nothing to check
        return []
    return _MosaicLint(path, src).run()


def lint_file(path: str) -> List[Finding]:
    with open(path, encoding="utf-8") as f:
        return lint_source(f.read(), path)


def lint_paths(paths) -> List[Finding]:
    out: List[Finding] = []
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = [d for d in dirs
                           if d not in ("__pycache__", ".git")]
                for fn in sorted(files):
                    if fn.endswith(".py"):
                        out.extend(lint_file(os.path.join(root, fn)))
        elif p.endswith(".py"):
            out.extend(lint_file(p))
    return sorted(out, key=lambda f: (f.path, f.line))
