from roc_tpu.optim.adam import Adam, AdamState

__all__ = ["Adam", "AdamState"]
