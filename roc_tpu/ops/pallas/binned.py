"""Binned two-phase sum-aggregation — the TPU answer to the reference's
`aggre_coop_kernel` (scattergather_kernel.cu:20-76) at full-graph scale.

Why a second kernel family exists (measured on v5e, docs/PERF.md): XLA
lowers the [E]-row gather behind every aggregation to a dynamic-slice loop
that issues one row per ~10 ns and reads a full (8,128) tile per row — at
Reddit scale (23.5M edges) the gather alone costs 235-300 ms, ~80% of the
epoch.  The reference never pays this: its CUDA kernel's random accesses
ride a GPU cache hierarchy.  TPUs have no HBM cache, so the fix is to
restructure the data movement itself, radix-style:

  PHASE 1 (bin scatter, sequential reads): edges are pre-sorted by
    (source block, destination bin).  The kernel streams x one SB-row
    block at a time (large sequential DMAs — no per-row gather), expands
    each chunk of CH edges into their source rows with ONE one-hot MXU
    matmul (T[CH, SB] @ xblk[SB, H]), and DMA-writes the result to a
    staging buffer in SLOT-row groups at plan-computed, slot-aligned
    offsets.  Staging is laid out bin-major, so phase 1 is a blocked
    transpose from source order to destination-bin order.

  PHASE 2 (windowed scatter, sequential reads): staging is consumed in
    chunk-sized sequential DMAs; each chunk belongs to ONE bin of RB
    destination rows held resident in VMEM, and one one-hot matmul
    (S[CH2, RB]^T @ chunk) scatter-adds the rows into the bin.  fp32
    accumulation; rows may sit in any order inside a bin, which is what
    lets phase 1 write cells block-major without a per-bin sort.

  Bin GROUPS stripe the staging buffer: phases 1+2 run per group of bins
  (a lax.scan over stacked per-group plans), so staging holds ~E/G rows
  instead of E; x is re-read once per group, which is noise (the table
  is ~100x smaller than the edge stream).

Cost per aggregation: read x G times (sequential) + write staging once
(SLOT-row DMAs with block-cell run locality) + read staging once
(sequential) + one-hot matmuls (~E*(SB+RB)*H MACs, bf16).  Two precisions:

  fast (default): staging rides bf16 — one-hot factors are exact, so
  features take exactly ONE bf16 rounding; accumulation stays fp32
  (golden curves within ±1 sample of fp32, docs/GOLDEN.md).

  exact: fp32 staging + 3-way bf16 splits through the MXU.  A fp32 value
  is hi+mid+lo of three bf16 roundings of successive residuals (8
  mantissa bits each covers fp32's 24); each split-dot's products against
  the EXACT one-hot factor are exact in fp32, so the only rounding is
  the fp32 accumulation itself — the same rounding the reference's fp32
  CUDA sums make (types.h:7).  Costs: 2x staging DMA bytes, 3x MXU MACs.
  The FAST path's phases measured DMA-issue-bound on hardware (29%/44%
  MXU, round 2, BASELINE.md), which predicts much of the extra compute
  hides behind the same DMAs; the exact mode's own epoch time is
  unmeasured until the next hardware window (tools/hw_revalidate.sh
  step 2a).  The one-hot `matmul` backend (roc_tpu/ops/aggregate.py)
  remains the plan-B exact path.

Static-shape discipline: every (source-block, bin) cell is padded to a
multiple of SLOT rows, every source block's chunk count and every bin's
chunk count to whole chunks, and per-group chunk counts to a common max.
Pad rows carry src-local 0 and dst-local RB; phase 2 zero-masks dst-local
RB rows *before* the dot so uninitialized staging garbage (even NaN)
cannot leak through a 0 coefficient.
"""

from __future__ import annotations

import dataclasses
import os
import warnings
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# stdlib-only tracer entry point (no obs package body is pulled in here)
from roc_tpu.obs.tracer import span as _obs_span
# Calibration ledger (stdlib-only, like the tracer): choose_geometry
# PREDICTS the winning schedule's step/staging-row counts, the plan
# builder MEASURES what it actually built, and the obs stream records
# both — `python -m roc_tpu.obs calibration` reads the ratio.
from roc_tpu.obs.ledger import content_key as _content_key
from roc_tpu.obs.ledger import get_ledger as _get_ledger

SB = 512      # source rows per x block (phase-1 streaming unit)
CH = 2048     # edge slots per phase-1 chunk
# Staging write granularity (rows; multiple of the bf16 sublane 16).  Swept
# on v5e at Reddit scale (docs/PERF.md): 32 -> 203.7 ms, 64 -> 189.2,
# 128 -> 184.4 per aggregation — phase 1 is partly DMA-issue-bound, and
# 4x fewer slot DMAs beats the slightly higher cell padding.
SLOT = 128
RB = 512      # destination rows per bin (phase-2 resident window)
CH2 = 4096    # staging rows per phase-2 chunk
# (nslot/slot2 derive on Geometry below — every consumer rebinds from the
# plan's geometry, so no module-level derived constants exist to go stale
# under tools/sweep_binned.py's monkeypatching of the five above)

# Flat-schedule staging granularity, rows.  One fp32 sublane tile is
# (8, 128), so 8-row cell padding is the finest the DMA engine can move
# without tearing tiles — and it is what gets pad1 under 1.05 at Reddit
# shape (avg cell ~113 edges: 8-row padding wastes ~3.3%, SLOT=128 wastes
# 43%).  Flat staging at this default unit is therefore fp32: a bf16 tile
# is (16, 128) and an 8-row slice of it is sublane-misaligned.  The
# bf16-storage pipeline (round 9) instead sets Geometry.unit=16 — cells
# pad to one whole bf16 sublane tile, every size-classed copy stays
# tile-aligned, and staging rides bf16 (halving the DMA bytes for ~2x the
# cell-padding tax: ~6.6% vs ~3.3% at Reddit's ~113-edge cells).
_UNIT = 8
# Staging-copy size classes for the flat schedule, in _UNIT-row units:
# each per-(chunk, staging) run of consecutive rows decomposes greedily
# into 128/32/8-row DMAs, so a dense cell still moves in few descriptors
# while an 8-row tail costs exactly one.
_DMA_CLS = (16, 4, 1)
# Build-time ceiling on a group's staging rows for storing a fused
# (phase-1/phase-2 interleaved) schedule on the plan: 2 x 32768 rows x
# fp32 x H must fit VMEM alongside the working buffers, so fusion only
# ever applies to small groups/widths; run_binned re-gates on the real H
# at trace time and falls back to the flat two-pass path.
_FUSE_MAX_STG_ROWS = 1 << 15


from typing import NamedTuple


class Geometry(NamedTuple):
    """One binned-schedule geometry: every constant the plan builders and
    kernels share.  Carried on the plan (static meta), so plans built with
    different geometries coexist in one process — the sparse-graph presets
    below are how products-density graphs get a binned fast path at all
    (VERDICT r3 item 3: the dense geometry's slot padding is ~5-20x there).

    Invariants (asserted at use): slot divides ch and ch2; slot is a
    multiple of 16 (bf16 sublane granularity of the staging slot DMAs);
    VMEM budget ~16 MB/core bounds ch*sb (phase-1 one-hot), ch2*rb
    (phase-2 one-hot) and the rb*H resident window."""
    sb: int       # source rows per x block (phase-1 streaming unit)
    ch: int       # edge slots per phase-1 chunk
    slot: int     # staging write granularity, rows
    rb: int       # destination rows per bin (phase-2 resident window)
    ch2: int      # staging rows per phase-2 chunk
    # Group-row target (0 = module default _GROUP_ROW_TARGET).  Part of the
    # geometry because chunk counts depend on it: fewer groups mean less
    # per-(group, block) chunk rounding in phase 1 (the products-shape
    # chunk-count lever, tools/sweep_binned.py) at the cost of a larger
    # staging buffer.
    grt: int = 0
    # Hub-split threshold (0 = pure binned): cells with fewer than
    # `hub_minc` edges route to the one-hot matmul side of a hybrid plan
    # (build_binned_plans).  Power-law graphs concentrate most edges into
    # a few dense hub cells while the degree tail sprays thin cells whose
    # slot padding dominates; the split keeps the binned kernels on the
    # dense cells only.
    hub_minc: int = 0
    # Flat compacted schedule (round 8): 1 = the plan builders pack every
    # (group, block) stream into one flat chunk list at 8-row granularity
    # (cells pad to _UNIT=8 rows instead of SLOT; a chunk may span two
    # source blocks; staging writes become per-run size-classed DMAs from
    # scalar-prefetched metadata), eliminating the per-(group, block)
    # chunk rounding that made pad1=1.43 at Reddit shape.  At the default
    # 8-row unit staging rides fp32 at both precisions — an 8-row slice of
    # a bf16 (16, 128)-tiled buffer is sublane-misaligned, so the finer
    # granularity buys its padding win with 2x staging DMA bytes
    # (hardware-window question; docs/DESIGN.md §Flat schedule, §Precision).
    flat: int = 0
    # Flat-schedule unit rows (0 = the module default _UNIT=8, fp32
    # staging).  unit=16 is the bf16-storage variant (round 9): cells pad
    # to one whole bf16 (16, 128) sublane tile, so staging and the
    # size-classed copies ride bf16 — half the DMA bytes of the fp32
    # 8-row unit for ~2x its cell-padding tax.  Only flat geometries use
    # it — FINAL (round 10): the slot-padded schedule will never grow a
    # bf16 staging unit, because its 8-row cells slice a bf16 (16, 128)
    # tile mid-sublane at every cell boundary; check() rejects non-flat
    # unit=16 so the dead end stays unreachable.  "exact" precision needs
    # fp32 staging and run_binned rejects the combination.  New fields MUST append after this one: native plan
    # builders and the sweep tooling consume tuple(geom)[:5], and the
    # plan-cache key/version hash the whole tuple.
    unit: int = 0

    @property
    def nslot(self) -> int:
        return self.ch // self.slot

    @property
    def slot2(self) -> int:
        return self.ch2 // self.slot

    @property
    def unit_rows(self) -> int:
        """Flat-schedule staging granularity, rows (module default when
        the field is 0)."""
        return self.unit or _UNIT

    @property
    def kd(self) -> int:
        """Flat-schedule DMA descriptor slots per chunk: worst case one
        copy per unit-row unit."""
        return self.ch // self.unit_rows

    @property
    def group_rows(self) -> int:
        return self.grt or _GROUP_ROW_TARGET

    def check(self) -> "Geometry":
        assert self.sb >= 1 and self.rb >= 1, self
        assert self.slot >= 16 and self.slot % 16 == 0, \
            f"slot must be a positive multiple of 16: {self}"
        assert self.ch >= self.slot and self.ch % self.slot == 0, self
        assert self.ch2 >= self.slot and self.ch2 % self.slot == 0, self
        assert self.unit in (0, 16), \
            f"unit must be 0 (fp32 8-row) or 16 (bf16 tile): {self}"
        if self.unit:
            assert self.flat, f"unit is a flat-schedule field: {self}"
        if self.flat:
            u = self.unit_rows
            assert self.ch % u == 0 and self.ch2 % u == 0, self
        return self


def _default_geom() -> Geometry:
    """The module constants above remain the source of truth for the
    default geometry (tools/sweep_binned.py monkeypatches them; the env
    knobs there must keep steering everything that doesn't pass an
    explicit geometry)."""
    return Geometry(SB, CH, SLOT, RB, CH2)


# Presets for sparser graphs than the (dense, Reddit-like) default serves.
# The padding tax of a geometry is cells_touched * slot / E; sparser graphs
# touch more cells per edge, so slot shrinks and (to keep the cell count
# down) the windows grow.  Larger windows cost more one-hot MACs per edge
# ((sb + rb) * H), which is why these are not the default: choose_geometry
# picks per graph from ACTUAL plan statistics.
# VMEM at H<=512 (fp32 worst case, ~16 MB/core budget):
#   mid    = dense windows, slot 32:  same footprint as the default.
#   sparse = 1024/2048-row windows:  p1 one-hot (2048x1024 bf16) 4 MB +
#            gbuf 2x2048xH, p2 one-hot (2048x1024 bf16) 4 MB + rb*H out.
GEOM_MID = Geometry(sb=512, ch=2048, slot=32, rb=512, ch2=4096)
GEOM_SPARSE = Geometry(sb=1024, ch=2048, slot=16, rb=1024, ch2=2048)
# Ultra-sparse: 2048-row windows quarter the cell count again; ch/ch2
# shrink to keep the one-hot intermediates inside VMEM (t = 1024x2048
# bf16 = 4 MB, phase-2 s_t likewise).  4096*H MACs per edge — only wins
# where the occupancy stats say every smaller window drowns in slot
# padding, which is exactly what the cost model weighs.
GEOM_XSPARSE = Geometry(sb=2048, ch=1024, slot=16, rb=2048, ch2=1024)

# Wide-chunk variants — the products-shape chunk-count lever (CPU sweep,
# 2026-08-04, tools/sweep_binned.py + BASELINE.md round-5 notes): at the
# 2.45M-node products shape the per-(group, block) chunk rounding and the
# per-grid-step overhead dominate both phases, so doubling the chunk sizes
# and quadrupling the group-row target (fewer groups = fewer rounded
# streams) cuts phase-1 steps ~50% (16512 -> 8208 at CH=4096 + grt=1<<23)
# and phase-2 steps ~49% (7692 -> 3891 at CH2=8192), modeled 310 -> 257 ms
# per aggregation.  VMEM doubles with the chunks, so these only fit
# H <= 256 with bf16 staging ("fast" precision) — _vmem_bytes gates them
# out of choose_geometry's candidate list beyond that.
GEOM_WIDE = Geometry(sb=512, ch=4096, slot=128, rb=512, ch2=8192,
                     grt=1 << 23)
GEOM_MID_WIDE = Geometry(sb=512, ch=4096, slot=32, rb=512, ch2=8192,
                         grt=1 << 23)
GEOM_SPARSE_WIDE = Geometry(sb=1024, ch=4096, slot=16, rb=1024, ch2=4096,
                            grt=1 << 23)

# Flat-schedule presets (round 8, docs/DESIGN.md §Flat schedule).  The flat
# packer removes per-(group, block) chunk rounding entirely, so the wide
# group-row target buys nothing — and fp32 staging at grt=1<<23 would be a
# multi-GB buffer — hence grt=0 (module default).  ch=ch2=4096 keeps both
# phases inside _VMEM_BUDGET with fp32 staging at the nominal width
# (phase 1: 4096x512 bf16 one-hot + 2 fp32 gbufs + 2 x blocks = 13 MB).
# `slot` is unused by the flat kernels but must still divide ch/ch2
# (Geometry invariant); kept at the dense default for the cache key.
GEOM_FLAT = Geometry(sb=512, ch=4096, slot=128, rb=512, ch2=4096, flat=1)
# Sparse flat variant: 1024-row windows for products-density graphs, where
# the 8-row cell padding (not chunk rounding) is what the flat schedule
# buys over GEOM_SPARSE's 16-row slots.
GEOM_FLAT_SPARSE = Geometry(sb=1024, ch=2048, slot=16, rb=1024, ch2=2048,
                            flat=1)

# bf16-storage flat variants (round 9, docs/DESIGN.md §Precision): 16-row
# units keep every staging copy aligned to the bf16 (16, 128) tile, so the
# staging buffer and its DMAs ride bf16 — half the bytes of the fp32 8-row
# unit.  choose_geometry only considers these when the caller declares
# bf16 storage (the driver's Config.bf16_storage / use_bf16 path); fp32
# runs never trade cell padding for a byte win they can't bank.
GEOM_FLAT_BF16 = GEOM_FLAT._replace(unit=16)
GEOM_FLAT_SPARSE_BF16 = GEOM_FLAT_SPARSE._replace(unit=16)

# Megakernel candidates (round 10, docs/DESIGN.md §Megakernel): the
# aggregate->linear megakernel runs on any flat plan whose fused schedule
# attaches (ch == ch2, group staging within _FUSE_MAX_STG_ROWS), so the
# mega presets ARE the fused-eligible flat geometries under explicit
# names — no new window shapes, no Geometry field (the plan-cache key and
# native builders stay untouched).  choose_geometry(fuse_linear=True)
# prices the difference instead: candidates whose schedule cannot feed
# the megakernel pay the eliminated intermediate's HBM round trip.
GEOM_MEGA = GEOM_FLAT
GEOM_MEGA_SPARSE = GEOM_FLAT_SPARSE
GEOM_MEGA_BF16 = GEOM_FLAT_BF16

# Named presets for the ROC_BINNED_GEOM escape hatch (build_binned_plans):
# force the auto-chosen FORWARD geometry to a specific preset, for
# hardware A/B runs that must isolate one variable — e.g. hw_revalidate
# step 4c runs both megakernel legs at "flat" so the measured delta is
# fusion, not the cost model's geometry pick.
GEOM_PRESETS = {
    "wide": GEOM_WIDE,
    "mid": GEOM_MID,
    "mid_wide": GEOM_MID_WIDE,
    "sparse": GEOM_SPARSE,
    "sparse_wide": GEOM_SPARSE_WIDE,
    "xsparse": GEOM_XSPARSE,
    "flat": GEOM_FLAT,
    "flat_sparse": GEOM_FLAT_SPARSE,
    "flat_bf16": GEOM_FLAT_BF16,
    "flat_sparse_bf16": GEOM_FLAT_SPARSE_BF16,
}

# Staging ceiling per bin group, in rows (~1 GiB bf16 at H=256).  Fewer
# groups = less per-(group, block) chunk-rounding padding in phase 1 at the
# cost of a proportionally larger staging buffer; ROC_BINNED_GROUP_ROWS
# overrides for hardware sweeps (tools/sweep_binned.py).
_GROUP_ROW_TARGET = int(os.environ.get("ROC_BINNED_GROUP_ROWS", 1 << 21))
# Cap on the dense (source-block x bin) cell table per group — bounds the
# plan builders' memory on huge sparse graphs to ~256 MiB of int64 cells
# (the native builder allocates it densely; mirrored there as BN_K2_CAP).
_K2_CAP = 1 << 25


@dataclasses.dataclass(frozen=True)
class BinnedPlan:
    """One direction (out = A @ x) of a binned aggregation schedule.

    Array fields carry a leading [G] group axis; int fields are static.
      p1_srcl [G, C1*CH, 1]  src row local to its block (pad rows: 0)
      p1_off  [G, C1, NSLOT] staging SLOT index per chunk slot
      p1_blk  [G, C1]        x block index per chunk
      p2_dstl [G, C2*CH2, 1] dst row local to its bin (pad rows: RB)
      p2_obi  [G, C2]        group-local bin index per chunk (nondecreasing)
      p2_first[G, C2]        1 iff first chunk of its bin

    Flat-schedule plans (geom.flat, round 8) reinterpret/extend the set:
    p1_off is None (replaced by the run-list DMA metadata), p1_srcl pad
    rows carry -1 (exact-zero one-hot row), a chunk may span two source
    blocks (secondary-block rows store sb + local), and:
      p1_blk2 [G, C1]        secondary x block (== p1_blk if none)
      p1_dsrc [G, C1, KD]    staging-copy source:  cls<<16 | chunk unit
                             (cls indexes _DMA_CLS; -1 = unused slot)
      p1_ddst [G, C1, KD]    staging-copy destination unit
                             (row / geom.unit_rows)
    Fused plans additionally carry a flattened interleaved step list
    (phase 2 of group g overlapped with phase 1 of group g+1; built by
    _attach_fused when the whole group's staging fits VMEM, else None):
      f_meta  [S, 4]         (kind 0=p1/1=p2, group parity, first, stg
                             chunk index within the group's staging)
      f_rows  [S*CH, 1]      per-step srcl (kind 0) or dstl (kind 1)
      f_blk/f_blk2/f_obi [S] x blocks + GLOBAL output bin per step (p1
                             steps repeat the previous p2 step's bin)
      f_dsrc/f_ddst [S, KD]  staging-copy run lists (kind 0; else -1)
      f_last  [S]            1 iff the step is the LAST real p2 chunk of
                             its output bin (the megakernel's in-register
                             activation point; pad steps carry 0)
    """
    p1_srcl: jnp.ndarray
    p1_off: jnp.ndarray
    p1_blk: jnp.ndarray
    p2_dstl: jnp.ndarray
    p2_obi: jnp.ndarray
    p2_first: jnp.ndarray
    p1_blk2: jnp.ndarray = None
    p1_dsrc: jnp.ndarray = None
    p1_ddst: jnp.ndarray = None
    f_meta: jnp.ndarray = None
    f_rows: jnp.ndarray = None
    f_blk: jnp.ndarray = None
    f_blk2: jnp.ndarray = None
    f_obi: jnp.ndarray = None
    f_dsrc: jnp.ndarray = None
    f_ddst: jnp.ndarray = None
    f_last: jnp.ndarray = None
    num_rows: int = dataclasses.field(metadata={"static": True}, default=0)
    table_rows: int = dataclasses.field(metadata={"static": True}, default=0)
    bins_per_group: int = dataclasses.field(
        metadata={"static": True}, default=0)
    # The geometry the plan was built for; the kernels replay it (static).
    geom: Geometry = dataclasses.field(metadata={"static": True},
                                       default=None)


# None-valued data fields are empty pytree subtrees: tree_map skips them,
# and two-pass vs flat vs fused plans simply have different treedefs
# (separate jit cache entries — intended).
_PLAN_DATA_FIELDS = [
    "p1_srcl", "p1_off", "p1_blk", "p2_dstl", "p2_obi", "p2_first",
    "p1_blk2", "p1_dsrc", "p1_ddst",
    "f_meta", "f_rows", "f_blk", "f_blk2", "f_obi", "f_dsrc", "f_ddst",
    "f_last"]

jax.tree_util.register_dataclass(
    BinnedPlan,
    data_fields=list(_PLAN_DATA_FIELDS),
    meta_fields=["num_rows", "table_rows", "bins_per_group", "geom"])


def _pad_to(n: int, m: int) -> int:
    return -(-n // m) * m


def staging_dtype(geom: Geometry, exact: bool):
    """The staging-buffer dtype a plan geometry implies at a precision —
    THE single decision point every byte consumer (kernels, VMEM gates,
    cost model, memory estimator, kernel budgets) shares.

    Slot schedule: bf16 for "fast", fp32 for "exact" (the original
    contract).  Flat schedule: a pure function of the geometry — fp32 at
    the default 8-row unit (tears bf16 tiles), bf16 at unit=16; "exact"
    needs fp32 staging, so run_binned rejects exact on unit=16 plans
    rather than silently widening."""
    if geom is not None and geom.flat:
        return jnp.bfloat16 if geom.unit == 16 else jnp.float32
    return jnp.float32 if exact else jnp.bfloat16


def staging_itemsize(geom: Geometry, exact: bool) -> int:
    return np.dtype(staging_dtype(geom, exact)).itemsize


def binned_viable(num_rows: int, table_rows: int, num_edges: int,
                  edge_src: np.ndarray = None,
                  edge_dst: np.ndarray = None) -> bool:
    """Is the binned schedule padding-tolerable for this graph?

    Cells are (source-block x bin) pairs and every non-empty cell pads to
    SLOT rows; with ~uniform edges the number of touched cells approaches
    min(E, blocks * bins), so the expected slot-padding factor is about
    blocks*bins*SLOT / E (each touched cell pays at least one SLOT).  The
    bound accepts up to ~25% slot-padding tax; beyond that (huge sparse
    graphs: ogbn-products-scale N with modest degree, measured ~5x padding)
    the one-hot matmul backend is the right fast path instead.  Threshold:
    average cell >= SLOT*4/5 = 102.4 edges — slightly tighter than the
    round-2 3*SLOT(=32) rule's >= 96; graphs averaging 96-102 edges/cell
    now take the matmul backend instead.

    With edge arrays the call defers to :func:`choose_geometry`'s
    measured-statistics policy (including the sparse presets and the hub
    hybrid) instead of the uniform-occupancy bound — a skewed or
    locality-ordered graph is credited for the cells it never touches."""
    if edge_src is not None:
        g, _ = choose_geometry(edge_src, edge_dst, num_rows, table_rows)
        return g is not None
    num_bins = max(-(-num_rows // RB), 1)
    num_blocks = max(-(-table_rows // SB), 1)
    return num_blocks * num_bins * SLOT * 4 <= num_edges * 5


# Cost-model calibration, measured on v5e at Reddit shape (docs/PERF.md,
# 2026-07-31): both phases are per-grid-step-overhead-bound at ~10/12 us
# per chunk, with the one-hot MACs sustaining ~35-44% of the 197 TF/s bf16
# peak when they dominate; phase 1 additionally pays a per-slot-DMA issue
# cost — the SLOT sweep's own signal (32 -> 128 saved 19.3 ms on ~624k
# fewer DMAs at equal padded rows = ~31 ns per slot DMA), without which
# the model would mis-rank small-slot presets above the measured SLOT=128
# winner on dense graphs.  t_phase1 = max(MAC, chunk overhead) + slot-DMA
# issue; the matmul backend's cost is its issue-rate-bound row gather
# (~10 ns/row, H-independent up to ~128 lanes) plus its cheap VB=8
# one-hot dots — calibrated end to end: 23.5M edges -> 351 ms = 15 ns/edge.
_MXU_EFF_FLOPS = 69e12        # 35% of v5e bf16 peak (phase-1 measured)
_CHUNK_OVERHEAD_S = 11e-6     # per grid step (9.6-12.2 us measured)
_SLOT_DMA_S = 31e-9           # per staging slot DMA (SLOT sweep delta)
# Matmul backend: per-chunk cost of the one-hot scan (gather EB rows +
# S1/S2 dots + DUS).  Re-fit 2026-08-04 from the round-2 Reddit point
# (23.5M edges -> 351 ms) against the REAL chunk count — ceil(E/EB) edge
# chunks PLUS the ceil(rows/VB) per-window >=1-chunk floor
# (segment_sum.build_chunk_plan) that the old flat 15 ns/edge model
# ignored.  That floor is exactly what inflates the matmul backend at
# products shape: 306k windows for 2.45M rows regardless of density.
_MM_CHUNK_S = 2.9e-6
_MODEL_H = 256                # nominal width: plans are H-independent
# HBM bandwidth for the fuse_linear round-trip credit (choose_geometry):
# one [rows, H] fp32 intermediate written by the aggregate and read back
# by the linear is what the megakernel eliminates.  The single-source
# roofline constant (obs/roofline.py, stdlib-only): one re-fit lands in
# bench.py, the memory estimator, and this credit at once.
from roc_tpu.obs.roofline import PEAK_BW as _HBM_BW  # noqa: E402
# VMEM feasibility for choose_geometry's candidates, at the nominal model
# width and bf16 staging (the "fast" precision the hardware path runs):
# phase 1 holds the ch x sb one-hot, double gbuf, and an sb x H x block;
# phase 2 the ch2 x rb one-hot, a ch2 x H staging chunk, and the fp32
# rb x H resident window.  ~16 MB/core on v5e; leave headroom.
_VMEM_BUDGET = 14 * (1 << 20)


_MEASURED_CAL: dict = {}   # path -> parsed rates (None = no device table)


def measured_calibration(path: str = ""):
    """Device-measured kernel rates from the ``measured`` table
    tools/kernel_bench.py persists into tools/kernel_budgets.json:
    ``{"chunk_s": <binned per-grid-step s>, "mm_chunk_s": <matmul
    per-chunk s or None>}`` (medians over the benched shapes/variants).

    Returns None — analytic constants stay in charge — when no table
    exists, the table was recorded in interpret mode (CPU harness
    timings, not rates), or ROC_NO_MEASURED_CAL=1 kills it.  The cost
    model (_binned_cost_model / _matmul_cost) and the balance prior
    (balance/cost_model.py) warm-start from these in place of the
    hand-fit _CHUNK_OVERHEAD_S / _MM_CHUNK_S.  Cached per path;
    ROC_MEASURED_CAL_PATH overrides the default table location."""
    if os.environ.get("ROC_NO_MEASURED_CAL"):
        return None
    if not path:
        path = os.environ.get("ROC_MEASURED_CAL_PATH") or os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            "..", "..", "..", "tools", "kernel_budgets.json")
    path = os.path.abspath(path)
    if path in _MEASURED_CAL:
        return _MEASURED_CAL[path]
    import json
    cal = None
    try:
        with open(path, encoding="utf-8") as f:
            m = json.load(f).get("measured") or {}
        if not m.get("interpret", True):
            steps, mm = [], []
            for shp in m.get("shapes", {}).values():
                for row in shp.get("kernels", {}).values():
                    if row.get("variant") == "matmul":
                        mm.append(float(row["per_chunk_s"]))
                    elif "per_step_s" in row:
                        steps.append(float(row["per_step_s"]))
            if steps:
                steps.sort()
                mm.sort()
                cal = {"chunk_s": steps[len(steps) // 2],
                       "mm_chunk_s": mm[len(mm) // 2] if mm else None}
    except (OSError, ValueError, KeyError, TypeError):
        cal = None
    _MEASURED_CAL[path] = cal
    return cal


def _matmul_chunks(num_edges: int, num_rows: int) -> int:
    """Chunk count of the one-hot matmul backend for this shape: edges
    pack EB per chunk, but every VB-row output window costs at least one
    chunk (the obi>=1 invariant, segment_sum.build_chunk_plan)."""
    from roc_tpu.ops.pallas.segment_sum import EB, VB
    return -(-num_edges // EB) + -(-num_rows // VB)


def _matmul_cost(num_edges: int, num_rows: int) -> float:
    cal = measured_calibration()
    rate = (cal or {}).get("mm_chunk_s") or _MM_CHUNK_S
    return _matmul_chunks(num_edges, num_rows) * rate


def _vmem_bytes(geom: Geometry, H: int = _MODEL_H,
                exact: bool = False) -> int:
    if geom.flat:
        # Flat staging dtype is a function of the geometry's unit (fp32 at
        # 8 rows — they tear bf16 (16, 128) tiles — bf16 at unit=16);
        # phase 1 streams TWO x blocks per chunk.
        stg = staging_itemsize(geom, exact)
        p1 = (geom.ch * geom.sb * 2 + 2 * geom.ch * H * stg
              + 2 * geom.sb * H * 4)
        p2 = (geom.ch2 * geom.rb * 2 + geom.ch2 * H * stg
              + geom.rb * H * 4)
        return max(p1, p2)
    stg = 4 if exact else 2
    p1 = (geom.ch * geom.sb * 2 + 2 * geom.ch * H * stg
          + geom.sb * H * 4)
    p2 = (geom.ch2 * geom.rb * 2 + geom.ch2 * H * stg
          + geom.rb * H * 4)
    return max(p1, p2)


def _binned_cost_model(padded_rows: int, geom: Geometry,
                       H: int = _MODEL_H, steps1: int = None,
                       steps2: int = None) -> float:
    """Modeled seconds for ONE aggregation pass at this geometry, given the
    actual slot-padded staging row count (from cell statistics).

    With ``steps1``/``steps2`` (exact grid step counts, _plan_steps) the
    MAC and per-step-overhead terms price the REAL schedule — including
    per-(group, block) chunk rounding and per-group max-padding, the
    effects the wide-chunk presets exist to shrink.  Without them the
    model falls back to the ideal padded_rows/chunk approximation."""
    rows1 = steps1 * geom.ch if steps1 is not None else padded_rows
    rows2 = steps2 * geom.ch2 if steps2 is not None else padded_rows
    mac1 = rows1 * geom.sb * H * 2 / _MXU_EFF_FLOPS
    mac2 = rows2 * geom.rb * H * 2 / _MXU_EFF_FLOPS
    # Per-grid-step overhead: the measured rate from the last hardware
    # kernel_bench run when one is committed, the hand-fit constant
    # otherwise (measured_calibration — interpret tables never apply).
    cal = measured_calibration()
    step_s = (cal or {}).get("chunk_s") or _CHUNK_OVERHEAD_S
    ov1 = (steps1 if steps1 is not None
           else padded_rows / geom.ch) * step_s
    ov2 = (steps2 if steps2 is not None
           else padded_rows / geom.ch2) * step_s
    if geom.flat:
        # Flat staging writes are per-run size-classed DMAs, not per-slot:
        # a typical cell (~1 run) moves in a few descriptors.  Modeled at
        # an average 4-unit copy, scaled by the staging itemsize relative
        # to the bf16 slot schedule the constant was fit on (fp32 8-row
        # units pay 2x the bytes; bf16 16-row units pay 1x on half the
        # descriptors) — constants to be re-fit from the next hardware
        # window (ROADMAP standing item; the policy and the grid test
        # price candidates through this same branch, so the ranking is
        # self-consistent either way).
        dma1 = (padded_rows / (geom.unit_rows * 4) * _SLOT_DMA_S
                * (staging_itemsize(geom, False) / 2))
    else:
        dma1 = padded_rows / geom.slot * _SLOT_DMA_S
    return max(mac1, ov1) + dma1 + max(mac2, ov2)


def _cell_stats(edge_src: np.ndarray, edge_dst: np.ndarray,
                sb: int, rb: int):
    """Nonzero (source-block x destination-bin) cells: returns
    (cell_blk, cell_bin, cnt) int64 arrays — one O(E) bincount, the single
    implementation every occupancy consumer shares."""
    blk = np.asarray(edge_src, np.int64) // sb
    bn = np.asarray(edge_dst, np.int64) // rb
    nbins = int(bn.max(initial=0)) + 1
    keys = blk * nbins + bn
    nkeys = int(blk.max(initial=0) + 1) * nbins
    if nkeys <= max(4 * len(keys), 1 << 20):
        # dense O(E + cells) bincount while the cell table is small
        cnt = np.bincount(keys, minlength=0)
        uniq = np.flatnonzero(cnt)
        cnt = cnt[uniq]
    else:
        # Sparse O(E log E) time / O(E) memory fallback: a dense bincount
        # is O(blocks*bins) memory regardless of occupancy — ~376 GB at
        # papers100M scale with sb=rb=512, which would OOM exactly the
        # offline preprocessing paths (-reorder auto, convert --reorder)
        # advertised for such graphs.
        uniq, cnt = np.unique(keys, return_counts=True)
    return uniq // nbins, uniq % nbins, cnt.astype(np.int64)


def _cell_counts(edge_src: np.ndarray, edge_dst: np.ndarray,
                 sb: int, rb: int) -> np.ndarray:
    """Nonzero cell occupancies only (see _cell_stats)."""
    return _cell_stats(edge_src, edge_dst, sb, rb)[2]


def _flat_pack(stream_g: np.ndarray, stream_units: np.ndarray,
               uc: int, G: int, segments: bool = False):
    """Flat-schedule phase-1 packer: lay each group's (source-block-major)
    unit streams into `uc`-unit chunks.  One stream = one (group, block)
    pair's ``geom.unit_rows``-row units, in cell order.  A chunk may span at most TWO
    streams — the kernel reads two x blocks per grid step — so when a
    third block would enter a partly-filled chunk the chunk is cut early;
    that cut and each group's final partial chunk are the only schedule
    waste left (vs. per-(group, block) rounding in the slot schedule).

    Returns (c1_per_g [G], segs) where segs is None unless ``segments``:
    a (stream, chunk, pos, take) int64 array, one row per contiguous span
    a stream contributes to a chunk, in global unit order.  SHARED by the
    plan builder and _plan_steps so the step predictor is exact by
    construction (pinned by test_plan_steps_match_built_plans)."""
    c1_per_g = np.zeros(G, np.int64)
    segs = [] if segments else None
    n = len(stream_g)
    i = 0
    while i < n:
        g = int(stream_g[i])
        chunk = 0
        fill = 0
        nblk = 0
        while i < n and int(stream_g[i]) == g:
            u = int(stream_units[i])
            if nblk >= 2 and 0 < fill and u > 0:
                chunk += 1          # early cut: a third distinct block
                fill = 0
                nblk = 0
            while u > 0:
                if fill == uc:
                    chunk += 1
                    fill = 0
                    nblk = 0
                take = min(u, uc - fill)
                if segments:
                    segs.append((i, chunk, fill, take))
                nblk += 1           # one span per (stream, chunk)
                fill += take
                u -= take
            i += 1
        c1_per_g[g] = chunk + (1 if fill > 0 else 0)
    if segments:
        segs = (np.asarray(segs, np.int64).reshape(-1, 4)
                if segs else np.zeros((0, 4), np.int64))
    return c1_per_g, segs


def _flat_plan_steps(cell_blk, cell_bin, cnt, geom, num_bins, num_blocks,
                     bpg, G):
    """Flat-schedule arm of _plan_steps: cells pad to unit_rows, phase-1
    chunks pack via _flat_pack, phase-2 bins pad to whole CH2 chunks."""
    U = geom.unit_rows
    cell_units = -(-cnt // U)
    padded = int(cell_units.sum() * U)
    # phase 1: streams in (group, block) order — np.unique sorts the key
    gb = (cell_bin // bpg) * num_blocks + cell_blk
    gb_uniq, gb_inv = np.unique(gb, return_inverse=True)
    gb_units = np.bincount(gb_inv, weights=cell_units).astype(np.int64)
    c1_per_g, _ = _flat_pack(gb_uniq // num_blocks, gb_units,
                             geom.ch // U, G)
    C1 = _pad_to(max(int(c1_per_g.max(initial=0)), 1), 8)
    # phase 2: bins stay CH2-aligned in staging (empty bins cost one chunk)
    u2 = geom.ch2 // U
    bin_units = np.bincount(cell_bin, weights=cell_units,
                            minlength=num_bins).astype(np.int64)
    bin_chunks = np.maximum(-(-bin_units // u2), 1)
    c2_per_g = np.bincount(np.arange(num_bins) // bpg, weights=bin_chunks,
                           minlength=G)
    C2 = max(int(c2_per_g.max(initial=0)), 1)
    return padded, G * C1, G * C2


def _plan_steps(cell_blk: np.ndarray, cell_bin: np.ndarray,
                cnt: np.ndarray, geom: Geometry, num_rows: int,
                table_rows: int, num_edges: int):
    """Exact (padded_rows, phase-1 steps, phase-2 steps) the plan builder
    would produce for these cells — same arithmetic as
    _build_binned_plan_numpy, O(cells).  Steps are G*C1 / G*C2: every
    group runs the per-group MAXIMUM chunk count (one stacked static
    program), so group-count and rounding effects are priced, which is
    what makes the chunk-count lever visible to the cost model."""
    num_bins = max(-(-num_rows // geom.rb), 1)
    num_blocks = max(-(-table_rows // geom.sb), 1)
    bpg = max(min(num_bins,
                  int(geom.group_rows / max(num_edges / num_bins, 1)),
                  _K2_CAP // num_blocks), 1)
    G = -(-num_bins // bpg)
    if geom.flat:
        return _flat_plan_steps(cell_blk, cell_bin, cnt, geom, num_bins,
                                num_blocks, bpg, G)
    cell_slots = -(-cnt // geom.slot)
    padded = int(cell_slots.sum() * geom.slot)
    # phase 1: chunks per (group, block) stream, per-group sums, max
    gb = (cell_bin // bpg) * num_blocks + cell_blk
    gb_uniq, gb_inv = np.unique(gb, return_inverse=True)
    gb_slots = np.bincount(gb_inv, weights=cell_slots).astype(np.int64)
    gb_chunks = -(-gb_slots // geom.nslot)
    c1_per_g = np.bincount((gb_uniq // num_blocks).astype(np.int64),
                           weights=gb_chunks, minlength=G)
    C1 = _pad_to(max(int(c1_per_g.max(initial=0)), 1), 8)
    # phase 2: chunks per bin (empty bins still cost one), per-group max
    bin_slots = np.bincount(cell_bin, weights=cell_slots,
                            minlength=num_bins).astype(np.int64)
    bin_chunks = np.maximum(-(-bin_slots // geom.slot2), 1)
    c2_per_g = np.bincount(np.arange(num_bins) // bpg, weights=bin_chunks,
                           minlength=G)
    C2 = max(int(c2_per_g.max(initial=0)), 1)
    return padded, G * C1, G * C2


def fused_plan_steps(cell_blk: np.ndarray, cell_bin: np.ndarray,
                     cnt: np.ndarray, geom: Geometry, num_rows: int,
                     table_rows: int, num_edges: int):
    """Exact fused/megakernel grid step count for these cells, or None
    when no fused schedule would attach (non-flat geometry, ch != ch2, or
    group staging beyond _FUSE_MAX_STG_ROWS).  The fused grid runs REAL
    chunks only — _attach_fused skips pad chunks — so its step count is
    pad8(sum c1_per_g + sum bin_chunks), vs the two-pass G*C1 + G*C2
    (per-group max-padded) that _plan_steps prices; the gap is what the
    kernel-budget mega gate pins (tools/check_kernel_budgets.py).  Same
    arithmetic as _flat_plan_steps/_attach_fused, O(cells)."""
    r = _fused_sched_stats(cell_blk, cell_bin, cnt, geom, num_rows,
                           table_rows, num_edges)
    return None if r is None else r[0]


def _fused_sched_stats(cell_blk, cell_bin, cnt, geom, num_rows, table_rows,
                       num_edges):
    """(fused_steps, C2, G) for these cells, or None when no fused schedule
    attaches — the shared arithmetic behind fused_plan_steps and the
    kernel-budget tool's megakernel rows (which also need C2 and the group
    count to evaluate _mega_vmem_ok/_mega_bwd_vmem_ok offline: a
    single-group plan stages on ONE parity, halving the dominant VMEM
    term)."""
    if not (geom.flat and geom.ch == geom.ch2):
        return None
    num_bins = max(-(-num_rows // geom.rb), 1)
    num_blocks = max(-(-table_rows // geom.sb), 1)
    bpg = max(min(num_bins,
                  int(geom.group_rows / max(num_edges / num_bins, 1)),
                  _K2_CAP // num_blocks), 1)
    G = -(-num_bins // bpg)
    U = geom.unit_rows
    cell_units = -(-cnt // U)
    gb = (cell_bin // bpg) * num_blocks + cell_blk
    gb_uniq, gb_inv = np.unique(gb, return_inverse=True)
    gb_units = np.bincount(gb_inv, weights=cell_units).astype(np.int64)
    c1_per_g, _ = _flat_pack(gb_uniq // num_blocks, gb_units,
                             geom.ch // U, G)
    u2 = geom.ch2 // U
    bin_units = np.bincount(cell_bin, weights=cell_units,
                            minlength=num_bins).astype(np.int64)
    bin_chunks = np.maximum(-(-bin_units // u2), 1)
    c2_per_g = np.bincount(np.arange(num_bins) // bpg, weights=bin_chunks,
                           minlength=G)
    C2 = max(int(c2_per_g.max(initial=0)), 1)
    if C2 * geom.ch2 > _FUSE_MAX_STG_ROWS:
        return None
    steps = _pad_to(max(int(c1_per_g.sum()) + int(bin_chunks.sum()), 1), 8)
    return steps, C2, G


def predicted_layer_hbm_bytes(num_rows: int, h_in: int, h_out: int,
                              mega: bool = False,
                              itemsize: int = 4) -> int:
    """Per-layer HBM bytes of the aggregate->linear handoff, OUTSIDE the
    x-block streaming and staging traffic the two modes share: the
    unfused path writes the [rows, H_in] aggregate to HBM and reads it
    back for the matmul; the megakernel never materializes it.  Both
    read the weight once and write the [rows, H_out] output.  Pinned by
    the kernel-budget mega entry and tests/test_binned_flat.py: the drop
    must be >= the intermediate's write + read."""
    out = num_rows * h_out * itemsize + h_in * h_out * 4
    if mega:
        return out
    return out + 2 * num_rows * h_in * itemsize


def predicted_trainstep_hbm_bytes(num_rows: int, h_in: int, h_out: int,
                                  mega_bwd: bool = False,
                                  itemsize: int = 4) -> int:
    """Per-layer TRAIN-STEP HBM bytes of the aggregate->linear handoff
    intermediates: the fused forward (predicted_layer_hbm_bytes with
    mega=True) plus the backward pass's handoff traffic, in the same
    scope — OUTSIDE the x-block streaming and staging both backward modes
    share.

    ``mega_bwd=False`` is the two-pass VJP replay: the backward re-reads
    x (one [rows, h_in]), recomputes the aggregate (write + the replayed
    linear's read + the dW pass's read = 3x [rows, h_in]) and the output
    (write + relu-mask read = 2x [rows, h_out]), then materializes the
    dagg cotangent ([rows, h_in] write + backward-aggregation read) —
    6 h_in + 2 h_out row trips.  ``mega_bwd=True`` is the fused backward:
    it writes only u = A^T g ([rows, h_out], read back once by the XLA dW
    GEMM) and re-reads the saved forward output for the in-kernel relu
    mask — 3 h_out trips; dx rides the same kernel.  The replay's own
    recompute staging round trip is NOT counted (the forward's staging is
    shared, the recompute's is not), so the claimed drop is conservative.
    The >=2x drop at the Reddit shape is pinned by the CI-gated
    ``megakernel_bwd`` kernel-budget row (tools/check_kernel_budgets.py)
    and tests/test_mega_bwd.py."""
    fwd = predicted_layer_hbm_bytes(num_rows, h_in, h_out, mega=True,
                                    itemsize=itemsize)
    if mega_bwd:
        return fwd + 3 * num_rows * h_out * itemsize
    return (fwd + 6 * num_rows * h_in * itemsize
            + 2 * num_rows * h_out * itemsize)


def predicted_xlayer_hbm_bytes(num_rows: int, h: int, depth: int,
                               itemsize: int = 4) -> int:
    """Forward HBM bytes of a DEPTH-layer fusion region at uniform width
    ``h``, in the same scope as predicted_layer_hbm_bytes (OUTSIDE the
    x-block streaming and staging traffic every mode shares): the region
    writes only the FINAL [rows, h] output — every interior layer
    boundary stays in the VMEM inter-layer buffer — and reads each of the
    ``depth`` weights once.  Compare against depth *
    predicted_layer_hbm_bytes(..., mega=True): the region drops
    (depth - 1) output-row writes."""
    return num_rows * h * itemsize + depth * h * h * 4


def predicted_xlayer_trainstep_hbm_bytes(num_rows: int, h: int, depth: int,
                                         itemsize: int = 4) -> int:
    """TRAIN-STEP HBM bytes of a DEPTH-layer fusion region, same scope as
    predicted_trainstep_hbm_bytes.  Forward: predicted_xlayer_hbm_bytes.
    Backward (_xlayer_bwd_run): the region cotangent g enters and dx
    leaves at the region boundary (boundary tensors, excluded — exactly
    as the per-layer accounting excludes them), interior cotangents
    ping-pong in VMEM, u never exists in HBM (dW accumulates in-kernel),
    and the relu masks come from the in-kernel forward replay — so the
    backward's counted traffic is one [rows, h] x re-read for the replay
    (the analogue of the unfused replay's counted x re-read), ``depth``
    dW writes, and ``depth`` weight re-reads for the replay.  Versus
    depth * the per-layer mega+bwd number this drops all 3*depth
    [rows, h] u/mask trips and (depth - 1) forward output writes — the
    >=2x cut the CI-gated ``megakernel_xlayer`` budget rows pin
    (tools/check_kernel_budgets.py check_xlayer_claim)."""
    fwd = predicted_xlayer_hbm_bytes(num_rows, h, depth, itemsize=itemsize)
    return fwd + num_rows * h * itemsize + 2 * depth * h * h * 4


def padded_rows_for(edge_src: np.ndarray, edge_dst: np.ndarray,
                    geom: Geometry) -> int:
    """ACTUAL slot-padded staging rows for this graph at this geometry:
    every touched (source-block x destination-bin) cell rounds up to whole
    SLOTs.  No uniform-graph assumption, so a locality-preserving vertex
    order (the greedy-cut partitioner's output) is credited for the cells
    it never touches."""
    cnt = _cell_counts(edge_src, edge_dst, geom.sb, geom.rb)
    if geom.flat:
        U = geom.unit_rows
        return int((-(-cnt // U)).sum() * U)
    return int((-(-cnt // geom.slot)).sum() * geom.slot)


def staging_bytes_for(edge_src: np.ndarray, edge_dst: np.ndarray,
                      geom: Geometry, H: int = _MODEL_H,
                      exact: bool = False) -> int:
    """Predicted staging-DMA bytes for ONE aggregation pass: every padded
    staging row is written once by phase 1 and read once by phase 2, at
    the geometry's staging dtype.  The byte axis the kernel-budget gate
    pins (tools/check_kernel_budgets.py): a bf16-unit flat geometry must
    move ~half the bytes of its fp32 twin at the same windows."""
    return (2 * padded_rows_for(edge_src, edge_dst, geom) * H
            * staging_itemsize(geom, exact))


def _plan_key(num_rows: int, table_rows: int, num_edges: int,
              geom: Geometry) -> str:
    """Content key joining choose_geometry's schedule predictions to the
    built plan's measurements: the full schedule-shaping input (shape +
    geometry tuple), so a prediction only ever pairs with the plan it was
    made for."""
    return _content_key(rows=int(num_rows), table_rows=int(table_rows),
                        edges=int(num_edges),
                        geom="/".join(str(v) for v in tuple(geom)))


def _ledger_note_plan(plan: "BinnedPlan", num_edges: int) -> None:
    """Measurement half of the plan_steps/staging_rows pairs: the BUILT
    plan's actual grid-step and staging-row counts, read off the plan
    arrays' shapes (O(1), host-side).  _plan_steps is exact by
    construction (test_plan_steps_match_built_plans), so a ratio off 1.0
    here means the predictor and builder have drifted apart."""
    led = _get_ledger()
    if not led.attached:
        return
    g = plan.geom
    G, C1 = plan.p1_blk.shape
    C2 = plan.p2_obi.shape[1]
    key = _plan_key(plan.num_rows, plan.table_rows, num_edges, g)
    led.measure("plan_steps", key, G * (C1 + C2), "steps")
    led.measure("staging_rows", key, G * C2 * g.ch2, "rows")


def _tuned_geometry(edge_src, edge_dst, num_rows, table_rows,
                    storage_dtype, fuse_linear):
    """The tuned-tier lookup (roc_tpu/tune/store.py), failure-isolated:
    a missing/invalid store, ROC_NO_TUNED=1, or any import problem reads
    as 'no tuned entry' and the analytic model stays in charge.  Lazy
    import — tune imports this module at load time."""
    if os.environ.get("ROC_NO_TUNED"):
        return None
    try:
        from roc_tpu.tune import store as _tstore
        g, _ = _tstore.lookup(edge_src, edge_dst, num_rows, table_rows,
                              storage_dtype=storage_dtype,
                              fuse_linear=fuse_linear)
        return g
    except Exception:
        return None


def _priced_tuned(edge_src, edge_dst, num_rows, table_rows, E, geom,
                  fuse_linear):
    """Price a tuned winner through the SAME exact-schedule model the
    analytic path uses (so the returned seconds stay comparable and the
    balancer's consumers see one currency) and emit the same calibration
    predictions a modeled win would — a tuned pick is still a prediction
    the built plan and the hardware get to grade."""
    cblk, cbin, cnt = _cell_stats(edge_src, edge_dst, geom.sb, geom.rb)
    padded, s1, s2 = _plan_steps(cblk, cbin, cnt, geom, num_rows,
                                 table_rows, E)
    t = _binned_cost_model(padded, geom, steps1=s1, steps2=s2)
    if fuse_linear:
        fs = _fused_sched_stats(cblk, cbin, cnt, geom, num_rows,
                                table_rows, E)
        if fs is not None:
            t *= fs[0] / max(s1 + s2, 1)
        else:
            t += (2 * num_rows * _MODEL_H * 4 / _HBM_BW
                  + -(-num_rows // 512) * _CHUNK_OVERHEAD_S)
    led = _get_ledger()
    if led.attached:
        key = _plan_key(num_rows, table_rows, E, geom)
        led.predict("plan_steps", key, s1 + s2, "steps")
        led.predict("staging_rows", key, s2 * geom.ch2, "rows")
        led.predict("geom_time", key, t, "s")
    return geom, t


def choose_geometry(edge_src: np.ndarray, edge_dst: np.ndarray,
                    num_rows: int, table_rows: int,
                    candidates=None, force: bool = False,
                    storage_dtype: str = "fp32",
                    fuse_linear: bool = False):
    """Pick the fastest-modeled binned geometry for this graph, or None if
    the matmul backend's modeled cost beats every candidate (VERDICT r3
    item 3: products-density graphs get a measured-stats policy instead of
    the uniform-occupancy rejection).

    Degree-aware: every candidate is priced at its EXACT schedule shape
    (_plan_steps over the actual cell statistics, so skew and grouping
    effects count) and additionally as a HYBRID — cells under half a slot
    (the padding-dominated tail of a power-law degree distribution) priced
    on the one-hot matmul side instead, the dense hub cells staying
    binned.  A hybrid winner is returned with ``hub_minc`` set on the
    geometry; build_binned_plans splits the edge list accordingly.

    Returns (geom, modeled_seconds), with geom None when matmul wins (and
    the seconds then model matmul).  ``force=True`` always returns the best
    binned candidate — the explicit `-aggr-backend binned` path, where
    falling back to the dense default geometry on a sparse graph would
    build a multi-GB plan.

    TUNED TIER (round 12): before any modeling, the auto path
    (``candidates is None``) consults the content-keyed tuned.json the
    autotuner persists alongside the plan cache (roc_tpu/tune) — a sweep
    winner recorded for this exact graph content + (storage, fuse)
    variant is returned outright, priced through the same exact-schedule
    model so the seconds stay comparable.  ROC_NO_TUNED=1 disables the
    tier; explicit candidate lists (forced A/Bs, the tuner's own trials)
    never consult it.

    ``storage_dtype``: "fp32" (default) or "bf16" — the feature-storage
    dtype the trainer will run.  bf16 storage adds the 16-row bf16-unit
    flat presets to the candidate list (their halved staging bytes only
    exist when the input rides bf16; an fp32 run gains nothing and would
    pay the doubled cell padding).

    ``fuse_linear``: price candidates for an aggregate->linear layer that
    the megakernel may fuse (round 10).  A candidate whose schedule
    CANNOT feed the megakernel (non-flat, ch != ch2, oversized groups, or
    a hybrid split) pays the rest of the layer: the eliminated
    intermediate's HBM round trip (one [rows, _MODEL_H] fp32 write + read
    at _HBM_BW) plus the separate linear pass's launch windows (one
    _CHUNK_OVERHEAD_S per 512-row output window — the same currency the
    kernel-budget mega gate uses).  A mega-eligible candidate is instead
    priced at its FUSED schedule: real chunks only, the W matmul riding
    the existing steps, no second pass.  The same pricing applies to BOTH
    plan directions since round 12: build_binned_plans passes
    ``fuse_linear`` through to the backward pick too, so the transposed
    plan's geometry is chosen knowing the fused backward elides the dagg
    cotangent's round trip the same way the forward elides the
    aggregate's.  VMEM admission is NOT checked
    here (H is unknown until trace time; the kernel's own gate falls back
    to the two-pass flat schedule, which this candidate also runs well)."""
    E = len(edge_src)
    if E == 0:
        return None, 0.0
    if storage_dtype not in ("fp32", "bf16"):
        raise ValueError(f"storage_dtype={storage_dtype!r}: must be "
                         f"'fp32' or 'bf16'")
    # Tuned tier (round 12, roc_tpu/tune): a persisted sweep winner for
    # this exact graph content + variant outranks the analytic model.
    # Only the AUTO path consults it — an explicit candidate list is a
    # forced A/B (kernel_bench, the tuner's own trials) and must never
    # be diverted to the thing it is measuring against.
    if candidates is None:
        tg = _tuned_geometry(edge_src, edge_dst, num_rows, table_rows,
                             storage_dtype, fuse_linear)
        if tg is not None:
            return _priced_tuned(edge_src, edge_dst, num_rows,
                                 table_rows, E, tg, fuse_linear)
    cands = list(candidates) if candidates is not None else \
        [_default_geom(), GEOM_WIDE, GEOM_MID, GEOM_MID_WIDE,
         GEOM_SPARSE, GEOM_SPARSE_WIDE, GEOM_XSPARSE,
         GEOM_FLAT, GEOM_FLAT_SPARSE]
    if candidates is None and storage_dtype == "bf16":
        cands += [GEOM_FLAT_BF16, GEOM_FLAT_SPARSE_BF16]
    # What a NON-fusable candidate pays on top of aggregation when the
    # layer could have fused: the intermediate [rows, H] fp32 write + read
    # the megakernel elides, plus the separate linear pass's launch
    # windows over the output rows.
    rt = 0.0
    if fuse_linear:
        rt = (2 * num_rows * _MODEL_H * 4 / _HBM_BW
              + -(-num_rows // 512) * _CHUNK_OVERHEAD_S)
    best, best_t = None, float("inf")
    best_steps = None   # winner's (s1, s2) for the calibration ledger
    stats_cache = {}
    for g in cands:
        g = g.check()
        if _vmem_bytes(g) > _VMEM_BUDGET:
            continue
        sk = (g.sb, g.rb)
        if sk not in stats_cache:
            # occupancy statistics depend only on the window pair; slot
            # and chunk variants reuse them
            stats_cache[sk] = _cell_stats(edge_src, edge_dst, g.sb, g.rb)
        cblk, cbin, cnt = stats_cache[sk]
        padded, s1, s2 = _plan_steps(cblk, cbin, cnt, g, num_rows,
                                     table_rows, E)
        t = _binned_cost_model(padded, g, steps1=s1, steps2=s2)
        if rt:
            fs = _fused_sched_stats(cblk, cbin, cnt, g, num_rows,
                                    table_rows, E)
            if fs is None:
                t += rt
            else:
                # fused layer: real chunks only, matmul in-pipeline —
                # scale the two-pass aggregation model by the step ratio
                t *= fs[0] / max(s1 + s2, 1)
        if t < best_t:
            best, best_t, best_steps = g, t, (s1, s2)
        # Hybrid variant: the sub-half-full cells' edges go to the matmul
        # side (they pay its per-chunk rate but no slot padding); the
        # matmul window floor is a fixed cost of having a matmul side at
        # all.  Only worth modeling when a meaningful split exists.
        # (Flat geometries skip it: 8-row cell padding already absorbs
        # the thin tail the hub split exists to offload.)
        minc = 0 if g.flat else g.slot // 2
        thin = cnt < minc
        E_thin = int(cnt[thin].sum())
        if 0 < E_thin < E:
            keep = ~thin
            padded_d, s1_d, s2_d = _plan_steps(
                cblk[keep], cbin[keep], cnt[keep], g, num_rows,
                table_rows, E - E_thin)
            t_h = (_binned_cost_model(padded_d, g, steps1=s1_d,
                                      steps2=s2_d)
                   + _matmul_cost(E_thin, num_rows)
                   + rt)    # hybrid plans carry a matmul side: never mega
            if t_h < best_t:
                best = g._replace(hub_minc=minc)
                best_t, best_steps = t_h, (s1_d, s2_d)
    t_matmul = _matmul_cost(E, num_rows) + rt
    if force or (best is not None and best_t < t_matmul):
        if best is not None and best_steps is not None:
            # Prediction half of the plan_steps/staging_rows calibration
            # pairs: the built plan's counts (build_binned_plan) join by
            # content key.  geom_time stays unpaired off-device — only a
            # hardware run (tools/kernel_bench.py) measures it.
            led = _get_ledger()
            if led.attached:
                key = _plan_key(num_rows, table_rows, E, best)
                s1, s2 = best_steps
                led.predict("plan_steps", key, s1 + s2, "steps")
                led.predict("staging_rows", key, s2 * best.ch2, "rows")
                led.predict("geom_time", key, best_t, "s")
        return best, best_t
    return None, t_matmul


def split_hub_edges(edge_src: np.ndarray, edge_dst: np.ndarray,
                    geom: Geometry):
    """Partition edges for the hybrid plan: a boolean mask that is True
    for edges in (source-block x destination-bin) cells with at least
    ``geom.hub_minc`` edges (the dense hub cells that stay binned);
    False edges take the one-hot matmul side."""
    blk = np.asarray(edge_src, np.int64) // geom.sb
    bn = np.asarray(edge_dst, np.int64) // geom.rb
    nbins = int(bn.max(initial=0)) + 1
    keys = blk * nbins + bn
    _, inv, cnt = np.unique(keys, return_inverse=True, return_counts=True)
    return cnt[inv] >= geom.hub_minc


def _prefix_within_runs(values: np.ndarray, keys: np.ndarray) -> np.ndarray:
    """Exclusive prefix sum of `values` restarted at each change of `keys`
    (keys must be grouped).  Both [n]; returns [n]."""
    if len(values) == 0:
        return np.zeros(0, np.int64)
    csum = np.cumsum(values) - values
    first = np.concatenate([[True], keys[1:] != keys[:-1]])
    run_len = np.diff(np.concatenate([np.flatnonzero(first), [len(keys)]]))
    return csum - np.repeat(csum[first], run_len)


# Process-wide count of ACTUAL plan builds (cache hits don't count).
# The serve cold-start contract ("cache load + one trace, zero plan
# rebuilds", roc_tpu/serve) snapshots this before/after engine
# construction — a counter is pinnable where a span name is not.
_PLAN_BUILD_COUNT = 0


def plan_build_count() -> int:
    """How many binned plans this process built from scratch (cache
    hits excluded).  Monotone; diff across a window to pin rebuilds."""
    return _PLAN_BUILD_COUNT


def build_binned_plan(edge_src: np.ndarray, edge_dst: np.ndarray,
                      num_rows: int, table_rows: int,
                      group_row_target: int = _GROUP_ROW_TARGET,
                      geom: Geometry = None,
                      tuned_ok: bool = True) -> BinnedPlan:
    """Host-side schedule: sort, slot-pad, and position every edge for both
    phases.  Big edge lists take the native C++ counting-sort builder
    (O(E), ~14x the NumPy lexsort path: 2.0 s vs 27.3 s at Reddit scale,
    docs/PERF.md); the vectorized
    NumPy fallback below is the correctness oracle
    (tests/test_binned.py::test_native_plan_equals_numpy).

    At 100M-edge scale even the native build is minutes of host work per
    direction, so built plans are cached on disk keyed by the edge-list
    content and the full schedule-shaping input (geometry incl. group
    target, shape) — see _plan_cache_path.

    PLAN-CACHE HYGIENE (round 12): with ``tuned_ok`` (the default), a
    requested geometry that disagrees with a NEWER tuned-tier winner for
    this same edge content warns once and yields to the tuned config —
    the cache keys on the geometry, so without this check a plan cached
    before a sweep would keep hitting at its stale geometry forever.
    ``tuned_ok=False`` is the forced-A/B escape hatch (kernel_bench, the
    tuner's own trials, ROC_BINNED_GEOM overrides): build exactly what
    was asked."""
    from roc_tpu import native
    geom = (geom or _default_geom()).check()
    if tuned_ok and not os.environ.get("ROC_NO_TUNED"):
        try:
            from roc_tpu.tune import store as _tstore
            tg = _tstore.stale_plan_geom(edge_src, edge_dst, num_rows,
                                         table_rows, geom)
        except Exception:
            tg = None
        if tg is not None:
            geom = tg.check()
    if geom.grt:
        group_row_target = geom.grt
    cache = _plan_cache_path(edge_src, edge_dst, num_rows, table_rows,
                             group_row_target, geom)
    if cache is not None and os.path.exists(cache):
        with _obs_span("plan_cache_load", rows=num_rows,
                       edges=len(edge_src)):
            plan = _plan_cache_load(cache, num_rows, table_rows, geom)
        if plan is not None:
            _ledger_note_plan(plan, len(edge_src))
            return plan
    global _PLAN_BUILD_COUNT
    _PLAN_BUILD_COUNT += 1
    if len(edge_src) >= (1 << 20) and native.available():
        if geom.flat:
            (p1_srcl, p1_blk, p1_blk2, p1_dsrc, p1_ddst, p2_dstl, p2_obi,
             p2_first, bpg) = native.binned_flat_plan(
                 edge_src, edge_dst, num_rows, table_rows,
                 group_row_target, geom)
            G, C1 = p1_blk.shape
            C2 = p2_obi.shape[1]
            plan = _attach_fused(BinnedPlan(
                p1_srcl=jnp.asarray(p1_srcl.reshape(G, C1 * geom.ch, 1)),
                p1_off=None,
                p1_blk=jnp.asarray(p1_blk),
                p2_dstl=jnp.asarray(p2_dstl.reshape(G, C2 * geom.ch2, 1)),
                p2_obi=jnp.asarray(p2_obi),
                p2_first=jnp.asarray(p2_first),
                p1_blk2=jnp.asarray(p1_blk2),
                p1_dsrc=jnp.asarray(p1_dsrc),
                p1_ddst=jnp.asarray(p1_ddst),
                num_rows=num_rows, table_rows=table_rows,
                bins_per_group=bpg, geom=geom))
        else:
            (p1_srcl, p1_off, p1_blk, p2_dstl, p2_obi, p2_first,
             bpg) = native.binned_plan(edge_src, edge_dst, num_rows,
                                       table_rows, group_row_target, geom)
            G, C1 = p1_blk.shape
            C2 = p2_obi.shape[1]
            plan = BinnedPlan(
                p1_srcl=jnp.asarray(p1_srcl.reshape(G, C1 * geom.ch, 1)),
                p1_off=jnp.asarray(p1_off),
                p1_blk=jnp.asarray(p1_blk),
                p2_dstl=jnp.asarray(p2_dstl.reshape(G, C2 * geom.ch2, 1)),
                p2_obi=jnp.asarray(p2_obi),
                p2_first=jnp.asarray(p2_first),
                num_rows=num_rows, table_rows=table_rows,
                bins_per_group=bpg, geom=geom)
    else:
        plan = _build_binned_plan_numpy(edge_src, edge_dst, num_rows,
                                        table_rows, group_row_target, geom)
    if cache is not None:
        _plan_cache_save(cache, plan)
    _ledger_note_plan(plan, len(edge_src))
    return plan


def _plan_cache_dir() -> str:
    """Plan cache location; '' disables.  ROC_PLAN_CACHE=0 opts out,
    ROC_PLAN_CACHE_DIR overrides (tests point it at tmp dirs)."""
    if os.environ.get("ROC_PLAN_CACHE", "1") == "0":
        return ""
    return os.environ.get(
        "ROC_PLAN_CACHE_DIR",
        os.path.join(os.path.expanduser("~"), ".cache",
                     f"roc_plans_u{os.getuid()}"))


def _plan_cache_path(edge_src, edge_dst, num_rows, table_rows,
                     group_row_target, geom):
    """Content-keyed cache file for one built plan, or None when caching
    is off or the graph is below the worth-it threshold (hashing is O(E)
    but cheap — ~1 s/GB — next to the minutes-long 100M-edge build)."""
    min_edges = int(os.environ.get("ROC_PLAN_CACHE_MIN_EDGES", 1 << 24))
    base = _plan_cache_dir()
    if not base or len(edge_src) < min_edges:
        return None
    import hashlib
    h = hashlib.sha1()
    h.update(np.ascontiguousarray(edge_src, np.int64).tobytes())
    h.update(np.ascontiguousarray(edge_dst, np.int64).tobytes())
    # v3: the geometry tuple grew the flat-unit field (bf16 staging), so
    # v2 files no longer match any key — a bf16<->fp32 storage flip can
    # never hit a stale plan.  (v2 was the flat-schedule field itself.)
    h.update(repr(("v3", num_rows, table_rows, group_row_target,
                   tuple(geom))).encode())
    return os.path.join(base, f"binned_plan_{h.hexdigest()}.npz")


def _plan_cache_load(path, num_rows, table_rows, geom):
    """Best-effort load; None on any mismatch/corruption (rebuilds)."""
    try:
        with np.load(path) as z:
            meta = z["meta"]
            if (int(meta[0]) != num_rows or int(meta[1]) != table_rows
                    or tuple(int(v) for v in z["geom"]) != tuple(geom)):
                return None
            G = z["p1_blk"].shape[0]
            C1 = z["p1_blk"].shape[1]
            C2 = z["p2_obi"].shape[1]
            plan = BinnedPlan(
                p1_srcl=jnp.asarray(z["p1_srcl"].reshape(
                    G, C1 * geom.ch, 1)),
                p1_off=(jnp.asarray(z["p1_off"])
                        if not geom.flat else None),
                p1_blk=jnp.asarray(z["p1_blk"]),
                p2_dstl=jnp.asarray(z["p2_dstl"].reshape(
                    G, C2 * geom.ch2, 1)),
                p2_obi=jnp.asarray(z["p2_obi"]),
                p2_first=jnp.asarray(z["p2_first"]),
                p1_blk2=(jnp.asarray(z["p1_blk2"])
                         if geom.flat else None),
                p1_dsrc=(jnp.asarray(z["p1_dsrc"].reshape(
                    G, C1, geom.kd)) if geom.flat else None),
                p1_ddst=(jnp.asarray(z["p1_ddst"].reshape(
                    G, C1, geom.kd)) if geom.flat else None),
                num_rows=num_rows, table_rows=table_rows,
                bins_per_group=int(meta[2]), geom=geom)
            # fused step lists are NOT cached — rebuilt from the flat
            # arrays (cheap next to the plan build they key on)
            return _attach_fused(plan) if geom.flat else plan
    except Exception:
        return None


# Process-wide count of failed plan-cache saves.  A save failure is
# deliberately non-fatal (the plan is already in memory; only the NEXT
# process pays a rebuild) but it must not be silent either: a full disk
# or bad permissions turns every future cold start into a minutes-long
# rebuild.  Warn once per process, count every failure, and emit an obs
# JSONL record when a metrics sink is attached (roc_tpu/fault).
_PLAN_CACHE_SAVE_ERRORS = 0
_PLAN_CACHE_SAVE_WARNED = False


def plan_cache_save_errors() -> int:
    """How many plan-cache saves failed in this process (monotone)."""
    return _PLAN_CACHE_SAVE_ERRORS


def _plan_cache_save(path, plan: BinnedPlan) -> None:
    """Best-effort durable save (tmp + fsync + rename); failures don't
    propagate — they warn once, count, and land in the obs JSONL."""
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + f".{os.getpid()}.tmp.npz"   # savez keeps .npz as-is
        G = plan.p1_blk.shape[0]
        arrays = dict(
            p1_srcl=np.asarray(plan.p1_srcl).reshape(G, -1),
            p1_blk=np.asarray(plan.p1_blk),
            p2_dstl=np.asarray(plan.p2_dstl).reshape(G, -1),
            p2_obi=np.asarray(plan.p2_obi),
            p2_first=np.asarray(plan.p2_first),
            meta=np.asarray([plan.num_rows, plan.table_rows,
                             plan.bins_per_group], np.int64),
            geom=np.asarray(tuple(plan.geom), np.int64))
        if plan.geom.flat:
            arrays.update(
                p1_blk2=np.asarray(plan.p1_blk2),
                p1_dsrc=np.asarray(plan.p1_dsrc).reshape(G, -1),
                p1_ddst=np.asarray(plan.p1_ddst).reshape(G, -1))
        else:
            arrays["p1_off"] = np.asarray(plan.p1_off)
        np.savez(tmp, **arrays)
        from roc_tpu.fault import fsync_replace
        fsync_replace(tmp, path)
    except Exception as e:
        global _PLAN_CACHE_SAVE_ERRORS, _PLAN_CACHE_SAVE_WARNED
        _PLAN_CACHE_SAVE_ERRORS += 1
        from roc_tpu import fault as _fault
        _fault.emit_event("plan_cache_save_error", path=str(path),
                          error=f"{type(e).__name__}: {e}")
        if not _PLAN_CACHE_SAVE_WARNED:
            _PLAN_CACHE_SAVE_WARNED = True
            warnings.warn(
                f"binned plan-cache save to {path!r} failed "
                f"({type(e).__name__}: {e}); this run is unaffected but "
                f"the next cold start will rebuild the plan from scratch "
                f"(warning once; subsequent failures are counted in "
                f"plan_cache_save_errors() and the obs JSONL)")


def _build_binned_plan_numpy(edge_src: np.ndarray, edge_dst: np.ndarray,
                             num_rows: int, table_rows: int,
                             group_row_target: int = _GROUP_ROW_TARGET,
                             geom: Geometry = None) -> BinnedPlan:
    """The oracle plan builder (vectorized NumPy lexsort + prefix sums)."""
    geom = (geom or _default_geom()).check()
    if geom.grt:
        group_row_target = geom.grt
    if geom.flat:
        return _build_flat_plan_numpy(edge_src, edge_dst, num_rows,
                                      table_rows, group_row_target, geom)
    SB, CH, SLOT, RB, CH2 = geom[:5]      # noqa: N806 — shadow the module
    NSLOT, SLOT2 = geom.nslot, geom.slot2   # constants with plan geometry
    edge_src = np.asarray(edge_src, np.int64)
    edge_dst = np.asarray(edge_dst, np.int64)
    E = edge_src.shape[0]
    num_bins = max(-(-num_rows // RB), 1)
    num_blocks = max(-(-table_rows // SB), 1)

    bins_per_group = max(min(
        num_bins,
        # bins such that expected group rows ~ group_row_target:
        int(group_row_target / max(E / num_bins, 1)),
        _K2_CAP // num_blocks), 1)
    G = -(-num_bins // bins_per_group)

    bin_of = edge_dst // RB
    blk_of = edge_src // SB
    grp_of = bin_of // bins_per_group

    # Sort edges by (group, block, bin); order within a cell is free.
    order = np.lexsort((bin_of, blk_of, grp_of))
    s_src, s_dst = edge_src[order], edge_dst[order]
    s_bin, s_blk, s_grp = bin_of[order], blk_of[order], grp_of[order]

    # --- cells = (g, blk, bin), in sorted-edge order ----------------------
    cell_key = (s_grp * num_blocks + s_blk) * num_bins + s_bin
    uniq, cell_start, cell_cnt = np.unique(
        cell_key, return_index=True, return_counts=True)
    ncell = len(uniq)
    cell_slots = -(-cell_cnt // SLOT)
    cell_g = uniq // (num_bins * num_blocks)
    cell_lbin = (uniq % num_bins) - cell_g * bins_per_group

    # --- phase-1 layout: per (g, blk) stream, cells in order --------------
    gb_key = uniq // num_bins                      # g * num_blocks + blk
    gb_uniq, gb_inv = np.unique(gb_key, return_inverse=True)
    gb_slots = np.zeros(len(gb_uniq), np.int64)
    np.add.at(gb_slots, gb_inv, cell_slots)
    gb_chunks = -(-gb_slots // NSLOT)
    gb_g = gb_uniq // num_blocks
    c1_per_g = np.zeros(G, np.int64)
    np.add.at(c1_per_g, gb_g, gb_chunks)
    C1 = int(_pad_to(max(int(c1_per_g.max(initial=0)), 1), 8))
    # chunk base of each (g, blk) stream within its group:
    gb_chunk_base = _prefix_within_runs(gb_chunks, gb_g)
    # slot base of each cell within its (g, blk) stream:
    cell_p1_slot = _prefix_within_runs(cell_slots, gb_key)

    # --- phase-2 layout: per group, bins in order, block-major cells ------
    dense_bin_slots = np.zeros(G * bins_per_group, np.int64)
    bin_idx = cell_g * bins_per_group + cell_lbin
    np.add.at(dense_bin_slots, bin_idx, cell_slots)
    dense_bin_chunks = np.maximum(-(-dense_bin_slots // SLOT2), 1)
    c2_per_g = dense_bin_chunks.reshape(G, bins_per_group).sum(1)
    C2 = int(max(int(c2_per_g.max(initial=0)), 1))
    # bin chunk base within its group:
    bin_g = np.repeat(np.arange(G), bins_per_group)
    bin_chunk_base = _prefix_within_runs(dense_bin_chunks, bin_g)
    # cell slot base within its bin (cells grouped by bin, keeping the
    # block-major cell order):
    bo = np.argsort(bin_idx, kind="stable")
    cell_off_in_bin = np.zeros(ncell, np.int64)
    cell_off_in_bin[bo] = _prefix_within_runs(cell_slots[bo], bin_idx[bo])
    # absolute staging slot of each cell (group-local):
    cell_stg_slot = bin_chunk_base[bin_idx] * SLOT2 + cell_off_in_bin

    # --- per-edge positions ------------------------------------------------
    edge_cell = np.repeat(np.arange(ncell), cell_cnt)
    in_cell = np.arange(E) - np.repeat(cell_start, cell_cnt)
    p1_row = (gb_chunk_base[gb_inv[edge_cell]] * CH
              + cell_p1_slot[edge_cell] * SLOT + in_cell)
    stg_row = cell_stg_slot[edge_cell] * SLOT + in_cell

    # --- per-slot staging offsets ------------------------------------------
    total_slots = int(cell_slots.sum())
    slot_cell = np.repeat(np.arange(ncell), cell_slots)
    slot_in_cell = (np.arange(total_slots)
                    - np.repeat(np.cumsum(cell_slots) - cell_slots,
                                cell_slots))
    p1_slot_pos = (gb_chunk_base[gb_inv[slot_cell]] * NSLOT
                   + cell_p1_slot[slot_cell] + slot_in_cell)
    stg_slot = cell_stg_slot[slot_cell] + slot_in_cell

    # --- materialize -------------------------------------------------------
    p1_srcl = np.zeros((G, C1 * CH), np.int32)
    p1_blk = np.zeros((G, C1), np.int32)
    p1_off = np.full((G, C1, NSLOT), -1, np.int32)   # -1: skip (pad slot)
    g_of_edge = cell_g[edge_cell]
    p1_srcl[g_of_edge, p1_row] = (s_src - s_blk * SB).astype(np.int32)
    if len(gb_uniq):
        blk_rep = np.repeat(gb_uniq % num_blocks, gb_chunks)
        pos_rep = (np.repeat(gb_chunk_base, gb_chunks)
                   + _prefix_within_runs(np.ones_like(blk_rep),
                                         np.repeat(np.arange(len(gb_uniq)),
                                                   gb_chunks)))
        p1_blk[np.repeat(gb_g, gb_chunks), pos_rep] = blk_rep.astype(np.int32)
    g_of_slot = cell_g[slot_cell]
    p1_off[g_of_slot, p1_slot_pos // NSLOT,
           p1_slot_pos % NSLOT] = stg_slot.astype(np.int32)

    p2_dstl = np.full((G, C2 * CH2), RB, np.int32)
    p2_dstl[g_of_edge, stg_row] = (s_dst - s_bin * RB).astype(np.int32)
    p2_obi = np.zeros((G, C2), np.int32)
    p2_first = np.zeros((G, C2), np.int32)
    dbc = dense_bin_chunks.reshape(G, bins_per_group)
    for g in range(G):
        reps = dbc[g]
        obi = np.repeat(np.arange(bins_per_group), reps).astype(np.int32)
        first = np.zeros(len(obi), np.int32)
        first[np.cumsum(reps) - reps] = 1
        p2_obi[g, :len(obi)] = obi
        p2_first[g, :len(obi)] = first
        if len(obi) < C2:   # pad chunks: revisit last bin, add only zeros
            p2_obi[g, len(obi):] = obi[-1]
    return BinnedPlan(
        p1_srcl=jnp.asarray(p1_srcl.reshape(G, C1 * CH, 1)),
        p1_off=jnp.asarray(p1_off),
        p1_blk=jnp.asarray(p1_blk),
        p2_dstl=jnp.asarray(p2_dstl.reshape(G, C2 * CH2, 1)),
        p2_obi=jnp.asarray(p2_obi),
        p2_first=jnp.asarray(p2_first),
        num_rows=num_rows, table_rows=table_rows,
        bins_per_group=bins_per_group, geom=geom)


def _build_flat_plan_numpy(edge_src: np.ndarray, edge_dst: np.ndarray,
                           num_rows: int, table_rows: int,
                           group_row_target: int,
                           geom: Geometry) -> BinnedPlan:
    """Flat-schedule oracle builder (geom.flat): same sort and cell
    machinery as the slot builder, but cells pad to unit_rows-row units
    (8 for fp32 staging, 16 for the bf16 tile-aligned variant),
    phase-1 chunks pack back-to-back across a group's (block) streams via
    _flat_pack (a chunk may span two source blocks), and the slot-offset
    table is replaced by per-chunk run lists of size-classed staging
    copies (p1_dsrc/p1_ddst, consumed via scalar prefetch).  Phase 2 keeps
    the existing kernel: bins stay CH2-aligned in staging, one bin per
    chunk."""
    U = geom.unit_rows
    SB, CH, RB, CH2 = geom.sb, geom.ch, geom.rb, geom.ch2  # noqa: N806
    UC, U2, KD = CH // U, CH2 // U, geom.kd                # noqa: N806
    edge_src = np.asarray(edge_src, np.int64)
    edge_dst = np.asarray(edge_dst, np.int64)
    E = edge_src.shape[0]
    num_bins = max(-(-num_rows // RB), 1)
    num_blocks = max(-(-table_rows // SB), 1)
    bins_per_group = max(min(
        num_bins,
        int(group_row_target / max(E / num_bins, 1)),
        _K2_CAP // num_blocks), 1)
    G = -(-num_bins // bins_per_group)

    bin_of = edge_dst // RB
    blk_of = edge_src // SB
    grp_of = bin_of // bins_per_group
    order = np.lexsort((bin_of, blk_of, grp_of))
    s_src, s_dst = edge_src[order], edge_dst[order]
    s_bin, s_blk = bin_of[order], blk_of[order]

    cell_key = ((grp_of[order] * num_blocks + s_blk) * num_bins + s_bin)
    uniq, cell_start, cell_cnt = np.unique(
        cell_key, return_index=True, return_counts=True)
    ncell = len(uniq)
    cell_units = -(-cell_cnt // U)
    cell_g = uniq // (num_bins * num_blocks)
    cell_lbin = (uniq % num_bins) - cell_g * bins_per_group

    # --- phase-2 layout (units; bins CH2-aligned, block-major cells) ------
    dense_bin_units = np.zeros(G * bins_per_group, np.int64)
    bin_idx = cell_g * bins_per_group + cell_lbin
    np.add.at(dense_bin_units, bin_idx, cell_units)
    dense_bin_chunks = np.maximum(-(-dense_bin_units // U2), 1)
    c2_per_g = dense_bin_chunks.reshape(G, bins_per_group).sum(1)
    C2 = int(max(int(c2_per_g.max(initial=0)), 1))          # noqa: N806
    bin_g = np.repeat(np.arange(G), bins_per_group)
    bin_chunk_base = _prefix_within_runs(dense_bin_chunks, bin_g)
    bo = np.argsort(bin_idx, kind="stable")
    cell_off_in_bin = np.zeros(ncell, np.int64)
    cell_off_in_bin[bo] = _prefix_within_runs(cell_units[bo], bin_idx[bo])
    cell_stg_unit = bin_chunk_base[bin_idx] * U2 + cell_off_in_bin

    # --- phase-1 flat packing (shared state machine) ----------------------
    gb_key = uniq // num_bins                      # g * num_blocks + blk
    gb_uniq, gb_inv = np.unique(gb_key, return_inverse=True)
    gb_units = np.zeros(len(gb_uniq), np.int64)
    np.add.at(gb_units, gb_inv, cell_units)
    gb_g = gb_uniq // num_blocks
    c1_per_g, segs = _flat_pack(gb_g, gb_units, UC, G, segments=True)
    C1 = int(_pad_to(max(int(c1_per_g.max(initial=0)), 1), 8))  # noqa
    seg_stream, seg_chunk, seg_pos, seg_take = segs.T
    seg_g = gb_g[seg_stream]
    seg_blk = gb_uniq[seg_stream] % num_blocks

    # Per-chunk block pair: the pos==0 segment opens the chunk (primary);
    # any pos>0 segment is a different stream of the same group
    # (secondary).  blk2 == blk means single-block.
    p1_blk = np.zeros((G, C1), np.int32)
    opens = seg_pos == 0
    p1_blk[seg_g[opens], seg_chunk[opens]] = seg_blk[opens].astype(np.int32)
    p1_blk2 = p1_blk.copy()
    tails = ~opens
    p1_blk2[seg_g[tails], seg_chunk[tails]] = seg_blk[tails].astype(np.int32)

    # --- per-unit chunk positions (global unit order == segment order) ----
    total_units = int(cell_units.sum())
    unit_cell = np.repeat(np.arange(ncell), cell_units)
    cell_unit_base = np.cumsum(cell_units) - cell_units
    unit_in_cell = np.arange(total_units) - np.repeat(cell_unit_base,
                                                      cell_units)
    seg_start = np.cumsum(seg_take) - seg_take
    in_seg = np.arange(total_units) - np.repeat(seg_start, seg_take)
    unit_chunk = np.repeat(seg_chunk, seg_take)
    unit_pos = np.repeat(seg_pos, seg_take) + in_seg
    unit_stg = cell_stg_unit[unit_cell] + unit_in_cell
    unit_g = cell_g[unit_cell]

    # --- per-edge positions -----------------------------------------------
    edge_cell = np.repeat(np.arange(ncell), cell_cnt)
    in_cell = np.arange(E) - np.repeat(cell_start, cell_cnt)
    uid = cell_unit_base[edge_cell] + in_cell // U
    p1_row = unit_chunk[uid] * CH + unit_pos[uid] * U + in_cell % U
    stg_row = cell_stg_unit[edge_cell] * U + in_cell
    g_of_edge = cell_g[edge_cell]

    # Pad rows carry -1: no lane matches, so the one-hot emits an exact
    # zero row — staging pad rows are deterministic zeros (unlike the slot
    # schedule, whose pad slots are simply never written).
    p1_srcl = np.full((G, C1 * CH), -1, np.int32)
    local = s_src - s_blk * SB
    sec = (p1_blk[g_of_edge, unit_chunk[uid]] != s_blk).astype(np.int64)
    p1_srcl[g_of_edge, p1_row] = (local + SB * sec).astype(np.int32)

    p2_dstl = np.full((G, C2 * CH2), RB, np.int32)
    p2_dstl[g_of_edge, stg_row] = (s_dst - s_bin * RB).astype(np.int32)

    # --- staging-copy run lists -------------------------------------------
    # A run: consecutive chunk units writing consecutive staging units
    # (cell fragments; accidental cross-cell merges are valid copies).
    # Greedy 128/32/8-row decomposition, entries ordered by source unit
    # within each chunk (== per-run order, the native builder's layout).
    K = int(c1_per_g.max(initial=0)) + 1
    ckey = unit_g * K + unit_chunk
    if total_units:
        brk = np.concatenate([[True],
                              (ckey[1:] != ckey[:-1])
                              | (unit_stg[1:] != unit_stg[:-1] + 1)])
    else:
        brk = np.zeros(0, bool)
    run_start = np.flatnonzero(brk)
    run_len = np.diff(np.concatenate([run_start, [total_units]]))
    run_pos0 = unit_pos[run_start] if total_units else run_start
    run_stg0 = unit_stg[run_start] if total_units else run_start
    run_key = ckey[run_start] if total_units else run_start
    ent_src, ent_dst, ent_cls, ent_key = [], [], [], []
    off = np.zeros(len(run_start), np.int64)
    for ci, csz in enumerate(_DMA_CLS):
        k = (run_len - off) // csz
        rep = np.repeat(np.arange(len(run_start)), k)
        within = np.arange(len(rep)) - np.repeat(np.cumsum(k) - k, k)
        start = off[rep] + within * csz
        ent_src.append(run_pos0[rep] + start)
        ent_dst.append(run_stg0[rep] + start)
        ent_cls.append(np.full(len(rep), ci, np.int64))
        ent_key.append(run_key[rep])
        off += k * csz
    ent_src = np.concatenate(ent_src)
    ent_dst = np.concatenate(ent_dst)
    ent_cls = np.concatenate(ent_cls)
    ent_key = np.concatenate(ent_key)
    eo = np.lexsort((ent_src, ent_key))
    ent_src, ent_dst = ent_src[eo], ent_dst[eo]
    ent_cls, ent_key = ent_cls[eo], ent_key[eo]
    epos = _prefix_within_runs(np.ones(len(ent_key), np.int64), ent_key)
    assert len(epos) == 0 or int(epos.max()) < KD
    p1_dsrc = np.full((G, C1, KD), -1, np.int32)
    p1_ddst = np.full((G, C1, KD), -1, np.int32)
    p1_dsrc[ent_key // K, ent_key % K, epos] = \
        (ent_cls * 65536 + ent_src).astype(np.int32)
    p1_ddst[ent_key // K, ent_key % K, epos] = ent_dst.astype(np.int32)

    # --- phase-2 chunk metadata (same as the slot schedule) ---------------
    p2_obi = np.zeros((G, C2), np.int32)
    p2_first = np.zeros((G, C2), np.int32)
    dbc = dense_bin_chunks.reshape(G, bins_per_group)
    for g in range(G):
        reps = dbc[g]
        obi = np.repeat(np.arange(bins_per_group), reps).astype(np.int32)
        first = np.zeros(len(obi), np.int32)
        first[np.cumsum(reps) - reps] = 1
        p2_obi[g, :len(obi)] = obi
        p2_first[g, :len(obi)] = first
        if len(obi) < C2:
            p2_obi[g, len(obi):] = obi[-1]
    plan = BinnedPlan(
        p1_srcl=jnp.asarray(p1_srcl.reshape(G, C1 * CH, 1)),
        p1_off=None,
        p1_blk=jnp.asarray(p1_blk),
        p2_dstl=jnp.asarray(p2_dstl.reshape(G, C2 * CH2, 1)),
        p2_obi=jnp.asarray(p2_obi),
        p2_first=jnp.asarray(p2_first),
        p1_blk2=jnp.asarray(p1_blk2),
        p1_dsrc=jnp.asarray(p1_dsrc),
        p1_ddst=jnp.asarray(p1_ddst),
        num_rows=num_rows, table_rows=table_rows,
        bins_per_group=bins_per_group, geom=geom)
    return _attach_fused(plan)


def _attach_fused(plan: BinnedPlan) -> BinnedPlan:
    """Build the interleaved phase-fusion step list onto a flat plan when
    an entire group's staging fits the VMEM gate (ch == ch2 and
    C2 * ch2 <= _FUSE_MAX_STG_ROWS) — phase 2 of group g then consumes
    VMEM-resident staging while phase 1 of group g+1 streams, removing the
    HBM staging round-trip.  Otherwise returns the plan unchanged (flat
    two-pass).  Built host-side at plan/cache/pad time: inside jit the
    plan arrays are tracers, so the schedule cannot be derived at trace
    time.  run_binned re-gates on the real H before using it."""
    geom = plan.geom
    if not (geom is not None and geom.flat and geom.ch == geom.ch2):
        return plan
    G, C2 = plan.p2_obi.shape
    if C2 * geom.ch2 > _FUSE_MAX_STG_ROWS:
        return plan
    CH, RB, KD, bpg = geom.ch, geom.rb, geom.kd, plan.bins_per_group
    srcl = np.asarray(plan.p1_srcl).reshape(G, -1)
    dstl = np.asarray(plan.p2_dstl).reshape(G, -1)
    blk = np.asarray(plan.p1_blk)
    blk2 = np.asarray(plan.p1_blk2)
    dsrc = np.asarray(plan.p1_dsrc)
    ddst = np.asarray(plan.p1_ddst)
    obi = np.asarray(plan.p2_obi)
    first = np.asarray(plan.p2_first)
    C1 = blk.shape[1]
    # Real (non-pad) chunks: a real phase-1 chunk's first unit row is a
    # live edge (srcl >= 0); a real phase-2 chunk either opens its bin
    # (first=1 — required even for empty bins: it zeroes the window) or
    # carries live rows.  Pad chunks are skipped outright.
    p1_real = [[c for c in range(C1) if srcl[g, c * CH] >= 0]
               for g in range(G)]
    p2_real = [[q for q in range(C2)
                if first[g, q] == 1
                or (dstl[g, q * CH:(q + 1) * CH] < RB).any()]
               for g in range(G)]
    steps = [(0, 0, c) for c in p1_real[0]]
    for g in range(G):
        a = [(1, g, q) for q in p2_real[g]]
        b = ([(0, g + 1, c) for c in p1_real[g + 1]]
             if g + 1 < G else [])
        for i in range(max(len(a), len(b))):
            if i < len(a):
                steps.append(a[i])
            if i < len(b):
                steps.append(b[i])
    S = _pad_to(max(len(steps), 1), 8)
    f_meta = np.zeros((S, 4), np.int32)
    f_rows = np.full((S, CH), RB, np.int32)   # pad steps: masked p2 no-op
    f_blk = np.zeros(S, np.int32)
    f_blk2 = np.zeros(S, np.int32)
    f_obi = np.zeros(S, np.int32)
    f_dsrc = np.full((S, KD), -1, np.int32)
    f_ddst = np.full((S, KD), -1, np.int32)
    f_meta[:, 0] = 1                           # pad steps are kind=p2
    # Last real p2 chunk of each output bin: the megakernel applies its
    # in-register activation there (the bin's accumulation is complete;
    # the out index is nondecreasing, so no later step reopens it — pad
    # steps revisit the bin but only add exact zeros, which commute with
    # ReLU).  Kept as a separate [S] array rather than a fifth f_meta
    # column so the existing (8, 4) SMEM BlockSpec stays untouched.
    f_last = np.zeros(S, np.int32)
    cur_blk = cur_blk2 = cur_obi = 0
    prev_p2 = -1
    for i, (kind, g, c) in enumerate(steps):
        if kind == 0:
            cur_blk, cur_blk2 = int(blk[g, c]), int(blk2[g, c])
            f_meta[i] = (0, g % 2, 0, 0)
            f_rows[i] = srcl[g, c * CH:(c + 1) * CH]
            f_dsrc[i] = dsrc[g, c]
            f_ddst[i] = ddst[g, c]
        else:
            nxt = g * bpg + int(obi[g, c])
            if prev_p2 >= 0 and nxt != cur_obi:
                f_last[prev_p2] = 1
            cur_obi = nxt
            prev_p2 = i
            f_meta[i] = (1, g % 2, int(first[g, c]), c)
            f_rows[i] = dstl[g, c * CH:(c + 1) * CH]
        f_blk[i], f_blk2[i], f_obi[i] = cur_blk, cur_blk2, cur_obi
    if prev_p2 >= 0:
        f_last[prev_p2] = 1
    if len(steps) < S:                         # pad: revisit the last bin
        f_meta[len(steps):, 1] = steps[-1][1] % 2 if steps else 0
        f_blk[len(steps):] = cur_blk
        f_blk2[len(steps):] = cur_blk2
        f_obi[len(steps):] = cur_obi
    return dataclasses.replace(
        plan,
        f_meta=jnp.asarray(f_meta),
        f_rows=jnp.asarray(f_rows.reshape(S * CH, 1)),
        f_blk=jnp.asarray(f_blk),
        f_blk2=jnp.asarray(f_blk2),
        f_obi=jnp.asarray(f_obi),
        f_dsrc=jnp.asarray(f_dsrc),
        f_ddst=jnp.asarray(f_ddst),
        f_last=jnp.asarray(f_last))


# ---------------------------------------------------------------------------
# Phase-1 kernel: one-hot expand + slot-scatter to staging.
# ---------------------------------------------------------------------------

def _onehot_dot(t, xv, dims, exact: bool):
    """One-hot contraction at either precision.

    fast: single bf16 pass (the designed feature rounding).  exact: split
    the fp32 operand into hi/mid/lo bf16 (bf16 roundings of successive
    residuals; 3 x 8 mantissa bits cover fp32's 24), dot each against the
    exact one-hot factor, sum in fp32 — bit-exact row selection/summation
    up to fp32 accumulation order."""
    if not exact:
        return jax.lax.dot_general(t, xv.astype(jnp.bfloat16), dims,
                                   preferred_element_type=jnp.float32)
    xf = xv.astype(jnp.float32)
    hi = xf.astype(jnp.bfloat16)
    r1 = xf - hi.astype(jnp.float32)
    mid = r1.astype(jnp.bfloat16)
    lo = (r1 - mid.astype(jnp.float32)).astype(jnp.bfloat16)
    out = jax.lax.dot_general(t, hi, dims,
                              preferred_element_type=jnp.float32)
    out += jax.lax.dot_general(t, mid, dims,
                               preferred_element_type=jnp.float32)
    out += jax.lax.dot_general(t, lo, dims,
                               preferred_element_type=jnp.float32)
    return out


def _stg_dtype(exact: bool):
    return jnp.float32 if exact else jnp.bfloat16


def _p1_kernel_simple(blk_ref, off_ref, srcl_ref, x_ref, stg_ref, gbuf,
                      offbuf, sems, *, exact: bool = False,
                      geom: Geometry = None):
    """Single-buffered fallback (ROC_BINNED_NO_PIPELINE=1): issue all slot
    DMAs then drain them in the same chunk.  No cross-chunk overlap, but
    structurally identical to the skeleton measured on hardware — keep as
    the bisection baseline if the pipelined kernel misbehaves on a new
    Mosaic version."""
    CH, SB, SLOT, NSLOT = geom.ch, geom.sb, geom.slot, geom.nslot  # noqa
    c = pl.program_id(0)

    lane = jax.lax.broadcasted_iota(jnp.int32, (CH, SB), 1)
    t = (lane == srcl_ref[:]).astype(jnp.bfloat16)
    gbuf[0] = _onehot_dot(t, x_ref[:], (((1,), (0,)), ((), ())),
                          exact).astype(_stg_dtype(exact))

    def issue(s, _):
        @pl.when(off_ref[c % 8, s] >= 0)
        def _():
            pltpu.make_async_copy(
                gbuf.at[0].at[pl.ds(s * SLOT, SLOT)],
                stg_ref.at[pl.ds(off_ref[c % 8, s] * SLOT, SLOT)],
                sems.at[0]).start()
        return 0
    jax.lax.fori_loop(0, NSLOT, issue, 0)

    def drain(s, _):
        @pl.when(off_ref[c % 8, s] >= 0)
        def _():
            pltpu.make_async_copy(
                gbuf.at[0].at[pl.ds(s * SLOT, SLOT)],
                stg_ref.at[pl.ds(off_ref[c % 8, s] * SLOT, SLOT)],
                sems.at[0]).wait()
        return 0
    jax.lax.fori_loop(0, NSLOT, drain, 0)


def _p1_kernel(blk_ref, off_ref, srcl_ref, x_ref, stg_ref, gbuf, offbuf,
               sems, *, exact: bool = False, geom: Geometry = None):
    """Double-buffered: the slot DMAs issued for chunk c drain at chunk
    c+2 (same gbuf parity), so the writes of one chunk overlap the next
    chunk's one-hot matmul.  ``offbuf`` keeps each parity's issued offsets
    (the wait must reconstruct the same descriptors); pad slots carry
    offset -1 and are skipped — per-block chunk rounding makes them
    20-40% of all slots, so not writing them matters."""
    CH, SB, SLOT, NSLOT = geom.ch, geom.sb, geom.slot, geom.nslot  # noqa
    c = pl.program_id(0)
    par = c % 2

    def drain_parity(p):
        def drain(s, _):
            @pl.when(offbuf[p, s] >= 0)
            def _():
                pltpu.make_async_copy(
                    gbuf.at[p].at[pl.ds(s * SLOT, SLOT)],
                    stg_ref.at[pl.ds(offbuf[p, s] * SLOT, SLOT)],
                    sems.at[p]).wait()
            return 0
        jax.lax.fori_loop(0, NSLOT, drain, 0)

    @pl.when(c >= 2)            # chunk c-2 used this parity's buffers
    def _():
        drain_parity(par)

    lane = jax.lax.broadcasted_iota(jnp.int32, (CH, SB), 1)
    t = (lane == srcl_ref[:]).astype(jnp.bfloat16)
    gbuf[par] = _onehot_dot(t, x_ref[:], (((1,), (0,)), ((), ())),
                            exact).astype(_stg_dtype(exact))

    # off rides in (8, NSLOT) SMEM blocks; this chunk's row is c % 8.
    def issue(s, _):
        offbuf[par, s] = off_ref[c % 8, s]
        @pl.when(off_ref[c % 8, s] >= 0)
        def _():
            pltpu.make_async_copy(
                gbuf.at[par].at[pl.ds(s * SLOT, SLOT)],
                stg_ref.at[pl.ds(off_ref[c % 8, s] * SLOT, SLOT)],
                sems.at[par]).start()
        return 0
    jax.lax.fori_loop(0, NSLOT, issue, 0)

    # Last chunk: drain everything still in flight (both parities) —
    # pallas does not wait for manual DMAs at grid end.
    @pl.when(c == pl.num_programs(0) - 1)
    def _():
        drain_parity(par)

        @pl.when(c >= 1)
        def _():
            drain_parity(1 - par)


@partial(jax.jit, static_argnames=("nchunks", "stg_rows", "interpret",
                                   "exact", "geom"))
def _p1_run(x, blk, off, srcl, nchunks: int, stg_rows: int,
            interpret: bool = False, exact: bool = False,
            geom: Geometry = None):
    kernel = _p1_kernel_simple \
        if os.environ.get("ROC_BINNED_NO_PIPELINE") else _p1_kernel
    kernel = partial(kernel, exact=exact, geom=geom)
    H = x.shape[-1]
    st = _stg_dtype(exact)
    CH, SB, NSLOT = geom.ch, geom.sb, geom.nslot                   # noqa
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,                  # blk [C1]
        grid=(nchunks,),
        in_specs=[
            pl.BlockSpec((8, NSLOT), lambda c, blk: (c // 8, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((CH, 1), lambda c, blk: (c, 0)),
            pl.BlockSpec((SB, H), lambda c, blk: (blk[c], 0)),
        ],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        scratch_shapes=[pltpu.VMEM((2, CH, H), st),
                        pltpu.SMEM((2, NSLOT), jnp.int32),
                        pltpu.SemaphoreType.DMA((2,))],
    )
    return pl.pallas_call(
        kernel, grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((stg_rows, H), st),
        interpret=interpret,
    )(blk, off, srcl, x)


def _flat_copy(gbuf, stg_ref, sems, p, v, du, start: bool,
               unit: int = _UNIT):
    """One size-classed staging copy from a packed descriptor: v encodes
    cls<<16 | source unit, du is the destination unit.  Three static
    branches — pl.ds sizes must be compile-time — of 16/4/1 units
    (128/32/8 rows fp32, 256/64/16 rows bf16; either way every slice is
    whole sublane tiles of the staging dtype)."""
    cls = v // 65536
    su = v - cls * 65536
    for ci, csz in enumerate(_DMA_CLS):
        @pl.when(cls == ci)
        def _(csz=csz):
            cp = pltpu.make_async_copy(
                gbuf.at[p].at[pl.ds(su * unit, csz * unit)],
                stg_ref.at[pl.ds(du * unit, csz * unit)],
                sems.at[p])
            (cp.start if start else cp.wait)()


def _p1_flat_kernel(blk_ref, blk2_ref, dsrc_ref, ddst_ref, srcl_ref,
                    x_ref, x2_ref, stg_ref, gbuf, dbs, dbd, sems, *,
                    exact: bool = False, geom: Geometry = None,
                    pipeline: bool = True):
    """Flat-schedule phase 1: every grid step is a full-width chunk.  The
    one-hot expands against TWO x blocks (srcl in [0, SB) hits the
    primary, [SB, 2SB) the secondary — a chunk spans at most two source
    blocks by plan construction; -1 pad rows match nothing and stage
    exact zeros), then the chunk scatters to bin-major staging via the
    plan's size-classed copy run list (KD descriptors, SMEM).  Double
    buffering mirrors _p1_kernel: copies issued for chunk c drain at
    c+2, with dbs/dbd keeping each parity's descriptors for the wait;
    pipeline=False is the ROC_BINNED_NO_PIPELINE bisection baseline."""
    CH, SB, KD = geom.ch, geom.sb, geom.kd                         # noqa
    U = geom.unit_rows
    st = staging_dtype(geom, exact)
    c = pl.program_id(0)
    par = c % 2 if pipeline else 0

    def drain_parity(p):
        def drain(e, _):
            @pl.when(dbs[p, e] >= 0)
            def _():
                _flat_copy(gbuf, stg_ref, sems, p, dbs[p, e], dbd[p, e],
                           start=False, unit=U)
            return 0
        jax.lax.fori_loop(0, KD, drain, 0)

    if pipeline:
        @pl.when(c >= 2)        # chunk c-2 used this parity's buffers
        def _():
            drain_parity(par)

    lane = jax.lax.broadcasted_iota(jnp.int32, (CH, SB), 1)
    sl = srcl_ref[:]
    t1 = (lane == sl).astype(jnp.bfloat16)
    gbuf[par] = _onehot_dot(t1, x_ref[:], (((1,), (0,)), ((), ())),
                            exact).astype(st)

    @pl.when(blk2_ref[c] != blk_ref[c])
    def _():
        # secondary-block rows (disjoint from the primary's by the
        # +SB encoding, so the sum is exact row selection — each row is
        # rounded to the staging dtype exactly once)
        t2 = (lane == sl - SB).astype(jnp.bfloat16)
        gbuf[par] = (gbuf[par].astype(jnp.float32) + _onehot_dot(
            t2, x2_ref[:], (((1,), (0,)), ((), ())), exact)).astype(st)

    # descriptors ride in (8, KD) SMEM blocks; this chunk's row is c % 8
    def issue(e, _):
        v = dsrc_ref[c % 8, e]
        dbs[par, e] = v
        dbd[par, e] = ddst_ref[c % 8, e]

        @pl.when(v >= 0)
        def _():
            _flat_copy(gbuf, stg_ref, sems, par, v, ddst_ref[c % 8, e],
                       start=True, unit=U)
        return 0
    jax.lax.fori_loop(0, KD, issue, 0)

    if pipeline:
        @pl.when(c == pl.num_programs(0) - 1)
        def _():
            drain_parity(par)

            @pl.when(c >= 1)
            def _():
                drain_parity(1 - par)
    else:
        drain_parity(0)


@partial(jax.jit, static_argnames=("nchunks", "stg_rows", "interpret",
                                   "exact", "geom"))
def _p1_flat_run(x, blk, blk2, dsrc, ddst, srcl, nchunks: int,
                 stg_rows: int, interpret: bool = False,
                 exact: bool = False, geom: Geometry = None):
    pipeline = not os.environ.get("ROC_BINNED_NO_PIPELINE")
    kernel = partial(_p1_flat_kernel, exact=exact, geom=geom,
                     pipeline=pipeline)
    H = x.shape[-1]
    CH, SB, KD = geom.ch, geom.sb, geom.kd                         # noqa
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,                  # blk, blk2 [C1]
        grid=(nchunks,),
        in_specs=[
            pl.BlockSpec((8, KD), lambda c, blk, blk2: (c // 8, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((8, KD), lambda c, blk, blk2: (c // 8, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((CH, 1), lambda c, blk, blk2: (c, 0)),
            pl.BlockSpec((SB, H), lambda c, blk, blk2: (blk[c], 0)),
            pl.BlockSpec((SB, H), lambda c, blk, blk2: (blk2[c], 0)),
        ],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        # flat staging dtype follows the geometry: fp32 for 8-row units
        # (bf16 (16,128) tiles would tear), bf16 for the 16-row unit
        # variant; gbuf matches so DMA src/dst dtypes agree
        scratch_shapes=[pltpu.VMEM((2, CH, H), staging_dtype(geom, exact)),
                        pltpu.SMEM((2, KD), jnp.int32),
                        pltpu.SMEM((2, KD), jnp.int32),
                        pltpu.SemaphoreType.DMA((2,))],
    )
    return pl.pallas_call(
        kernel, grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((stg_rows, H),
                                       staging_dtype(geom, exact)),
        interpret=interpret,
    )(blk, blk2, dsrc, ddst, srcl, x, x)


# ---------------------------------------------------------------------------
# Phase-2 kernel: sequential staging read + windowed one-hot scatter.
# ---------------------------------------------------------------------------

def _p2_kernel(obi_ref, first_ref, dstl_ref, stg_ref, out_ref, *,
               exact: bool = False, geom: Geometry = None):
    CH2, RB = geom.ch2, geom.rb                                    # noqa
    c = pl.program_id(0)

    @pl.when(first_ref[c] == 1)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    # Zero-mask pad/garbage rows BEFORE the dot: a 0 one-hot coefficient
    # alone would still propagate NaN garbage (0 * NaN = NaN).
    zero = _stg_dtype(exact)(0)
    rows = jnp.where(dstl_ref[:] == RB, zero, stg_ref[:])
    lane = jax.lax.broadcasted_iota(jnp.int32, (CH2, RB), 1)
    s_t = (lane == dstl_ref[:]).astype(jnp.bfloat16)   # [CH2, RB]
    out_ref[:] += _onehot_dot(s_t, rows, (((0,), (0,)), ((), ())), exact)


@partial(jax.jit, static_argnames=("nchunks", "out_rows", "interpret",
                                   "exact", "geom"))
def _p2_run(stg, obi, first, dstl, nchunks: int, out_rows: int,
            interpret: bool = False, exact: bool = False,
            geom: Geometry = None):
    H = stg.shape[-1]
    CH2, RB = geom.ch2, geom.rb                                    # noqa
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,                  # obi, first
        grid=(nchunks,),
        in_specs=[
            pl.BlockSpec((CH2, 1), lambda c, obi, first: (c, 0)),
            pl.BlockSpec((CH2, H), lambda c, obi, first: (c, 0)),
        ],
        out_specs=pl.BlockSpec((RB, H), lambda c, obi, first: (obi[c], 0)),
    )
    return pl.pallas_call(
        partial(_p2_kernel, exact=exact, geom=geom), grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((out_rows, H), jnp.float32),
        interpret=interpret,
    )(obi, first, dstl, stg)


# ---------------------------------------------------------------------------
# Fused pipeline: phase-1/phase-2 steps interleaved in ONE grid, staging
# resident in VMEM (flat plans whose whole group fits the budget).
# ---------------------------------------------------------------------------

def _fused_kernel(blk_ref, blk2_ref, obi_ref, meta_ref, dsrc_ref, ddst_ref,
                  rows_ref, x_ref, x2_ref, out_ref, gbuf, stgbuf, sems, *,
                  exact: bool = False, geom: Geometry = None):
    """One grid step = one plan-scheduled step: kind 0 (phase 1) expands
    a chunk and copies it into the VMEM-resident staging parity of its
    group; kind 1 (phase 2) scatter-adds one staging chunk of that parity
    into the resident out bin.  Group parities alternate, so phase 2 of
    group g reads parity g%2 while phase 1 of group g+1 fills the other —
    the interleave order (plan-built, _attach_fused) guarantees p1(g)
    precedes p2(g) and p2(g) completes before p1(g+2) reuses its parity.
    The out index (global bin) is nondecreasing, so out windows are never
    revisited after writeback; every bin opens with first=1, which zeroes
    the fetched garbage."""
    CH, SB, RB, KD = geom.ch, geom.sb, geom.rb, geom.kd            # noqa
    U = geom.unit_rows
    st = staging_dtype(geom, exact)
    c = pl.program_id(0)
    kind = meta_ref[c % 8, 0]
    par = meta_ref[c % 8, 1]
    first = meta_ref[c % 8, 2]
    sq = meta_ref[c % 8, 3]

    @pl.when(kind == 0)
    def _():
        lane = jax.lax.broadcasted_iota(jnp.int32, (CH, SB), 1)
        sl = rows_ref[:]
        t1 = (lane == sl).astype(jnp.bfloat16)
        gbuf[:] = _onehot_dot(t1, x_ref[:], (((1,), (0,)), ((), ())),
                              exact).astype(st)

        @pl.when(blk2_ref[c] != blk_ref[c])
        def _():
            t2 = (lane == sl - SB).astype(jnp.bfloat16)
            gbuf[:] = (gbuf[:].astype(jnp.float32) + _onehot_dot(
                t2, x2_ref[:], (((1,), (0,)), ((), ())), exact)).astype(st)

        # VMEM->VMEM staging copies: issue all, drain all within the step
        # (the overlap is across phases here, not across copies)
        def issue(e, _):
            v = dsrc_ref[c % 8, e]

            @pl.when(v >= 0)
            def _():
                cls = v // 65536
                su = v - cls * 65536
                du = ddst_ref[c % 8, e]
                for ci, csz in enumerate(_DMA_CLS):
                    @pl.when(cls == ci)
                    def _(csz=csz):
                        pltpu.make_async_copy(
                            gbuf.at[pl.ds(su * U, csz * U)],
                            stgbuf.at[par].at[
                                pl.ds(du * U, csz * U)],
                            sems.at[0]).start()
            return 0
        jax.lax.fori_loop(0, KD, issue, 0)

        def drain(e, _):
            v = dsrc_ref[c % 8, e]

            @pl.when(v >= 0)
            def _():
                cls = v // 65536
                su = v - cls * 65536
                du = ddst_ref[c % 8, e]
                for ci, csz in enumerate(_DMA_CLS):
                    @pl.when(cls == ci)
                    def _(csz=csz):
                        pltpu.make_async_copy(
                            gbuf.at[pl.ds(su * U, csz * U)],
                            stgbuf.at[par].at[
                                pl.ds(du * U, csz * U)],
                            sems.at[0]).wait()
            return 0
        jax.lax.fori_loop(0, KD, drain, 0)

    @pl.when(kind == 1)
    def _():
        @pl.when(first == 1)
        def _():
            out_ref[:] = jnp.zeros_like(out_ref)

        dl = rows_ref[:]
        chunk = stgbuf[par, pl.ds(sq * CH, CH)]
        rows = jnp.where(dl == RB, jnp.float32(0), chunk)
        lane = jax.lax.broadcasted_iota(jnp.int32, (CH, RB), 1)
        s_t = (lane == dl).astype(jnp.bfloat16)
        out_ref[:] += _onehot_dot(s_t, rows, (((0,), (0,)), ((), ())),
                                  exact)


@partial(jax.jit, static_argnames=("nsteps", "c2", "out_rows", "interpret",
                                   "exact", "geom"))
def _fused_run(x, blk, blk2, obi, meta, dsrc, ddst, rows, nsteps: int,
               c2: int, out_rows: int, interpret: bool = False,
               exact: bool = False, geom: Geometry = None):
    H = x.shape[-1]
    CH, SB, RB, KD = geom.ch, geom.sb, geom.rb, geom.kd            # noqa
    srows = c2 * geom.ch2
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,                  # blk, blk2, obi [S]
        grid=(nsteps,),
        in_specs=[
            pl.BlockSpec((8, 4), lambda c, b, b2, o: (c // 8, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((8, KD), lambda c, b, b2, o: (c // 8, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((8, KD), lambda c, b, b2, o: (c // 8, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((CH, 1), lambda c, b, b2, o: (c, 0)),
            pl.BlockSpec((SB, H), lambda c, b, b2, o: (b[c], 0)),
            pl.BlockSpec((SB, H), lambda c, b, b2, o: (b2[c], 0)),
        ],
        out_specs=pl.BlockSpec((RB, H), lambda c, b, b2, o: (o[c], 0)),
        scratch_shapes=[pltpu.VMEM((CH, H), staging_dtype(geom, exact)),
                        pltpu.VMEM((2, srows, H),
                                   staging_dtype(geom, exact)),
                        pltpu.SemaphoreType.DMA((1,))],
    )
    return pl.pallas_call(
        partial(_fused_kernel, exact=exact, geom=geom),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((out_rows, H), jnp.float32),
        interpret=interpret,
    )(blk, blk2, obi, meta, dsrc, ddst, rows, x, x)


def _fused_vmem_ok(geom: Geometry, Hp: int, c2: int) -> bool:
    """Trace-time gate for actually RUNNING a stored fused schedule at
    this width: both staging parities + gbuf + the one-hot intermediates
    + two x blocks + the out window must fit the VMEM budget."""
    srows = c2 * geom.ch2
    stg = staging_itemsize(geom, False)
    need = (2 * srows * Hp * stg + geom.ch * Hp * stg
            + max(geom.ch * geom.sb, geom.ch2 * geom.rb) * 2
            + 2 * geom.sb * Hp * 4 + geom.rb * Hp * 4)
    return need <= _VMEM_BUDGET


# ---------------------------------------------------------------------------
# Whole-layer megakernel: aggregate -> linear (-> ReLU) in the SAME fused
# grid (round 10, docs/DESIGN.md §Megakernel).  Each phase-2 step's [RB, H]
# aggregation tile stays in registers/VMEM and feeds the MXU weight matmul
# directly; only the post-linear (optionally post-ReLU) [RB, H_out] window
# ever reaches HBM — the [rows, H_in] aggregate never materializes.
# ---------------------------------------------------------------------------

def _mega_kernel(blk_ref, blk2_ref, obi_ref, last_ref, meta_ref, dsrc_ref,
                 ddst_ref, rows_ref, x_ref, x2_ref, w_ref, out_ref, gbuf,
                 stgbuf, sems, *, exact: bool = False,
                 geom: Geometry = None, relu: bool = False):
    """_fused_kernel with the layer's W matmul grafted onto every phase-2
    step.  Kind 0 (phase 1) is byte-identical to the fused kernel; kind 1
    scatter-adds one staging chunk into a per-chunk [RB, H] aggregate
    tile, then accumulates tile @ W into the resident [RB, H_out] out
    window (fp32, `highest` — the ops.linear fp32 contract).  Correct per
    chunk because matmul distributes over the bin's chunk sum:
    sum_c(tile_c) @ W == sum_c(tile_c @ W) exactly on fp32 adds of the
    same addends.  The optional ReLU applies on the bin's LAST real chunk
    (f_last; the out index is nondecreasing so the window is still
    resident) — pad-step revisits add exact zeros, which commute with it.
    The weight rides a constant-index BlockSpec: fetched into VMEM once
    and double-buffer-stable across the whole grid (the index map never
    changes, so pallas never refetches it alongside the parity staging).
    """
    CH, SB, RB, KD = geom.ch, geom.sb, geom.rb, geom.kd            # noqa
    U = geom.unit_rows
    st = staging_dtype(geom, exact)
    c = pl.program_id(0)
    kind = meta_ref[c % 8, 0]
    par = meta_ref[c % 8, 1]
    first = meta_ref[c % 8, 2]
    sq = meta_ref[c % 8, 3]

    @pl.when(kind == 0)
    def _():
        lane = jax.lax.broadcasted_iota(jnp.int32, (CH, SB), 1)
        sl = rows_ref[:]
        t1 = (lane == sl).astype(jnp.bfloat16)
        gbuf[:] = _onehot_dot(t1, x_ref[:], (((1,), (0,)), ((), ())),
                              exact).astype(st)

        @pl.when(blk2_ref[c] != blk_ref[c])
        def _():
            t2 = (lane == sl - SB).astype(jnp.bfloat16)
            gbuf[:] = (gbuf[:].astype(jnp.float32) + _onehot_dot(
                t2, x2_ref[:], (((1,), (0,)), ((), ())), exact)).astype(st)

        def issue(e, _):
            v = dsrc_ref[c % 8, e]

            @pl.when(v >= 0)
            def _():
                cls = v // 65536
                su = v - cls * 65536
                du = ddst_ref[c % 8, e]
                for ci, csz in enumerate(_DMA_CLS):
                    @pl.when(cls == ci)
                    def _(csz=csz):
                        pltpu.make_async_copy(
                            gbuf.at[pl.ds(su * U, csz * U)],
                            stgbuf.at[par].at[
                                pl.ds(du * U, csz * U)],
                            sems.at[0]).start()
            return 0
        jax.lax.fori_loop(0, KD, issue, 0)

        def drain(e, _):
            v = dsrc_ref[c % 8, e]

            @pl.when(v >= 0)
            def _():
                cls = v // 65536
                su = v - cls * 65536
                du = ddst_ref[c % 8, e]
                for ci, csz in enumerate(_DMA_CLS):
                    @pl.when(cls == ci)
                    def _(csz=csz):
                        pltpu.make_async_copy(
                            gbuf.at[pl.ds(su * U, csz * U)],
                            stgbuf.at[par].at[
                                pl.ds(du * U, csz * U)],
                            sems.at[0]).wait()
            return 0
        jax.lax.fori_loop(0, KD, drain, 0)

    @pl.when(kind == 1)
    def _():
        @pl.when(first == 1)
        def _():
            out_ref[:] = jnp.zeros_like(out_ref)

        dl = rows_ref[:]
        chunk = stgbuf[par, pl.ds(sq * CH, CH)]
        rows = jnp.where(dl == RB, jnp.float32(0), chunk)
        lane = jax.lax.broadcasted_iota(jnp.int32, (CH, RB), 1)
        s_t = (lane == dl).astype(jnp.bfloat16)
        tile = _onehot_dot(s_t, rows, (((0,), (0,)), ((), ())), exact)
        out_ref[:] += jax.lax.dot_general(
            tile, w_ref[:], (((1,), (0,)), ((), ())),
            precision=jax.lax.Precision.HIGHEST,
            preferred_element_type=jnp.float32)
        if relu:
            @pl.when(last_ref[c] == 1)
            def _():
                out_ref[:] = jnp.maximum(out_ref[:], 0.0)


@partial(jax.jit, static_argnames=("nsteps", "c2", "out_rows", "interpret",
                                   "exact", "geom", "relu", "nparity"))
def _mega_run(x, w, blk, blk2, obi, last, meta, dsrc, ddst, rows,
              nsteps: int, c2: int, out_rows: int, interpret: bool = False,
              exact: bool = False, geom: Geometry = None,
              relu: bool = False, nparity: int = 2):
    H = x.shape[-1]
    Ho = w.shape[-1]
    CH, SB, RB, KD = geom.ch, geom.sb, geom.rb, geom.kd            # noqa
    srows = c2 * geom.ch2
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,                  # blk, blk2, obi, last [S]
        grid=(nsteps,),
        in_specs=[
            pl.BlockSpec((8, 4), lambda c, b, b2, o, l: (c // 8, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((8, KD), lambda c, b, b2, o, l: (c // 8, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((8, KD), lambda c, b, b2, o, l: (c // 8, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((CH, 1), lambda c, b, b2, o, l: (c, 0)),
            pl.BlockSpec((SB, H), lambda c, b, b2, o, l: (b[c], 0)),
            pl.BlockSpec((SB, H), lambda c, b, b2, o, l: (b2[c], 0)),
            # whole weight, constant index: fetched once, VMEM-resident
            pl.BlockSpec((H, Ho), lambda c, b, b2, o, l: (0, 0)),
        ],
        out_specs=pl.BlockSpec((RB, Ho), lambda c, b, b2, o, l: (o[c], 0)),
        # Single-group plans stage on ONE parity (every step's meta parity
        # is g%2 == 0, pads included — _attach_fused), so the second
        # stgbuf parity would be dead VMEM; dropping it is what admits
        # C2>1 fp32 fusion at the mega-shard shape (round 12).
        scratch_shapes=[pltpu.VMEM((CH, H), staging_dtype(geom, exact)),
                        pltpu.VMEM((nparity, srows, H),
                                   staging_dtype(geom, exact)),
                        pltpu.SemaphoreType.DMA((1,))],
    )
    return pl.pallas_call(
        partial(_mega_kernel, exact=exact, geom=geom, relu=relu),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((out_rows, Ho), jnp.float32),
        interpret=interpret,
    )(blk, blk2, obi, last, meta, dsrc, ddst, rows, x, x, w)


def _mega_vmem_ok(geom: Geometry, Hp: int, Ho_p: int, c2: int,
                  groups: int = 2) -> bool:
    """_fused_vmem_ok extended with the megakernel's extra residents: the
    [Hp, Ho_p] weight tile, the per-chunk [rb, Hp] aggregate tile the dot
    produces, and the [rb, Ho_p] post-linear out window (replacing the
    fused kernel's [rb, Hp] one).  An oversized H_out fails here and
    run_binned_linear falls back to two-pass aggregate + XLA linear.

    ``groups`` is the plan's group count G: a single-group plan stages on
    ONE parity (the schedule's parity is g%2 == 0 on every step, pads
    included), so only one srows*Hp staging buffer is resident — the
    round-12 admission raise that lets fp32 fuse at C2>1 (the mega-shard
    shape fits at C2=3 single-parity where double-parity busts the
    budget).  The default groups=2 is the conservative double-parity
    charge for callers that don't know G."""
    srows = c2 * geom.ch2
    stg = staging_itemsize(geom, False)
    nparity = 1 if groups == 1 else 2
    need = (nparity * srows * Hp * stg + geom.ch * Hp * stg
            + max(geom.ch * geom.sb, geom.ch2 * geom.rb) * 2
            + 2 * geom.sb * Hp * 4
            + Hp * Ho_p * 4              # resident weight tile
            + geom.rb * Hp * 4           # per-chunk aggregate tile
            + geom.rb * Ho_p * 4)        # post-linear out window
    return need <= _VMEM_BUDGET


# ROC_NO_MEGAFUSE kill switch: one warning per process — a kill this high
# up changes the layer program (two device passes instead of one), worth
# one notice where ROC_BINNED_NO_FUSE stays a silent bisection knob.
_MEGA_KILL_WARNED = [False]


def megafuse_killed() -> bool:
    """True when ROC_NO_MEGAFUSE=1 disables aggregate->linear megakernel
    fusion at runtime (checked at every dispatch site; warn-once)."""
    if not os.environ.get("ROC_NO_MEGAFUSE"):
        return False
    if not _MEGA_KILL_WARNED[0]:
        _MEGA_KILL_WARNED[0] = True
        warnings.warn(
            "ROC_NO_MEGAFUSE=1: aggregate->linear megakernel fusion "
            "disabled; eligible layers run the two-pass aggregation plus "
            "the XLA linear instead.", stacklevel=2)
    return True


def run_binned_linear(x, w, plan: BinnedPlan, interpret: bool = False,
                      precision: str = "fast", activation: str = "none"):
    """linear(aggregate-sum(x), w)[, ReLU] in ONE Pallas grid — the
    whole-layer megakernel (round 10).

    x: [table_rows, H_in], w: [H_in, H_out] -> [num_rows, H_out] in
    x.dtype.  Semantics match run_binned followed by ops.linear (fp32
    accumulation, `highest`-precision matmul); on the megakernel path
    the [num_rows, H_in] aggregate never reaches HBM.  Gating mirrors
    run_binned's fused gate plus the weight/accumulator VMEM budget
    (_mega_vmem_ok) and the ROC_NO_MEGAFUSE kill switch; any gate
    failure falls back to exactly that two-pass composition, so callers
    always get the layer, just not always in one kernel.  Differentiable
    through the fallback only — training uses the custom VJP in
    ops.aggregate.scatter_gather_linear_binned, whose backward replays
    the two-pass path."""
    if activation not in ("none", "relu"):
        raise ValueError(f"activation={activation!r}: the megakernel "
                         f"fuses 'none' or 'relu' only")
    if precision not in ("fast", "exact"):
        raise ValueError(f"precision={precision!r}: must be 'fast' or "
                         f"'exact'")
    exact = precision == "exact" and x.dtype == jnp.float32
    geom = plan.geom or _default_geom()
    H = x.shape[-1]
    Ho = w.shape[-1]
    Hp = _pad_to(H, 128)
    Ho_p = _pad_to(Ho, 128)
    C2 = plan.p2_obi.shape[1]
    G = plan.p1_blk.shape[0]
    if (geom.flat and plan.f_meta is not None
            and plan.f_last is not None
            and not (exact and geom.unit == 16)
            and not os.environ.get("ROC_BINNED_NO_FUSE")
            and not megafuse_killed()
            and _mega_vmem_ok(geom, Hp, Ho_p, C2, groups=G)):
        out_rows = G * plan.bins_per_group * geom.rb
        xp = jnp.pad(x, ((0, _pad_to(plan.table_rows, geom.sb)
                          - x.shape[0]), (0, Hp - H)))
        # fp32 weight, zero-padded to whole lanes on both axes: pad H_in
        # rows multiply x's zero pad lanes, pad H_out lanes are stripped
        wp = jnp.pad(w.astype(jnp.float32),
                     ((0, Hp - H), (0, Ho_p - Ho)))
        S = int(plan.f_blk.shape[0])
        with jax.named_scope("roc_binned_mega"):
            out = _mega_run(xp, wp, plan.f_blk, plan.f_blk2, plan.f_obi,
                            plan.f_last, plan.f_meta, plan.f_dsrc,
                            plan.f_ddst, plan.f_rows, S, C2, out_rows,
                            interpret, exact, geom,
                            activation == "relu",
                            1 if G == 1 else 2)
        return out[:plan.num_rows, :Ho].astype(x.dtype)
    # VMEM-gate / kill-switch fallback: the identical two-pass layer
    from roc_tpu.ops.linear import linear
    return linear(run_binned(x, plan, interpret, precision), w, activation)


# ---------------------------------------------------------------------------
# Megakernel BACKWARD (round 12): the layer's whole cotangent pipeline —
# relu mask, transposed aggregation u = A^T g, and dx = u @ W^T — in one
# Pallas grid over the TRANSPOSED (plans.bwd) flat schedule.  dW = x^T u
# stays an XLA GEMM outside (it needs x, which the kernel never streams).
# ---------------------------------------------------------------------------

# ROC_MEGA_BWD=0 kill switch for the FUSED BACKWARD only (the forward
# megakernel keeps running): gradients fall back to the two-pass VJP
# replay — today's bitwise-gradient behavior, byte for byte.  Warn-once
# like megafuse_killed: flipping it changes the backward program.
_MEGA_BWD_KILL_WARNED = [False]


def mega_bwd_killed() -> bool:
    """True when ROC_MEGA_BWD=0 disables the fused megakernel backward at
    runtime (checked at every VJP dispatch; warn-once)."""
    if os.environ.get("ROC_MEGA_BWD", "") != "0":
        return False
    if not _MEGA_BWD_KILL_WARNED[0]:
        _MEGA_BWD_KILL_WARNED[0] = True
        warnings.warn(
            "ROC_MEGA_BWD=0: fused megakernel backward disabled; "
            "eligible layers' gradients replay the two-pass "
            "aggregate+linear composition instead.", stacklevel=2)
    return True


def _mega_bwd_vmem_ok(geom: Geometry, Ho_p: int, Hi_p: int, c2: int,
                      groups: int = 2, relu: bool = False) -> bool:
    """Trace-time admission for the backward megakernel.  Mirrors
    _mega_vmem_ok at the backward's widths — staging/gbuf/one-hots ride
    the OUTPUT width Ho_p (the cotangent is what aggregates) — plus the
    backward's own residents: the relu path streams TWO extra saved-output
    blocks alongside the cotangent blocks, the transposed [Ho_p, Hi_p]
    weight tile sits where the forward's [Hp, Ho_p] one did, and BOTH
    output windows (u at Ho_p, dx at Hi_p) are resident per bin."""
    srows = c2 * geom.ch2
    stg = staging_itemsize(geom, False)
    nparity = 1 if groups == 1 else 2
    need = (nparity * srows * Ho_p * stg + geom.ch * Ho_p * stg
            + max(geom.ch * geom.sb, geom.ch2 * geom.rb) * 2
            + (4 if relu else 2) * geom.sb * Ho_p * 4
            + Ho_p * Hi_p * 4            # resident W^T tile
            + geom.rb * Ho_p * 4         # per-chunk cotangent tile
            + geom.rb * Ho_p * 4         # u out window
            + geom.rb * Hi_p * 4)        # dx out window
    return need <= _VMEM_BUDGET


def _mega_bwd_kernel(*args, exact: bool = False, geom: Geometry = None,
                     relu: bool = False):
    """Backward twin of _mega_kernel over the transposed plan.  Kind 0
    expands a chunk of the OUTPUT cotangent g — masked in-register by the
    saved forward output when the layer fused a relu (mask before the
    one-hot: it is per-source-row, and pad rows carry y=0 so they stay
    zero) — and stages it; kind 1 scatter-adds one staging chunk into the
    per-bin cotangent tile u_tile = (A^T g_masked)[bin], accumulates it
    into the u window (written to HBM for the XLA dW GEMM: dW = x^T u),
    AND accumulates u_tile @ W^T into the dx window — both outputs ride
    the same nondecreasing out index, so one grid produces the layer's
    full input cotangent.  Correct per chunk for the same distributivity
    reason as the forward (integer data is bit-exact; fp32 reassociates
    within the documented ULP bound).  No f_last epilogue exists here:
    the relu mask is a PRE-aggregation operation, applied in kind 0."""
    if relu:
        (blk_ref, blk2_ref, obi_ref, meta_ref, dsrc_ref, ddst_ref,
         rows_ref, g_ref, g2_ref, y_ref, y2_ref, wt_ref,
         u_ref, dx_ref, gbuf, stgbuf, sems) = args
    else:
        (blk_ref, blk2_ref, obi_ref, meta_ref, dsrc_ref, ddst_ref,
         rows_ref, g_ref, g2_ref, wt_ref,
         u_ref, dx_ref, gbuf, stgbuf, sems) = args
        y_ref = y2_ref = None
    CH, SB, RB, KD = geom.ch, geom.sb, geom.rb, geom.kd            # noqa
    U = geom.unit_rows
    st = staging_dtype(geom, exact)
    c = pl.program_id(0)
    kind = meta_ref[c % 8, 0]
    par = meta_ref[c % 8, 1]
    first = meta_ref[c % 8, 2]
    sq = meta_ref[c % 8, 3]

    @pl.when(kind == 0)
    def _():
        lane = jax.lax.broadcasted_iota(jnp.int32, (CH, SB), 1)
        sl = rows_ref[:]
        t1 = (lane == sl).astype(jnp.bfloat16)
        gv = g_ref[:]
        if relu:
            # d/dy relu at the saved output: pass g where y > 0.  At an
            # exact pre-activation zero this differs from jnp.maximum's
            # tie-splitting VJP (0.5*g) — measure-zero on continuous
            # data; docs/DESIGN.md §Megakernel documents the tie rule.
            gv = jnp.where(y_ref[:] > 0, gv, jnp.zeros_like(gv))
        gbuf[:] = _onehot_dot(t1, gv, (((1,), (0,)), ((), ())),
                              exact).astype(st)

        @pl.when(blk2_ref[c] != blk_ref[c])
        def _():
            t2 = (lane == sl - SB).astype(jnp.bfloat16)
            gv2 = g2_ref[:]
            if relu:
                gv2 = jnp.where(y2_ref[:] > 0, gv2, jnp.zeros_like(gv2))
            gbuf[:] = (gbuf[:].astype(jnp.float32) + _onehot_dot(
                t2, gv2, (((1,), (0,)), ((), ())), exact)).astype(st)

        def issue(e, _):
            v = dsrc_ref[c % 8, e]

            @pl.when(v >= 0)
            def _():
                cls = v // 65536
                su = v - cls * 65536
                du = ddst_ref[c % 8, e]
                for ci, csz in enumerate(_DMA_CLS):
                    @pl.when(cls == ci)
                    def _(csz=csz):
                        pltpu.make_async_copy(
                            gbuf.at[pl.ds(su * U, csz * U)],
                            stgbuf.at[par].at[
                                pl.ds(du * U, csz * U)],
                            sems.at[0]).start()
            return 0
        jax.lax.fori_loop(0, KD, issue, 0)

        def drain(e, _):
            v = dsrc_ref[c % 8, e]

            @pl.when(v >= 0)
            def _():
                cls = v // 65536
                su = v - cls * 65536
                du = ddst_ref[c % 8, e]
                for ci, csz in enumerate(_DMA_CLS):
                    @pl.when(cls == ci)
                    def _(csz=csz):
                        pltpu.make_async_copy(
                            gbuf.at[pl.ds(su * U, csz * U)],
                            stgbuf.at[par].at[
                                pl.ds(du * U, csz * U)],
                            sems.at[0]).wait()
            return 0
        jax.lax.fori_loop(0, KD, drain, 0)

    @pl.when(kind == 1)
    def _():
        @pl.when(first == 1)
        def _():
            u_ref[:] = jnp.zeros_like(u_ref)
            dx_ref[:] = jnp.zeros_like(dx_ref)

        dl = rows_ref[:]
        chunk = stgbuf[par, pl.ds(sq * CH, CH)]
        rows = jnp.where(dl == RB, jnp.float32(0), chunk)
        lane = jax.lax.broadcasted_iota(jnp.int32, (CH, RB), 1)
        s_t = (lane == dl).astype(jnp.bfloat16)
        tile = _onehot_dot(s_t, rows, (((0,), (0,)), ((), ())), exact)
        u_ref[:] += tile
        dx_ref[:] += jax.lax.dot_general(
            tile, wt_ref[:], (((1,), (0,)), ((), ())),
            precision=jax.lax.Precision.HIGHEST,
            preferred_element_type=jnp.float32)


@partial(jax.jit, static_argnames=("nsteps", "c2", "out_rows", "interpret",
                                   "exact", "geom", "relu", "nparity"))
def _mega_bwd_run(g, y, wt, blk, blk2, obi, meta, dsrc, ddst, rows,
                  nsteps: int, c2: int, out_rows: int,
                  interpret: bool = False, exact: bool = False,
                  geom: Geometry = None, relu: bool = False,
                  nparity: int = 2):
    Ho = g.shape[-1]
    Hi = wt.shape[-1]
    CH, SB, RB, KD = geom.ch, geom.sb, geom.rb, geom.kd            # noqa
    srows = c2 * geom.ch2
    # The saved-output blocks (relu mask source) ride the SAME index maps
    # as the cotangent blocks: masking happens per source row, before the
    # one-hot expand.
    y_specs = [
        pl.BlockSpec((SB, Ho), lambda c, b, b2, o: (b[c], 0)),
        pl.BlockSpec((SB, Ho), lambda c, b, b2, o: (b2[c], 0)),
    ] if relu else []
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,                  # blk, blk2, obi [S]
        grid=(nsteps,),
        in_specs=[
            pl.BlockSpec((8, 4), lambda c, b, b2, o: (c // 8, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((8, KD), lambda c, b, b2, o: (c // 8, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((8, KD), lambda c, b, b2, o: (c // 8, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((CH, 1), lambda c, b, b2, o: (c, 0)),
            pl.BlockSpec((SB, Ho), lambda c, b, b2, o: (b[c], 0)),
            pl.BlockSpec((SB, Ho), lambda c, b, b2, o: (b2[c], 0)),
            *y_specs,
            # transposed weight tile, constant index: fetched once,
            # VMEM-resident for the whole grid (the forward's weight
            # BlockSpec pattern at the transposed shape)
            pl.BlockSpec((Ho, Hi), lambda c, b, b2, o: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((RB, Ho), lambda c, b, b2, o: (o[c], 0)),
            pl.BlockSpec((RB, Hi), lambda c, b, b2, o: (o[c], 0)),
        ],
        scratch_shapes=[pltpu.VMEM((CH, Ho), staging_dtype(geom, exact)),
                        pltpu.VMEM((nparity, srows, Ho),
                                   staging_dtype(geom, exact)),
                        pltpu.SemaphoreType.DMA((1,))],
    )
    ins = (blk, blk2, obi, meta, dsrc, ddst, rows, g, g)
    ins += (y, y) if relu else ()
    return pl.pallas_call(
        partial(_mega_bwd_kernel, exact=exact, geom=geom, relu=relu),
        grid_spec=grid_spec,
        out_shape=[jax.ShapeDtypeStruct((out_rows, Ho), jnp.float32),
                   jax.ShapeDtypeStruct((out_rows, Hi), jnp.float32)],
        interpret=interpret,
    )(*ins, wt)


def run_binned_linear_bwd(g, y, w, plan: BinnedPlan,
                          interpret: bool = False, precision: str = "fast",
                          relu: bool = False):
    """Fused backward of the megakernel layer, over the TRANSPOSED plan
    (ops.aggregate passes plans.bwd): given the output cotangent
    g [num_rows_fwd, H_out], the saved forward output y (relu mask
    source; ignored when relu=False) and the layer weight w [H_in, H_out],
    returns (u, dx) with u = A^T (g * relu_mask) [table_rows_fwd, H_out]
    and dx = u @ W^T [table_rows_fwd, H_in] — the [rows, H_in] dagg
    cotangent never reaches HBM.  The caller finishes with the XLA GEMM
    dW = x^T u.

    Returns None when ANY admission gate fails (non-fused plan, exact on
    a bf16 unit, ROC_BINNED_NO_FUSE / ROC_NO_MEGAFUSE / ROC_MEGA_BWD=0,
    or the VMEM budget): the caller must then replay the two-pass
    composition — which is also the bitwise oracle the fused path is
    tested against on integer data."""
    if precision not in ("fast", "exact"):
        raise ValueError(f"precision={precision!r}: must be 'fast' or "
                         f"'exact'")
    exact = precision == "exact" and g.dtype == jnp.float32
    geom = plan.geom or _default_geom()
    Ho = g.shape[-1]
    Hi = w.shape[0]
    Ho_p = _pad_to(Ho, 128)
    Hi_p = _pad_to(Hi, 128)
    C2 = plan.p2_obi.shape[1]
    G = plan.p1_blk.shape[0]
    if not (geom.flat and plan.f_meta is not None
            and plan.f_last is not None
            and not (exact and geom.unit == 16)
            and not os.environ.get("ROC_BINNED_NO_FUSE")
            and not megafuse_killed()
            and not mega_bwd_killed()
            and _mega_bwd_vmem_ok(geom, Ho_p, Hi_p, C2, groups=G,
                                  relu=relu)):
        return None
    out_rows = G * plan.bins_per_group * geom.rb
    rows_pad = _pad_to(plan.table_rows, geom.sb)
    gp = jnp.pad(g, ((0, rows_pad - g.shape[0]), (0, Ho_p - Ho)))
    # pad rows carry y=0 -> masked to zero, matching their zero cotangent
    yp = jnp.pad(y, ((0, rows_pad - y.shape[0]), (0, Ho_p - Ho))) \
        if relu else None
    # fp32 W^T, zero-padded: pad H_out rows multiply g's zero pad lanes,
    # pad H_in lanes are stripped from dx below
    wtp = jnp.pad(jnp.transpose(w.astype(jnp.float32)),
                  ((0, Ho_p - Ho), (0, Hi_p - Hi)))
    S = int(plan.f_blk.shape[0])
    with jax.named_scope("roc_binned_mega_bwd"):
        u, dx = _mega_bwd_run(gp, yp, wtp, plan.f_blk, plan.f_blk2,
                              plan.f_obi, plan.f_meta, plan.f_dsrc,
                              plan.f_ddst, plan.f_rows, S, C2, out_rows,
                              interpret, exact, geom, relu,
                              1 if G == 1 else 2)
    return u[:plan.num_rows, :Ho], dx[:plan.num_rows, :Hi]


# ---------------------------------------------------------------------------
# CROSS-LAYER megakernel (round 16): a whole fusion REGION —
# aggregate -> linear (-> relu) [-> fold scales] -> aggregate -> linear ... —
# in ONE Pallas grid.  The flat fused schedule is depth-agnostic: the grid
# replays the SAME plan steps once per layer (step = c % S, depth = c // S),
# and layer d's post-linear [RB, H] tiles accumulate into a VMEM-resident
# inter-layer buffer that layer d+1's phase-1 staging reads back at block
# granularity — the [rows, H] layer boundary never touches HBM for
# shard-local rows.  Per-depth weights ride a stacked [D, Hm, Hm] input
# whose (1, Hm, Hm) BlockSpec double-buffers the NEXT depth's tile while
# the current one computes.  Admission (region_ok) additionally requires a
# SQUARE shard-local plan (table_rows == num_rows: no halo frontier — the
# SPMD path keeps per-layer fusion) and full bin coverage of the block
# range (out_rows >= padded table_rows) so every inter-layer block read
# lands in a window the schedule zeroed (every bin opens with first=1,
# empty bins included — _attach_fused).
# ---------------------------------------------------------------------------

# ROC_XLAYER=0 kill switch: disables REGION fusion only — per-layer
# megakernels (rounds 8-12) keep running, restoring PR-10 behavior
# exactly.  Warn-once like the other program-changing switches.
_XLAYER_KILL_WARNED = [False]


def xlayer_killed() -> bool:
    """True when ROC_XLAYER=0 disables cross-layer fusion-region kernels
    at runtime (checked at every region dispatch; warn-once).  Per-layer
    megakernel fusion is unaffected."""
    if os.environ.get("ROC_XLAYER", "") != "0":
        return False
    if not _XLAYER_KILL_WARNED[0]:
        _XLAYER_KILL_WARNED[0] = True
        warnings.warn(
            "ROC_XLAYER=0: cross-layer fusion regions disabled; eligible "
            "regions run the per-layer megakernel chain instead.",
            stacklevel=2)
    return True


def _xlayer_vmem_ok(geom: Geometry, Hm_p: int, c2: int, depth: int,
                    groups: int = 2, tp: int = 0) -> bool:
    """Trace-time admission for the cross-layer FORWARD grid: the
    per-layer megakernel's residents (_mega_vmem_ok) at the region's
    uniform padded width, with the weight tile DOUBLE-buffered (its block
    index now changes once per depth), plus the inter-layer VMEM buffers
    — one [tp, Hm] activation plane for depth 2, two (ping-pong) beyond.
    This is the term that keys region admission to SHARD-local row
    counts: at full-graph scale tp*Hm busts the budget and the planner
    declines down to per-layer fusion."""
    srows = c2 * geom.ch2
    stg = staging_itemsize(geom, False)
    nparity = 1 if groups == 1 else 2
    ipar = 1 if depth == 2 else 2
    need = (nparity * srows * Hm_p * stg + geom.ch * Hm_p * stg
            + max(geom.ch * geom.sb, geom.ch2 * geom.rb) * 2
            + 2 * geom.sb * Hm_p * 4
            + 2 * Hm_p * Hm_p * 4        # per-depth weight, double-buffered
            + geom.rb * Hm_p * 4         # per-chunk aggregate tile
            + geom.rb * Hm_p * 4         # out window
            + ipar * tp * Hm_p * 4)      # inter-layer activation planes
    return need <= _VMEM_BUDGET


def _xlayer_bwd_vmem_ok(geom: Geometry, Hm_p: int, c2: int, depth: int,
                        groups: int = 2, tp: int = 0,
                        relu_last: bool = False) -> bool:
    """Trace-time admission for the cross-layer BACKWARD grid: staging +
    one-hot residents at the region width, the streamed blocks (x pair
    for the replay, cotangent pair, saved-output pair when the last layer
    fused a relu, plus the dW z-window), BOTH stacked weight inputs and
    the dW out block double-buffered, and the big ones — (depth-1)
    replayed activation planes plus the cotangent ping-pong."""
    srows = c2 * geom.ch2
    stg = staging_itemsize(geom, False)
    nparity = 1 if groups == 1 else 2
    ncg = 1 if depth == 2 else 2
    need = (nparity * srows * Hm_p * stg + geom.ch * Hm_p * stg
            + max(geom.ch * geom.sb, geom.ch2 * geom.rb) * 2
            + (4 + (2 if relu_last else 0)) * geom.sb * Hm_p * 4
            + geom.rb * Hm_p * 4         # dW z window
            + 4 * Hm_p * Hm_p * 4        # ws + wst, double-buffered
            + 2 * Hm_p * Hm_p * 4        # dW out block, double-buffered
            + geom.rb * Hm_p * 4         # per-chunk cotangent tile
            + geom.rb * Hm_p * 4         # dx out window
            + (depth - 1 + ncg) * tp * Hm_p * 4)  # replay + cotangent
    return need <= _VMEM_BUDGET


def region_ok(plan: BinnedPlan, widths, precision: str = "fast",
              x_dtype=jnp.float32) -> bool:
    """Trace-time admission for a fusion REGION over this (forward) plan.
    ``widths`` is the region's feature-width chain (H_0, H_1, ..., H_D);
    all gating is static, so a False here lets the executor hook decline
    and the per-layer (depth-1) program run byte-identical.  Mirrors the
    per-layer megakernel gates plus the region-only ones: >=2 layers, a
    square shard-local plan (table_rows == num_rows — halo-frontier rows
    would read garbage from the inter-layer buffer), bin coverage of the
    whole block range, the ROC_XLAYER kill switch, and the region VMEM
    price."""
    geom = plan.geom or _default_geom()
    depth = len(widths) - 1
    exact = precision == "exact" and x_dtype == jnp.float32
    if depth < 2 or geom is None or not geom.flat:
        return False
    if plan.f_meta is None or plan.f_last is None:
        return False
    Hm_p = max(_pad_to(int(h), 128) for h in widths)
    C2 = plan.p2_obi.shape[1]
    G = plan.p1_blk.shape[0]
    out_rows = G * plan.bins_per_group * geom.rb
    tp = _pad_to(max(_pad_to(plan.table_rows, geom.sb), out_rows),
                 max(geom.sb, geom.rb))
    return (not (exact and geom.unit == 16)
            and not os.environ.get("ROC_BINNED_NO_FUSE")
            and not megafuse_killed()
            and not xlayer_killed()
            and plan.table_rows == plan.num_rows
            and out_rows >= _pad_to(plan.table_rows, geom.sb)
            and _xlayer_vmem_ok(geom, Hm_p, C2, depth, groups=G, tp=tp))


def _xlayer_kernel(*args, exact: bool = False, geom: Geometry = None,
                   depth: int = 2, nsteps_per: int = 0, relus=(),
                   fold: bool = False):
    """Cross-layer forward: grid step c runs plan step c % S at depth
    c // S.  Depth 0's phase 1 stages from the x HBM blocks exactly like
    _mega_kernel; depth d>0 stages from the inter-layer VMEM plane that
    depth d-1's phase 2 filled (parity (d-1) % ipar).  Phase 2 at the
    LAST depth accumulates tile @ W_d into the HBM out window (index
    pinned to 0 on earlier depths: block 0 is also the first real bin,
    so its first=1 zeroing lands before any real writeback); earlier
    depths accumulate into their inter-layer window and, on the bin's
    last real chunk (f_last), apply the layer epilogue in place — relu,
    then for norm-folded regions the two diagonal scales (v*s)*s, the
    exact multiply sequence the per-layer hook runs outside the kernel,
    so the staged values match the depth-1 chain bitwise on fp32."""
    if fold:
        (blk_ref, blk2_ref, obi_ref, last_ref, meta_ref, dsrc_ref,
         ddst_ref, rows_ref, x_ref, x2_ref, ws_ref, s_ref, out_ref,
         gbuf, stgbuf, tbuf, sems) = args
    else:
        (blk_ref, blk2_ref, obi_ref, last_ref, meta_ref, dsrc_ref,
         ddst_ref, rows_ref, x_ref, x2_ref, ws_ref, out_ref,
         gbuf, stgbuf, tbuf, sems) = args
        s_ref = None
    CH, SB, RB, KD = geom.ch, geom.sb, geom.rb, geom.kd            # noqa
    U = geom.unit_rows
    st = staging_dtype(geom, exact)
    S = nsteps_per
    D = depth
    ipar = 1 if D == 2 else 2
    c = pl.program_id(0)
    step = c % S
    d = c // S
    kind = meta_ref[c % 8, 0]
    par = meta_ref[c % 8, 1]
    first = meta_ref[c % 8, 2]
    sq = meta_ref[c % 8, 3]

    @pl.when(kind == 0)
    def _():
        lane = jax.lax.broadcasted_iota(jnp.int32, (CH, SB), 1)
        sl = rows_ref[:]
        t1 = (lane == sl).astype(jnp.bfloat16)
        t2 = (lane == sl - SB).astype(jnp.bfloat16)
        two = blk2_ref[step] != blk_ref[step]

        @pl.when(d == 0)
        def _():
            gbuf[:] = _onehot_dot(t1, x_ref[:], (((1,), (0,)), ((), ())),
                                  exact).astype(st)

            @pl.when(two)
            def _():
                gbuf[:] = (gbuf[:].astype(jnp.float32) + _onehot_dot(
                    t2, x2_ref[:], (((1,), (0,)), ((), ())),
                    exact)).astype(st)

        for dd in range(1, D):
            @pl.when(d == dd)
            def _(dd=dd):
                j = (dd - 1) % ipar
                src = tbuf[j, pl.ds(blk_ref[step] * SB, SB), :]
                gbuf[:] = _onehot_dot(t1, src, (((1,), (0,)), ((), ())),
                                      exact).astype(st)

                @pl.when(two)
                def _(j=j):
                    src2 = tbuf[j, pl.ds(blk2_ref[step] * SB, SB), :]
                    gbuf[:] = (gbuf[:].astype(jnp.float32) + _onehot_dot(
                        t2, src2, (((1,), (0,)), ((), ())),
                        exact)).astype(st)

        def issue(e, _):
            v = dsrc_ref[c % 8, e]

            @pl.when(v >= 0)
            def _():
                cls = v // 65536
                su = v - cls * 65536
                du = ddst_ref[c % 8, e]
                for ci, csz in enumerate(_DMA_CLS):
                    @pl.when(cls == ci)
                    def _(csz=csz):
                        pltpu.make_async_copy(
                            gbuf.at[pl.ds(su * U, csz * U)],
                            stgbuf.at[par].at[
                                pl.ds(du * U, csz * U)],
                            sems.at[0]).start()
            return 0
        jax.lax.fori_loop(0, KD, issue, 0)

        def drain(e, _):
            v = dsrc_ref[c % 8, e]

            @pl.when(v >= 0)
            def _():
                cls = v // 65536
                su = v - cls * 65536
                du = ddst_ref[c % 8, e]
                for ci, csz in enumerate(_DMA_CLS):
                    @pl.when(cls == ci)
                    def _(csz=csz):
                        pltpu.make_async_copy(
                            gbuf.at[pl.ds(su * U, csz * U)],
                            stgbuf.at[par].at[
                                pl.ds(du * U, csz * U)],
                            sems.at[0]).wait()
            return 0
        jax.lax.fori_loop(0, KD, drain, 0)

    @pl.when(kind == 1)
    def _():
        dl = rows_ref[:]
        chunk = stgbuf[par, pl.ds(sq * CH, CH)]
        rows = jnp.where(dl == RB, jnp.float32(0), chunk)
        lane = jax.lax.broadcasted_iota(jnp.int32, (CH, RB), 1)
        s_t = (lane == dl).astype(jnp.bfloat16)
        tile = _onehot_dot(s_t, rows, (((0,), (0,)), ((), ())), exact)
        contrib = jax.lax.dot_general(
            tile, ws_ref[0], (((1,), (0,)), ((), ())),
            precision=jax.lax.Precision.HIGHEST,
            preferred_element_type=jnp.float32)

        @pl.when(d == D - 1)
        def _():
            @pl.when(first == 1)
            def _():
                out_ref[:] = jnp.zeros_like(out_ref)

            out_ref[:] += contrib
            if relus[-1]:
                @pl.when(last_ref[step] == 1)
                def _():
                    out_ref[:] = jnp.maximum(out_ref[:], 0.0)

        for dd in range(D - 1):
            @pl.when(d == dd)
            def _(dd=dd):
                j = dd % ipar
                off = obi_ref[step] * RB

                @pl.when(first == 1)
                def _(j=j):
                    tbuf[j, pl.ds(off, RB), :] = jnp.zeros(
                        (RB, tbuf.shape[-1]), jnp.float32)

                tbuf[j, pl.ds(off, RB), :] = (
                    tbuf[j, pl.ds(off, RB), :] + contrib)

                @pl.when(last_ref[step] == 1)
                def _(dd=dd, j=j):
                    v = tbuf[j, pl.ds(off, RB), :]
                    if relus[dd]:
                        v = jnp.maximum(v, 0.0)
                    if fold:
                        v = (v * s_ref[:]) * s_ref[:]
                    tbuf[j, pl.ds(off, RB), :] = v


@partial(jax.jit, static_argnames=("nsteps_per", "c2", "out_rows", "tp",
                                   "interpret", "exact", "geom", "depth",
                                   "relus", "fold", "nparity"))
def _xlayer_run(x, ws, s, blk, blk2, obi, last, meta, dsrc, ddst, rows,
                nsteps_per: int, c2: int, out_rows: int, tp: int,
                interpret: bool = False, exact: bool = False,
                geom: Geometry = None, depth: int = 2, relus=(),
                fold: bool = False, nparity: int = 2):
    Hm = x.shape[-1]
    CH, SB, RB, KD = geom.ch, geom.sb, geom.rb, geom.kd            # noqa
    S = nsteps_per
    D = depth
    srows = c2 * geom.ch2
    ipar = 1 if D == 2 else 2
    in_specs = [
        pl.BlockSpec((8, 4), lambda c, b, b2, o, l: ((c % S) // 8, 0),
                     memory_space=pltpu.SMEM),
        pl.BlockSpec((8, KD), lambda c, b, b2, o, l: ((c % S) // 8, 0),
                     memory_space=pltpu.SMEM),
        pl.BlockSpec((8, KD), lambda c, b, b2, o, l: ((c % S) // 8, 0),
                     memory_space=pltpu.SMEM),
        pl.BlockSpec((CH, 1), lambda c, b, b2, o, l: (c % S, 0)),
        # x blocks stream at depth 0 only; pinned to block 0 above so the
        # buffer never refetches while the inter-layer planes feed
        pl.BlockSpec((SB, Hm),
                     lambda c, b, b2, o, l: (
                         jnp.where(c // S == 0, b[c % S], 0), 0)),
        pl.BlockSpec((SB, Hm),
                     lambda c, b, b2, o, l: (
                         jnp.where(c // S == 0, b2[c % S], 0), 0)),
        # stacked per-depth weights: the block index changes once per
        # depth, so pallas double-buffers the NEXT layer's tile
        pl.BlockSpec((1, Hm, Hm), lambda c, b, b2, o, l: (c // S, 0, 0)),
    ]
    if fold:
        in_specs.append(
            pl.BlockSpec((RB, 1), lambda c, b, b2, o, l: (o[c % S], 0)))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,                  # blk, blk2, obi, last [S]
        grid=(D * S,),
        in_specs=in_specs,
        # real out windows on the last depth only; the pin to block 0 on
        # earlier depths is safe because the out index is nondecreasing
        # from bin 0, whose first=1 zeroing precedes any writeback
        out_specs=pl.BlockSpec(
            (RB, Hm),
            lambda c, b, b2, o, l: (
                jnp.where(c // S == D - 1, o[c % S], 0), 0)),
        scratch_shapes=[pltpu.VMEM((CH, Hm), staging_dtype(geom, exact)),
                        pltpu.VMEM((nparity, srows, Hm),
                                   staging_dtype(geom, exact)),
                        pltpu.VMEM((ipar, tp, Hm), jnp.float32),
                        pltpu.SemaphoreType.DMA((1,))],
    )
    ins = (blk, blk2, obi, last, meta, dsrc, ddst, rows, x, x, ws)
    ins += (s,) if fold else ()
    return pl.pallas_call(
        partial(_xlayer_kernel, exact=exact, geom=geom, depth=D,
                nsteps_per=S, relus=relus, fold=fold),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((out_rows, Hm), jnp.float32),
        interpret=interpret,
    )(*ins)


def run_binned_region(x, ws, in_degree, plan: BinnedPlan,
                      interpret: bool = False, precision: str = "fast",
                      activations=(), fold: bool = False):
    """relu_D(A ... relu_1(A (x W_1)) W_2 ...) — a whole fusion region in
    ONE Pallas grid.  ``ws`` is the region's weight chain (depth =
    len(ws) >= 2), ``activations`` the per-layer "none"/"relu" chain, and
    for norm-folded (GCN) regions ``fold=True`` applies the interior
    (D^-1/2)^2 diagonal scales in-kernel from ``in_degree`` (the caller
    still owns the region-boundary pre/post scales, exactly like the
    per-layer hook).  The caller MUST pre-gate with region_ok — this
    asserts it, because a half-admitted region has no cheap fallback
    composition at this level (ops.aggregate.region_linear_binned owns
    the differentiable wrapper and oracle)."""
    if any(a not in ("none", "relu") for a in activations):
        raise ValueError(f"activations={activations!r}: the region kernel "
                         f"fuses 'none' or 'relu' only")
    if precision not in ("fast", "exact"):
        raise ValueError(f"precision={precision!r}: must be 'fast' or "
                         f"'exact'")
    D = len(ws)
    widths = (x.shape[-1],) + tuple(w.shape[-1] for w in ws)
    assert region_ok(plan, widths, precision, x.dtype), \
        "run_binned_region called without region_ok admission"
    exact = precision == "exact" and x.dtype == jnp.float32
    geom = plan.geom or _default_geom()
    Hm = max(_pad_to(int(h), 128) for h in widths)
    C2 = plan.p2_obi.shape[1]
    G = plan.p1_blk.shape[0]
    out_rows = G * plan.bins_per_group * geom.rb
    rows_pad = _pad_to(plan.table_rows, geom.sb)
    tp = _pad_to(max(rows_pad, out_rows), max(geom.sb, geom.rb))
    xp = jnp.pad(x, ((0, rows_pad - x.shape[0]), (0, Hm - x.shape[-1])))
    wsp = jnp.stack([jnp.pad(w.astype(jnp.float32),
                             ((0, Hm - w.shape[0]), (0, Hm - w.shape[1])))
                     for w in ws])
    sp = None
    if fold:
        # the EXACT per-row multiplier ops.indegree_norm applies (x *
        # rsqrt(deg)); pad rows scale by 1 so zeros stay zeros
        sp = jnp.pad(jax.lax.rsqrt(in_degree)[:, None],
                     ((0, tp - in_degree.shape[0]), (0, 0)),
                     constant_values=1.0)
    relus = tuple(a == "relu" for a in activations)
    S = int(plan.f_blk.shape[0])
    with jax.named_scope("roc_binned_xlayer"):
        out = _xlayer_run(xp, wsp, sp, plan.f_blk, plan.f_blk2, plan.f_obi,
                          plan.f_last, plan.f_meta, plan.f_dsrc,
                          plan.f_ddst, plan.f_rows, S, C2, out_rows, tp,
                          interpret, exact, geom, D, relus, fold,
                          1 if G == 1 else 2)
    return out[:plan.num_rows, :ws[-1].shape[-1]].astype(x.dtype)


def _xlayer_bwd_kernel(*args, exact: bool = False, geom: Geometry = None,
                       depth: int = 2, sf: int = 0, sbs: int = 0, relus=(),
                       fold: bool = False):
    """Cross-layer backward: one grid, two phases.  Steps [0, (D-1)*sf)
    REPLAY the forward over the fwd plan (arrays [0, sf) of the
    concatenated schedule), filling the (D-1) inter-layer activation
    planes — scaled form for fold, exactly what the per-layer chain
    staged.  Steps after run D sweeps of the TRANSPOSED plan (arrays
    [sf, sf+sbs)), layer order ld = D-1-db: phase 1 stages the layer's
    output cotangent — from g HBM blocks at db=0 (masked by the saved
    region output, the per-layer rule) or from the cotangent ping-pong
    plane at db>0 (fold scales (s*)(s*) then the replayed-plane relu
    mask, the exact per-layer outside-ops order) — and phase 2
    accumulates BOTH gradients per chunk: dW_ld += z^T @ tile in the
    resident [1, Hm, Hm] dW block (z = the replayed previous-layer plane
    window, or the x window at ld=0; valid by distributivity — the same
    z window spans all of a bin's chunks, and masked pad rows contribute
    exact zeros) and the cotangent hand-off tile @ W_ld^T into the
    OTHER ping-pong parity (or the dx HBM window at db=D-1).  u never
    exists in HBM; each dW block zeroes at its depth's first step."""
    args = list(args)
    blk_ref, blk2_ref, obi_ref, last_ref = args[:4]
    (meta_ref, dsrc_ref, ddst_ref, rows_ref,
     x_ref, x2_ref, g_ref, g2_ref) = args[4:12]
    i = 12
    if relus[-1]:
        y_ref, y2_ref = args[i:i + 2]
        i += 2
    else:
        y_ref = y2_ref = None
    xw_ref, ws_ref, wst_ref = args[i:i + 3]
    i += 3
    if fold:
        s_ref, sb1_ref, sb2_ref = args[i:i + 3]
        i += 3
    else:
        s_ref = sb1_ref = sb2_ref = None
    dw_ref, dx0_ref, gbuf, stgbuf, tbuf, cg, sems = args[i:]
    CH, SB, RB, KD = geom.ch, geom.sb, geom.rb, geom.kd            # noqa
    U = geom.unit_rows
    st = staging_dtype(geom, exact)
    D = depth
    RPT = (D - 1) * sf
    NCG = 1 if D == 2 else 2
    c = pl.program_id(0)
    in_rep = c < RPT
    in_bwd = jnp.logical_not(in_rep)
    cb = c - RPT
    pidx = jnp.where(in_rep, c % sf, sf + cb % sbs)
    kind = meta_ref[c % 8, 0]
    par = meta_ref[c % 8, 1]
    first = meta_ref[c % 8, 2]
    sq = meta_ref[c % 8, 3]

    # the resident dW block zeroes at its depth's first step (the block
    # index just switched to this depth, so the fetched content is HBM
    # garbage or a stale writeback — never real)
    @pl.when(in_bwd & (cb % sbs == 0))
    def _():
        dw_ref[...] = jnp.zeros(dw_ref.shape, jnp.float32)

    @pl.when(kind == 0)
    def _():
        lane = jax.lax.broadcasted_iota(jnp.int32, (CH, SB), 1)
        sl = rows_ref[:]
        t1 = (lane == sl).astype(jnp.bfloat16)
        t2 = (lane == sl - SB).astype(jnp.bfloat16)
        two = blk2_ref[pidx] != blk_ref[pidx]

        @pl.when(in_rep & (c < sf))
        def _():
            gbuf[:] = _onehot_dot(t1, x_ref[:], (((1,), (0,)), ((), ())),
                                  exact).astype(st)

            @pl.when(two)
            def _():
                gbuf[:] = (gbuf[:].astype(jnp.float32) + _onehot_dot(
                    t2, x2_ref[:], (((1,), (0,)), ((), ())),
                    exact)).astype(st)

        for dd in range(1, D - 1):
            @pl.when(in_rep & (c // sf == dd))
            def _(dd=dd):
                src = tbuf[dd - 1, pl.ds(blk_ref[pidx] * SB, SB), :]
                gbuf[:] = _onehot_dot(t1, src, (((1,), (0,)), ((), ())),
                                      exact).astype(st)

                @pl.when(two)
                def _(dd=dd):
                    src2 = tbuf[dd - 1, pl.ds(blk2_ref[pidx] * SB, SB), :]
                    gbuf[:] = (gbuf[:].astype(jnp.float32) + _onehot_dot(
                        t2, src2, (((1,), (0,)), ((), ())),
                        exact)).astype(st)

        @pl.when(in_bwd & (cb < sbs))
        def _():
            gv = g_ref[:]
            gv2 = g2_ref[:]
            if relus[-1]:
                gv = jnp.where(y_ref[:] > 0, gv, jnp.zeros_like(gv))
                gv2 = jnp.where(y2_ref[:] > 0, gv2, jnp.zeros_like(gv2))
            gbuf[:] = _onehot_dot(t1, gv, (((1,), (0,)), ((), ())),
                                  exact).astype(st)

            @pl.when(two)
            def _():
                gbuf[:] = (gbuf[:].astype(jnp.float32) + _onehot_dot(
                    t2, gv2, (((1,), (0,)), ((), ())), exact)).astype(st)

        for dbs in range(1, D):
            @pl.when(in_bwd & (cb // sbs == dbs))
            def _(dbs=dbs):
                ld = D - 1 - dbs
                gv = cg[(dbs - 1) % NCG,
                        pl.ds(blk_ref[pidx] * SB, SB), :]
                if fold:
                    gv = (gv * sb1_ref[:]) * sb1_ref[:]
                if relus[ld]:
                    msk = tbuf[ld, pl.ds(blk_ref[pidx] * SB, SB), :]
                    gv = jnp.where(msk > 0, gv, jnp.zeros_like(gv))
                gbuf[:] = _onehot_dot(t1, gv, (((1,), (0,)), ((), ())),
                                      exact).astype(st)

                @pl.when(two)
                def _(dbs=dbs, ld=ld):
                    gv2 = cg[(dbs - 1) % NCG,
                             pl.ds(blk2_ref[pidx] * SB, SB), :]
                    if fold:
                        gv2 = (gv2 * sb2_ref[:]) * sb2_ref[:]
                    if relus[ld]:
                        msk2 = tbuf[ld,
                                    pl.ds(blk2_ref[pidx] * SB, SB), :]
                        gv2 = jnp.where(msk2 > 0, gv2,
                                        jnp.zeros_like(gv2))
                    gbuf[:] = (gbuf[:].astype(jnp.float32) + _onehot_dot(
                        t2, gv2, (((1,), (0,)), ((), ())),
                        exact)).astype(st)

        def issue(e, _):
            v = dsrc_ref[c % 8, e]

            @pl.when(v >= 0)
            def _():
                cls = v // 65536
                su = v - cls * 65536
                du = ddst_ref[c % 8, e]
                for ci, csz in enumerate(_DMA_CLS):
                    @pl.when(cls == ci)
                    def _(csz=csz):
                        pltpu.make_async_copy(
                            gbuf.at[pl.ds(su * U, csz * U)],
                            stgbuf.at[par].at[
                                pl.ds(du * U, csz * U)],
                            sems.at[0]).start()
            return 0
        jax.lax.fori_loop(0, KD, issue, 0)

        def drain(e, _):
            v = dsrc_ref[c % 8, e]

            @pl.when(v >= 0)
            def _():
                cls = v // 65536
                su = v - cls * 65536
                du = ddst_ref[c % 8, e]
                for ci, csz in enumerate(_DMA_CLS):
                    @pl.when(cls == ci)
                    def _(csz=csz):
                        pltpu.make_async_copy(
                            gbuf.at[pl.ds(su * U, csz * U)],
                            stgbuf.at[par].at[
                                pl.ds(du * U, csz * U)],
                            sems.at[0]).wait()
            return 0
        jax.lax.fori_loop(0, KD, drain, 0)

    @pl.when(kind == 1)
    def _():
        dl = rows_ref[:]
        chunk = stgbuf[par, pl.ds(sq * CH, CH)]
        rows = jnp.where(dl == RB, jnp.float32(0), chunk)
        lane = jax.lax.broadcasted_iota(jnp.int32, (CH, RB), 1)
        s_t = (lane == dl).astype(jnp.bfloat16)
        tile = _onehot_dot(s_t, rows, (((0,), (0,)), ((), ())), exact)

        for dd in range(D - 1):
            @pl.when(in_rep & (c // sf == dd))
            def _(dd=dd):
                contrib = jax.lax.dot_general(
                    tile, ws_ref[0], (((1,), (0,)), ((), ())),
                    precision=jax.lax.Precision.HIGHEST,
                    preferred_element_type=jnp.float32)
                off = obi_ref[pidx] * RB

                @pl.when(first == 1)
                def _(dd=dd):
                    tbuf[dd, pl.ds(off, RB), :] = jnp.zeros(
                        (RB, tbuf.shape[-1]), jnp.float32)

                tbuf[dd, pl.ds(off, RB), :] = (
                    tbuf[dd, pl.ds(off, RB), :] + contrib)

                @pl.when(last_ref[pidx] == 1)
                def _(dd=dd):
                    v = tbuf[dd, pl.ds(off, RB), :]
                    if relus[dd]:
                        v = jnp.maximum(v, 0.0)
                    if fold:
                        v = (v * s_ref[:]) * s_ref[:]
                    tbuf[dd, pl.ds(off, RB), :] = v

        for dbs in range(D):
            @pl.when(in_bwd & (cb // sbs == dbs))
            def _(dbs=dbs):
                ld = D - 1 - dbs
                off = obi_ref[pidx] * RB
                if ld == 0:
                    z = xw_ref[:]
                else:
                    z = tbuf[ld - 1, pl.ds(off, RB), :]
                dw_ref[0] = dw_ref[0] + jax.lax.dot_general(
                    z, tile, (((0,), (0,)), ((), ())),
                    precision=jax.lax.Precision.HIGHEST,
                    preferred_element_type=jnp.float32)
                dxc = jax.lax.dot_general(
                    tile, wst_ref[0], (((1,), (0,)), ((), ())),
                    precision=jax.lax.Precision.HIGHEST,
                    preferred_element_type=jnp.float32)
                if dbs == D - 1:
                    @pl.when(first == 1)
                    def _():
                        dx0_ref[:] = jnp.zeros_like(dx0_ref)

                    dx0_ref[:] += dxc
                else:
                    j = dbs % NCG

                    @pl.when(first == 1)
                    def _(j=j):
                        cg[j, pl.ds(off, RB), :] = jnp.zeros(
                            (RB, cg.shape[-1]), jnp.float32)

                    cg[j, pl.ds(off, RB), :] = (
                        cg[j, pl.ds(off, RB), :] + dxc)


@partial(jax.jit, static_argnames=("sf", "sbs", "c2", "out_rows", "tp",
                                   "interpret", "exact", "geom", "depth",
                                   "relus", "fold", "nparity"))
def _xlayer_bwd_run(x, g, y, ws, wst, s, blk, blk2, obi, last, meta, dsrc,
                    ddst, rows, sf: int, sbs: int, c2: int, out_rows: int,
                    tp: int, interpret: bool = False, exact: bool = False,
                    geom: Geometry = None, depth: int = 2, relus=(),
                    fold: bool = False, nparity: int = 2):
    Hm = x.shape[-1]
    CH, SB, RB, KD = geom.ch, geom.sb, geom.rb, geom.kd            # noqa
    D = depth
    RPT = (D - 1) * sf
    srows = c2 * geom.ch2
    ncg = 1 if D == 2 else 2

    def pidx(c):
        return jnp.where(c < RPT, c % sf, sf + (c - RPT) % sbs)

    in_specs = [
        pl.BlockSpec((8, 4), lambda c, b, b2, o, l: (pidx(c) // 8, 0),
                     memory_space=pltpu.SMEM),
        pl.BlockSpec((8, KD), lambda c, b, b2, o, l: (pidx(c) // 8, 0),
                     memory_space=pltpu.SMEM),
        pl.BlockSpec((8, KD), lambda c, b, b2, o, l: (pidx(c) // 8, 0),
                     memory_space=pltpu.SMEM),
        pl.BlockSpec((CH, 1), lambda c, b, b2, o, l: (pidx(c), 0)),
        # x blocks feed the replay's depth 0 only
        pl.BlockSpec((SB, Hm),
                     lambda c, b, b2, o, l: (
                         jnp.where(c < sf, b[pidx(c)], 0), 0)),
        pl.BlockSpec((SB, Hm),
                     lambda c, b, b2, o, l: (
                         jnp.where(c < sf, b2[pidx(c)], 0), 0)),
        # region-output cotangent blocks feed the backward's first sweep
        pl.BlockSpec((SB, Hm),
                     lambda c, b, b2, o, l: (
                         jnp.where((c >= RPT) & (c < RPT + sbs),
                                   b[pidx(c)], 0), 0)),
        pl.BlockSpec((SB, Hm),
                     lambda c, b, b2, o, l: (
                         jnp.where((c >= RPT) & (c < RPT + sbs),
                                   b2[pidx(c)], 0), 0)),
    ]
    if relus[-1]:
        in_specs += [
            pl.BlockSpec((SB, Hm),
                         lambda c, b, b2, o, l: (
                             jnp.where((c >= RPT) & (c < RPT + sbs),
                                       b[pidx(c)], 0), 0)),
            pl.BlockSpec((SB, Hm),
                         lambda c, b, b2, o, l: (
                             jnp.where((c >= RPT) & (c < RPT + sbs),
                                       b2[pidx(c)], 0), 0)),
        ]
    in_specs += [
        # dW z windows at layer 0 (the last backward sweep)
        pl.BlockSpec((RB, Hm),
                     lambda c, b, b2, o, l: (
                         jnp.where(c >= RPT + (D - 1) * sbs,
                                   o[pidx(c)], 0), 0)),
        pl.BlockSpec((1, Hm, Hm),
                     lambda c, b, b2, o, l: (
                         jnp.where(c < RPT, c // sf, 0), 0, 0)),
        pl.BlockSpec((1, Hm, Hm),
                     lambda c, b, b2, o, l: (
                         jnp.where(c >= RPT,
                                   D - 1 - (c - RPT) // sbs, 0), 0, 0)),
    ]
    if fold:
        in_specs += [
            pl.BlockSpec((RB, 1),
                         lambda c, b, b2, o, l: (
                             jnp.where(c < RPT, o[pidx(c)], 0), 0)),
            pl.BlockSpec((SB, 1),
                         lambda c, b, b2, o, l: (
                             jnp.where(c >= RPT, b[pidx(c)], 0), 0)),
            pl.BlockSpec((SB, 1),
                         lambda c, b, b2, o, l: (
                             jnp.where(c >= RPT, b2[pidx(c)], 0), 0)),
        ]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,      # concatenated blk, blk2, obi, last
        grid=(RPT + D * sbs,),
        in_specs=in_specs,
        out_specs=[
            # per-depth dW blocks: index walks D-1 .. 0 across the
            # backward sweeps (block 0's stale replay-phase writeback is
            # overwritten by its real depth, which runs LAST)
            pl.BlockSpec((1, Hm, Hm),
                         lambda c, b, b2, o, l: (
                             jnp.where(c >= RPT,
                                       D - 1 - (c - RPT) // sbs, 0),
                             0, 0)),
            pl.BlockSpec((RB, Hm),
                         lambda c, b, b2, o, l: (
                             jnp.where(c >= RPT + (D - 1) * sbs,
                                       o[pidx(c)], 0), 0)),
        ],
        scratch_shapes=[pltpu.VMEM((CH, Hm), staging_dtype(geom, exact)),
                        pltpu.VMEM((nparity, srows, Hm),
                                   staging_dtype(geom, exact)),
                        pltpu.VMEM((D - 1, tp, Hm), jnp.float32),
                        pltpu.VMEM((ncg, tp, Hm), jnp.float32),
                        pltpu.SemaphoreType.DMA((1,))],
    )
    ins = (blk, blk2, obi, last, meta, dsrc, ddst, rows, x, x, g, g)
    ins += (y, y) if relus[-1] else ()
    ins += (x, ws, wst)
    ins += (s, s, s) if fold else ()
    return pl.pallas_call(
        partial(_xlayer_bwd_kernel, exact=exact, geom=geom, depth=D,
                sf=sf, sbs=sbs, relus=relus, fold=fold),
        grid_spec=grid_spec,
        out_shape=[jax.ShapeDtypeStruct((D, Hm, Hm), jnp.float32),
                   jax.ShapeDtypeStruct((out_rows, Hm), jnp.float32)],
        interpret=interpret,
    )(*ins)


def run_binned_region_bwd(g, y, x, ws, in_degree, fwd_plan: BinnedPlan,
                          bwd_plan: BinnedPlan, interpret: bool = False,
                          precision: str = "fast", activations=(),
                          fold: bool = False):
    """Fused backward of a whole fusion region: given the region-output
    cotangent g, the saved region output y (last-layer relu mask source),
    the saved region input x and weight chain ws, returns
    (dx [rows, H_0], (dW_1, ..., dW_D)) — interior cotangents ping-pong
    in VMEM, the relu masks come from an in-kernel forward replay, and
    every dW accumulates in-kernel (u never exists in HBM).  Integer
    data reproduces the per-layer-fused chain bitwise; fp32 dW
    reassociates (bin-ordered adds vs one XLA GEMM) within the
    documented ULP bound.

    Returns None when ANY admission gate fails (region_ok on the forward
    plan, the transposed plan's own fused-schedule/geometry gates,
    ROC_MEGA_BWD=0, or the backward VMEM price): the caller replays the
    per-layer composition under jax.vjp — the bitwise oracle."""
    if precision not in ("fast", "exact"):
        raise ValueError(f"precision={precision!r}: must be 'fast' or "
                         f"'exact'")
    D = len(ws)
    widths = (x.shape[-1],) + tuple(w.shape[-1] for w in ws)
    geom = fwd_plan.geom or _default_geom()
    relus = tuple(a == "relu" for a in activations)
    if not region_ok(fwd_plan, widths, precision, x.dtype):
        return None
    if mega_bwd_killed():
        return None
    bgeom = bwd_plan.geom or _default_geom()
    if (bgeom != geom or bwd_plan.f_meta is None
            or bwd_plan.f_last is None
            or bwd_plan.table_rows != bwd_plan.num_rows):
        return None
    Hm = max(_pad_to(int(h), 128) for h in widths)
    C2f = fwd_plan.p2_obi.shape[1]
    C2b = bwd_plan.p2_obi.shape[1]
    C2 = max(C2f, C2b)
    Gf = fwd_plan.p1_blk.shape[0]
    Gb = bwd_plan.p1_blk.shape[0]
    out_rows_f = Gf * fwd_plan.bins_per_group * geom.rb
    out_rows_b = Gb * bwd_plan.bins_per_group * geom.rb
    rows_pad = _pad_to(fwd_plan.table_rows, geom.sb)
    if out_rows_b < _pad_to(bwd_plan.table_rows, geom.sb):
        return None
    tp = _pad_to(max(rows_pad, out_rows_f, out_rows_b),
                 max(geom.sb, geom.rb))
    if not _xlayer_bwd_vmem_ok(geom, Hm, C2, D, groups=max(Gf, Gb), tp=tp,
                               relu_last=relus[-1]):
        return None
    exact = precision == "exact" and x.dtype == jnp.float32
    xp = jnp.pad(x.astype(jnp.float32),
                 ((0, tp - x.shape[0]), (0, Hm - x.shape[-1])))
    gp = jnp.pad(g.astype(jnp.float32),
                 ((0, tp - g.shape[0]), (0, Hm - g.shape[-1])))
    yp = jnp.pad(y.astype(jnp.float32),
                 ((0, tp - y.shape[0]), (0, Hm - y.shape[-1]))) \
        if relus[-1] else None
    wsp = jnp.stack([jnp.pad(w.astype(jnp.float32),
                             ((0, Hm - w.shape[0]), (0, Hm - w.shape[1])))
                     for w in ws])
    wstp = jnp.stack([jnp.pad(jnp.transpose(w.astype(jnp.float32)),
                              ((0, Hm - w.shape[1]), (0, Hm - w.shape[0])))
                      for w in ws])
    sp = None
    if fold:
        sp = jnp.pad(jax.lax.rsqrt(in_degree)[:, None],
                     ((0, tp - in_degree.shape[0]), (0, 0)),
                     constant_values=1.0)
    blkc = jnp.concatenate([fwd_plan.f_blk, bwd_plan.f_blk])
    blk2c = jnp.concatenate([fwd_plan.f_blk2, bwd_plan.f_blk2])
    obic = jnp.concatenate([fwd_plan.f_obi, bwd_plan.f_obi])
    lastc = jnp.concatenate([fwd_plan.f_last, bwd_plan.f_last])
    metac = jnp.concatenate([fwd_plan.f_meta, bwd_plan.f_meta])
    dsrcc = jnp.concatenate([fwd_plan.f_dsrc, bwd_plan.f_dsrc])
    ddstc = jnp.concatenate([fwd_plan.f_ddst, bwd_plan.f_ddst])
    rowsc = jnp.concatenate([fwd_plan.f_rows, bwd_plan.f_rows])
    Sf = int(fwd_plan.f_blk.shape[0])
    Sb = int(bwd_plan.f_blk.shape[0])
    nparity = 1 if max(Gf, Gb) == 1 else 2
    with jax.named_scope("roc_binned_xlayer_bwd"):
        dws, dx0 = _xlayer_bwd_run(xp, gp, yp, wsp, wstp, sp, blkc, blk2c,
                                   obic, lastc, metac, dsrcc, ddstc, rowsc,
                                   Sf, Sb, C2, out_rows_b, tp, interpret,
                                   exact, geom, D, relus, fold, nparity)
    dx = dx0[:bwd_plan.num_rows, :widths[0]]
    gws = tuple(dws[d, :ws[d].shape[0], :ws[d].shape[1]]
                for d in range(D))
    return dx, gws


# one-shot: the eager path is a silent ~9x dispatch-overhead footgun
# (1.65 s vs 184 ms jitted at Reddit scale, docs/PERF.md) — warn once
# per process, never per call.
_EAGER_WARNED = [False]


def run_binned(x, plan: BinnedPlan, interpret: bool = False,
               precision: str = "fast"):
    """out[v] = sum over in-edges of x[src] via the two-phase schedule.

    x: [table_rows, H] (any float dtype) -> [num_rows, H] in x.dtype.
    fp32 accumulation; precision "fast" rounds features once to bf16,
    "exact" keeps fp32 end to end via 3-way bf16 splits (module doc).
    A bf16 input makes the two identical, so exact quietly degrades to
    the cheaper fast path there.

    Call under jit (the trainer always does): measured on v5e at Reddit
    scale, the eager path pays ~6x in scan dispatch overhead (1.65 s vs
    213 ms jitted — docs/PERF.md)."""
    if not _EAGER_WARNED[0] and jax.core.trace_state_clean():
        _EAGER_WARNED[0] = True
        warnings.warn(
            "run_binned called outside a jit trace: the eager scan path "
            "pays ~9x in dispatch overhead (1.65 s vs 184 ms jitted at "
            "Reddit scale, docs/PERF.md) — wrap the caller in jax.jit.",
            stacklevel=2)
    if precision not in ("fast", "exact"):
        # same rule as ops.aggregate.matmul_precision: a silent fallthrough
        # to the fast path would drop the fp32-exact guarantee
        raise ValueError(f"precision={precision!r}: must be 'fast' or "
                         f"'exact'")
    exact = precision == "exact" and x.dtype == jnp.float32
    if precision == "exact" and x.dtype not in (jnp.float32, jnp.bfloat16):
        # bf16 degrades to fast losslessly (identical semantics); any
        # other dtype would silently round through bf16 staging
        raise ValueError(f"precision='exact' supports float32/bfloat16 "
                         f"inputs, got {x.dtype}")
    # Mosaic requires DMA slices lane-aligned to the (8,128) tile: the slot
    # DMAs out of gbuf slice the H axis, so H must be a multiple of 128
    # (observed hard error at H=41: "Slice shape along dimension 2 must be
    # aligned to tiling (128)").  Pad features up and strip at the end —
    # the extra lanes ride the same tiles the hardware moves anyway.
    H = x.shape[-1]
    Hp = _pad_to(H, 128)
    geom = plan.geom or _default_geom()
    if exact and geom.flat and geom.unit == 16:
        # the 16-row unit exists only to make bf16 staging tile-legal;
        # routing fp32-exact through it would round every staged row
        raise ValueError(
            "precision='exact' is incompatible with a unit=16 (bf16 "
            "staging) flat geometry: pick a unit=0 flat preset or "
            "precision='fast'")
    G, C1 = plan.p1_blk.shape
    C2 = plan.p2_obi.shape[1]
    xp = jnp.pad(x, ((0, _pad_to(plan.table_rows, geom.sb) - x.shape[0]),
                     (0, Hp - H)))
    stg_rows = C2 * geom.ch2

    if geom.flat:
        out_rows = G * plan.bins_per_group * geom.rb
        if (plan.f_meta is not None
                and not os.environ.get("ROC_BINNED_NO_FUSE")
                and _fused_vmem_ok(geom, Hp, C2)):
            # fused pipeline: one grid, staging VMEM-resident, phases of
            # adjacent groups interleaved (gating re-checked against the
            # REAL padded width — the plan-build gate used a model H)
            S = int(plan.f_blk.shape[0])
            with jax.named_scope("roc_binned_fused"):
                out = _fused_run(xp, plan.f_blk, plan.f_blk2, plan.f_obi,
                                 plan.f_meta, plan.f_dsrc, plan.f_ddst,
                                 plan.f_rows, S, C2, out_rows, interpret,
                                 exact, geom)
            return out[:plan.num_rows, :H].astype(x.dtype)

        def fbody(_, gplan):
            srcl, blk, blk2, dsrc, ddst, dstl, obi, first = gplan
            with jax.named_scope("roc_binned_p1_flat"):
                stg = _p1_flat_run(xp, blk, blk2, dsrc, ddst, srcl, C1,
                                   stg_rows, interpret, exact, geom)
            with jax.named_scope("roc_binned_p2"):
                out_g = _p2_run(stg, obi, first, dstl, C2,
                                plan.bins_per_group * geom.rb, interpret,
                                exact, geom)
            return None, out_g

        _, outs = jax.lax.scan(
            fbody, None,
            (plan.p1_srcl, plan.p1_blk, plan.p1_blk2,
             plan.p1_dsrc, plan.p1_ddst,
             plan.p2_dstl, plan.p2_obi, plan.p2_first))
        out = outs.reshape(out_rows, Hp)
        return out[:plan.num_rows, :H].astype(x.dtype)

    def body(_, gplan):
        srcl, off, blk, dstl, obi, first = gplan
        with jax.named_scope("roc_binned_p1"):
            stg = _p1_run(xp, blk, off, srcl, C1, stg_rows, interpret,
                          exact, geom)
        with jax.named_scope("roc_binned_p2"):
            out_g = _p2_run(stg, obi, first, dstl, C2,
                            plan.bins_per_group * geom.rb, interpret,
                            exact, geom)
        return None, out_g

    _, outs = jax.lax.scan(
        body, None,
        (plan.p1_srcl, plan.p1_off, plan.p1_blk,
         plan.p2_dstl, plan.p2_obi, plan.p2_first))
    out = outs.reshape(G * plan.bins_per_group * geom.rb, Hp)
    return out[:plan.num_rows, :H].astype(x.dtype)


def pad_binned_plan(plan: BinnedPlan, C1: int, C2: int) -> BinnedPlan:
    """Pad a plan's chunk counts up to (C1, C2) with canonical no-ops so
    per-shard plans can be stacked into one static shard_map program
    (the binned analog of segment_sum.pad_chunks).

    Pad phase-1 chunks: block 0, all slots skipped (-1).  Pad phase-2
    chunks: revisit the last bin with first=0 and every row masked (RB)."""
    geom = plan.geom or _default_geom()
    G, c1 = plan.p1_blk.shape
    c2 = plan.p2_obi.shape[1]
    assert C1 >= c1 and C2 >= c2 and C1 % 8 == 0
    d1, d2 = C1 - c1, C2 - c2
    if d1 == 0 and d2 == 0:
        return plan
    if geom.flat:
        # flat pads: every slot masked (-1 -> one-hot no-match -> zero
        # row), no staging copies (dsrc/ddst -1), phase 2 revisits the
        # last bin fully masked.  Fused arrays stay valid — they index
        # only real chunks, and staging chunk ids are a prefix of the
        # padded layout — so keep them.
        return dataclasses.replace(
            plan,
            p1_srcl=jnp.pad(plan.p1_srcl,
                            ((0, 0), (0, d1 * geom.ch), (0, 0)),
                            constant_values=-1),
            p1_blk=jnp.pad(plan.p1_blk, ((0, 0), (0, d1))),
            p1_blk2=jnp.pad(plan.p1_blk2, ((0, 0), (0, d1))),
            p1_dsrc=jnp.pad(plan.p1_dsrc, ((0, 0), (0, d1), (0, 0)),
                            constant_values=-1),
            p1_ddst=jnp.pad(plan.p1_ddst, ((0, 0), (0, d1), (0, 0)),
                            constant_values=-1),
            p2_dstl=jnp.pad(plan.p2_dstl,
                            ((0, 0), (0, d2 * geom.ch2), (0, 0)),
                            constant_values=geom.rb),
            p2_obi=jnp.pad(plan.p2_obi, ((0, 0), (0, d2)), mode="edge"),
            p2_first=jnp.pad(plan.p2_first, ((0, 0), (0, d2))))
    return BinnedPlan(
        p1_srcl=jnp.pad(plan.p1_srcl, ((0, 0), (0, d1 * geom.ch), (0, 0))),
        p1_off=jnp.pad(plan.p1_off, ((0, 0), (0, d1), (0, 0)),
                       constant_values=-1),
        p1_blk=jnp.pad(plan.p1_blk, ((0, 0), (0, d1))),
        p2_dstl=jnp.pad(plan.p2_dstl, ((0, 0), (0, d2 * geom.ch2), (0, 0)),
                        constant_values=geom.rb),
        p2_obi=jnp.pad(plan.p2_obi, ((0, 0), (0, d2)), mode="edge"),
        p2_first=jnp.pad(plan.p2_first, ((0, 0), (0, d2))),
        num_rows=plan.num_rows, table_rows=plan.table_rows,
        bins_per_group=plan.bins_per_group, geom=plan.geom)


# -- incremental cell re-cut (dynamic-graph deltas, roc_tpu/serve/delta) ----
#
# The builders above are whole-graph; serving-time edge churn must not
# rebuild (minutes of host work at scale) or retrace (new buffers = new
# jit cache entry).  The delta path instead re-cuts ONE (source-block x
# destination-bin) cell at a time: a plan's cells are contiguous,
# capacity-padded row ranges of p1_srcl / p2_dstl whose positions are a
# pure function of the BUILD-TIME edge list and geometry, so rewriting a
# cell's rows in place (live edges compacted first, pad values after)
# reproduces the builder's semantics exactly while every other array —
# p1_off / p1_blk / p1_dsrc / p1_ddst / p2_obi / p2_first — stays
# untouched (they encode the cell LAYOUT, not the cell CONTENTS).
# plan_cell_layout re-derives that layout with builder-identical
# arithmetic; patch_plan_cells rewrites one cell into host copies of the
# two content arrays, which the caller device_puts into the SAME padded
# shapes (same treedef, same jit cache — zero retraces by construction).


class CellOverflowError(Exception):
    """An edge delta does not fit a cell's build-time slot padding (or
    lands in a cell the plan never cut).  Not a failure: the caller's
    escalation ladder answers with a full replan (roc_tpu/serve/delta)."""


@dataclasses.dataclass
class CellLayout:
    """Per-cell row geometry of one built plan direction.

    ``cell_ptr[i]:cell_ptr[i+1]`` indexes the flat row maps for cell i
    (capacity rows, in in-cell order):
      row_p1  row into the group's [C1*CH] phase-1 srcl rows
      row_stg row into the group's [C2*CH2] staging rows
      row_sec flat-schedule secondary-block addend (0 or sb; slot: 0)
    ``pad_srcl`` is the builder's value for unwritten p1 rows (slot
    schedule 0 — staged garbage masked at phase 2; flat -1 — exact-zero
    one-hot row)."""
    num_rows: int
    table_rows: int
    bins_per_group: int
    geom: Geometry
    G: int
    C1: int
    C2: int
    num_bins: int
    num_blocks: int
    cell_blk: np.ndarray    # [ncell] int64 source block
    cell_bin: np.ndarray    # [ncell] int64 GLOBAL destination bin
    cell_cap: np.ndarray    # [ncell] int64 padded row capacity
    cell_ptr: np.ndarray    # [ncell+1] int64 prefix into the row maps
    row_p1: np.ndarray      # [sum(cap)] int64
    row_stg: np.ndarray     # [sum(cap)] int64
    row_sec: np.ndarray     # [sum(cap)] int64
    pad_srcl: int

    def __post_init__(self):
        k = self.cell_blk * self.num_bins + self.cell_bin
        self._korder = np.argsort(k)
        self._ksorted = k[self._korder]

    @property
    def ncell(self) -> int:
        return len(self.cell_blk)

    def cells_of(self, src: np.ndarray, dst: np.ndarray) -> np.ndarray:
        """Cell index of each (src, dst) edge; -1 where the plan never
        cut that (block, bin) cell (caller escalates to a replan)."""
        src = np.asarray(src, np.int64)
        dst = np.asarray(dst, np.int64)
        q = (src // self.geom.sb) * self.num_bins + dst // self.geom.rb
        pos = np.searchsorted(self._ksorted, q)
        pos = np.minimum(pos, max(len(self._ksorted) - 1, 0))
        out = np.full(len(q), -1, np.int64)
        if len(self._ksorted):
            hit = self._ksorted[pos] == q
            out[hit] = self._korder[pos[hit]]
        return out


def plan_cell_layout(edge_src: np.ndarray, edge_dst: np.ndarray,
                     num_rows: int, table_rows: int,
                     geom: Geometry = None,
                     group_row_target: int = _GROUP_ROW_TARGET
                     ) -> CellLayout:
    """Re-derive a built plan's per-cell row layout from its BUILD-TIME
    edge list (the same arrays the plan was built from, in the same
    order) with builder-identical arithmetic — every formula below
    mirrors _build_binned_plan_numpy / _build_flat_plan_numpy, and the
    delta manager verifies the claim by re-rendering the content arrays
    from this layout and comparing them to the plan's (so native-builder
    drift refuses the patch path instead of corrupting it)."""
    geom = (geom or _default_geom()).check()
    if geom.grt:
        group_row_target = geom.grt
    SB, CH, SLOT, RB, CH2 = geom[:5]                   # noqa: N806
    edge_src = np.asarray(edge_src, np.int64)
    edge_dst = np.asarray(edge_dst, np.int64)
    E = edge_src.shape[0]
    num_bins = max(-(-num_rows // RB), 1)
    num_blocks = max(-(-table_rows // SB), 1)
    bins_per_group = max(min(
        num_bins,
        int(group_row_target / max(E / num_bins, 1)),
        _K2_CAP // num_blocks), 1)
    G = -(-num_bins // bins_per_group)

    bin_of = edge_dst // RB
    blk_of = edge_src // SB
    grp_of = bin_of // bins_per_group
    order = np.lexsort((bin_of, blk_of, grp_of))
    s_bin, s_blk = bin_of[order], blk_of[order]
    cell_key = (grp_of[order] * num_blocks + s_blk) * num_bins + s_bin
    uniq, cell_start, cell_cnt = np.unique(
        cell_key, return_index=True, return_counts=True)
    ncell = len(uniq)
    cell_g = uniq // (num_bins * num_blocks)
    cell_blk = (uniq // num_bins) % num_blocks
    cell_gbin = uniq % num_bins
    cell_lbin = cell_gbin - cell_g * bins_per_group
    bin_idx = cell_g * bins_per_group + cell_lbin
    gb_key = uniq // num_bins
    gb_uniq, gb_inv = np.unique(gb_key, return_inverse=True)
    gb_g = gb_uniq // num_blocks

    if geom.flat:
        U = geom.unit_rows                              # noqa: N806
        UC, U2 = CH // U, CH2 // U                      # noqa: N806
        cell_units = -(-cell_cnt // U)
        cell_cap = cell_units * U
        dense_bin_units = np.zeros(G * bins_per_group, np.int64)
        np.add.at(dense_bin_units, bin_idx, cell_units)
        dense_bin_chunks = np.maximum(-(-dense_bin_units // U2), 1)
        C2 = int(max(int(dense_bin_chunks.reshape(                 # noqa
            G, bins_per_group).sum(1).max(initial=0)), 1))
        bin_g = np.repeat(np.arange(G), bins_per_group)
        bin_chunk_base = _prefix_within_runs(dense_bin_chunks, bin_g)
        bo = np.argsort(bin_idx, kind="stable")
        cell_off_in_bin = np.zeros(ncell, np.int64)
        cell_off_in_bin[bo] = _prefix_within_runs(cell_units[bo],
                                                  bin_idx[bo])
        cell_stg_unit = bin_chunk_base[bin_idx] * U2 + cell_off_in_bin

        gb_units = np.zeros(len(gb_uniq), np.int64)
        np.add.at(gb_units, gb_inv, cell_units)
        c1_per_g, segs = _flat_pack(gb_g, gb_units, UC, G, segments=True)
        C1 = int(_pad_to(max(int(c1_per_g.max(initial=0)), 1), 8))  # noqa
        seg_stream, seg_chunk, seg_pos, seg_take = segs.T
        seg_g = gb_g[seg_stream]
        seg_blk = gb_uniq[seg_stream] % num_blocks
        p1_blk = np.zeros((G, C1), np.int64)
        opens = seg_pos == 0
        p1_blk[seg_g[opens], seg_chunk[opens]] = seg_blk[opens]

        total_units = int(cell_units.sum())
        cell_unit_base = np.cumsum(cell_units) - cell_units
        seg_start = np.cumsum(seg_take) - seg_take
        in_seg = np.arange(total_units) - np.repeat(seg_start, seg_take)
        unit_chunk = np.repeat(seg_chunk, seg_take)
        unit_pos = np.repeat(seg_pos, seg_take) + in_seg

        cell_ptr = np.concatenate([[0], np.cumsum(cell_cap)])
        tot = int(cell_ptr[-1])
        rc = np.repeat(np.arange(ncell), cell_cap)
        ri = np.arange(tot) - np.repeat(cell_ptr[:-1], cell_cap)
        uid = cell_unit_base[rc] + ri // U
        row_p1 = unit_chunk[uid] * CH + unit_pos[uid] * U + ri % U
        row_stg = cell_stg_unit[rc] * U + ri
        row_sec = SB * (p1_blk[cell_g[rc], unit_chunk[uid]]
                        != cell_blk[rc]).astype(np.int64)
        pad_srcl = -1
    else:
        NSLOT, SLOT2 = geom.nslot, geom.slot2           # noqa: N806
        cell_slots = -(-cell_cnt // SLOT)
        cell_cap = cell_slots * SLOT
        gb_slots = np.zeros(len(gb_uniq), np.int64)
        np.add.at(gb_slots, gb_inv, cell_slots)
        gb_chunks = -(-gb_slots // NSLOT)
        c1_per_g = np.zeros(G, np.int64)
        np.add.at(c1_per_g, gb_g, gb_chunks)
        C1 = int(_pad_to(max(int(c1_per_g.max(initial=0)), 1), 8))  # noqa
        gb_chunk_base = _prefix_within_runs(gb_chunks, gb_g)
        cell_p1_slot = _prefix_within_runs(cell_slots, gb_key)

        dense_bin_slots = np.zeros(G * bins_per_group, np.int64)
        np.add.at(dense_bin_slots, bin_idx, cell_slots)
        dense_bin_chunks = np.maximum(-(-dense_bin_slots // SLOT2), 1)
        C2 = int(max(int(dense_bin_chunks.reshape(                  # noqa
            G, bins_per_group).sum(1).max(initial=0)), 1))
        bin_g = np.repeat(np.arange(G), bins_per_group)
        bin_chunk_base = _prefix_within_runs(dense_bin_chunks, bin_g)
        bo = np.argsort(bin_idx, kind="stable")
        cell_off_in_bin = np.zeros(ncell, np.int64)
        cell_off_in_bin[bo] = _prefix_within_runs(cell_slots[bo],
                                                  bin_idx[bo])
        cell_stg_slot = bin_chunk_base[bin_idx] * SLOT2 + cell_off_in_bin

        cell_ptr = np.concatenate([[0], np.cumsum(cell_cap)])
        tot = int(cell_ptr[-1])
        rc = np.repeat(np.arange(ncell), cell_cap)
        ri = np.arange(tot) - np.repeat(cell_ptr[:-1], cell_cap)
        base_p1 = gb_chunk_base[gb_inv] * CH + cell_p1_slot * SLOT
        row_p1 = base_p1[rc] + ri
        row_stg = cell_stg_slot[rc] * SLOT + ri
        row_sec = np.zeros(tot, np.int64)
        pad_srcl = 0

    del cell_start
    return CellLayout(
        num_rows=num_rows, table_rows=table_rows,
        bins_per_group=bins_per_group, geom=geom, G=G, C1=C1, C2=C2,
        num_bins=num_bins, num_blocks=num_blocks,
        cell_blk=cell_blk, cell_bin=cell_gbin,
        cell_cap=cell_cap.astype(np.int64), cell_ptr=cell_ptr,
        row_p1=row_p1, row_stg=row_stg, row_sec=row_sec,
        pad_srcl=pad_srcl)


def empty_cell_arrays(layout: CellLayout):
    """Pad-initialized host copies of the two content arrays — what the
    builders start from before writing any edge (slot p1 rows 0, flat
    -1; staging rows RB = phase-2 masked)."""
    p1 = np.full((layout.G, layout.C1 * layout.geom.ch),
                 layout.pad_srcl, np.int32)
    p2 = np.full((layout.G, layout.C2 * layout.geom.ch2),
                 layout.geom.rb, np.int32)
    return p1, p2


def patch_plan_cells(layout: CellLayout, p1_srcl: np.ndarray,
                     p2_dstl: np.ndarray, ci: int,
                     src: np.ndarray, dst: np.ndarray) -> None:
    """Rewrite ONE cell of the host content arrays in place: the cell's
    live edges (in global-order; values must land in this cell) occupy
    its first len(src) rows, the rest revert to pad values.  Raises
    CellOverflowError when the edges exceed the cell's build-time
    capacity — the escalation ladder's trigger, never a partial write."""
    lo, hi = int(layout.cell_ptr[ci]), int(layout.cell_ptr[ci + 1])
    cap = hi - lo
    n = len(src)
    if n > cap:
        raise CellOverflowError(
            f"cell {ci} (blk={int(layout.cell_blk[ci])}, "
            f"bin={int(layout.cell_bin[ci])}): {n} edges exceed the "
            f"build-time capacity of {cap} rows")
    g = int(layout.cell_bin[ci]) // layout.bins_per_group
    blk = int(layout.cell_blk[ci])
    bn = int(layout.cell_bin[ci])
    p1v = np.full(cap, layout.pad_srcl, np.int32)
    p2v = np.full(cap, layout.geom.rb, np.int32)
    if n:
        src = np.asarray(src, np.int64)
        dst = np.asarray(dst, np.int64)
        p1v[:n] = (src - blk * layout.geom.sb
                   + layout.row_sec[lo:lo + n]).astype(np.int32)
        p2v[:n] = (dst - bn * layout.geom.rb).astype(np.int32)
    p1_srcl[g, layout.row_p1[lo:hi]] = p1v
    p2_dstl[g, layout.row_stg[lo:hi]] = p2v
