"""Static collective auditor: budget the communication a config compiles to.

ROC gets data-race freedom and placement correctness structurally from
Legion's region requirements; the XLA/SPMD port's only guard so far was
the *runtime* numerical checker (`parallel/check.py`).  This module adds
the static half: lower the jitted train/eval step for a config (no
execution — works on a CPU dev box for TPU-shaped programs), extract
every collective / transfer op and dtype widening from the StableHLO
text, and diff the result against a checked-in per-config budget
manifest (``budgets.json``).  A GSPMD-inserted resharding, an exchange
that grew an extra all_gather, or a silent f64 upcast then fails loudly
at lint time — with the offending op's source location — instead of
surfacing months later as an unattributable perf regression.

What is budgeted per step function (train and eval separately):
  * count and total result elements for each tracked op
    (``all_gather``, ``all_reduce``, ``reduce_scatter``, ``all_to_all``,
    ``collective_permute``, ``dynamic_slice``, ``dynamic_update_slice``);
    region-form ops that print their result type on the region's closing
    line (e.g. ``all_reduce``) are budgeted count-only (elems 0);
  * lines mentioning ``f64`` and ``convert``-to-f64 upcasts (normally 0 —
    the tree is fp32/bf16 by design);
  * the entry arguments' ``mhlo.sharding`` signature — a dropped or
    altered placement (e.g. a replicated tensor that should be
    parts-sharded) changes this string before it changes any op count.

Budgets are keyed ``model/dataset/p<parts>/<configured-backend>/<exchange>``
and are *lowering*-level: regenerate with ``tools/roclint.py
--update-budgets`` whenever a deliberate change alters the compiled
communication pattern (the diff in budgets.json then documents exactly
what changed).  The audit matrix lowers on CPU with 8 forced host
devices — the manifest is only comparable under that topology, which is
what conftest.py and the roclint CLI both pin.
"""

from __future__ import annotations

import dataclasses
import json
import os
import re
from typing import Dict, List, Optional

BUDGETS_PATH = os.path.join(os.path.dirname(__file__), "budgets.json")

TRACKED_OPS = (
    "all_gather", "all_reduce", "reduce_scatter", "all_to_all",
    "collective_permute", "dynamic_slice", "dynamic_update_slice",
)
_OP_RES = {op: re.compile(r"\bstablehlo\." + op + r"\b")
           for op in TRACKED_OPS}
_ARROW_TENSOR_RE = re.compile(r"->\s*tensor<([^>]*)>")
_CONVERT_F64_RE = re.compile(r"stablehlo\.convert\b.*->\s*tensor<[^>]*f64")
_SHARDING_RE = re.compile(r'mhlo\.sharding = "([^"]+)"')


def _tensor_elems(body: str) -> int:
    """Element count of a ``tensor<...>`` body like ``4x24x8xf32``."""
    n = 1
    for tok in body.split("x"):
        if tok.isdigit():
            n *= int(tok)
    return n


def _main_arg_shardings(txt: str) -> List[str]:
    """Per-entry-arg mhlo.sharding strings ("" = unannotated), in order."""
    i = txt.find("@main(")
    if i < 0:
        return []
    j = txt.find("\n", i)
    sig = txt[i:j if j > 0 else len(txt)]
    out = []
    for seg in re.split(r"%arg\d+", sig)[1:]:
        m = _SHARDING_RE.search(seg)
        out.append(m.group(1) if m else "")
    return out


def audit_hlo_text(txt: str) -> dict:
    """Audit one StableHLO module (``Lowered.as_text()``) → budget dict."""
    ops: Dict[str, Dict[str, int]] = {}
    f64_lines = 0
    convert_f64 = 0
    for line in txt.splitlines():
        if "f64" in line:
            f64_lines += 1
            if _CONVERT_F64_RE.search(line):
                convert_f64 += 1
        for op, rx in _OP_RES.items():
            if rx.search(line):
                ent = ops.setdefault(op, {"count": 0, "elems": 0})
                ent["count"] += 1
                m = _ARROW_TENSOR_RE.search(line)
                if m:
                    ent["elems"] += _tensor_elems(m.group(1))
    return {
        "ops": ops,
        "f64_lines": f64_lines,
        "convert_f64": convert_f64,
        "arg_shardings": _main_arg_shardings(txt),
    }


def audit_lowered(lowered) -> dict:
    return audit_hlo_text(lowered.as_text())


def op_locations(lowered, op: str, limit: int = 3) -> List[str]:
    """Source locations of ``op`` in a lowered module (debug-info ASM)."""
    try:
        asm = lowered.compiler_ir().operation.get_asm(
            enable_debug_info=True, large_elements_limit=16)
    except Exception:
        return []
    rx = _OP_RES[op]
    locs: List[str] = []
    for line in asm.splitlines():
        if rx.search(line):
            m = re.search(r"loc\((.*)\)\s*$", line)
            locs.append(m.group(1) if m else line.strip()[:160])
            if len(locs) >= limit:
                break
    return locs


# -- whole-trainer audit ---------------------------------------------------

@dataclasses.dataclass
class AuditReport:
    """Audit of one built trainer: ``steps`` maps step name → budget dict;
    ``lowereds`` keeps the jax Lowered objects for source-location lookups
    (not serialized)."""
    key: Optional[str]
    steps: Dict[str, dict]
    lowereds: Dict[str, object] = dataclasses.field(default_factory=dict,
                                                    repr=False)

    def to_json(self) -> dict:
        return self.steps

    def summary(self) -> str:
        lines = [f"# audit {self.key or '<unkeyed>'}"]
        for name, st in sorted(self.steps.items()):
            opstr = ", ".join(
                f"{op}x{v['count']}({v['elems']})"
                for op, v in sorted(st["ops"].items())) or "no collectives"
            lines.append(f"#   {name}: {opstr}; f64_lines="
                         f"{st['f64_lines']} convert_f64={st['convert_f64']}")
        return "\n".join(lines)


def trainer_key(trainer) -> str:
    """Budget-manifest key for a built trainer (configured backend, not the
    resolved one, so CPU and TPU runs of the same flags share a key)."""
    cfg = trainer.config
    ds = cfg.dataset or (os.path.basename(cfg.filename)
                         if cfg.filename else "mem")
    if cfg.num_parts > 1:
        exch = "edge" if getattr(trainer, "_use_edge_shard", False) \
            else trainer._exchange_mode
    else:
        exch = "single"
    return (f"{cfg.model}/{ds}/p{cfg.num_parts}/"
            f"{cfg.aggregate_backend}/{exch}")


def lower_steps(trainer) -> Dict[str, object]:
    """Lower the trainer's jitted train/eval steps with its real arguments
    (lowering only — nothing runs).  Shared by the HLO audit below and the
    memory estimator's XLA cross-checks (roc_tpu/memory/estimator.py)."""
    import jax
    import jax.numpy as jnp
    rng = jax.random.PRNGKey(0)
    alpha = jnp.float32(trainer.optimizer.alpha)
    lo_train = trainer._train_step.lower(
        trainer.params, trainer.opt_state, trainer.x, trainer.labels,
        trainer.mask, trainer.gdata, rng, alpha, jnp.float32(1.0))
    lo_eval = trainer._eval_step.lower(
        trainer.params, trainer.x, trainer.labels, trainer.mask,
        trainer.gdata)
    return {"train": lo_train, "eval": lo_eval}


def audit_trainer(trainer, key: Optional[str] = None) -> AuditReport:
    """Lower the trainer's compiled train/eval steps with its real
    arguments and audit the StableHLO."""
    lowereds = lower_steps(trainer)
    return AuditReport(key=key or trainer_key(trainer),
                       steps={n: audit_lowered(lo)
                              for n, lo in lowereds.items()},
                       lowereds=lowereds)


def check_invariants(report: AuditReport) -> List[str]:
    """Budget-free invariants that hold for every config: no f64 anywhere
    (the tree is fp32/bf16 by design), so any ``convert``-to-f64 is a
    silent dtype widening XLA decided on its own."""
    viol = []
    for name, st in sorted(report.steps.items()):
        if st["convert_f64"]:
            viol.append(f"{report.key}/{name}: {st['convert_f64']} "
                        f"convert-to-f64 upcast(s) in the lowered program")
        elif st["f64_lines"]:
            viol.append(f"{report.key}/{name}: {st['f64_lines']} line(s) "
                        f"mention f64 in the lowered program")
    return viol


def compare_report(report: AuditReport, budget: dict) -> List[str]:
    """Diff a report against one manifest entry; [] = within budget.

    Exact-match semantics: collective counts and element totals, the f64
    counters, and the entry-arg sharding signature must all be identical.
    On a count mismatch the message carries the op's source locations from
    the debug-info ASM when available.
    """
    viol: List[str] = []
    for name in sorted(set(report.steps) | set(budget)):
        got = report.steps.get(name)
        want = budget.get(name)
        if got is None or want is None:
            viol.append(f"{report.key}/{name}: step "
                        f"{'missing from audit' if got is None else 'not in budget'}")
            continue
        for op in sorted(set(got["ops"]) | set(want["ops"])):
            g = got["ops"].get(op, {"count": 0, "elems": 0})
            w = want["ops"].get(op, {"count": 0, "elems": 0})
            if g != w:
                msg = (f"{report.key}/{name}: {op} count/elems "
                       f"{g['count']}/{g['elems']} != budget "
                       f"{w['count']}/{w['elems']}")
                lo = report.lowereds.get(name)
                if lo is not None and g["count"] > w["count"]:
                    locs = op_locations(lo, op)
                    if locs:
                        msg += f" (at {'; '.join(locs)})"
                viol.append(msg)
        for k in ("f64_lines", "convert_f64"):
            if got[k] != want.get(k, 0):
                viol.append(f"{report.key}/{name}: {k} {got[k]} != "
                            f"budget {want.get(k, 0)}")
        if got["arg_shardings"] != want.get("arg_shardings", []):
            ga, wa = got["arg_shardings"], want.get("arg_shardings", [])
            detail = []
            for i in range(max(len(ga), len(wa))):
                a = ga[i] if i < len(ga) else "<absent>"
                b = wa[i] if i < len(wa) else "<absent>"
                if a != b:
                    detail.append(f"arg{i}: {a or '<none>'} != "
                                  f"budget {b or '<none>'}")
            viol.append(f"{report.key}/{name}: entry-arg sharding "
                        f"signature changed (GSPMD resharding or dropped "
                        f"placement): {'; '.join(detail[:4])}")
    return viol


# -- the audit matrix ------------------------------------------------------

# Tiny deterministic SBM graph: big enough that every part keeps real halo
# traffic at 4 parts (96/4 = 24-node shards, avg degree 4), small enough
# that the full 24-config matrix lowers in well under a minute on CPU.
AUDIT_DATASET = dict(num_nodes=96, avg_degree=4.0, in_dim=8, num_classes=4,
                     n_train=48, n_val=24, n_test=24, seed=7)
AUDIT_LAYERS = [8, 8, 4]


@dataclasses.dataclass(frozen=True)
class AuditSpec:
    model: str
    parts: int
    backend: str     # configured -aggr-backend
    exchange: str    # halo | allgather | ring | single
    serve: bool = False  # audit the serving engine's bucketed query step
                         # instead of the trainer's train/eval steps


def audit_specs() -> List[AuditSpec]:
    """model × parts × backend × exchange matrix (ring rides matmul —
    spmd forces it; parts=1 has no exchange), plus serve rows: the
    serving engine's jitted query step at the smallest and largest
    padded buckets, so a compiled-program change on the serving path
    (an extra collective, a dtype widening, a gather blowup) diffs in
    budgets.json exactly like a training-step change would."""
    specs: List[AuditSpec] = []
    for model in ("gcn", "gat"):
        for backend in ("matmul", "binned"):
            specs.append(AuditSpec(model, 1, backend, "single"))
        for parts in (2, 4):
            for backend in ("matmul", "binned"):
                for exch in ("halo", "allgather"):
                    specs.append(AuditSpec(model, parts, backend, exch))
            specs.append(AuditSpec(model, parts, "matmul", "ring"))
        for backend in ("matmul", "binned"):
            specs.append(AuditSpec(model, 1, backend, "serve", serve=True))
    return specs


def spec_key(spec: AuditSpec) -> str:
    return (f"{spec.model}/roc-audit/p{spec.parts}/{spec.backend}/"
            f"{spec.exchange}")


def build_audit_trainer(spec: AuditSpec, *, exchange: Optional[str] = None):
    """Build (without training) the trainer for one matrix entry.
    ``exchange`` overrides the lowered exchange mode while keeping the
    spec's budget key — the seeded-mutation tests use this to audit an
    allgather program against the halo budget."""
    import roc_tpu  # noqa: F401 — installs the jax.shard_map polyfill
    from roc_tpu.graph import datasets
    from roc_tpu.models import build_model
    from roc_tpu.train.config import Config
    from roc_tpu.train.driver import make_trainer
    ds = datasets.synthetic("roc-audit", **AUDIT_DATASET)
    exch = exchange if exchange is not None else spec.exchange
    cfg = Config(dataset="roc-audit", layers=list(AUDIT_LAYERS),
                 num_epochs=1, model=spec.model, heads=2,
                 aggregate_backend=spec.backend, num_parts=spec.parts,
                 exchange=("" if exch == "single" else exch),
                 edge_shard="off", eval_every=10 ** 6, seed=3)
    model = build_model(cfg.model, cfg.layers, cfg.dropout_rate, cfg.aggr,
                        heads=cfg.heads)
    return make_trainer(cfg, ds, model)


def build_audit_engine(spec: AuditSpec):
    """Cold-start (queueless) the serving engine for one serve row."""
    import roc_tpu  # noqa: F401 — installs the jax.shard_map polyfill
    from roc_tpu.graph import datasets
    from roc_tpu.models import build_model
    from roc_tpu.serve.engine import ServeEngine
    from roc_tpu.train.config import Config
    ds = datasets.synthetic("roc-audit", **AUDIT_DATASET)
    cfg = Config(dataset="roc-audit", layers=list(AUDIT_LAYERS),
                 num_epochs=1, model=spec.model, heads=2,
                 aggregate_backend=spec.backend, edge_shard="off",
                 eval_every=10 ** 6, seed=3, serve_batch=8)
    model = build_model(cfg.model, cfg.layers, cfg.dropout_rate, cfg.aggr,
                        heads=cfg.heads)
    return ServeEngine(cfg, ds, model, start_queue=False)


def audit_serve_engine(spec: AuditSpec,
                       key: Optional[str] = None) -> AuditReport:
    """Lower the engine's serve_step at the bucket ladder's ends: the
    two programs bound the padded-shape set (middle buckets only vary
    the gather width between them)."""
    import jax.numpy as jnp
    import numpy as np
    eng = build_audit_engine(spec)
    try:
        lowereds = {}
        for b in (eng.buckets[0], eng.buckets[-1]):
            lowereds[f"serve_b{b}"] = eng._serve_step.lower(
                eng.bundle.params, eng.bundle.x, eng.bundle.gdata,
                jnp.int32(b), jnp.asarray(np.zeros(b, np.int32)))
        return AuditReport(key=key or spec_key(spec),
                           steps={n: audit_lowered(lo)
                                  for n, lo in lowereds.items()},
                           lowereds=lowereds)
    finally:
        eng.close()


def audit_spec(spec: AuditSpec, key: Optional[str] = None) -> AuditReport:
    """One matrix entry → report (trainer steps or serve buckets)."""
    if spec.serve:
        return audit_serve_engine(spec, key=key)
    return audit_trainer(build_audit_trainer(spec), key=key)


def run_audit(specs: Optional[List[AuditSpec]] = None,
              progress=None) -> Dict[str, dict]:
    """Lower + audit every matrix entry → {budget key: steps dict}."""
    out: Dict[str, dict] = {}
    for spec in specs or audit_specs():
        key = spec_key(spec)
        if progress:
            progress(key)
        out[key] = audit_spec(spec, key=key).to_json()
    return out


# -- manifest --------------------------------------------------------------

def load_budgets(path: str = BUDGETS_PATH) -> Dict[str, dict]:
    if not os.path.exists(path):
        return {}
    with open(path, encoding="utf-8") as f:
        return json.load(f)


def save_budgets(budgets: Dict[str, dict], path: str = BUDGETS_PATH):
    with open(path, "w", encoding="utf-8") as f:
        json.dump(budgets, f, indent=1, sort_keys=True)
        f.write("\n")


def audit_against_budgets(specs: Optional[List[AuditSpec]] = None,
                          path: str = BUDGETS_PATH,
                          progress=None) -> List[str]:
    """Run the matrix and diff every entry against the manifest."""
    budgets = load_budgets(path)
    if not budgets:
        return [f"no budget manifest at {path}; run "
                f"tools/roclint.py --update-budgets"]
    viol: List[str] = []
    for spec in specs or audit_specs():
        key = spec_key(spec)
        if progress:
            progress(key)
        report = audit_spec(spec, key=key)
        if key not in budgets:
            viol.append(f"{key}: not in budget manifest (run "
                        f"--update-budgets)")
            continue
        viol.extend(compare_report(report, budgets[key]))
        viol.extend(check_invariants(report))
    return viol
