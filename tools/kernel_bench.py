#!/usr/bin/env python
"""Per-kernel microbench harness: time each Pallas kernel variant in
isolation across the geometry space and persist the measured table.

Variants, per shape x geometry (only those whose gates admit them):
  twopass    run_binned over the slot-padded two-phase schedule, plus
             phase 1 and phase 2 timed alone (staging round-tripped)
  flat       run_binned over the flat compacted schedule with the fused
             step list stripped — the scan fallback the VMEM gate runs
  fused      run_binned over the fused single-grid pipeline
  mega_fwd   run_binned_linear (aggregate->linear megakernel) at H=KB_H
  mega_bwd   run_binned_linear_bwd over the TRANSPOSED plan (relu path)
  gat        run_binned_gat (+_bwd when the head-group gate admits it):
             the fused per-head score->softmax->aggregate megakernel at
             K=2 heads x F=64 (the lane-packed Hp=128 shape); also pairs
             the ledger's gat_fused_hbm_bytes prediction (same content
             key dense_graph_data predicts under) against the compiled
             program's XLA bytes-accessed figure
  matmul     scatter_gather_matmul — the one-hot backend the balance
             cost model's warm-start prior prices

On CPU the kernels run in Pallas interpret mode: the numbers are HARNESS
timings (they validate schema + mechanics in CI), not performance — the
table records ``interpret: true`` and every measured-calibration
consumer (binned.measured_calibration, the balance prior) ignores such
tables.  On hardware (tools/hw_revalidate.sh step 3h) the same command
produces the rates of record.

The table lands under the ``measured`` key of tools/kernel_budgets.json
with --update; check_kernel_budgets.py diffs AROUND that key, so a fresh
hardware table never trips the schedule-drift gate.  Each benched plan
is also written to the content-keyed plan cache (the bench forces
ROC_PLAN_CACHE_MIN_EDGES=0 for its own builds), so a trainer hitting the
same graph content warm-starts its plan build from disk; the measured
per-grid-step and per-chunk rates are what binned.measured_calibration
feeds back into choose_geometry's cost model and the balance prior
(cost_model.fit seeds them at MEASURED_PRIOR_WEIGHT).

The bench attaches the calibration ledger around each choose_geometry
call and measures the winner's wall time under the same plan content
key, pairing the ``geom_time`` predictions nothing else can measure; the
records ride KB_OBS_DIR/metrics.jsonl (default roc_obs_kb) and feed
`python -m roc_tpu.obs calibration`.

    python tools/kernel_bench.py                 # CI shape, interpret
    python tools/kernel_bench.py --update        # + write measured table
    KB_DEVICE=1 python tools/kernel_bench.py --update   # hardware table
    python tools/kernel_bench.py --filter flat/mega_shard_scaled
        # bench only the selected rows: each --filter is an fnmatch
        # pattern against "<variant>/<shape>" (or "<shape>/<variant>",
        # or a bare variant/shape name); repeat or comma-separate to
        # select several.  --update still rewrites the whole measured
        # key, so filtered runs are for iteration, not for the table of
        # record (docs/DESIGN.md §Autotuner).
"""

import dataclasses
import fnmatch
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Bench builds always hit the plan cache (warm-start side effect of
# record); must be set before roc_tpu import.
os.environ.setdefault("ROC_PLAN_CACHE_MIN_EDGES", "0")

import numpy as np  # noqa: E402

BUDGETS_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "kernel_budgets.json")

DEVICE = bool(int(os.environ.get("KB_DEVICE", "0")))
H = int(os.environ.get("KB_H", "128"))
REPS = int(os.environ.get("KB_REPS", "5" if DEVICE else "1"))

# CI shape: the mega-shard scale where the fused schedule attaches and
# the VMEM gate admits the megakernel at H=128, so interpret mode
# exercises EVERY variant.  Device mode adds the dense/sparse scales the
# step-budget table pins (check_kernel_budgets.SHAPES).
SHAPES_CI = [("mega_shard_scaled", 1024, 8192, 2)]
SHAPES_DEVICE = SHAPES_CI + [
    ("reddit_scaled", 32768, 4_194_304, 0),
    ("products_scaled", 262_144, 2_097_152, 1),
]

#: --filter patterns (fnmatch); empty = bench everything.
FILTERS = []


def _want(shape: str, variant: str) -> bool:
    """Row selection for --filter: a pattern may name the row as
    variant/shape or shape/variant, or just one side of it."""
    if not FILTERS:
        return True
    keys = (f"{variant}/{shape}", f"{shape}/{variant}", variant, shape)
    return any(fnmatch.fnmatch(k, p) for p in FILTERS for k in keys)


def _geometries():
    import roc_tpu.ops.pallas.binned as B
    geoms = [("default", B._default_geom()),
             ("flat", B.GEOM_FLAT),
             ("flat_bf16", B.GEOM_FLAT_BF16)]
    if DEVICE:
        geoms += [("wide", B.GEOM_WIDE),
                  ("sparse_wide", B.GEOM_SPARSE_WIDE),
                  ("flat_sparse", B.GEOM_FLAT_SPARSE)]
    return geoms


def _timeit(fn):
    """Mean seconds per call over REPS, after a compile+warm call.
    obs.span is the sanctioned clock (raw-timing lint rule)."""
    import jax
    from roc_tpu import obs
    jax.block_until_ready(fn())
    with obs.span("kernel_bench", reps=REPS) as sp:
        for _ in range(REPS):
            out = fn()
        jax.block_until_ready(out)
    return sp.dur_s / REPS


def _strip_fused(plan):
    """The flat scan-fallback variant: same plan, fused step list gone."""
    return dataclasses.replace(
        plan, f_meta=None, f_rows=None, f_blk=None, f_blk2=None,
        f_obi=None, f_dsrc=None, f_ddst=None, f_last=None)


def _phase_times(x, plan, geom, interpret):
    """(p1_s, p2_s): each phase scanned over all groups in isolation."""
    import jax
    import jax.numpy as jnp
    import roc_tpu.ops.pallas.binned as B
    G, C1 = plan.p1_blk.shape
    C2 = plan.p2_obi.shape[1]
    Hp = B._pad_to(x.shape[1], 128)
    xp = jnp.pad(x, ((0, B._pad_to(plan.table_rows, geom.sb) - x.shape[0]),
                     (0, Hp - x.shape[1])))
    stg_rows = C2 * geom.ch2

    @jax.jit
    def p1_all(xp):
        if geom.flat:
            def body(_, gp):
                srcl, blk, blk2, dsrc, ddst = gp
                stg = B._p1_flat_run(xp, blk, blk2, dsrc, ddst, srcl, C1,
                                     stg_rows, interpret, False, geom)
                return None, jnp.sum(stg.astype(jnp.float32))
            xs = (plan.p1_srcl, plan.p1_blk, plan.p1_blk2,
                  plan.p1_dsrc, plan.p1_ddst)
        else:
            def body(_, gp):
                srcl, off, blk = gp
                stg = B._p1_run(xp, blk, off, srcl, C1, stg_rows,
                                interpret, False, geom)
                return None, jnp.sum(stg.astype(jnp.float32))
            xs = (plan.p1_srcl, plan.p1_off, plan.p1_blk)
        _, s = jax.lax.scan(body, None, xs)
        return s

    stg = jnp.zeros((stg_rows, Hp), B.staging_dtype(geom, False))

    @jax.jit
    def p2_all(stg):
        def body(_, gp):
            dstl, obi, first = gp
            out = B._p2_run(stg, obi, first, dstl, C2,
                            plan.bins_per_group * geom.rb, interpret,
                            False, geom)
            return None, jnp.sum(out)
        _, s = jax.lax.scan(body, None,
                            (plan.p2_dstl, plan.p2_obi, plan.p2_first))
        return s

    return _timeit(lambda: p1_all(xp)), _timeit(lambda: p2_all(stg))


def bench_shape(name, n, e, seed, interpret, led):
    import jax
    import jax.numpy as jnp
    import roc_tpu.ops.pallas.binned as B
    from roc_tpu.ops.aggregate import (build_aggregate_plans,
                                       scatter_gather_matmul)
    rng = np.random.default_rng(seed)
    src = rng.integers(0, n, size=e).astype(np.int64)
    dst = rng.integers(0, n, size=e).astype(np.int64)
    x = jnp.asarray(rng.standard_normal((n, H)).astype(np.float32))
    w = jnp.asarray(rng.standard_normal((H, H)).astype(np.float32) * 0.1)
    entry = {"num_rows": n, "num_edges": e, "seed": seed, "kernels": {}}

    for gname, geom in _geometries():
        if not _want(name, gname):
            continue
        cb, cn, cnt = B._cell_stats(src, dst, geom.sb, geom.rb)
        _, s1, s2 = B._plan_steps(cb, cn, cnt, geom, n, n, e)
        # geom_time pairing: predict under the ledger with THIS geometry
        # forced, then measure the built plan's wall time by content key.
        _, pred_t = B.choose_geometry(src, dst, n, n, candidates=[geom],
                                      force=True)
        # tuned_ok=False: the bench times exactly the geometry it names
        # (a tuned-tier swap here would silently A/B the wrong config)
        plan = B.build_binned_plan(src, dst, n, n, geom=geom,
                                   tuned_ok=False)
        key = B._plan_key(n, n, e, plan.geom)
        row = {"steps_total": int(s1 + s2)}

        if geom.flat:
            flat_plan = (_strip_fused(plan) if plan.f_meta is not None
                         else plan)
            t = _timeit(lambda p=flat_plan: jax.jit(
                lambda xx: B.run_binned(xx, p, interpret))(x))
            row["variant"], row["flat_s"] = "flat", t
            if plan.f_meta is not None:
                tf = _timeit(lambda p=plan: jax.jit(
                    lambda xx: B.run_binned(xx, p, interpret))(x))
                row["fused_s"] = tf
                tm = _timeit(lambda p=plan: jax.jit(
                    lambda xx, ww: B.run_binned_linear(
                        xx, ww, p, interpret))(x, w))
                row["mega_fwd_s"] = tm
                t = min(t, tf)
        else:
            t = _timeit(lambda p=plan: jax.jit(
                lambda xx: B.run_binned(xx, p, interpret))(x))
            row["variant"], row["total_s"] = "twopass", t
            p1, p2 = _phase_times(x, plan, geom, interpret)
            row["p1_s"], row["p2_s"] = p1, p2
        row["total_s"] = t
        row["per_step_s"] = t / max(s1 + s2, 1)
        if led is not None:
            led.measure("geom_time", key, t, "s")
        entry["kernels"][gname] = row
        print(f"{name}/{gname}: {row['variant']} {t * 1e3:.2f} ms "
              f"({row['steps_total']} steps, modeled {pred_t * 1e3:.2f} ms)")

    # Fused backward over the transposed plan (the plans.bwd direction).
    if _want(name, "mega_bwd"):
        bwd_geom = B.GEOM_FLAT_BF16
        bwd_plan = B.build_binned_plan(dst, src, n, n, geom=bwd_geom,
                                       tuned_ok=False)
        g = jnp.asarray(rng.standard_normal((n, H)).astype(np.float32))
        y = jnp.abs(x)
        probe = B.run_binned_linear_bwd(g, y, w, bwd_plan, interpret,
                                        relu=True)
        if probe is not None:
            tb = _timeit(lambda: jax.jit(
                lambda gg, yy, ww: B.run_binned_linear_bwd(
                    gg, yy, ww, bwd_plan, interpret, relu=True))(g, y, w))
            entry["kernels"]["flat_bf16/mega_bwd"] = {
                "variant": "mega_bwd", "total_s": tb,
                "steps_total": int(bwd_plan.f_blk.shape[0]),
                "per_step_s": tb / max(int(bwd_plan.f_blk.shape[0]), 1)}
            print(f"{name}/flat_bf16 mega_bwd: {tb * 1e3:.2f} ms")
        else:
            print(f"{name}/flat_bf16 mega_bwd: gate closed (skipped)")

    # Fused GAT attention (round 19): forward + hand-derived backward
    # over the fwd/transposed plan pair, at the K=2 x F=64 head-stacked
    # shape (Hp = 128, one head group).  The section also closes the
    # gat_fused_hbm_bytes calibration loop: it re-issues the plan-build
    # prediction under the bench ledger (same predictor + content key as
    # train/driver.dense_graph_data) and measures the jitted step's XLA
    # bytes-accessed — a compiler figure, so it is paired on hardware
    # AND in interpret mode (where it prices the emulation, another
    # reason interpret tables are harness-only).
    if _want(name, "gat"):
        import roc_tpu.ops.pallas.gat as G
        from roc_tpu.obs.ledger import content_key
        K, F = 2, H // 2
        gplan = B.build_binned_plan(src, dst, n, n, geom=B.GEOM_FLAT,
                                    tuned_ok=False)
        gbwd = B.build_binned_plan(dst, src, n, n, geom=B.GEOM_FLAT,
                                   tuned_ok=False)
        ng, bwd_ok = G.gat_head_groups(gplan, gbwd, K, F)
        if ng:
            table = x.reshape(n, K, F)
            a_src = jnp.asarray(
                rng.standard_normal((K, F)).astype(np.float32))
            ad_l = jnp.asarray(
                rng.standard_normal((n, K)).astype(np.float32))
            sf = int(gplan.f_meta.shape[0])

            def gat_fwd(tt, aa, dd):
                return G.run_binned_gat(tt, aa, dd, gplan, 0.2,
                                        interpret, "exact")

            jfwd = jax.jit(gat_fwd)
            tg = _timeit(lambda: jfwd(table, a_src, ad_l))
            entry["kernels"]["flat/gat_fwd"] = {
                "variant": "gat_fwd", "total_s": tg, "heads": K,
                "head_dim": F, "steps_total": 2 * sf,
                "per_step_s": tg / max(2 * sf, 1)}
            print(f"{name}/flat gat_fwd: {tg * 1e3:.2f} ms "
                  f"({2 * sf} steps, K={K} F={F})")

            if bwd_ok:
                out, m, z = jfwd(table, a_src, ad_l)
                gout = jnp.asarray(rng.standard_normal(
                    (n, K, F)).astype(np.float32))
                sb_ = int(gbwd.f_meta.shape[0])

                def gat_bwd(gg, oo, tt, aa, dd, mm, zz):
                    return G.run_binned_gat_bwd(
                        gg, oo, tt, aa, dd, mm, zz, gplan, gbwd, 0.2,
                        interpret, "exact")

                jbwd = jax.jit(gat_bwd)
                tb2 = _timeit(lambda: jbwd(gout, out, table, a_src,
                                           ad_l, m, z))
                entry["kernels"]["flat/gat_bwd"] = {
                    "variant": "gat_bwd", "total_s": tb2,
                    "steps_total": sf + sb_,
                    "per_step_s": tb2 / max(sf + sb_, 1)}
                print(f"{name}/flat gat_bwd: {tb2 * 1e3:.2f} ms "
                      f"({sf + sb_} steps)")

            if led is not None:
                def _bytes_accessed(jitted, *a):
                    try:
                        ca = jitted.lower(*a).compile().cost_analysis()
                        if isinstance(ca, (list, tuple)):
                            ca = ca[0] if ca else {}
                        return float(ca.get("bytes accessed", 0.0))
                    except Exception:  # cost analysis is backend-optional
                        return 0.0

                gkey = content_key(rows=n, edges=e, heads=K, fdim=F)
                led.predict(
                    "gat_fused_hbm_bytes", gkey,
                    G.predicted_gat_trainstep_hbm_bytes(
                        n, e, K, F, fused=True),
                    "bytes", shape=name)
                measured = _bytes_accessed(jfwd, table, a_src, ad_l)
                if bwd_ok:
                    measured += _bytes_accessed(jbwd, gout, out, table,
                                                a_src, ad_l, m, z)
                if measured:
                    ratio = led.measure("gat_fused_hbm_bytes", gkey,
                                        measured, "bytes", shape=name)
                    if ratio is not None:
                        print(f"{name}/flat gat_fused_hbm_bytes: "
                              f"measured/predicted {ratio:.3g}")
                else:
                    print(f"{name}/flat gat: no bytes-accessed figure "
                          "from this backend (measurement skipped)")
        else:
            print(f"{name}/flat gat: head-group gate closed (skipped)")

    # The one-hot matmul backend — the rate the balance prior prices.
    # Its chunk planner requires dst-sorted edges (csr order; the binned
    # planners sort internally).
    if _want(name, "matmul"):
        order = np.argsort(dst, kind="stable")
        plans = build_aggregate_plans(src[order], dst[order], n, n)
        chunks = B._matmul_chunks(e, n)
        tm = _timeit(lambda: jax.jit(
            lambda xx: scatter_gather_matmul(xx, plans, n, n))(x))
        entry["kernels"]["matmul"] = {
            "variant": "matmul", "chunks": int(chunks), "total_s": tm,
            "per_chunk_s": tm / max(chunks, 1)}
        print(f"{name}/matmul: {tm * 1e3:.2f} ms ({chunks} chunks)")
    return entry


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    update = "--update" in argv
    it = iter(argv)
    for a in it:
        if a == "--filter":
            FILTERS.extend(p for p in next(it, "").split(",") if p)
        elif a.startswith("--filter="):
            FILTERS.extend(p for p in a.split("=", 1)[1].split(",") if p)
    import jax
    from roc_tpu import obs
    platform = jax.default_backend()
    interpret = platform not in ("tpu", "axon")
    if DEVICE and interpret:
        print("KB_DEVICE=1 but no accelerator backend is live; refusing "
              "to write interpret timings as a device table",
              file=sys.stderr)
        return 1

    obs_dir = os.environ.get("KB_OBS_DIR", "roc_obs_kb")
    os.makedirs(obs_dir, exist_ok=True)
    reg = obs.MetricsRegistry(
        jsonl_path=os.path.join(obs_dir, "metrics.jsonl"))
    led = obs.get_ledger()
    led.attach(reg.emit)

    shapes = SHAPES_DEVICE if DEVICE else SHAPES_CI
    t0 = time.time()
    table = {"platform": platform, "interpret": interpret, "h": H,
             "reps": REPS, "shapes": {}}
    try:
        for name, n, e, seed in shapes:
            entry = bench_shape(name, n, e, seed, interpret, led)
            if entry["kernels"]:        # --filter may deselect a shape
                table["shapes"][name] = entry
    finally:
        led.detach()
    table["wall_s"] = round(time.time() - t0, 3)
    rep = obs.ledger.calibration_report(
        [{"type": k, **r} for k, r in led.records])
    gt = rep["models"].get("geom_time")
    if gt:
        print(f"# geom_time calibration: {gt['pairs']} pairs, mean ratio "
              f"{gt['ratio_mean']:.3g} (measured/modeled)")

    if update:
        committed = {}
        if os.path.exists(BUDGETS_PATH):
            with open(BUDGETS_PATH, encoding="utf-8") as f:
                committed = json.load(f)
        committed["measured"] = table
        with open(BUDGETS_PATH, "w", encoding="utf-8") as f:
            json.dump(committed, f, indent=1, sort_keys=True)
            f.write("\n")
        print(f"# kernel_bench: wrote measured table -> {BUDGETS_PATH}")
    else:
        print("# kernel_bench: dry run (pass --update to persist)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
