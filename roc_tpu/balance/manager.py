"""BalanceManager: the collect -> fit -> propose -> apply loop.

Orchestrates one "balance round" at an epoch boundary (train/driver.py):

  collect  probe each part's live aggregation time (a jitted repeated
           scatter_gather over that part's live edge slice — per-part
           arrays are padded to a common E, so timing the padded arrays
           would show identical work everywhere and fit nothing), plus the
           work counters from the partition + halo structure;
  fit      refit the online least-squares cost model on the telemetry ring;
  propose  run the min-max repartition search under the frozen shard shape;
  apply    hysteresis — reshard only when the predicted relative gain
           clears ``min_gain`` AND the projected saving over the remaining
           epochs exceeds the *measured* resharding cost.  The first apply
           is optimistic (no measurement exists yet; applying is how we get
           one); every later decision amortizes the measured cost.

No-op safety: a proposal identical to the current cut is skipped outright,
so a balancer whose search reproduces the static cut leaves the training
trajectory bit-for-bit unchanged.
"""

from __future__ import annotations

import functools
from typing import List, Optional

import numpy as np

from roc_tpu import obs
from roc_tpu.balance import search
from roc_tpu.balance.cost_model import OnlineCostModel
from roc_tpu.balance.telemetry import ShardSample, TelemetryBuffer
from roc_tpu.graph.csr import Csr
from roc_tpu.graph.partition import Partition

# Probe geometry: feature width and the edge-op target that sets the
# repeat count (amortizes dispatch overhead into the timed region).
_PROBE_WIDTH = 32
_PROBE_TARGET_EDGES = 600_000
_PROBE_MAX_REPS = 192
_PROBE_TRIES = 5


@functools.lru_cache(maxsize=256)
def _probe_fn(reps: int, part_index: int, shard_nodes: int, width: int):
    """Jitted probe: ``reps`` chained scatter_gathers over one part's live
    edges.  The output is written back into the padded-global table slice it
    came from, giving each iteration a true data dependency — without it XLA
    hoists the loop-invariant gather and the loop times nothing."""
    import jax
    import jax.numpy as jnp
    from roc_tpu import ops

    def run(table, src, dst):
        def body(_, tab):
            out = ops.scatter_gather(tab, src, dst, shard_nodes, "sum")
            out = out / jnp.maximum(jnp.abs(out).max(), 1.0)
            return jax.lax.dynamic_update_slice(
                tab, out, (part_index * shard_nodes, 0))
        return jax.lax.fori_loop(0, reps, body, table)

    return jax.jit(run)


def probe_part_times(part: Partition, width: int = _PROBE_WIDTH
                     ) -> List[float]:
    """Measured per-iteration aggregation time for each part's live edges."""
    import jax.numpy as jnp
    P, S = part.num_parts, part.shard_nodes
    table = jnp.ones((P * S, width), jnp.float32)
    out = []
    for p in range(P):
        ne = int(part.num_edges_valid[p])
        if ne == 0:
            out.append(0.0)
            continue
        src = jnp.asarray(part.edge_src[p, :ne])
        dst = jnp.asarray(part.edge_dst[p, :ne])
        reps = min(max(1, -(-_PROBE_TARGET_EDGES // ne)), _PROBE_MAX_REPS)
        fn = _probe_fn(reps, p, S, width)
        fn(table, src, dst).block_until_ready()  # compile + warm
        best = np.inf
        for _ in range(_PROBE_TRIES):
            # the probe span times exactly this sync: device latency of
            # one part's aggregation, min-of-tries against timer noise
            # (obs.span is the sanctioned clock — raw-timing lint rule)
            with obs.span("probe", part=p, reps=reps) as sp:
                fn(table, src, dst).block_until_ready()
            best = min(best, sp.dur_s)
        out.append(best / reps)
    return out


class BalanceManager:
    """Per-trainer balancer state; one instance lives for the whole run."""

    def __init__(self, min_gain: float = 0.05, trace_path: str = "",
                 telemetry: Optional[TelemetryBuffer] = None,
                 halo_width: int = 0, halo_itemsize: int = 0):
        self.min_gain = float(min_gain)
        # 0 = "caller didn't thread the run's shape" — the cost model keeps
        # its probe-width fp32 fallback for the warm-start prior.
        kw = {}
        if halo_width:
            kw["halo_width"] = int(halo_width)
        if halo_itemsize:
            kw["halo_itemsize"] = int(halo_itemsize)
        self.model = OnlineCostModel(**kw)
        # `is not None`, not `or`: an empty TelemetryBuffer is falsy (len 0).
        self.telemetry = (telemetry if telemetry is not None
                          else TelemetryBuffer(trace_path=trace_path))
        self.reshard_cost_s: Optional[float] = None
        self.rounds = 0
        self.events: List[dict] = []
        # Optional obs.PerfWatchdog: when the driver runs with -obs it
        # points this at its watchdog so probe-time stragglers land in the
        # same alert stream as slow epochs.
        self.watchdog = None

    @classmethod
    def from_config(cls, cfg, halo_width: int = 0, halo_itemsize: int = 0,
                    telemetry: Optional[TelemetryBuffer] = None
                    ) -> "BalanceManager":
        return cls(min_gain=cfg.balance_min_gain,
                   trace_path=cfg.balance_trace, telemetry=telemetry,
                   halo_width=halo_width, halo_itemsize=halo_itemsize)

    # -- the four stages --------------------------------------------------
    def collect(self, part: Partition, graph: Csr, epoch: int
                ) -> List[ShardSample]:
        """Probe + counters for every part; records into the telemetry ring."""
        times = probe_part_times(part)
        halo_in, halo_out = search.halo_counts(graph.row_ptr, graph.col_idx,
                                               part.bounds)
        samples = []
        for p in range(part.num_parts):
            s = ShardSample(
                epoch=epoch, part=p, time_s=float(times[p]),
                nodes=int(part.num_valid[p]),
                edges=int(part.num_edges_valid[p]),
                halo_in=int(halo_in[p]), halo_out=int(halo_out[p]))
            self.telemetry.record(s)
            samples.append(s)
        return samples

    def fit(self) -> float:
        X, t = self.telemetry.design()
        if len(t) == 0:
            return float("nan")
        return self.model.fit(X, t)

    def propose(self, part: Partition, graph: Csr):
        """(bounds, predicted_times_new, predicted_times_current)."""
        bounds, times = search.propose_bounds(
            graph.row_ptr, graph.col_idx, part.num_parts, self.model,
            max_nodes=part.shard_nodes - 1, max_edges=part.shard_edges)
        cur = self.model.predict(
            search.part_features(graph.row_ptr, graph.col_idx, part.bounds))
        return bounds, times, cur

    def step(self, trainer, epoch: int, remaining_epochs: int
             ) -> Optional[dict]:
        """One balance round against a live trainer.  Returns the decision
        record (also appended to ``self.events`` and the JSONL trace), or
        None when balancing is impossible for this trainer."""
        part = getattr(trainer, "part", None)
        if part is None:
            return None
        graph = trainer.dataset.graph
        self.rounds += 1
        # Calibration pair for the fitted cost model: predict the slowest
        # shard's probe time BEFORE probing (only once a measured fit
        # exists — round 1 would test the warm-start prior, not the fit),
        # measure it right after.  One pair per balance round.
        led = obs.get_ledger()
        pred_key = None
        if led.attached and self.rounds > 1:
            from roc_tpu.obs.ledger import content_key
            feats = search.part_features(graph.row_ptr, graph.col_idx,
                                         part.bounds)
            pred_key = content_key(round=self.rounds,
                                   parts=part.num_parts)
            led.predict("shard_cost", pred_key,
                        float(np.max(self.model.predict(feats))), "s",
                        epoch=int(epoch))
        samples = self.collect(part, graph, epoch)
        if pred_key is not None:
            led.measure("shard_cost", pred_key,
                        max(s.time_s for s in samples), "s",
                        epoch=int(epoch))
        if self.watchdog is not None:
            # same probe times the cost model fits; a straggler alert
            # lands in the JSONL next to the round that should fix it
            for alert in self.watchdog.observe_shards(
                    epoch, [s.time_s for s in samples]):
                self.telemetry.record_event("watchdog", **alert)
        r2 = self.fit()
        bounds, t_new, t_cur = self.propose(part, graph)
        ev = self._decide(trainer, part, bounds, t_new, t_cur, epoch,
                          remaining_epochs, r2)
        self.events.append(ev)
        self.telemetry.record_event("balance", **ev)
        return ev

    def _decide(self, trainer, part, bounds, t_new, t_cur, epoch,
                remaining_epochs, r2) -> dict:
        max_new, max_cur = float(np.max(t_new)), float(np.max(t_cur))
        rel_gain = 1.0 - max_new / max_cur if max_cur > 0 else 0.0
        ev = {"epoch": epoch, "round": self.rounds, "r2": r2,
              "pred_max_cur_s": max_cur, "pred_max_new_s": max_new,
              "rel_gain": rel_gain, "action": "skip"}
        if np.array_equal(np.asarray(bounds), np.asarray(part.bounds)):
            ev["action"] = "noop"          # proposal == current cut
            return ev
        if rel_gain < self.min_gain:
            ev["reason"] = f"gain {rel_gain:.3f} < min_gain {self.min_gain}"
            return ev
        # Hysteresis: projected epoch-time saving over the remaining epochs
        # must beat the measured reshard cost.  Scale the probe-level gain
        # by the latest measured epoch time (probe seconds are per-layer
        # aggregation iterations, not epochs).
        epoch_s = trainer.epoch_times[-1] if getattr(
            trainer, "epoch_times", None) else 0.0
        if self.reshard_cost_s is not None:
            saving = rel_gain * epoch_s * remaining_epochs
            if saving <= self.reshard_cost_s:
                ev["reason"] = (f"projected saving {saving:.3f}s <= measured "
                                f"reshard cost {self.reshard_cost_s:.3f}s")
                return ev
        cost = trainer.reshard(np.asarray(bounds, dtype=np.int64))
        self.reshard_cost_s = float(cost)
        ev.update(action="reshard", reshard_cost_s=float(cost),
                  bounds=np.asarray(bounds).tolist())
        return ev
