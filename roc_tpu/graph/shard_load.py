"""Per-host (pod-scale) partition loading.

Reference analog: each Legion point task seeks only its partition's byte
ranges of the `.lux` file (load_task.cu:201-245) — no node ever holds the
whole topology.  Round-1 of this framework regressed that: every host read
the full graph and built all P parts.  This module restores per-host cost:

  * process 0 reads ONLY the row-offset section (8 bytes/vertex), runs the
    greedy edge-balanced cut, and broadcasts the packed O(P) geometry
    (:class:`roc_tpu.graph.partition.PartitionMeta`);
  * every process then reads only its local parts' row/column slices
    (native `roc_lux_read_slice` when built, seek+fromfile otherwise) and
    builds only local shards' padded edge arrays;
  * halo maps need remote information (what each *other* shard's edges
    reference of ours), so the row-index lists are exchanged host-side:
    one allgather of an O(P^2) size matrix + one allgather of the padded
    [L, P, K] need lists.  The exchange callable is injected — real runs
    pass `jax.experimental.multihost_utils.process_allgather`, tests pass a
    thread-barrier mock — and the outputs are bit-identical to the
    single-host `build_halo_maps` path (asserted by tests/test_shard_load.py).

Per-host peak memory: O(N/P + E/P) arrays + the O(P^2 K) halo exchange,
vs O(N + E + P*E_shard) for the single-host path.  (Process 0 additionally
holds the O(N) row pointer transiently during the cut.)
"""

from __future__ import annotations

import dataclasses
from typing import Callable, List, Sequence

import numpy as np

from roc_tpu.graph.csr import E_DTYPE, V_DTYPE
from roc_tpu.graph.lux import read_cols_slice, read_header, read_rows_slice
from roc_tpu.graph.partition import PartitionMeta, compute_meta

# allgather(x: np.ndarray) -> np.ndarray of shape [num_processes, *x.shape],
# process-major in process-index order.  multihost_utils.process_allgather
# has exactly this contract.
AllGather = Callable[[np.ndarray], np.ndarray]


def allgather_floors(counts, allgather) -> "list[int]":
    """Cross-process static-shape floors: local per-side maxima →
    allgather → global maxima.  Every process must compile the SAME
    shard_map program, so per-shard pad targets take the global max chunk
    count per side.  ``counts``: [n_sides][n_local_shards] ints;
    ``allgather`` None (single-process) returns the local maxima."""
    local = np.asarray(counts, np.int64).max(axis=1)
    if allgather is None:
        return [int(v) for v in local]
    g = np.asarray(allgather(local)).max(axis=0)
    return [int(v) for v in np.reshape(g, -1)]


def single_process_allgather(x: np.ndarray) -> np.ndarray:
    return np.asarray(x)[None]


def jax_allgather() -> AllGather:
    """process_allgather with an int64-safe detour.

    Without jax_enable_x64 (this repo never enables it), jax canonicalizes
    int64 inputs to int32 — which would silently wrap num_edges/edge_starts
    past 2^31 edges, i.e. at exactly the pod scale this loader exists for.
    int64 arrays are split into two uint32 planes (which canonicalization
    leaves alone) and reassembled after the gather."""
    import jax
    from jax.experimental import multihost_utils

    def gather(x):
        # Old jax (< 0.5) returns the bare array from a single-process
        # gather; new jax always prepends a process axis.  Callers index
        # [proc], so normalize to the process-axis form.
        g = np.asarray(multihost_utils.process_allgather(x))
        return g[None] if g.shape == x.shape else g

    def ag(x):
        x = np.asarray(x)
        if x.dtype == np.int64 and not jax.config.jax_enable_x64:
            hi = (x >> 32).astype(np.uint32)          # arithmetic shift
            lo = (x & 0xFFFFFFFF).astype(np.uint32)
            g = gather(np.stack([hi, lo], axis=-1))
            ghi = g[..., 0].astype(np.int64)
            ghi -= (ghi >> 31) << 32                  # re-sign the high word
            return (ghi << 32) | g[..., 1].astype(np.int64)
        return gather(x)

    return ag


def _pack_meta(meta: PartitionMeta) -> np.ndarray:
    return np.concatenate([
        np.asarray([meta.num_parts, meta.shard_nodes, meta.shard_edges,
                    meta.num_nodes, meta.num_edges], np.int64),
        meta.bounds.reshape(-1).astype(np.int64),
        meta.num_edges_valid.astype(np.int64),
        meta.edge_starts.astype(np.int64),
    ])


def _unpack_meta(buf: np.ndarray) -> PartitionMeta:
    P = int(buf[0])
    bounds = buf[5:5 + 2 * P].reshape(P, 2).copy()
    return PartitionMeta(
        num_parts=P, shard_nodes=int(buf[1]), shard_edges=int(buf[2]),
        num_nodes=int(buf[3]), num_edges=int(buf[4]), bounds=bounds,
        num_valid=np.maximum(bounds[:, 1] - bounds[:, 0] + 1, 0),
        num_edges_valid=buf[5 + 2 * P:5 + 3 * P].copy(),
        edge_starts=buf[5 + 3 * P:5 + 4 * P].copy())


def meta_from_lux(path: str, num_parts: int, process_index: int = 0,
                  allgather: AllGather = single_process_allgather,
                  bounds=None, shard_nodes: int = 0,
                  shard_edges: int = 0) -> PartitionMeta:
    """Compute (on process 0) and share the partition geometry.

    Only process 0 pays the O(N) row-offset read + greedy cut; everyone else
    receives the packed O(P) result through the allgather (a broadcast is
    just an allgather we read row 0 of — keeps the injected-exchange surface
    to one primitive).

    ``bounds`` / ``shard_nodes`` / ``shard_edges`` pass through to
    ``compute_meta``: an external cut (a balancer reshard under streaming
    re-reads moved byte ranges) with the padded shapes frozen to the
    original geometry, so downstream compiled steps keep their shapes.
    External bounds are validated (contiguous, non-overlapping, within the
    file's node range) before any byte range is derived from them —
    streaming hits this path on every reshard."""
    if process_index == 0:
        num_nodes, num_edges = read_header(path)
        raw_rows = read_rows_slice(path, 0, num_nodes)
        row_ptr = np.zeros(num_nodes + 1, dtype=E_DTYPE)
        row_ptr[1:] = raw_rows.astype(E_DTYPE)
        if not np.all(np.diff(row_ptr) >= 0):
            raise ValueError(f"non-monotone .lux row offsets in {path}: "
                             "edge ranges would overlap or run backwards")
        meta = compute_meta(row_ptr, num_parts, bounds=bounds,
                            shard_nodes=shard_nodes or None,
                            shard_edges=shard_edges or None)
        packed = _pack_meta(meta)
    else:
        packed = np.zeros(5 + 4 * num_parts, np.int64)
    shared = allgather(packed)[0]
    return _unpack_meta(shared)


@dataclasses.dataclass(frozen=True)
class LocalShards:
    """Edge arrays for this process's parts only (L = len(part_ids) rows,
    same per-row layout/padding rules as :class:`Partition`'s arrays —
    tests assert bit-equality against the single-host builder)."""
    part_ids: tuple
    edge_src: np.ndarray   # [L, E] padded-global source ids
    edge_dst: np.ndarray   # [L, E] local dest rows, ascending
    in_degree: np.ndarray  # [L, S] float32, 1.0 on pad rows
    node_mask: np.ndarray  # [L, S] bool

    def nbytes(self) -> int:
        return (self.edge_src.nbytes + self.edge_dst.nbytes
                + self.in_degree.nbytes + self.node_mask.nbytes)


def load_local_shards(path: str, meta: PartitionMeta,
                      part_ids: Sequence[int]) -> LocalShards:
    """Build the padded edge arrays for `part_ids` reading only those parts'
    `.lux` byte ranges (the reference's per-partition seek,
    load_task.cu:231-243)."""
    L = len(part_ids)
    P, S, E = meta.num_parts, meta.shard_nodes, meta.shard_edges
    edge_src = np.zeros((L, E), dtype=E_DTYPE)
    edge_dst = np.zeros((L, E), dtype=V_DTYPE)
    in_degree = np.ones((L, S), dtype=np.float32)
    node_mask = np.zeros((L, S), dtype=bool)
    uppers = meta.bounds[:, 1]
    for i, p in enumerate(part_ids):
        lo, hi = meta.bounds[p]
        n = int(meta.num_valid[p])
        ne = int(meta.num_edges_valid[p])
        if n > 0:
            e0 = int(meta.edge_starts[p])
            # local row offsets -> per-vertex degrees for vertices lo..hi
            ends = read_rows_slice(path, lo, hi + 1).astype(np.int64)
            deg = np.diff(np.concatenate([[e0], ends]))
            in_degree[i, :n] = deg.astype(np.float32)
            node_mask[i, :n] = True
            if ne > 0:
                src_global = read_cols_slice(path, meta.num_nodes, e0,
                                             e0 + ne).astype(np.int64)
                owner = np.searchsorted(uppers, src_global, side="left")
                edge_src[i, :ne] = (owner * S + src_global
                                    - meta.bounds[owner, 0]).astype(E_DTYPE)
                # dst of edge e = vertex whose CSR range contains e
                edge_dst[i, :ne] = np.repeat(
                    np.arange(n, dtype=np.int64), deg).astype(V_DTYPE)
        # pad edges (and whole rows of empty parts): source = this shard's
        # first pad row (zero features), dst = last pad row, keeping
        # edge_dst ascending — identical rules to partition_graph
        edge_src[i, ne:] = p * S + n
        edge_dst[i, ne:] = S - 1
    return LocalShards(part_ids=tuple(part_ids), edge_src=edge_src,
                       edge_dst=edge_dst, in_degree=in_degree,
                       node_mask=node_mask)


def load_local_degrees(path: str, meta: PartitionMeta,
                       part_ids: Sequence[int]) -> np.ndarray:
    """[L, S] in-degrees for this process's parts (1.0 on pad rows) —
    the slice of Partition.in_degree edge-sharded -perhost needs without
    paying load_local_shards' cols reads (edge mode loads edges by BLOCK,
    not by part)."""
    L, S = len(part_ids), meta.shard_nodes
    in_degree = np.ones((L, S), dtype=np.float32)
    for i, p in enumerate(part_ids):
        lo, hi = meta.bounds[p]
        n = int(meta.num_valid[p])
        if n > 0:
            e0 = int(meta.edge_starts[p])
            ends = read_rows_slice(path, lo, hi + 1).astype(np.int64)
            in_degree[i, :n] = np.diff(
                np.concatenate([[e0], ends])).astype(np.float32)
    return in_degree


def _bisect_rows(path: str, target: int, num_nodes: int) -> int:
    """Smallest vertex v whose inclusive end offset raw_rows[v] > target —
    i.e. the vertex whose CSR range contains edge index ``target``.
    O(log N) 8-byte file reads; no O(N) array is ever resident (the point
    of per-host loading)."""
    lo, hi = 0, num_nodes          # invariant: answer in [lo, hi]
    while lo < hi:
        mid = (lo + hi) // 2
        if int(read_rows_slice(path, mid, mid + 1)[0]) > target:
            hi = mid
        else:
            lo = mid + 1
    return lo


def load_edge_blocks(path: str, meta: PartitionMeta,
                     block_ids: Sequence[int]):
    """This process's blocks of the exactly-edge-balanced edge cut —
    byte-identical to ``edge_block_arrays(g, meta)[block_ids]`` (the
    single-host builder; tests pin the equality) but reading ONLY the
    blocks' `.lux` byte ranges: the dst-sorted edge list IS the on-disk
    cols section, so block b is cols [b*Eb, (b+1)*Eb) plus the covering
    slice of row offsets (located by binary search over the offset
    section).  Pass the ``TLUX_SUFFIX`` file to get the transposed
    (src-sorted) blocks the backward plans need — with the SAME ``meta``
    (the vertex partition lives on the original orientation).

    Returns (gather [L, Eb], scatter [L, Eb]) padded-global int64."""
    from roc_tpu.graph.partition import _EDGE_ALIGN, _round_up
    P, S = meta.num_parts, meta.shard_nodes
    E = meta.num_edges
    num_nodes, num_edges_f = read_header(path)
    if num_nodes != meta.num_nodes or num_edges_f != E:
        raise ValueError(
            f"{path}: header ({num_nodes}, {num_edges_f}) != meta "
            f"({meta.num_nodes}, {E}) — wrong/mismatched transpose "
            f"sidecar?")
    Eb = _round_up(-(-E // P), _EDGE_ALIGN)
    L = len(block_ids)
    gather = np.zeros((L, Eb), dtype=np.int64)
    scatter = np.zeros((L, Eb), dtype=np.int64)
    to_padded = meta.to_padded

    for i, b in enumerate(block_ids):
        # a late block can start past E entirely (small E, many parts):
        # its row is ALL pad edges, like edge_block_arrays' tail padding
        e0 = b * Eb
        ne = max(min((b + 1) * Eb, E) - e0, 0)
        e1 = e0 + ne
        if ne > 0:
            src_global = read_cols_slice(path, num_nodes, e0,
                                         e1).astype(np.int64)
            # vertices whose ranges intersect [e0, e1): v0 owns edge e0
            v0 = _bisect_rows(path, e0, num_nodes)
            v1 = _bisect_rows(path, e1 - 1, num_nodes)
            ends = read_rows_slice(path, v0, v1 + 1).astype(np.int64)
            starts = np.concatenate(
                [read_rows_slice(path, v0 - 1, v0).astype(np.int64)
                 if v0 else np.zeros(1, np.int64), ends[:-1]])
            deg_in_blk = (np.minimum(ends, e1)
                          - np.maximum(starts, e0)).clip(min=0)
            dst_global = np.repeat(np.arange(v0, v1 + 1), deg_in_blk)
            gather[i, :ne] = to_padded(src_global)
            scatter[i, :ne] = to_padded(dst_global)
        # pad edges: identical recipe to edge_block_arrays — src = part 0's
        # first pad row (zero features), dst = the global last pad row
        gather[i, ne:] = int(meta.num_valid[0])
        scatter[i, ne:] = P * S - 1
    return gather, scatter


@dataclasses.dataclass(frozen=True)
class LocalHalo:
    """This process's rows of the halo maps (cf. parallel/halo.py HaloMaps:
    same K / same contents, restricted to part_ids)."""
    K: int
    part_ids: tuple
    send_idx: np.ndarray        # [L, P, K] int32
    edge_src_local: np.ndarray  # [L, E] int32 into [S local ++ P*K recv]
    halo_rows_total: int


def build_halo_local(meta: PartitionMeta, local: LocalShards,
                     allgather: AllGather = single_process_allgather
                     ) -> LocalHalo:
    """Halo maps for local parts via a host-side index exchange.

    Each process knows what its parts *receive* (their edges' remote
    sources); what a part must *send* lives in other processes' edges, so
    the per-(dest, owner) sorted-unique row lists are allgathered: first the
    O(P^2) size matrix (fixes the global pad width K), then the padded
    [L, P, K] need lists.  send_idx is the transpose of the assembled need
    tensor — exactly `build_halo_maps`'s send_lists, built without any
    process reading another's edges."""
    part_ids = local.part_ids
    L, P, S = len(part_ids), meta.num_parts, meta.shard_nodes
    need: List[dict] = []   # per local part: {owner q: sorted unique locals}
    sizes = np.zeros((P, P), np.int64)   # [dest p, owner q]
    for i, p in enumerate(part_ids):
        src = local.edge_src[i]
        owner = src // S
        remote = owner != p
        per_owner = {}
        for q in np.unique(owner[remote]):
            locals_q = np.unique(src[remote & (owner == q)] - q * S)
            per_owner[int(q)] = locals_q
            sizes[p, int(q)] = len(locals_q)
        need.append(per_owner)

    all_sizes = allgather(sizes).sum(axis=0)   # disjoint rows: sum = union
    K = max(int(all_sizes.max()), 1)
    halo_total = int(all_sizes.sum())

    # Pad value S-1 is a guaranteed pad row (partition.py keeps >=1 pad row
    # per shard) whose features are zero.
    my_need = np.full((L, P, K), S - 1, dtype=np.int32)
    for i in range(L):
        for q, rows in need[i].items():
            my_need[i, q, :len(rows)] = rows
    gathered = allgather(my_need)               # [nproc, L, P, K]
    assert gathered.shape[0] * L == P, (
        "uneven parts per process: per-host loading needs P divisible by "
        "process count")
    full_need = gathered.reshape(P, P, K)       # [dest p, owner q, K]
    # Process-major order must equal part order (asserted by caller wiring).
    send_full = full_need.transpose(1, 0, 2)    # [owner q, dest p, K]
    send_idx = np.ascontiguousarray(send_full[list(part_ids)])

    edge_src_local = np.empty((L, meta.shard_edges), dtype=np.int32)
    for i, p in enumerate(part_ids):
        src = local.edge_src[i]
        owner = (src // S).astype(np.int64)
        local_row = (src - owner * S).astype(np.int64)
        out = np.empty(meta.shard_edges, dtype=np.int64)
        own = owner == p
        out[own] = local_row[own]
        for q, rows in need[i].items():
            sel = owner == q
            pos = np.searchsorted(rows, local_row[sel])
            out[sel] = S + q * K + pos
        edge_src_local[i] = out
    return LocalHalo(K=K, part_ids=part_ids, send_idx=send_idx,
                     edge_src_local=edge_src_local,
                     halo_rows_total=halo_total)
