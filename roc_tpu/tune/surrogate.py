"""Trial pricing: the parameterized analytic model, the seeded CI
surrogate, and the device timing path.

Three layers, one formula:

* ``analytic_seconds`` mirrors binned's ``_binned_cost_model`` exactly —
  same terms, same exact ``_plan_steps`` schedule inputs — but takes the
  rate constants as PARAMETERS instead of reading module globals +
  ``measured_calibration()``.  The search screens and the surrogate both
  price through this closed world, so a measured table committed on some
  machine can never leak into the CI sweep's arithmetic (the
  byte-identical-tuned.json pin depends on that), and refit.py can solve
  the inverse problem against the same structure it was generated from.
  ``test_tune.py::test_analytic_matches_binned_cost_model`` pins the
  mirror against the production model so they cannot drift apart.

* ``surrogate_seconds`` is the CI pseudo-measurement: the analytic time
  times ``(1 + eps)`` with eps drawn from sha256 over (seed, salt,
  candidate label) — hashlib, NOT Python's ``hash()``, so the draw is
  independent of PYTHONHASHSEED and identical across processes.  The
  noise band (±2%) is wide enough that the halving stages genuinely
  reorder near-ties (the search can't sleepwalk through) and narrow
  enough that refit's least-squares recovers the generating constants
  inside the 5% acceptance band.

* ``measure_seconds`` is the hardware path: build the real plan
  (``tuned_ok=False`` — a previous sweep must never steer this sweep's
  measurements) and time the kernel through the obs tracer, the same
  clock discipline as tools/kernel_bench.py.  It REFUSES to run under
  interpret — the same contract as ``measured_calibration``: CPU harness
  timings are not rates and must never be recorded as such.
"""

from __future__ import annotations

import hashlib

import numpy as np

from roc_tpu.ops.pallas import binned as B
from roc_tpu.ops.pallas.binned import (Geometry, _CHUNK_OVERHEAD_S,
                                       _MM_CHUNK_S, _MODEL_H,
                                       _MXU_EFF_FLOPS, _SLOT_DMA_S,
                                       staging_itemsize)

#: The generating constants, by refit-able name.  These are the exact
#: values the CI surrogate manufactures its timings from, so the refit
#: acceptance test closes the loop: sweep -> records -> refit -> these.
CONSTANTS = {"chunk_s": _CHUNK_OVERHEAD_S, "slot_dma_s": _SLOT_DMA_S,
             "mm_chunk_s": _MM_CHUNK_S}

#: Surrogate noise half-width (fractional).
NOISE = 0.02


def analytic_seconds(padded_rows: int, geom: Geometry, steps1: int,
                     steps2: int, H: int = _MODEL_H,
                     chunk_s: float = _CHUNK_OVERHEAD_S,
                     slot_dma_s: float = _SLOT_DMA_S) -> float:
    """One aggregation pass at this geometry — ``_binned_cost_model``
    with the rates as explicit parameters (see module docstring)."""
    rows1 = steps1 * geom.ch
    rows2 = steps2 * geom.ch2
    mac1 = rows1 * geom.sb * H * 2 / _MXU_EFF_FLOPS
    mac2 = rows2 * geom.rb * H * 2 / _MXU_EFF_FLOPS
    ov1 = steps1 * chunk_s
    ov2 = steps2 * chunk_s
    dma1 = dma_units(padded_rows, geom) * slot_dma_s
    return max(mac1, ov1) + dma1 + max(mac2, ov2)


def dma_units(padded_rows: int, geom: Geometry) -> float:
    """The staging-DMA regressor: how many slot-DMA-equivalents phase 1
    issues.  Factored out of analytic_seconds because refit solves the
    rate per THIS unit — non-flat schedules issue one DMA per slot, flat
    schedules one size-classed copy per ~4 units scaled by the staging
    itemsize (the flat staging-DMA term the ISSUE names)."""
    if geom.flat:
        return (padded_rows / (geom.unit_rows * 4)
                * (staging_itemsize(geom, False) / 2))
    return padded_rows / geom.slot


def matmul_seconds(num_edges: int, num_rows: int,
                   mm_chunk_s: float = _MM_CHUNK_S) -> float:
    """The one-hot matmul backend, parameterized like analytic_seconds."""
    return B._matmul_chunks(num_edges, num_rows) * mm_chunk_s


def knob_factors(cfg) -> tuple:
    """(overhead_factor, dma_factor) for a candidate's non-Geometry
    knobs.  These are PRIORS — modest, documented multipliers that let
    the screen rank knob variants at all; the device sweep is what turns
    them into measurements (hw_revalidate step 3h), and refit treats
    knob-default trials as the calibration set so the priors never
    contaminate the recovered constants.

      dma_cls (32, 8, 1): doubled size classes halve the descriptor
        count on dense runs but round thin runs up harder — net prior
        -4% on the staging-DMA term.
      depth 3: a third pipeline buffer hides more of the DMA launch
        window behind compute — prior -2% on per-step overhead, paid in
        VMEM (lattice.py admissibility already charges the buffer).
      dimension_semantics "parallel": neutral (1.0) — both phases carry
        cross-step staging dependences, so until a device run proves the
        revolving-window lowering legal AND faster it cannot win a tie.
      ghg (GAT head-stacking groups, round 19): forcing MORE groups than
        the auto divisor multiplies the fused-attention pass count, so a
        modest per-group overhead prior (+3% per forced group beyond the
        first) lets the screen prefer auto/single unless a device trial
        shows the split's smaller VMEM window wins.
    """
    ov, dma = 1.0, 1.0
    if cfg.geom.flat and tuple(cfg.dma_cls) != B._DMA_CLS:
        dma *= 0.96
    if cfg.depth == 3:
        ov *= 0.98
    if getattr(cfg, "ghg", 0) > 1:
        ov *= 1.0 + 0.03 * (cfg.ghg - 1)
    return ov, dma


def modeled_seconds(cfg, stats, num_rows: int, table_rows: int,
                    num_edges: int, fuse_linear: bool = False,
                    chunk_s: float = _CHUNK_OVERHEAD_S,
                    slot_dma_s: float = _SLOT_DMA_S,
                    sched=None) -> tuple:
    """Candidate price at exact schedule counts: (seconds, sched) where
    sched = (padded, s1, s2) feeds the trial records refit solves from.
    Mirrors choose_geometry's pricing structure: a fused (mega) candidate
    scales to its real-chunks-only step count; under ``fuse_linear`` a
    non-mega candidate pays the eliminated intermediate's HBM round trip
    plus the separate linear pass's launch windows.  ``sched`` short-
    circuits the O(cells) _plan_steps when the caller already derived it
    for this geometry (knob variants share schedules)."""
    cblk, cbin, cnt = stats
    g = cfg.geom
    padded, s1, s2 = sched if sched is not None else B._plan_steps(
        cblk, cbin, cnt, g, num_rows, table_rows, num_edges)
    ovf, dmaf = knob_factors(cfg)
    mac_ov1 = max(s1 * g.ch * g.sb * _MODEL_H * 2 / _MXU_EFF_FLOPS,
                  s1 * chunk_s * ovf)
    mac_ov2 = max(s2 * g.ch2 * g.rb * _MODEL_H * 2 / _MXU_EFF_FLOPS,
                  s2 * chunk_s * ovf)
    t = mac_ov1 + dma_units(padded, g) * slot_dma_s * dmaf + mac_ov2
    if cfg.mega:
        fs = B._fused_sched_stats(cblk, cbin, cnt, g, num_rows,
                                  table_rows, num_edges)
        if fs is None:
            return float("inf"), (padded, s1, s2)
        t *= fs[0] / max(s1 + s2, 1)
        if cfg.fdepth != 1:
            # cross-layer region (round 16): the inter-layer [rows, H]
            # boundary write + next layer's read never reach HBM for
            # shard-local rows — credit one amortized boundary per fused
            # layer.  Documented prior; device trials refit it.
            t = max(t - 2 * num_rows * _MODEL_H * 4 / B._HBM_BW,
                    t * 0.5)
    elif fuse_linear:
        t += (2 * num_rows * _MODEL_H * 4 / B._HBM_BW
              + -(-num_rows // 512) * chunk_s)
    return t, (padded, s1, s2)


def noise_eps(seed: int, salt: str, label: str,
              width: float = NOISE) -> float:
    """Deterministic noise draw in [-width, +width]: sha256 over the
    (seed, salt, candidate) triple — PYTHONHASHSEED-independent, stable
    across platforms and processes, the root of the byte-identical
    tuned.json pin."""
    h = hashlib.sha256(f"{seed}|{salt}|{label}".encode()).digest()
    u = int.from_bytes(h[:8], "big") / float(1 << 64)
    return (2.0 * u - 1.0) * width


def surrogate_seconds(modeled: float, seed: int, salt: str,
                      label: str) -> float:
    """The CI pseudo-measurement for one trial."""
    return modeled * (1.0 + noise_eps(seed, salt, label))


def measure_seconds(cfg, edge_src, edge_dst, num_rows: int,
                    table_rows: int, H: int = 128, reps: int = 3,
                    precision: str = "fast") -> float:
    """Hardware trial: build the candidate's real plan (tuned_ok=False)
    and time the two-pass (or flat/fused) aggregation on device, median
    of ``reps``, through the obs tracer's clock.  Raises on interpret
    backends — the measured_calibration refusal contract."""
    import jax
    import jax.numpy as jnp
    from roc_tpu import obs
    if jax.default_backend() not in ("tpu", "axon"):
        raise SystemExit(
            "tune.measure_seconds: refusing to record interpret/CPU "
            "timings as kernel rates (measured_calibration contract); "
            "run the surrogate sweep instead")
    plan = B.build_binned_plan(np.asarray(edge_src), np.asarray(edge_dst),
                               num_rows, table_rows, geom=cfg.geom,
                               tuned_ok=False)
    x = jnp.asarray(
        np.random.default_rng(0).standard_normal(H * table_rows)
        .reshape(table_rows, H).astype(np.float32))
    fn = jax.jit(lambda v: B.run_binned(v, plan, precision=precision))
    jax.block_until_ready(fn(x))     # compile outside the timed region
    times = []
    for _ in range(max(reps, 1)):
        with obs.span("tune_trial", label=cfg.label) as sp:
            jax.block_until_ready(fn(x))
        times.append(sp.dur_s)
    times.sort()
    return times[len(times) // 2]
