"""Edge-balanced contiguous vertex partitioning + TPU shard layout.

The reference partitions vertices into contiguous ranges whose in-edge counts
are balanced: it walks vertices accumulating in-degrees and cuts a new part
whenever the running count exceeds ``edge_cap = ceil(numEdges/numParts)``
(gnn.cc:806-829).  Work in the aggregation kernel is proportional to edges, so
this balances the hot loop.  We reproduce that algorithm bit-for-bit (it is
also what decides which `.lux` byte ranges each host reads at pod scale), then
go one step further than the reference needs to: XLA wants *static, equal*
shapes per device, so each part is padded to a common shard size S (nodes) and
E (edges), with padding constructed so it is algebraically inert:

  * pad nodes carry zero features; every live op maps zero rows to zero rows
    (linear has no bias — linear_kernel.cu:76-80 is a pure GEMM — and
    norm/relu/dropout/aggregate are zero-preserving), so pad rows stay zero
    through the whole network;
  * pad edges point source-at-a-pad-node (contributes +0 to any sum) and
    dst-at-the-last-pad-row (keeps edge_dst ascending for sorted segment
    sums; the accumulated zeros land on a row that unpad drops);
  * pad nodes get in-degree 1 (never divided-by-zero) and mask NONE (never
    counted in loss/metrics — the same mechanism the reference uses for
    unlabeled vertices, softmax_kernel.cu:19-33).

The replacement mapping: Legion's DomainColoring over vertex/edge index spaces
(gnn.cc:836-870) becomes this explicit permutation ``global vertex v ↦
(part p, local row v - lo_p)`` plus padded dense arrays that a
`jax.sharding.NamedSharding` splits over the mesh's 'parts' axis.
"""

from __future__ import annotations

import dataclasses
from typing import List, Tuple

import numpy as np

from roc_tpu.graph.csr import Csr, E_DTYPE, V_DTYPE

# TPU fp32 tiles are (8, 128): keep the node (sublane) dimension a multiple
# of 8 so per-shard feature blocks tile cleanly.
_NODE_ALIGN = 8
_EDGE_ALIGN = 8


def edge_balanced_bounds(g: Csr, num_parts: int) -> List[Tuple[int, int]]:
    """Greedy cut over a CSR graph (see :func:`bounds_from_row_ptr`)."""
    return bounds_from_row_ptr(g.row_ptr, num_parts)


def bounds_from_row_ptr(row_ptr: np.ndarray,
                        num_parts: int) -> List[Tuple[int, int]]:
    """The reference's greedy cut (gnn.cc:806-829): accumulate in-degrees,
    cut when the running count *exceeds* ceil(E/P).  Returns inclusive
    (lo, hi) vertex bounds per part.  Needs only the exclusive-prefix row
    pointer — the per-host loader calls this without ever reading edge
    columns.

    The reference simply asserts it got exactly P parts (gnn.cc:829); that
    can fail for skewed graphs (a huge-degree vertex early eats several
    caps).  We keep the identical cut rule but repair the result when it
    yields != P parts by splitting the largest parts / merging empties, so
    the partitioner totals P for any graph.
    """
    assert num_parts >= 1
    num_nodes = len(row_ptr) - 1
    num_edges = int(row_ptr[-1])
    if num_nodes == 0:
        return [(0, -1)] * num_parts
    from roc_tpu import native
    if native.available():
        n, nb = native.partition(np.ascontiguousarray(row_ptr[1:], np.uint64),
                                 num_edges, num_parts)
        if n > num_parts:
            # C side dropped the overflow parts; fall back to the Python
            # scan whose full result the repair loops below can merge.
            bounds = _python_bounds(row_ptr, num_parts)
        else:
            bounds = [tuple(b) for b in nb[:n]]
    else:
        bounds = _python_bounds(row_ptr, num_parts)
    # Repair (reference would assert instead):
    while len(bounds) > num_parts:  # merge the two lightest neighbors
        w = [int(row_ptr[hi + 1] - row_ptr[lo]) for lo, hi in bounds]
        i = int(np.argmin([w[j] + w[j + 1] for j in range(len(bounds) - 1)]))
        bounds[i] = (bounds[i][0], bounds[i + 1][1])
        del bounds[i + 1]
    while len(bounds) < num_parts:  # split the part with the most vertices
        sizes = [hi - lo + 1 for lo, hi in bounds]
        i = int(np.argmax(sizes))
        lo, hi = bounds[i]
        if hi <= lo:  # cannot split single-vertex parts further: emit empties
            bounds.append((num_nodes, num_nodes - 1))
            continue
        mid = (lo + hi) // 2
        bounds[i] = (lo, mid)
        bounds.insert(i + 1, (mid + 1, hi))
    return bounds


def _python_bounds(row_ptr: np.ndarray,
                   num_parts: int) -> List[Tuple[int, int]]:
    """Pure-NumPy greedy cut (oracle for the native implementation)."""
    deg = np.diff(row_ptr)
    num_nodes = len(row_ptr) - 1
    num_edges = int(row_ptr[-1])
    edge_cap = (num_edges + num_parts - 1) // num_parts
    bounds: List[Tuple[int, int]] = []
    left, cnt = 0, 0
    for v in range(num_nodes):
        cnt += int(deg[v])
        if cnt > edge_cap:
            bounds.append((left, v))
            cnt = 0
            left = v + 1
    if cnt > 0 or left < num_nodes:
        bounds.append((left, num_nodes - 1))
    return bounds


def _round_up(x: int, align: int) -> int:
    return (x + align - 1) // align * align


@dataclasses.dataclass(frozen=True)
class PartitionMeta:
    """Partition geometry: everything global about the shard layout that is
    O(P) to store — vertex bounds, padded shapes, live counts — plus the
    global↔padded vertex-id mapping.  The per-host loader
    (roc_tpu/graph/shard_load.py) broadcasts exactly this and builds edge
    arrays only for its local parts; :class:`Partition` extends it with the
    full per-part arrays for the single-host path.

      bounds          [P, 2]  inclusive global vertex range per part
      num_valid       [P]     live nodes per shard
      num_edges_valid [P]     live edges per shard
      edge_starts     [P]     global edge offset of each part's first edge
    """

    num_parts: int
    shard_nodes: int
    shard_edges: int
    num_nodes: int
    num_edges: int
    bounds: np.ndarray
    num_valid: np.ndarray
    num_edges_valid: np.ndarray
    edge_starts: np.ndarray

    # -- vertex id mapping ------------------------------------------------
    def to_padded(self, v: np.ndarray) -> np.ndarray:
        """Map global vertex ids to padded ids p*S + (v - lo_p)."""
        part = np.searchsorted(self.bounds[:, 1], v, side="left")
        return (part * self.shard_nodes + v - self.bounds[part, 0]).astype(E_DTYPE)

    def pad_nodes(self, x: np.ndarray, fill=0) -> np.ndarray:
        """[N, ...] node array -> [P*S, ...] padded (shard-major) array."""
        return np.concatenate(
            [self.pad_part(x, p, fill) for p in range(self.num_parts)],
            axis=0)

    def pad_part(self, x: np.ndarray, p: int, fill=0,
                 dtype=None) -> np.ndarray:
        """One part's padded [S, ...] block, touching only rows
        [lo_p, hi_p] of ``x`` — with a memmapped ``x`` this reads just this
        part's bytes from disk (sharded host loading; the analog of the
        reference's per-partition `.lux` seeking, load_task.cu:231-243)."""
        lo, hi = self.bounds[p]
        n = max(int(hi - lo + 1), 0)
        out = np.full((self.shard_nodes,) + x.shape[1:], fill,
                      dtype=dtype or x.dtype)
        if n > 0:
            out[:n] = x[lo: hi + 1]
        return out

    def unpad_nodes(self, x: np.ndarray) -> np.ndarray:
        """Inverse of pad_nodes (drops pad rows)."""
        parts = []
        for p in range(self.num_parts):
            n = int(self.num_valid[p])
            parts.append(x[p * self.shard_nodes: p * self.shard_nodes + n])
        return np.concatenate(parts, axis=0)


@dataclasses.dataclass(frozen=True)
class Partition(PartitionMeta):
    """Device-ready padded shard layout for a partitioned graph: the meta
    geometry plus full per-part arrays.

    Array shapes (P parts, S padded nodes/shard, E padded edges/shard):
      edge_src        [P, E]  per-edge source as *padded global* id in [0, P*S)
      edge_dst        [P, E]  per-edge dest as *local* row in [0, S), ascending
      in_degree       [P, S]  float32 in-degrees, 1.0 on pad rows
      node_mask       [P, S]  bool, True on live rows
    """

    edge_src: np.ndarray
    edge_dst: np.ndarray
    in_degree: np.ndarray
    node_mask: np.ndarray

    @property
    def meta(self) -> PartitionMeta:
        return PartitionMeta(
            num_parts=self.num_parts, shard_nodes=self.shard_nodes,
            shard_edges=self.shard_edges, num_nodes=self.num_nodes,
            num_edges=self.num_edges, bounds=self.bounds,
            num_valid=self.num_valid, num_edges_valid=self.num_edges_valid,
            edge_starts=self.edge_starts)


def validate_bounds(bounds: np.ndarray, num_nodes: int) -> None:
    """Check that inclusive (lo, hi) bounds contiguously cover [0, num_nodes).

    Empty parts are encoded hi < lo (the repair loops emit
    ``(num_nodes, num_nodes - 1)``); non-empty parts must tile the vertex
    range in ascending order with no gaps — the contract every consumer of
    ``PartitionMeta.bounds`` (to_padded's searchsorted, the per-host byte
    ranges, the balancer's proposals) relies on.
    """
    bounds = np.asarray(bounds, dtype=np.int64)
    nxt = 0
    for lo, hi in bounds:
        if hi < lo:  # empty part
            continue
        if lo != nxt:
            raise ValueError(
                f"bounds not contiguous: part starts at {lo}, expected {nxt}")
        nxt = int(hi) + 1
    if nxt != num_nodes:
        raise ValueError(
            f"bounds cover [0, {nxt}) but graph has {num_nodes} nodes")


def compute_meta(row_ptr: np.ndarray, num_parts: int,
                 bounds: np.ndarray | None = None,
                 shard_nodes: int | None = None,
                 shard_edges: int | None = None) -> PartitionMeta:
    """Partition geometry from the row pointer alone (no edge columns).

    ``bounds`` overrides the greedy cut with an externally proposed cut (the
    online balancer's path); ``shard_nodes``/``shard_edges`` force the padded
    shard shape so a reshard keeps the *same* static S/E — jit caches and the
    content-keyed plan cache then absorb the rebuild instead of recompiling
    for a new shape.  Forced shapes must still fit the cut.
    """
    if bounds is None:
        bounds = np.asarray(bounds_from_row_ptr(row_ptr, num_parts),
                            dtype=np.int64)
    else:
        bounds = np.asarray(bounds, dtype=np.int64)
        if bounds.shape != (num_parts, 2):
            raise ValueError(f"bounds shape {bounds.shape} != ({num_parts}, 2)")
        validate_bounds(bounds, len(row_ptr) - 1)
    num_valid = np.maximum(bounds[:, 1] - bounds[:, 0] + 1, 0)
    # Always leave >=1 pad row per shard so pad edges have a zero source row
    # to point at even in the fullest shard.
    need_nodes = _round_up(int(num_valid.max()) + 1, _NODE_ALIGN)
    if shard_nodes is None:
        shard_nodes = need_nodes
    elif shard_nodes < need_nodes:
        raise ValueError(
            f"shard_nodes={shard_nodes} cannot hold {int(num_valid.max())} "
            f"nodes + 1 pad row (need >= {need_nodes})")
    edge_lo = row_ptr[np.maximum(bounds[:, 0], 0)]
    edge_hi = row_ptr[bounds[:, 1] + 1]
    num_edges_valid = np.where(num_valid > 0, edge_hi - edge_lo, 0)
    need_edges = max(_round_up(int(num_edges_valid.max()), _EDGE_ALIGN),
                     _EDGE_ALIGN)
    if shard_edges is None:
        shard_edges = need_edges
    elif shard_edges < need_edges:
        raise ValueError(
            f"shard_edges={shard_edges} cannot hold "
            f"{int(num_edges_valid.max())} edges (need >= {need_edges})")
    return PartitionMeta(
        num_parts=num_parts, shard_nodes=shard_nodes,
        shard_edges=shard_edges, num_nodes=len(row_ptr) - 1,
        num_edges=int(row_ptr[-1]), bounds=bounds,
        num_valid=num_valid.astype(np.int64),
        num_edges_valid=np.asarray(num_edges_valid, np.int64),
        edge_starts=np.asarray(edge_lo, np.int64))


def edge_block_arrays(g: Csr, part: PartitionMeta):
    """Exactly-edge-balanced blocks for the edge-sharded aggregation mode
    (roc_tpu/parallel/spmd.py, `-edge-shard`).

    The vertex partitioner cannot split a vertex's in-edges, so a hub
    vertex overruns the edge cap and every other shard pays the padded-max
    tax (see SpmdTrainer._log_shard_stats).  Here the dst-sorted edge list
    is cut into P blocks of exactly ceil(E/P) edges — mid-vertex cuts
    allowed, padding tax ~0 regardless of skew.  Both endpoints are padded
    global ids; dst stays nondecreasing (padded ids are monotone in global
    vertex id), so each block's segment-sum is still a sorted reduction.

    Returns (edge_src [P, Eb], edge_dst [P, Eb]), both padded-global.
    """
    P, S = part.num_parts, part.shard_nodes
    Eb = _round_up(-(-g.num_edges // P), _EDGE_ALIGN)
    src = part.to_padded(g.col_idx)
    dst = part.to_padded(g.dst_idx)
    pad = P * Eb - g.num_edges
    # pad edges: src = a guaranteed zero-feature pad row (part 0's first pad
    # row), dst = the global last pad row (keeps dst ascending; its sums are
    # dropped with the padding)
    src = np.concatenate([src, np.full(pad, int(part.num_valid[0]), E_DTYPE)])
    dst = np.concatenate([dst, np.full(pad, P * S - 1, E_DTYPE)])
    return src.reshape(P, Eb), dst.reshape(P, Eb)


def edge_block_arrays_t(g: Csr, part: PartitionMeta):
    """Transposed edge blocks for the backward of edge-sharded aggregation:
    the gradient flow dx[u] = Σ_{e: src(e)=u} g[dst(e)] is itself an edge
    aggregation with roles swapped, so the same exactly-equal cuts apply to
    the *src*-sorted edge list.  Sorting by src makes each block's scatter
    targets a contiguous padded-id range — the property the windowed chunk
    plans need (mirrors the reference re-launching its forward kernel with
    roles swapped, scattergather_kernel.cu:160-170, at block granularity).

    Returns (gather [P, Eb], scatter [P, Eb]): gather = padded dst ids
    (rows of the all-gathered gradient), scatter = padded src ids,
    nondecreasing within each block.  Implemented as edge_block_arrays of
    the transposed CSR so the pad-edge recipe lives in exactly one place
    (Csr.transpose's stable sort makes this element-identical to sorting
    the in-edge list by src)."""
    return edge_block_arrays(g.transpose(), part)


def partition_graph(g: Csr, num_parts: int,
                    bounds: np.ndarray | None = None,
                    shard_nodes: int | None = None,
                    shard_edges: int | None = None) -> Partition:
    """Partition + pad a CSR into the static shard layout described above.

    The optional overrides (see :func:`compute_meta`) are the epoch-boundary
    resharding path: a new cut under the old padded S/E.
    """
    g.validate()
    meta = compute_meta(g.row_ptr, num_parts, bounds=bounds,
                        shard_nodes=shard_nodes, shard_edges=shard_edges)
    bounds = meta.bounds
    num_valid = meta.num_valid
    num_edges_valid = meta.num_edges_valid
    P, S, E = num_parts, meta.shard_nodes, meta.shard_edges
    # Precompute the global->padded permutation for edge source remapping.
    part_of = np.zeros(g.num_nodes, dtype=np.int64)
    local_of = np.zeros(g.num_nodes, dtype=np.int64)
    for p in range(P):
        lo, hi = bounds[p]
        if hi >= lo:
            part_of[lo: hi + 1] = p
            local_of[lo: hi + 1] = np.arange(hi - lo + 1)
    padded_id = part_of * S + local_of

    edge_src = np.zeros((P, E), dtype=E_DTYPE)
    edge_dst = np.zeros((P, E), dtype=V_DTYPE)
    dst_all = g.dst_idx
    for p in range(P):
        lo, hi = bounds[p]
        ne = int(num_edges_valid[p])
        if ne == 0:
            # whole row is padding: src = this shard's first pad row
            edge_src[p, :] = p * S + int(num_valid[p])
            edge_dst[p, :] = S - 1
            continue
        e0 = int(g.row_ptr[lo])
        edge_src[p, :ne] = padded_id[g.col_idx[e0: e0 + ne]]
        edge_dst[p, :ne] = (dst_all[e0: e0 + ne] - lo).astype(V_DTYPE)
        # pad edges: source = this shard's first pad row (zero features),
        # dst = last pad row (S-1 is always padding since num_valid < S) so
        # edge_dst stays ascending — segment_sum is told indices_are_sorted
        edge_src[p, ne:] = p * S + int(num_valid[p])
        edge_dst[p, ne:] = S - 1

    deg = np.diff(g.row_ptr).astype(np.float32)
    in_degree = np.ones((P, S), dtype=np.float32)
    node_mask = np.zeros((P, S), dtype=bool)
    for p in range(P):
        lo, hi = bounds[p]
        n = int(num_valid[p])
        if n > 0:
            in_degree[p, :n] = deg[lo: hi + 1]
            node_mask[p, :n] = True

    return Partition(
        **{f.name: getattr(meta, f.name)
           for f in dataclasses.fields(PartitionMeta)},
        edge_src=edge_src, edge_dst=edge_dst,
        in_degree=in_degree, node_mask=node_mask,
    )
