"""tuned.json — the content-keyed tuned-geometry tier.

One JSON document maps *graph content* (shape + an edge-list digest, the
same key discipline as binned's ``_plan_cache_path``) to the sweep's
winning kernel config per *variant* (storage dtype x fuse_linear).
``choose_geometry`` consults this tier BEFORE its analytic model, and
``build_binned_plan`` cross-checks explicitly-passed geometries against
it so a stale plan-cache hit can never silently pin an untuned geometry
(warn-once + prefer the tuned config).

Location: alongside the plan cache (``<plan cache dir>/tuned.json``) so a
plan-cache hit is also a tuned-config hit; ``ROC_TUNED_PATH`` overrides,
``ROC_NO_TUNED=1`` disables the tier entirely (the analytic model stays
in charge — the tuner's own trials run this way so a previous sweep can
never steer the next one's measurements).

Schema (validate_store is the single source of truth; the preflight gate
runs it over the selftest sweep's output)::

  {"version": 1,
   "interpret": <bool — true = CI surrogate sweep, not device times>,
   "seed": <int — the surrogate seed, for reproduction>,
   "entries": {
     "<content key: edges=..|rows=..|sha=..|table_rows=..>": {
       "<variant: fp32|bf16[+fuse]>": {
         "geom":      [<the full Geometry tuple, len-validated>],
         "knobs":     {"dma_cls": [...], "dimension_semantics": str,
                       "depth": int, "mega": 0|1,
                       "fdepth": 1|2|0 (cross-layer region cap,
                                        absent = 1 in older stores),
                       "ghg": int (GAT head-stacking groups, 0 = auto,
                                   absent = 0 in older stores)},
         "modeled_s": <stage-0 analytic seconds>,
         "trial_s":   <winning confirmation-trial seconds>,
         "source":    "surrogate" | "device"}}}}

Unlike the ``measured`` rate table (binned.measured_calibration), tuned
entries apply on ANY backend: they are a policy choice (which schedule to
build), not a rate claim, and the CI tests exercise the tier under
interpret.  The rates themselves keep the refusal contract — see
refit.py.  Entry geometries are still re-validated at lookup time
(Geometry.check() + the VMEM budget) so a hand-edited or stale file
degrades to the analytic model instead of crashing a run.
"""

from __future__ import annotations

import hashlib
import json
import os
import warnings

import numpy as np

from roc_tpu.obs.ledger import content_key
from roc_tpu.ops.pallas.binned import (Geometry, _plan_cache_dir,
                                       _vmem_bytes, _VMEM_BUDGET)

VERSION = 1
_GEOM_FIELDS = len(Geometry._fields)
_VARIANTS = ("fp32", "bf16", "fp32+fuse", "bf16+fuse")

# Parsed-store cache: path -> (mtime_ns, size, doc-or-None).  choose_geometry
# consults the tier on every auto pick, so the file parses once per change,
# not once per plan.  clear_cache() for tests that rewrite the file in place.
_CACHE: dict = {}
# Warn-once registry for stale-geometry / invalid-entry findings, keyed by
# (path, content key): one warning per graph per process, not per rebuild.
_WARNED: set = set()


def tuned_store_path() -> str:
    """Resolved tuned.json path; '' disables the tier.  Rides the plan
    cache's location (and its ROC_PLAN_CACHE=0 opt-out) unless
    ROC_TUNED_PATH points elsewhere; ROC_NO_TUNED=1 kills it outright."""
    if os.environ.get("ROC_NO_TUNED"):
        return ""
    p = os.environ.get("ROC_TUNED_PATH")
    if p:
        return p
    base = _plan_cache_dir()
    return os.path.join(base, "tuned.json") if base else ""


def graph_key(edge_src, edge_dst, num_rows: int, table_rows: int) -> str:
    """Content key for one graph direction: shape plus a sha1 digest over
    the int64 edge bytes — the same content discipline as the plan cache,
    so the tuned entry and the cached plan invalidate together when the
    edges change.  O(E), only paid when a store exists (lookup
    short-circuits on the parsed doc first)."""
    h = hashlib.sha1()
    h.update(np.ascontiguousarray(edge_src, np.int64).tobytes())
    h.update(np.ascontiguousarray(edge_dst, np.int64).tobytes())
    return content_key(rows=int(num_rows), table_rows=int(table_rows),
                       edges=int(len(edge_src)), sha=h.hexdigest()[:16])


def variant_key(storage_dtype: str = "fp32",
                fuse_linear: bool = False) -> str:
    """The per-entry variant axis: the two inputs that change which
    candidates choose_geometry may even consider (bf16 flat units; the
    megakernel's round-trip credit)."""
    return storage_dtype + ("+fuse" if fuse_linear else "")


def validate_store(doc) -> list:
    """Schema problems in a tuned.json document (empty list = valid).
    The preflight selftest gates on this, so a field rename in the sweep
    shows up in CI, not as a silently-ignored tier on the chip."""
    problems = []
    if not isinstance(doc, dict):
        return ["document is not an object"]
    if doc.get("version") != VERSION:
        problems.append(f"version {doc.get('version')!r} != {VERSION}")
    if not isinstance(doc.get("interpret"), bool):
        problems.append("missing/non-bool 'interpret'")
    entries = doc.get("entries")
    if not isinstance(entries, dict):
        return problems + ["missing/non-object 'entries'"]
    for gkey, variants in entries.items():
        if not isinstance(variants, dict):
            problems.append(f"{gkey}: variants not an object")
            continue
        for vkey, e in variants.items():
            where = f"{gkey}[{vkey}]"
            if vkey not in _VARIANTS:
                problems.append(f"{where}: unknown variant")
            if not isinstance(e, dict):
                problems.append(f"{where}: entry not an object")
                continue
            g = e.get("geom")
            if (not isinstance(g, list) or len(g) != _GEOM_FIELDS
                    or not all(isinstance(v, int) for v in g)):
                problems.append(
                    f"{where}: geom must be {_GEOM_FIELDS} ints")
            else:
                try:
                    Geometry(*g).check()
                except AssertionError as err:
                    problems.append(f"{where}: invalid geometry ({err})")
            for f in ("modeled_s", "trial_s"):
                if not isinstance(e.get(f), (int, float)) \
                        or isinstance(e.get(f), bool):
                    problems.append(f"{where}: non-numeric {f}")
            if e.get("source") not in ("surrogate", "device"):
                problems.append(f"{where}: bad source")
            if not isinstance(e.get("knobs"), dict):
                problems.append(f"{where}: missing knobs")
    return problems


def load_store(path: str = ""):
    """Parsed + validated tuned.json, or None (no file / invalid / tier
    disabled).  Cached per (path, mtime, size); an invalid document warns
    once and reads as absent — degrade to the analytic model, never
    crash a training run over a tuning artifact."""
    path = path or tuned_store_path()
    if not path:
        return None
    try:
        st = os.stat(path)
    except OSError:
        return None
    ck = (st.st_mtime_ns, st.st_size)
    hit = _CACHE.get(path)
    if hit is not None and hit[0] == ck:
        return hit[1]
    doc = None
    try:
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
    except (OSError, ValueError):
        doc = None
    if doc is not None:
        problems = validate_store(doc)
        if problems:
            _warn_once((path, "schema"),
                       f"tuned store {path}: invalid schema "
                       f"({problems[0]}); ignoring the tuned tier")
            doc = None
    _CACHE[path] = (ck, doc)
    return doc


def save_store(path: str, doc: dict) -> None:
    """Deterministic, durable atomic write: sorted keys + fixed
    separators so the same sweep produces byte-identical files (the CI
    determinism pin), tmp + fsync + rename (fault.fsync_replace) so
    readers never see a torn document and a kill never leaves an
    unflushed one."""
    from roc_tpu.fault import fsync_replace
    problems = validate_store(doc)
    if problems:
        raise ValueError(f"refusing to write invalid tuned store: "
                         f"{problems[:3]}")
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")
    fsync_replace(tmp, path)
    _CACHE.pop(path, None)


def merge_entries(path: str, entries: dict, interpret: bool,
                  seed: int) -> dict:
    """Fold a sweep's winners into the store at ``path`` (creating it if
    absent) and write it back.  Per (graph, variant) the newest sweep
    wins; other graphs' entries survive — the store accumulates tuned
    shapes the way the plan cache accumulates plans."""
    doc = load_store(path) or {"version": VERSION, "interpret": interpret,
                               "seed": seed, "entries": {}}
    doc["interpret"] = bool(interpret)
    doc["seed"] = int(seed)
    for gkey, variants in entries.items():
        doc["entries"].setdefault(gkey, {}).update(variants)
    save_store(path, doc)
    return doc


def _warn_once(key, msg: str) -> None:
    if key in _WARNED:
        return
    _WARNED.add(key)
    warnings.warn(msg, stacklevel=3)


def _entry_geom(path: str, gkey: str, vkey: str, e: dict):
    """Entry -> validated Geometry, or None (warn-once) when the stored
    tuple no longer passes the live invariants/VMEM budget — e.g. a file
    from a future field layout or a hand-edit."""
    try:
        g = Geometry(*e["geom"]).check()
    except (AssertionError, TypeError):
        _warn_once((path, gkey, vkey),
                   f"tuned entry {vkey} for {gkey.split('|')[-1]} has an "
                   f"invalid geometry; falling back to the analytic model")
        return None
    if _vmem_bytes(g) > _VMEM_BUDGET:
        _warn_once((path, gkey, vkey),
                   f"tuned entry {vkey} geometry {tuple(g)} exceeds the "
                   f"VMEM budget; falling back to the analytic model")
        return None
    return g


def lookup(edge_src, edge_dst, num_rows: int, table_rows: int,
           storage_dtype: str = "fp32", fuse_linear: bool = False,
           path: str = ""):
    """(Geometry, entry) for this graph + variant, or (None, None).
    EXACT variant match only — a fuse_linear pick never inherits the
    unfused winner (their round-trip economics differ, which is the whole
    point of the variant axis); misses fall back to the analytic model."""
    doc = load_store(path)
    if doc is None:
        return None, None
    variants = doc["entries"].get(
        graph_key(edge_src, edge_dst, num_rows, table_rows))
    if not variants:
        return None, None
    vkey = variant_key(storage_dtype, fuse_linear)
    e = variants.get(vkey)
    if e is None:
        return None, None
    g = _entry_geom(path or tuned_store_path(),
                    graph_key(edge_src, edge_dst, num_rows, table_rows),
                    vkey, e)
    return (g, e) if g is not None else (None, None)


def stale_plan_geom(edge_src, edge_dst, num_rows: int, table_rows: int,
                    geom: Geometry, path: str = ""):
    """Plan-cache hygiene check (build_binned_plan): the tuned geometry
    this explicitly-requested ``geom`` should yield to, or None when the
    request agrees with the tier (or no tier entry exists).

    Variant selection without the caller's storage declaration: a
    single-variant entry is unambiguous; otherwise the geometry's own
    staging unit implies the storage family (unit=16 is bf16-only by the
    Geometry invariant) and the unfused variant is preferred — the fused
    variants only differ through choose_geometry, which already consults
    the tier directly.  Warn-once per graph when a switch happens."""
    doc = load_store(path)
    if doc is None:
        return None
    gkey = graph_key(edge_src, edge_dst, num_rows, table_rows)
    variants = doc["entries"].get(gkey)
    if not variants:
        return None
    storage = "bf16" if geom.unit == 16 else "fp32"
    order = [storage, storage + "+fuse"]
    if len(variants) == 1:
        order = list(variants)
    for vkey in order:
        e = variants.get(vkey)
        if e is None:
            continue
        tg = _entry_geom(path or tuned_store_path(), gkey, vkey, e)
        if tg is None:
            return None
        if tuple(tg) == tuple(geom):
            return None
        _warn_once((path or tuned_store_path(), gkey, "stale"),
                   f"requested plan geometry {tuple(geom)} disagrees with "
                   f"the tuned winner {tuple(tg)} for this graph "
                   f"({vkey}); building the tuned geometry instead "
                   f"(pass tuned_ok=False to force an A/B)")
        return tg
    return None


def clear_cache() -> None:
    """Drop the parsed-store cache and the warn-once registry (tests)."""
    _CACHE.clear()
    _WARNED.clear()
