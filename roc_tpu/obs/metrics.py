"""Metrics registry + exporters: one stream for train/balance telemetry.

Before this module the repo had two metric sinks with two shapes:
`TrainStats` (a dataclass bench.py flattens) and the balance JSONL trace
(`balance/telemetry.py`).  The registry unifies them by *wrapping* a
:class:`TelemetryBuffer` — every record goes through the same
``{"type": <kind>, **fields}`` envelope and the same best-effort JSONL
writer, so a `-obs` run's metrics stream and a `-balance-trace` stream are
one format (and, when both are on without an explicit balance path, one
file).  Exporters: the JSONL stream itself, an optional Prometheus
textfile (node_exporter textfile-collector format) of the latest scalar
per series, and the in-memory `records` tail that bench.py stamps into
artifacts.
"""

from __future__ import annotations

import os
from collections import deque
from typing import List, Optional, Tuple

from roc_tpu.balance.telemetry import TelemetryBuffer

_RECORD_TAIL = 4096  # in-memory records kept for bench/report consumers


class MetricsRegistry:
    """Named-record sink over the shared telemetry JSONL schema."""

    def __init__(self, telemetry: Optional[TelemetryBuffer] = None,
                 jsonl_path: str = ""):
        self.telemetry = telemetry if telemetry is not None \
            else TelemetryBuffer(trace_path=jsonl_path)
        # (kind, fields) tail + latest scalar per "<kind>_<field>" series
        self.records: deque = deque(maxlen=_RECORD_TAIL)
        self.latest: dict = {}
        # labeled gauges: (name, ((label, value), ...)) -> float.
        # Exporter-only state (the JSONL already carries the records they
        # are derived from).
        self.gauges: dict = {}

    def emit(self, kind: str, /, **fields):
        """One record: JSONL line (shared schema) + in-memory tail.
        ``kind`` is positional-only — watchdog alerts carry a "kind"
        FIELD of their own (slow-epoch/straggler)."""
        self.telemetry.record_event(kind, **fields)
        self.records.append((kind, dict(fields)))
        for k, v in fields.items():
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                continue
            self.latest[f"{kind}_{k}"] = float(v)
        # Calibration-ledger measurements additionally export as a
        # per-model labeled gauge: roc_calibration_ratio{model="..."}.
        if kind == "measurement" and "ratio" in fields and "model" in fields:
            self.set_gauge("calibration_ratio", fields["ratio"],
                           model=str(fields["model"]))

    def set_gauge(self, name: str, value, **labels) -> None:
        """Latest value of a labeled Prometheus gauge (write_prometheus
        renders it; non-numeric values are dropped, like ``latest``)."""
        if isinstance(value, bool) or not isinstance(value, (int, float)):
            return
        self.gauges[(str(name), tuple(sorted(
            (str(k), str(v)) for k, v in labels.items())))] = float(value)

    def of_kind(self, kind: str) -> List[dict]:
        return [f for k, f in self.records if k == kind]

    def series(self, kind: str, field: str) -> List[float]:
        """One field's trajectory across records of ``kind`` (bench.py's
        grad-norm trajectory comes from here)."""
        return [float(f[field]) for k, f in self.records
                if k == kind and field in f]

    def write_prometheus(self, path: str) -> bool:
        """Latest scalar per series (plus labeled gauges) as a Prometheus
        textfile (best-effort, like every exporter here: observability
        must never kill a run).  Non-finite values are skipped — a NaN
        gauge poisons rate()/avg() queries downstream and carries no
        information a missing series doesn't."""
        import math
        try:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            lines = []
            for name in sorted(self.latest):
                v = self.latest[name]
                if not math.isfinite(v):
                    continue
                lines.append(f"{_metric_name(name)} {v:.10g}")
            for (name, labels) in sorted(self.gauges):
                v = self.gauges[(name, labels)]
                if not math.isfinite(v):
                    continue
                lab = ",".join(f'{_metric_name(k, prefix="")}='
                               f'"{_escape_label_value(val)}"'
                               for k, val in labels)
                lines.append(f"{_metric_name(name)}"
                             f"{{{lab}}} {v:.10g}" if lab
                             else f"{_metric_name(name)} {v:.10g}")
            with open(path, "w", encoding="utf-8") as f:
                f.write("\n".join(lines) + "\n")
            return True
        except OSError:
            return False


def _metric_name(name: str, prefix: str = "roc_") -> str:
    """Sanitize to the Prometheus metric/label-name charset
    [a-zA-Z_][a-zA-Z0-9_]*."""
    out = prefix + "".join(
        c if c.isalnum() or c == "_" else "_" for c in name)
    return out if not out[:1].isdigit() else "_" + out


def _escape_label_value(v: str) -> str:
    """Prometheus text-format label-value escaping: backslash, double
    quote, and newline (exposition format spec)."""
    return (v.replace("\\", "\\\\").replace('"', '\\"')
             .replace("\n", "\\n"))


def load_jsonl(path: str) -> List[dict]:
    """Read a metrics/telemetry JSONL stream (skips unparseable lines —
    a crashed run may leave a torn last line)."""
    import json
    out: List[dict] = []
    try:
        with open(path, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    out.append(json.loads(line))
                except ValueError:
                    # roclint: allow(silent-swallow) — torn JSONL tail post-crash
                    continue
    except OSError:
        # roclint: allow(silent-swallow) — absent stream = no records
        pass
    return out
