#!/bin/bash
# Preflight gate: run the tier-1 lane (ROADMAP.md §Tier-1 verify) and
# refuse to let a snapshot/commit proceed on red.
#
# Usage:
#   bash tools/preflight.sh            # run lane, report DOTS_PASSED, exit rc
#   bash tools/preflight.sh --commit "msg"   # lane, then git commit -am only
#                                            # if the lane is green
#
# The DOTS_PASSED count is the lane's progress-dot tally — compare it
# against the last recorded baseline (CHANGES.md) to catch silently
# deselected tests, which a bare exit code cannot.
set -u
cd "$(dirname "$0")/.."
LOG=/tmp/_t1.log

set -o pipefail

# Stage 0: static analysis (roc_tpu/analysis/) — AST lint over the tree,
# then the collective budget audit (lowering only; CPU suffices).  Red
# here means a host sync / tracer hazard crept in, or a config's compiled
# communication drifted from budgets.json (regenerate DELIBERATE drifts
# with tools/roclint.py --update-budgets and review the manifest diff).
echo "== roclint =="
python tools/roclint.py || {
    echo "preflight: roclint findings — refusing to snapshot" >&2; exit 1; }
echo "== budget audit =="
timeout -k 10 600 python tools/roclint.py --audit --no-lint || {
    echo "preflight: collective budget audit RED" >&2; exit 1; }
# Lock-discipline gate: the whole-tree concurrency analyzer must report
# zero findings (after reasoned waivers) and zero drift against the
# committed threads.json lock-order baseline (exit 3 on either).
# Regenerate DELIBERATE discipline changes with --update-threads and
# review the diff; the analyzer's own seeded-mutation matrix (inversion,
# dropped guard, waitless condvar, ...) must keep biting.
echo "== lock discipline =="
timeout -k 10 120 python tools/roclint.py --threads --no-lint || {
    echo "preflight: lock discipline RED (threads findings or baseline drift)" >&2; exit 3; }
echo "== threads selftest =="
timeout -k 10 120 python -m roc_tpu.analysis.threads --selftest || {
    echo "preflight: threads analyzer selftest RED" >&2; exit 1; }
# Kernel step budgets: predicted binned grid-step counts at the canonical
# shapes must match tools/kernel_budgets.json exactly, and the flat
# schedule must hold its >=25% step reduction over the shipped default.
# Regenerate deliberate drifts with tools/check_kernel_budgets.py --update.
echo "== kernel step budgets =="
timeout -k 10 120 env JAX_PLATFORMS=cpu \
    python tools/check_kernel_budgets.py || {
    echo "preflight: kernel step budgets RED" >&2; exit 1; }
# Bench-artifact schema: the BENCH_rNN.json round receipts feed the
# perf-ledger fold (BENCH_TRAJECTORY.json / docs/PERF.md table); a field
# rename in the driver would break that join silently months later.
echo "== bench artifact schema =="
timeout -k 10 60 python tools/perf_ledger.py --check || {
    echo "preflight: bench artifact schema RED" >&2; exit 1; }

# Obs gate: the observability layer holds its own contracts — tracer
# span nesting + Chrome-trace schema validity, watchdog fires on an
# injected 3x slow epoch / stays quiet on noise, and the span overhead
# bound (stdlib-only, so this costs ~100 ms).
echo "== obs selftest =="
timeout -k 10 120 env JAX_PLATFORMS=cpu python -m roc_tpu.obs selftest || {
    echo "preflight: obs selftest RED" >&2; exit 1; }

# Calibration gate: the prediction/measurement ledger must actually pair
# on a tiny CPU run — >= 5 cost models joined by content key, each inside
# its sanity band.  This is the wiring proof for the flight recorder: a
# renamed field or a broken content key shows up here, not on the chip.
echo "== calibration selftest =="
timeout -k 10 300 env JAX_PLATFORMS=cpu \
    python -m roc_tpu.obs calibration --selftest || {
    echo "preflight: calibration selftest RED" >&2; exit 1; }

# Autotune gate: the geometry autotuner's closed CPU world must hold —
# seeded-surrogate sweep byte-identical across two runs, tuned.json
# schema valid, choose_geometry provably consumes the tuned entry (and
# falls back off-key), refit recovers the generating constants within
# 5%, and every trial pairs in the calibration ledger.
echo "== autotune selftest =="
timeout -k 10 300 env JAX_PLATFORMS=cpu \
    python -m roc_tpu.tune --selftest || {
    echo "preflight: autotune selftest RED" >&2; exit 1; }

# Memory-plan determinism gate: the same config must produce a
# byte-identical plan JSON (the plan participates in the step cache key —
# nondeterminism here means phantom retraces and unreproducible OOM
# triage).  Pure analytic path (no jax arrays), so this costs ~a second.
echo "== memory-plan determinism =="
PLAN_A=$(mktemp) PLAN_B=$(mktemp)
timeout -k 10 120 env JAX_PLATFORMS=cpu \
    python -m roc_tpu.memory --mode auto --budget 6g > "$PLAN_A" && \
timeout -k 10 120 env JAX_PLATFORMS=cpu \
    python -m roc_tpu.memory --mode auto --budget 6g > "$PLAN_B" && \
cmp -s "$PLAN_A" "$PLAN_B" || {
    echo "preflight: memory plan JSON not deterministic" >&2
    diff "$PLAN_A" "$PLAN_B" >&2; rm -f "$PLAN_A" "$PLAN_B"; exit 1; }
rm -f "$PLAN_A" "$PLAN_B"

# Fusion-region determinism gate (round 16): same graph + config must
# produce byte-identical region-plan JSON — the region partition keys
# the step cache via fusion_depth, so nondeterminism here means phantom
# retraces on device.  Covers the chainable model (gcn-chain, full
# region), a per-layer-only model (sage, empty partition), and the
# MLP-break negative case (gin).  Analytic op-IR walk, ~a second.
echo "== fusion-region determinism =="
REG_A=$(mktemp) REG_B=$(mktemp)
for pass in "$REG_A" "$REG_B"; do
    { timeout -k 10 120 env JAX_PLATFORMS=cpu python -m roc_tpu.models \
          --model gcn-chain --layers 100-256-256-256-47 --depth 0 && \
      timeout -k 10 120 env JAX_PLATFORMS=cpu python -m roc_tpu.models \
          --model sage --layers 100-256-256-47 --depth 0 && \
      timeout -k 10 120 env JAX_PLATFORMS=cpu python -m roc_tpu.models \
          --model gin --layers 100-256-256-47 --depth 2; } > "$pass" || {
        echo "preflight: region-plan dump failed" >&2
        rm -f "$REG_A" "$REG_B"; exit 1; }
done
cmp -s "$REG_A" "$REG_B" || {
    echo "preflight: fusion-region plan JSON not deterministic" >&2
    diff "$REG_A" "$REG_B" >&2; rm -f "$REG_A" "$REG_B"; exit 1; }
rm -f "$REG_A" "$REG_B"

# Streamed smoke: the out-of-core executor must still train end-to-end
# (tiny graph, 2 shards through 2 slots).  This is the cheapest proof that
# slot rotation, the prefetch ring, and the host-side gradient scatter all
# still compose — unit tests cover the pieces, this covers the wiring.
echo "== streamed smoke =="
timeout -k 10 300 env JAX_PLATFORMS=cpu python -m roc_tpu \
    -dataset roc-audit -layers 8-16-4 -e 2 -parts 2 \
    -stream -stream-slots 2 -eval-every 100 >/dev/null || {
    echo "preflight: streamed smoke RED" >&2; exit 1; }

# Serve smoke: cold start from a warm plan cache (zero plan rebuilds,
# asserted), ~100 mixed-batch-size queries on the tiny CPU dataset with
# served-vs-eval parity <= 32 ULPs and zero retraces after warmup — the
# serving contracts, end-to-end in one process (roc_tpu/serve/__main__).
# Includes the delta leg: journaled add/retire churn patched with zero
# retraces / zero plan rebuilds, then a restart that replays the delta
# journal to bitwise-identical served logits.
echo "== serve smoke =="
timeout -k 10 300 env JAX_PLATFORMS=cpu \
    python -m roc_tpu.serve --selftest >/dev/null || {
    echo "preflight: serve smoke RED" >&2; exit 1; }
# Serving bench artifact: tools/serve_bench.py must emit a BENCH_SERVE
# payload that passes the perf-ledger schema gate (tmp root — the real
# BENCH_SERVE.json is only written by an actual bench invocation).
echo "== serve bench selftest =="
timeout -k 10 300 env JAX_PLATFORMS=cpu \
    python tools/serve_bench.py --selftest || {
    echo "preflight: serve bench selftest RED" >&2; exit 1; }

# Fleet drill: 3 replicas behind the router under a 1000-event mixed
# query+delta stream — WAL-shipped segment replication keeps every
# member in seq lockstep (bitwise parity vs a single-engine oracle,
# zero retraces / zero plan rebuilds), a seeded hard kill of one
# follower mid-stream loses nothing (local WAL replay + snapshot
# catch-up while the survivors keep answering), and backpressure is
# typed + counted (roc_tpu/fleet/__main__).
echo "== fleet drill =="
timeout -k 10 570 env JAX_PLATFORMS=cpu \
    python -m roc_tpu.fleet --selftest >/dev/null || {
    echo "preflight: fleet drill RED" >&2; exit 1; }

# Fault-harness gate: the chaos machinery itself must be provably live —
# seeded spec determinism, retry recovery/exhaustion/kill-switch, the
# fsync-rename durability helper, the jitted non-finite skip, a seeded
# NaN-injection mini-train + serve-queue shed smoke, and the delta-
# journal kill-window matrix (lost-before-WAL vs replayed-after-WAL).
# Without this, "the faults didn't fire" and "the faults fired and were
# survived" are indistinguishable from a green run.
echo "== fault selftest =="
timeout -k 10 300 env JAX_PLATFORMS=cpu \
    python -m roc_tpu.fault --selftest >/dev/null || {
    echo "preflight: fault selftest RED" >&2; exit 1; }

rm -f "$LOG"
# ROC_T1_TIMEOUT: the full tier-1 lane needs ~1030 s on a 1-core box
# (PR 18 note) — the old hard-coded 870 s stopwatch lied.  Env knob so
# slow boxes can widen it without editing the gate.
timeout -k 10 "${ROC_T1_TIMEOUT:-1500}" env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
    -m 'not slow' --continue-on-collection-errors -p no:cacheprovider \
    -p no:xdist -p no:randomly 2>&1 | tee "$LOG"
rc=${PIPESTATUS[0]}
dots=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' "$LOG" | tr -cd . | wc -c)
echo "DOTS_PASSED=$dots"

if [ "$rc" -ne 0 ]; then
    echo "preflight: tier-1 lane RED (rc=$rc) — refusing to snapshot" >&2
    exit "$rc"
fi
echo "preflight: tier-1 lane green"

if [ "${1:-}" = "--commit" ]; then
    shift
    git commit -am "${1:?--commit needs a message}"
fi
