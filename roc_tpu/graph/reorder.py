"""Locality-preserving vertex reordering (reverse Cuthill-McKee style).

TPU-first design, no reference counterpart: the reference never reorders
vertices because its CUDA aggregation kernel rides the GPU cache hierarchy,
where vertex order barely matters (scattergather_kernel.cu:20-76 — random
scatter/gather at warp granularity).  On TPU the fast aggregation paths are
tiled: the binned schedule's cost is governed by how many (source-block x
destination-bin) cells the edge set touches (ops/pallas/binned.py,
choose_geometry's occupancy statistics), and that count is a property of
the vertex ORDER, not of the graph.  A bandwidth-reducing order concentrates
edges near the diagonal — on community-structured graphs (products-like) it
cuts touched cells by 10-100x, which is exactly what flips choose_geometry
from "matmul" to a binned geometry at sparse densities.

The order is a degree-sorted level-synchronous BFS from minimum-degree
seeds, reversed at the end — RCM's recipe, vectorized per level so the
whole pass is O(E) NumPy (products scale: seconds).  Determinism: ties
break on vertex id everywhere, so the permutation is reproducible.
"""

from __future__ import annotations

import numpy as np

from roc_tpu.graph.csr import Csr, E_DTYPE, V_DTYPE


def _union_neighbors(g: Csr, gt: Csr, frontier: np.ndarray) -> np.ndarray:
    """Concatenated in- and out-neighbors of ``frontier`` (with repeats)."""
    outs = []
    for c in (g, gt):
        lens = np.diff(c.row_ptr)[frontier]
        total = int(lens.sum())
        if total == 0:
            continue
        starts = c.row_ptr[:-1][frontier]
        # gather-runs: positions of every neighbor of every frontier node
        base = np.repeat(starts - np.concatenate(
            ([0], np.cumsum(lens)[:-1])), lens)
        outs.append(c.col_idx[base + np.arange(total)])
    if not outs:
        return np.zeros(0, V_DTYPE)
    return np.concatenate(outs)


def rcm_order(g: Csr, use_native: bool = None) -> np.ndarray:
    """Reverse-Cuthill-McKee-style order: ``order[new_id] = old_id``.

    BFS treats the graph as undirected (in- plus out-neighbors); levels are
    visited in increasing total-degree order (ids break ties).  Isolated
    vertices (self-loop only) go to the end in id order — they touch no
    off-diagonal cells, so their position is irrelevant to locality.

    Big graphs take the C++ BFS (roc_native.cc roc_rcm_order — the (deg,
    id) level order is a unique total order, so it matches this NumPy
    oracle element for element; pinned in tests/test_reorder.py); the
    vectorized level-synchronous NumPy path below is the oracle.
    """
    n = g.num_nodes
    if n == 0:
        return np.zeros(0, np.int64)
    gt = g.transpose()
    from roc_tpu import native
    if use_native is None:
        use_native = g.num_edges >= (1 << 20)
    if use_native and native.available():
        return native.rcm_order(g.row_ptr, g.col_idx, gt.row_ptr,
                                gt.col_idx)
    deg_in = np.diff(g.row_ptr)
    deg_out = np.diff(gt.row_ptr)
    # self-loops count toward both; subtract them from the "connects me to
    # someone" degree used for the isolated-vertex fast path
    self_cnt = np.zeros(n, np.int64)
    sl = g.col_idx == g.dst_idx
    np.add.at(self_cnt, g.col_idx[sl], 1)
    conn_deg = deg_in + deg_out - 2 * self_cnt
    deg = deg_in + deg_out

    visited = np.zeros(n, bool)
    isolated = conn_deg == 0
    visited[isolated] = True
    chunks = []
    # seed scan in (degree, id) order, skipping visited — each outer
    # iteration consumes a whole connected component
    seed_order = np.lexsort((np.arange(n), deg))
    seed_pos = 0
    while True:
        while seed_pos < n and visited[seed_order[seed_pos]]:
            seed_pos += 1
        if seed_pos >= n:
            break
        frontier = np.array([seed_order[seed_pos]], np.int64)
        visited[frontier] = True
        while frontier.size:
            chunks.append(frontier)
            neigh = np.unique(_union_neighbors(g, gt, frontier))
            neigh = neigh[~visited[neigh]]
            visited[neigh] = True
            # degree-sorted next level (unique already id-sorts; stable
            # lexsort keeps the id tiebreak)
            frontier = neigh[np.argsort(deg[neigh], kind="stable")]
    chunks.append(np.flatnonzero(isolated))
    order = np.concatenate(chunks) if chunks else np.zeros(0, np.int64)
    return order[::-1].astype(np.int64).copy()   # the "reverse" in RCM


def permute_csr(g: Csr, order: np.ndarray) -> Csr:
    """Relabel vertices: new id i is old vertex ``order[i]``.  O(E)."""
    n = g.num_nodes
    rank = np.empty(n, np.int64)
    rank[order] = np.arange(n)
    lens = np.diff(g.row_ptr)[order]
    row_ptr = np.zeros(n + 1, E_DTYPE)
    np.cumsum(lens, out=row_ptr[1:])
    starts_old = g.row_ptr[:-1][order]
    E = g.num_edges
    base = np.repeat(starts_old - row_ptr[:-1], lens)
    col_idx = rank[g.col_idx[base + np.arange(E)]].astype(V_DTYPE)
    return Csr(n, E, row_ptr, col_idx)


def maybe_reorder_dataset(ds, mode):
    """Apply the RCM pass per ``mode``: "on"/True always, "auto" only when
    it actually concentrates cells — the order is computed, the
    (block, bin) occupancy compared at GEOM_MID before/after (the same
    statistic choose_geometry consumes), and kept only on a >=10%
    padded-row reduction.  Returns (dataset, applied: bool, note: str).

    "auto" exists because locality is a property of the graph: community
    graphs with shuffled ids gain 2-10x, while graphs whose inter-edges
    are uniform (or already well-ordered) gain nothing and should not pay
    the permutation.  The stats beat guessing."""
    if mode in (False, None, "off"):
        return ds, False, ""
    from roc_tpu.ops.pallas.binned import GEOM_MID, padded_rows_for
    order = rcm_order(ds.graph)
    if mode in (True, "on"):
        ds2, _ = reorder_dataset(ds, order)
        return ds2, True, "RCM locality reorder applied"
    assert mode == "auto", mode
    g = ds.graph
    before = padded_rows_for(g.col_idx.astype(np.int64),
                             g.dst_idx.astype(np.int64), GEOM_MID)
    gp = permute_csr(g, order)
    after = padded_rows_for(gp.col_idx.astype(np.int64),
                            gp.dst_idx.astype(np.int64), GEOM_MID)
    if after <= 0.9 * before:
        ds2, _ = reorder_dataset(ds, order, graph=gp)
        return ds2, True, (f"RCM locality reorder kept: padded rows "
                           f"{before} -> {after} "
                           f"({after / max(before, 1):.2f}x)")
    return ds, False, (f"RCM locality reorder skipped: padded rows "
                       f"{before} -> {after} (< 10% gain)")


def reorder_dataset(ds, order: np.ndarray = None, graph: Csr = None):
    """Apply a locality order to a whole dataset (graph + every per-vertex
    array).  Training on the result is isomorphic to the original — same
    losses up to fp32 reassociation — because features, labels, and masks
    move with their vertices.  Returns (new_dataset, order).  ``graph``
    may pass an already-permuted CSR (the auto mode measured one) so the
    O(E) permutation isn't paid twice."""
    from roc_tpu.graph.datasets import Dataset
    if order is None:
        order = rcm_order(ds.graph)
    g = graph if graph is not None else permute_csr(ds.graph, order)
    return Dataset(
        name=ds.name, graph=g,
        features=ds.features[order],
        labels=None if ds.labels is None else ds.labels[order],
        label_ids=ds.label_ids[order],
        mask=ds.mask[order],
        in_dim=ds.in_dim, num_classes=ds.num_classes), order
