"""roc-threads tests: lock-discipline analyzer + runtime witness.

Mirrors test_analysis.py's evidence pattern for roc-verify:
  * the tree is CLEAN against the committed threads.json (no findings
    after reasoned waivers, zero baseline drift);
  * seeded mutations — a lock inversion, a dropped guard, a waitless
    condvar wait, an unjoined thread, a lock held across fsync, a
    mislabeled witness name — are each caught (the analyzer provably
    bites, it does not just bless);
  * the runtime witness records real acquisition orders when armed,
    validates them against the static graph (transitive closure), is a
    zero-record passthrough when disarmed, and ships `lock_order`
    events into the fault/obs telemetry sink;
  * every `# roclint: allow(...)` waiver in the tree carries a reason.

The threaded suites (test_serve/test_delta/test_stream/test_fleet) run
each test under the armed witness via an autouse fixture; the stress
cases there are what pin the graph against reality — this file pins the
machinery itself.
"""

import json
import os
import threading

import pytest

from roc_tpu.analysis import threads as T
from roc_tpu.analysis import witness as W

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- the tree against its committed baseline --------------------------------

@pytest.fixture(scope="module")
def tree_report():
    return T.analyze_paths([os.path.join(ROOT, "roc_tpu")])


def test_tree_is_clean_under_waivers(tree_report):
    assert tree_report.findings == [], [str(f) for f in tree_report.findings]


def test_tree_matches_committed_baseline():
    # analyze with repo-relative paths so LockNode.path matches what
    # --update-threads committed
    os.chdir(ROOT)
    rep = T.analyze_paths(["roc_tpu"])
    drift = T.diff_baseline(rep)
    assert drift == [], "\n".join(drift)


def test_baseline_pins_the_known_discipline():
    base = T.load_baseline()
    edges = {tuple(e) for e in base["edges"]}
    # the two real cross-lock orders in the tree today
    assert ("DeltaManager._mu", "ServeEngine._plan_lock") in edges
    assert ("ServeEngine._plan_lock", "PrefetchRing._lock") in edges
    # declared edges carry reasons
    for a, b, reason in base["declared_edges"]:
        assert reason.strip(), f"declared edge {a}->{b} missing a reason"
    # the load-bearing guarded-by facts
    gb = base["guarded_by"]
    assert gb["MicrobatchQueue._pending"] == "MicrobatchQueue._cv"
    assert gb["DeltaManager._seq"] == "DeltaManager._mu"
    assert gb["PrefetchRing.stall_s"] == "PrefetchRing._lock"
    assert gb["InProcTransport._q"] == "InProcTransport._cv"
    # every production lock the witness wraps is named correctly
    wrapped = {lk["name"]: lk["witness"] for lk in base["locks"]
               if lk["witness"] is not None}
    assert wrapped == {
        "DeltaManager._mu": "DeltaManager._mu",
        "InProcTransport._cv": "InProcTransport._cv",
        "MicrobatchQueue._cv": "MicrobatchQueue._cv",
        "PrefetchRing._lock": "PrefetchRing._lock",
        "ServeEngine._plan_lock": "ServeEngine._plan_lock",
    }
    # spawned threads/pools are all joinable from close()
    assert all(th["joined"] for th in base["threads"]), base["threads"]


def test_baseline_json_is_deterministic(tmp_path):
    os.chdir(ROOT)
    rep = T.analyze_paths(["roc_tpu"])
    p1, p2 = tmp_path / "a.json", tmp_path / "b.json"
    T.save_baseline(rep, str(p1))
    T.save_baseline(T.analyze_paths(["roc_tpu"]), str(p2))
    assert p1.read_bytes() == p2.read_bytes()
    assert json.loads(p1.read_text()) == T.report_dict(rep)


# -- seeded mutations (the analyzer bites) ----------------------------------

def _rules(src):
    return {f.rule for f in T.analyze_source(src).findings}


def test_clean_fixture_is_clean():
    rep = T.analyze_source(T._FIX_CLEAN)
    assert rep.findings == []
    assert ("Worker.a", "Worker.b") in rep.edges
    assert rep.guarded_by["Worker.items"] == "Worker.cv"


def test_seeded_inversion_caught():
    assert "lock-cycle" in _rules(T._MUT_INVERSION)


def test_seeded_dropped_guard_caught():
    assert "unguarded-attr" in _rules(T._MUT_UNGUARDED)


def test_seeded_waitless_condvar_caught():
    assert "condvar-wait" in _rules(T._MUT_WAITLESS)


def test_seeded_unjoined_thread_caught():
    assert "thread-join" in _rules(T._MUT_UNJOINED)


def test_seeded_lock_across_fsync_caught():
    assert "lock-blocking" in _rules(T._MUT_BLOCKING)


def test_seeded_witness_name_mismatch_caught():
    assert "witness-name" in _rules(T._MUT_WITNESS_NAME)


def test_waiver_silences_exactly_its_rule():
    waived = T._MUT_BLOCKING.replace(
        "        with self.a:\n            os.fsync(0)",
        "        with self.a:\n"
        "            # roclint: allow(lock-blocking) — fixture reason\n"
        "            os.fsync(0)")
    rep = T.analyze_source(waived)
    assert rep.findings == [] and rep.waived == 1
    # the waiver must not bleed into other rules
    assert "lock-cycle" in _rules(T._MUT_INVERSION.replace(
        "with self.b:\n            with self.a:",
        "with self.b:\n            # roclint: allow(lock-blocking) — wrong rule\n"
        "            with self.a:"))


def test_selftest_matrix_passes():
    assert T.selftest(verbose=False) == 0


# -- runtime witness mechanics ----------------------------------------------

@pytest.fixture
def armed():
    was = W.armed()
    W.reset()
    W.arm(True)
    yield W
    W.arm(was)
    W.reset()


def test_disarmed_is_passthrough_with_zero_records():
    was = W.armed()
    W.arm(False)
    try:
        W.reset()
        raw = threading.Lock()
        assert W.trace("X.raw", raw) is raw          # zero overhead
        with W.trace("X.a", threading.Lock()):
            with W.trace("X.b", threading.Lock()):
                pass
        assert W.records() == 0                      # zero telemetry
    finally:
        W.arm(was)


def test_armed_records_and_validates(armed):
    a = armed.trace("X.a", threading.Lock())
    b = armed.trace("X.b", threading.Lock())
    with a:
        with b:
            pass
    assert armed.observed_pairs()[("X.a", "X.b")] == 1
    assert armed.validate(edges=[("X.a", "X.b")]) == []
    viol = armed.validate(edges=[("X.b", "X.a")])
    assert len(viol) == 1 and "X.a -> X.b" in viol[0]
    # transitive closure: a->c->b sanctions the observed a->b
    assert armed.validate(edges=[("X.a", "X.c"), ("X.c", "X.b")]) == []


def test_armed_rlock_reentry_orders_nothing(armed):
    r = armed.trace("X.r", threading.RLock())
    inner = armed.trace("X.i", threading.Lock())
    with r:
        with r:            # re-entry: no (r, r) pair
            with inner:
                pass
    pairs = armed.observed_pairs()
    assert ("X.r", "X.r") not in pairs
    assert pairs[("X.r", "X.i")] == 1


def test_armed_condvar_wait_rerecords_order(armed):
    cv = armed.trace("X.cv", threading.Condition())
    outer = armed.trace("X.outer", threading.Lock())
    with outer:
        with cv:
            cv.wait(timeout=0.01)   # drop + re-record under `outer`
    pairs = armed.observed_pairs()
    # recorded at first acquire AND again at wait's reacquisition
    assert pairs[("X.outer", "X.cv")] == 2


def test_witness_emits_lock_order_telemetry(armed):
    from roc_tpu import fault
    events = []
    fault.attach(lambda kind, **f: events.append((kind, f)))
    try:
        a = armed.trace("X.t1", threading.Lock())
        b = armed.trace("X.t2", threading.Lock())
        for _ in range(3):
            with a:
                with b:
                    pass
    finally:
        fault.detach()
    lock_events = [f for k, f in events if k == "lock_order"]
    # each distinct pair ships exactly once, not once per acquisition
    assert lock_events == [{"outer": "X.t1", "inner": "X.t2"}]


def test_validate_defaults_to_committed_baseline(armed):
    # the production edge, driven for real through witness-wrapped locks
    a = armed.trace("DeltaManager._mu", threading.Lock())
    b = armed.trace("ServeEngine._plan_lock", threading.RLock())
    with a:
        with b:
            pass
    assert armed.validate() == []    # reads threads.json
    armed.reset()
    with b:
        with a:
            pass
    assert len(armed.validate()) == 1   # inverted: off-graph


# -- waiver inventory --------------------------------------------------------

def test_every_waiver_has_a_reason():
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "roclint_tool", os.path.join(ROOT, "tools", "roclint.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    os.chdir(ROOT)
    rows = mod.list_waivers(["roc_tpu", "tools", "bench.py"])
    assert rows, "waiver inventory came back empty — walker broke"
    missing = [(p, ln, rules) for p, ln, rules, reason in rows
               if not reason]
    assert missing == [], missing
