"""Runtime perf watchdog: EWMA epoch-time regressions + shard stragglers.

The dynamic counterpart to the static analyzer's gates (roc_tpu/analysis):
PR 3 proves a program *can't* silently grow collectives or retraces, but
the round-5 8.5x forced-vs-auto anomaly (docs/PERF.md) was harness state —
byte-identical HLO, wildly different wall-clock — which only a runtime
detector can catch.  The watchdog keeps an EWMA of epoch wall time and
flags any epoch slower than ``ratio`` x the mean; on binned runs the EWMA
can be *seeded* from the committed kernel-budget predictions
(tools/kernel_budgets.json steps_total x the measured per-grid-step
overhead), so the very first epochs are already checked against what the
cost model says the kernel floor should be.

Per-shard stragglers: `observe_shards` flags any probe time above
``straggler_ratio`` x the shard median — the balancer feeds it the same
probe samples its cost model fits, so a straggler alert lands in the
telemetry JSONL next to the balance round that should fix it.

Alerts are plain dicts (JSONL-ready, same `{"type": ...}` envelope as
balance telemetry once emitted through the registry); the driver prints
them under -v and `verdict()` stamps the bench artifact.
"""

from __future__ import annotations

import json
import os
from typing import List, Optional

DEFAULT_RATIO = 2.0        # alert when epoch > ratio x EWMA
DEFAULT_ALPHA = 0.25       # EWMA smoothing (higher = adapts faster)
DEFAULT_WARMUP = 2         # unseeded: observe this many epochs first
                           # (epoch 0 carries compile time; never judge it)
STRAGGLER_RATIO = 2.0      # shard alert when t > ratio x median shard time
# Calibration drift: alert when a cost model's measured/predicted ratio
# EWMA leaves this band.  Wide on purpose — the analytic models are
# order-of-magnitude instruments (the step-count models sit at exactly
# 1.0; the time models carry TPU-fit constants) and the alert exists for
# "the model stopped describing reality", not for 20% noise.
CALIBRATION_BAND = (0.5, 2.0)


class PerfWatchdog:
    """EWMA slow-epoch detector + per-shard straggler check."""

    def __init__(self, ratio: float = DEFAULT_RATIO,
                 alpha: float = DEFAULT_ALPHA,
                 warmup: int = DEFAULT_WARMUP,
                 seed_s: Optional[float] = None,
                 straggler_ratio: float = STRAGGLER_RATIO,
                 calibration_band=CALIBRATION_BAND):
        self.ratio = float(ratio)
        self.alpha = float(alpha)
        self.warmup = int(warmup)
        self.straggler_ratio = float(straggler_ratio)
        self.seeded = bool(seed_s and seed_s > 0)
        self.ewma: Optional[float] = float(seed_s) if self.seeded else None
        self.observed = 0
        self.alerts: List[dict] = []
        # stream-stall EWMA (fraction of epoch wall spent blocked on
        # host->device prefetch; stream executor runs only)
        self.stall_ewma: Optional[float] = None
        self.stall_observed = 0
        # spill-stall EWMA (fraction of epoch wall spent blocked on
        # boundary-store spill writes; -stream-spill runs only)
        self.spill_ewma: Optional[float] = None
        self.spill_observed = 0
        # serving p99-latency EWMA (serve engine runs only)
        self.serve_ewma: Optional[float] = None
        self.serve_observed = 0
        # delta-apply latency EWMA (dynamic-graph serving runs only)
        self.delta_ewma: Optional[float] = None
        self.delta_observed = 0
        # fleet replication-lag EWMA (roc_tpu/fleet router runs only)
        self.fleet_ewma: Optional[float] = None
        self.fleet_observed = 0
        # per-cost-model measured/predicted ratio EWMAs (ledger feed)
        self.calibration_band = (float(calibration_band[0]),
                                 float(calibration_band[1]))
        self.calib_ewma: dict = {}
        self.calib_observed: dict = {}
        # non-finite step guard feed (roc_tpu/fault): total skipped steps
        self.nonfinite_steps = 0

    # -- checkpoint round trip (roc_tpu/fault crash-consistent resume) ----
    _STATE_KEYS = ("ewma", "observed", "seeded", "stall_ewma",
                   "stall_observed", "spill_ewma", "spill_observed",
                   "serve_ewma", "serve_observed",
                   "delta_ewma", "delta_observed",
                   "fleet_ewma", "fleet_observed",
                   "calib_ewma", "calib_observed", "nonfinite_steps")

    def state_dict(self) -> dict:
        """JSON-able EWMA state for the checkpoint `extra` record, so a
        resumed run's watchdog is armed from epoch one instead of
        re-warming (and judging post-resume epochs against nothing)."""
        return {k: getattr(self, k) for k in self._STATE_KEYS}

    def load_state(self, state: dict) -> None:
        """Restore `state_dict` output; unknown/missing keys ignored (old
        checkpoints predate the watchdog extra)."""
        if not isinstance(state, dict):
            return
        for k in self._STATE_KEYS:
            if k in state:
                setattr(self, k, state[k])

    def observe_epoch(self, epoch: int, wall_s: float) -> Optional[dict]:
        """Feed one epoch's wall time; returns an alert dict or None."""
        wall_s = float(wall_s)
        armed = self.ewma is not None and \
            (self.seeded or self.observed >= self.warmup)
        alert = None
        if armed and wall_s > self.ratio * self.ewma:
            alert = {"kind": "slow-epoch", "epoch": int(epoch),
                     "wall_s": wall_s, "ewma_s": float(self.ewma),
                     "ratio": wall_s / self.ewma}
            self.alerts.append(alert)
            # Clamp the outlier's pull on the mean: one anomaly must not
            # poison the baseline it was measured against (or the NEXT
            # slow epoch would look fine by comparison).
            wall_s = self.ratio * self.ewma
        if self.observed >= 1 or self.seeded:
            # epoch 0 of an unseeded run carries jit compile time; start
            # the average at the first post-compile epoch
            self.ewma = wall_s if self.ewma is None else \
                self.alpha * wall_s + (1.0 - self.alpha) * self.ewma
        self.observed += 1
        return alert

    def observe_stream(self, epoch: int,
                       stall_frac: float) -> Optional[dict]:
        """Feed one streamed epoch's stall fraction (stream executor:
        stall_s / epoch wall).  Straggler-style alert when it exceeds
        ``ratio`` x its own EWMA — the signal that prefetch stopped hiding
        transfers (store contention, a slow host read, ring too shallow).
        Near-zero baselines are floored so a 0.001 -> 0.003 wiggle on a
        fully-overlapped run doesn't page anyone."""
        frac = float(stall_frac)
        armed = self.stall_ewma is not None and \
            self.stall_observed >= self.warmup
        baseline = max(self.stall_ewma or 0.0, 0.02)
        alert = None
        if armed and frac > self.ratio * baseline:
            alert = {"kind": "stream-stall", "epoch": int(epoch),
                     "stall_frac": frac, "ewma": float(self.stall_ewma),
                     "ratio": frac / baseline}
            self.alerts.append(alert)
            frac = self.ratio * baseline  # clamp, as observe_epoch does
        if self.stall_observed >= 1:
            # epoch 0 stalls on every first-touch transfer while the jit
            # compiles; never let it set the baseline
            self.stall_ewma = frac if self.stall_ewma is None else \
                self.alpha * frac + (1.0 - self.alpha) * self.stall_ewma
        self.stall_observed += 1
        return alert

    def observe_spill(self, epoch: int,
                      stall_frac: float) -> Optional[dict]:
        """Feed one spilled epoch's spill-stall fraction (stream executor
        under -stream-spill: boundary-store write seconds / epoch wall —
        the reads overlap on the prefetch ring, the writes block the
        consumer).  Alert when it exceeds ``ratio`` x its own EWMA: the
        signal that the spill device stopped keeping up (NVMe throttling,
        a full page cache flushing synchronously, a competing writer).
        Near-zero baselines floored and epoch 0 excluded, mirroring
        observe_stream."""
        frac = float(stall_frac)
        armed = self.spill_ewma is not None and \
            self.spill_observed >= self.warmup
        baseline = max(self.spill_ewma or 0.0, 0.02)
        alert = None
        if armed and frac > self.ratio * baseline:
            alert = {"kind": "spill-stall", "epoch": int(epoch),
                     "stall_frac": frac, "ewma": float(self.spill_ewma),
                     "ratio": frac / baseline}
            self.alerts.append(alert)
            frac = self.ratio * baseline  # clamp, as observe_epoch does
        if self.spill_observed >= 1:
            # epoch 0 pays first-touch page faults for every store while
            # the jit compiles; never let it set the baseline
            self.spill_ewma = frac if self.spill_ewma is None else \
                self.alpha * frac + (1.0 - self.alpha) * self.spill_ewma
        self.spill_observed += 1
        return alert

    def observe_serve(self, window: int, p99_s: float) -> Optional[dict]:
        """Feed one serving p99 sample (the engine aggregates a few
        windows of per-request latencies before each feed —
        serve/engine.py _note_window).  Alert when the p99 exceeds
        ``ratio`` x its own EWMA: queueing collapse or a slow device
        dispatch shows up in the tail long before the mean moves.
        Observation 0 carries warmup-trace and first-touch noise and
        never sets the baseline, mirroring observe_stream."""
        p99 = float(p99_s)
        armed = self.serve_ewma is not None and \
            self.serve_observed >= self.warmup
        alert = None
        if armed and p99 > self.ratio * self.serve_ewma:
            alert = {"kind": "serve-latency", "window": int(window),
                     "p99_s": p99, "ewma_s": float(self.serve_ewma),
                     "ratio": p99 / self.serve_ewma}
            self.alerts.append(alert)
            p99 = self.ratio * self.serve_ewma  # clamp, as observe_epoch
        if self.serve_observed >= 1:
            self.serve_ewma = p99 if self.serve_ewma is None else \
                self.alpha * p99 + (1.0 - self.alpha) * self.serve_ewma
        self.serve_observed += 1
        return alert

    def observe_delta(self, batch: int, apply_s: float) -> Optional[dict]:
        """Feed one delta-apply wall time (serve/delta.py feeds every
        applied batch; replay batches are excluded — restart replay is
        bulk work, not a serving-path sample).  Alert when an apply
        exceeds ``ratio`` x its own EWMA — a patch that suddenly re-cuts
        far more cells, or journal fsync latency, shows up here before
        it backs up the mutation path.  Observation 0 carries the
        first device_put/allocation noise and never sets the baseline,
        mirroring observe_serve."""
        t = float(apply_s)
        armed = self.delta_ewma is not None and \
            self.delta_observed >= self.warmup
        alert = None
        if armed and t > self.ratio * self.delta_ewma:
            alert = {"kind": "delta-apply", "batch": int(batch),
                     "apply_s": t, "ewma_s": float(self.delta_ewma),
                     "ratio": t / self.delta_ewma}
            self.alerts.append(alert)
            t = self.ratio * self.delta_ewma  # clamp, as observe_epoch
        if self.delta_observed >= 1:
            self.delta_ewma = t if self.delta_ewma is None else \
                self.alpha * t + (1.0 - self.alpha) * self.delta_ewma
        self.delta_observed += 1
        return alert

    def observe_fleet(self, event: int, lag_s: float,
                      shed_rate: float = 0.0) -> Optional[dict]:
        """Feed one fleet replication-lag sample (roc_tpu/fleet/router.py
        feeds the seal-to-applied wall per shipped segment, worst
        follower).  Alert when the lag exceeds ``ratio`` x its own EWMA
        — a follower falling behind shows up here before the freshness
        floor starts starving the dispatcher.  The alert carries the
        router's current shed rate so autoscale decisions in the JSONL
        are reconstructable.  Observation 0 carries first-segment
        device_put/trace noise and never sets the baseline, mirroring
        observe_serve."""
        lag = float(lag_s)
        armed = self.fleet_ewma is not None and \
            self.fleet_observed >= self.warmup
        alert = None
        if armed and lag > self.ratio * self.fleet_ewma:
            alert = {"kind": "fleet-lag", "event": int(event),
                     "lag_s": lag, "ewma_s": float(self.fleet_ewma),
                     "ratio": lag / self.fleet_ewma,
                     "shed_rate": float(shed_rate)}
            self.alerts.append(alert)
            lag = self.ratio * self.fleet_ewma  # clamp, as observe_epoch
        if self.fleet_observed >= 1:
            self.fleet_ewma = lag if self.fleet_ewma is None else \
                self.alpha * lag + (1.0 - self.alpha) * self.fleet_ewma
        self.fleet_observed += 1
        return alert

    def observe_nonfinite(self, epoch: int,
                          consecutive: int) -> Optional[dict]:
        """Feed one skipped (non-finite loss/grad) step from the in-graph
        guard (roc_tpu/fault).  Always alerts — a NaN step is never
        expected behavior — with the current consecutive-skip streak so
        the escalation ladder's state is visible in the JSONL."""
        self.nonfinite_steps += 1
        alert = {"kind": "nonfinite", "epoch": int(epoch),
                 "consecutive": int(consecutive),
                 "total": int(self.nonfinite_steps)}
        self.alerts.append(alert)
        return alert

    def observe_shards(self, epoch: int, times_s) -> List[dict]:
        """Feed per-shard probe times (balance/manager.py's samples);
        returns straggler alerts (possibly empty)."""
        times = [float(t) for t in times_s if t and t > 0]
        if len(times) < 2:
            return []
        med = sorted(times)[len(times) // 2]
        if med <= 0:
            return []
        alerts = []
        for part, t in enumerate(times):
            if t > self.straggler_ratio * med:
                alerts.append({"kind": "straggler", "epoch": int(epoch),
                               "part": part, "time_s": t,
                               "median_s": med, "ratio": t / med})
        self.alerts.extend(alerts)
        return alerts

    def observe_calibration(self, model: str, ratio: float,
                            epoch: int = -1) -> Optional[dict]:
        """Feed one joined (cost model, measured/predicted ratio) pair
        from the calibration ledger; returns a drift alert when the
        model's ratio EWMA leaves ``calibration_band``.  Per-model warmup
        mirrors observe_epoch: the first ``warmup`` pairs only build the
        EWMA (a model's very first joins may carry compile-epoch noise),
        later pairs are judged."""
        r = float(ratio)
        if r <= 0:
            return None    # a non-positive ratio is a broken pair, not drift
        model = str(model)
        ew = self.calib_ewma.get(model)
        self.calib_ewma[model] = r if ew is None else \
            self.alpha * r + (1.0 - self.alpha) * ew
        seen = self.calib_observed.get(model, 0) + 1
        self.calib_observed[model] = seen
        lo, hi = self.calibration_band
        cur = self.calib_ewma[model]
        if seen <= self.warmup or lo <= cur <= hi:
            return None
        alert = {"kind": "calibration-drift", "epoch": int(epoch),
                 "model": model, "ewma_ratio": float(cur),
                 "band_lo": lo, "band_hi": hi}
        self.alerts.append(alert)
        return alert

    def verdict(self) -> str:
        """"nonfinite" outranks everything (numerics beat perf), then
        "regressed" if any slow-epoch fired, then "straggler", then
        "stream-stall", then "spill-stall", then "serve-latency", then
        "delta-apply", then "fleet-lag", then "calibration-drift", "ok"
        otherwise — stamped into bench artifacts."""
        kinds = {a["kind"] for a in self.alerts}
        if "nonfinite" in kinds:
            return "nonfinite"
        if "slow-epoch" in kinds:
            return "regressed"
        if "straggler" in kinds:
            return "straggler"
        if "stream-stall" in kinds:
            return "stream-stall"
        if "spill-stall" in kinds:
            return "spill-stall"
        if "serve-latency" in kinds:
            return "serve-latency"
        if "delta-apply" in kinds:
            return "delta-apply"
        if "fleet-lag" in kinds:
            return "fleet-lag"
        if "calibration-drift" in kinds:
            return "calibration-drift"
        return "ok"


# -- budget seeding --------------------------------------------------------

_BUDGETS_PATH = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "tools",
    "kernel_budgets.json")


def seed_for_graph(num_rows: int, num_edges: int,
                   geometry: str = "default",
                   path: str = "") -> Optional[float]:
    """Predicted binned-kernel floor (seconds per aggregation pass) for a
    graph shape pinned in tools/kernel_budgets.json: the committed
    steps_total x the measured per-grid-step overhead the binned cost
    model uses (`_CHUNK_OVERHEAD_S`, 9.6-12.2 us measured on v5e).  None
    when the shape isn't pinned — the EWMA then warms up from measured
    epochs instead.  This is a *floor* (one aggregation pass, no matmuls),
    so seeding only arms the "order of magnitude off" detector early; it
    never replaces measured epochs, which take over after one EWMA step."""
    try:
        with open(path or _BUDGETS_PATH, encoding="utf-8") as f:
            budgets = json.load(f)
        from roc_tpu.ops.pallas.binned import _CHUNK_OVERHEAD_S
        for entry in budgets.values():
            if entry.get("num_rows") == num_rows and \
                    entry.get("num_edges") == num_edges:
                geo = entry["geometries"].get(geometry)
                if geo:
                    return float(geo["steps_total"]) * _CHUNK_OVERHEAD_S
    except (OSError, ValueError, KeyError, ImportError):
        # seeding is strictly best-effort: no budgets file / unpinned
        # shape degrades to measured-epoch warmup, the documented
        # fallback, not an error  # roclint: allow(silent-swallow) — documented best-effort seeding fallback, not an error path
        pass
    return None
