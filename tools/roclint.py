#!/usr/bin/env python
"""roclint — static SPMD invariant checks for the roc_tpu tree.

    python tools/roclint.py [paths...]        AST lint (default: the tree)
    python tools/roclint.py --audit           collective budget audit
    python tools/roclint.py --update-budgets  regenerate budgets.json
    python tools/roclint.py --threads         lock-discipline analysis +
                                              exact-diff vs threads.json
    python tools/roclint.py --update-threads  regenerate threads.json
    python tools/roclint.py --list-waivers    inventory every roclint
                                              waiver; missing reasons fail

The lint pass is pure AST — no jax, no devices, milliseconds.  The audit
pass lowers the train/eval step of every config in the audit matrix
(roc_tpu.analysis.hlo_audit.audit_specs) and diffs collectives/dtypes/
shardings against roc_tpu/analysis/budgets.json; lowering needs no
accelerator, so both run in CPU-only CI.  The audit pins JAX to CPU with
8 forced host devices — the manifest is only meaningful under that
topology (same pin as tests/conftest.py).

Exit status: 0 clean, 1 findings/violations (lint, audit, waivers),
2 usage error, 3 thread-discipline violation (finding or threads.json
drift — the same hard-gate contract as the budget audit, on its own
code so preflight can name the failing gate).
"""

import argparse
import os
import sys

DEFAULT_PATHS = ["roc_tpu", "tools", "bench.py"]


def _pin_cpu_topology():
    """Must run before jax is imported anywhere in this process."""
    if "jax" in sys.modules:
        print("# roclint: jax already imported; cannot pin the 8-device "
              "CPU topology the budgets were recorded under",
              file=sys.stderr)
        return
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    os.environ.setdefault("JAX_PLATFORMS", "cpu")


def list_waivers(paths):
    """Every ``# roclint: allow(...)`` in the tree as
    ``(path, line, rules, reason)``.  The reason is whatever prose
    follows the closing paren on the same line — a waiver without one is
    unauditable and fails the inventory."""
    from roc_tpu.analysis.lint import _WAIVER_RE
    from roc_tpu.analysis.threads import _iter_py
    out = []
    for path in _iter_py(paths):
        with open(path, encoding="utf-8") as f:
            for ln, line in enumerate(f.read().splitlines(), 1):
                m = _WAIVER_RE.search(line)
                if not m:
                    continue
                if m.start() > 0 and line[m.start() - 1] == "`":
                    continue   # doc mention (``# roclint: allow(...)``)
                rules = ",".join(r.strip() for r in m.group(1).split(","))
                reason = line[m.end():].strip().lstrip("—-: ").strip()
                out.append((path, ln, rules, reason))
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="roclint", description=__doc__)
    ap.add_argument("paths", nargs="*", help="files/dirs to lint "
                    "(default: roc_tpu tools bench.py)")
    ap.add_argument("--audit", action="store_true",
                    help="lower the audit matrix and diff against "
                    "budgets.json (skips the lint pass unless paths given)")
    ap.add_argument("--update-budgets", action="store_true",
                    help="regenerate roc_tpu/analysis/budgets.json from "
                    "the current tree")
    ap.add_argument("--threads", action="store_true",
                    help="lock-discipline analysis, exact-diffed against "
                    "roc_tpu/analysis/threads.json (exit 3 on violation)")
    ap.add_argument("--update-threads", action="store_true",
                    help="regenerate roc_tpu/analysis/threads.json from "
                    "the current tree")
    ap.add_argument("--list-waivers", action="store_true",
                    help="machine-readable inventory of every "
                    "`# roclint: allow(...)` waiver; exit 1 if any is "
                    "missing a reason")
    ap.add_argument("--no-lint", action="store_true",
                    help="skip the AST lint pass")
    args = ap.parse_args(argv)

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    os.chdir(repo)
    sys.path.insert(0, repo)

    rc = 0
    alt_mode = (args.audit or args.update_budgets or args.threads
                or args.update_threads or args.list_waivers)
    do_lint = not args.no_lint and (bool(args.paths) or not alt_mode)
    if do_lint:
        from roc_tpu.analysis import lint, mosaic
        paths = args.paths or DEFAULT_PATHS
        findings = sorted(lint.lint_paths(paths) + mosaic.lint_paths(paths),
                          key=lambda f: (f.path, f.line))
        for f in findings:
            print(f)
        n = len(findings)
        print(f"# roclint: {n} finding(s)", file=sys.stderr)
        if n:
            rc = 1

    if args.audit or args.update_budgets:
        _pin_cpu_topology()
        from roc_tpu.analysis import hlo_audit

        def progress(key):
            print(f"#   lowering {key}", file=sys.stderr)

        if args.update_budgets:
            budgets = hlo_audit.run_audit(progress=progress)
            hlo_audit.save_budgets(budgets)
            print(f"# roclint: wrote {len(budgets)} budget entr(y/ies) to "
                  f"{hlo_audit.BUDGETS_PATH}", file=sys.stderr)
        else:
            viol = hlo_audit.audit_against_budgets(progress=progress)
            for v in viol:
                print(f"BUDGET VIOLATION: {v}")
            print(f"# roclint audit: {len(viol)} violation(s)",
                  file=sys.stderr)
            if viol:
                rc = 1

    if args.threads or args.update_threads:
        from roc_tpu.analysis import threads as _threads
        rep = _threads.analyze_paths(args.paths or ("roc_tpu",))
        if args.update_threads:
            _threads.save_baseline(rep)
            print(f"# roclint: wrote {_threads.BASELINE_PATH} "
                  f"({len(rep.edges)} edge(s), {len(rep.guarded_by)} "
                  f"guarded-by fact(s))", file=sys.stderr)
        else:
            for f in rep.findings:
                print(f)
            drift = _threads.diff_baseline(rep)
            for line in drift:
                print(f"THREADS VIOLATION: {line}")
            print(f"# roclint threads: {len(rep.findings)} finding(s), "
                  f"{len(drift)} drift line(s), {rep.waived} waived",
                  file=sys.stderr)
            if rep.findings or drift:
                rc = 3

    if args.list_waivers:
        rows = list_waivers(args.paths or DEFAULT_PATHS)
        missing = 0
        for path, ln, rules, reason in rows:
            if not reason:
                missing += 1
                print(f"{path}:{ln}\t{rules}\tMISSING REASON")
            else:
                print(f"{path}:{ln}\t{rules}\t{reason}")
        print(f"# roclint waivers: {len(rows)} waiver(s), "
              f"{missing} missing reason(s)", file=sys.stderr)
        if missing:
            rc = max(rc, 1)
    return rc


if __name__ == "__main__":
    sys.exit(main())
