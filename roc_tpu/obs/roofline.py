"""The ONE roofline: peak constants + epoch FLOPs/bytes walked from the
op IR, shared by bench.py, the memory estimator, and the binned kernels.

Before this module, the peak-FLOPs/bandwidth constants and the
model-FLOPs formula lived twice (bench.py and memory/estimator.py) and
the HBM-bandwidth figure a third time (ops/pallas/binned.py) — exactly
the measurement-methodology drift that corrupts cross-run comparisons.
Every mfu / roofline_frac / recompute-price figure in the tree now flows
through here, so a constant re-fit (hw_revalidate) lands everywhere at
once.

Stdlib-only on purpose: kernel modules (ops/pallas) import the constants
at module load, before jax/numpy are welcome.

Accounting convention (standard MFU): count matmul/aggregation terms
only — norms, activations, dropout, and the optimizer are O(N*F) noise
against the N*F*F' and E*F terms.  Per op, for one training epoch
(fwd + bwd + opt):

  linear Fin->Fout:  6*N*Fin*Fout FLOPs (fwd + dX + dW),
                     3*(N*Fin + N*Fout)*b bytes (3 passes/epoch)
  aggregate at F:    4*E*F FLOPs (fwd + transposed bwd),
                     2*(E*F*b + N*F*b + E*4) bytes — every edge reads its
                     source row once per pass (gathers don't cache across
                     destinations in the worst case) + result writes +
                     index bytes  [scattergather_kernel.cu:20-76 is the
                     reference's corresponding hot kernel]
  gat (K heads, head_dim D): the projection matmul folded into the op
                     (Fin -> K*D) plus the aggregation sweep at K*D; the
                     per-edge score/softmax terms are O(E*K) and dropped.

b = 2 (bf16 fast path) or 4 (fp32 exact).  Walking the IR (instead of
re-deriving widths from a layer spec) makes residual projections, GAT
head folding, and SAGE concat widths come out right by construction.
"""

from __future__ import annotations

import os

__all__ = ["PEAK_FLOPS", "PEAK_BW", "TPU_BACKENDS", "itemsize_for",
           "model_flops_bytes", "roofline_time", "mfu", "roofline_frac"]


def _env_float(name: str, default: float) -> float:
    """Env-overridable constant with a safe fallback — a malformed value
    must not break import (bench.py's one-JSON-line contract)."""
    try:
        return float(os.environ.get(name, default))
    except (TypeError, ValueError):
        return float(default)


# Per-chip peaks; v5e: 197 TFLOP/s bf16 MXU, 819 GB/s HBM (public spec
# sheet).  Overridable for new hardware — this is the single definition
# site (`grep -rn PEAK_FLOPS` acceptance gate).
PEAK_FLOPS = _env_float("ROC_BENCH_PEAK_FLOPS", 197e12)
PEAK_BW = _env_float("ROC_BENCH_PEAK_BW_BYTES", 819e9)

# Backends the PEAK_* figures describe ("axon" is this container's tunnel
# name for the real v5e chip).  mfu / roofline_frac are only *claimed*
# against these — on any other backend the number would be plausible but
# meaningless.
TPU_BACKENDS = ("tpu", "axon")


def itemsize_for(precision: str = "fast") -> int:
    """Feature-stream element width under the aggregation precision."""
    return 2 if precision == "fast" else 4


def model_flops_bytes(model, num_nodes: int, num_edges: int,
                      precision: str = "fast"):
    """(FLOPs, min HBM bytes) for ONE training epoch of ``model`` on a
    graph of ``num_nodes`` rows / ``num_edges`` in-edges, walked from the
    op IR (models/model.py) under the convention in the module docstring.

    The bytes figure is the standard SpMM roofline lower bound;
    roofline_frac = that bound over the measured time, 1.0 = at the
    roofline.
    """
    N, E = float(num_nodes), float(num_edges)
    b = itemsize_for(precision)
    dims = {model.input.id: model.input.dim}
    flops = nbytes = 0.0
    for op in model.ops:
        a = dims[op.inputs[0]]
        if op.kind == "linear":
            out = int(op.attrs["out_dim"])
            flops += 6.0 * N * a * out
            nbytes += 3.0 * (N * a * b + N * out * b)
        elif op.kind == "gat":
            out = int(op.attrs["heads"]) * int(op.attrs["head_dim"])
            flops += 6.0 * N * a * out + 4.0 * E * out
            nbytes += 3.0 * (N * a * b + N * out * b)
            nbytes += 2.0 * (E * out * b + N * out * b + E * 4)
        elif op.kind == "aggregate":
            out = a
            flops += 4.0 * E * out
            nbytes += 2.0 * (E * out * b + N * out * b + E * 4)
        else:
            out = a          # elementwise: O(N*F) noise, not counted
        dims[op.out] = out
    return flops, nbytes


def forward_flops_bytes(model, num_nodes: int, num_edges: int,
                        precision: str = "fast"):
    """(FLOPs, min HBM bytes) for ONE inference forward — the serving
    window's cost.  Same IR walk and convention as ``model_flops_bytes``
    with the backward shares removed: a linear is one 2·N·Fin·Fout pass
    over one byte-sweep (training's 6/3 is fwd + two bwd), an aggregate
    is one 2·E·F pass over one edge-stream sweep (training's 4/2).  The
    serving ledger pair (serve/engine.py) predicts window p50 from this
    bound; `python -m roc_tpu.obs calibration` then reports how far the
    measured serving path sits above it."""
    N, E = float(num_nodes), float(num_edges)
    b = itemsize_for(precision)
    dims = {model.input.id: model.input.dim}
    flops = nbytes = 0.0
    for op in model.ops:
        a = dims[op.inputs[0]]
        if op.kind == "linear":
            out = int(op.attrs["out_dim"])
            flops += 2.0 * N * a * out
            nbytes += N * a * b + N * out * b
        elif op.kind == "gat":
            out = int(op.attrs["heads"]) * int(op.attrs["head_dim"])
            flops += 2.0 * N * a * out + 2.0 * E * out
            nbytes += N * a * b + N * out * b
            nbytes += E * out * b + N * out * b + E * 4
        elif op.kind == "aggregate":
            out = a
            flops += 2.0 * E * out
            nbytes += E * out * b + N * out * b + E * 4
        else:
            out = a          # elementwise: O(N*F) noise, not counted
        dims[op.out] = out
    return flops, nbytes


def roofline_time(flops: float, nbytes: float, n_dev: int = 1,
                  peak_flops: float = None, peak_bw: float = None) -> float:
    """Best-possible epoch seconds: max of the compute- and memory-bound
    lower bounds across ``n_dev`` chips."""
    pf = PEAK_FLOPS if peak_flops is None else peak_flops
    pb = PEAK_BW if peak_bw is None else peak_bw
    return max(flops / (n_dev * pf), nbytes / (n_dev * pb))


def mfu(flops: float, seconds: float, n_dev: int = 1,
        peak_flops: float = None):
    """Achieved model-FLOPs/s over the chips' peak; None if unmeasurable."""
    pf = PEAK_FLOPS if peak_flops is None else peak_flops
    if seconds <= 0.0 or pf <= 0.0:
        return None
    return flops / seconds / (n_dev * pf)


def roofline_frac(flops: float, nbytes: float, seconds: float,
                  n_dev: int = 1, peak_flops: float = None,
                  peak_bw: float = None):
    """roofline_time over the measured seconds; 1.0 = at the roofline."""
    if seconds <= 0.0:
        return None
    return roofline_time(flops, nbytes, n_dev, peak_flops, peak_bw) / seconds
