"""Device mesh construction (replaces the reference's GnnMapper placement).

The reference's mapper round-robins per-partition point tasks across
machines then GPUs and caches the placement (gnn_mapper.cc:88-134).  On TPU
the equivalent decision is a 1-D `jax.sharding.Mesh` over the vertex-shard
axis; XLA's SPMD partitioner owns placement from there.  Multi-host pods
arrive the same way: `jax.distributed.initialize()` + the global device list
— DCN-connected hosts simply contribute more devices to the same axis.
"""

from __future__ import annotations

import jax

PARTS_AXIS = "parts"


def make_mesh(num_parts: int, devices=None) -> jax.sharding.Mesh:
    """1-D mesh along the 'parts' axis.

    ``num_parts <= devices``: one part per device (mesh over the first
    num_parts devices).  ``num_parts > devices``: the reference's
    parts-per-GPU overcommit (gnn.cc:61-63 multiplexes numParts point tasks
    onto fewer GPUs) — the mesh spans every device and each one stacks
    ``k = num_parts / devices`` shard blocks inside the shard_map body
    (num_parts must divide evenly).  This is what lets a single bench chip
    run multi-part code paths for real.
    """
    devices = list(jax.devices() if devices is None else devices)
    if num_parts <= len(devices):
        return jax.sharding.Mesh(devices[:num_parts], (PARTS_AXIS,))
    assert num_parts % len(devices) == 0, (
        f"num_parts={num_parts} must be a multiple of the device count "
        f"({len(devices)}) for parts-per-device overcommit")
    return jax.sharding.Mesh(devices, (PARTS_AXIS,))
