"""Open-loop load generator for the serving engine.

Offers requests at a target QPS on a fixed schedule regardless of how
fast responses come back (open-loop), because closed-loop generators
hide queueing collapse: a closed loop slows its own offer rate exactly
when the engine falls behind, so the measured p99 stays flat while real
clients would be timing out.  Tail latency claims (tools/serve_bench.py,
PERF.md serving table) are only honest under open-loop offered load.

Request sizes cycle through a caller-supplied mix so a run exercises
every padded bucket — the same stream shape the zero-retrace test pins.
"""

from __future__ import annotations

import time
from typing import List, Optional, Sequence

import numpy as np


def percentile(sorted_vals: Sequence[float], q: float) -> float:
    """Nearest-rank percentile over an ascending-sorted sequence."""
    if not sorted_vals:
        return 0.0
    i = min(int(q * (len(sorted_vals) - 1) + 0.5), len(sorted_vals) - 1)
    return float(sorted_vals[i])


def run_load(engine, n_requests: int, qps: float,
             sizes: Sequence[int] = (1, 3, 8),
             rng: Optional[np.random.Generator] = None,
             timeout: float = 120.0) -> dict:
    """Offer ``n_requests`` at ``qps`` (open loop); return latency stats.

    Each request queries ``sizes[i % len(sizes)]`` random node ids.  All
    futures are collected first and resolved after the offer schedule
    completes, so a slow window never stalls the offered load.
    """
    assert n_requests >= 1 and qps > 0
    rng = rng or np.random.default_rng(0)
    nn = engine.bundle.num_nodes
    futures = []
    # Open-loop schedule anchor: each request fires at t0 + i/qps on the
    # host clock.  obs spans time device work, not an offer schedule (and
    # the submit side must never sync), hence the documented waiver.
    t0 = time.perf_counter()  # roclint: allow(raw-timing) — open-loop offer schedule anchor; the submit side must never sync
    for i in range(n_requests):
        target = t0 + i / qps
        delay = target - time.perf_counter()
        if delay > 0:
            time.sleep(delay)
        k = int(sizes[i % len(sizes)])
        futures.append(engine.submit(rng.integers(0, nn, size=k)))
    for f in futures:
        f.result(timeout)
    wall = time.perf_counter() - t0
    lats: List[float] = sorted(f.latency_s for f in futures)
    return {
        "n": n_requests,
        "qps_offered": round(qps, 3),
        "qps_achieved": round(n_requests / max(wall, 1e-9), 3),
        "p50_s": round(percentile(lats, 0.50), 6),
        "p99_s": round(percentile(lats, 0.99), 6),
        "mean_s": round(float(np.mean(lats)), 6),
    }
