"""GraphSAGE-mean (BASELINE.json config #3: "exercises scatter-gather
variants").

The reference enumerates AGGR_AVG in its AggrType (gnn.h:77-81) but only
ever wires AGGR_SUM into the built-in GCN; this model exercises the mean
path.  Per layer:

    t      = dropout(t)
    self_  = W_self · t
    neigh  = W_neigh · mean_{u in N(v) ∪ {v}} t[u]
    t      = self_ + neigh            (+ ReLU except on the output layer)

(expressed entirely in the reference's op vocabulary: linear /
scatter_gather / add / relu.  The input contract guarantees self-edges
(.add_self_edge.lux), so the mean includes the vertex itself — the
GraphSAGE-mean "mean over neighborhood including self" convention from the
original paper's Algorithm 1 variant, not the self-excluded mean.)
"""

from __future__ import annotations

from typing import Sequence

from roc_tpu.models.model import Model


def build_sage(layers: Sequence[int], dropout_rate: float = 0.5,
               aggr: str = "avg") -> Model:
    assert len(layers) >= 2
    model = Model(in_dim=layers[0])
    t = model.input
    for i in range(1, len(layers)):
        t = model.dropout(t, dropout_rate)
        self_ = model.linear(t, layers[i])
        neigh = model.scatter_gather(t, aggr)
        neigh = model.linear(neigh, layers[i])
        t = model.add(self_, neigh)
        if i != len(layers) - 1:
            t = model.relu(t)
        model.end_layer()
    model.softmax_cross_entropy(t)
    return model
