"""Real multi-process coverage (VERDICT round-1 items 4/5/9): two
`jax.distributed` CPU processes form one 8-device mesh and train the same
sharded GCN the single-process tests train, with

  * per-host `.lux` slice loading (-perhost): each process builds only its
    4 parts' edge arrays / halo maps,
  * `_place_nodes` running with a non-zero process_index (each process
    places only its addressable shards),
  * process-0-only checkpoint writing + barrier.

The reference's analog is the Legion/GASNet multi-machine launch
(gnn_mapper.cc:88-134); its parts>GPUs trick is covered by the virtual-mesh
tests — this file covers the genuinely-multi-process seams those can't.
"""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

from roc_tpu.graph import datasets, lux

pytestmark = pytest.mark.filterwarnings("ignore")

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_WORKER = os.path.join(_REPO, "tests", "multihost_worker.py")


def _free_port():
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


@pytest.fixture(scope="module")
def roc_prefix(tmp_path_factory):
    ds = datasets.synthetic("mh", 600, 6.0, 12, 5,
                            n_train=100, n_val=100, n_test=100, seed=7)
    prefix = str(tmp_path_factory.mktemp("mh") / "g")
    lux.write_dataset(prefix, ds.graph, ds.features, ds.label_ids, ds.mask)
    return prefix, ds


def _spawn_workers(prefix, tmp_path):
    """One full 2-process run: spawn both workers on a fresh port, wait
    out the (load-sensitive) distributed init + train, return outputs.
    Raises TimeoutExpired after killing the pair so a retry starts from
    a clean slate — a fresh port, no half-formed gloo mesh."""
    port = _free_port()
    env = dict(os.environ, PALLAS_AXON_POOL_IPS="")
    env.pop("JAX_PLATFORMS", None)
    env.pop("XLA_FLAGS", None)
    procs = [subprocess.Popen(
        [sys.executable, _WORKER, str(i), "2", str(port), prefix,
         str(tmp_path)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
        for i in range(2)]
    outs = []
    try:
        for p in procs:
            # generous deadline: under CI load the two interpreters can
            # take minutes just to import jax and form the mesh (the
            # PR 19 flake was a too-tight 240 s here)
            out, err = p.communicate(timeout=420)
            outs.append((out, err, p.returncode))
    except subprocess.TimeoutExpired:
        for q in procs:
            q.kill()
        for q in procs:
            q.communicate()  # reap, so the retry's port is truly free
        raise
    return outs


def test_two_process_training(roc_prefix, tmp_path):
    prefix, ds = roc_prefix
    # one bounded retry through the repo's own retry primitive: a hung
    # spawn under load is the transient being deflaked, a second timeout
    # is a real failure worth a red test
    from roc_tpu import fault
    try:
        outs = fault.retrying(
            "test.multihost_spawn", lambda: _spawn_workers(prefix, tmp_path),
            attempts=2, retry_on=(subprocess.TimeoutExpired,))
    except subprocess.TimeoutExpired:
        pytest.fail("multihost worker hung (twice, 420 s deadline each)")
    for out, err, code in outs:
        assert code == 0, f"worker failed:\n{err[-3000:]}"

    results = [json.load(open(tmp_path / f"out_{i}.json")) for i in range(2)]

    # process-0-only checkpointing: exactly one writer, file visible to both
    assert results[0]["saves"] == 1 and results[1]["saves"] == 0
    assert all(r["ckpt_exists"] for r in results)

    # both processes agree on the (psum-replicated) metrics
    m0, m1 = results[0]["metrics"], results[1]["metrics"]
    assert m0 == m1

    # and the distributed run matches a single-process 8-virtual-device run
    # of the identical config (the virtual mesh is the oracle; count metrics
    # must agree exactly, loss up to collective reassociation)
    from roc_tpu.models import build_gcn
    from roc_tpu.parallel.spmd import SpmdTrainer
    from roc_tpu.train.config import Config
    import jax

    cfg = Config(layers=[12, 16, 5], num_epochs=3, dropout_rate=0.0,
                 num_parts=8, halo=True, eval_every=10**9)
    tr = SpmdTrainer(cfg, datasets.load_roc_dataset(prefix, 12, 5),
                     build_gcn(cfg.layers, 0.0))
    for _ in range(cfg.num_epochs):
        tr.run_epoch()
    ref = jax.device_get(tr.evaluate())
    for k in ref._fields:
        a, b = float(getattr(ref, k)), m0[k]
        tol = 1e-3 * max(abs(a), 1.0) if k == "train_loss" else 0.0
        assert abs(a - b) <= tol, (k, a, b)

    # perhost plan-backend GAT (round 3): both processes agree, and the
    # losses match a single-process full-load run of the same config
    assert results[0]["gat_losses"] == results[1]["gat_losses"]
    from roc_tpu.models import build_gat
    cfg_g = Config(layers=[12, 8, 5], num_epochs=2, dropout_rate=0.0,
                   num_parts=8, halo=True, eval_every=10**9, model="gat",
                   heads=2, aggregate_backend="matmul")
    tr_g = SpmdTrainer(cfg_g, datasets.load_roc_dataset(prefix, 12, 5),
                       build_gat(cfg_g.layers, 0.0, heads=2))
    ref_g = [float(tr_g.run_epoch()) for _ in range(2)]
    # same tolerance policy as the GCN train_loss check above: the
    # 2-process gloo psum reassociates float sums differently from the
    # single-process virtual mesh
    np.testing.assert_allclose(results[0]["gat_losses"], ref_g, rtol=1e-3)
