"""roc_tpu — a TPU-native framework for distributed full-graph GNN training.

A ground-up JAX/XLA/Pallas re-design of the capabilities of the ROC system
(MLSys'20, reference: /root/reference — C++/CUDA on the Legion runtime):
edge-balanced graph partitioning, CSR scatter-gather aggregation, GCN-family
models, masked softmax cross-entropy with train/val/test metrics, Adam with
ROC's exact weight-decay formulation, and multi-chip SPMD execution over a
`jax.sharding.Mesh` (ICI collectives instead of Legion's implicit zero-copy
region coherence).

Layer map (the TPU-native analog of SURVEY.md §1):

  L0  XLA / TPU runtime            (external)
  L1  parallel/   mesh + shardings + halo exchange  (replaces GnnMapper,
                  ResourceManager, zero-copy staging — none of which exist
                  on TPU: HBM residency + sharding specs do their jobs)
  L2  graph/      CSR core, .lux IO, edge-balanced partitioner, datasets
  L3  ops/        pure-function ops with custom VJPs where sparsity needs it
  L4  models/     op-graph builder + model zoo (GCN, SAGE, GIN, GAT,
                  residual deep GCN)
  L5  train/      config, driver epoch loop, metrics, checkpointing, CLI
"""

__version__ = "0.2.0"

import roc_tpu._jax_compat  # noqa: F401  (installs jax 0.4.x polyfills)
from roc_tpu.graph.csr import Csr  # noqa: F401
