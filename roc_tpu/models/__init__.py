from roc_tpu.models.model import GraphCtx, Model
from roc_tpu.models.gcn import build_gcn
from roc_tpu.models.sage import build_sage
from roc_tpu.models.gin import build_gin
from roc_tpu.models.gat import build_gat


def build_model(name: str, layers, dropout_rate: float = 0.5,
                aggr: str = "", heads: int = 8) -> Model:
    """Model registry keyed by the CLI's -model flag.

    aggr="" means "the model's own default" (gcn: sum — the reference's only
    wired AggrType; sage: avg; gin: sum, where a non-sum choice is rejected
    because the GIN update is defined on sums).  heads only applies to gat."""
    if name == "gcn":
        return build_gcn(layers, dropout_rate, aggr or "sum")
    if name == "gcn-chain":
        # residual-free deep GCN: every hidden layer's boundary is the
        # plain activation tensor, so the round-16 fusion-region planner
        # can chain the whole stack (build_gcn docstring)
        return build_gcn(layers, dropout_rate, aggr or "sum",
                         residual=False)
    if name == "sage":
        return build_sage(layers, dropout_rate, aggr or "avg")
    if name == "gin":
        if aggr not in ("", "sum"):
            raise ValueError("gin is defined on sum aggregation")
        return build_gin(layers, dropout_rate)
    if name == "gat":
        return build_gat(layers, dropout_rate, heads=heads)
    raise ValueError(f"unknown model {name!r} (gcn|sage|gin|gat)")


__all__ = ["Model", "GraphCtx", "build_gcn", "build_sage", "build_gin",
           "build_gat", "build_model"]
