"""Native C++ runtime layer tests: build the library and pin every entry
point to its NumPy fallback (the fallback is the oracle)."""

import os

import numpy as np
import pytest

from roc_tpu import native
from roc_tpu.graph import datasets, lux
from roc_tpu.graph.partition import _python_bounds, edge_balanced_bounds


@pytest.fixture(scope="module")
def built():
    if not native.available():
        pytest.skip("native toolchain unavailable")
    return native


@pytest.fixture(scope="module")
def ds():
    return datasets.synthetic("t", 300, 4.0, 10, 4, n_train=60, n_val=60,
                              n_test=60, seed=51)


def test_build_produces_shared_lib(built):
    assert os.path.exists(os.path.join(os.path.dirname(native.__file__),
                                       "libroc_native.so"))


def test_lux_native_roundtrip(built, ds, tmp_path):
    path = str(tmp_path / "g") + lux.LUX_SUFFIX
    g = ds.graph
    built.lux_write(path, g.row_ptr[1:].astype(np.uint64),
                    g.col_idx.astype(np.uint32))
    nv, ne = built.lux_header(path)
    assert (nv, ne) == (g.num_nodes, g.num_edges)
    rows, cols = built.lux_read_slice(path, 0, nv, 0, ne)
    np.testing.assert_array_equal(rows.astype(np.int64), g.row_ptr[1:])
    np.testing.assert_array_equal(cols.astype(np.int32), g.col_idx)
    # python reader agrees with native writer (and vice versa through
    # read_lux's native path)
    g2 = lux.read_lux(path)
    np.testing.assert_array_equal(g2.col_idx, g.col_idx)


def test_lux_slice_matches_full_read(built, ds, tmp_path):
    # the per-partition seeking pattern (reference load_graph_impl)
    path = str(tmp_path / "g") + lux.LUX_SUFFIX
    g = ds.graph
    lux.write_lux(path, g)
    row_lo, row_hi = 57, 203
    col_lo = int(g.row_ptr[row_lo])
    col_hi = int(g.row_ptr[row_hi])
    rows, cols = built.lux_read_slice(path, row_lo, row_hi, col_lo, col_hi)
    np.testing.assert_array_equal(rows.astype(np.int64),
                                  g.row_ptr[1 + row_lo: 1 + row_hi])
    np.testing.assert_array_equal(cols.astype(np.int32),
                                  g.col_idx[col_lo:col_hi])


def test_partition_native_equals_python(built, ds):
    g = ds.graph
    for parts in (1, 2, 4, 7):
        n, nb = built.partition(g.row_ptr[1:], g.num_edges, parts)
        py = _python_bounds(g.row_ptr, parts)
        assert n == len(py)
        assert [tuple(b) for b in nb[:n][: len(py)]] == py[: min(n, parts)]
        # and the public API (whichever path it takes) stays self-consistent
        bounds = edge_balanced_bounds(g, parts)
        assert len(bounds) == parts


def test_csv_parse_native_equals_numpy(built, ds, tmp_path):
    prefix = str(tmp_path / "d")
    np.savetxt(prefix + ".feats.csv", ds.features, delimiter=",", fmt="%.6g")
    out = built.parse_feats_csv(prefix + ".feats.csv", ds.features.shape[0],
                                ds.features.shape[1])
    ref = np.loadtxt(prefix + ".feats.csv", delimiter=",", dtype=np.float32,
                     ndmin=2)
    np.testing.assert_allclose(out, ref, rtol=1e-6)


def test_csv_parse_rejects_malformed(built, tmp_path):
    # The NumPy path errors on ragged/malformed CSVs; the native parser must
    # be exactly as strict (empty cell, too few cols, trailing junk).
    for bad in ["1.0,,2.0\n3,4,5\n", "1.0,2.0\n3,4,5\n", "1,2,3,9\n4,5,6\n"]:
        p = tmp_path / "bad.csv"
        p.write_text(bad)
        with pytest.raises(IOError):
            built.parse_feats_csv(str(p), 2, 3)
    # short file (fewer rows than expected) also errors
    p = tmp_path / "short.csv"
    p.write_text("1,2,3\n")
    with pytest.raises(IOError):
        built.parse_feats_csv(str(p), 2, 3)
    # extra rows error too (NumPy's shape assert catches this case)
    p = tmp_path / "long.csv"
    p.write_text("1,2,3\n4,5,6\n7,8,9\n")
    with pytest.raises(IOError):
        built.parse_feats_csv(str(p), 2, 3)
    # ...but trailing blank lines are fine
    p = tmp_path / "blank.csv"
    p.write_text("1,2,3\n4,5,6\n\n")
    out = built.parse_feats_csv(str(p), 2, 3)
    np.testing.assert_allclose(out, [[1, 2, 3], [4, 5, 6]])


def test_in_degrees(built, ds):
    deg = built.in_degrees(ds.graph.row_ptr[1:].astype(np.uint64))
    np.testing.assert_array_equal(
        deg, np.diff(ds.graph.row_ptr).astype(np.float32))


@pytest.mark.parametrize("shape", [(500, 9000), (64, 0), (40, 1000),
                                   (1, 17), (8, 5000)])
def test_chunk_plan_native_equals_numpy(built, shape):
    # native builder vs the vectorized-NumPy oracle in build_chunk_plan
    from roc_tpu.ops.pallas.segment_sum import build_chunk_plan
    n, e = shape
    rng = np.random.default_rng(n + e)
    src = rng.integers(0, max(n, 1), e).astype(np.int64)
    dst = np.sort(rng.integers(0, max(n, 1), e)).astype(np.int64)
    plan = build_chunk_plan(src, dst, n)          # E < 2^20 -> NumPy path
    obi, first, esrc, edst = built.chunk_plan(src, dst, n)
    np.testing.assert_array_equal(obi, plan.obi)
    np.testing.assert_array_equal(first, plan.first)
    np.testing.assert_array_equal(esrc, plan.esrc)
    np.testing.assert_array_equal(edst, plan.edst)


def test_load_features_uses_native_and_caches(built, ds, tmp_path):
    prefix = str(tmp_path / "d")
    np.savetxt(prefix + ".feats.csv", ds.features, delimiter=",", fmt="%.6g")
    feats = lux.load_features(prefix, ds.features.shape[0],
                              ds.features.shape[1])
    np.testing.assert_allclose(feats, ds.features, rtol=1e-5, atol=1e-5)
    assert os.path.exists(prefix + ".feats.bin")


def test_csr_transpose_native_equals_numpy(built):
    """roc_csr_transpose (stable counting sort) must be element-identical
    to Csr.transpose's NumPy stable-argsort oracle — including edge
    multiplicity, isolated vertices, and hub rows."""
    from roc_tpu.graph.csr import Csr, add_self_edges, from_edges
    rng = np.random.default_rng(9)
    for (n, e) in [(300, 2000), (64, 0), (50, 1), (1000, 20000)]:
        src = rng.integers(0, n, e)
        dst = rng.integers(0, n, e)
        if e > 100:
            src[: e // 4] = 7                    # hub source
        g = add_self_edges(from_edges(n, src, dst))
        ref = g.transpose()                      # small E: NumPy oracle
        t_row, t_col = built.csr_transpose(g.row_ptr, g.col_idx)
        np.testing.assert_array_equal(t_row, ref.row_ptr,
                                      err_msg=f"n={n} e={e}")
        np.testing.assert_array_equal(t_col, ref.col_idx,
                                      err_msg=f"n={n} e={e}")
        # involution sanity: (A^T)^T == A up to within-row order (the
        # double transpose sorts each row's sources; same multiset)
        tt = Csr(g.num_nodes, g.num_edges, t_row.astype(ref.row_ptr.dtype),
                 t_col.astype(ref.col_idx.dtype)).transpose()
        np.testing.assert_array_equal(tt.row_ptr, g.row_ptr)
        for v in range(n):
            sl = slice(int(g.row_ptr[v]), int(g.row_ptr[v + 1]))
            np.testing.assert_array_equal(np.sort(tt.col_idx[sl]),
                                          np.sort(g.col_idx[sl]))
