"""Refit: re-solve the cost-model rate constants from trial records.

The analytic model prices one aggregation pass as

    t = max(mac1, s1*chunk_s) + dma_units*slot_dma_s + max(mac2, s2*chunk_s)

(surrogate.analytic_seconds — the parameterized mirror of binned's
``_binned_cost_model``), and the matmul backend as ``chunks *
mm_chunk_s``.  Each trial record carries its measured seconds AND its
schedule facts (step counts, the DMA regressor, flat/non-flat) — so
recovering the rates is a small linear least-squares, not a re-derive:

    t_i = chunk_s * steps_i + slot_dma_s * dma_nonflat_i
                            + flat_dma_s * dma_flat_i

over the overhead-bound, knob-default aggregation trials (MAC-bound
trials are excluded: their max() clamps break linearity in the rate;
knob-variant trials are excluded: the screen's priors would contaminate
the solve).  When the sweep's PROBE records are present they are the
whole calibration set — the halving's survivors cluster around the
winner, leaving steps and dma_units nearly collinear, while the probes
are designed pairs that pull those columns apart (search.REFIT_PROBES).  ``flat_dma_s`` is the flat staging-DMA term solved as its
own column — same nominal constant today, but the hardware fit is
allowed to disagree (the flat schedule's size-classed copies are a
different DMA population than the slot schedule's, which is exactly the
standing re-fit question in the ROADMAP).  ``mm_chunk_s`` is the median
implied rate of the matmul reference trials.

On the CI surrogate the recovered rates must land within 5% of the
generating constants (surrogate.CONSTANTS) — the acceptance pin that
proves sweep -> ledger records -> refit closes the loop.  On device the
same solve produces the real constants, and ``to_measured_table`` /
``update_budgets`` persist them in the kernel_bench ``measured`` format
(tools/kernel_budgets.json) that ``measured_calibration`` and the
balance prior warm-start from — with the same refusal contract:
``update_budgets`` will not commit an interpret table as rates.
"""

from __future__ import annotations

import json
import os

import numpy as np

from roc_tpu.tune.surrogate import CONSTANTS


def _fields(tr):
    """Normalize a TrialRecord or a raw ledger measurement dict to the
    solve's inputs; None when the record lacks the schedule facts."""
    if isinstance(tr, dict):
        if tr.get("model") not in ("tune_trial", "tune_confirm",
                                   "tune_probe") or "steps" not in tr:
            return None
        return {"t": float(tr["value"]), "steps": int(tr["steps"]),
                "dma_units": float(tr.get("dma_units", 0.0)),
                "flat": bool(tr.get("flat", 0)),
                "mac_bound": bool(tr.get("mac_bound", False)),
                "default_knobs": bool(tr.get("default_knobs", True)),
                "matmul": bool(tr.get("matmul", False)),
                "stage": str(tr.get("stage", "")),
                "variant": str(tr.get("variant", "")),
                "shape": str(tr.get("shape", ""))}
    return {"t": tr.trial_s, "steps": tr.steps, "dma_units": tr.dma_units,
            "flat": bool(tr.geom and tr.geom[7]) if len(tr.geom) > 7
            else False, "mac_bound": tr.mac_bound,
            "default_knobs": tr.default_knobs,
            "matmul": tr.stage == "matmul", "stage": tr.stage,
            "variant": tr.variant, "shape": tr.shape}


def refit_rates(trials) -> dict:
    """Solve the rate constants from trial records (TrialRecords from a
    live sweep, or ledger measurement dicts from the JSONL stream).

    Returns {chunk_s, slot_dma_s, flat_dma_s, mm_chunk_s, n_agg, n_mm,
    vs_constants: {name: refit/committed ratio}} — rates are None when
    no eligible trials identify them (e.g. no flat trials survived the
    halving: the flat column drops out rather than polluting the fit)."""
    agg, mm = [], []
    for tr in trials:
        f = _fields(tr)
        if f is None:
            continue
        if f["matmul"]:
            if f["steps"] > 0:
                mm.append(f["t"] / f["steps"])
            continue
        if f["mac_bound"] or not f["default_knobs"] or \
                "+fuse" in f["variant"]:
            continue
        agg.append(f)
    # The probe stage is search.py's designed experiment; the halving's
    # own survivors cluster (near-collinear steps vs dma_units), so when
    # probes exist they ARE the calibration set.
    probes = [f for f in agg if f["stage"] == "probe"]
    if probes:
        agg = probes
    out = {"chunk_s": None, "slot_dma_s": None, "flat_dma_s": None,
           "mm_chunk_s": None, "n_agg": len(agg), "n_mm": len(mm)}
    if agg:
        cols = [[f["steps"] for f in agg],
                [0.0 if f["flat"] else f["dma_units"] for f in agg],
                [f["dma_units"] if f["flat"] else 0.0 for f in agg]]
        names = ["chunk_s", "slot_dma_s", "flat_dma_s"]
        # drop all-zero columns (no flat or no non-flat trials) so the
        # lstsq stays full-rank and deterministic
        keep = [i for i, c in enumerate(cols) if any(v != 0 for v in c)]
        A = np.asarray([cols[i] for i in keep], dtype=np.float64).T
        b = np.asarray([f["t"] for f in agg], dtype=np.float64)
        # measurement noise is multiplicative (a fraction of each total),
        # so weight rows by 1/t: otherwise the long trials' absolute
        # noise drowns the small DMA column's contrast
        w = 1.0 / np.maximum(b, 1e-12)
        sol, *_ = np.linalg.lstsq(A * w[:, None], b * w, rcond=None)
        for i, v in zip(keep, sol):
            out[names[i]] = float(v)
    if mm:
        mm.sort()
        out["mm_chunk_s"] = mm[len(mm) // 2]
    committed = {"chunk_s": CONSTANTS["chunk_s"],
                 "slot_dma_s": CONSTANTS["slot_dma_s"],
                 "flat_dma_s": CONSTANTS["slot_dma_s"],
                 "mm_chunk_s": CONSTANTS["mm_chunk_s"]}
    out["vs_constants"] = {
        k: out[k] / committed[k]
        for k in committed if out.get(k) is not None and committed[k]}
    return out


def to_measured_table(trials, interpret: bool, platform: str = "",
                      h: int = 0) -> dict:
    """Trial records -> the kernel_bench ``measured`` table shape
    (binned.measured_calibration's input): per shape, the confirm-stage
    aggregation rows as per_step_s and the matmul reference as
    per_chunk_s.  ``interpret`` rides the table so the refusal contract
    holds end to end — a surrogate table validates schema in CI but is
    never read back as rates."""
    shapes: dict = {}
    for tr in trials:
        f = _fields(tr)
        if f is None or f["steps"] <= 0:
            continue
        stage = tr.get("stage", "") if isinstance(tr, dict) else tr.stage
        label = (tr.get("cand", tr.get("label", "")) if isinstance(tr, dict)
                 else tr.label)
        kernels = shapes.setdefault(f["shape"] or "swept",
                                    {"kernels": {}})["kernels"]
        if f["matmul"]:
            kernels["matmul"] = {
                "variant": "matmul", "chunks": f["steps"],
                "total_s": f["t"], "per_chunk_s": f["t"] / f["steps"]}
        elif stage == "confirm" and f["default_knobs"] \
                and not f["mac_bound"]:
            kernels[f"tuned/{label}"] = {
                "variant": "flat" if f["flat"] else "twopass",
                "steps_total": f["steps"], "total_s": f["t"],
                "per_step_s": f["t"] / f["steps"]}
    return {"interpret": bool(interpret), "platform": platform, "h": h,
            "source": "roc_tpu.tune refit", "shapes": shapes}


def update_budgets(table: dict, path: str = "") -> str:
    """Commit a refit table under kernel_budgets.json's ``measured`` key
    (the kernel_bench --update discipline: everything AROUND the key is
    preserved).  Refuses interpret tables — CI surrogate timings must
    never become the rates a device run warm-starts from."""
    if table.get("interpret", True):
        raise SystemExit(
            "tune.refit: refusing to commit an interpret/surrogate table "
            "as measured rates (measured_calibration contract)")
    path = path or os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "..", "tools", "kernel_budgets.json")
    path = os.path.abspath(path)
    committed = {}
    if os.path.exists(path):
        with open(path, encoding="utf-8") as f:
            committed = json.load(f)
    committed["measured"] = table
    with open(path, "w", encoding="utf-8") as f:
        json.dump(committed, f, indent=1, sort_keys=True)
        f.write("\n")
    return path
