"""Chunk-plan machinery + plan-backend tests (interpret/CPU).

Round-1's blocked-CSR Pallas kernel was removed in round 2: it cannot lower
on hardware (per-row DMA slices of tiled HBM refs; docs/PERF.md).  Its
chunk-plan machinery lives on under the `matmul` backend, and the "pallas"
backend name now resolves to the binned two-phase kernels
(ops/pallas/binned.py, tests/test_binned.py).  The XLA take+segment_sum
path remains the correctness oracle (SURVEY.md §7.3).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from roc_tpu import ops
from roc_tpu.graph import datasets
from roc_tpu.graph.partition import partition_graph
from roc_tpu.models import build_gcn
from roc_tpu.ops.pallas.segment_sum import EB, VB, build_chunk_plan
from roc_tpu.parallel.spmd import SpmdTrainer
from roc_tpu.train.config import Config
from roc_tpu.train.driver import Trainer


def graph_and_x(seed=3, n=150, h=16):
    ds = datasets.synthetic("t", n, 4.0, 8, 4, n_train=20, n_val=20,
                            n_test=20, seed=seed)
    g = ds.graph
    x = np.random.default_rng(seed).normal(size=(g.num_nodes, h)).astype(
        np.float32)
    return ds, g, x


def dense_agg(g, x):
    out = np.zeros_like(x)
    np.add.at(out, g.dst_idx, x[g.col_idx])
    return out


def test_chunk_plan_invariants():
    _, g, _ = graph_and_x()
    plan = build_chunk_plan(g.col_idx.astype(np.int32),
                            g.dst_idx.astype(np.int32), g.num_nodes)
    # windows visited in order; one 'first' per window; every window present
    assert np.all(np.diff(plan.obi) >= 0)
    assert plan.first[plan.obi != np.roll(plan.obi, 1)].all()
    assert set(plan.obi.tolist()) == set(range(plan.num_windows))
    # pad slots are masked (dst == VB) and point at row 0
    live = plan.edst != VB
    total_live = int(live.sum())
    assert total_live == g.num_edges
    assert np.all(plan.esrc[~live] == 0)
    assert plan.esrc.shape[1] == EB


def test_forward_matches_dense():
    _, g, x = graph_and_x()
    plans = ops.build_aggregate_plans(g.col_idx, g.dst_idx, g.num_nodes,
                                      g.num_nodes)
    out = ops.scatter_gather_matmul(jnp.asarray(x), plans, g.num_nodes,
                                    g.num_nodes)
    np.testing.assert_allclose(np.asarray(out), dense_agg(g, x), rtol=1e-5,
                               atol=1e-5)


def test_vjp_matches_transposed_aggregation():
    _, g, x = graph_and_x(h=8)
    plans = ops.build_aggregate_plans(g.col_idx, g.dst_idx, g.num_nodes,
                                      g.num_nodes)
    ct = np.random.default_rng(9).normal(size=x.shape).astype(np.float32)

    def f(x):
        return jnp.sum(ops.scatter_gather_matmul(
            x, plans, g.num_nodes, g.num_nodes) * ct)
    grad = jax.grad(f)(jnp.asarray(x))
    a = np.zeros((g.num_nodes, g.num_nodes), np.float32)
    np.add.at(a, (g.dst_idx, g.col_idx), 1.0)
    np.testing.assert_allclose(np.asarray(grad), a.T @ ct, rtol=1e-4,
                               atol=1e-4)


def test_rectangular_table():
    # table larger than out (the halo case: local rows + received rows)
    _, g, x = graph_and_x()
    extra = 24
    table = np.concatenate(
        [x, np.random.default_rng(1).normal(size=(extra, x.shape[1]))
         .astype(np.float32)])
    # route some edges to the extra rows
    src = g.col_idx.astype(np.int64).copy()
    src[::7] = g.num_nodes + (src[::7] % extra)
    plans = ops.build_aggregate_plans(src, g.dst_idx, g.num_nodes,
                                      table.shape[0])
    out = ops.scatter_gather_matmul(jnp.asarray(table), plans, g.num_nodes,
                                    table.shape[0])
    expect = np.zeros_like(x)
    np.add.at(expect, g.dst_idx, table[src])
    np.testing.assert_allclose(np.asarray(out), expect, rtol=1e-5, atol=1e-5)


def test_training_pallas_equals_xla_single_device():
    ds, g, _ = graph_and_x()
    cfg_x = Config(layers=[ds.in_dim, 8, ds.num_classes], num_epochs=3,
                   dropout_rate=0.0, eval_every=10**9)
    cfg_p = Config(layers=[ds.in_dim, 8, ds.num_classes], num_epochs=3,
                   dropout_rate=0.0, eval_every=10**9,
                   aggregate_backend="pallas")
    tx = Trainer(cfg_x, ds, build_gcn(cfg_x.layers, 0.0))
    tp = Trainer(cfg_p, ds, build_gcn(cfg_p.layers, 0.0))
    # "pallas" resolves to the binned kernels: features take one designed
    # bf16 rounding per aggregation (ops/pallas/binned.py), so equality to
    # the fp32-exact xla path is to bf16 tolerance, not bit-level.
    for i in range(3):
        lx, lp = float(tx.run_epoch()), float(tp.run_epoch())
        np.testing.assert_allclose(lp, lx, rtol=5e-3, err_msg=f"epoch {i}")
    # atol floors the comparison for near-zero params: after 3 Adam steps
    # the bf16 rounding noise accumulates to a few 1e-4 absolute on
    # elements of ~1e-4 magnitude (the exact rounding differs per jax
    # version's interpret mode), where rtol is meaningless.
    np.testing.assert_allclose(
        np.asarray(tp.params["linear_0"]), np.asarray(tx.params["linear_0"]),
        rtol=5e-3, atol=5e-4)


@pytest.mark.parametrize("halo", [False, True])
def test_training_pallas_equals_xla_sharded(halo):
    ds, g, _ = graph_and_x(n=220)
    base = dict(layers=[ds.in_dim, 8, ds.num_classes], num_epochs=2,
                dropout_rate=0.0, eval_every=10**9, num_parts=4, halo=halo)
    tx = SpmdTrainer(Config(**base), ds, build_gcn(base["layers"], 0.0))
    tp = SpmdTrainer(Config(**base, aggregate_backend="pallas"), ds,
                     build_gcn(base["layers"], 0.0))
    # "pallas" = the binned kernels (sharded): bf16-rounding tolerance,
    # same as the single-device variant above.
    for i in range(2):
        lx, lp = float(tx.run_epoch()), float(tp.run_epoch())
        np.testing.assert_allclose(lp, lx, rtol=5e-3, err_msg=f"epoch {i}")


def test_empty_graph_plan():
    from roc_tpu.ops.pallas.segment_sum import CPAD
    plan = build_chunk_plan(np.zeros(0, np.int32), np.zeros(0, np.int32), 10)
    # one (zeroing) chunk per window, padded up to the CPAD block size
    assert plan.num_chunks == -(-plan.num_windows // CPAD) * CPAD
    x = jnp.ones((10, 8))
    plans = ops.build_aggregate_plans(np.zeros(0, np.int64),
                                      np.zeros(0, np.int64), 10, 10)
    out = ops.scatter_gather_matmul(x, plans, 10, 10)
    np.testing.assert_array_equal(np.asarray(out), np.zeros((10, 8)))
