"""Polyfills so the jax-0.9-targeted codebase also runs on jax 0.4.x.

The repo is written against the post-0.5 shard_map surface (`jax.shard_map`
with `check_vma=`, `jax.lax.pcast`, `jax.typeof(...).vma`).  Some
containers ship jax 0.4.37, where none of those exist — the varying-
manual-axes (vma) type system hadn't landed yet and shard_map still lived
in `jax.experimental.shard_map` with the older `check_rep=` mechanism.

Rather than fork every call site, this module installs equivalents INTO
the `jax` namespace on first import of `roc_tpu` (tests monkeypatch
`jax.shard_map` directly, so the attribute must exist there).  On a jax
that already provides an API the polyfill is skipped — this file is a
no-op on 0.9+.

Degradation contract on old jax:

- ``jax.shard_map(..., check_vma=...)`` maps to the experimental
  shard_map with ``check_rep=False``.  check_rep is NOT the same check:
  it is a replication-inference pass with no rules for ``custom_vjp`` or
  ``pallas_call``, so passing ``check_rep=check_vma`` rejects valid
  programs this repo compiles under real vma checking.  Static vma
  verification simply does not exist pre-0.5; callers still pass (and
  tests still assert) the intended ``check_vma`` value so behavior is
  unchanged the moment a modern jax is present.
- ``jax.lax.pcast(x, axes, to="varying")`` is identity: with no vma
  annotations there is nothing to promote.  All pcast call sites here
  are promotions of replicated carries/inits (no gradient edge), which
  are correct unannotated on old jax.
- ``jax.typeof(x)`` returns the aval behind a proxy whose ``.vma`` is an
  empty frozenset when the aval predates vma support.
"""

import functools

import jax

HAS_VMA = hasattr(jax, "shard_map")


class _AvalProxy:
    """Delegates to a pre-vma ShapedArray, adding an empty .vma."""

    __slots__ = ("_aval",)
    vma = frozenset()

    def __init__(self, aval):
        self._aval = aval

    def __getattr__(self, name):
        return getattr(self._aval, name)


def _install():
    if not hasattr(jax, "shard_map"):
        from jax.experimental.shard_map import shard_map as _legacy

        @functools.wraps(_legacy)
        def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True,
                      **kw):
            del check_vma  # no vma machinery on this jax (see module doc)
            return _legacy(f, mesh=mesh, in_specs=in_specs,
                           out_specs=out_specs, check_rep=False, **kw)

        jax.shard_map = shard_map

    if not hasattr(jax.lax, "pcast"):
        def pcast(x, axes, *, to="varying"):
            del axes, to
            return x

        jax.lax.pcast = pcast

    if not hasattr(jax, "typeof"):
        def typeof(x):
            aval = jax.core.get_aval(x)
            return aval if hasattr(aval, "vma") else _AvalProxy(aval)

        jax.typeof = typeof


_install()
