"""Per-layer activation-byte + recompute-time estimates (ROC's DP inputs).

The reference's memory manager (Algorithm 2) plans over measured tensor
sizes and task runtimes; here the analogous inputs come from two sources:

  * **Bytes** are exact: the op IR (models/model.py) carries every
    intermediate's row width, so per-layer activation bytes are
    ``rows * width * itemsize`` sums — the same accounting XLA's buffer
    assigner does for the tensors whose lifetime the planner controls.
    ``step_arg_bytes`` / ``xla_memory_stats`` cross-check this against the
    compiled program's own buffer sizes (per-device, via the lowering
    machinery in analysis/hlo_audit.py); tests pin agreement within 10%.
  * **Recompute time** is priced in the units the balancer already trusts:
    aggregation ops through ``balance.cost_model.prior_times`` (the
    calibrated ``_matmul_cost`` chunk rate, width-scaled), linears through
    a peak-FLOPs/bandwidth roofline with the same constants bench.py
    reports against.  Absolute accuracy matters less than the RATIO of
    recompute cost to step time — that is all the DP compares.

Granularity decision (ROADMAP "per-layer flag vs per-tensor"): decisions
are PER LAYER, but the saved set within a kept layer is PER TENSOR — only
the expensive-to-recompute outputs (linear / aggregate / gat, plus the
layer boundary) are checkpoint-name-tagged for saving; elementwise
outputs (norm / activation / dropout / add) always rematerialize under an
active plan because recomputing them is bandwidth-cheap.  This is why a
planned layer costs ``bytes_saved`` (tagged tensors only) while an
unplanned (no-wrap, all-KEEP) layer costs ``bytes_full``.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Tuple

# TPU peaks from the single-source roofline module (obs/roofline.py, the
# same constants bench.py reports against); used here only to PRICE
# recompute relative to step time, never as a claim about achieved
# throughput.
from roc_tpu.obs.roofline import PEAK_BW, PEAK_FLOPS
# Feature width _MM_CHUNK_S (the aggregation chunk prior) was measured at
# (the reddit bench's in_dim); aggregation recompute scales linearly in
# width from there.
PRIOR_AGG_WIDTH = 602

# Op kinds whose outputs a kept layer SAVES under an active plan (the
# per-tensor half of the granularity decision — see module docstring).
SAVED_KINDS = frozenset({"linear", "aggregate", "gat"})
# Elementwise kinds: cheap to recompute, never saved under an active plan.
CHEAP_KINDS = frozenset({"dropout", "norm", "activation", "add"})


@dataclasses.dataclass(frozen=True)
class LayerEstimate:
    """One layer's planning inputs (all byte figures are per device)."""

    index: int
    name: str                 # "L<i>" — matches the checkpoint-name prefix
    bytes_full: int           # every op output (all-KEEP residual cost)
    bytes_saved: int          # tagged outputs only (KEEP under a plan)
    bytes_boundary: int       # the layer-boundary tensor alone
    recompute_full_s: float   # forward recompute of the whole segment
    recompute_cheap_s: float  # elementwise-only recompute (KEEP under plan)


@dataclasses.dataclass(frozen=True)
class ModelEstimate:
    """Planner inputs for one (model, shard shape) pair."""

    layers: Tuple[LayerEstimate, ...]
    fixed_bytes: int     # params + opt state + grads + placed node tensors
    base_step_s: float   # predicted all-KEEP step time (fwd + ~2x bwd)
    rows: int
    edges: int

    def total_full_bytes(self) -> int:
        return sum(l.bytes_full for l in self.layers)


def _op_out_dims(model) -> Dict[int, int]:
    """Output width per tensor id, walked from the op IR."""
    dims: Dict[int, int] = {0: model.input.dim}
    for op in model.ops:
        a = dims[op.inputs[0]]
        if op.kind == "linear":
            dims[op.out] = op.attrs["out_dim"]
        elif op.kind == "gat":
            dims[op.out] = op.attrs["head_dim"] * op.attrs["heads"]
        else:
            dims[op.out] = a
    return dims


def _op_forward_s(op, in_dim: int, out_dim: int, rows: int,
                  edges: int) -> float:
    """Forward time of one op at the given shard shape (seconds)."""
    if op.kind == "linear":
        flops = 2.0 * rows * in_dim * out_dim
        bytes_moved = 4.0 * rows * (in_dim + out_dim)
        return max(flops / PEAK_FLOPS, bytes_moved / PEAK_BW)
    if op.kind in ("aggregate", "gat"):
        from roc_tpu.balance.cost_model import prior_times
        import numpy as np
        t = float(prior_times(np.array([[rows, edges, 0, 0, 1.0]]))[0])
        t *= max(out_dim, 1) / PRIOR_AGG_WIDTH
        if op.kind == "gat":
            # projection matmul + per-edge score/softmax passes on top of
            # the aggregation sweep
            flops = 2.0 * rows * in_dim * out_dim
            t = 2.0 * t + flops / PEAK_FLOPS
        return t
    # elementwise: read input, write output (+ one op in between)
    return 4.0 * rows * (in_dim + 2 * out_dim) / PEAK_BW


def estimate_model(model, rows: int, edges: int, itemsize: int = 4,
                   fixed_bytes: int = 0,
                   megafuse: bool = False, fusion_depth: int = 1,
                   halo_rows: int = 0) -> ModelEstimate:
    """Per-layer byte/recompute estimates for ``model`` at a per-device
    shard of ``rows`` node rows and ``edges`` edges.

    ``itemsize`` is the activation element width (4 for fp32, 2 for bf16);
    ``fixed_bytes`` is the plan-independent resident set (params, optimizer
    state, placed node tensors) the caller already knows.

    ``megafuse=True`` applies the whole-layer megakernel's tensor
    elimination: every ``mega_matches`` record names the output tensors
    that never materialize under fusion in its ``gone`` tuple — the
    aggregate's output (and the linear's, when a trailing relu folds in)
    for the direct chain; the linear's, aggregate's, and second norm's
    for the norm-folded GCN chain (the first norm's output stays counted
    as the proxy for the pre-scaled input the folded path materializes
    instead).  Those contribute zero to ``bytes_full``/``bytes_saved``
    and the DP plans over the fused layer's real residual set.

    ``fusion_depth != 1`` (with megafuse) additionally applies the
    round-16 fusion REGION's kept/dropped tuple: ``mega_regions`` names
    the inter-layer boundary tensors the cross-layer grid keeps in VMEM
    for shard-local rows.  Those are NOT free — the halo frontier's
    rows still round-trip HBM between layers (parallel/halo.py exchange
    contract) — so they are priced at ``halo_rows`` rows instead of the
    full shard (zero on a single device, where every row is local).
    """
    fused_gone: set = set()
    frontier_gone: set = set()
    if megafuse:
        from roc_tpu.models.model import mega_matches, mega_regions
        for rec in mega_matches(model).values():
            fused_gone.update(rec["gone"])
        if fusion_depth != 1:
            for reg in mega_regions(model, fusion_depth).values():
                # region-dropped minus per-layer-dropped = the inter-layer
                # boundaries the region ALSO eliminates; halo rows survive
                frontier_gone.update(
                    t for t in reg["gone"] if t not in fused_gone)
    dims = _op_out_dims(model)
    per_layer: Dict[int, List] = {}
    for op in model.ops:
        per_layer.setdefault(op.attrs.get("layer", 0), []).append(op)
    layers = []
    total_fwd = 0.0
    for idx in sorted(per_layer):
        full = saved = boundary = 0
        fwd = cheap = 0.0
        saw_boundary = False
        for op in per_layer[idx]:
            in_dim = dims[op.inputs[0]]
            out_dim = dims[op.out]
            if op.out in fused_gone:
                out_bytes = 0
            elif op.out in frontier_gone:
                # inter-layer boundary inside a fusion region: only the
                # halo frontier's rows materialize (kept/dropped honesty)
                out_bytes = halo_rows * out_dim * itemsize
            else:
                out_bytes = rows * out_dim * itemsize
            t = _op_forward_s(op, in_dim, out_dim, rows, edges)
            full += out_bytes
            fwd += t
            if op.kind in SAVED_KINDS or op.attrs.get("ckpt_boundary"):
                saved += out_bytes
            else:
                cheap += t
            if op.attrs.get("ckpt_boundary"):
                boundary = out_bytes
                saw_boundary = True
        # fallback only when the layer has NO tagged boundary op: a tagged
        # boundary that priced to 0 is a region-interior tensor the fused
        # grid keeps in VMEM — re-pricing it full would undo the honesty
        if not saw_boundary and per_layer[idx]:
            last = per_layer[idx][-1]
            boundary = rows * dims[last.out] * itemsize
        layers.append(LayerEstimate(
            index=idx, name=f"L{idx}", bytes_full=int(full),
            bytes_saved=int(saved), bytes_boundary=int(boundary),
            recompute_full_s=fwd, recompute_cheap_s=cheap))
        total_fwd += fwd
    # backward ~ 2x forward (grad-of-linear is two matmuls; grad-of-
    # aggregate is one transposed aggregation + accumulation)
    return ModelEstimate(layers=tuple(layers), fixed_bytes=int(fixed_bytes),
                         base_step_s=3.0 * total_fwd, rows=rows, edges=edges)


def mega_bwd_cotangent_drop(model, rows: int, itemsize: int = 4) -> int:
    """Predicted backward-intermediate HBM bytes the fused megakernel
    BACKWARD eliminates: per ``mega_matches`` layer, the ``[rows, H_in]``
    aggregation cotangent (dL/dagg = g @ W^T) no longer round-trips HBM —
    one write + one read each (see ``binned.predicted_trainstep_hbm_bytes``
    for the full train-step accounting this slots into).  bench.py reports
    this in the mem artifact block on fused-backward legs."""
    from roc_tpu.models.model import mega_matches
    total = 0
    for rec in mega_matches(model).values():
        total += 2 * rows * rec["linear"].attrs["in_dim"] * itemsize
    return total


def gat_residual_drop(model, rows: int, edges: int,
                      itemsize: int = 4) -> int:
    """Predicted residual HBM bytes the fused GAT attention kernel
    (round 19, ops/pallas/gat.py) eliminates: per gat layer the unfused
    oracle's VJP saves per-EDGE softmax residuals — the normalized
    exponentials ``e [E,K]`` fp32 and the leaky-relu sign ``qpos [E,K]``
    bool — while the fused path keeps per-NODE max/normalizer planes
    (2 × [rows, K] fp32) instead, pricing the edge-width alpha/gather
    intermediates at 0.  Reported in bench.py's mem artifact block on
    fused-attention legs, next to ``mega_bwd_cotangent_drop``."""
    from roc_tpu.models.model import gat_matches
    total = 0
    for rec in gat_matches(model).values():
        k = rec["heads"]
        total += edges * k * (itemsize + 1) - 2 * rows * k * 4
    return max(total, 0)


def fixed_bytes_for(model, rows: int, in_dim: int, num_classes: int,
                    edges: int, itemsize: int = 4) -> int:
    """Plan-independent per-device residents: replicated params + Adam
    m/v + one grad copy (4x params), placed node tensors (x, one-hot
    labels, mask) and the edge arrays."""
    params = 0
    for op in model.ops:
        if op.kind == "linear":
            params += op.attrs["in_dim"] * op.attrs["out_dim"]
        elif op.kind == "gat":
            kf = op.attrs["heads"] * op.attrs["head_dim"]
            params += op.attrs["in_dim"] * kf + 2 * kf
    node = rows * (in_dim * itemsize + num_classes * 4 + 4 + 4)
    edge = edges * 2 * 4
    return int(4 * params * 4 + node + edge)


def estimate_for_trainer(trainer) -> ModelEstimate:
    """Estimates at the trainer's actual per-device shard shape."""
    import numpy as np
    ds = trainer.dataset
    part = getattr(trainer, "part", None)
    k = getattr(trainer, "k", 1)
    if part is not None:
        rows = int(part.shard_nodes) * k
        edges = int(getattr(part, "shard_edges", 0)) * k or \
            -(-ds.graph.num_edges // trainer.config.num_parts)
    else:
        rows = ds.graph.num_nodes
        edges = ds.graph.num_edges
    itemsize = int(np.dtype(trainer.dtype).itemsize)
    fixed = fixed_bytes_for(trainer.model, rows, ds.features.shape[1],
                            ds.num_classes, edges, itemsize)
    # halo frontier (round 16): rows other shards reference still
    # round-trip HBM at fused region boundaries — the received halo
    # block is [P*K] rows per device in halo-exchange mode; 0 on a
    # single device / allgather mode (where the region drop is total)
    halo = getattr(trainer, "halo", None)
    halo_rows = 0
    if halo is not None and part is not None:
        halo_rows = int(part.num_parts) * int(halo.K)
    return estimate_model(trainer.model, rows, edges, itemsize=itemsize,
                          fixed_bytes=fixed,
                          megafuse=getattr(trainer.config, "megafuse",
                                           False),
                          fusion_depth=getattr(trainer.config,
                                               "fusion_depth", 1),
                          halo_rows=halo_rows)


# -- XLA cross-checks (analysis/hlo_audit.py lowering machinery) ----------

def step_arg_bytes(trainer) -> int:
    """Analytic per-device bytes of the train step's arguments: each
    leaf's local-shard size (sharded leaves count one shard, replicated
    leaves count in full) — the quantity XLA reports as argument (+
    donation-aliased) buffer bytes."""
    import jax
    import jax.numpy as jnp
    rng = jax.random.PRNGKey(0)
    alpha = jnp.float32(trainer.optimizer.alpha)
    args = (trainer.params, trainer.opt_state, trainer.x, trainer.labels,
            trainer.mask, trainer.gdata, rng, alpha)
    total = 0
    for leaf in jax.tree_util.tree_leaves(args):
        shards = getattr(leaf, "addressable_shards", None)
        if shards:
            total += shards[0].data.size * leaf.dtype.itemsize
        else:
            total += leaf.size * leaf.dtype.itemsize
    return int(total)


def xla_memory_stats(trainer) -> dict:
    """XLA-reported per-device buffer sizes of the compiled train step
    (argument/output/temp/alias bytes), via the audit subsystem's
    lowering."""
    from roc_tpu.analysis.hlo_audit import lower_steps
    ma = lower_steps(trainer)["train"].compile().memory_analysis()
    if ma is None:   # some backends don't implement memory analysis
        return {}
    return {
        "argument_bytes": int(ma.argument_size_in_bytes),
        "output_bytes": int(ma.output_size_in_bytes),
        "temp_bytes": int(ma.temp_size_in_bytes),
        "alias_bytes": int(ma.alias_size_in_bytes),
    }
