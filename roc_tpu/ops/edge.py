"""Edge-tensor ops: per-edge scores, edge softmax, attention aggregation.

The reference declares edge tensors as first-class (create_edge_tensor,
gnn.cc:534-589; EDGE_TENSOR input paths in linear.cc:73-77,
activation.cc:48-52, dropout.cc:42-46) but ships no op that produces one —
the capability is latent (SURVEY.md §2.1).  Here edge tensors are realized
the TPU way: an edge tensor is an [E, ...] array aligned with the CSR's
dst-sorted edge order, sharded over the mesh's 'parts' axis by the same
edge partition that shards edge_src/edge_dst (roc_tpu/graph/partition.py).

These ops are what GAT-style models need: endpoint scores, a per-destination
softmax over in-edges, and attention-weighted aggregation.  All are pure
XLA (sorted segment reductions); pad edges are inert because the partitioner
routes them to pad destination rows (partition.py edge padding invariants).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def edge_softmax(scores, edge_dst, num_nodes: int):
    """Per-destination softmax over in-edges.

    scores: [E, ...] (any trailing dims, e.g. one column per attention
    head); edge_dst: [E] sorted ascending.  Returns alpha with
    sum over {e : dst(e)=v} alpha[e] == 1 for every v with in-edges.
    """
    m = jax.ops.segment_max(scores, edge_dst, num_segments=num_nodes,
                            indices_are_sorted=True)
    m = jnp.where(jnp.isfinite(m), m, 0.0)          # edgeless destinations
    e = jnp.exp(scores - jnp.take(m, edge_dst, axis=0))
    s = jax.ops.segment_sum(e, edge_dst, num_segments=num_nodes,
                            indices_are_sorted=True)
    return e / jnp.maximum(jnp.take(s, edge_dst, axis=0), 1e-38)


# GAT switches to the edge-chunked scan above the same gathered-intermediate
# budget as aggregate._chunked_segment_sum (2^28 elems = 1 GiB fp32 — at
# Reddit scale the dense [E, K, F] alone is ~24 GB, over a v5e's HBM).
# Shared constants so the two memory policies cannot drift.
from roc_tpu.ops.aggregate import (          # noqa: E402
    _CHUNK_TARGET_ELEMS as _GAT_CHUNK_TARGET_ELEMS,
    _CHUNK_THRESHOLD_ELEMS as _GAT_CHUNK_THRESHOLD_ELEMS)

_GAT_CHUNK_MIN = 1024     # floor on edge-chunk length (tests shrink it)


def gat_attend(h, table, edge_src, edge_dst, num_nodes: int,
               a_src, a_dst, slope: float):
    """Multi-head graph attention aggregation (GAT).

    h:       [N_local, K, F] W-projected features of the *destination* rows.
    table:   [T, K, F] source feature table (== h on one device; local rows
             ++ halo rows, or the all-gathered tensor, under SPMD).
    a_src/a_dst: [K, F] attention vectors (the two halves of the GAT `a`).
    Per edge: s_e = LeakyReLU(a_dst.h[dst_e] + a_src.table[src_e]);
    alpha = edge_softmax(s); out[v] = sum_e alpha_e * table[src_e].
    Returns [N_local, K, F].
    """
    E, (K, F) = edge_src.shape[0], h.shape[1:]
    if E * K * F > _GAT_CHUNK_THRESHOLD_ELEMS:
        return _chunked_gat_attend(h, table, edge_src, edge_dst, num_nodes,
                                   a_src, a_dst, slope)
    as_t = jnp.einsum("tkf,kf->tk", table, a_src)     # [T, K]
    ad_l = jnp.einsum("nkf,kf->nk", h, a_dst)         # [N_local, K]
    s = jax.nn.leaky_relu(
        jnp.take(ad_l, edge_dst, axis=0) + jnp.take(as_t, edge_src, axis=0),
        negative_slope=slope)                          # [E, K]
    alpha = edge_softmax(s, edge_dst, num_nodes)       # [E, K]
    g = jnp.take(table, edge_src, axis=0)              # [E, K, F]
    return jax.ops.segment_sum(g * alpha[:, :, None], edge_dst,
                               num_segments=num_nodes,
                               indices_are_sorted=True)


def _chunked_gat_attend(h, table, edge_src, edge_dst, num_nodes: int,
                        a_src, a_dst, slope: float):
    """Memory-bounded GAT: never materializes [E, K, F].

    Standard streaming softmax shape: (1) one edge-chunk scan accumulates
    the per-destination score max m; (2) a second scan accumulates both the
    normalizer z[v] = Σ exp(s_e - m[v]) and the unnormalized output
    Σ exp(s_e - m[v])·table[src_e]; out = unnorm / z.  Same math as the
    dense path (softmax shift by the exact per-dst max), different sum
    order — equal up to float reassociation.  Working set per step:
    [chunk, K, F] plus the [N, K(, F)] accumulators.  Pad edges (routed to
    pad dst rows) only pollute pad rows.

    The bound must survive autodiff, where lax.scan stacks per-step
    residuals back up to O(E*K*F): the accumulate body is rematerialized
    (jax.checkpoint — backward recomputes each chunk's gather/exp instead
    of saving them) and the max scan carries no gradient at all
    (stop_gradient on m: softmax is shift-invariant, d out/d m == 0).
    """
    E, (K, F) = edge_src.shape[0], h.shape[1:]
    as_t = jnp.einsum("tkf,kf->tk", table, a_src)     # [T, K]
    ad_l = jnp.einsum("nkf,kf->nk", h, a_dst)         # [N_local, K]

    chunk = max(_GAT_CHUNK_TARGET_ELEMS // max(K * F, 1), _GAT_CHUNK_MIN)
    nchunks = -(-E // chunk)
    pad = nchunks * chunk - E
    # pad edges: src 0 (harmless), dst at the extra throwaway row
    src = jnp.pad(edge_src, (0, pad)).reshape(nchunks, chunk)
    dst = jnp.pad(edge_dst, (0, pad),
                  constant_values=num_nodes).reshape(nchunks, chunk)

    def scores(s_ids, d_ids):
        return jax.nn.leaky_relu(
            jnp.take(ad_l, jnp.minimum(d_ids, num_nodes - 1), axis=0)
            + jnp.take(as_t, s_ids, axis=0), negative_slope=slope)

    def max_body(m, sl):
        s_ids, d_ids = sl
        return m.at[d_ids].max(scores(s_ids, d_ids),
                               indices_are_sorted=True,
                               mode="promise_in_bounds"), None
    m0 = jnp.full((num_nodes + 1, K), -jnp.inf, as_t.dtype)
    m, _ = jax.lax.scan(max_body, m0, (src, dst))
    m = jnp.where(jnp.isfinite(m), m, 0.0)            # edgeless destinations
    m = jax.lax.stop_gradient(m)

    def acc_body(carry, sl):
        z, out = carry
        s_ids, d_ids = sl
        e = jnp.exp(scores(s_ids, d_ids)
                    - jnp.take(m, d_ids, axis=0))     # [chunk, K]
        z = z.at[d_ids].add(e, indices_are_sorted=True,
                            mode="promise_in_bounds")
        g = jnp.take(table, s_ids, axis=0)            # [chunk, K, F]
        out = out.at[d_ids].add(g * e[:, :, None], indices_are_sorted=True,
                                mode="promise_in_bounds")
        return (z, out), None
    z0 = jnp.zeros((num_nodes + 1, K), as_t.dtype)
    o0 = jnp.zeros((num_nodes + 1, K, F), h.dtype)
    (z, out), _ = jax.lax.scan(
        jax.checkpoint(acc_body, prevent_cse=False), (z0, o0), (src, dst))
    return (out[:num_nodes]
            / jnp.maximum(z[:num_nodes], 1e-38)[:, :, None])
