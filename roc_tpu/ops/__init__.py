from roc_tpu.ops.aggregate import (
    AggregatePlans, BinnedPlans, build_aggregate_plans, build_binned_plans,
    divide_by_degree, matmul_precision, pad_binned_plans, pad_plans,
    region_linear_binned, scatter_gather, scatter_gather_binned,
    scatter_gather_linear_binned, scatter_gather_matmul)
from roc_tpu.ops.edge import (GatPlans, build_gat_plans, edge_softmax,
                              gat_attend, gat_attend_binned,
                              gat_attend_plan, pad_gat_plans)
from roc_tpu.ops.norm import indegree_norm
from roc_tpu.ops.linear import linear
from roc_tpu.ops.activation import apply_activation, elu, relu, sigmoid
from roc_tpu.ops.element import add, mul
from roc_tpu.ops.dropout import dropout
from roc_tpu.ops.softmax import (
    PerfMetrics, masked_softmax_cross_entropy, perf_metrics)
from roc_tpu.ops.init import glorot_uniform

__all__ = [
    "scatter_gather", "scatter_gather_matmul",
    "scatter_gather_binned", "scatter_gather_linear_binned",
    "region_linear_binned",
    "BinnedPlans", "build_binned_plans",
    "pad_binned_plans", "matmul_precision", "divide_by_degree",
    "edge_softmax", "gat_attend", "gat_attend_binned", "gat_attend_plan",
    "GatPlans", "build_gat_plans", "pad_gat_plans",
    "indegree_norm", "linear", "relu", "sigmoid", "elu",
    "apply_activation", "add",
    "mul", "dropout", "PerfMetrics", "masked_softmax_cross_entropy",
    "perf_metrics", "glorot_uniform",
]
