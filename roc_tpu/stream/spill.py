"""NVMe spill tier: memory-mapped boundary stores for -stream-spill.

The third rung of the rotation ladder (HBM slot -> host store -> disk):
segment-boundary activation and cotangent stores move from host RAM to
``np.memmap`` files under the ``-stream-spill`` directory, so host
memory only has to hold the graph-shaped arrays (features, labels,
edges) while the per-segment boundary tensors — the part that scales
with model depth times P*S — page through the OS cache from NVMe.  The
PrefetchRing's worker reads slot i+1's rows off the map behind slot i's
compute exactly like device staging, which is what keeps the tier
composable with the existing overlap machinery.

Each store file carries a 64-byte CRC'd header (the same hardening the
.lux loader and the serve delta journal use: magic, version, dtype,
shape, CRC32 over all of it) written via ``fault.fsync_replace`` so a
crash can never leave an undetected torn header; the data region is
extended sparsely after the promote.  A bad magic/version/CRC/short
file raises :class:`SpillHeaderError` — typed, so callers distinguish
"corrupt spill state" from transient I/O (which the ring already
retries).
"""

from __future__ import annotations

import os
import struct
import zlib

import ml_dtypes  # registers bfloat16 with numpy (jax dependency)
import numpy as np

__all__ = ["SpillError", "SpillHeaderError", "create_store", "open_store",
           "HEADER_BYTES"]

_MAGIC = b"RSPL"
_VERSION = 1
HEADER_BYTES = 64
# magic | u16 version | u16 dtype-code | u64 rows | u64 cols | u32 crc32
_HDR = struct.Struct("<4sHHQQI")

# dtype codes are part of the on-disk format: append-only.
_DTYPES = {1: np.dtype(np.float32), 2: np.dtype(ml_dtypes.bfloat16)}
_CODES = {v: k for k, v in _DTYPES.items()}


class SpillError(RuntimeError):
    """A spill store that cannot be used (I/O layout, unknown dtype)."""


class SpillHeaderError(SpillError):
    """A spill store that cannot be *trusted*: torn/corrupt header."""


def _pack_header(dtype: np.dtype, rows: int, cols: int) -> bytes:
    code = _CODES.get(np.dtype(dtype))
    if code is None:
        raise SpillError(f"spill store: unsupported dtype {dtype!r}")
    body = _HDR.pack(_MAGIC, _VERSION, code, rows, cols, 0)[:-4]
    crc = zlib.crc32(body) & 0xFFFFFFFF
    hdr = body + struct.pack("<I", crc)
    return hdr + b"\0" * (HEADER_BYTES - len(hdr))


def create_store(path: str, shape, dtype) -> np.ndarray:
    """Create a zero-filled spill store at ``path`` and return its
    writable memmap.  The CRC'd header is promoted durably
    (tmp + fsync + rename, ``fault.fsync_replace``) before the data
    region is extended, so every visible file has a valid header."""
    from roc_tpu import fault

    rows, cols = int(shape[0]), int(shape[1])
    dtype = np.dtype(dtype)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(_pack_header(dtype, rows, cols))
    fault.fsync_replace(tmp, path)
    nbytes = rows * cols * dtype.itemsize
    with open(path, "r+b") as f:
        f.truncate(HEADER_BYTES + nbytes)  # sparse: zero pages on demand
    return np.memmap(path, dtype=dtype, mode="r+",
                     offset=HEADER_BYTES, shape=(rows, cols))


def open_store(path: str) -> np.ndarray:
    """Open an existing spill store, validating the header end to end."""
    try:
        size = os.path.getsize(path)
        with open(path, "rb") as f:
            raw = f.read(HEADER_BYTES)
    except OSError as e:
        raise SpillError(f"spill store {path!r}: {e}") from e
    if len(raw) < HEADER_BYTES:
        raise SpillHeaderError(
            f"spill store {path!r}: truncated header "
            f"({len(raw)} < {HEADER_BYTES} bytes)")
    magic, version, code, rows, cols, crc = _HDR.unpack(raw[:_HDR.size])
    if magic != _MAGIC:
        raise SpillHeaderError(
            f"spill store {path!r}: bad magic {magic!r} (not a spill store)")
    if zlib.crc32(raw[:_HDR.size - 4]) & 0xFFFFFFFF != crc:
        raise SpillHeaderError(
            f"spill store {path!r}: header CRC mismatch — torn or corrupt "
            "write; delete the spill directory and rerun")
    if version != _VERSION:
        raise SpillHeaderError(
            f"spill store {path!r}: version {version} (expected {_VERSION})")
    dtype = _DTYPES.get(code)
    if dtype is None:
        raise SpillHeaderError(
            f"spill store {path!r}: unknown dtype code {code}")
    want = HEADER_BYTES + rows * cols * dtype.itemsize
    if size < want:
        raise SpillHeaderError(
            f"spill store {path!r}: data region truncated "
            f"({size} < {want} bytes)")
    return np.memmap(path, dtype=dtype, mode="r+",
                     offset=HEADER_BYTES, shape=(rows, cols))
