"""Binned two-phase aggregation (ops/pallas/binned.py) vs the segment-sum
oracle, in interpret mode on CPU.  Hardware behavior is covered by the
TPU-gated tests in tests/test_tpu_hw.py, skipped off-TPU (interpret mode
has already let two Mosaic lowering bugs ship; see docs/PERF.md)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from roc_tpu import ops
from roc_tpu.ops.pallas.binned import RB, SB, build_binned_plan, run_binned


def oracle_bf16(x, src, dst, n):
    """The binned backend's numerical contract: features rounded to bf16
    once, fp32 accumulation.  Shared with tests/test_tpu_hw.py."""
    xb = np.asarray(jnp.asarray(x).astype(jnp.bfloat16).astype(jnp.float32))
    out = np.zeros((n, x.shape[1]), np.float32)
    np.add.at(out, dst, xb[src])
    return out


_oracle_bf16 = oracle_bf16


CASES = [
    # (num_rows, table_rows, num_edges, hidden)
    (700, 700, 5000, 64),
    (1500, 2000, 30000, 64),    # multi-group, table != out rows
    (100, 100, 0, 64),          # empty edge list
    (513, 513, 1, 8),           # single edge, just past one bin
    (SB + 1, SB + 1, 300, 16),  # two source blocks
    (3 * RB, 1000, 3000, 16),   # partial last bin group (G=2, bpg=2)
    (700, 700, 5000, 41),       # lane-unaligned H (GCN output layer):
                                # run_binned pads H to 128 internally
]


@pytest.mark.parametrize("n,t,e,h", CASES)
def test_binned_matches_oracle(n, t, e, h):
    rng = np.random.default_rng(42)
    src = rng.integers(0, t, e).astype(np.int64)
    dst = rng.integers(0, n, e).astype(np.int64)
    if e > 100:
        dst[: e // 4] = 7       # hub destination spanning many slots
    x = rng.standard_normal((t, h), dtype=np.float32)
    plan = build_binned_plan(src, dst, n, t, group_row_target=1 << 14)
    out = np.asarray(run_binned(jnp.asarray(x), plan, interpret=True))
    ref = _oracle_bf16(x, src, dst, n)
    # identical sums up to fp32 reassociation (chunk order != edge order)
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-3)


def test_binned_hub_source_and_dst():
    """A single source feeding a single dst many times (parallel edges) —
    multiplicity must be preserved exactly (one-hot columns are per-edge)."""
    n = 64
    src = np.full(1000, 3, np.int64)
    dst = np.full(1000, 5, np.int64)
    x = np.ones((n, 8), np.float32) * 1.5
    plan = build_binned_plan(src, dst, n, n, group_row_target=1 << 14)
    out = np.asarray(run_binned(jnp.asarray(x), plan, interpret=True))
    assert out[5, 0] == 1500.0 and np.all(out[:5] == 0) and np.all(out[6:] == 0)


def oracle_fp32(x, src, dst, n):
    """The exact path's contract: fp32 values, fp32 accumulation (the
    reference's precision, types.h:7), differing only by sum order."""
    out = np.zeros((n, x.shape[1]), np.float32)
    np.add.at(out, dst, np.asarray(x)[src])
    return out


@pytest.mark.parametrize("n,t,e,h", CASES)
def test_binned_exact_matches_fp32_oracle(n, t, e, h):
    """precision="exact" (fp32 staging + 3-way bf16 split dots) must agree
    with the fp32 oracle to reassociation-level error — and be strictly
    tighter than the fast path's designed bf16 rounding."""
    rng = np.random.default_rng(43)
    src = rng.integers(0, t, e).astype(np.int64)
    dst = rng.integers(0, n, e).astype(np.int64)
    x = rng.standard_normal((t, h), dtype=np.float32)
    plan = build_binned_plan(src, dst, n, t, group_row_target=1 << 14)
    out = np.asarray(run_binned(jnp.asarray(x), plan, interpret=True,
                                precision="exact"))
    ref = oracle_fp32(x, src, dst, n)
    np.testing.assert_allclose(out, ref, rtol=2e-6, atol=1e-5)
    if e >= 5000:
        # the fast path cannot meet the exact tolerance on this data —
        # guards against "exact" silently running the fast kernels
        fast = np.asarray(run_binned(jnp.asarray(x), plan, interpret=True))
        assert np.abs(fast - ref).max() > 10 * np.abs(out - ref).max()


def test_binned_exact_vjp():
    rng = np.random.default_rng(11)
    n, e, h = 300, 2000, 32
    src = rng.integers(0, n, e).astype(np.int64)
    dst = rng.integers(0, n, e).astype(np.int64)
    x = rng.standard_normal((n, h), dtype=np.float32)
    g = rng.standard_normal((n, h), dtype=np.float32)
    plans = ops.build_binned_plans(src, dst, n, n)
    _, vjp = jax.vjp(
        lambda x: ops.scatter_gather_binned(x, plans, True, "exact"), x)
    (gx,) = vjp(jnp.asarray(g))
    ref = oracle_fp32(g, dst, src, n)
    np.testing.assert_allclose(np.asarray(gx), ref, rtol=2e-6, atol=1e-5)


def test_binned_exact_sharded_matches_xla():
    """The sharded (halo) binned path must honor precision='exact': losses
    match the single-device fp32 xla run to reassociation error, tighter
    than the fast path's bf16 rounding could."""
    from roc_tpu.graph import datasets
    from roc_tpu.models import build_gcn
    from roc_tpu.parallel.spmd import SpmdTrainer
    from roc_tpu.train.config import Config
    from roc_tpu.train.driver import Trainer

    ds = datasets.synthetic("bx", 300, 5.0, 10, 4, n_train=60, n_val=60,
                            n_test=60, seed=13)
    layers = [10, 8, 4]
    base = dict(layers=layers, num_epochs=3, dropout_rate=0.0,
                eval_every=10**9)
    t1 = Trainer(Config(**base), ds, build_gcn(layers, 0.0))
    tb = SpmdTrainer(Config(**base, num_parts=4, halo=True,
                            aggregate_backend="binned",
                            aggregate_precision="exact"), ds,
                     build_gcn(layers, 0.0))
    assert tb.gdata.backend == "binned"
    for i in range(3):
        l1, lb = float(t1.run_epoch()), float(tb.run_epoch())
        np.testing.assert_allclose(lb, l1, rtol=2e-5, err_msg=f"epoch {i}")


def test_binned_rejects_unknown_precision():
    """Same rule as matmul_precision: a silent fallthrough to fast would
    drop the fp32-exact guarantee."""
    src = np.array([0], np.int64)
    dst = np.array([1], np.int64)
    plan = build_binned_plan(src, dst, 8, 8, group_row_target=1 << 14)
    x = jnp.ones((8, 8), jnp.float32)
    with pytest.raises(ValueError, match="precision"):
        run_binned(x, plan, interpret=True, precision="highest")


def test_binned_exact_degrades_to_fast_for_bf16_input():
    """A bf16 input makes exact == fast; run_binned must take the cheap
    path (same staging dtype) rather than pay 3x dots for nothing."""
    from roc_tpu.ops.pallas import binned as B
    rng = np.random.default_rng(12)
    n, e, h = 256, 1000, 16
    src = rng.integers(0, n, e).astype(np.int64)
    dst = rng.integers(0, n, e).astype(np.int64)
    x = jnp.asarray(rng.standard_normal((n, h), dtype=np.float32)
                    ).astype(jnp.bfloat16)
    plan = B.build_binned_plan(src, dst, n, n, group_row_target=1 << 14)
    out_e = run_binned(x, plan, interpret=True, precision="exact")
    out_f = run_binned(x, plan, interpret=True, precision="fast")
    assert out_e.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(out_e, np.float32),
                               np.asarray(out_f, np.float32))


def test_binned_vjp_is_transposed_aggregation():
    rng = np.random.default_rng(7)
    n, e, h = 300, 2000, 32
    src = rng.integers(0, n, e).astype(np.int64)
    dst = rng.integers(0, n, e).astype(np.int64)
    x = rng.standard_normal((n, h), dtype=np.float32)
    g = rng.standard_normal((n, h), dtype=np.float32)
    plans = ops.build_binned_plans(src, dst, n, n)

    _, vjp = jax.vjp(lambda x: ops.scatter_gather_binned(x, plans, True), x)
    (gx,) = vjp(jnp.asarray(g))
    ref = _oracle_bf16(g, dst, src, n)   # grad_x = A^T @ g
    np.testing.assert_allclose(np.asarray(gx), ref, rtol=1e-5, atol=1e-3)


def test_binned_backend_resolution():
    from roc_tpu.train.driver import resolve_backend
    assert resolve_backend("pallas", 10) == "binned"
    assert resolve_backend("binned", 10) == "binned"
    assert resolve_backend("matmul", 10) == "matmul"


def test_binned_in_trainer():
    """End-to-end: the GCN trains with the binned backend and matches the
    xla backend to bf16-rounding tolerance on the first epoch loss."""
    from roc_tpu.graph import datasets
    from roc_tpu.models import build_gcn
    from roc_tpu.train.config import Config
    from roc_tpu.train.driver import Trainer

    ds = datasets.synthetic("binned-e2e", 600, 6.0, 32, 5,
                            n_train=200, n_val=100, n_test=100, seed=3)
    losses = {}
    for backend in ("xla", "binned"):
        cfg = Config(layers=[32, 16, 5], num_epochs=1, dropout_rate=0.0,
                     eval_every=10 ** 9, aggregate_backend=backend, seed=11)
        tr = Trainer(cfg, ds, build_gcn(cfg.layers, 0.0))
        losses[backend] = float(tr.run_epoch())
    assert np.isfinite(losses["binned"])
    assert abs(losses["binned"] - losses["xla"]) < 1e-2 * max(
        abs(losses["xla"]), 1.0)


@pytest.mark.parametrize("backend", ["binned", "matmul"])
def test_plan_backend_avg_matches_xla(backend):
    """avg rides the plan backends as sum / in-degree; it must match the
    xla segment-avg oracle (GraphSAGE-mean's aggregation) on both the
    single-device and the sharded path."""
    from roc_tpu.graph import datasets
    from roc_tpu.models import build_sage
    from roc_tpu.parallel.spmd import SpmdTrainer
    from roc_tpu.train.config import Config
    from roc_tpu.train.driver import Trainer, dense_graph_data, make_gctx

    ds = datasets.synthetic("avg-fast", 900, 5.0, 16, 4,
                            n_train=300, n_val=100, n_test=100, seed=9)
    # op-level: aggregate(x, "avg") vs the xla oracle
    g = ds.graph
    x = jnp.asarray(np.random.default_rng(1).standard_normal(
        (g.num_nodes, 16), dtype=np.float32))
    want = np.asarray(ops.scatter_gather(
        x, jnp.asarray(g.col_idx, jnp.int32), jnp.asarray(g.dst_idx,
                                                          jnp.int32),
        g.num_nodes, "avg"))
    gctx = make_gctx(dense_graph_data(g, backend), g.num_nodes)
    got = np.asarray(gctx.aggregate(x, "avg"))
    tol = 5e-2 if backend == "binned" else 1e-3    # one bf16 rounding
    np.testing.assert_allclose(got, want, rtol=tol, atol=tol)

    # end-to-end: SAGE-mean trains on the plan backend and tracks xla
    losses = {}
    for b in ("xla", backend):
        cfg = Config(layers=[16, 8, 4], num_epochs=1, dropout_rate=0.0,
                     eval_every=10 ** 9, aggregate_backend=b, seed=5,
                     num_parts=4, halo=True)
        tr = SpmdTrainer(cfg, ds, build_sage(cfg.layers, 0.0))
        assert b == "xla" or tr.gdata.backend == backend
        losses[b] = float(tr.run_epoch())
    assert abs(losses[backend] - losses["xla"]) < 1e-2 * max(
        abs(losses["xla"]), 1.0)


def test_native_plan_equals_numpy():
    """The C++ counting-sort plan builder must match the NumPy oracle bit
    for bit (same invariant style as the native halo/chunk builders)."""
    from roc_tpu import native
    from roc_tpu.ops.pallas.binned import _build_binned_plan_numpy
    if not native.available():
        pytest.skip("native library unavailable")
    rng = np.random.default_rng(13)
    for (n, t, e) in [(700, 700, 5000), (1500, 2000, 30000),
                      (100, 100, 0), (513, 513, 1), (5000, 4000, 120000),
                      # partial last group: num_bins=3, bpg=2, G=2 — the
                      # phantom-bin placeholder path in both builders
                      (3 * 512, 1000, 3000)]:
        src = rng.integers(0, t, e).astype(np.int64)
        dst = rng.integers(0, n, e).astype(np.int64)
        if e > 100:
            dst[: e // 4] = 7
        tgt = 2000 if n == 3 * 512 else 1 << 14
        ref = _build_binned_plan_numpy(src, dst, n, t, tgt)
        (p1_srcl, p1_off, p1_blk, p2_dstl, p2_obi, p2_first,
         bpg) = native.binned_plan(src, dst, n, t, tgt)
        assert bpg == ref.bins_per_group
        G, C1 = p1_blk.shape
        np.testing.assert_array_equal(
            p1_srcl.reshape(G, C1 * 2048, 1), np.asarray(ref.p1_srcl))
        np.testing.assert_array_equal(p1_off, np.asarray(ref.p1_off))
        np.testing.assert_array_equal(p1_blk, np.asarray(ref.p1_blk))
        C2 = p2_obi.shape[1]
        np.testing.assert_array_equal(
            p2_dstl.reshape(G, C2 * 4096, 1), np.asarray(ref.p2_dstl))
        np.testing.assert_array_equal(p2_obi, np.asarray(ref.p2_obi))
        np.testing.assert_array_equal(p2_first, np.asarray(ref.p2_first))


def test_native_plan_equals_numpy_nondefault_geometry():
    """The geometry-parametric native builder (roc_binned_plan_*_g) must
    match the NumPy oracle bit for bit at the sparse presets too."""
    from roc_tpu import native
    from roc_tpu.ops.pallas import binned as B
    if not native.available():
        pytest.skip("native library unavailable")
    rng = np.random.default_rng(17)
    for geom in (B.GEOM_MID, B.GEOM_SPARSE, B.GEOM_XSPARSE):
        for (n, t, e) in [(700, 700, 5000), (3 * geom.rb, 1000, 3000),
                          (5000, 4000, 120000), (100, 100, 0)]:
            src = rng.integers(0, t, e).astype(np.int64)
            dst = rng.integers(0, n, e).astype(np.int64)
            if e > 100:
                dst[: e // 4] = 7
            tgt = 1 << 14
            ref = B._build_binned_plan_numpy(src, dst, n, t, tgt, geom)
            (p1_srcl, p1_off, p1_blk, p2_dstl, p2_obi, p2_first,
             bpg) = native.binned_plan(src, dst, n, t, tgt, geom)
            msg = f"geom={geom} n={n} t={t} e={e}"
            assert bpg == ref.bins_per_group, msg
            G, C1 = p1_blk.shape
            C2 = p2_obi.shape[1]
            np.testing.assert_array_equal(
                p1_srcl.reshape(G, C1 * geom.ch, 1),
                np.asarray(ref.p1_srcl), err_msg=msg)
            np.testing.assert_array_equal(p1_off, np.asarray(ref.p1_off),
                                          err_msg=msg)
            np.testing.assert_array_equal(p1_blk, np.asarray(ref.p1_blk),
                                          err_msg=msg)
            np.testing.assert_array_equal(
                p2_dstl.reshape(G, C2 * geom.ch2, 1),
                np.asarray(ref.p2_dstl), err_msg=msg)
            np.testing.assert_array_equal(p2_obi, np.asarray(ref.p2_obi),
                                          err_msg=msg)
            np.testing.assert_array_equal(p2_first, np.asarray(ref.p2_first),
                                          err_msg=msg)


@pytest.mark.parametrize("halo", [False, True])
def test_binned_sharded_matches_xla(halo):
    """Sharded binned plans (stacked per-shard, common static geometry)
    must train equal to the sharded xla path up to the designed bf16
    rounding — both halo and all-gather exchange modes."""
    from roc_tpu.graph import datasets
    from roc_tpu.models import build_gcn
    from roc_tpu.parallel.spmd import SpmdTrainer
    from roc_tpu.train.config import Config

    ds = datasets.synthetic("bs", 220, 4.0, 8, 4, n_train=40, n_val=40,
                            n_test=40, seed=3)
    base = dict(layers=[8, 8, 4], num_epochs=2, dropout_rate=0.0,
                eval_every=10 ** 9, num_parts=4, halo=halo,
                edge_shard="off")
    tx = SpmdTrainer(Config(**base), ds, build_gcn(base["layers"], 0.0))
    tb = SpmdTrainer(Config(**base, aggregate_backend="binned"), ds,
                     build_gcn(base["layers"], 0.0))
    # halo_overlap (default on) stores the split pair instead of `plans`
    assert tb.gdata.backend == "binned" and (
        tb.gdata.plans is not None or tb.gdata.plans_local is not None)
    for i in range(2):
        lx, lb = float(tx.run_epoch()), float(tb.run_epoch())
        np.testing.assert_allclose(lb, lx, rtol=5e-3, err_msg=f"epoch {i}")


def test_pad_binned_plans_floors():
    """pad_binned_plans must honor (C1, C2) floors — the perhost path
    passes allgathered global maxima so every process compiles the same
    program — and padded plans must still produce correct sums."""
    rng = np.random.default_rng(3)
    n, t, h = 400, 400, 16
    shard_plans, xs, refs = [], [], []
    for e in (900, 4000):   # different edge counts -> different C1/C2
        src = rng.integers(0, t, e).astype(np.int64)
        dst = rng.integers(0, n, e).astype(np.int64)
        x = rng.standard_normal((t, h), dtype=np.float32)
        shard_plans.append(ops.build_binned_plans(src, dst, n, t))
        xs.append(x)
        refs.append(oracle_bf16(x, src, dst, n))
    stacked = ops.pad_binned_plans(shard_plans, min_fwd=(64, 9),
                                   min_bwd=(64, 9))
    assert stacked.fwd.p1_blk.shape[1:] == (
        shard_plans[0].fwd.p1_blk.shape[0], 64)
    assert stacked.fwd.p2_obi.shape[2] >= 9
    for i in range(2):
        one = jax.tree.map(lambda a: a[i], stacked)
        out = np.asarray(ops.scatter_gather_binned(
            jnp.asarray(xs[i]), one, True))
        np.testing.assert_allclose(out, refs[i], rtol=1e-5, atol=1e-3)


def test_auto_binned_selection(monkeypatch):
    """With AUTO_BINNED on (the hardware flip), auto picks binned exactly
    when the cell-occupancy criterion holds — dense-enough graphs yes,
    huge sparse ones no."""
    import roc_tpu.train.driver as drv
    from roc_tpu.ops.pallas.binned import binned_viable

    monkeypatch.setattr(drv, "AUTO_BINNED", True)
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    # Reddit-shape: viable (measured case)
    assert binned_viable(232_965, 232_965, 23_526_267)
    assert drv.resolve_backend("auto", 23_526_267, 232_965,
                               232_965) == "binned"
    # products-shape: not viable (measured ~5x padding)
    assert not binned_viable(2_449_029, 2_449_029, 124_000_000)
    assert drv.resolve_backend("auto", 124_000_000, 2_449_029,
                               2_449_029) == "matmul"
    # small graphs stay on xla regardless
    assert drv.resolve_backend("auto", 1000, 500, 500) == "xla"


def test_auto_binned_shard_level_refinement(monkeypatch):
    """When the global viability check fails but the per-shard halo table
    is dense (locality-heavy partitions, small K), the SPMD trainer must
    upgrade auto->matmul to binned at shard geometry."""
    import roc_tpu.train.driver as drv
    from roc_tpu.graph.csr import add_self_edges, from_edges
    from roc_tpu.graph import datasets
    from roc_tpu.models import build_gcn
    from roc_tpu.parallel.spmd import SpmdTrainer
    from roc_tpu.ops.pallas.binned import binned_viable
    from roc_tpu.train.config import Config

    monkeypatch.setattr(drv, "AUTO_BINNED", True)
    monkeypatch.setattr(drv, "AUTO_MATMUL_EDGES", 1 << 10)
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    # the backend spoof above must not push the kernels out of interpret
    # mode on the CPU test platform
    monkeypatch.setattr(drv, "pallas_interpret", lambda: True)

    # 4 near-disjoint communities: global cells fail the bound, per-shard
    # (own rows + tiny halo) cells pass it
    n, P_ = 16384, 4
    rng = np.random.default_rng(0)
    q = n // P_
    src = np.concatenate([rng.integers(i * q, (i + 1) * q, 15000)
                          for i in range(P_)])
    dst = np.concatenate([rng.integers(i * q, (i + 1) * q, 15000)
                          for i in range(P_)])
    keep = src != dst
    g = add_self_edges(from_edges(n, src[keep], dst[keep]))
    assert not binned_viable(n, n, g.num_edges)          # global: no
    ds = datasets.Dataset(
        name="comm", graph=g,
        features=rng.normal(size=(n, 8)).astype(np.float32),
        labels=None, label_ids=np.zeros(n, np.int64),
        mask=np.zeros(n, np.int32), in_dim=8, num_classes=4)
    cfg = Config(layers=[8, 8, 4], num_epochs=1, dropout_rate=0.0,
                 eval_every=10 ** 9, num_parts=P_, halo=True,
                 edge_shard="off")
    tr = SpmdTrainer(cfg, ds, build_gcn(cfg.layers, 0.0))
    assert tr.gdata.backend == "binned", tr.gdata.backend
    assert np.isfinite(float(tr.run_epoch()))


@pytest.mark.parametrize("geom_name", ["mid", "sparse", "xsparse"])
def test_binned_nondefault_geometry_matches_oracle(geom_name):
    """The sparse-graph geometry presets (VERDICT r3 item 3) must produce
    oracle-correct sums through the same kernels, fast and exact."""
    from roc_tpu.ops.pallas import binned as B
    geom = {"mid": B.GEOM_MID, "sparse": B.GEOM_SPARSE,
            "xsparse": B.GEOM_XSPARSE}[geom_name]
    rng = np.random.default_rng(21)
    for (n, t, e, h) in [(700, 700, 5000, 64),
                         (1500, 2000, 12000, 41),    # lane-unaligned H,
                         (100, 100, 0, 16),          # multi-group (tgt 4k)
                         (geom.sb + 1, geom.sb + 1, 300, 16),
                         (3 * geom.rb, 1000, 3000, 16)]:
        src = rng.integers(0, t, e).astype(np.int64)
        dst = rng.integers(0, n, e).astype(np.int64)
        x = rng.standard_normal((t, h), dtype=np.float32)
        plan = B.build_binned_plan(src, dst, n, t,
                                   group_row_target=1 << 12, geom=geom)
        assert plan.geom == geom
        out = np.asarray(run_binned(jnp.asarray(x), plan, interpret=True))
        np.testing.assert_allclose(
            out, oracle_bf16(x, src, dst, n), rtol=1e-5, atol=1e-3,
            err_msg=f"{geom_name}: n={n} t={t} e={e} h={h}")
        out_e = np.asarray(run_binned(jnp.asarray(x), plan, interpret=True,
                                      precision="exact"))
        np.testing.assert_allclose(
            out_e, oracle_fp32(x, src, dst, n), rtol=2e-6, atol=1e-5,
            err_msg=f"{geom_name} exact: n={n} t={t} e={e} h={h}")


def test_pad_binned_plan_preserves_geometry():
    from roc_tpu.ops.pallas import binned as B
    rng = np.random.default_rng(22)
    n, e = 3 * B.GEOM_SPARSE.rb, 4000
    src = rng.integers(0, n, e).astype(np.int64)
    dst = rng.integers(0, n, e).astype(np.int64)
    x = rng.standard_normal((n, 16), dtype=np.float32)
    plan = B.build_binned_plan(src, dst, n, n, group_row_target=1 << 14,
                               geom=B.GEOM_SPARSE)
    padded = B.pad_binned_plan(plan, plan.p1_blk.shape[1] + 8,
                               plan.p2_obi.shape[1] + 3)
    assert padded.geom == B.GEOM_SPARSE
    out = np.asarray(run_binned(jnp.asarray(x), padded, interpret=True))
    np.testing.assert_allclose(out, oracle_bf16(x, src, dst, n),
                               rtol=1e-5, atol=1e-3)


def test_choose_geometry_policy():
    """The stats-based policy (calibrated cost model, docs/PERF.md numbers):
    dense graphs keep a dense-window geometry; uniform sparse at products
    density correctly prefers matmul; the SAME density with community
    locality (the partitioner's output order) gets a binned geometry —
    the uniform bound could never see that difference."""
    from roc_tpu.ops.pallas import binned as B
    rng = np.random.default_rng(5)

    # dense: Reddit-like occupancy at small scale.  The chosen slot must
    # be the hardware sweep's winner (128): at equal padded rows the
    # smaller-slot presets pay the per-slot-DMA term the sweep measured
    # (docs/PERF.md SLOT 32 -> 128 = -19.3 ms), which the model must
    # reproduce or it mis-ranks presets on every dense graph.
    n, e = 2048, 200_000
    src = rng.integers(0, n, e).astype(np.int64)
    dst = rng.integers(0, n, e).astype(np.int64)
    g, t = B.choose_geometry(src, dst, n, n)
    assert g is not None and g.slot == 128, (g, t)

    # uniform products-density: ~13 edges per (512,512) cell.  The refit
    # model prices the matmul backend's per-VB-window >=1-chunk floor
    # (segment_sum.build_chunk_plan — ceil(100k/8) = 12.5k chunks here
    # REGARDLESS of edge count, the products-shape matmul pathology), so
    # even uniform sparse now beats it — either on a sparse-window preset
    # (small slots) or, since round 8, on a FLAT preset whose 8-row cell
    # granularity removes slot padding outright.  The round-2 model,
    # floorless, pinned matmul here.
    n, e = 100_000, 500_000
    src = rng.integers(0, n, e).astype(np.int64)
    dst = rng.integers(0, n, e).astype(np.int64)
    g_u, t_u = B.choose_geometry(src, dst, n, n)
    assert g_u is not None and (g_u.flat or g_u.slot <= 32), (g_u, t_u)
    assert t_u < B._matmul_cost(e, n), (t_u, B._matmul_cost(e, n))

    # same density, block-diagonal communities: cells concentrate on the
    # diagonal, the model credits the untouched cells, and the modeled
    # time drops further
    q, k = 512, 100_000 // 512 + 1
    comm = rng.integers(0, k, 500_000) * q
    src = (comm + rng.integers(0, q, 500_000)).astype(np.int64)
    dst = (comm + rng.integers(0, q, 500_000)).astype(np.int64)
    g_c, t_c = B.choose_geometry(src, dst, k * q, k * q)
    assert g_c is not None and t_c < t_u, (g_c, t_c, t_u)


def test_resolve_backend_uses_stats(monkeypatch):
    """resolve_backend with edge arrays routes through choose_geometry:
    community-local graphs upgrade to binned even where the uniform bound
    says no."""
    import roc_tpu.train.driver as drv
    from roc_tpu.ops.pallas.binned import binned_viable

    monkeypatch.setattr(drv, "AUTO_BINNED", True)
    monkeypatch.setattr(drv, "AUTO_MATMUL_EDGES", 1 << 10)
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    rng = np.random.default_rng(6)
    q, k, e = 512, 64, 300_000
    n = q * k
    comm = rng.integers(0, k, e) * q
    src = (comm + rng.integers(0, q, e)).astype(np.int64)
    dst = (comm + rng.integers(0, q, e)).astype(np.int64)
    assert not binned_viable(n, n, e)               # uniform bound: no
    assert drv.resolve_backend("auto", e, n, n) == "matmul"
    assert drv.resolve_backend("auto", e, n, n, src, dst) == "binned"


def test_sweep_products_configs_match_presets():
    """tools/sweep_binned.py hardcodes the preset tuples so its parent
    process never imports jax (subprocess isolation); this pin fails if a
    preset retune forgets that mirror."""
    import importlib.util
    import os as _os
    from roc_tpu.ops.pallas import binned as B
    spec = importlib.util.spec_from_file_location(
        "sweep_binned", _os.path.join(_os.path.dirname(__file__), "..",
                                      "tools", "sweep_binned.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    want = [tuple(g)[:5] + (g.grt or B._GROUP_ROW_TARGET, 0)
            for g in (B.GEOM_MID, B.GEOM_MID_WIDE, B.GEOM_SPARSE,
                      B.GEOM_SPARSE_WIDE, B.GEOM_XSPARSE)]
    # flat A/B leg: GEOM_FLAT_SPARSE at the production group target,
    # paired against the same-shape GEOM_SPARSE row above
    want.append(tuple(B.GEOM_FLAT_SPARSE)[:5]
                + (B.GEOM_FLAT_SPARSE.grt or B._GROUP_ROW_TARGET, 1))
    assert mod.CONFIGS_PRODUCTS == want, (mod.CONFIGS_PRODUCTS, want)


def test_binned_fuzz_plan_and_run():
    """Property fuzz: random geometries through both plan builders and the
    interpret-mode kernels must match the oracle (and each other)."""
    from roc_tpu import native
    from roc_tpu.ops.pallas.binned import _build_binned_plan_numpy

    rng = np.random.default_rng(2026)
    for trial in range(6):
        n = int(rng.integers(40, 3000))
        t = int(rng.integers(40, 3000))
        e = int(rng.integers(0, 25000))
        tgt = int(rng.integers(1 << 12, 1 << 16))
        src = rng.integers(0, t, e).astype(np.int64)
        dst = rng.integers(0, n, e).astype(np.int64)
        if e and trial % 2:
            dst[: e // 3] = int(rng.integers(0, n))   # random hub
        x = rng.standard_normal((t, 8), dtype=np.float32)
        plan = _build_binned_plan_numpy(src, dst, n, t, tgt)
        out = np.asarray(run_binned(jnp.asarray(x), plan, interpret=True))
        ref = oracle_bf16(x, src, dst, n)
        np.testing.assert_allclose(
            out, ref, rtol=1e-5, atol=1e-3,
            err_msg=f"trial {trial}: n={n} t={t} e={e} tgt={tgt}")
        if native.available():
            nat = native.binned_plan(src, dst, n, t, tgt)
            np.testing.assert_array_equal(nat[1], np.asarray(plan.p1_off),
                                          err_msg=f"trial {trial}")

def test_plan_steps_match_built_plans():
    """_plan_steps (the cost model's schedule predictor) must EXACTLY
    reproduce the built plan's grid shape.  It re-implements the builder
    arithmetic in O(cells); any drift silently mis-prices every candidate
    choose_geometry weighs, so this pin is what lets the grid-validation
    test below use model steps as build truth."""
    from roc_tpu.ops.pallas import binned as B
    rng = np.random.default_rng(7)
    shapes = [(3000, 40_000, 0), (20_000, 80_000, 0), (20_000, 80_000, 512)]
    for g in (B._default_geom(), B.GEOM_MID, B.GEOM_SPARSE_WIDE,
              B.GEOM_FLAT, B.GEOM_FLAT_SPARSE):
        for n, e, q in shapes:
            if q:                     # block-diagonal community locality
                comm = rng.integers(0, n // q, e) * q
                src = (comm + rng.integers(0, q, e)).astype(np.int64)
                dst = (comm + rng.integers(0, q, e)).astype(np.int64)
            else:
                src = rng.integers(0, n, e).astype(np.int64)
                dst = rng.integers(0, n, e).astype(np.int64)
            cblk, cbin, cnt = B._cell_stats(src, dst, g.sb, g.rb)
            padded, s1, s2 = B._plan_steps(cblk, cbin, cnt, g, n, n, e)
            plan = B.build_binned_plan(src, dst, n, n, geom=g)
            G, C1 = plan.p1_blk.shape
            C2 = plan.p2_obi.shape[1]
            assert (s1, s2) == (G * C1, G * C2), \
                (g, n, e, q, (s1, s2), (G * C1, G * C2))
            assert padded == B.padded_rows_for(src, dst, g)


def test_cost_model_grid_validation():
    """Tentpole check: across the CPU-reachable grid (two scales x three
    densities x {uniform, community-reordered}), choose_geometry must pick
    the measured-cheapest candidate — 'measured' meaning the calibrated
    cost model evaluated at the BUILD-TRUTH step counts of actually built
    plans (anchored to the builder by test_plan_steps_match_built_plans).
    >= 90% of grid cells must agree; a hybrid pick counts as agreeing when
    its base geometry is the pure winner."""
    from roc_tpu.ops.pallas import binned as B
    rng = np.random.default_rng(11)
    cands = [B._default_geom(), B.GEOM_WIDE, B.GEOM_MID, B.GEOM_MID_WIDE,
             B.GEOM_SPARSE, B.GEOM_SPARSE_WIDE, B.GEOM_XSPARSE,
             B.GEOM_FLAT, B.GEOM_FLAT_SPARSE]
    cells = []
    for n in (8192, 24576):
        for deg in (4, 16, 48):
            e = n * deg
            src = rng.integers(0, n, e).astype(np.int64)
            dst = rng.integers(0, n, e).astype(np.int64)
            cells.append((n, deg, "uniform", src, dst))
            q = 512
            comm = rng.integers(0, n // q, e) * q
            cells.append((n, deg, "reordered",
                          (comm + rng.integers(0, q, e)).astype(np.int64),
                          (comm + rng.integers(0, q, e)).astype(np.int64)))
    match, mismatches = 0, []
    for n, deg, order, src, dst in cells:
        truth = {}
        for g in cands:
            g = g.check()
            if B._vmem_bytes(g) > B._VMEM_BUDGET:
                continue
            plan = B.build_binned_plan(src, dst, n, n, geom=g)
            G, C1 = plan.p1_blk.shape
            C2 = plan.p2_obi.shape[1]
            truth[g] = B._binned_cost_model(
                B.padded_rows_for(src, dst, g), g,
                steps1=G * C1, steps2=G * C2)
        best_true = min(truth, key=truth.get)
        pick, _ = B.choose_geometry(src, dst, n, n, force=True)
        if pick is not None and pick._replace(hub_minc=0) == best_true:
            match += 1
        else:
            mismatches.append((n, deg, order, pick, best_true))
    assert match >= 0.9 * len(cells), (match, len(cells), mismatches)


def test_hybrid_forced_correctness():
    """Hybrid binned+matmul plan (hub_minc split), forced via an explicit
    geometry on a bimodal cell structure: one fat dense cell plus a dust
    spray of ~6-edge cells.  Both sides must contribute — fwd against the
    np.add.at oracle and the VJP against the transpose scatter, exactly
    (fp32 staging, 'exact' precision)."""
    from roc_tpu.ops import aggregate as A
    from roc_tpu.ops.pallas import binned as B
    rng = np.random.default_rng(1)
    n = 3000
    dsrc = rng.integers(0, 512, 4000)       # (block 0, bin 0): dense hub
    ddst = rng.integers(0, 512, 4000)
    tsrc = rng.integers(0, n, 200)          # dust over the whole grid
    tdst = rng.integers(0, n, 200)
    src = np.concatenate([dsrc, tsrc]).astype(np.int64)
    dst = np.concatenate([ddst, tdst]).astype(np.int64)
    g = B._default_geom()._replace(hub_minc=64)
    keep = B.split_hub_edges(src, dst, g)
    assert 0 < int(keep.sum()) < len(src)
    plans = A.build_binned_plans(src, dst, n, n, geom=(g, "auto"))
    assert plans.mm is not None

    h = 16
    x = rng.standard_normal((n, h), dtype=np.float32)
    out = A.scatter_gather_binned(jnp.asarray(x), plans, precision="exact",
                                  interpret=True)
    ref = np.zeros((n, h), np.float32)
    np.add.at(ref, dst, x[src])
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-5, atol=1e-4)

    w = rng.standard_normal((n, h), dtype=np.float32)
    gx = jax.grad(lambda xx: jnp.sum(
        A.scatter_gather_binned(xx, plans, precision="exact",
                                interpret=True) * w))(jnp.asarray(x))
    gref = np.zeros((n, h), np.float32)
    np.add.at(gref, src, w[dst])
    np.testing.assert_allclose(np.asarray(gx), gref, rtol=1e-5, atol=1e-4)


def test_choose_geometry_hybrid_arm():
    """The policy's hybrid arm: dust cells well under half a slot next to
    a heavy hub mass make the split win over both pure binned (dust slot
    padding) and pure matmul (the hub edges' chunk cost) — restricted to
    the dense default candidate so the sparse presets can't absorb the
    dust first.  The returned hub_minc must agree with split_hub_edges."""
    from roc_tpu.ops.pallas import binned as B
    rng = np.random.default_rng(2)
    n = 100_000
    g0 = B._default_geom()
    nblk, nbin = -(-n // g0.sb), -(-n // g0.rb)
    cells = rng.permutation(nblk * nbin)
    ds = np.repeat(cells // nbin, 10) * g0.sb \
        + rng.integers(0, g0.sb, cells.size * 10)
    dd = np.repeat(cells % nbin, 10) * g0.rb \
        + rng.integers(0, g0.rb, cells.size * 10)
    hub = cells[:40]
    he = 50_000
    hs = np.repeat(hub // nbin, he) * g0.sb + rng.integers(0, g0.sb, 40 * he)
    hd = np.repeat(hub % nbin, he) * g0.rb + rng.integers(0, g0.rb, 40 * he)
    src = np.clip(np.concatenate([ds, hs]), 0, n - 1)
    dst = np.clip(np.concatenate([dd, hd]), 0, n - 1)
    g, t = B.choose_geometry(src, dst, n, n, candidates=[g0])
    assert g is not None and g.hub_minc == g0.slot // 2, (g, t)
    assert t < B._matmul_cost(len(src), n)
    keep = B.split_hub_edges(src, dst, g)
    _, _, cnt = B._cell_stats(src, dst, g.sb, g.rb)
    assert int(keep.sum()) == int(cnt[cnt >= g.hub_minc].sum())
    # the full candidate list absorbs the dust with a sparse preset
    # instead — hybrid is the fallback when dense windows are forced
    g_full, t_full = B.choose_geometry(src, dst, n, n)
    assert g_full is not None and t_full <= t


def test_skewed_powerlaw_binned_selected_matches_xla():
    """Products-shape skewed synthetic (power-law out-degrees): the
    measured-stats policy must select binned over matmul, and the built
    plans must reproduce the XLA segment-sum backend exactly at 'exact'
    precision."""
    from roc_tpu.ops import aggregate as A
    from roc_tpu.ops.pallas import binned as B
    rng = np.random.default_rng(13)
    n = 20_000
    deg = np.minimum(rng.pareto(1.1, n) + 1, 500).astype(np.int64)
    dst = np.repeat(np.arange(n, dtype=np.int64), deg)
    src = rng.integers(0, n, dst.size).astype(np.int64)
    g, t = B.choose_geometry(src, dst, n, n)
    assert g is not None, (g, t)
    assert B.binned_viable(n, n, dst.size, src, dst)

    plans = A.build_binned_plans(src, dst, n, n, geom=(g, "auto"))
    h = 16
    x = rng.standard_normal((n, h), dtype=np.float32)
    out = A.scatter_gather_binned(jnp.asarray(x), plans, precision="exact",
                                  interpret=True)
    ref = jax.ops.segment_sum(jnp.asarray(x)[src], jnp.asarray(dst),
                              num_segments=n)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-4)


def test_plan_cache_roundtrip(tmp_path, monkeypatch):
    """Content-keyed on-disk plan cache: second build with identical
    inputs must come from the cache file (the builder is poisoned to
    prove it) and match the first plan field for field."""
    from roc_tpu.ops.pallas import binned as B
    monkeypatch.setenv("ROC_PLAN_CACHE_DIR", str(tmp_path))
    monkeypatch.setenv("ROC_PLAN_CACHE_MIN_EDGES", "0")
    rng = np.random.default_rng(3)
    n, e = 4000, 30_000
    src = rng.integers(0, n, e).astype(np.int64)
    dst = rng.integers(0, n, e).astype(np.int64)
    p1 = B.build_binned_plan(src, dst, n, n, geom=B.GEOM_MID)
    files = [f for f in tmp_path.iterdir() if f.suffix == ".npz"]
    assert len(files) == 1, files
    monkeypatch.setattr(B, "_build_binned_plan_numpy",
                        lambda *a, **k: pytest.fail("cache missed"))
    p2 = B.build_binned_plan(src, dst, n, n, geom=B.GEOM_MID)
    assert p2.geom == p1.geom == B.GEOM_MID
    assert p2.bins_per_group == p1.bins_per_group
    for f in ("p1_srcl", "p1_off", "p1_blk", "p2_dstl", "p2_obi",
              "p2_first"):
        np.testing.assert_array_equal(np.asarray(getattr(p1, f)),
                                      np.asarray(getattr(p2, f)), f)
    # a different geometry misses (key covers the schedule-shaping input)
    monkeypatch.setattr(B, "_build_binned_plan_numpy", _orig_numpy_builder)
    p3 = B.build_binned_plan(src, dst, n, n, geom=B.GEOM_SPARSE)
    assert p3.geom == B.GEOM_SPARSE
    assert len([f for f in tmp_path.iterdir() if f.suffix == ".npz"]) == 2


from roc_tpu.ops.pallas.binned import \
    _build_binned_plan_numpy as _orig_numpy_builder  # noqa: E402
