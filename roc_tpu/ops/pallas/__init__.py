from roc_tpu.ops.pallas.segment_sum import ChunkPlan, build_chunk_plan

__all__ = ["ChunkPlan", "build_chunk_plan"]
