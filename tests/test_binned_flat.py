"""Flat compacted chunk schedule + fused pipeline (ops/pallas/binned.py,
Geometry.flat) vs the slot-padded two-pass path and the oracles, in
interpret mode on CPU.  Hardware behavior: tests/test_tpu_hw.py.

Bit-equality tests use INTEGER-valued features and cotangents: small
integers survive the bf16 rounding and fp32 summation exactly, so the
flat schedule's different chunking (hence different fp32 add order) still
produces bit-identical sums.  Random fp32 data would differ at
reassociation level between the schedules — by design, same as chunk
order vs edge order in the two-pass path."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from roc_tpu import ops
from roc_tpu.ops.pallas import binned as B

# Small flat geometry for CPU interpret runs; slot rides along unused by
# the flat kernels but must keep the Geometry invariant (divides ch/ch2).
GF = B.Geometry(sb=256, ch=512, slot=128, rb=256, ch2=512, grt=1 << 14,
                flat=1)
GF2 = GF._replace(flat=0)           # the slot-padded control at same shape
GFB = GF._replace(unit=16)          # bf16-staging variant (16-row units)

CASES = [
    # (num_rows, table_rows, num_edges, hidden)
    (700, 700, 5000, 64),
    (1500, 2000, 30000, 64),    # multi-group, table != out rows
    (100, 100, 0, 64),          # empty edge list
    (GF.sb + 1, GF.sb + 1, 300, 16),    # two source blocks
    (3 * GF.rb, 1000, 3000, 16),        # partial last bin group
    (700, 700, 5000, 41),       # lane-unaligned H (GCN output layer)
]


def _int_graph(n, t, e, h, seed):
    rng = np.random.default_rng(seed)
    src = rng.integers(0, t, e).astype(np.int64)
    dst = rng.integers(0, n, e).astype(np.int64)
    if e > 100:
        dst[: e // 4] = 7       # hub destination spanning many chunks
    x = rng.integers(-4, 5, (t, h)).astype(np.float32)
    return src, dst, x


def _oracle_int(x, src, dst, n):
    out = np.zeros((n, x.shape[1]), np.float32)
    np.add.at(out, dst, x[src])
    return out


@pytest.mark.parametrize("n,t,e,h", CASES)
@pytest.mark.parametrize("fuse", [False, True])
def test_flat_bit_equals_twopass_and_oracle(n, t, e, h, fuse, monkeypatch):
    """Flat schedule (both the fused pipeline and the scan fallback) must
    be BIT-identical to the existing two-pass path and the add.at oracle
    on integer data, fwd, at every case incl. lane-unaligned H=41."""
    if not fuse:
        monkeypatch.setenv("ROC_BINNED_NO_FUSE", "1")
    src, dst, x = _int_graph(n, t, e, h, 42)
    pf = B.build_binned_plan(src, dst, n, t, geom=GF)
    pt = B.build_binned_plan(src, dst, n, t, geom=GF2)
    if fuse:
        assert pf.f_meta is not None    # small cases must fuse
    out_f = np.asarray(B.run_binned(jnp.asarray(x), pf, interpret=True))
    out_t = np.asarray(B.run_binned(jnp.asarray(x), pt, interpret=True))
    np.testing.assert_array_equal(out_f, out_t)
    np.testing.assert_array_equal(out_f, _oracle_int(x, src, dst, n))
    # exact precision rides the same flat schedule
    out_e = np.asarray(B.run_binned(jnp.asarray(x), pf, interpret=True,
                                    precision="exact"))
    np.testing.assert_array_equal(out_e, _oracle_int(x, src, dst, n))


@pytest.mark.parametrize("fuse", [False, True])
def test_flat_bf16_unit_bit_equals_oracle(fuse, monkeypatch):
    """unit=16 flat plans stage in bf16 (16-row Mosaic tiles, half the
    staging-DMA bytes): primary and secondary chunk rows are disjoint, so
    every staged row is rounded to bf16 exactly once, and small-integer
    data survives that rounding — both run paths must stay BIT-identical
    to the add.at oracle, exactly like the fp32-staged flat plan."""
    if not fuse:
        monkeypatch.setenv("ROC_BINNED_NO_FUSE", "1")
    for n, t, e, h in [(700, 700, 5000, 64), (GF.sb + 1, GF.sb + 1, 300, 16),
                       (700, 700, 5000, 41)]:
        src, dst, x = _int_graph(n, t, e, h, 42)
        pb = B.build_binned_plan(src, dst, n, t, geom=GFB)
        assert pb.geom.unit == 16
        assert B.staging_dtype(pb.geom, False) == jnp.bfloat16
        if fuse:
            assert pb.f_meta is not None
        out = np.asarray(B.run_binned(jnp.asarray(x), pb, interpret=True))
        np.testing.assert_array_equal(out, _oracle_int(x, src, dst, n),
                                      err_msg=f"n={n} t={t} e={e} h={h}")


def test_flat_bf16_unit_rejects_exact():
    """precision='exact' contracts fp32 staging; a unit=16 plan can't
    provide it, and silently widening would desync gbuf/DMA dtypes — so
    run_binned must refuse."""
    src = np.array([0, 1], np.int64)
    dst = np.array([1, 0], np.int64)
    plan = B.build_binned_plan(src, dst, 32, 32, geom=GFB)
    with pytest.raises(ValueError, match="exact"):
        B.run_binned(jnp.ones((32, 16), jnp.float32), plan, interpret=True,
                     precision="exact")


def test_flat_bf16_staging_bytes_pin():
    """bf16-storage acceptance pin (same reddit_scaled shape as the
    kernel-budget gate): GEOM_FLAT_BF16 must move <= 0.6x GEOM_FLAT's
    predicted staging-DMA bytes.  Not a clean 0.5: the 16-row unit pads
    every touched cell to twice the fp32 unit's rows (~0.50 measured on
    this shape)."""
    n, e = 32768, 4_194_304
    rng = np.random.default_rng(0)
    src = rng.integers(0, n, e).astype(np.int64)
    dst = rng.integers(0, n, e).astype(np.int64)
    b32 = B.staging_bytes_for(src, dst, B.GEOM_FLAT)
    b16 = B.staging_bytes_for(src, dst, B.GEOM_FLAT_BF16)
    assert b16 <= 0.6 * b32, (b16, b32, b16 / b32)


def test_flat_bwd_bit_equals_twopass_and_oracle():
    """VJP through the flat plans (integer cotangents) == the two-pass
    VJP == the transpose scatter, bitwise."""
    n, e, h = 900, 7000, 32
    src, dst, x = _int_graph(n, n, e, h, 7)
    g = np.random.default_rng(8).integers(-3, 4, (n, h)).astype(np.float32)
    plans_f = ops.build_binned_plans(src, dst, n, n, geom=GF)
    plans_t = ops.build_binned_plans(src, dst, n, n, geom=GF2)
    assert plans_f.fwd.geom == GF and plans_f.bwd.geom == GF
    gx = {}
    for name, plans in (("flat", plans_f), ("twopass", plans_t)):
        y, vjp = jax.vjp(
            lambda xx, p=plans: ops.scatter_gather_binned(xx, p, True),
            jnp.asarray(x))
        (gxi,) = vjp(jnp.asarray(g))
        gx[name] = np.asarray(gxi)
        np.testing.assert_array_equal(np.asarray(y),
                                      _oracle_int(x, src, dst, n), name)
    np.testing.assert_array_equal(gx["flat"], gx["twopass"])
    np.testing.assert_array_equal(gx["flat"], _oracle_int(g, dst, src, n))


def test_fused_bitwise_matches_flat_twopass_random_fp32(monkeypatch):
    """The fused pipeline replays the SAME per-chunk math as the flat
    two-pass scan (one-hot dots over identical chunks), so the two must
    agree bitwise even on random fp32 data — any divergence means the
    interleaved schedule visited chunks in a different per-bin order."""
    rng = np.random.default_rng(5)
    n, e, h = 1100, 20000, 48
    src = rng.integers(0, n, e).astype(np.int64)
    dst = rng.integers(0, n, e).astype(np.int64)
    x = rng.standard_normal((n, h), dtype=np.float32)
    plan = B.build_binned_plan(src, dst, n, n, geom=GF)
    assert plan.f_meta is not None
    out_fused = np.asarray(B.run_binned(jnp.asarray(x), plan,
                                        interpret=True))
    monkeypatch.setenv("ROC_BINNED_NO_FUSE", "1")
    out_scan = np.asarray(B.run_binned(jnp.asarray(x), plan,
                                       interpret=True))
    np.testing.assert_array_equal(out_fused, out_scan)


def test_flat_sharded_bit_equals_single_device():
    """Stacked flat shard plans (fused lists stripped at stacking — one
    static program across shards) must reproduce the per-shard
    single-device flat results bitwise on integer data."""
    rng = np.random.default_rng(3)
    n, t, h = 400, 400, 16
    shard_plans, xs, refs = [], [], []
    for e in (900, 4000):
        src = rng.integers(0, t, e).astype(np.int64)
        dst = rng.integers(0, n, e).astype(np.int64)
        x = rng.integers(-4, 5, (t, h)).astype(np.float32)
        shard_plans.append(ops.build_binned_plans(src, dst, n, t, geom=GF))
        xs.append(x)
        refs.append(_oracle_int(x, src, dst, n))
    stacked = ops.pad_binned_plans(shard_plans)
    # fused step lists bake in per-shard chunk counts -> must be stripped
    assert stacked.fwd.f_meta is None and stacked.bwd.f_meta is None
    assert stacked.fwd.geom == GF
    for i in range(2):
        one = jax.tree.map(lambda a: a[i], stacked)
        out = np.asarray(ops.scatter_gather_binned(
            jnp.asarray(xs[i]), one, True))
        np.testing.assert_array_equal(out, refs[i], err_msg=f"shard {i}")


def test_flat_padded_plan_bit_equal():
    """pad_binned_plan on a flat plan: padded chunks are exact no-ops
    (srcl -1 one-hot rows, dstl RB masks), so outputs stay bit-identical."""
    src, dst, x = _int_graph(3 * GF.rb, 1000, 3000, 16, 9)
    plan = B.build_binned_plan(src, dst, 3 * GF.rb, 1000, geom=GF)
    padded = B.pad_binned_plan(plan, plan.p1_blk.shape[1] + 8,
                               plan.p2_obi.shape[1] + 3)
    assert padded.geom == GF
    a = np.asarray(B.run_binned(jnp.asarray(x), plan, interpret=True))
    b = np.asarray(B.run_binned(jnp.asarray(x), padded, interpret=True))
    np.testing.assert_array_equal(a, b)


def test_flat_step_reduction_pin():
    """Tentpole acceptance pin (Reddit-scale shape, the kernel_budgets
    table's reddit_scaled row): GEOM_FLAT must predict >= 25% fewer total
    grid steps than the shipped SLOT=128 default, with pad1 <= 1.05."""
    n, e = 32768, 4_194_304
    rng = np.random.default_rng(0)
    src = rng.integers(0, n, e).astype(np.int64)
    dst = rng.integers(0, n, e).astype(np.int64)
    totals = {}
    for name, g in (("default", B._default_geom()), ("flat", B.GEOM_FLAT)):
        cb, cn, cnt = B._cell_stats(src, dst, g.sb, g.rb)
        padded, s1, s2 = B._plan_steps(cb, cn, cnt, g, n, n, e)
        totals[name] = s1 + s2
        if name == "flat":
            assert padded <= 1.05 * e, (padded, e)    # pad1 bound
    assert totals["flat"] <= 0.75 * totals["default"], totals


def test_flat_plan_steps_match_built_plans():
    """_plan_steps must EXACTLY reproduce the flat builder's grid shape
    (same pin as the two-pass schedules — any drift mis-prices every flat
    candidate choose_geometry weighs)."""
    rng = np.random.default_rng(7)
    for g in (GF, B.GEOM_FLAT_SPARSE):
        for n, e in ((3000, 40_000), (20_000, 80_000)):
            src = rng.integers(0, n, e).astype(np.int64)
            dst = rng.integers(0, n, e).astype(np.int64)
            cblk, cbin, cnt = B._cell_stats(src, dst, g.sb, g.rb)
            padded, s1, s2 = B._plan_steps(cblk, cbin, cnt, g, n, n, e)
            plan = B.build_binned_plan(src, dst, n, n, geom=g)
            G, C1 = plan.p1_blk.shape
            C2 = plan.p2_obi.shape[1]
            assert (s1, s2) == (G * C1, G * C2), \
                (g, n, e, (s1, s2), (G * C1, G * C2))
            assert padded == B.padded_rows_for(src, dst, g)


def test_native_flat_plan_equals_numpy():
    """The C++ flat builder must match the NumPy flat oracle bit for bit
    (chunk packing, run-list DMA metadata, and the phase-2 layout)."""
    from roc_tpu import native
    if not native.available():
        pytest.skip("native library unavailable")
    rng = np.random.default_rng(13)
    for geom in (GF, B.GEOM_FLAT_SPARSE._replace(grt=1 << 14), GFB):
        for (n, t, e) in [(700, 700, 5000), (3 * geom.rb, 1000, 3000),
                          (5000, 4000, 120000), (100, 100, 0)]:
            src = rng.integers(0, t, e).astype(np.int64)
            dst = rng.integers(0, n, e).astype(np.int64)
            if e > 100:
                dst[: e // 4] = 7
            ref = B._build_flat_plan_numpy(src, dst, n, t, 1 << 14, geom)
            (p1_srcl, p1_blk, p1_blk2, p1_dsrc, p1_ddst, p2_dstl, p2_obi,
             p2_first, bpg) = native.binned_flat_plan(
                 src, dst, n, t, 1 << 14, geom)
            msg = f"geom={geom} n={n} t={t} e={e}"
            assert bpg == ref.bins_per_group, msg
            G, C1 = p1_blk.shape
            C2 = p2_obi.shape[1]
            np.testing.assert_array_equal(
                p1_srcl.reshape(G, C1 * geom.ch, 1),
                np.asarray(ref.p1_srcl), err_msg=msg)
            for f, got in (("p1_blk", p1_blk), ("p1_blk2", p1_blk2),
                           ("p2_obi", p2_obi), ("p2_first", p2_first)):
                np.testing.assert_array_equal(
                    got, np.asarray(getattr(ref, f)), err_msg=f"{msg} {f}")
            np.testing.assert_array_equal(
                p1_dsrc.reshape(G, C1, geom.kd), np.asarray(ref.p1_dsrc),
                err_msg=msg)
            np.testing.assert_array_equal(
                p1_ddst.reshape(G, C1, geom.kd), np.asarray(ref.p1_ddst),
                err_msg=msg)
            np.testing.assert_array_equal(
                p2_dstl.reshape(G, C2 * geom.ch2, 1),
                np.asarray(ref.p2_dstl), err_msg=msg)


def test_flat_plan_cache_roundtrip(tmp_path, monkeypatch):
    """Flat plans round-trip the content-keyed cache: every schedule array
    is restored, and the fused step list (deliberately NOT cached) is
    rebuilt identically by _attach_fused at load."""
    monkeypatch.setenv("ROC_PLAN_CACHE_DIR", str(tmp_path))
    monkeypatch.setenv("ROC_PLAN_CACHE_MIN_EDGES", "0")
    rng = np.random.default_rng(3)
    n, e = 4000, 30_000
    src = rng.integers(0, n, e).astype(np.int64)
    dst = rng.integers(0, n, e).astype(np.int64)
    p1 = B.build_binned_plan(src, dst, n, n, geom=GF)
    assert len([f for f in tmp_path.iterdir() if f.suffix == ".npz"]) == 1
    monkeypatch.setattr(B, "_build_binned_plan_numpy",
                        lambda *a, **k: pytest.fail("cache missed"))
    p2 = B.build_binned_plan(src, dst, n, n, geom=GF)
    assert p2.geom == GF and p2.bins_per_group == p1.bins_per_group
    assert (p1.f_meta is None) == (p2.f_meta is None)
    for f in B._PLAN_DATA_FIELDS:
        a, b = getattr(p1, f), getattr(p2, f)
        assert (a is None) == (b is None), f
        if a is not None:
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b), f)
    # the flat bit is part of the key: same shape, flat=0, must MISS
    monkeypatch.setattr(B, "_build_binned_plan_numpy", _ORIG_NUMPY)
    p3 = B.build_binned_plan(src, dst, n, n, geom=GF2)
    assert p3.geom == GF2
    assert len([f for f in tmp_path.iterdir() if f.suffix == ".npz"]) == 2
    # ... and so is the staging unit: unit=16 (bf16) at the same windows
    # must MISS too — a cached fp32-unit plan served to a bf16 run would
    # stage through the wrong dtype
    p4 = B.build_binned_plan(src, dst, n, n, geom=GFB)
    assert p4.geom == GFB
    assert len([f for f in tmp_path.iterdir() if f.suffix == ".npz"]) == 3


def test_run_binned_warns_once_outside_jit():
    """The eager path is a silent ~9x dispatch-overhead footgun: exactly
    one process-wide warning, and none under jit."""
    import warnings as W
    src = np.array([0, 1], np.int64)
    dst = np.array([1, 0], np.int64)
    plan = B.build_binned_plan(src, dst, 8, 8, group_row_target=1 << 14)
    x = jnp.ones((8, 8), jnp.float32)
    B._EAGER_WARNED[0] = False
    with W.catch_warnings(record=True) as rec:
        W.simplefilter("always")
        B.run_binned(x, plan, interpret=True)
        B.run_binned(x, plan, interpret=True)
    assert len([w for w in rec if "outside a jit trace" in
                str(w.message)]) == 1
    B._EAGER_WARNED[0] = False
    with W.catch_warnings(record=True) as rec:
        W.simplefilter("always")
        jax.jit(lambda v: B.run_binned(v, plan, interpret=True))(x)
    assert not [w for w in rec if "outside a jit trace" in str(w.message)]
    assert not B._EAGER_WARNED[0]


def test_build_binned_plans_accepts_bare_geometry():
    """Regression: a bare Geometry (itself a NamedTuple) means 'both
    directions' — it must not be unpacked as a (fwd, bwd) pair."""
    src = np.array([0, 1, 2], np.int64)
    dst = np.array([1, 2, 0], np.int64)
    plans = ops.build_binned_plans(src, dst, 8, 8, geom=B.GEOM_SPARSE)
    assert plans.fwd.geom == B.GEOM_SPARSE
    assert plans.bwd.geom == B.GEOM_SPARSE
    plans2 = ops.build_binned_plans(src, dst, 8, 8,
                                    geom=(B.GEOM_SPARSE, B.GEOM_MID))
    assert plans2.fwd.geom == B.GEOM_SPARSE
    assert plans2.bwd.geom == B.GEOM_MID


def test_spmd_flat_env_flag(monkeypatch):
    """ROC_BINNED_FLAT=1 is the hardware A/B lever: the SPMD trainer's
    shard plans come out flat, and training still tracks the xla path."""
    monkeypatch.setenv("ROC_BINNED_FLAT", "1")
    from roc_tpu.graph import datasets
    from roc_tpu.models import build_gcn
    from roc_tpu.parallel.spmd import SpmdTrainer
    from roc_tpu.train.config import Config

    ds = datasets.synthetic("bf", 220, 4.0, 8, 4, n_train=40, n_val=40,
                            n_test=40, seed=3)
    base = dict(layers=[8, 8, 4], num_epochs=2, dropout_rate=0.0,
                eval_every=10 ** 9, num_parts=4, halo=True,
                edge_shard="off")
    tx = SpmdTrainer(Config(**base), ds, build_gcn(base["layers"], 0.0))
    tb = SpmdTrainer(Config(**base, aggregate_backend="binned"), ds,
                     build_gcn(base["layers"], 0.0))
    assert tb.gdata.backend == "binned"
    plans = tb.gdata.plans if tb.gdata.plans is not None \
        else tb.gdata.plans_local
    assert plans.fwd.geom.flat == 1, plans.fwd.geom
    for i in range(2):
        lx, lb = float(tx.run_epoch()), float(tb.run_epoch())
        np.testing.assert_allclose(lb, lx, rtol=5e-3, err_msg=f"epoch {i}")


_ORIG_NUMPY = B._build_binned_plan_numpy
