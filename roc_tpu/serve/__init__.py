"""Inference serving path: microbatched node queries over the frozen
plan cache (ISSUE "serving" tentpole; design in docs/DESIGN.md §Serving).

  queue.py    MicrobatchQueue + ServeFuture — accumulate requests into
              windows (-serve-batch / -serve-wait-ms)
  engine.py   ServeEngine — frozen params in device buffers, bucketed
              jitted serve_step over the training forward, cold start =
              plan-cache load + one trace (zero rebuilds, pinned)
  delta.py    crash-consistent dynamic-graph deltas — write-ahead
              journal, incremental binned-cell patching (zero retraces,
              zero rebuilds), background-replan escalation ladder
  parity.py   max_ulp_diff — the ≤32-ULP served-vs-eval gate
  loadgen.py  open-loop QPS generator for benches and the smoke gate

`python -m roc_tpu.serve --selftest` is the CPU end-to-end smoke:
cold start from a warm plan cache, ~100 mixed-size queries, parity +
zero-retrace asserted, plus a delta leg (mixed add/retire churn, journal
restart-replay parity) — wired into tools/preflight.sh.
"""

from roc_tpu.serve.delta import (DeltaError, DeltaJournal,
                                 DeltaJournalError, DeltaManager)
from roc_tpu.serve.engine import ServeEngine, bucket_sizes
from roc_tpu.serve.loadgen import run_load
from roc_tpu.serve.parity import max_ulp_diff
from roc_tpu.serve.queue import MicrobatchQueue, Overloaded, ServeFuture

__all__ = ["ServeEngine", "MicrobatchQueue", "Overloaded", "ServeFuture",
           "DeltaError", "DeltaJournal", "DeltaJournalError",
           "DeltaManager", "bucket_sizes", "max_ulp_diff", "run_load"]
