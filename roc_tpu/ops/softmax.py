"""Masked softmax cross-entropy + training metrics (the reference's
SoftmaxCrossEntropy op).

Reference semantics (softmax_kernel.cu):
  * gradient: ``softmax(logits) - onehot_label``, zeroed for every vertex
    whose mask != TRAIN, with NO normalization by the train count
    (softmax_backward, softmax_kernel.cu:19-33).  The scalar loss whose
    gradient is exactly that is the *unreduced sum* of cross-entropy over
    train vertices — that is what :func:`masked_softmax_cross_entropy`
    returns, so `jax.grad` reproduces the reference update bit-for-bit in
    expectation.
  * reported "train_loss" metric: ``Σ_train (1 - p_true)`` — a margin-style
    sum, NOT the CE above (calc_loss, softmax_kernel.cu:65).  Reproduced
    exactly in :func:`perf_metrics` for curve comparability.
  * accuracy: argmax over softmax probabilities vs. one-hot label, tallied
    separately for TRAIN/VAL/TEST masks (softmax_kernel.cu:50-79).  NONE
    (and our pad rows) count nowhere.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

# Mask encoding, gnn.h:98-103.
MASK_TRAIN, MASK_VAL, MASK_TEST, MASK_NONE = 0, 1, 2, 3


class PerfMetrics(NamedTuple):
    """Mirror of the reference's PerfMetrics struct (softmax_kernel.cu:35-40)."""
    train_loss: jnp.ndarray   # Σ_train (1 - p_true)
    train_all: jnp.ndarray
    train_correct: jnp.ndarray
    val_all: jnp.ndarray
    val_correct: jnp.ndarray
    test_all: jnp.ndarray
    test_correct: jnp.ndarray


def masked_softmax_cross_entropy(logits, labels, mask):
    """Sum of CE over MASK_TRAIN rows (the loss whose grad is the reference's).

    logits: [N, C]; labels: [N, C] one-hot float; mask: [N] int32.
    """
    logp = jax.nn.log_softmax(logits, axis=-1)
    ce = -jnp.sum(labels * logp, axis=-1)
    train = (mask == MASK_TRAIN).astype(logits.dtype)
    return jnp.sum(ce * train)


def perf_metrics(logits, labels, mask) -> PerfMetrics:
    """The reference's evaluation pass (calc_loss, softmax_kernel.cu:41-79)."""
    probs = jax.nn.softmax(logits, axis=-1)
    p_true = jnp.sum(probs * labels, axis=-1)
    # Reference picks the first strictly-greater maximum starting from 0.0;
    # probabilities are strictly positive, so this is plain argmax.
    correct = jnp.argmax(probs, axis=-1) == jnp.argmax(labels, axis=-1)

    def tally(m):
        sel = mask == m
        return jnp.sum(sel), jnp.sum(sel & correct)

    train_all, train_correct = tally(MASK_TRAIN)
    val_all, val_correct = tally(MASK_VAL)
    test_all, test_correct = tally(MASK_TEST)
    train_loss = jnp.sum(jnp.where(mask == MASK_TRAIN, 1.0 - p_true, 0.0))
    return PerfMetrics(train_loss, train_all, train_correct,
                       val_all, val_correct, test_all, test_correct)


def format_metrics(epoch: int, m: PerfMetrics, infer: bool = True) -> str:
    """Reference's printed report line (softmax_kernel.cu:141-152)."""
    mode = "\t[INFER]" if infer else "[TRAIN]"
    def pct(c, a):
        return 100.0 * float(c) / max(float(a), 1.0)
    return (f"{mode}[{epoch}] train_loss: {float(m.train_loss):.4f}  "
            f"train_accuracy: {pct(m.train_correct, m.train_all):.2f}%"
            f"({int(m.train_correct)}/{int(m.train_all)})  "
            f"val_accuracy: {pct(m.val_correct, m.val_all):.2f}%"
            f"({int(m.val_correct)}/{int(m.val_all)})  "
            f"test_accuracy: {pct(m.test_correct, m.test_all):.2f}%"
            f"({int(m.test_correct)}/{int(m.test_all)})")
