"""Out-of-core host-streaming executor (roc_tpu/stream/).

The contract under test mirrors ISSUE 9's acceptance gates:

- streamed training matches the in-core trainer's loss (the rotation
  through fixed device slots is a *schedule*, not a different algorithm);
- shard rotation never retraces — every shard-varying tensor is a jit
  argument against frozen padded slot shapes;
- a graph bigger than the configured aggregate device budget fails
  loudly without ``-stream`` and trains with it;
- the .lux byte-range loader rejects malformed bounds/offset inputs
  instead of silently reading garbage (the streamed path re-reads byte
  ranges on every reshard, so these guards run in the hot loop's setup).
"""

import threading
import time

import numpy as np
import pytest

from roc_tpu.analysis import retrace as retrace_mod
from roc_tpu.analysis.retrace import RetraceGuard
from roc_tpu.graph import datasets, lux, shard_load
from roc_tpu.models import build_model
from roc_tpu.stream import incore_resident_bytes
from roc_tpu.train.config import Config
from roc_tpu.train.driver import make_trainer


@pytest.fixture(autouse=True)
def _lock_order_witness(lock_witness):
    # every stream test runs under the armed lock-order witness; any
    # acquisition order outside threads.json fails at teardown
    yield


def _trainer(ds, *, model="gcn", num_parts=1, stream=False, epochs=3,
             heads=2, stream_budget=""):
    cfg = Config(layers=[ds.in_dim, 16, ds.num_classes], num_epochs=epochs,
                 dropout_rate=0.0, eval_every=10**9, num_parts=num_parts,
                 model=model, heads=heads, stream=stream,
                 stream_budget=stream_budget)
    m = build_model(model, cfg.layers, cfg.dropout_rate, "", heads=heads)
    return make_trainer(cfg, ds, m)


@pytest.mark.parametrize("model", ["gcn", "sage", "gat"])
def test_streamed_loss_matches_incore(model):
    """Same seed, dropout 0: streamed (4 shards / 2 slots) vs in-core.

    Loss is an unreduced sum of CE over train rows and PerfMetrics are
    sums, so shard-wise partials are exactly summable — observed diffs are
    a few ULPs from reassociation, far inside the 1e-3 gate.
    """
    ds = datasets.get("roc-audit", seed=1)
    ref = _trainer(ds, model=model, num_parts=1)
    tr = _trainer(ds, model=model, num_parts=4, stream=True)
    for _ in range(3):
        want = ref.run_epoch()
        got = tr.run_epoch()
    assert abs(float(want) - float(got)) <= 1e-3


def test_zero_retrace_across_rotations_and_reshard():
    """Rotating 4 shards through 2 slots — and a reshard onto the same
    frozen shapes — must reuse the warm programs bit-for-bit."""
    ds = datasets.get("roc-audit", seed=1)
    tr = _trainer(ds, num_parts=4, stream=True)
    tr.run_epoch()                      # compile everything once
    tr.evaluate()
    with RetraceGuard(warmup=1, on_violation="raise"):
        retrace_mod.epoch_boundary(1)   # warmup boundary -> armed
        tr.run_epoch()
        tr.run_epoch()
        tr.reshard(tr.part.bounds)      # rotation map rebuild, same shapes
        tr.run_epoch()
        tr.evaluate()


def test_over_budget_requires_stream():
    """>2x-budget fixture: in-core build refuses with an actionable error;
    the streaming executor trains the same graph end-to-end."""
    # big enough that the padded slot working set amortizes: the point of
    # the fixture is a graph whose in-core bytes dwarf what two slots hold
    ds = datasets.synthetic("oocore", 3000, 6.0, 16, 4,
                            n_train=600, n_val=600, n_test=600, seed=5)
    need = incore_resident_bytes(ds)
    budget = str(need // 3)             # graph is >2x the device budget
    with pytest.raises(SystemExit, match="rerun with -stream"):
        _trainer(ds, num_parts=2, stream=False, stream_budget=budget)
    tr = _trainer(ds, num_parts=8, stream=True, stream_budget=budget)
    loss = tr.run_epoch()
    assert np.isfinite(float(loss))
    # the streamed leg's slot working set actually fits where in-core can't
    assert tr.slot_bytes() * tr.config.stream_slots < need


@pytest.fixture(scope="module")
def lux_graph(tmp_path_factory):
    ds = datasets.synthetic("streamfuzz", 400, 5.0, 8, 4,
                            n_train=80, n_val=80, n_test=80, seed=11)
    path = str(tmp_path_factory.mktemp("lux") / ("g" + lux.LUX_SUFFIX))
    lux.write_lux(path, ds.graph)
    return path, ds


def _random_bounds(num_nodes, num_parts, rng):
    cuts = np.sort(rng.choice(np.arange(1, num_nodes), size=num_parts - 1,
                              replace=False))
    edges = np.concatenate(([0], cuts, [num_nodes]))
    return [(int(edges[i]), int(edges[i + 1]) - 1)
            for i in range(num_parts)]


def test_lux_bounds_fuzz_valid_cuts(lux_graph):
    path, ds = lux_graph
    rng = np.random.default_rng(3)
    row_ptr = ds.graph.row_ptr
    for _ in range(20):
        bounds = _random_bounds(ds.graph.num_nodes, 4, rng)
        meta = shard_load.meta_from_lux(path, 4, bounds=bounds)
        assert [tuple(b) for b in np.asarray(meta.bounds)] == bounds
        # per-part edge counts match the row-offset deltas the byte
        # ranges were derived from
        for p, (lo, hi) in enumerate(bounds):
            assert meta.num_edges_valid[p] == row_ptr[hi + 1] - row_ptr[lo]


def test_lux_bounds_rejects_malformed(lux_graph):
    path, ds = lux_graph
    n = ds.graph.num_nodes
    bad = [
        [(0, 99), (99, n - 1)],          # overlap at the seam
        [(0, 99), (101, n - 1)],         # gap
        [(0, 99), (100, n)],             # runs past the last vertex
        [(0, n - 1), (0, n - 1)],        # full-range twice
    ]
    for bounds in bad:
        with pytest.raises(ValueError):
            shard_load.meta_from_lux(path, 2, bounds=bounds)


def test_lux_slice_hardening(lux_graph):
    path, ds = lux_graph
    with pytest.raises(ValueError):
        lux.read_rows_slice(path, -1, 5)
    with pytest.raises(ValueError):
        lux.read_rows_slice(path, 5, 2)
    with pytest.raises(ValueError):
        lux.read_rows_slice(path, 0, ds.graph.num_nodes + 10**6)
    with pytest.raises(ValueError):
        lux.read_cols_slice(path, ds.graph.num_nodes, -4, 4)
    with pytest.raises(ValueError):
        lux.read_cols_slice(path, ds.graph.num_nodes, 0,
                            ds.graph.num_edges + 10**6)


def test_frozen_shapes_reject_oversized_cut(lux_graph):
    """Reshard under frozen slot shapes: a cut that needs more rows/edges
    than the allocation raises instead of silently truncating."""
    path, ds = lux_graph
    n = ds.graph.num_nodes
    with pytest.raises(ValueError, match="cannot hold"):
        shard_load.meta_from_lux(path, 2, bounds=[(0, n - 2), (n - 1, n - 1)],
                                 shard_nodes=8)


# -- prefetch-ring stats under the lock (regression: torn float +=) ---------

def test_ring_stats_consistent_under_concurrent_readers():
    """busy_s/stall_s are written by the worker and the consumer and
    read by epoch_stats() from anywhere; all three now go through
    _lock.  Regression for the torn-update race: hammer fetches from
    several consumer threads while readers snapshot/reset, and require
    every snapshot internally consistent (finite, non-negative, overlap
    clamped) and the final busy_s to have absorbed every fetch."""
    from roc_tpu.stream.ring import PrefetchRing

    fetched = []

    def fetch(item):
        time.sleep(0.001)
        fetched.append(item)
        return item

    ring = PrefetchRing(4, fetch)
    bad = []
    stop = threading.Event()

    def reader():
        while not stop.is_set():
            st = ring.epoch_stats()
            if not (np.isfinite(st["stall_s"]) and st["stall_s"] >= 0.0
                    and np.isfinite(st["transfer_s"])
                    and st["transfer_s"] >= 0.0
                    and 0.0 <= st["overlap_frac"] <= 1.0):
                bad.append(st)
                return

    def consumer(base):
        for i in range(24):
            assert ring.wait(("item", base, i)) == ("item", base, i)

    try:
        rt = threading.Thread(target=reader)
        cs = [threading.Thread(target=consumer, args=(b,)) for b in range(3)]
        rt.start()
        for t in cs:
            t.start()
        for t in cs:
            t.join(60.0)
        stop.set()
        rt.join(10.0)
        assert not rt.is_alive() and not any(t.is_alive() for t in cs)
        assert bad == [], bad
        assert len(fetched) == 3 * 24
        # the worker's increments all landed: busy_s covers every fetch
        assert ring.epoch_stats()["transfer_s"] >= 3 * 24 * 0.001
        ring.reset_epoch_stats()
        assert ring.epoch_stats()["transfer_s"] == 0.0
    finally:
        ring.close()
